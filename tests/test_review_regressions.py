"""Regression tests for review findings on the client/loop layer:
credential refresh, token rotation, repeated run() episodes, canonical-query
plus-sign handling, and fresh-clock down-gate evaluation.
"""

import time

from kube_sqs_autoscaler_tpu.core.clock import FakeClock
from kube_sqs_autoscaler_tpu.core.loop import ControlLoop, LoopConfig
from kube_sqs_autoscaler_tpu.core.policy import PolicyConfig
from kube_sqs_autoscaler_tpu.metrics import FakeQueueService, QueueMetricSource
from kube_sqs_autoscaler_tpu.metrics.sqs_aws import AwsSqsService
from kube_sqs_autoscaler_tpu.scale import FakeDeploymentAPI, PodAutoScaler
from kube_sqs_autoscaler_tpu.scale.kube import ClusterConfig
from kube_sqs_autoscaler_tpu.utils.sigv4 import (
    Credentials,
    SignableRequest,
    _canonical_query,
    sign_request,
)


def test_run_twice_gives_two_full_episodes():
    # A second run(max_ticks=N) must do N fresh ticks, not exit immediately.
    api = FakeDeploymentAPI.with_deployments("ns", 3, "deploy")
    scaler = PodAutoScaler(
        client=api, max=5, min=1, scale_up_pods=1, scale_down_pods=1,
        deployment="deploy", namespace="ns",
    )
    queue = FakeQueueService.with_depths(0)
    loop = ControlLoop(
        scaler,
        QueueMetricSource(client=queue, queue_url="q"),
        LoopConfig(poll_interval=1.0, policy=PolicyConfig(
            scale_up_messages=100, scale_down_messages=3,
            scale_up_cooldown=0.0, scale_down_cooldown=0.0)),
        clock=FakeClock(),
    )
    loop.run(max_ticks=2)
    assert queue.get_calls == 2
    loop.run(max_ticks=2)
    assert queue.get_calls == 4
    assert loop.ticks == 4  # cumulative across episodes


def test_canonical_query_preserves_literal_plus():
    # RFC3986 query: '+' is a literal plus, not a space.
    assert _canonical_query("Marker=a+b") == "Marker=a%2Bb"
    assert _canonical_query("b=2&a=1") == "a=1&b=2"
    assert _canonical_query("k=%41") == "k=A"
    assert _canonical_query("empty=") == "empty="


def test_expired_chain_credentials_are_refreshed(monkeypatch):
    monkeypatch.setenv("AWS_ACCESS_KEY_ID", "AKIDFRESH")
    monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "fresh")
    monkeypatch.delenv("AWS_SESSION_TOKEN", raising=False)
    service = AwsSqsService(region="us-east-1")
    # simulate a previously chain-resolved temporary credential near expiry
    service._credentials = Credentials(
        "AKIDOLD", "old", "tok", expires_at=time.time() + 10
    )
    assert service._current_credentials().access_key_id == "AKIDFRESH"


def test_injected_credentials_are_never_refreshed():
    creds = Credentials("AKIDPIN", "pin", expires_at=time.time() - 1000)
    service = AwsSqsService(region="us-east-1", credentials=creds)
    assert service._current_credentials() is creds


def test_bearer_token_reread_from_rotating_file(tmp_path):
    token_file = tmp_path / "token"
    token_file.write_text("token-v1\n")
    config = ClusterConfig(
        server="http://x", token="token-v1", token_file=str(token_file)
    )
    assert config.bearer_token() == "token-v1"
    token_file.write_text("token-v2\n")  # kubelet rotates the projected token
    assert config.bearer_token() == "token-v2"
    token_file.unlink()
    assert config.bearer_token() == "token-v1"  # falls back to startup token


def test_down_gate_sees_time_advanced_by_scale_up_rpc():
    # Reference semantics (main.go:66): time.Now() is re-read after the
    # scale-up RPCs, so a down-cooldown that expires *during* the scale-up
    # call still fires in the same tick.
    clock = FakeClock()

    api = FakeDeploymentAPI.with_deployments("ns", 3, "deploy")

    class SlowRpcApi:
        # wraps the fake, advancing the clock 1s per RPC like a slow network
        def get(self, name):
            clock.advance(0.5)
            return api.get(name)

        def update(self, deployment):
            clock.advance(0.5)
            return api.update(deployment)

    scaler = PodAutoScaler(
        client=SlowRpcApi(), max=10, min=1, scale_up_pods=1, scale_down_pods=1,
        deployment="deploy", namespace="ns",
    )
    # overlapping thresholds: depth 5 triggers both directions
    loop = ControlLoop(
        scaler,
        QueueMetricSource(client=FakeQueueService.with_depths(5), queue_url="q"),
        LoopConfig(poll_interval=10.0, policy=PolicyConfig(
            scale_up_messages=5, scale_down_messages=5,
            scale_up_cooldown=0.0,
            # expires at t=10.5: after the tick-1 plan instant (t=10) but
            # before the post-scale-up clock read (t=11)
            scale_down_cooldown=10.5,
        )),
        clock=clock,
    )
    loop.run(max_ticks=1)
    # up fired (3 -> 4) at some t in (10, 11); down gate evaluated at t=11
    # where 0 + 10.5 > 11 is false -> down fires too (4 -> 3)
    assert api.replicas("deploy") == 3
