"""Beam search: beams=1 must equal greedy decoding exactly (both
families), wider beams must never find a worse joint log-probability
than greedy, return_all is sorted best-first, and eos freezes beams."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kube_sqs_autoscaler_tpu.workloads.beam import (
    beam_search,
    beam_search_jit,
)
from kube_sqs_autoscaler_tpu.workloads.decode import generate
from kube_sqs_autoscaler_tpu.workloads.model import (
    ModelConfig,
    forward,
    init_params,
)

TINY = ModelConfig(
    vocab_size=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
    max_seq_len=96,
)


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.key(0), TINY)


def prompt_tokens(batch=3, length=6, seed=1):
    return jax.random.randint(
        jax.random.key(seed), (batch, length), 0, TINY.vocab_size, jnp.int32
    )


def sequence_logprob(params, config, prompt, continuation):
    """Teacher-forced joint log-probability of the continuation."""
    full = jnp.concatenate([prompt, jnp.asarray(continuation)], axis=1)
    logp = jax.nn.log_softmax(forward(params, full, config), axis=-1)
    total = np.zeros(full.shape[0])
    for b in range(full.shape[0]):
        for t in range(continuation.shape[1]):
            pos = prompt.shape[1] - 1 + t
            total[b] += float(logp[b, pos, full[b, pos + 1]])
    return total


def test_single_beam_equals_greedy(params):
    prompt = prompt_tokens()
    ref = np.asarray(generate(params, prompt, 10, TINY))
    got = np.asarray(beam_search(params, TINY, prompt, 10, beams=1))
    np.testing.assert_array_equal(got, ref)


def test_wider_beam_never_worse_than_greedy(params):
    prompt = prompt_tokens()
    greedy = np.asarray(generate(params, prompt, 10, TINY))
    beamed = np.asarray(beam_search(params, TINY, prompt, 10, beams=4))
    greedy_lp = sequence_logprob(params, TINY, prompt, greedy)
    beam_lp = sequence_logprob(params, TINY, prompt, beamed)
    assert (beam_lp >= greedy_lp - 1e-3).all()


def test_return_all_sorted_and_shaped(params):
    prompt = prompt_tokens()
    seqs, scores = beam_search_jit(params, TINY, prompt, 8, 4,
                                   return_all=True)
    assert seqs.shape == (3, 4, 8)
    s = np.asarray(scores)
    assert (s[:, :-1] >= s[:, 1:] - 1e-6).all()  # best first
    # row 0 of return_all == the single-sequence API
    best = np.asarray(beam_search(params, TINY, prompt, 8, beams=4))
    np.testing.assert_array_equal(np.asarray(seqs)[:, 0], best)


def test_llama_family_beam(params):
    from kube_sqs_autoscaler_tpu.workloads.llama import (
        LlamaConfig,
        init_llama_params,
        llama_generate,
    )

    config = LlamaConfig(vocab_size=64, d_model=32, n_heads=2, n_kv_heads=1,
                         n_layers=2, d_ff=48, max_seq_len=96,
                         dtype=jnp.float32)
    lparams = init_llama_params(jax.random.key(2), config)
    prompt = prompt_tokens()
    ref = np.asarray(llama_generate(lparams, prompt, 8, config))
    got = np.asarray(beam_search(lparams, config, prompt, 8, beams=1))
    np.testing.assert_array_equal(got, ref)
    # a wider llama beam is at least as probable too
    beamed = beam_search(lparams, config, prompt, 8, beams=3)
    # (scores checked via the gpt-family test; here shape/validity)
    assert beamed.shape == (3, 8)
    assert 0 <= int(jnp.min(beamed)) and int(jnp.max(beamed)) < 64


def test_eos_freezes_and_pads(params):
    prompt = prompt_tokens()
    greedy = np.asarray(generate(params, prompt, 10, TINY))
    eos = int(greedy[0, 3])  # an id the model actually produces
    out = np.asarray(beam_search(params, TINY, prompt, 10, beams=3,
                                 eos_id=eos, length_penalty=1.0))
    for row in out:
        ids = row.tolist()
        if eos in ids:
            first = ids.index(eos)
            assert all(x == eos for x in ids[first:])


def test_ragged_prompts(params):
    prompt = prompt_tokens()
    lengths = jnp.asarray([3, 6, 4], jnp.int32)
    full = np.asarray(generate(params, prompt, 8, TINY, lengths=lengths))
    got = np.asarray(beam_search(params, TINY, prompt, 8, beams=1,
                                 lengths=lengths))
    np.testing.assert_array_equal(got, full)


def test_beam_tp_sharded_matches_single_chip(params):
    # VERDICT r3 composition hole: beams over a (data, model) mesh —
    # identical sequences to the single-chip search (deterministic)
    from kube_sqs_autoscaler_tpu.workloads.beam import make_beam_serving_fn
    from kube_sqs_autoscaler_tpu.workloads.train import (
        make_mesh,
        param_shardings,
    )

    mesh = make_mesh(jax.devices()[:4], model_parallel=2, seq_parallel=1)
    placed = jax.device_put(params, param_shardings(mesh, params))
    prompt = prompt_tokens(batch=2)
    lengths = jnp.full((2,), prompt.shape[1], jnp.int32)
    single = np.asarray(beam_search(params, TINY, prompt, 8, beams=3))

    run = make_beam_serving_fn(mesh, TINY, placed, beams=3)
    sharded = np.asarray(run(placed, prompt, lengths, 8))
    np.testing.assert_array_equal(sharded, single)

    # eos rides the sharded search too
    eos = int(single[0, 1])
    single_eos = np.asarray(
        beam_search(params, TINY, prompt, 8, beams=3, eos_id=eos)
    )
    run_eos = make_beam_serving_fn(mesh, TINY, placed, beams=3, eos_id=eos)
    np.testing.assert_array_equal(
        np.asarray(run_eos(placed, prompt, lengths, 8)), single_eos
    )


def test_serve_binary_beams_flag():
    from kube_sqs_autoscaler_tpu.workloads.__main__ import main

    main(["--demo", "2", "--batch-size", "1", "--seq-len", "8",
          "--generate-tokens", "4", "--beams", "3"])
    main(["--family", "llama", "--demo", "2", "--batch-size", "1",
          "--seq-len", "8", "--generate-tokens", "4", "--beams", "2"])
    # tp-sharded beams from the binary (the fail-fast this composed away)
    import os

    if "xla_force_host_platform_device_count" in os.environ.get(
            "XLA_FLAGS", ""):
        main(["--demo", "4", "--batch-size", "4", "--seq-len", "8",
              "--generate-tokens", "4", "--beams", "2",
              "--model-parallel", "2", "--eos-id", "5"])
    with pytest.raises(SystemExit, match="deterministic"):
        main(["--demo", "1", "--generate-tokens", "4", "--beams", "2",
              "--temperature", "0.5"])
    with pytest.raises(SystemExit, match="beams"):
        main(["--demo", "1", "--generate-tokens", "4", "--beams", "2",
              "--speculative-draft-layers", "1"])
    with pytest.raises(SystemExit, match="beams"):
        main(["--demo", "1", "--generate-tokens", "4", "--beams", "0"])


def test_validation(params):
    prompt = prompt_tokens()
    with pytest.raises(ValueError, match="beams"):
        beam_search(params, TINY, prompt, 4, beams=0)
    with pytest.raises(ValueError, match="num_tokens"):
        beam_search(params, TINY, prompt, 0)
    with pytest.raises(ValueError, match="max_seq_len"):
        beam_search(params, TINY, prompt, 96)


def test_beam_int8_cache_and_sharded_prefix(params):
    # int8 beams: the row-repeat and parent gather are layout-agnostic,
    # so the quantized search runs and the SHARDED quantized search is
    # bitwise the single-chip one; a pinned prefix rides the sharded
    # factory as a replicated-batch operand (VERDICT r4 weak #3 —
    # serve-side fail-fast cluster)
    from kube_sqs_autoscaler_tpu.workloads.beam import make_beam_serving_fn
    from kube_sqs_autoscaler_tpu.workloads.decode import (
        prefill_prefix,
        quantized_prefill_prefix,
    )
    from kube_sqs_autoscaler_tpu.workloads.train import (
        make_mesh,
        param_shardings,
    )

    mesh = make_mesh(jax.devices()[:4], model_parallel=2, seq_parallel=1)
    placed = jax.device_put(params, param_shardings(mesh, params))
    prompt = prompt_tokens(batch=2)
    lengths = jnp.full((2,), prompt.shape[1], jnp.int32)

    single_q = np.asarray(beam_search(params, TINY, prompt, 6, beams=2,
                                      quantized_cache=True))
    run_q = make_beam_serving_fn(mesh, TINY, placed, beams=2,
                                 quantized_cache=True)
    np.testing.assert_array_equal(
        np.asarray(run_q(placed, prompt, lengths, 6)), single_q
    )

    prefix = jnp.arange(1, 7, dtype=jnp.int32)
    pc = prefill_prefix(params, prefix, TINY)
    single_p = np.asarray(beam_search(params, TINY, prompt, 6, beams=2,
                                      prefix_cache=pc))
    run_p = make_beam_serving_fn(mesh, TINY, placed, beams=2,
                                 prefix_cache=pc)
    np.testing.assert_array_equal(
        np.asarray(run_p(placed, prompt, lengths, 6)), single_p
    )

    # prefix x int8 compose too (layout-matched prefix)
    pc_q = quantized_prefill_prefix(params, prefix, TINY)
    single_pq = np.asarray(beam_search(
        params, TINY, prompt, 6, beams=2, prefix_cache=pc_q,
        quantized_cache=True,
    ))
    run_pq = make_beam_serving_fn(mesh, TINY, placed, beams=2,
                                  prefix_cache=pc_q, quantized_cache=True)
    np.testing.assert_array_equal(
        np.asarray(run_pq(placed, prompt, lengths, 6)), single_pq
    )


def test_serve_binary_length_penalty_flag():
    # the --length-penalty knob threads from the binary into every beam
    # path (was dead config: ContinuousBatcher/beam_search took it, the
    # CLI never offered it)
    from kube_sqs_autoscaler_tpu.workloads.__main__ import main

    main(["--demo", "2", "--batch-size", "1", "--seq-len", "8",
          "--generate-tokens", "4", "--beams", "3",
          "--length-penalty", "0.6"])
    main(["--demo", "2", "--batch-size", "1", "--seq-len", "8",
          "--generate-tokens", "4", "--beams", "2", "--continuous",
          "--length-penalty", "0.6"])
    with pytest.raises(SystemExit, match="length-penalty"):
        main(["--demo", "1", "--generate-tokens", "4",
              "--length-penalty", "0.6"])  # needs --beams > 1
    with pytest.raises(SystemExit, match=">= 0"):
        main(["--demo", "1", "--generate-tokens", "4", "--beams", "2",
              "--length-penalty", "-1"])
