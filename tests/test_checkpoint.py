"""Checkpoint/resume: save a sharded train state, restore it onto the mesh,
and confirm training continues bit-for-bit where it left off.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kube_sqs_autoscaler_tpu.workloads.checkpoint import TrainCheckpointer
from kube_sqs_autoscaler_tpu.workloads.model import ModelConfig
from kube_sqs_autoscaler_tpu.workloads.train import (
    TrainConfig,
    batch_sharding,
    init_train_state,
    make_mesh,
    make_train_step,
    place_state,
    state_shardings,
)

TINY = ModelConfig(
    vocab_size=256, d_model=128, n_heads=4, n_layers=2, d_ff=256, max_seq_len=64
)


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(jax.devices())


def tokens_for(mesh, seed=1):
    return jax.device_put(
        jax.random.randint(jax.random.key(seed), (4, 32), 0, TINY.vocab_size,
                           jnp.int32),
        batch_sharding(mesh),
    )


def test_save_restore_resume_is_exact(tmp_path, mesh):
    config = TrainConfig(learning_rate=1e-3)
    state = place_state(mesh, init_train_state(jax.random.key(0), TINY, config))
    step_fn = make_train_step(mesh, TINY, config, state)
    batch = tokens_for(mesh)

    for _ in range(2):
        state, _ = step_fn(state, batch)

    ckpt = TrainCheckpointer(tmp_path / "ckpts")
    # save a copy: the train step donates its input state buffers
    saved_step = int(jax.device_get(state["step"]))
    ckpt.save(state)

    # branch A: continue directly
    state_a, loss_a = step_fn(state, batch)

    # branch B: restore from disk and continue
    reference = place_state(
        mesh, init_train_state(jax.random.key(0), TINY, config)
    )
    restored = ckpt.restore(mesh, reference)
    assert int(jax.device_get(restored["step"])) == saved_step
    # restored arrays carry the mesh shardings the step expects
    expected = state_shardings(mesh, reference)
    assert (
        restored["params"]["layers"][0]["wqkv"].sharding
        == expected["params"]["layers"][0]["wqkv"]
    )
    state_b, loss_b = step_fn(restored, batch)

    assert float(loss_a) == float(loss_b)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(state_a["params"]["embed"])),
        np.asarray(jax.device_get(state_b["params"]["embed"])),
    )


def test_latest_step_and_missing(tmp_path, mesh):
    ckpt = TrainCheckpointer(tmp_path / "empty")
    assert ckpt.latest_step() is None
    with pytest.raises(FileNotFoundError):
        ckpt.restore(mesh, {})


def test_checkpoint_retention_keeps_newest_n(tmp_path):
    import jax

    from kube_sqs_autoscaler_tpu.workloads.checkpoint import TrainCheckpointer
    from kube_sqs_autoscaler_tpu.workloads.model import ModelConfig
    from kube_sqs_autoscaler_tpu.workloads.train import (
        TrainConfig,
        init_train_state,
    )

    config = ModelConfig(vocab_size=64, d_model=32, n_heads=4, n_layers=1,
                         d_ff=64, max_seq_len=16, dtype=jnp.float32)
    state = init_train_state(jax.random.key(0), config, TrainConfig())
    ckpt = TrainCheckpointer(tmp_path / "ckpt", keep=2)
    for step in (1, 2, 3, 4):
        state["step"] = jnp.asarray(step, jnp.int32)
        ckpt.save(state)
    steps = sorted(
        int(p.name.split("_")[1])
        for p in (tmp_path / "ckpt").glob("step_*")
    )
    assert steps == [3, 4]
    assert ckpt.latest_step() == 4


def test_trainer_checkpoint_keep_flag(tmp_path):
    from kube_sqs_autoscaler_tpu.workloads.trainer import main as trainer_main

    ckpt = str(tmp_path / "ckpt")
    trainer_main([
        "--vocab-size", "256", "--d-model", "64", "--n-heads", "4",
        "--n-layers", "2", "--d-ff", "128", "--seq-len", "32",
        "--batch-size", "8", "--steps", "6", "--checkpoint-dir", ckpt,
        "--checkpoint-every", "2", "--checkpoint-keep", "1",
    ])
    from pathlib import Path

    steps = sorted(p.name for p in Path(ckpt).glob("step_*"))
    assert steps == ["step_00000006"]
    # the kept checkpoint resumes
    result = trainer_main([
        "--vocab-size", "256", "--d-model", "64", "--n-heads", "4",
        "--n-layers", "2", "--d-ff", "128", "--seq-len", "32",
        "--batch-size", "8", "--steps", "2", "--checkpoint-dir", ckpt,
        "--resume",
    ])
    assert result["final_step"] == 8
