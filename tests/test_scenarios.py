"""Arrival processes: analytic integrals verified against numeric
quadrature, and the simulator's time-varying world against its
constant-rate seed behavior.
"""

import pytest

from tests.proptest import given, settings, st

from kube_sqs_autoscaler_tpu.core.loop import LoopConfig
from kube_sqs_autoscaler_tpu.core.policy import PolicyConfig
from kube_sqs_autoscaler_tpu.sim import (
    BurstArrival,
    ConstantArrival,
    DiurnalArrival,
    RampArrival,
    SimConfig,
    Simulation,
    StepArrival,
)
from kube_sqs_autoscaler_tpu.sim.scenarios import as_process

PROCESSES = [
    ConstantArrival(rate=42.0),
    StepArrival(before=20.0, after=120.0, at=100.0),
    RampArrival(start_rate=10.0, end_rate=150.0, t_start=60.0, t_end=660.0),
    DiurnalArrival(base=80.0, amplitude=60.0, period=450.0, phase=33.0),
    BurstArrival(base=25.0, burst_rate=250.0, period=300.0, burst_len=45.0,
                 first_burst=120.0),
]

INTERVALS = [(0.0, 5.0), (0.0, 900.0), (95.0, 105.0), (100.0, 100.0),
             (119.9, 165.1), (333.3, 666.6), (58.0, 62.0)]


def numeric_integral(process, t0, t1, steps=200_000):
    """Midpoint rule; tight enough to pin the analytic forms."""
    if t1 <= t0:
        return 0.0
    dt = (t1 - t0) / steps
    return sum(
        process.rate_at(t0 + (i + 0.5) * dt) for i in range(steps)
    ) * dt


@pytest.mark.parametrize("process", PROCESSES, ids=lambda p: type(p).__name__)
@pytest.mark.parametrize("interval", INTERVALS)
def test_analytic_integral_matches_quadrature(process, interval):
    t0, t1 = interval
    exact = process.arrivals_between(t0, t1)
    approx = numeric_integral(process, t0, t1)
    assert exact == pytest.approx(approx, rel=1e-4, abs=1e-3)


def trapezoid_integral(process, t0, t1, steps=4000):
    """Composite trapezoid rule over ``rate_at`` — an independent check of
    the analytic ``arrivals_between`` forms the *compiled* world consumes
    verbatim (sim/compiled.py precomputes per-tick arrivals from these
    exact functions, so this property covers both worlds)."""
    if t1 <= t0:
        return 0.0
    dt = (t1 - t0) / steps
    total = 0.5 * (process.rate_at(t0) + process.rate_at(t1))
    total += sum(process.rate_at(t0 + i * dt) for i in range(1, steps))
    return total * dt


@settings(max_examples=40, deadline=None)
@given(
    t0=st.floats(min_value=0.0, max_value=1500.0),
    span=st.floats(min_value=0.1, max_value=900.0),
    before=st.floats(min_value=0.0, max_value=200.0),
    after=st.floats(min_value=0.0, max_value=300.0),
    at=st.floats(min_value=10.0, max_value=1000.0),
    base=st.floats(min_value=50.0, max_value=150.0),
    amp_frac=st.floats(min_value=0.0, max_value=1.0),
    period=st.floats(min_value=30.0, max_value=900.0),
    burst_len_frac=st.floats(min_value=0.05, max_value=1.0),
)
def test_analytic_integrals_match_trapezoid_on_random_windows(
    t0, span, before, after, at, base, amp_frac, period, burst_len_frac
):
    # Random window x random parameters, all four time-varying shapes:
    # the property the battery, the Python world, and the compiled world
    # all lean on is that arrivals_between IS the integral of rate_at.
    t1 = t0 + span
    import math

    rate_range = before + after + base
    dt = span / 4000
    omega = 2.0 * math.pi / period
    # Trapezoid error budget per shape: each jump discontinuity costs up
    # to rate_range * dt (step: 1 edge; burst: 2 per period in-window),
    # smooth curvature costs span * dt^2 * max|f''| / 12 (diurnal:
    # max|f''| = amp * omega^2); ramp kinks are continuous (O(dt^2),
    # covered by the 2x safety factor on the edge bound).
    edge = rate_range * dt
    processes = [
        (StepArrival(before=before, after=after, at=at), 2 * edge),
        (
            RampArrival(start_rate=before, end_rate=after, t_start=at,
                        t_end=at + period),
            2 * edge,
        ),
        (
            DiurnalArrival(base=base, amplitude=base * amp_frac,
                           period=period, phase=at),
            2 * span * dt * dt * (base * amp_frac) * omega * omega / 12,
        ),
        (
            BurstArrival(base=before, burst_rate=before + after,
                         period=period, burst_len=period * burst_len_frac,
                         first_burst=at),
            2 * (2 * (span / period + 2)) * edge,
        ),
    ]
    for process, tol in processes:
        exact = process.arrivals_between(t0, t1)
        approx = trapezoid_integral(process, t0, t1)
        assert exact == pytest.approx(approx, abs=max(tol, 1e-6), rel=1e-6), (
            type(process).__name__, t0, t1,
        )


def test_rates_are_nonnegative_everywhere():
    for process in PROCESSES:
        for i in range(0, 1800, 7):
            assert process.rate_at(float(i)) >= 0.0


def test_diurnal_rejects_clipping_amplitude():
    with pytest.raises(ValueError):
        DiurnalArrival(base=10.0, amplitude=20.0, period=100.0)


def test_burst_rejects_bad_burst_len():
    with pytest.raises(ValueError):
        BurstArrival(base=1.0, burst_rate=2.0, period=10.0, burst_len=11.0)


def test_as_process_wraps_numbers_and_passes_processes_through():
    wrapped = as_process(50)
    assert isinstance(wrapped, ConstantArrival)
    assert wrapped.rate_at(123.0) == 50.0
    ramp = PROCESSES[2]
    assert as_process(ramp) is ramp


def _loop():
    return LoopConfig(
        poll_interval=5.0,
        policy=PolicyConfig(
            scale_up_messages=100, scale_down_messages=10,
            scale_up_cooldown=10.0, scale_down_cooldown=30.0,
        ),
    )


def test_constant_process_reproduces_float_config_timeline_exactly():
    # Satellite guarantee: the generalized world, fed the seed's constant
    # rate via a process, must match the float fast path sample-for-sample.
    float_cfg = SimConfig(arrival_rate=50.0, duration=600.0, max_pods=8,
                          loop=_loop())
    proc_cfg = SimConfig(arrival_rate=ConstantArrival(50.0), duration=600.0,
                         max_pods=8, loop=_loop())
    float_result = Simulation(float_cfg).run()
    proc_result = Simulation(proc_cfg).run()
    assert float_result.timeline == proc_result.timeline
    assert float_result.max_depth == proc_result.max_depth
    assert float_result.final_replicas == proc_result.final_replicas


def test_step_arrival_scales_the_pool_after_the_step():
    # flat 20 msg/s (2 replicas keep up), step to 120 msg/s at t=300:
    # the pool must grow to 12 replicas after the step.
    sim = Simulation(
        SimConfig(
            arrival_rate=StepArrival(before=20.0, after=120.0, at=300.0),
            service_rate_per_replica=10.0, duration=900.0,
            initial_replicas=2, max_pods=15, loop=_loop(),
        )
    )
    result = sim.run()
    assert result.final_replicas >= 12
    mid = [r for (t, _, r) in result.timeline if t < 300.0]
    assert max(mid) <= 3  # pre-step the pool stayed small


def test_burst_world_grows_during_bursts_and_recovers():
    sim = Simulation(
        SimConfig(
            arrival_rate=BurstArrival(
                base=5.0, burst_rate=200.0, period=300.0, burst_len=30.0,
                first_burst=60.0,
            ),
            service_rate_per_replica=10.0, duration=900.0,
            initial_replicas=1, max_pods=20, loop=_loop(),
        )
    )
    result = sim.run()
    assert result.max_depth > 100.0  # bursts visibly pile up backlog
    assert result.final_depth < result.max_depth  # and the pool drains it


# --- widened shapes: composed / pulse / regime-switch / heavy tails ---------


def _composed(base, pulse_rate, start, width):
    from kube_sqs_autoscaler_tpu.sim.scenarios import (
        ComposedArrival,
        PulseArrival,
    )

    return ComposedArrival(parts=(
        ConstantArrival(base),
        PulseArrival(rate=pulse_rate, start=start, width=width),
    ))


def _regime(low, burst_base, burst_rate, t1, t2, period, burst_len):
    from kube_sqs_autoscaler_tpu.sim.scenarios import RegimeSwitchArrival

    return RegimeSwitchArrival(regimes=(
        (0.0, ConstantArrival(low)),
        (t1, BurstArrival(base=burst_base, burst_rate=burst_rate,
                          period=period, burst_len=burst_len)),
        (t2, ConstantArrival(low / 2)),
    ))


@settings(max_examples=30, deadline=None)
@given(
    t0=st.floats(min_value=0.0, max_value=800.0),
    span=st.floats(min_value=0.5, max_value=600.0),
    base=st.floats(min_value=0.0, max_value=100.0),
    surge=st.floats(min_value=1.0, max_value=400.0),
    start=st.floats(min_value=0.0, max_value=700.0),
    width=st.floats(min_value=0.1, max_value=300.0),
)
def test_composed_and_pulse_integrals_match_trapezoid(
    t0, span, base, surge, start, width
):
    t1 = t0 + span
    process = _composed(base, surge, start, width)
    dt = span / 4000
    # two jump edges from the pulse, each costing up to rate_range * dt
    tol = 2 * 2 * (base + surge) * dt
    exact = process.arrivals_between(t0, t1)
    approx = trapezoid_integral(process, t0, t1)
    assert exact == pytest.approx(approx, abs=max(tol, 1e-6), rel=1e-6)


@settings(max_examples=25, deadline=None)
@given(
    t0=st.floats(min_value=0.0, max_value=500.0),
    span=st.floats(min_value=0.5, max_value=500.0),
    low=st.floats(min_value=1.0, max_value=50.0),
    burst_rate=st.floats(min_value=60.0, max_value=300.0),
    t1=st.floats(min_value=10.0, max_value=300.0),
    gap=st.floats(min_value=10.0, max_value=300.0),
    period=st.floats(min_value=20.0, max_value=200.0),
    burst_frac=st.floats(min_value=0.05, max_value=1.0),
)
def test_regime_switch_integral_matches_trapezoid(
    t0, span, low, burst_rate, t1, gap, period, burst_frac
):
    process = _regime(
        low, low, burst_rate, t1, t1 + gap, period, period * burst_frac
    )
    end = t0 + span
    dt = span / 4000
    # edges: 2 regime boundaries + up to 2 burst edges per in-window
    # period of the middle regime
    edges = 2 + 2 * (span / period + 2)
    tol = 2 * edges * (low + burst_rate) * dt
    exact = process.arrivals_between(t0, end)
    approx = trapezoid_integral(process, t0, end)
    assert exact == pytest.approx(approx, abs=max(tol, 1e-6), rel=1e-6)


def test_regime_switch_boundaries_are_exact():
    from kube_sqs_autoscaler_tpu.sim.scenarios import RegimeSwitchArrival

    process = RegimeSwitchArrival(regimes=(
        (0.0, ConstantArrival(10.0)),
        (100.0, ConstantArrival(30.0)),
    ))
    # split at the boundary == integral across it, exactly (no seam)
    assert (
        process.arrivals_between(90.0, 100.0)
        + process.arrivals_between(100.0, 110.0)
        == process.arrivals_between(90.0, 110.0)
    )
    assert process.arrivals_between(90.0, 110.0) == 10.0 * 10 + 30.0 * 10
    # the regime runs on its LOCAL clock: a burst regime starting at
    # t=100 fires its first burst at the switch instant
    burst = RegimeSwitchArrival(regimes=(
        (0.0, ConstantArrival(0.0)),
        (100.0, BurstArrival(base=0.0, burst_rate=50.0, period=60.0,
                             burst_len=10.0)),
    ))
    assert burst.rate_at(99.9) == 0.0
    assert burst.rate_at(100.0) == 50.0
    assert burst.arrivals_between(100.0, 110.0) == pytest.approx(500.0)
    assert burst.arrivals_between(0.0, 100.0) == 0.0


def test_regime_switch_validation():
    from kube_sqs_autoscaler_tpu.sim.scenarios import RegimeSwitchArrival

    with pytest.raises(ValueError, match="t=0"):
        RegimeSwitchArrival(regimes=((5.0, ConstantArrival(1.0)),))
    with pytest.raises(ValueError, match="strictly increasing"):
        RegimeSwitchArrival(regimes=(
            (0.0, ConstantArrival(1.0)), (10.0, ConstantArrival(2.0)),
            (10.0, ConstantArrival(3.0)),
        ))


def test_pulse_validation_and_edges():
    from kube_sqs_autoscaler_tpu.sim.scenarios import PulseArrival

    with pytest.raises(ValueError):
        PulseArrival(rate=1.0, start=0.0, width=0.0)
    pulse = PulseArrival(rate=8.0, start=10.0, width=5.0)
    assert pulse.arrivals_between(0.0, 10.0) == 0.0
    assert pulse.arrivals_between(10.0, 15.0) == 40.0
    assert pulse.arrivals_between(15.0, 99.0) == 0.0


@settings(max_examples=25, deadline=None)
@given(seed=st.integers(0, 10_000), n=st.integers(1, 200),
       lo=st.integers(1, 8), extra=st.integers(0, 56),
       alpha=st.floats(0.3, 3.0))
def test_heavy_tail_lengths_seeded_and_bounded(seed, n, lo, extra, alpha):
    from kube_sqs_autoscaler_tpu.sim.scenarios import heavy_tail_lengths

    hi = lo + extra
    tag = f"seed{seed}"
    draws = heavy_tail_lengths(tag, n, lo, hi, alpha)
    assert draws == heavy_tail_lengths(tag, n, lo, hi, alpha)
    assert len(draws) == n
    assert all(lo <= d <= hi for d in draws)


def test_heavy_tail_lengths_are_heavy_tailed():
    from kube_sqs_autoscaler_tpu.sim.scenarios import heavy_tail_lengths

    draws = heavy_tail_lengths("tail-shape", 4000, 1, 64, 1.1)
    import statistics

    # bounded-Pareto signature: mass concentrates at the floor (median
    # near lo) while rare long draws pull the mean well above it
    assert statistics.median(draws) <= 4
    assert statistics.mean(draws) > 1.5 * statistics.median(draws)
    assert max(draws) > 16


def test_variants_cover_composite_shapes():
    import dataclasses as dc

    from kube_sqs_autoscaler_tpu.sim.scenarios import (
        arrival_variant,
        variant_bounds,
    )

    composed = _composed(10.0, 50.0, 60.0, 20.0)
    bounds = variant_bounds(composed)
    assert "part0.rate" in bounds and "part1.start" in bounds
    v1 = arrival_variant(composed, 3, "flash", 0)
    v2 = arrival_variant(composed, 3, "flash", 0)
    v3 = arrival_variant(composed, 4, "flash", 0)
    assert v1 == v2 and v1 != v3
    assert type(v1) is type(composed)
    # parts jitter independently within their declared bounds
    lo, hi = bounds["part1.rate"]
    assert lo - 1e-9 <= v1.parts[1].rate <= hi + 1e-9

    regime = _regime(10.0, 10.0, 80.0, 100.0, 240.0, 60.0, 15.0)
    rv = arrival_variant(regime, 7, "regime", 1)
    assert rv == arrival_variant(regime, 7, "regime", 1)
    starts = [s for s, _ in rv.regimes]
    assert starts[0] == 0.0
    assert all(b > a for a, b in zip(starts, starts[1:]))
    # variant integrals stay exact (same analytic classes recursively)
    exact = rv.arrivals_between(37.0, 333.0)
    approx = trapezoid_integral(rv, 37.0, 333.0, steps=40000)
    assert exact == pytest.approx(approx, rel=2e-3, abs=0.6)


# --- seeded scenario variants (learn/ train-vs-held-out splits) -------------


def _variant_battery():
    from kube_sqs_autoscaler_tpu.sim.evaluate import default_battery

    return list(default_battery())


def test_variants_are_deterministic_per_seed_and_disjoint_across_seeds():
    from kube_sqs_autoscaler_tpu.sim.scenarios import scenario_variants

    base = _variant_battery()
    a = scenario_variants(base, 2, seed=7)
    b = scenario_variants(base, 2, seed=7)
    c = scenario_variants(base, 2, seed=8)
    assert [s.arrival for s in a] == [s.arrival for s in b]
    assert [s.name for s in a] == [s.name for s in b]
    # a different seed re-draws every world (frozen dataclasses compare
    # by value, so equality here would mean an identical parameter draw)
    assert all(x.arrival != y.arrival for x, y in zip(a, c))
    assert len(a) == 2 * len(base)


def test_variants_keep_world_fields_and_tag_names():
    from kube_sqs_autoscaler_tpu.sim.scenarios import scenario_variants

    base = _variant_battery()
    for scenario, variant in zip(base, scenario_variants(base, 1, seed=3)):
        assert variant.name == f"{scenario.name}~v0s3"
        assert variant.duration == scenario.duration
        assert variant.max_pods == scenario.max_pods
        assert variant.slo_depth == scenario.slo_depth
        assert variant.initial_replicas == scenario.initial_replicas
        assert type(variant.arrival) is type(scenario.arrival)


@settings(max_examples=30, deadline=None)
@given(seed=st.integers(0, 10_000), index=st.integers(0, 20),
       jitter=st.floats(0.05, 0.4))
def test_variant_parameters_stay_inside_declared_bounds(seed, index, jitter):
    import dataclasses

    from kube_sqs_autoscaler_tpu.sim.scenarios import (
        DiurnalArrival as Diurnal,
        RampArrival as Ramp,
        arrival_variant,
        variant_bounds,
    )

    for scenario in _variant_battery():
        process = scenario.arrival
        bounds = variant_bounds(process, jitter)
        variant = arrival_variant(
            process, seed, scenario.name, index, jitter
        )
        values = dataclasses.asdict(variant)
        if isinstance(process, Ramp):
            # t_end is declared through the jittered ramp duration
            values["ramp_len"] = values.pop("t_end") - values["t_start"]
        for key, (lo, hi) in bounds.items():
            assert lo - 1e-9 <= values[key] <= hi + 1e-9, (
                scenario.name, key, values[key], (lo, hi),
            )
        # class invariants survive the jitter (the generator clamps
        # within the declared bounds, never outside them)
        if isinstance(variant, Diurnal):
            assert variant.amplitude <= variant.base


@settings(max_examples=15, deadline=None)
@given(seed=st.integers(0, 10_000), t0=st.floats(0.0, 800.0),
       span=st.floats(1.0, 400.0))
def test_variant_integrals_stay_exact(seed, t0, span):
    """Variants are instances of the same analytic classes, so
    arrivals_between must remain the exact integral of rate_at — the
    property both simulators consume verbatim."""
    from kube_sqs_autoscaler_tpu.sim.scenarios import arrival_variant

    t1 = t0 + span
    for scenario in _variant_battery():
        variant = arrival_variant(scenario.arrival, seed, scenario.name, 0)
        exact = variant.arrivals_between(t0, t1)
        approx = trapezoid_integral(variant, t0, t1, steps=8000)
        scale = max(abs(exact), 1.0)
        assert exact == pytest.approx(approx, rel=5e-3, abs=0.05 * scale), (
            scenario.name, t0, t1,
        )
