"""Shutdown semantics: stop is sticky across the run() boundary (the
SIGTERM-before-run race) and reset() re-arms a stopped loop.
"""

import pytest

from kube_sqs_autoscaler_tpu.core.clock import FakeClock
from kube_sqs_autoscaler_tpu.core.loop import ControlLoop, LoopConfig
from kube_sqs_autoscaler_tpu.core.policy import PolicyConfig
from kube_sqs_autoscaler_tpu.metrics import FakeQueueService, QueueMetricSource
from kube_sqs_autoscaler_tpu.scale import FakeDeploymentAPI, PodAutoScaler


def make_loop():
    api = FakeDeploymentAPI.with_deployments("ns", 3, "deploy")
    scaler = PodAutoScaler(
        client=api, max=5, min=1, scale_up_pods=1, scale_down_pods=1,
        deployment="deploy", namespace="ns",
    )
    queue = FakeQueueService.with_depths(50)
    return ControlLoop(
        scaler,
        QueueMetricSource(client=queue, queue_url="q"),
        LoopConfig(poll_interval=1.0, policy=PolicyConfig()),
        clock=FakeClock(),
    ), queue


def test_stop_before_run_prevents_any_tick():
    # The SIGTERM-before-run race: a stop that lands before run() must hold.
    loop, queue = make_loop()
    loop.stop()
    loop.run()  # must return immediately, forever-run notwithstanding
    assert loop.ticks == 0
    assert queue.get_calls == 0


def test_reset_rearms_a_stopped_loop():
    loop, queue = make_loop()
    loop.stop()
    loop.run()
    assert loop.ticks == 0
    loop.reset()
    loop.run(max_ticks=2)
    assert loop.ticks == 2
    assert queue.get_calls == 2


def test_model_rejects_overlong_sequence():
    import jax
    import jax.numpy as jnp

    from kube_sqs_autoscaler_tpu.workloads.model import (
        ModelConfig,
        forward,
        init_params,
    )

    config = ModelConfig(
        vocab_size=64, d_model=128, n_heads=4, n_layers=1, d_ff=256,
        max_seq_len=16,
    )
    params = init_params(jax.random.key(0), config)
    tokens = jnp.zeros((1, 17), jnp.int32)
    with pytest.raises(ValueError, match="exceeds max_seq_len"):
        forward(params, tokens, config)
