"""Journal replay: deterministic re-drive + counterfactual re-scoring.

The acceptance bar (ISSUE 2): a journal recorded from a simulated episode,
replayed through ``sim/replay.py``, reproduces the recorded gate decisions
and replica trajectory tick-for-tick; the same journal re-scores under any
other policy/forecaster through the battery's scoring.
"""

import dataclasses

import pytest

from kube_sqs_autoscaler_tpu.core.policy import Gate
from kube_sqs_autoscaler_tpu.obs.journal import read_journal
from kube_sqs_autoscaler_tpu.sim import BurstArrival, SimConfig, StepArrival
from kube_sqs_autoscaler_tpu.sim.replay import (
    RecordedArrival,
    counterfactual,
    infer_arrivals,
    record_episode,
    replay,
    replay_journal,
    sim_journal_meta,
)


def demo_config(**overrides) -> SimConfig:
    defaults = dict(
        arrival_rate=BurstArrival(
            base=20.0, burst_rate=140.0, period=120.0,
            burst_len=40.0, first_burst=30.0,
        ),
        service_rate_per_replica=10.0,
        duration=200.0,
        initial_replicas=2,
        max_pods=10,
    )
    defaults.update(overrides)
    return SimConfig(**defaults)


def record(tmp_path, **overrides):
    path = str(tmp_path / "journal.jsonl")
    meta, result = record_episode(demo_config(**overrides), path)
    return path, meta, result


# --- deterministic re-drive -------------------------------------------------


def test_replay_reproduces_recorded_decisions_tick_for_tick(tmp_path):
    path, _, _ = record(tmp_path)
    meta, records = read_journal(path)
    result = replay(records, meta)
    assert result.ticks == len(records) == 40  # 200 s / 5 s poll
    assert result.divergences == []
    assert result.ok
    # the episode actually exercised the interesting paths
    assert any(r.up is Gate.FIRE for r in records)
    assert any(r.up is Gate.COOLING for r in records)


def test_replay_reproduces_replica_trajectory(tmp_path):
    path, _, sim_result = record(tmp_path)
    result = replay_journal(path)
    # sim timeline entry k = replicas entering tick k (observed mid-read);
    # the replayed trajectory must match at every tick
    recorded_replicas = [r for (_, _, r) in sim_result.timeline]
    assert result.start_replicas == recorded_replicas[: result.ticks]
    assert result.final_replicas == sim_result.final_replicas


def test_replay_detects_a_tampered_decision(tmp_path):
    path, _, _ = record(tmp_path)
    meta, records = read_journal(path)
    fired = next(i for i, r in enumerate(records) if r.up is Gate.FIRE)
    records[fired] = dataclasses.replace(records[fired], up=Gate.IDLE)
    result = replay(records, meta)
    assert not result.ok
    assert any(
        d.tick == fired and d.tick_field == "up" for d in result.divergences
    )


def test_replay_reproduces_recorded_actuation_failures():
    """A recorded scale failure must replay as a failure (policy state not
    advanced), not as a success that shifts every later cooldown."""
    from kube_sqs_autoscaler_tpu.core.events import TickRecord

    meta = {
        "t0": 0.0,
        "poll_interval": 5.0,
        "policy_config": {
            "scale_up_messages": 100, "scale_down_messages": 10,
            "scale_up_cooldown": 10.0, "scale_down_cooldown": 30.0,
        },
        "policy": "reactive",
        "world": {"initial_replicas": 1, "min_pods": 1, "max_pods": 5,
                  "scale_up_pods": 1, "scale_down_pods": 1},
    }
    records = [
        TickRecord(start=5.0, num_messages=200, decision_messages=200,
                   up=Gate.COOLING),  # startup grace
        TickRecord(start=10.0, num_messages=200, decision_messages=200,
                   up=Gate.FIRE, up_error="Failed to scale up"),
        # failure did NOT advance the cooldown: the next tick fires again
        TickRecord(start=15.0, num_messages=200, decision_messages=200,
                   up=Gate.FIRE, down=Gate.IDLE),
        TickRecord(start=20.0, num_messages=200, decision_messages=200,
                   up=Gate.COOLING),
    ]
    result = replay(records, meta)
    assert result.divergences == []
    assert result.final_replicas == 2  # only the successful fire actuated


def test_replay_reproduces_metric_failure_ticks():
    from kube_sqs_autoscaler_tpu.core.events import TickRecord

    meta = {
        "t0": 0.0, "poll_interval": 5.0, "policy": "reactive",
        "policy_config": {
            "scale_up_messages": 100, "scale_down_messages": 10,
            "scale_up_cooldown": 10.0, "scale_down_cooldown": 30.0,
        },
        "world": {"initial_replicas": 1, "min_pods": 1, "max_pods": 5,
                  "scale_up_pods": 1, "scale_down_pods": 1},
    }
    records = [
        TickRecord(start=5.0, metric_error="Failed to get messages in SQS"),
        TickRecord(start=10.0, num_messages=50, decision_messages=50,
                   up=Gate.IDLE, down=Gate.IDLE),
    ]
    result = replay(records, meta)
    assert result.divergences == []


def test_replay_of_predictive_episode(tmp_path):
    """Predictive journals replay through the rebuilt forecaster+history —
    the jit forecast pipeline is deterministic, so decisions reproduce."""
    path, _, _ = record(
        tmp_path, policy="predictive", forecaster="holt",
        forecast_horizon=30.0, duration=150.0,
    )
    meta, records = read_journal(path)
    assert meta["policy"] == "predictive"
    assert meta["forecast"]["forecaster"] == "holt"
    result = replay(records, meta)
    assert result.divergences == []
    # the forecast actually moved at least one decision off the observation
    assert any(
        r.decision_messages != r.num_messages
        for r in records
        if r.num_messages is not None
    )


def test_replay_empty_journal_raises(tmp_path):
    with pytest.raises(ValueError):
        replay([], {"poll_interval": 5.0})


# --- arrival inference ------------------------------------------------------


def test_recorded_arrival_integrates_piecewise():
    arrival = RecordedArrival(times=(0.0, 10.0, 20.0), rates=(1.0, 3.0, 0.5))
    assert arrival.rate_at(5.0) == 1.0
    assert arrival.rate_at(10.0) == 3.0
    assert arrival.rate_at(100.0) == 0.5
    assert arrival.arrivals_between(0.0, 30.0) == pytest.approx(
        1.0 * 10 + 3.0 * 10 + 0.5 * 10
    )
    assert arrival.arrivals_between(5.0, 15.0) == pytest.approx(
        1.0 * 5 + 3.0 * 5
    )
    # before the first boundary the first rate extends backwards
    assert arrival.arrivals_between(-10.0, 5.0) == pytest.approx(15.0)


def test_inferred_arrivals_reproduce_recorded_world(tmp_path):
    """The fidelity identity behind counterfactuals: re-simulating the
    inferred arrivals under the SAME policy reproduces the recorded
    episode's scorecard (depth floored per-interval, int observations)."""
    from kube_sqs_autoscaler_tpu.sim.evaluate import score_result

    path, _, sim_result = record(tmp_path)
    meta, records = read_journal(path)
    rescored = counterfactual(records, meta, policy="reactive")
    recorded = score_result(sim_result, 300.0)
    assert rescored["replica_changes"] == recorded["replica_changes"]
    assert rescored["final_replicas"] == recorded["final_replicas"]
    assert rescored["max_depth"] == pytest.approx(
        recorded["max_depth"], rel=0.02
    )
    assert rescored["time_over_slo_s"] == pytest.approx(
        recorded["time_over_slo_s"], abs=10.0
    )


def test_infer_arrivals_requires_world_meta(tmp_path):
    path, _, _ = record(tmp_path)
    meta, records = read_journal(path)
    del meta["world"]["service_rate_per_replica"]
    with pytest.raises(ValueError, match="service_rate_per_replica"):
        infer_arrivals(records, meta)


# --- counterfactual re-scoring ----------------------------------------------


def test_counterfactual_scores_other_policies_on_the_recorded_world(tmp_path):
    path, _, _ = record(tmp_path)
    meta, records = read_journal(path)
    row = counterfactual(
        records, meta, policy="predictive", forecaster="ewma", horizon=30.0
    )
    assert row["policy"] == "predictive:ewma"
    assert row["ticks"] == len(records)
    for key in ("max_depth", "time_over_slo_s", "replica_changes"):
        assert key in row


def test_sim_journal_meta_round_trips_loop_config():
    from kube_sqs_autoscaler_tpu.sim.replay import loop_config_from_meta

    config = demo_config()
    meta = sim_journal_meta(config)
    rebuilt = loop_config_from_meta(meta)
    assert rebuilt == config.loop


# --- the make replay-demo entry ---------------------------------------------


def test_replay_main_records_and_verifies(tmp_path, capsys):
    import json

    from kube_sqs_autoscaler_tpu.sim.replay import main

    journal = str(tmp_path / "demo.jsonl")
    assert main(["--record-to", journal]) == 0
    verdict = json.loads(capsys.readouterr().out)
    assert verdict["ok"] is True and verdict["divergences"] == 0
    # the journal it wrote replays standalone too
    assert main(["--journal", journal]) == 0


def test_replay_main_fails_on_divergence(tmp_path, capsys):
    """The make replay-demo contract: decision drift exits non-zero."""
    import json

    from kube_sqs_autoscaler_tpu.sim.replay import main

    path = str(tmp_path / "journal.jsonl")
    record_episode(demo_config(), path)
    meta, records = read_journal(path)
    # tamper: claim a fired gate never fired, rewrite the journal
    from kube_sqs_autoscaler_tpu.obs.journal import TickJournal

    fired = next(i for i, r in enumerate(records) if r.up is Gate.FIRE)
    records[fired] = dataclasses.replace(records[fired], up=Gate.IDLE)
    tampered = str(tmp_path / "tampered.jsonl")
    with TickJournal(tampered, meta=meta) as journal:
        for r in records:
            journal.on_tick(r)
    assert main(["--journal", tampered]) == 2
    out = capsys.readouterr()
    assert json.loads(out.out)["ok"] is False


# --- review-finding regressions ---------------------------------------------


def test_replay_journal_replays_last_episode_of_restarted_file(tmp_path):
    """A restarted controller appends a second episode with its own clock
    epoch and startup grace; replaying the flattened file as one run would
    report spurious divergences — replay_journal must pick one episode."""
    path = str(tmp_path / "journal.jsonl")
    record_episode(demo_config(duration=100.0), path)
    meta2, result2 = record_episode(demo_config(duration=150.0), path)
    result = replay_journal(path)
    assert result.ok
    assert result.ticks == 30  # the LAST episode only (150 s / 5 s)
    assert result.final_replicas == result2.final_replicas


def test_counterfactual_handles_wall_clock_epochs():
    """Live journals carry time.monotonic() epochs and no t0; the inferred
    arrivals must land in the rebuilt sim's 0-based window, not 800k
    seconds away from it (review finding: silent garbage world)."""
    from kube_sqs_autoscaler_tpu.core.events import TickRecord

    epoch = 812345.678  # a plausible monotonic reading
    meta = {
        "source": "live",
        "poll_interval": 5.0,
        "policy_config": {
            "scale_up_messages": 100, "scale_down_messages": 10,
            "scale_up_cooldown": 10.0, "scale_down_cooldown": 30.0,
        },
        "policy": "reactive",
        "world": {
            "service_rate_per_replica": 10.0, "initial_depth": 100.0,
            "initial_replicas": 1, "min_pods": 1, "max_pods": 5,
            "scale_up_pods": 1, "scale_down_pods": 1,
        },
    }
    # steady observed depth 100 with 1 replica at 10 msg/s ⇒ the implied
    # arrival rate is exactly 10 msg/s on every interval
    records = [
        TickRecord(start=epoch + 5.0 * (i + 1), num_messages=100,
                   decision_messages=100, up=Gate.COOLING)
        for i in range(8)
    ]
    arrival = infer_arrivals(records, meta)
    assert arrival.times[0] == 0.0  # episode-relative, not wall-clock
    assert all(rate == pytest.approx(10.0) for rate in arrival.rates)
    row = counterfactual(records, meta, policy="reactive", slo_depth=300.0)
    # a faithful world: the backlog stays at the observed plateau instead
    # of the runaway (or empty) world a broken time base would produce
    assert row["max_depth"] == pytest.approx(100.0, abs=10.0)
    assert row["time_over_slo_s"] == 0.0


def test_counterfactual_duration_counts_metric_failure_ticks(tmp_path):
    """Metric-failure ticks consumed a poll interval; dropping them from
    the duration would score a truncated world (review finding)."""
    path, _, _ = record(tmp_path)
    meta, records = read_journal(path)
    failed = dataclasses.replace(
        records[3], num_messages=None, decision_messages=None,
        metric_error="Failed to get messages in SQS",
        up=Gate.SKIPPED, down=Gate.SKIPPED, up_error=None, down_error=None,
    )
    records[3] = failed
    row = counterfactual(records, meta, policy="reactive")
    assert row["ticks"] == len(records)  # 40, not 39


def test_replay_journal_rejoins_episode_across_rotation(tmp_path):
    """Size rotation splits one episode across <path>.1 and the live file;
    replay must rejoin it instead of re-applying startup grace mid-episode
    (which would report false divergences on a healthy build)."""
    import os

    from kube_sqs_autoscaler_tpu.obs.journal import TickJournal
    from kube_sqs_autoscaler_tpu.sim import Simulation

    config = demo_config()  # 40 ticks ≈ 6 KB of journal
    path = str(tmp_path / "journal.jsonl")
    with TickJournal(path, meta=sim_journal_meta(config),
                     max_bytes=4096) as journal:
        Simulation(config, extra_observers=(journal,)).run()
    assert os.path.exists(path + ".1")  # rotation actually happened
    meta, _ = read_journal(path)
    assert meta["_continuation"] is True
    result = replay_journal(path)
    assert result.ok and result.ticks == 40  # the FULL rejoined episode


def test_replay_journal_refuses_when_episode_head_rotated_away(tmp_path):
    from kube_sqs_autoscaler_tpu.obs.journal import TickJournal
    from kube_sqs_autoscaler_tpu.sim import Simulation

    config = demo_config(duration=700.0)  # ≈ 20 KB: several rotations
    path = str(tmp_path / "journal.jsonl")
    with TickJournal(path, meta=sim_journal_meta(config),
                     max_bytes=4096) as journal:
        Simulation(config, extra_observers=(journal,)).run()
    with pytest.raises(ValueError, match="rotation continuation"):
        replay_journal(path)


def test_live_journal_without_initial_replicas_flags_assumed_trajectory():
    """The live CLI meta deliberately omits initial_replicas (the
    controller cannot know the deployment's size); replay must mark the
    trajectory as assumed rather than reporting it as authoritative."""
    from kube_sqs_autoscaler_tpu.core.events import TickRecord

    meta = {
        "source": "live", "poll_interval": 5.0, "policy": "reactive",
        "policy_config": {
            "scale_up_messages": 100, "scale_down_messages": 10,
            "scale_up_cooldown": 10.0, "scale_down_cooldown": 30.0,
        },
        "world": {"min_pods": 1, "max_pods": 5,
                  "scale_up_pods": 1, "scale_down_pods": 1},
    }
    records = [TickRecord(start=5.0, num_messages=50, decision_messages=50,
                          up=Gate.IDLE, down=Gate.IDLE)]
    result = replay(records, meta)
    assert result.ok
    assert result.assumed_initial_replicas
    # sim journals carry the real start: not assumed
    assert "initial_replicas" in sim_journal_meta(demo_config())["world"]


def test_replay_journal_restart_header_rotated_out_before_first_tick(tmp_path):
    """Restart onto a nearly-full journal: the restart header is rotated
    into <path>.1 with zero ticks before the new run's first tick lands.
    The rejoin must treat that empty episode as the episode boundary — not
    graft the previous run's records onto the new episode (review repro:
    a 3-tick episode replayed as a 28-tick hybrid of two runs)."""
    import os

    from kube_sqs_autoscaler_tpu.core.events import TickRecord
    from kube_sqs_autoscaler_tpu.obs.journal import TickJournal

    path = str(tmp_path / "journal.jsonl")
    # run 1: fill to just under the rotation threshold without tripping it
    with TickJournal(path, meta={"run": 1}, max_bytes=4096) as journal:
        i = 0
        while os.path.getsize(path) < 3700:
            journal.on_tick(
                TickRecord(start=5.0 * (i + 1), num_messages=50,
                           decision_messages=50, up=Gate.IDLE, down=Gate.IDLE)
            )
            i += 1
    assert not os.path.exists(path + ".1")  # run 1 never rotated
    # run 2 (restart): header appends past the threshold; the FIRST tick
    # trips rotation, sweeping the empty run-2 header into <path>.1
    meta2 = {
        "run": 2, "t0": 0.0, "poll_interval": 5.0, "policy": "reactive",
        "policy_config": {
            "scale_up_messages": 100, "scale_down_messages": 10,
            "scale_up_cooldown": 10.0, "scale_down_cooldown": 30.0,
        },
        "world": {"initial_replicas": 1, "min_pods": 1, "max_pods": 5,
                  "scale_up_pods": 1, "scale_down_pods": 1},
    }
    run2 = [
        TickRecord(start=5.0, num_messages=200, decision_messages=200,
                   up=Gate.COOLING),
        TickRecord(start=10.0, num_messages=200, decision_messages=200,
                   up=Gate.FIRE, down=Gate.IDLE),
        TickRecord(start=15.0, num_messages=200, decision_messages=200,
                   up=Gate.COOLING),
    ]
    with TickJournal(path, meta=meta2, max_bytes=4096) as journal:
        for record in run2:
            journal.on_tick(record)
    from kube_sqs_autoscaler_tpu.obs.journal import read_journal_episodes

    assert os.path.exists(path + ".1")
    assert read_journal_episodes(path + ".1")[-1] == (meta2, [])  # the boundary
    result = replay_journal(path)
    assert result.ticks == 3  # run 2 only, NOT run 1's records grafted on
    assert result.ok
    assert result.final_replicas == 2


def test_counterfactual_honors_recorded_forecast_config(tmp_path):
    """Re-scoring 'the recorded policy' must rebuild its recorded warm-up
    and gating config, not the defaults — matching what replay() does."""
    path, _, _ = record(
        tmp_path, policy="predictive", forecaster="ewma",
        forecast_horizon=30.0, forecast_min_samples=10,
        forecast_conservative=False, forecast_history=64, duration=100.0,
    )
    meta, records = read_journal(path)
    assert meta["forecast"] == {
        "forecaster": "ewma", "horizon": 30.0, "history": 64,
        "min_samples": 10, "conservative": False,
    }
    row = counterfactual(records, meta, policy="predictive",
                         forecaster="ewma")
    assert row["ticks"] == len(records)
    # the rebuilt sim under the SAME policy+config reproduces the recorded
    # churn exactly — with default min_samples/conservative it would not
    from kube_sqs_autoscaler_tpu.sim.replay import replay as _replay

    assert _replay(records, meta).ok


# --- resilient episodes (stale-depth hold) replay tick-for-tick -------------


def _stale_hold_config(**overrides) -> SimConfig:
    """Overloaded world + metric blackout: the episode records fresh
    ticks, stale-held ticks, TTL-expired fail-static ticks, and recovery
    (metric_retries stays 0 so live in-tick clock consumption matches
    the replayed loop exactly)."""
    from kube_sqs_autoscaler_tpu.core.resilience import ResilienceConfig
    from kube_sqs_autoscaler_tpu.sim.faults import Blackout

    defaults = dict(
        arrival_rate=StepArrival(before=20.0, after=120.0, at=30.0),
        service_rate_per_replica=10.0,
        duration=300.0,
        initial_replicas=2,
        max_pods=15,
        faults=Blackout(start=60.0, duration=120.0, metric=True),
        resilience=ResilienceConfig(stale_depth_ttl=60.0),
    )
    defaults.update(overrides)
    return SimConfig(**defaults)


def test_reactive_stale_hold_episode_replays_exactly(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    record_episode(_stale_hold_config(), path)
    meta, records = read_journal(path)
    assert meta["resilience"]["stale_depth_ttl"] == 60.0
    stale = [r for r in records if r.stale]
    static = [r for r in records if r.metric_error is not None]
    assert stale and static  # the episode exercises hold AND expiry
    result = replay(records, meta)
    assert result.ok, result.format_divergences()
    # the replayed loop re-derived the holds, not transcribed them
    assert [r.stale for r in result.records] == [r.stale for r in records]


def test_predictive_stale_hold_episode_replays_exactly(tmp_path):
    # the regression shape: held depths must NOT enter the replayed
    # forecaster history (the live DepthHistory skipped them), or the
    # forecast — and with it decision_messages — diverges mid-episode
    pytest.importorskip("jax")
    path = str(tmp_path / "journal.jsonl")
    record_episode(
        _stale_hold_config(
            policy="predictive", forecaster="ewma", forecast_horizon=30.0
        ),
        path,
    )
    meta, records = read_journal(path)
    assert any(r.stale for r in records)
    result = replay(records, meta)
    assert result.ok, result.format_divergences()


def test_stale_records_without_ttl_meta_flag_divergence(tmp_path):
    # a journal whose records carry stale ticks but whose meta lost the
    # resilience block cannot re-derive the holds — replay must say so
    # loudly (divergences), never silently feed held depths as fresh
    path = str(tmp_path / "journal.jsonl")
    record_episode(_stale_hold_config(), path)
    meta, records = read_journal(path)
    del meta["resilience"]
    result = replay(records, meta)
    assert not result.ok
    assert any(d.tick_field == "stale" for d in result.divergences)
