"""Ring attention correctness: sequence-parallel attention over the mesh
must reproduce dense causal attention exactly (up to fp tolerance), and the
full dp x sp x tp train step must run and learn.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kube_sqs_autoscaler_tpu.workloads.model import ModelConfig, forward, init_params
from kube_sqs_autoscaler_tpu.workloads.ring import (
    dense_causal_attention,
    make_ring_attention,
)
from kube_sqs_autoscaler_tpu.workloads.train import (
    TrainConfig,
    batch_sharding,
    init_train_state,
    make_mesh,
    make_train_step,
    mesh_attention_fn,
    place_state,
)

TINY = ModelConfig(
    vocab_size=256, d_model=128, n_heads=8, n_layers=2, d_ff=256, max_seq_len=64
)


def qkv(batch=8, heads=8, seq=32, dim=16, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.key(seed), 3)
    shape = (batch, heads, seq, dim)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


@pytest.mark.parametrize("seq_parallel", [2, 4, 8])
def test_ring_matches_dense_causal(seq_parallel):
    mesh = make_mesh(jax.devices(), model_parallel=1, seq_parallel=seq_parallel)
    q, k, v = qkv()
    expected = dense_causal_attention(q, k, v)
    ring_fn = make_ring_attention(mesh)
    actual = jax.jit(ring_fn)(q, k, v)
    np.testing.assert_allclose(
        np.asarray(expected), np.asarray(actual), rtol=1e-5, atol=1e-5
    )


@pytest.mark.parametrize("window", [5, 16, 31])
def test_windowed_ring_matches_windowed_dense(window):
    # the sliding-window x sequence-parallelism composition: the per-hop
    # global band mask must reproduce the dense windowed path exactly,
    # including windows that cross shard boundaries
    mesh = make_mesh(jax.devices(), model_parallel=1, seq_parallel=4)
    q, k, v = qkv()
    expected = dense_causal_attention(q, k, v, window=window)
    ring_fn = make_ring_attention(mesh, window=window)
    actual = jax.jit(ring_fn)(q, k, v)
    np.testing.assert_allclose(
        np.asarray(expected), np.asarray(actual), rtol=1e-5, atol=1e-5
    )


def test_windowed_ring_gqa_and_grads_match_dense():
    from kube_sqs_autoscaler_tpu.workloads.llama import repeat_kv

    mesh = make_mesh(jax.devices(), model_parallel=2, seq_parallel=2)
    q, _, _ = qkv(batch=4, heads=4, seq=16, dim=8, seed=5)
    _, k, v = (None, *qkv(batch=4, heads=2, seq=16, dim=8, seed=6)[1:])
    window = 7

    def ring_loss(q, k, v):
        out = make_ring_attention(mesh, window=window)(q, k, v)
        return jnp.mean(out.astype(jnp.float32) ** 2)

    def dense_loss(q, k, v):
        out = dense_causal_attention(
            q, repeat_kv(k, 2), repeat_kv(v, 2), window=window
        )
        return jnp.mean(out.astype(jnp.float32) ** 2)

    ring_grads = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    dense_grads = jax.jit(jax.grad(dense_loss, argnums=(0, 1, 2)))(q, k, v)
    # both losses take compact GQA k/v (autodiff through the broadcast
    # sums the groups), so the grad trees compare leaf for leaf
    for got, ref in zip(ring_grads, dense_grads):
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)


def test_trainer_windowed_llama_seq_parallel_trains():
    # Mistral-style long-context training under sp from the binary —
    # previously a fail-fast ("ring attention has no windowed schedule")
    from kube_sqs_autoscaler_tpu.workloads.trainer import main

    base = [
        "--vocab-size", "256", "--d-model", "64", "--n-heads", "4",
        "--n-layers", "2", "--d-ff", "128", "--seq-len", "32",
        "--batch-size", "8", "--learning-rate", "1e-2", "--log-every", "1",
        "--steps", "4", "--family", "llama", "--n-kv-heads", "2",
        "--sliding-window", "8", "--overfit",
    ]
    result = main(base + ["--seq-parallel", "2"])
    assert result["final_step"] == 4
    assert all(np.isfinite(result["losses"]))
    assert result["losses"][-1] < result["losses"][0]

    # the window does NOT compose with the permuted zig-zag schedule —
    # loudly, not as a silent full-causal drop
    with pytest.raises(ValueError, match="zig-zag"):
        main(base + ["--seq-parallel", "2", "--zigzag"])
    # nor with the gpt family (no windowed config)
    with pytest.raises(SystemExit, match="llama"):
        main(["--steps", "1", "--family", "gpt", "--sliding-window", "8"])


def test_ring_matches_dense_with_tp_and_dp():
    # full 3-axis layout: data=2, seq=2, model=2 — heads sharded too
    mesh = make_mesh(jax.devices(), model_parallel=2, seq_parallel=2)
    assert mesh.shape == {"data": 2, "seq": 2, "model": 2}
    q, k, v = qkv(batch=4, heads=4, seq=16, dim=8, seed=3)
    expected = dense_causal_attention(q, k, v)
    actual = jax.jit(make_ring_attention(mesh))(q, k, v)
    np.testing.assert_allclose(
        np.asarray(expected), np.asarray(actual), rtol=1e-5, atol=1e-5
    )


def test_ring_is_causal_across_shard_boundaries():
    # perturb a token in the last sequence shard; earlier shards' outputs
    # must be bit-identical
    mesh = make_mesh(jax.devices(), model_parallel=1, seq_parallel=4)
    ring_fn = jax.jit(make_ring_attention(mesh))
    q, k, v = qkv(seq=32, seed=5)
    base = np.asarray(ring_fn(q, k, v))
    k2 = k.at[:, :, 31, :].add(1.0)
    v2 = v.at[:, :, 31, :].add(1.0)
    pert = np.asarray(ring_fn(q, k2, v2))
    np.testing.assert_array_equal(base[:, :, :24], pert[:, :, :24])
    assert not np.allclose(base[:, :, 31], pert[:, :, 31])


def test_seq_parallel_forward_matches_dense_model():
    # whole-model equivalence: forward() with ring attention on a seq-sharded
    # mesh == forward() with the default dense path
    mesh = make_mesh(jax.devices(), model_parallel=2, seq_parallel=2)
    params = init_params(jax.random.key(0), TINY)
    tokens = jax.random.randint(jax.random.key(1), (2, 32), 0, TINY.vocab_size,
                                jnp.int32)
    dense = forward(params, tokens, TINY)
    ring_fn = mesh_attention_fn(mesh)
    assert ring_fn is not None
    sharded = jax.jit(lambda p, t: forward(p, t, TINY, ring_fn))(
        params, jax.device_put(tokens, batch_sharding(mesh))
    )
    np.testing.assert_allclose(
        np.asarray(dense), np.asarray(sharded), rtol=2e-2, atol=2e-2
    )


def test_train_step_with_all_three_axes_learns():
    mesh = make_mesh(jax.devices(), model_parallel=2, seq_parallel=2)
    config = TrainConfig(learning_rate=1e-2)
    state = place_state(mesh, init_train_state(jax.random.key(0), TINY, config))
    step_fn = make_train_step(mesh, TINY, config, state)
    tokens = jax.device_put(
        jax.random.randint(jax.random.key(1), (4, 32), 0, TINY.vocab_size,
                           jnp.int32),
        batch_sharding(mesh),
    )
    losses = []
    for _ in range(4):
        state, loss = step_fn(state, tokens)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_trivial_seq_axis_uses_sharded_flash_dispatcher():
    # seq=1 meshes get the per-shard flash-or-dense dispatcher (the train
    # hot path), which is GQA-native; only seq>1 meshes use ring attention
    mesh = make_mesh(jax.devices())  # seq=1
    attend = mesh_attention_fn(mesh)
    assert attend is not None
    assert getattr(attend, "gqa_native", False)


def test_ring_gqa_matches_broadcast_dense():
    """Compact [B, H_kv, S, D] k/v rotate around the ring and must equal
    repeat_kv + dense causal (the llama family's sp path)."""
    from kube_sqs_autoscaler_tpu.workloads.llama import repeat_kv

    mesh = make_mesh(jax.devices(), model_parallel=2, seq_parallel=2)
    keys = jax.random.split(jax.random.key(7), 3)
    q = jax.random.normal(keys[0], (2, 4, 32, 16), jnp.float32)
    k = jax.random.normal(keys[1], (2, 2, 32, 16), jnp.float32)
    v = jax.random.normal(keys[2], (2, 2, 32, 16), jnp.float32)
    ring_fn = make_ring_attention(mesh)
    assert ring_fn.gqa_native
    expected = dense_causal_attention(q, repeat_kv(k, 2), repeat_kv(v, 2))
    np.testing.assert_allclose(
        np.asarray(ring_fn(q, k, v)), np.asarray(expected),
        rtol=2e-5, atol=2e-5,
    )


def test_ring_matches_dense_bf16():
    # the production dtype: bf16 q/k/v take the MXU fast path (storage
    # dtype into the score matmul, fp32 accumulation, probs rounded to
    # bf16 for the value matmul) — the same convention as the dense path,
    # so ring == dense stays tight even in bf16
    mesh = make_mesh(jax.devices(), model_parallel=1, seq_parallel=4)
    q, k, v = qkv(dtype=jnp.bfloat16)
    expected = dense_causal_attention(q, k, v)
    actual = jax.jit(make_ring_attention(mesh))(q, k, v)
    assert actual.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(expected, np.float32), np.asarray(actual, np.float32),
        rtol=2e-2, atol=2e-2,
    )


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16],
                         ids=["fp32", "bf16"])
def test_ring_kernel_path_matches_dense(dtype):
    # the flash kernel as the per-hop local op (interpret mode on CPU):
    # same math as dense causal attention, with later hops skipped
    mesh = make_mesh(jax.devices(), model_parallel=1, seq_parallel=4)
    q, k, v = qkv(dtype=dtype)
    expected = dense_causal_attention(q, k, v)
    ring_fn = make_ring_attention(mesh, use_kernel=True, interpret=True)
    actual = jax.jit(ring_fn)(q, k, v)
    tol = 1e-5 if dtype == jnp.float32 else 2e-2
    np.testing.assert_allclose(
        np.asarray(expected, np.float32), np.asarray(actual, np.float32),
        rtol=tol, atol=tol,
    )


def test_ring_kernel_path_gqa_and_grads():
    from kube_sqs_autoscaler_tpu.workloads.llama import repeat_kv

    mesh = make_mesh(jax.devices(), model_parallel=1, seq_parallel=2)
    keys = jax.random.split(jax.random.key(5), 3)
    q = jax.random.normal(keys[0], (4, 4, 32, 16), jnp.float32)
    k = jax.random.normal(keys[1], (4, 2, 32, 16), jnp.float32)
    v = jax.random.normal(keys[2], (4, 2, 32, 16), jnp.float32)
    ring_fn = make_ring_attention(mesh, use_kernel=True, interpret=True)
    expected = dense_causal_attention(q, repeat_kv(k, 2), repeat_kv(v, 2))
    np.testing.assert_allclose(
        np.asarray(jax.jit(ring_fn)(q, k, v)), np.asarray(expected),
        rtol=1e-5, atol=1e-5,
    )

    # the whole ring (kernel hops + cross-hop merge + ppermutes) must
    # differentiate to the dense gradients
    def loss_ring(q, k, v):
        return jnp.mean(ring_fn(q, k, v).astype(jnp.float32) ** 2)

    def loss_dense(q, k, v):
        return jnp.mean(
            dense_causal_attention(
                q, repeat_kv(k, 2), repeat_kv(v, 2)
            ).astype(jnp.float32) ** 2
        )

    got = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    want = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for g, w, name in zip(got, want, "qkv"):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), rtol=1e-4, atol=1e-5,
            err_msg=f"d{name}",
        )


def test_ring_kernel_gate_falls_back_on_non_tiling_local_shape():
    # S_local = 48 (seq 96 over 2 shards): 48 tiles (block 48 <= 128), but
    # S_local = 192 would pick block 128 and not divide — the gate must
    # route such shapes to the einsum body instead of raising.  Forcing
    # use_kernel=True with a 192-per-shard input exercises the fallback.
    from kube_sqs_autoscaler_tpu.workloads.flash import tiles_cleanly

    assert tiles_cleanly(128) and tiles_cleanly(48) and tiles_cleanly(512)
    assert not tiles_cleanly(192)
    mesh = make_mesh(jax.devices(), model_parallel=1, seq_parallel=2)
    q, k, v = qkv(batch=4, heads=4, seq=384, dim=16)  # S_local=192
    ring_fn = make_ring_attention(mesh, use_kernel=True, interpret=True)
    out = jax.jit(ring_fn)(q, k, v)  # would raise without the gate
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dense_causal_attention(q, k, v)),
        rtol=1e-5, atol=1e-5,
    )
