"""Token-level serving twin: cycle-exact fidelity vs the real sharded
plane, serving-unit training/scoring plumbing, checkpoint twin-kind
deployment seams, and the serving sweep path.

Tier-1 (CPU JAX, tiny model, short episodes).  The full battery at the
committed BENCH_r17 configuration runs in the slow tier.
"""

import dataclasses
import json

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from kube_sqs_autoscaler_tpu.learn.checkpoint import (  # noqa: E402
    CheckpointError,
    PolicyCheckpoint,
    checkpoint_twin,
    load_checkpoint,
    save_checkpoint,
)
from kube_sqs_autoscaler_tpu.learn.network import param_count  # noqa: E402
from kube_sqs_autoscaler_tpu.learn.serving import (  # noqa: E402
    ServingESConfig,
    serving_reference_scales,
    serving_reward_vector,
    train_serving,
)
from kube_sqs_autoscaler_tpu.sim.scenarios import (  # noqa: E402
    ConstantArrival,
    RampArrival,
)
from kube_sqs_autoscaler_tpu.sim.twin import (  # noqa: E402
    ServingScenario,
    twin_variants,
    verify_twin_fidelity,
)
from kube_sqs_autoscaler_tpu.sim.twin.compiled import (  # noqa: E402
    SERVING_SUMMARY_KEYS,
    TwinConfig,
    run_twin_episodes,
    run_twin_grouped,
    serving_lex_key,
    twin_config_for_point,
)
from kube_sqs_autoscaler_tpu.sim.twin.host import run_host_episode  # noqa: E402


def small_scenario(**overrides):
    defaults = dict(
        name="t-small",
        arrival=ConstantArrival(rate=24.0),
        cycles=48,
        shards=3,
        shard_slots=2,
        decode_block=2,
        generate_tokens=5,
    )
    defaults.update(overrides)
    return ServingScenario(**defaults)


@pytest.fixture(scope="module")
def tiny_serving_checkpoint():
    scenarios = [
        small_scenario(),
        small_scenario(
            name="t-ramp",
            arrival=RampArrival(
                start_rate=6.0, end_rate=40.0, t_start=0.2, t_end=1.6
            ),
        ),
    ]
    return train_serving(
        scenarios, ServingESConfig(population=4, generations=2)
    ).checkpoint


# ---------------------------------------------------------------------------
# Scenario script derivation
# ---------------------------------------------------------------------------


def test_sends_are_exact_integral_floor_differences():
    s = small_scenario(arrival=ConstantArrival(rate=30.0), cycles=40)
    sends = s.sends()
    assert sends.sum() == int(30.0 * 40 * s.cycle_dt)
    # cumulative floors, so no cycle can over- or under-count
    cum = np.cumsum(sends)
    for c in range(40):
        assert cum[c] == int(30.0 * (c + 1) * s.cycle_dt)
    assert len(s.arrival_cycles()) == s.total_requests()


def test_heavy_tail_budgets_are_seeded_and_bounded():
    s = small_scenario(heavy_tail=(1, 5, 1.2), generate_tokens=5)
    a, b = s.request_budgets(), s.request_budgets()
    assert np.array_equal(a, b)
    assert a.min() >= 1 and a.max() <= 5
    reseeded = dataclasses.replace(s, budget_seed=7).request_budgets()
    assert not np.array_equal(a, reseeded)


def test_twin_variants_are_deterministic_and_keep_geometry():
    base = [small_scenario()]
    a = twin_variants(base, 2, seed=9)
    b = twin_variants(base, 2, seed=9)
    c = twin_variants(base, 2, seed=10)
    assert [v.arrival for v in a] == [v.arrival for v in b]
    assert all(x.arrival != y.arrival for x, y in zip(a, c))
    for v in a:
        assert v.shards == base[0].shards
        assert v.cycles == base[0].cycles
        assert v.name.startswith("t-small~v")


def test_scenario_validation():
    with pytest.raises(ValueError):
        small_scenario(initial_shards=9)
    with pytest.raises(ValueError):
        small_scenario(heavy_tail=(1, 99, 1.0))
    with pytest.raises(ValueError):
        small_scenario(pool_entries=2)  # pool needs tenants
    with pytest.raises(ValueError):
        small_scenario(tenants=2, pool_entries=1)  # < shard_slots
    with pytest.raises(ValueError, match="pooled insert"):
        # the real plane's pooled admission has no per-request budgets
        small_scenario(
            tenants=2, pool_entries=2, heavy_tail=(1, 5, 1.1)
        )


# ---------------------------------------------------------------------------
# Fidelity: the compiled scan vs the REAL ShardedBatcher, cycle for cycle
# ---------------------------------------------------------------------------


def test_fidelity_reactive_scaling_world():
    report = verify_twin_fidelity([
        small_scenario(
            name="t-scale",
            arrival=RampArrival(
                start_rate=6.0, end_rate=44.0, t_start=0.2, t_end=1.6
            ),
        ),
    ])
    assert report.ok, report.format_divergences()
    assert report.cycles == 48


def test_fidelity_heavy_tail_budgets():
    report = verify_twin_fidelity([
        small_scenario(name="t-tail", heavy_tail=(1, 5, 1.1)),
    ])
    assert report.ok, report.format_divergences()


def test_fidelity_prefix_pool_and_sticky_routing():
    report = verify_twin_fidelity([
        small_scenario(name="t-prefix", tenants=4, pool_entries=2),
    ])
    assert report.ok, report.format_divergences()
    # and the world genuinely exercised the pool
    twin = run_twin_episodes(
        [TwinConfig(scenario=small_scenario(
            name="t-prefix", tenants=4, pool_entries=2))],
    )[0]
    assert twin.summary["pool_misses"] > 0
    assert twin.summary["pool_hits"] > 0


def test_fidelity_learned_policy(tiny_serving_checkpoint):
    report = verify_twin_fidelity([
        TwinConfig(
            scenario=small_scenario(name="t-learned"),
            policy="learned",
            checkpoint=tiny_serving_checkpoint,
        ),
    ])
    assert report.ok, report.format_divergences()


def test_fidelity_swept_gate_points():
    from kube_sqs_autoscaler_tpu.sim.sweep import SweepPoint

    point = SweepPoint(
        scale_up_messages=3, scale_down_messages=0,
        scale_up_cooldown=0.25, scale_down_cooldown=1.0,
    )
    report = verify_twin_fidelity([
        twin_config_for_point(point, small_scenario(name="t-swept")),
    ])
    assert report.ok, report.format_divergences()


def test_fidelity_report_formats_divergences():
    from kube_sqs_autoscaler_tpu.sim.replay import Divergence
    from kube_sqs_autoscaler_tpu.sim.twin.fidelity import TwinFidelityReport

    report = TwinFidelityReport(
        episodes=1, cycles=8,
        divergences=[("world/reactive", Divergence(3, "tokens", 5, 4))],
    )
    assert not report.ok
    line = report.format_divergences()[0]
    assert "world/reactive" in line and "cycle 3" in line


# ---------------------------------------------------------------------------
# Summary accumulators pinned against the host scorer
# ---------------------------------------------------------------------------


def test_in_scan_summary_matches_trajectory_and_host_scorer():
    scenario = small_scenario(name="t-pin")
    twin = run_twin_episodes([TwinConfig(scenario=scenario)])[0]
    # the in-scan accumulators must equal their own trajectory sums...
    assert twin.summary["tokens"] == int(twin.trajectory["tokens"].sum())
    assert twin.summary["completions"] == int(
        twin.trajectory["completed"].sum()
    )
    assert twin.summary["admitted"] == int(
        twin.trajectory["admitted"].sum()
    )
    assert twin.summary["ttft_cycles_sum"] == int(
        twin.trajectory["ttft_cycles"].sum()
    )
    assert twin.summary["max_queue"] == int(twin.trajectory["queue"].max())
    # ...and the independently-computed host scorer's summary exactly
    host = run_host_episode(TwinConfig(scenario=scenario))
    for key in SERVING_SUMMARY_KEYS:
        if key == "time_over_slo_s":
            assert host.summary[key] == pytest.approx(
                twin.summary[key], abs=1e-9
            )
        else:
            assert host.summary[key] == twin.summary[key], key


def test_unserved_backlog_counts_as_slo_debt():
    # a plane pinned at 1 shard under heavy load must end with backlog,
    # and that backlog must surface as time-over-SLO (refusing
    # admission can never launder SLO debt)
    scenario = small_scenario(
        name="t-overload", arrival=ConstantArrival(rate=60.0),
        max_shards=1, initial_shards=1,
    )
    twin = run_twin_episodes(
        [TwinConfig(scenario=scenario)], trajectory=False
    )[0]
    assert twin.summary["final_queue"] > 0
    assert twin.summary["time_over_slo_s"] > 1.0


# ---------------------------------------------------------------------------
# Population rollouts (learn/rollout.py serving accumulators)
# ---------------------------------------------------------------------------


def test_population_rollout_matches_single_episode(tiny_serving_checkpoint):
    from kube_sqs_autoscaler_tpu.learn.checkpoint import checkpoint_history
    from kube_sqs_autoscaler_tpu.learn.rollout import (
        SERVING_TRAIN_KEYS as ROLLOUT_KEYS,
        evaluate_population_serving,
    )

    ck = tiny_serving_checkpoint
    scenarios = [small_scenario(), small_scenario(name="t-b")]
    history, _ = checkpoint_history(ck)
    out = evaluate_population_serving(
        np.stack([ck.theta, ck.theta]), scenarios,
        hidden=ck.hidden, history=history,
    )
    episodes = run_twin_grouped(
        [TwinConfig(scenario=s, policy="learned", checkpoint=ck)
         for s in scenarios],
        trajectory=False,
    )
    for key in ROLLOUT_KEYS:
        assert out[key].shape == (2, 2)
        for e, episode in enumerate(episodes):
            for p in range(2):
                assert out[key][p, e] == pytest.approx(
                    episode.summary[key], abs=1e-9
                ), key


def test_serving_reward_prefers_more_tokens_less_debt():
    scenarios = [small_scenario()]
    scales = serving_reference_scales(scenarios)
    config = ServingESConfig(population=2, generations=1)
    good = {
        "tokens": np.array([[100.0]]), "time_over_slo_s": np.array([[0.0]]),
        "shard_changes": np.array([[1.0]]),
        "shard_seconds": np.array([[2.0]]),
    }
    bad = {
        "tokens": np.array([[50.0]]), "time_over_slo_s": np.array([[3.0]]),
        "shard_changes": np.array([[9.0]]),
        "shard_seconds": np.array([[2.0]]),
    }
    assert serving_reward_vector(good, scales, config) > (
        serving_reward_vector(bad, scales, config)
    )


def test_train_serving_is_seeded_deterministic():
    scenarios = [small_scenario()]
    config = ServingESConfig(population=4, generations=2)
    a = train_serving(scenarios, config).checkpoint
    b = train_serving(scenarios, config).checkpoint
    assert a.hash == b.hash
    assert a.meta["twin"] == "serving"
    assert "tokens/s" in a.meta["reward_units"]


# ---------------------------------------------------------------------------
# Checkpoint twin-kind deployment seams
# ---------------------------------------------------------------------------


def fluid_checkpoint():
    return PolicyCheckpoint(
        theta=np.zeros(param_count(4), np.float32), hidden=4, meta={}
    )


def serving_checkpoint():
    return PolicyCheckpoint(
        theta=np.zeros(param_count(4), np.float32), hidden=4,
        meta={"twin": "serving"},
    )


def test_twin_kind_defaults_to_fluid_for_old_checkpoints():
    assert checkpoint_twin(fluid_checkpoint()) == "fluid"


def test_learned_policy_rejects_serving_checkpoint():
    from kube_sqs_autoscaler_tpu.core.policy import PolicyConfig
    from kube_sqs_autoscaler_tpu.learn.policy import LearnedPolicy

    with pytest.raises(CheckpointError, match="serving.*twin"):
        LearnedPolicy(
            serving_checkpoint(), policy=PolicyConfig(),
            poll_interval=5.0, max_pods=5,
        )


def test_fluid_compiled_twin_rejects_serving_checkpoint():
    from kube_sqs_autoscaler_tpu.sim.compiled import encode_config
    from kube_sqs_autoscaler_tpu.sim.simulator import SimConfig

    config = SimConfig(
        arrival_rate=10.0, duration=50.0, policy="learned",
        learned_checkpoint=serving_checkpoint(),
    )
    with pytest.raises(CheckpointError, match="fluid"):
        encode_config(config)


def test_serving_twin_rejects_fluid_checkpoint_by_default():
    with pytest.raises(ValueError, match="fluid.*twin"):
        TwinConfig(
            scenario=small_scenario(), policy="learned",
            checkpoint=fluid_checkpoint(),
        )
    # the bench's explicit baseline escape hatch still works
    TwinConfig(
        scenario=small_scenario(), policy="learned",
        checkpoint=fluid_checkpoint(), allow_twin_mismatch=True,
    )


def test_twin_stamp_survives_save_load_and_changes_hash(tmp_path):
    serving = serving_checkpoint()
    path = tmp_path / "serving.json"
    save_checkpoint(str(path), serving)
    loaded = load_checkpoint(str(path))
    assert checkpoint_twin(loaded) == "serving"
    assert loaded.hash == serving.hash
    # same weights, different twin kind = a different policy identity;
    # fluid checkpoints keep their pre-stamp hashes (back-compat)
    assert serving.hash != fluid_checkpoint().hash


def test_invalid_twin_stamp_rejected():
    with pytest.raises(CheckpointError, match="twin"):
        PolicyCheckpoint(
            theta=np.zeros(param_count(4), np.float32), hidden=4,
            meta={"twin": "quantum"},
        )


def test_cli_rejects_serving_checkpoint_as_usage_error(tmp_path):
    import contextlib
    import io

    from kube_sqs_autoscaler_tpu.cli import (
        build_parser,
        load_learned_checkpoint,
    )

    path = tmp_path / "serving.json"
    save_checkpoint(str(path), serving_checkpoint())
    parser = build_parser()
    args = parser.parse_args(
        ["--policy", "learned", "--policy-checkpoint", str(path)]
    )
    stderr = io.StringIO()
    with pytest.raises(SystemExit) as excinfo:
        with contextlib.redirect_stderr(stderr):
            load_learned_checkpoint(parser, args)
    assert excinfo.value.code == 2
    assert "serving" in stderr.getvalue()


def test_replay_rejects_serving_checkpoint():
    from kube_sqs_autoscaler_tpu.sim.replay import _depth_policy_from_meta

    meta = {
        "policy": "learned",
        "learn": {"checkpoint_hash": serving_checkpoint().hash},
        "loop": {"poll_interval": 5.0},
    }
    with pytest.raises(CheckpointError, match="serving"):
        _depth_policy_from_meta(meta, serving_checkpoint())


# ---------------------------------------------------------------------------
# The serving sweep path (sim/sweep.py scores twin results)
# ---------------------------------------------------------------------------


def test_run_sweep_on_serving_scenarios_scores_serving_units():
    from kube_sqs_autoscaler_tpu.sim.sweep import SweepPoint, run_sweep

    points = [
        SweepPoint(scale_up_messages=3, scale_down_messages=0,
                   scale_up_cooldown=0.25, scale_down_cooldown=1.0),
        SweepPoint(scale_up_messages=12, scale_down_messages=1,
                   scale_up_cooldown=1.0, scale_down_cooldown=2.0),
    ]
    scenarios = [small_scenario(name="t-sweep")]
    report = run_sweep(points, scenarios)
    assert report.points == 2
    for row in report.rows:
        assert "tokens_per_second" in row["score"]
        assert "shard_changes" in row["score"]
    best = report.best_per_scenario()["t-sweep"]
    # the eager low-threshold gates must win the serving lex ordering
    assert best["label"].startswith("up3/")
    # winners are re-runnable points
    assert report.best_points_per_scenario()["t-sweep"].scale_up_messages == 3


def test_run_sweep_rejects_mixed_and_forecaster_only():
    from kube_sqs_autoscaler_tpu.sim.evaluate import default_battery
    from kube_sqs_autoscaler_tpu.sim.sweep import SweepPoint, run_sweep

    with pytest.raises(ValueError, match="not a mix"):
        run_sweep(
            [SweepPoint()], [small_scenario(), default_battery()[0]]
        )
    with pytest.raises(ValueError, match="reactive"):
        run_sweep(
            [SweepPoint(policy="holt")], [small_scenario()]
        )


def test_twin_config_for_point_rejects_forecasters():
    from kube_sqs_autoscaler_tpu.sim.sweep import SweepPoint

    with pytest.raises(ValueError, match="reactive"):
        twin_config_for_point(
            SweepPoint(policy="ewma"), small_scenario()
        )


def test_serving_lex_key_orders_tokens_first():
    more_tokens = [{"tokens_per_second": 10.0, "time_over_slo_s": 9.0,
                    "shard_changes": 9}]
    fewer = [{"tokens_per_second": 9.0, "time_over_slo_s": 0.0,
              "shard_changes": 0}]
    assert serving_lex_key(more_tokens) < serving_lex_key(fewer)


# ---------------------------------------------------------------------------
# Bench suite smoke (fidelity-gated; the held-out win gate runs slow)
# ---------------------------------------------------------------------------


def test_twin_suite_smoke(tmp_path):
    from bench import run_twin_suite

    out = tmp_path / "bench_twin.json"
    ck_out = tmp_path / "serving_policy.json"
    headline = run_twin_suite(
        str(out), str(ck_out), cycles=80, population=4, generations=2,
        train_variants=0, held_variants=1, fidelity_learned_limit=1,
        require_win=False,
    )
    artifact = json.loads(out.read_text())
    assert artifact["fidelity"]["pre_train"]["divergences"] == 0
    assert artifact["fidelity"]["post_train"]["divergences"] == 0
    assert artifact["training"]["twin_kind"] == "serving"
    assert set(artifact["held_out"]["totals"]) == {
        "reactive", "tuned_reactive", "fluid_checkpoint",
        "serving_checkpoint",
    }
    assert artifact["held_out"]["gated"] is False
    # the published artifact is a loadable serving-twin checkpoint
    loaded = load_checkpoint(str(ck_out))
    assert checkpoint_twin(loaded) == "serving"
    assert loaded.hash == artifact["training"]["checkpoint_hash"]
    assert "fidelity" in headline["unit"]


@pytest.mark.slow
def test_twin_suite_full_gate(tmp_path):
    # the committed-artifact configuration: full battery, full training,
    # held-out win gate armed (SystemExit(2) otherwise)
    from bench import run_twin_suite

    out = tmp_path / "bench_r17.json"
    run_twin_suite(str(out), str(tmp_path / "serving_policy.json"))
    artifact = json.loads(out.read_text())
    assert artifact["held_out"]["gated"] is True
    assert all(artifact["held_out"]["beats"].values())
    for phase in artifact["fidelity"].values():
        assert phase["divergences"] == 0


# ---------------------------------------------------------------------------
# The host driver's scale ordering is the real pool's (pinned)
# ---------------------------------------------------------------------------


def test_host_scale_ordering_matches_sharded_worker_pool():
    from kube_sqs_autoscaler_tpu.fleet.sharded import (
        DRAINING as POOL_DRAINING,
        INACTIVE as POOL_INACTIVE,
        SERVING as POOL_SERVING,
        ShardedWorkerPool,
    )
    from kube_sqs_autoscaler_tpu.sim.twin.host import _scale_down, _scale_up
    from kube_sqs_autoscaler_tpu.sim.twin.scenario import (
        SHARD_DRAINING,
        SHARD_INACTIVE,
        SHARD_SERVING,
    )

    to_pool = {SHARD_INACTIVE: POOL_INACTIVE, SHARD_SERVING: POOL_SERVING,
               SHARD_DRAINING: POOL_DRAINING}
    from_pool = {v: k for k, v in to_pool.items()}

    class _StubBatcher:
        shards = 4

        def set_shard_active(self, shard, active):
            pass

        def shard_busy(self, shard):
            return 0

    class _StubWorker:
        batcher = _StubBatcher()

    pool = ShardedWorkerPool(lambda p: _StubWorker(), min=1, max=4)
    rng = np.random.default_rng(5)
    for trial in range(200):
        states = [int(x) for x in rng.integers(0, 3, size=4)]
        pool.shard_states = [to_pool[s] for s in states]
        twin_states = list(states)
        if rng.integers(0, 2):
            before = list(pool.shard_states)
            pool.scale_up()
            serving = sum(1 for s in twin_states if s == SHARD_SERVING)
            if serving < 4:
                twin_states[_scale_up(twin_states)] = SHARD_SERVING
        else:
            pool.scale_down()
            serving = sum(1 for s in twin_states if s == SHARD_SERVING)
            if serving > 1:
                twin_states[_scale_down(twin_states)] = SHARD_DRAINING
        assert twin_states == [
            from_pool[s] for s in pool.shard_states
        ], (trial, states)
