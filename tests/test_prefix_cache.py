"""Prefix caching: a shared prompt prefix prefilled ONCE must produce
exactly what prefilling the concatenated prompts produces — logits,
caches, and whole greedy generations — for both families, ragged
suffixes included.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kube_sqs_autoscaler_tpu.workloads.decode import (
    generate,
    prefill,
    prefill_prefix,
    prefill_with_prefix,
)
from kube_sqs_autoscaler_tpu.workloads.llama import (
    LlamaConfig,
    init_llama_params,
    llama_generate,
    llama_prefill_prefix,
)
from kube_sqs_autoscaler_tpu.workloads.model import ModelConfig, init_params

# fp32 so prefix-vs-concat comparisons are exact
TINY = ModelConfig(
    vocab_size=256, d_model=64, n_heads=4, n_layers=2, d_ff=128,
    max_seq_len=64, dtype=jnp.float32,
)
TINY_LLAMA = LlamaConfig(
    vocab_size=256, d_model=64, n_heads=4, n_kv_heads=2, n_layers=2,
    d_ff=128, max_seq_len=64, dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def gpt_params():
    return init_params(jax.random.key(0), TINY)


@pytest.fixture(scope="module")
def llama_params():
    return init_llama_params(jax.random.key(0), TINY_LLAMA)


def ids(shape, seed, vocab=256):
    return jax.random.randint(jax.random.key(seed), shape, 0, vocab,
                              jnp.int32)


def test_prefill_with_prefix_equals_concat_prefill(gpt_params):
    prefix = ids((8,), 1)
    suffix = ids((4, 6), 2)
    concat = jnp.concatenate(
        [jnp.broadcast_to(prefix, (4, 8)), suffix], axis=1
    )

    ref_logits, ref_cache = prefill(gpt_params, concat, TINY)
    pc = prefill_prefix(gpt_params, prefix, TINY)
    logits, cache = prefill_with_prefix(gpt_params, pc, suffix, TINY)

    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref_logits),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(np.asarray(cache["length"]),
                                  np.asarray(ref_cache["length"]))
    # the populated cache region must match exactly too
    for got, ref in zip(cache["layers"], ref_cache["layers"]):
        np.testing.assert_allclose(
            np.asarray(got["k"][:, :, :14]), np.asarray(ref["k"][:, :, :14]),
            rtol=1e-5, atol=1e-6,
        )
        np.testing.assert_allclose(
            np.asarray(got["v"][:, :, :14]), np.asarray(ref["v"][:, :, :14]),
            rtol=1e-5, atol=1e-6,
        )


def test_generate_with_prefix_equals_concat(gpt_params):
    prefix = ids((8,), 3)
    suffix = ids((4, 5), 4)
    concat = jnp.concatenate(
        [jnp.broadcast_to(prefix, (4, 8)), suffix], axis=1
    )
    pc = prefill_prefix(gpt_params, prefix, TINY)

    ref = generate(gpt_params, concat, 12, TINY)
    got = generate(gpt_params, suffix, 12, TINY, prefix_cache=pc)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    # the prefix cache is reusable: a second, different batch gets its
    # own rows (no mutation of the shared prefix)
    suffix2 = ids((2, 5), 5)
    concat2 = jnp.concatenate(
        [jnp.broadcast_to(prefix, (2, 8)), suffix2], axis=1
    )
    np.testing.assert_array_equal(
        np.asarray(generate(gpt_params, suffix2, 6, TINY, prefix_cache=pc)),
        np.asarray(generate(gpt_params, concat2, 6, TINY)),
    )


def test_ragged_suffixes_with_prefix(gpt_params):
    # rows with different suffix lengths, right-padded: each row must
    # generate exactly what its unpadded concat prompt would
    prefix = ids((8,), 6)
    lens = [5, 3]
    suffix = ids((2, 5), 7)
    pc = prefill_prefix(gpt_params, prefix, TINY)
    got = generate(gpt_params, suffix, 8, TINY, prefix_cache=pc,
                   lengths=jnp.asarray(lens, jnp.int32))
    for i, n in enumerate(lens):
        concat = jnp.concatenate([prefix, suffix[i, :n]])[None, :]
        ref = generate(gpt_params, concat, 8, TINY)
        np.testing.assert_array_equal(np.asarray(got[i]), np.asarray(ref[0]))


def test_llama_generate_with_prefix_equals_concat(llama_params):
    prefix = ids((8,), 8)
    suffix = ids((4, 5), 9)
    concat = jnp.concatenate(
        [jnp.broadcast_to(prefix, (4, 8)), suffix], axis=1
    )
    pc = llama_prefill_prefix(llama_params, prefix, TINY_LLAMA)
    ref = llama_generate(llama_params, concat, 10, TINY_LLAMA)
    got = llama_generate(llama_params, suffix, 10, TINY_LLAMA,
                         prefix_cache=pc)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_llama_windowed_prefix(llama_params):
    # sliding-window config: the window mask spans the prefix boundary
    cfg = LlamaConfig(
        vocab_size=256, d_model=64, n_heads=4, n_kv_heads=2, n_layers=2,
        d_ff=128, max_seq_len=64, sliding_window=6, dtype=jnp.float32,
    )
    params = init_llama_params(jax.random.key(1), cfg)
    prefix = ids((8,), 10)
    suffix = ids((2, 4), 11)
    concat = jnp.concatenate(
        [jnp.broadcast_to(prefix, (2, 8)), suffix], axis=1
    )
    pc = llama_prefill_prefix(params, prefix, cfg)
    ref = llama_generate(params, concat, 8, cfg)
    got = llama_generate(params, suffix, 8, cfg, prefix_cache=pc)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_continuous_batcher_with_prefix_equals_concat(gpt_params):
    # continuous batching x prefix caching: slots start past the shared
    # prefix; every request's greedy output must equal generate() of its
    # CONCATENATED prompt — slot reuse included (requests > slots)
    from kube_sqs_autoscaler_tpu.workloads.continuous import (
        ContinuousBatcher,
    )

    prefix = ids((6,), 20)
    pc = prefill_prefix(gpt_params, prefix, TINY)
    batcher = ContinuousBatcher(
        gpt_params, TINY, batch_size=2, prompt_len=8, generate_tokens=5,
        prefix_cache=pc,
    )
    assert batcher.prefix_len == 6
    rng = np.random.default_rng(21)
    requests = [
        rng.integers(1, TINY.vocab_size, rng.integers(2, 9))
        .astype(np.int32)
        for _ in range(5)
    ]
    results = {}
    queue = list(enumerate(requests))
    for _ in range(200):
        while queue and batcher.free_slots:
            idx, toks = queue.pop(0)
            batcher.submit(toks, payload=idx)
        for idx, tokens in batcher.step():
            results[idx] = tokens
        if not queue and batcher.active == 0:
            break
    assert len(results) == 5
    for idx, toks in enumerate(requests):
        concat = jnp.concatenate(
            [prefix, jnp.asarray(toks, jnp.int32)]
        )[None, :]
        ref = np.asarray(generate(gpt_params, concat, 5, TINY)[0])
        np.testing.assert_array_equal(results[idx], ref,
                                      err_msg=f"request {idx}")


def test_continuous_prefix_layout_mismatch_rejected(gpt_params):
    # int8 slots take an int8 prefix; a bf16 prefix cache fails loudly
    # instead of KeyError-ing deep inside the chunk decoder
    from kube_sqs_autoscaler_tpu.workloads.continuous import (
        ContinuousBatcher,
    )

    pc = prefill_prefix(gpt_params, ids((4,), 22), TINY)
    with pytest.raises(ValueError, match="layout mismatch"):
        ContinuousBatcher(
            gpt_params, TINY, batch_size=2, prompt_len=8,
            generate_tokens=4, prefix_cache=pc, quantized_kv=True,
        )


def test_continuous_quantized_prefix_equals_quantized_concat(gpt_params):
    # the LAST serve-side composition hole (prefix x int8 x continuous):
    # int8 slots start past a quantized shared prefix; greedy outputs
    # equal generate(quantized_cache=True) of each concatenated prompt
    from kube_sqs_autoscaler_tpu.workloads.continuous import (
        ContinuousBatcher,
    )
    from kube_sqs_autoscaler_tpu.workloads.decode import (
        quantized_prefill_prefix,
    )
    from tests.conftest import drain_batcher

    prefix = ids((6,), 50)
    pc = quantized_prefill_prefix(gpt_params, prefix, TINY)
    batcher = ContinuousBatcher(
        gpt_params, TINY, batch_size=2, prompt_len=8, generate_tokens=5,
        prefix_cache=pc, quantized_kv=True,
    )
    rng = np.random.default_rng(51)
    requests = [
        rng.integers(1, TINY.vocab_size, rng.integers(2, 9))
        .astype(np.int32)
        for _ in range(4)
    ]
    results = drain_batcher(batcher, requests, max_steps=200)
    assert len(results) == 4
    for idx, toks in enumerate(requests):
        concat = jnp.concatenate(
            [prefix, jnp.asarray(toks, jnp.int32)]
        )[None, :]
        ref = np.asarray(generate(gpt_params, concat, 5, TINY,
                                  quantized_cache=True)[0])
        np.testing.assert_array_equal(results[idx], ref,
                                      err_msg=f"request {idx}")

    # the full quadruple — prefix x int8 x continuous x SPECULATIVE:
    # quantized spec rounds continue past the shared quantized prefix
    # (the draft's prefix is the layer slice), still bitwise the plain
    # quantized generate of the concatenated prompts
    spec_batcher = ContinuousBatcher(
        gpt_params, TINY, batch_size=2, prompt_len=8, generate_tokens=5,
        prefix_cache=pc, quantized_kv=True, draft_layers=1,
        draft_tokens=2,
    )
    spec_results = drain_batcher(spec_batcher, requests, max_steps=200)
    assert len(spec_results) == 4
    for idx, toks in enumerate(requests):
        concat = jnp.concatenate(
            [prefix, jnp.asarray(toks, jnp.int32)]
        )[None, :]
        ref = np.asarray(generate(gpt_params, concat, 5, TINY,
                                  quantized_cache=True)[0])
        np.testing.assert_array_equal(spec_results[idx], ref,
                                      err_msg=f"spec request {idx}")


def test_worker_binary_continuous_prefix_demo():
    from kube_sqs_autoscaler_tpu.workloads.__main__ import main

    main(["--demo", "3", "--batch-size", "2", "--seq-len", "8",
          "--generate-tokens", "4", "--continuous",
          "--prefix-ids", "5,6,7"])


def test_speculative_slots_with_prefix_equal_concat(gpt_params):
    # prefix x speculative x continuous: slots start past the shared
    # prefix AND advance by draft-and-verify rounds; greedy outputs
    # equal generate() of each concatenated prompt (the draft's prefix
    # cache is the layer-wise slice of the target's — no second prefill)
    from kube_sqs_autoscaler_tpu.workloads.continuous import (
        ContinuousBatcher,
    )

    prefix = ids((6,), 40)
    pc = prefill_prefix(gpt_params, prefix, TINY)
    batcher = ContinuousBatcher(
        gpt_params, TINY, batch_size=2, prompt_len=8, generate_tokens=5,
        prefix_cache=pc, draft_layers=1, draft_tokens=2,
    )
    from tests.conftest import drain_batcher

    rng = np.random.default_rng(41)
    requests = [
        rng.integers(1, TINY.vocab_size, rng.integers(2, 9))
        .astype(np.int32)
        for _ in range(4)
    ]
    results = drain_batcher(batcher, requests, max_steps=200)
    assert len(results) == 4
    for idx, toks in enumerate(requests):
        concat = jnp.concatenate(
            [prefix, jnp.asarray(toks, jnp.int32)]
        )[None, :]
        ref = np.asarray(generate(gpt_params, concat, 5, TINY)[0])
        np.testing.assert_array_equal(results[idx], ref,
                                      err_msg=f"request {idx}")


def test_llama_sharded_prefix_matches_single_chip(llama_params):
    # prefix over a (data, model) mesh, llama: kv heads shard over
    # "model", the batch-1 prefix replicates over "data" — bitwise the
    # single-chip prefix generate (VERDICT r4 missing #3)
    from kube_sqs_autoscaler_tpu.workloads.llama import (
        make_llama_serving_fns,
    )
    from kube_sqs_autoscaler_tpu.workloads.train import make_mesh

    mesh = make_mesh(jax.devices()[:4], model_parallel=2, seq_parallel=1)
    prefix = ids((6,), 30)
    suffix = ids((4, 5), 31)
    lengths = jnp.full((4,), 5, jnp.int32)
    pc = llama_prefill_prefix(llama_params, prefix, TINY_LLAMA)
    _, _, gen = make_llama_serving_fns(
        mesh, TINY_LLAMA, llama_params, prefix_cache=pc
    )
    got = np.asarray(gen(llama_params, suffix, jax.random.key(0),
                         lengths, 8, 0.0, 0, 1.0, 7))
    expected = np.asarray(llama_generate(
        llama_params, suffix, 8, TINY_LLAMA, prefix_cache=pc,
        eos_id=7, lengths=lengths,
    ))
    np.testing.assert_array_equal(got, expected)


def test_continuous_sharded_prefix_equals_concat(gpt_params):
    # continuous batching x prefix x (data, model) mesh: the broadcast
    # prefix rows land under cache_shardings, the batch-1 prefix rides
    # the insert as a replicated operand — greedy outputs equal
    # generate() of each concatenated prompt (VERDICT r4 missing #3)
    from kube_sqs_autoscaler_tpu.workloads.continuous import (
        ContinuousBatcher,
    )
    from kube_sqs_autoscaler_tpu.workloads.train import (
        make_mesh,
        param_shardings,
    )

    mesh = make_mesh(jax.devices()[:4], model_parallel=2, seq_parallel=1)
    placed = jax.device_put(gpt_params, param_shardings(mesh, gpt_params))
    prefix = ids((6,), 32)
    pc = prefill_prefix(gpt_params, prefix, TINY)
    batcher = ContinuousBatcher(
        placed, TINY, batch_size=2, prompt_len=8, generate_tokens=5,
        prefix_cache=pc, mesh=mesh,
    )
    assert batcher.prefix_len == 6
    from tests.conftest import drain_batcher

    rng = np.random.default_rng(33)
    requests = [
        rng.integers(1, TINY.vocab_size, rng.integers(2, 9))
        .astype(np.int32)
        for _ in range(4)
    ]
    results = drain_batcher(batcher, requests, max_steps=200)
    assert len(results) == 4
    for idx, toks in enumerate(requests):
        concat = jnp.concatenate(
            [prefix, jnp.asarray(toks, jnp.int32)]
        )[None, :]
        ref = np.asarray(generate(gpt_params, concat, 5, TINY)[0])
        np.testing.assert_array_equal(results[idx], ref,
                                      err_msg=f"request {idx}")


def test_speculative_with_prefix_equals_concat(gpt_params):
    # speculative x prefix: the early-exit self-draft's prefix cache is
    # the layer slice of the target's; greedy speculative output must
    # equal plain greedy generate of the CONCATENATED prompts
    from kube_sqs_autoscaler_tpu.workloads.speculative import (
        draft_prefix_from_target,
        speculative_generate,
    )

    draft_cfg = ModelConfig(
        vocab_size=TINY.vocab_size, d_model=TINY.d_model,
        n_heads=TINY.n_heads, n_layers=1, d_ff=TINY.d_ff,
        max_seq_len=TINY.max_seq_len, dtype=jnp.float32,
    )
    draft_params = dict(gpt_params, layers=gpt_params["layers"][:1])
    prefix = ids((8,), 30)
    suffix = ids((2, 5), 31)
    concat = jnp.concatenate(
        [jnp.broadcast_to(prefix, (2, 8)), suffix], axis=1
    )
    pc = prefill_prefix(gpt_params, prefix, TINY)
    got = speculative_generate(
        gpt_params, TINY, draft_params, draft_cfg, suffix, 10,
        draft_tokens=3, prefix_cache=pc,
        draft_prefix_cache=draft_prefix_from_target(pc, 1),
    )
    ref = generate(gpt_params, concat, 10, TINY)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))

    with pytest.raises(ValueError, match="come together"):
        speculative_generate(
            gpt_params, TINY, draft_params, draft_cfg, suffix, 4,
            prefix_cache=pc,
        )


def test_beam_with_prefix_equals_concat(gpt_params):
    # beam x prefix: the search over suffixes continued from the cached
    # prefix must pick exactly the beams of the concatenated prompts
    from kube_sqs_autoscaler_tpu.workloads.beam import beam_search

    prefix = ids((8,), 40)
    suffix = ids((2, 5), 41)
    concat = jnp.concatenate(
        [jnp.broadcast_to(prefix, (2, 8)), suffix], axis=1
    )
    pc = prefill_prefix(gpt_params, prefix, TINY)
    ref = beam_search(gpt_params, TINY, concat, 8, beams=3)
    got = beam_search(gpt_params, TINY, suffix, 8, beams=3,
                      prefix_cache=pc)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_worker_binary_beam_prefix_demo():
    from kube_sqs_autoscaler_tpu.workloads.__main__ import main

    main(["--demo", "2", "--batch-size", "1", "--seq-len", "8",
          "--generate-tokens", "4", "--prefix-ids", "5,6,7",
          "--beams", "2"])


def test_worker_binary_speculative_prefix_demo():
    from kube_sqs_autoscaler_tpu.workloads.__main__ import main

    main(["--demo", "2", "--batch-size", "1", "--seq-len", "8",
          "--generate-tokens", "4", "--prefix-ids", "5,6,7",
          "--speculative-draft-layers", "1",
          "--speculative-draft-tokens", "2"])


def test_worker_binary_prefix_flag():
    # the serve binary end to end: --prefix-ids prefills once and every
    # demo message decodes as a suffix (both families)
    from kube_sqs_autoscaler_tpu.workloads.__main__ import main

    main(["--demo", "2", "--batch-size", "1", "--seq-len", "8",
          "--generate-tokens", "4", "--prefix-ids", "5,6,7"])
    main(["--family", "llama", "--demo", "2", "--batch-size", "1",
          "--seq-len", "8", "--generate-tokens", "4",
          "--prefix-ids", "5,6,7"])
    # the round-4 hole: --prefix-ids rejected --model-parallel; now the
    # prefix pins into the sharded generate (and the sharded slot
    # machine under --continuous)
    main(["--demo", "2", "--batch-size", "4", "--seq-len", "8",
          "--generate-tokens", "4", "--prefix-ids", "5,6,7",
          "--model-parallel", "2"])
    main(["--demo", "3", "--batch-size", "4", "--seq-len", "8",
          "--generate-tokens", "4", "--prefix-ids", "5,6,7",
          "--continuous", "--model-parallel", "2"])


def test_worker_binary_prefix_combo_rejections():
    from kube_sqs_autoscaler_tpu.workloads.__main__ import main

    base = ["--demo", "1", "--seq-len", "8", "--generate-tokens", "4",
            "--prefix-ids", "1,2"]
    # every decode mode now takes a prefix — int8 slots included
    main(base + ["--quantize-kv", "--continuous", "--batch-size", "2"])
    with pytest.raises(SystemExit, match="generate-tokens"):
        main(["--demo", "1", "--seq-len", "8", "--prefix-ids", "1,2"])
    with pytest.raises(SystemExit, match="integers"):
        main(base[:-1] + ["1,two"])
    with pytest.raises(SystemExit, match="out of range"):
        main(base[:-1] + ["9999999"])


def test_quantized_prefix_equals_quantized_concat(gpt_params, llama_params):
    # int8 KV x prefix: per-position quantization is position-local, so
    # the prefix's codes are bitwise what the concat prefill writes —
    # quantized decode from a quantized prefix equals quantized decode
    # of the concatenated prompts, both families
    from kube_sqs_autoscaler_tpu.workloads.decode import (
        quantized_prefill_prefix,
    )
    from kube_sqs_autoscaler_tpu.workloads.llama import (
        llama_quantized_prefill_prefix,
    )

    prefix = ids((8,), 50)
    suffix = ids((2, 5), 51)
    concat = jnp.concatenate(
        [jnp.broadcast_to(prefix, (2, 8)), suffix], axis=1
    )
    qpc = quantized_prefill_prefix(gpt_params, prefix, TINY)
    ref = generate(gpt_params, concat, 8, TINY, quantized_cache=True)
    got = generate(gpt_params, suffix, 8, TINY, quantized_cache=True,
                   prefix_cache=qpc)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))

    lqpc = llama_quantized_prefill_prefix(llama_params, prefix, TINY_LLAMA)
    lref = llama_generate(llama_params, concat, 8, TINY_LLAMA,
                          quantized_cache=True)
    lgot = llama_generate(llama_params, suffix, 8, TINY_LLAMA,
                          quantized_cache=True, prefix_cache=lqpc)
    np.testing.assert_array_equal(np.asarray(lgot), np.asarray(lref))


def test_worker_binary_quantized_prefix_demo():
    from kube_sqs_autoscaler_tpu.workloads.__main__ import main

    main(["--demo", "2", "--batch-size", "1", "--seq-len", "8",
          "--generate-tokens", "4", "--prefix-ids", "5,6,7",
          "--quantize-kv"])


def test_prefix_rejects_other_cache_layouts(gpt_params, llama_params):
    # a prefix cache must match the decode path's layout (bf16 prefix
    # into a quantized decode and vice versa fail loudly)
    pc = prefill_prefix(gpt_params, ids((4,), 12), TINY)
    with pytest.raises(ValueError, match="layout mismatch"):
        generate(gpt_params, ids((2, 3), 13), 4, TINY, prefix_cache=pc,
                 quantized_cache=True)
    lpc = llama_prefill_prefix(llama_params, ids((4,), 14), TINY_LLAMA)
    with pytest.raises(ValueError, match="layout mismatch"):
        llama_generate(llama_params, ids((2, 3), 15), 4, TINY_LLAMA,
                       prefix_cache=lpc, quantized_cache=True)
    from kube_sqs_autoscaler_tpu.workloads.decode import (
        quantized_prefill_prefix,
    )

    qpc = quantized_prefill_prefix(gpt_params, ids((4,), 16), TINY)
    with pytest.raises(ValueError, match="layout mismatch"):
        generate(gpt_params, ids((2, 3), 17), 4, TINY, prefix_cache=qpc)
