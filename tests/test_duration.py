"""Go duration grammar parity (utils/duration.py).

The reference accepts every knob as a Go ``time.Duration`` flag
(``main.go:83-85``); these cases mirror ``time.ParseDuration`` semantics.
"""

import pytest

from kube_sqs_autoscaler_tpu.utils.duration import (
    DurationError,
    format_duration,
    parse_duration,
)


@pytest.mark.parametrize(
    "text,expected",
    [
        ("0", 0.0),
        ("5s", 5.0),
        ("30s", 30.0),
        ("10s", 10.0),
        ("300ms", 0.3),
        ("1.5h", 5400.0),
        ("2h45m", 9900.0),
        ("1m30s", 90.0),
        ("-1.5h", -5400.0),
        ("+5s", 5.0),
        ("100us", 1e-4),
        ("100µs", 1e-4),
        ("1000ns", 1e-6),
        ("1h1m1s", 3661.0),
        (".5s", 0.5),
        ("1.s", 1.0),
    ],
)
def test_parse_valid(text, expected):
    assert parse_duration(text) == pytest.approx(expected)


@pytest.mark.parametrize("text", ["", "10", "5 s", "s", "1.2.3s", "-", "1d", "5x"])
def test_parse_invalid(text):
    with pytest.raises(DurationError):
        parse_duration(text)


@pytest.mark.parametrize("seconds", [0.0, 5.0, 30.0, 90.0, 5400.0, 0.3, 1e-4, 9900.0])
def test_format_round_trips(seconds):
    assert parse_duration(format_duration(seconds)) == pytest.approx(seconds)


def test_format_examples():
    assert format_duration(5.0) == "5s"
    assert format_duration(90.0) == "1m30s"
    assert format_duration(0.0) == "0s"
    assert format_duration(3600.0) == "1h"
