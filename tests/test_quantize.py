"""int8 post-training quantization for serving: the quantized pytree is a
drop-in (same model code), close to the full-precision outputs, and half
the bytes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kube_sqs_autoscaler_tpu.workloads.model import (
    ModelConfig,
    forward,
    init_params,
)
from kube_sqs_autoscaler_tpu.workloads.quantize import (
    QuantizedTensor,
    quantize_params,
    quantized_bytes,
)

TINY = ModelConfig(
    vocab_size=128, d_model=64, n_heads=4, n_layers=2, d_ff=128,
    max_seq_len=32, dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.key(0), TINY)


def test_roundtrip_error_is_small(params):
    w = params["layers"][0]["wqkv"]
    q = quantize_params(params)["layers"][0]["wqkv"]
    assert isinstance(q, QuantizedTensor)
    assert q.codes.dtype == jnp.int8
    err = np.abs(np.asarray(q.dequantize(), np.float32) -
                 np.asarray(w, np.float32))
    # per-channel symmetric int8: max error is scale/2 per channel
    scale = np.asarray(q.scale)
    assert (err <= scale / 2 + 1e-7).all()


def test_quantized_forward_close_to_full_precision(params):
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0,
                                TINY.vocab_size, jnp.int32)
    full = np.asarray(forward(params, tokens, TINY))
    quant = np.asarray(
        jax.jit(lambda p, t: forward(p, t, TINY))(
            quantize_params(params), tokens
        )
    )
    # int8 weights: logits move a little, the distribution barely
    assert np.isfinite(quant).all()
    np.testing.assert_allclose(quant, full, rtol=0.2, atol=0.35)
    # greedy decisions overwhelmingly agree on the tiny model
    agree = (quant[:, -1].argmax(-1) == full[:, -1].argmax(-1)).mean()
    assert agree == 1.0


def test_quantized_generate_runs(params):
    from kube_sqs_autoscaler_tpu.workloads.decode import generate

    prompt = jax.random.randint(jax.random.key(2), (2, 8), 1,
                                TINY.vocab_size, jnp.int32)
    out = generate(quantize_params(params), prompt, 4, TINY)
    assert out.shape == (2, 4)
    assert np.isfinite(np.asarray(out)).all()


def test_quantized_bytes_shrink(params):
    full = quantized_bytes(params)
    quant = quantized_bytes(quantize_params(params))
    # fp32 matmul weights -> int8 codes (+small scales): well under half
    assert quant < 0.45 * full


def test_llama_family_quantizes():
    from kube_sqs_autoscaler_tpu.workloads.llama import (
        LlamaConfig,
        init_llama_params,
        llama_forward,
    )

    config = LlamaConfig(
        vocab_size=128, d_model=64, n_heads=4, n_kv_heads=2, n_layers=2,
        d_ff=128, max_seq_len=32, dtype=jnp.float32,
    )
    lparams = init_llama_params(jax.random.key(0), config)
    tokens = jax.random.randint(jax.random.key(3), (2, 16), 0, 128,
                                jnp.int32)
    full = np.asarray(llama_forward(lparams, tokens, config))
    quant = np.asarray(
        llama_forward(quantize_params(lparams, family="llama"), tokens,
                      config)
    )
    assert np.isfinite(quant).all()
    assert (quant[:, -1].argmax(-1) == full[:, -1].argmax(-1)).all()


def test_worker_binary_serves_quantized():
    from kube_sqs_autoscaler_tpu.workloads.__main__ import main as worker_main

    worker_main(["--demo", "4", "--quantize", "int8", "--batch-size", "2",
                 "--seq-len", "16"])
    # quantize + generate mode together
    worker_main(["--demo", "2", "--quantize", "int8", "--batch-size", "2",
                 "--seq-len", "12", "--generate-tokens", "2"])
