"""int8 post-training quantization for serving: the quantized pytree is a
drop-in (same model code), close to the full-precision outputs, and half
the bytes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kube_sqs_autoscaler_tpu.workloads.model import (
    ModelConfig,
    forward,
    init_params,
)
from kube_sqs_autoscaler_tpu.workloads.quantize import (
    QuantizedTensor,
    quantize_params,
    quantized_bytes,
)

TINY = ModelConfig(
    vocab_size=128, d_model=64, n_heads=4, n_layers=2, d_ff=128,
    max_seq_len=32, dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.key(0), TINY)


def test_roundtrip_error_is_small(params):
    w = params["layers"][0]["wqkv"]
    q = quantize_params(params)["layers"][0]["wqkv"]
    assert isinstance(q, QuantizedTensor)
    assert q.codes.dtype == jnp.int8
    err = np.abs(np.asarray(q.dequantize(), np.float32) -
                 np.asarray(w, np.float32))
    # per-channel symmetric int8: max error is scale/2 per channel
    scale = np.asarray(q.scale)
    assert (err <= scale / 2 + 1e-7).all()


def test_quantized_forward_close_to_full_precision(params):
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0,
                                TINY.vocab_size, jnp.int32)
    full = np.asarray(forward(params, tokens, TINY))
    quant = np.asarray(
        jax.jit(lambda p, t: forward(p, t, TINY))(
            quantize_params(params), tokens
        )
    )
    # int8 weights: logits move a little, the distribution barely
    assert np.isfinite(quant).all()
    np.testing.assert_allclose(quant, full, rtol=0.2, atol=0.35)
    # greedy decisions overwhelmingly agree on the tiny model
    agree = (quant[:, -1].argmax(-1) == full[:, -1].argmax(-1)).mean()
    assert agree == 1.0


def test_quantized_generate_runs(params):
    from kube_sqs_autoscaler_tpu.workloads.decode import generate

    prompt = jax.random.randint(jax.random.key(2), (2, 8), 1,
                                TINY.vocab_size, jnp.int32)
    out = generate(quantize_params(params), prompt, 4, TINY)
    assert out.shape == (2, 4)
    assert np.isfinite(np.asarray(out)).all()


def test_quantized_bytes_shrink(params):
    full = quantized_bytes(params)
    quant = quantized_bytes(quantize_params(params))
    # fp32 matmul weights -> int8 codes (+small scales): well under half
    assert quant < 0.45 * full


def test_llama_family_quantizes():
    from kube_sqs_autoscaler_tpu.workloads.llama import (
        LlamaConfig,
        init_llama_params,
        llama_forward,
    )

    config = LlamaConfig(
        vocab_size=128, d_model=64, n_heads=4, n_kv_heads=2, n_layers=2,
        d_ff=128, max_seq_len=32, dtype=jnp.float32,
    )
    lparams = init_llama_params(jax.random.key(0), config)
    tokens = jax.random.randint(jax.random.key(3), (2, 16), 0, 128,
                                jnp.int32)
    full = np.asarray(llama_forward(lparams, tokens, config))
    quant = np.asarray(
        llama_forward(quantize_params(lparams, family="llama"), tokens,
                      config)
    )
    assert np.isfinite(quant).all()
    assert (quant[:, -1].argmax(-1) == full[:, -1].argmax(-1)).all()


def test_worker_binary_serves_quantized():
    from kube_sqs_autoscaler_tpu.workloads.__main__ import main as worker_main

    worker_main(["--demo", "4", "--quantize", "int8", "--batch-size", "2",
                 "--seq-len", "16"])
    # quantize + generate mode together
    worker_main(["--demo", "2", "--quantize", "int8", "--batch-size", "2",
                 "--seq-len", "12", "--generate-tokens", "2"])


# ---------------------------------------------------- tp-sharded int8


def test_int8_tp_sharded_serving_matches_single_chip(params):
    # VERDICT r3 #6: int8 codes shard like the bf16 weights would
    # (codes take the weight's Megatron spec, per-channel scales its
    # output-axis slice) — sharded int8 generate ≡ single-chip int8
    from kube_sqs_autoscaler_tpu.workloads.decode import (
        generate,
        make_serving_fns,
    )
    from kube_sqs_autoscaler_tpu.workloads.train import (
        make_mesh,
        param_shardings,
    )

    mesh = make_mesh(jax.devices()[:4], model_parallel=2, seq_parallel=1)
    qparams = quantize_params(params)
    shardings = param_shardings(mesh, qparams)
    # codes carry the weight's spec, scales the output-axis slice
    wqkv = shardings["layers"][0]["wqkv"]
    assert wqkv.codes.spec == jax.sharding.PartitionSpec(None, "model")
    assert wqkv.scale.spec == jax.sharding.PartitionSpec("model")
    wo = shardings["layers"][0]["wo"]
    assert wo.codes.spec == jax.sharding.PartitionSpec("model", None)
    assert wo.scale.spec == jax.sharding.PartitionSpec(None)

    placed = jax.device_put(qparams, shardings)
    _, _, gen = make_serving_fns(mesh, TINY, placed)
    prompt = jax.random.randint(jax.random.key(3), (4, 8), 1,
                                TINY.vocab_size, jnp.int32)
    lengths = jnp.full((4,), 8, jnp.int32)
    sharded = np.asarray(gen(placed, prompt, jax.random.key(0), lengths, 5))
    single = np.asarray(generate(qparams, prompt, 5, TINY))
    np.testing.assert_array_equal(sharded, single)


def test_worker_binary_serves_int8_model_parallel():
    from kube_sqs_autoscaler_tpu.workloads.__main__ import main as worker_main

    worker_main(["--demo", "4", "--quantize", "int8", "--model-parallel",
                 "2", "--batch-size", "4", "--seq-len", "12",
                 "--generate-tokens", "3"])


def test_worker_binary_serves_quantized_kv_model_parallel():
    # the round-4 hole: --quantize-kv rejected --model-parallel; now the
    # int8 cache shards by head over the serving mesh (plain generate AND
    # the continuous slot machine), and int8 weights compose on top.
    # clear_caches between the two binary runs: this test sits ~65% into
    # the slow tier and the second run (llama + int8 weights + int8 KV +
    # continuous + mesh) has twice aborted the whole suite inside XLA CPU
    # with the backend's accumulated state — each run is a full worker
    # binary, so dropping executables between them is free
    from kube_sqs_autoscaler_tpu.workloads.__main__ import main as worker_main

    jax.clear_caches()
    worker_main(["--demo", "2", "--quantize-kv", "--model-parallel", "2",
                 "--batch-size", "4", "--seq-len", "8",
                 "--generate-tokens", "3"])
    jax.clear_caches()
    worker_main(["--demo", "3", "--quantize-kv", "--model-parallel", "2",
                 "--continuous", "--quantize", "int8", "--batch-size", "4",
                 "--seq-len", "8", "--generate-tokens", "3",
                 "--family", "llama"])


# ------------------------------------------------------ int8 KV cache


def test_quantized_cache_decode_close_to_exact(params):
    # the factorized dequantize must track the full-precision decode to
    # int8 rounding, step after step (errors compound through the scan)
    from kube_sqs_autoscaler_tpu.workloads.decode import (
        decode_step,
        prefill,
        quantized_decode_step,
        quantized_prefill,
    )

    prompt = jax.random.randint(jax.random.key(4), (2, 8), 1,
                                TINY.vocab_size, jnp.int32)
    logits_q, qcache = quantized_prefill(params, prompt, TINY)
    logits_f, fcache = prefill(params, prompt, TINY)
    np.testing.assert_array_equal(np.asarray(logits_q),
                                  np.asarray(logits_f))  # prompt pass: exact
    tok = jnp.argmax(logits_f, axis=-1).astype(jnp.int32)
    for _ in range(4):
        lq, qcache = quantized_decode_step(params, qcache, tok, TINY)
        lf, fcache = decode_step(params, fcache, tok, TINY)
        np.testing.assert_allclose(
            np.asarray(lq), np.asarray(lf), rtol=0.25, atol=0.6
        )
        tok = jnp.argmax(lf, axis=-1).astype(jnp.int32)


def test_quantized_cache_generate_runs_both_families(params):
    from kube_sqs_autoscaler_tpu.workloads.decode import generate
    from kube_sqs_autoscaler_tpu.workloads.llama import (
        LlamaConfig,
        init_llama_params,
        llama_generate,
    )

    prompt = jax.random.randint(jax.random.key(5), (2, 8), 1,
                                TINY.vocab_size, jnp.int32)
    out = generate(params, prompt, 4, TINY, quantized_cache=True,
                   eos_id=5)
    assert out.shape == (2, 4)
    assert np.isfinite(np.asarray(out)).all()

    lcfg = LlamaConfig(vocab_size=128, d_model=64, n_heads=4, n_kv_heads=2,
                       n_layers=2, d_ff=128, max_seq_len=32,
                       dtype=jnp.float32)
    lparams = init_llama_params(jax.random.key(6), lcfg)
    lout = llama_generate(lparams, prompt, 4, lcfg, quantized_cache=True)
    assert lout.shape == (2, 4)
    assert np.isfinite(np.asarray(lout)).all()


def test_quantized_cache_bytes_halve(params):
    from kube_sqs_autoscaler_tpu.workloads.decode import (
        init_cache,
        quantize_cache,
    )

    def nbytes(tree):
        return sum(
            leaf.size * jnp.dtype(leaf.dtype).itemsize
            for leaf in jax.tree.leaves(tree)
        )

    # bf16 baseline (the production cache dtype; TINY here is fp32).
    # head_dim 64 so the per-vector fp32 scale amortizes like it does at
    # production widths: (64·1 + 4) / (64·2) ≈ 0.53
    bf16 = ModelConfig(vocab_size=128, d_model=256, n_heads=4, n_layers=2,
                       d_ff=128, max_seq_len=32)
    cache = init_cache(bf16, batch=4)
    q = quantize_cache(cache)
    assert nbytes(q) < 0.6 * nbytes(cache)


def test_rolling_and_quantized_cache_fail_fast():
    from kube_sqs_autoscaler_tpu.workloads.llama import (
        LlamaConfig,
        init_llama_params,
        llama_generate,
    )

    cfg = LlamaConfig(vocab_size=128, d_model=64, n_heads=4, n_kv_heads=2,
                      n_layers=2, d_ff=128, max_seq_len=32,
                      sliding_window=8, dtype=jnp.float32)
    p = init_llama_params(jax.random.key(0), cfg)
    prompt = jnp.ones((1, 8), jnp.int32)
    with pytest.raises(ValueError, match="rolling"):
        llama_generate(p, prompt, 2, cfg, rolling=True,
                       quantized_cache=True)


def test_worker_binary_quantize_kv_flag():
    from kube_sqs_autoscaler_tpu.workloads.__main__ import main as worker_main

    worker_main(["--demo", "2", "--quantize-kv", "--batch-size", "2",
                 "--seq-len", "12", "--generate-tokens", "3"])
    worker_main(["--demo", "2", "--quantize-kv", "--family", "llama",
                 "--quantize", "int8", "--batch-size", "2",
                 "--seq-len", "12", "--generate-tokens", "3",
                 "--temperature", "0.7"])
    with pytest.raises(SystemExit, match="generate-tokens"):
        worker_main(["--demo", "1", "--quantize-kv"])
    # the triple quantize-kv x model-parallel x speculative now serves
    # (the sharded factory streams int8 caches for both models)
    worker_main(["--demo", "2", "--quantize-kv", "--generate-tokens",
                 "3", "--model-parallel", "2", "--batch-size", "4",
                 "--seq-len", "8", "--speculative-draft-layers", "1"])
    # so does beam search over the int8 cache
    worker_main(["--demo", "2", "--quantize-kv", "--generate-tokens",
                 "3", "--beams", "2", "--batch-size", "2",
                 "--seq-len", "8"])
