"""Child process for the two-process multi-host test (see
``tests/test_multihost.py``).

Each process: bootstrap via ``initialize_from_env`` (coordinator env
vars), build the global ``("data", "seq", "model")`` mesh over all 8
devices (4 per process), feed the global-batch synthetic stream through
``prefetch_to_mesh`` against the global batch sharding, run 2 sharded
train steps, and print the loss.  The parent asserts both processes
bootstrapped, saw the global device count, and computed the SAME loss —
the only place a per-host-array/global-sharding mismatch could surface.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kube_sqs_autoscaler_tpu.utils.platforms import honor_env_platforms

honor_env_platforms()

from kube_sqs_autoscaler_tpu.workloads.distributed import initialize_from_env


def main() -> None:
    ok = initialize_from_env()
    assert ok, "initialize_from_env did not trigger"

    import jax
    import jax.numpy as jnp

    print(
        f"BOOT process={jax.process_index()}/{jax.process_count()} "
        f"global_devices={jax.device_count()} "
        f"local_devices={len(jax.local_devices())}",
        flush=True,
    )
    assert jax.process_count() == 2
    assert jax.device_count() == 8

    from kube_sqs_autoscaler_tpu.workloads.data import (
        prefetch_to_mesh,
        synthetic_token_stream,
    )
    from kube_sqs_autoscaler_tpu.workloads.model import ModelConfig
    from kube_sqs_autoscaler_tpu.workloads.train import (
        TrainConfig,
        batch_sharding,
        init_train_state,
        make_mesh,
        make_train_step,
        place_state,
    )

    config = ModelConfig(
        vocab_size=128, d_model=32, n_heads=4, n_layers=1, d_ff=64,
        max_seq_len=16, dtype=jnp.float32,
    )
    # global mesh over BOTH processes' devices: dp4 x sp1 x tp2
    mesh = make_mesh(jax.devices(), model_parallel=2, seq_parallel=1)
    state = place_state(
        mesh, init_train_state(jax.random.key(0), config, TrainConfig())
    )
    step_fn = make_train_step(mesh, config, TrainConfig(), state)

    # every process generates the same global batch (same seed); device_put
    # against the global sharding takes each process's addressable shards
    stream = synthetic_token_stream(config.vocab_size, batch=8, seq=16,
                                    seed=7)
    batches = prefetch_to_mesh(stream, batch_sharding(mesh))
    for _ in range(2):
        state, loss = step_fn(state, next(batches))
    # fetching a fully-replicated scalar is legal on every process
    print(f"LOSS {float(loss):.6f}", flush=True)


if __name__ == "__main__":
    main()
