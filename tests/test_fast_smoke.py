"""Fast-tier serving smokes: the advanced serving compositions at the
smallest useful scale, so the DEFAULT gate (`make test`, <10 min)
touches the round-5 machinery — the full pinned-equality tests live in
the slow tier (test_continuous/test_prefix_cache/test_beam/...).

jax/numpy imports stay inside the test (the conftest's optional-extras
collection invariant: without them the controller tests must still
collect and run).
"""

from tests.conftest import drain_batcher


def test_speculative_and_beam_slots_smoke():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from kube_sqs_autoscaler_tpu.workloads.beam import beam_search
    from kube_sqs_autoscaler_tpu.workloads.continuous import (
        ContinuousBatcher,
    )
    from kube_sqs_autoscaler_tpu.workloads.decode import generate
    from kube_sqs_autoscaler_tpu.workloads.model import (
        ModelConfig,
        init_params,
    )

    tiny = ModelConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
        max_seq_len=32, dtype=jnp.float32,
    )
    params = init_params(jax.random.key(0), tiny)
    rng = np.random.default_rng(1)
    requests = [
        rng.integers(1, tiny.vocab_size, 4).astype(np.int32)
        for _ in range(3)
    ]

    spec = drain_batcher(ContinuousBatcher(
        params, tiny, batch_size=2, prompt_len=4, generate_tokens=4,
        draft_layers=1, draft_tokens=2,
    ), requests, max_steps=100)
    assert len(spec) == 3
    for idx, ids in enumerate(requests):
        ref = np.asarray(generate(params, jnp.asarray(ids)[None], 4,
                                  tiny)[0])
        np.testing.assert_array_equal(spec[idx], ref)

    beam = drain_batcher(ContinuousBatcher(
        params, tiny, batch_size=2, prompt_len=4, generate_tokens=4,
        beams=2,
    ), requests, max_steps=100)
    assert len(beam) == 3
    for idx, ids in enumerate(requests):
        ref = np.asarray(beam_search(params, tiny, jnp.asarray(ids)[None],
                                     4, beams=2)[0])
        np.testing.assert_array_equal(beam[idx], ref)
