"""Contract test for the ``Scaler`` actuator seam.

Three production actuators implement the seam: :class:`PodAutoScaler`
(a Deployment's replica integer over an orchestrator API), the fleet's
:class:`WorkerPool` (real in-process serving replicas), and the
:class:`ShardedWorkerPool` (device-side shard-active mask flips over one
gang-stepped serving plane).  The ControlLoop must not be able to tell
them apart: min/max clamping, boundary-no-op success, cooldown
interaction, and failure behavior (ScaleError ends the tick without
advancing the cooldown) are asserted IDENTICAL through the real loop,
tick for tick.

JAX-free: the pools under contract run featherweight stub replicas /
stub sharded batchers — the scaling semantics live entirely in the
pools, not in the serving engine.
"""

from __future__ import annotations

import pytest

from kube_sqs_autoscaler_tpu.core.clock import FakeClock
from kube_sqs_autoscaler_tpu.core.loop import ControlLoop, LoopConfig
from kube_sqs_autoscaler_tpu.core.policy import Gate, PolicyConfig
from kube_sqs_autoscaler_tpu.core.types import ScaleError, Scaler
from kube_sqs_autoscaler_tpu.fleet import ShardedWorkerPool, WorkerPool
from kube_sqs_autoscaler_tpu.scale import FakeDeploymentAPI, PodAutoScaler


class _StubBatcher:
    def __init__(self):
        self.active = 0
        self.free_slots = []
        self.tokens_emitted = 0
        self.decode_block = 1


class _StubWorker:
    """The replica surface the pool needs, with no serving engine."""

    def __init__(self):
        self.admitting = True
        self.killed = False
        self.hung = False
        self.processed = 0
        self.batcher = _StubBatcher()

    def run_once(self):
        return 0

    def stop(self):
        pass

    def kill(self):
        self.killed = True

    def hang(self):
        self.hung = True

    def take_inflight(self):
        return []

    def release_inflight(self):
        return 0

    def _admit(self, messages):
        return len(messages)


def make_pod(initial, min_, max_, up=1, down=1):
    api = FakeDeploymentAPI.with_deployments("ns", initial, "deploy")
    scaler = PodAutoScaler(
        client=api, max=max_, min=min_, scale_up_pods=up,
        scale_down_pods=down, deployment="deploy", namespace="ns",
    )

    def fail_next_up(err):
        api.fail_next_get = err

    return scaler, (lambda: api.replicas("deploy")), fail_next_up


def make_pool(initial, min_, max_, up=1, down=1):
    pool = WorkerPool(
        lambda p: _StubWorker(), min=min_, max=max_, scale_up_pods=up,
        scale_down_pods=down, initial=initial,
    )

    def fail_next_up(err):
        pool.fail_next_up = err

    return pool, (lambda: pool.replicas), fail_next_up


class _StubShardedBatcher:
    """The sharded-plane surface ShardedWorkerPool needs, with no JAX."""

    def __init__(self, shards):
        self.shards = shards
        self.shard_admitting = [True] * shards
        self.active = 0
        self.free_slots = []
        self.tokens_emitted = 0

    def set_shard_active(self, shard, active):
        self.shard_admitting[shard] = bool(active)

    def shard_busy(self, shard):
        return 0

    def shard_stats(self, served_since=None):
        return []


class _StubShardedWorker(_StubWorker):
    def __init__(self, shards):
        super().__init__()
        self.batcher = _StubShardedBatcher(shards)


def make_shards(initial, min_, max_, up=1, down=1):
    pool = ShardedWorkerPool(
        lambda p: _StubShardedWorker(max_), min=min_, max=max_,
        scale_up_pods=up, scale_down_pods=down, initial=initial,
    )

    def fail_next_up(err):
        pool.fail_next_up = err

    return pool, (lambda: pool.replicas), fail_next_up


def make_disagg(initial, min_, max_, up=1, down=1):
    # the prefill plane is the Scaler surface; the embedded decode
    # plane (one stub sharded worker) rides along un-actuated.  The
    # shuttle getattr-guards the handoff surface, so plain stubs work.
    from kube_sqs_autoscaler_tpu.planes import DisaggregatedPool

    pool = DisaggregatedPool(
        lambda p: _StubWorker(), lambda p: _StubShardedWorker(2),
        min=min_, max=max_, scale_up_pods=up, scale_down_pods=down,
        initial=initial, decode_min=1, decode_max=2, decode_initial=2,
    )

    def fail_next_up(err):
        pool.fail_next_up = err

    return pool, (lambda: pool.replicas), fail_next_up


MAKERS = [make_pod, make_pool, make_shards, make_disagg]
IDS = ["pod", "pool", "shards", "disagg"]


@pytest.mark.parametrize("make", MAKERS, ids=IDS)
def test_scaler_protocol(make):
    scaler, _, _ = make(3, 1, 5)
    assert isinstance(scaler, Scaler)


@pytest.mark.parametrize("make", MAKERS, ids=IDS)
def test_up_steps_and_clamps_to_max(make):
    scaler, replicas, _ = make(3, 1, 5)
    scaler.scale_up()
    assert replicas() == 4
    scaler.scale_up()
    assert replicas() == 5
    scaler.scale_up()  # boundary no-op must be success, not an error
    assert replicas() == 5


@pytest.mark.parametrize("make", MAKERS, ids=IDS)
def test_up_step_size_clamps(make):
    scaler, replicas, _ = make(3, 1, 10, up=5)
    scaler.scale_up()
    assert replicas() == 8
    scaler.scale_up()
    assert replicas() == 10


@pytest.mark.parametrize("make", MAKERS, ids=IDS)
def test_down_steps_and_clamps_to_min(make):
    scaler, replicas, _ = make(3, 1, 5)
    scaler.scale_down()
    assert replicas() == 2
    scaler.scale_down()
    assert replicas() == 1
    scaler.scale_down()
    assert replicas() == 1


@pytest.mark.parametrize("make", MAKERS, ids=IDS)
def test_down_step_size_clamps(make):
    scaler, replicas, _ = make(8, 1, 10, down=5)
    scaler.scale_down()
    assert replicas() == 3
    scaler.scale_down()
    assert replicas() == 1


@pytest.mark.parametrize("make", MAKERS, ids=IDS)
def test_failure_raises_scale_error_and_changes_nothing(make):
    scaler, replicas, fail_next_up = make(3, 1, 5)
    fail_next_up(ConnectionError("backend down"))
    with pytest.raises(ScaleError):
        scaler.scale_up()
    assert replicas() == 3
    scaler.scale_up()  # the injected failure was one-shot
    assert replicas() == 4


class _ScriptedSource:
    """Deterministic depth sequence (repeats the last value)."""

    def __init__(self, depths):
        self.depths = list(depths)
        self.i = 0

    def num_messages(self):
        depth = self.depths[min(self.i, len(self.depths) - 1)]
        self.i += 1
        return depth


def _drive(make, depths, *, fail_up_at=None, initial=2):
    """Run the REAL ControlLoop over a scripted world; returns the
    per-tick (up, down, up_error?, down_error?, replicas-after) tuples —
    the full behavioral fingerprint the contract compares."""
    scaler, replicas, fail_next_up = make(initial, 1, 5)
    clock = FakeClock()
    rows = []

    class Recorder:
        def on_tick(self, record):
            rows.append(
                (
                    record.up,
                    record.down,
                    record.up_error is not None,
                    record.down_error is not None,
                    replicas(),
                )
            )

    loop = ControlLoop(
        scaler,
        _ScriptedSource(depths),
        LoopConfig(
            poll_interval=5.0,
            policy=PolicyConfig(
                scale_up_messages=100,
                scale_down_messages=10,
                scale_up_cooldown=10.0,
                scale_down_cooldown=20.0,
            ),
        ),
        clock=clock,
        observer=Recorder(),
    )
    if fail_up_at is not None:
        # arm the one-shot failure right before the target tick
        original_tick = loop.tick

        def tick(state):
            if len(rows) == fail_up_at:
                fail_next_up(ConnectionError("injected"))
            return original_tick(state)

        loop.tick = tick
    loop.run(max_ticks=len(depths))
    return rows


# High depth long enough to cross the up cooldown twice, then low depth
# across the down cooldown — exercises FIRE, COOLING, IDLE and both
# boundary no-ops within one episode.
SCRIPT = [150, 150, 150, 150, 150, 150, 5, 5, 5, 5, 5, 5, 5, 150, 150]


def test_identical_through_control_loop():
    fingerprints = [_drive(make, SCRIPT) for make in MAKERS]
    assert all(fp == fingerprints[0] for fp in fingerprints[1:])
    # sanity: the script really exercised the interesting gates
    ups = [row[0] for row in fingerprints[0]]
    assert Gate.FIRE in ups and Gate.COOLING in ups


def test_failure_behavior_identical_through_control_loop():
    # tick 2 (the first FIRE for this cooldown schedule) fails; the
    # cooldown must NOT advance, so the very next tick fires again —
    # identically for every actuator
    fingerprints = [
        _drive(make, SCRIPT, fail_up_at=2) for make in MAKERS
    ]
    assert all(fp == fingerprints[0] for fp in fingerprints[1:])
    failed = [row for row in fingerprints[0] if row[2]]
    assert failed, "the injected actuation failure never surfaced"


def test_pool_multi_step_spawn_failure_changes_nothing():
    # PodAutoScaler's failed scale is atomic (one read-modify-write);
    # the pool's build-then-commit must match even when the SECOND of
    # scale_up_pods replicas fails to build
    calls = {"n": 0}

    def flaky_factory(pool):
        calls["n"] += 1
        if calls["n"] == 5:  # 3 initial spawns + 1 ok + 1 boom
            raise MemoryError("cache allocation failed")
        return _StubWorker()

    pool = WorkerPool(
        flaky_factory, min=1, max=10, scale_up_pods=2, initial=3,
    )
    with pytest.raises(ScaleError):
        pool.scale_up()
    assert pool.replicas == 3  # the successfully built sibling rolled back
    pool.scale_up()
    assert pool.replicas == 5


def test_pool_prunes_retired_replicas_but_keeps_counts():
    pool = WorkerPool(lambda p: _StubWorker(), min=1, max=50, initial=1)
    pool.retired_keep = 2
    for _ in range(6):
        pool.scale_up()
        victim = max(
            (r for r in pool.members if r.state == "serving"),
            key=lambda r: r.index,
        )
        victim.worker.processed = 3
        pool.kill_worker(victim.index)
        pool.run_cycle()
    retired = [r for r in pool.members if r.state == "dead"]
    assert len(retired) == 2  # bounded corpse history
    assert pool.processed == 6 * 3  # pruned counts folded in
    with pytest.raises(ValueError):
        pool.kill_worker(1)  # long-pruned index: killing a corpse raises


def test_pool_drain_excluded_from_replica_count():
    # scale_down marks replicas draining and they stop counting
    # immediately — the pool analogue of spec.replicas dropping while
    # pods terminate
    pool, replicas, _ = make_pool(3, 1, 5)
    pool.scale_down()
    assert replicas() == 2
    from kube_sqs_autoscaler_tpu.fleet import DRAINING

    draining = [r for r in pool.members if r.state == DRAINING]
    assert len(draining) == 1
    assert draining[0].worker.admitting is False
    # newest serving replica drains first
    assert draining[0].index == 2


def test_pool_cycle_cost_flat_under_retired_history():
    # the fleet cycle computes its member-state partition ONCE: cycle
    # cost (full scans of `members`, itself bounded by retired_keep)
    # must not grow however much retirement history churns through
    class CountingList(list):
        def __init__(self, items=()):
            super().__init__(items)
            self.iterations = 0

        def __iter__(self):
            self.iterations += 1
            return super().__iter__()

    pool = WorkerPool(lambda p: _StubWorker(), min=1, max=500, initial=1)
    pool.retired_keep = 4

    def churn(n):
        for _ in range(n):
            pool.scale_up()
            victim = max(
                (r for r in pool.members if r.state == "serving"),
                key=lambda r: r.index,
            )
            victim.worker.processed = 2
            pool.kill_worker(victim.index)
            pool.run_cycle()

    churn(10)
    counting = CountingList(pool.members)
    pool.members = counting
    base = counting.iterations
    pool.run_cycle()
    per_cycle_early = counting.iterations - base
    assert per_cycle_early > 0
    churn(100)
    assert pool.members is counting  # mutated in place, never rebound
    base = counting.iterations
    pool.run_cycle()
    assert counting.iterations - base == per_cycle_early
    assert len(pool.members) <= 1 + pool.retired_keep
    assert pool.processed == 110 * 2  # pruned history's counts folded in


def test_sharded_pool_scale_up_resurrects_draining_shards_first():
    pool, replicas, _ = make_shards(4, 1, 5)
    pool.scale_down()
    pool.scale_down()
    assert replicas() == 2
    from kube_sqs_autoscaler_tpu.fleet import DRAINING, SERVING

    assert pool.shard_states[2] == DRAINING
    assert pool.shard_states[3] == DRAINING
    # admission really stopped on the drained shards (the mask
    # flipped); shard 4 was never activated (initial=4 of max=5)
    assert pool.worker.batcher.shard_admitting == [
        True, True, False, False, False,
    ]
    pool.scale_up()
    # the newest drain resurrects first — same O(1) flip back
    assert pool.shard_states[3] == SERVING
    assert pool.shard_states[2] == DRAINING
    assert pool.worker.batcher.shard_admitting[3] is True
    assert replicas() == 3


def test_sharded_pool_max_clamped_to_allocated_shards():
    with pytest.raises(ValueError, match="allocated shards"):
        ShardedWorkerPool(
            lambda p: _StubShardedWorker(2), min=1, max=5,
        )
