"""Deadline-aware admission under overload: the shed ladder end to
end, the degraded-row device contract, the per-tenant forecaster seam,
the adversarial scenario builders, and the overload bench smoke.

Tier-1 (tiny model, CPU); the full zipf/flood battery with wall-clock
gates runs in the slow tier.  The EDF/DRR scheduler invariants
themselves live in tests/test_admission.py — this module covers the
layers ABOVE the scheduler: worker integration, forecasting, scenarios,
and the BENCH_r16 gates.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from kube_sqs_autoscaler_tpu.core.clock import FakeClock  # noqa: E402
from kube_sqs_autoscaler_tpu.metrics.fake import FakeMessageQueue  # noqa: E402
from kube_sqs_autoscaler_tpu.workloads.continuous import (  # noqa: E402
    ContinuousBatcher,
    ContinuousWorker,
)
from kube_sqs_autoscaler_tpu.workloads.model import (  # noqa: E402
    ModelConfig,
    init_params,
)
from kube_sqs_autoscaler_tpu.workloads.service import (  # noqa: E402
    ServiceConfig,
    collect_replies,
)
from kube_sqs_autoscaler_tpu.workloads.tenancy import (  # noqa: E402
    TenancyConfig,
)

BATCH, PROMPT, TOKENS, BLOCK = 2, 4, 8, 2


@pytest.fixture(scope="module")
def model():
    return ModelConfig(
        vocab_size=64, d_model=16, n_heads=2, n_layers=2, d_ff=32,
        max_seq_len=PROMPT + TOKENS, dtype=jnp.float32,
    )


@pytest.fixture(scope="module")
def params(model):
    return init_params(jax.random.key(0), model)


def _config(**overrides):
    base = dict(
        queue_url="t://q", batch_size=BATCH, seq_len=PROMPT,
        generate_tokens=TOKENS, decode_block=BLOCK,
        result_queue_url="t://r",
    )
    base.update(overrides)
    return ServiceConfig(**base)


def _send(queue, tenant, ids, url="t://q"):
    return queue.send_message(
        url, json.dumps({"tenant": tenant,
                         "ids": np.asarray(ids).tolist()})
    )


# ---------------------------------------------------------------------------
# The staged-expiry refund bugfix (redelivered/expired picks must not
# skew DRR accounting, and the freed room must be re-picked)
# ---------------------------------------------------------------------------


def test_staged_expiry_sheds_refund_and_repick(model, params):
    clock = FakeClock()
    queue = FakeMessageQueue(now_fn=clock.now)
    results = FakeMessageQueue(now_fn=clock.now)
    worker = ContinuousWorker(
        queue, params, model, _config(request_ttl_s=5.0),
        result_queue=results,
        tenancy=TenancyConfig(tenants=("victim", "flood"),
                              staging_per_tenant=4, staging_total=8),
        now_fn=clock.now,
    )
    rng = np.random.default_rng(3)
    # three flood messages sent (and staged) at t=0
    for _ in range(3):
        _send(queue, "flood", rng.integers(1, 64, 3))
    for message in queue.receive_messages("t://q", max_messages=3):
        worker._fair.stage("flood", (
            "flood", None,
            np.asarray(json.loads(message["Body"])["ids"], np.int32),
            message,
        ))
    # ten seconds later the staged flood items are long expired; two
    # fresh victim messages arrive
    clock.advance(10.0)
    for _ in range(2):
        _send(queue, "victim", rng.integers(1, 64, 3))
    admitted = worker._refill()
    # ONE refill: expired flood picks shed (explicit replies, TTL
    # reason, deficit refunded) and the freed room re-picked the fresh
    # victims — work conservation holds through the sheds: every free
    # slot got a victim even though the DRR's first picks were all
    # doomed flood items
    assert worker.shed_by_reason["ttl"] == 2
    assert admitted == 2
    assert worker.batcher.active == 2
    tenants = [s.tenant for s in worker.batcher.slots if s.busy]
    assert tenants == ["victim", "victim"]
    # the refund: the flood was charged for picks that consumed no
    # slot, then refunded — its banked deficit lets its NEXT staged
    # item pick without re-earning, instead of silently shrinking its
    # future share
    assert worker._fair.drr.deficit("flood") >= 1.0
    # the remaining expired item sheds as soon as a refill has room
    for _ in range(200):
        worker.run_once()
        if worker.processed + worker.shed >= 5:
            break
    assert worker.shed_by_reason["ttl"] == 3
    replies, duplicates = collect_replies(results, "t://r")
    assert duplicates == 0
    assert sum(
        1 for p in replies.values() if p.get("error") == "expired"
    ) == 3
    assert sum(1 for p in replies.values() if "tokens" in p) == 2


# ---------------------------------------------------------------------------
# The overload ladder through a real worker
# ---------------------------------------------------------------------------


def _flood_worker(model, params, *, shed_tiers, queue, results,
                  generate_tokens=TOKENS):
    tenancy = TenancyConfig(
        tenants=("victim", "flood"), ttft_slo_s=(0.5, 0.0),
        urgency_window_s=0.6, shed_tiers=shed_tiers,
        staging_per_tenant=6, staging_total=6,
    )
    return ContinuousWorker(
        queue, params, model, _config(generate_tokens=generate_tokens),
        result_queue=results, tenancy=tenancy,
    )


def _drive_flood(worker, queue, *, cycles=14, flood_per_cycle=4,
                 victim_every=3):
    rng = np.random.default_rng(7)
    sent = {"victim": [], "flood": []}
    for cycle in range(cycles):
        for _ in range(flood_per_cycle):
            sent["flood"].append(
                _send(queue, "flood", rng.integers(1, 64, PROMPT))
            )
        if cycle % victim_every == 0:
            sent["victim"].append(
                _send(queue, "victim", rng.integers(1, 64, PROMPT))
            )
        worker.run_once()
    total = len(sent["victim"]) + len(sent["flood"])
    for _ in range(4000):
        if (worker.processed + worker.shed_by_reason["ttl"]
                + worker.shed_by_reason["pressure"]) >= total:
            break
        worker.run_once()
    return sent, total


def test_tier3_sheds_flood_with_explicit_replies_never_victims(
    model, params,
):
    queue, results = FakeMessageQueue(), FakeMessageQueue()
    worker = _flood_worker(model, params, shed_tiers=3, queue=queue,
                           results=results)
    sent, total = _drive_flood(worker, queue)
    assert worker.shed_by_reason["pressure"] > 0
    assert worker.ladder.entered_total[3] >= 1
    replies, duplicates = collect_replies(results, "t://r")
    assert duplicates == 0
    assert len(replies) == total  # every shed answered: exactly-once
    # every victim request COMPLETED (the no-victim-shed contract)
    for mid in sent["victim"]:
        assert "tokens" in replies[mid], replies[mid]
    shed_replies = [
        p for p in replies.values()
        if p.get("error") == "shed under overload pressure"
    ]
    assert len(shed_replies) == worker.shed_by_reason["pressure"]
    assert {p.get("tenant") for p in shed_replies} == {"flood"}


def test_tier1_degrades_flood_budgets_not_victims(model, params):
    queue, results = FakeMessageQueue(), FakeMessageQueue()
    worker = _flood_worker(model, params, shed_tiers=1, queue=queue,
                           results=results)
    sent, total = _drive_flood(worker, queue, cycles=10)
    assert worker.shed_by_reason["degraded"] > 0
    assert worker.shed_by_reason["pressure"] == 0  # tier capped at 1
    replies, _ = collect_replies(results, "t://r")
    assert len(replies) == total  # degraded requests still complete
    degraded = max(1, TOKENS // 2)
    flood_lengths = {len(replies[m]["tokens"]) for m in sent["flood"]}
    assert degraded in flood_lengths  # some flood replies were cut
    for mid in sent["victim"]:  # victims keep their full budget
        assert len(replies[mid]["tokens"]) == TOKENS
    assert worker.completed_by_tenant["victim"] == len(sent["victim"])


def test_tier2_evicts_cold_pool_entries_under_pressure(model, params):
    queue, results = FakeMessageQueue(), FakeMessageQueue()
    tenancy = TenancyConfig(
        tenants=("victim", "flood"), prefix_pool=4, prefix_len=PROMPT,
        shed_tiers=2, staging_per_tenant=6, staging_total=6,
    )
    config = _config(seq_len=PROMPT)
    # the pooled budget check needs prefix + prompt + tokens to fit
    small = ModelConfig(
        vocab_size=64, d_model=16, n_heads=2, n_layers=2, d_ff=32,
        max_seq_len=2 * PROMPT + TOKENS, dtype=jnp.float32,
    )
    small_params = init_params(jax.random.key(1), small)
    worker = ContinuousWorker(
        queue, small_params, small, config, result_queue=results,
        tenancy=tenancy,
    )
    pool = worker.batcher.prefix_pool
    rng = np.random.default_rng(11)
    # warm three pool entries (distinct prefixes), then flood plain
    # traffic to raise pressure past tier 2
    for prefix_seed in range(3):
        prefix = rng.integers(1, 64, PROMPT)
        queue.send_message("t://q", json.dumps({
            "tenant": "victim", "prefix": prefix.tolist(),
            "ids": rng.integers(1, 64, PROMPT).tolist(),
        }))
        worker.run_once()
    for _ in range(30):
        worker.run_once()
    resident_before = sum(pool.stats()["resident"])
    assert resident_before == 3
    for cycle in range(12):
        for _ in range(4):
            _send(queue, "flood", rng.integers(1, 64, PROMPT))
        worker.run_once()
    assert worker.ladder.entered_total[2] >= 1
    assert pool.evictions >= 1  # tier 2 shrank the resident set
    assert sum(pool.stats()["resident"]) <= max(1, pool.entries // 2)


def test_shed_reason_counters_and_overload_gauges_render(model, params):
    from kube_sqs_autoscaler_tpu.obs import WorkloadMetrics

    queue, results = FakeMessageQueue(), FakeMessageQueue()
    worker = _flood_worker(model, params, shed_tiers=3, queue=queue,
                           results=results)
    metrics = WorkloadMetrics()
    worker.attach_metrics(metrics)
    _drive_flood(worker, queue, cycles=8)
    text = metrics.render()
    prefix = "kube_sqs_autoscaler_workload"
    assert f"# TYPE {prefix}_requests_shed_total counter" in text
    # the unlabeled series is the sum of the reason-labeled ones
    # (dashboard compatibility)
    total_line = [
        line for line in text.splitlines()
        if line.startswith(f"{prefix}_requests_shed_total ")
    ]
    assert total_line and float(total_line[0].split()[-1]) == float(
        worker.shed
    )
    for reason in ("ttl", "degraded", "pressure"):
        assert (
            f'{prefix}_requests_shed_total{{reason="{reason}"}}' in text
        )
    assert f"{prefix}_overload_tier " in text
    assert f"{prefix}_overload_pressure " in text
    assert f"{prefix}_overload_tier_transitions_total" in text


# ---------------------------------------------------------------------------
# The degraded-row device contract (quiesce + taint)
# ---------------------------------------------------------------------------


def test_degraded_row_reuse_is_byte_identical(model, params):
    # a degraded slot finishes while its DEVICE budget is unspent; the
    # row must be quiesced and kept out of admission until the
    # in-flight block settles — re-admitting sooner would leak the old
    # request's stale tokens into the new request's slot
    rng = np.random.default_rng(19)
    prompts = [rng.integers(1, 64, PROMPT).astype(np.int32)
               for _ in range(3)]

    def reference(prompt):
        ref = ContinuousBatcher(
            params, model, batch_size=BATCH, prompt_len=PROMPT,
            generate_tokens=TOKENS, decode_block=BLOCK,
        )
        ref.submit(prompt, "ref")
        out = []
        for _ in range(100):
            out += ref.step()
            if out:
                return out[0][1].tolist()
        raise AssertionError("reference did not finish")

    batcher = ContinuousBatcher(
        params, model, batch_size=BATCH, prompt_len=PROMPT,
        generate_tokens=TOKENS, decode_block=BLOCK,
        tenancy=TenancyConfig(tenants=("a",)),
    )
    rows = batcher.submit_many([(prompts[0], "m0"), (prompts[1], "m1")])
    # simulate the ladder's tier-1 action on m0: budget cut below the
    # device's static budget
    batcher.slots[rows[0]].budget = 2
    batcher.slots[rows[0]].degraded = True
    finished = {}
    taint_seen = False
    for _ in range(200):
        for payload, tokens in batcher.step():
            finished[payload] = tokens.tolist()
        if batcher._tainted:
            taint_seen = True
            # a tainted row is not admissible this cycle
            assert not set(batcher.free_slots) & batcher._tainted
        if "m0" in finished and "m2" not in finished \
                and batcher.free_slots:
            batcher.submit_many([(prompts[2], "m2")])
        if len(finished) == 3:
            break
    assert taint_seen
    assert len(finished) == 3
    assert len(finished["m0"]) == 2  # the degraded reply is short
    # the request admitted into the recycled row decoded exactly what
    # a fresh engine decodes — no stale-token leak
    assert finished["m2"] == reference(prompts[2])
    assert finished["m1"] == reference(prompts[1])


# ---------------------------------------------------------------------------
# The forecaster seam: per-tenant depths -> SLO-weighted gate depth
# ---------------------------------------------------------------------------


def test_slo_urgency_weights_anchor_at_loosest_slo():
    from kube_sqs_autoscaler_tpu.forecast.tenants import (
        slo_urgency_weights,
    )

    tenancy = TenancyConfig(
        tenants=("tight", "loose", "free"),
        ttft_slo_s=(0.25, 1.0, 0.0),
    )
    weights = slo_urgency_weights(tenancy)
    assert weights == {"tight": 4.0, "loose": 1.0, "free": 1.0}
    # no SLOs at all: every weight degenerates to 1.0
    assert set(slo_urgency_weights(
        TenancyConfig(tenants=("a", "b"))
    ).values()) == {1.0}


def test_tenant_depth_history_records_and_bounds():
    from kube_sqs_autoscaler_tpu.forecast.tenants import (
        OTHER_TENANTS,
        TenantDepthHistory,
    )

    history = TenantDepthHistory(capacity=8, max_tenants=2)
    history.observe(1.0, {"a": 3, "b": 1})
    history.observe(2.0, {"a": 5, "evil1": 7, "evil2": 9})
    assert history.latest()["a"] == 5.0
    assert history.latest()["b"] == 0.0  # absent = explicit zero
    # past max_tenants, new labels fold into the catch-all
    assert set(history.tenants()) == {"a", "b", OTHER_TENANTS}
    assert history.latest()[OTHER_TENANTS] == 16.0


def test_tenant_aware_depth_boosts_gates_by_weighted_backlog():
    from kube_sqs_autoscaler_tpu.forecast.tenants import (
        TenantAwareDepth,
    )

    tenancy = TenancyConfig(
        tenants=("tight", "loose"), ttft_slo_s=(0.25, 1.0),
    )
    depths = {"tight": 10, "loose": 4}
    policy = TenantAwareDepth(lambda: depths, tenancy)
    # 10 tight requests weigh 4x: 40 + 4 = 44 > the observed 20
    assert policy.effective_messages(0.0, 20) == 44
    assert policy.last_weighted == pytest.approx(44.0)
    # monotone: a large observation passes through unshrunk
    assert policy.effective_messages(1.0, 100) == 100
    # unknown labels weigh 1.0
    depths = {"stranger": 7}
    assert policy.effective_messages(2.0, 0) == 7


def test_tenant_aware_depth_forecasts_per_tenant():
    from kube_sqs_autoscaler_tpu.forecast import EwmaForecaster
    from kube_sqs_autoscaler_tpu.forecast.tenants import (
        TenantAwareDepth,
    )

    tenancy = TenancyConfig(tenants=("tight",), ttft_slo_s=(0.5,))
    feed = {"tight": 0}
    policy = TenantAwareDepth(
        lambda: feed, tenancy, forecaster=EwmaForecaster(alpha=0.9),
        horizon=5.0, min_samples=2,
    )
    for t, depth in enumerate((2, 4, 6, 8)):
        feed = {"tight": depth}
        boosted = policy.effective_messages(float(t), 0)
    # the forecast can only RAISE the weighted depth past the latest
    # observation, never below it (conservative, like PredictivePolicy)
    assert boosted >= 8
    assert policy.name == "tenant-aware:ewma"


def test_worker_pool_aggregates_staged_by_tenant(model, params):
    from kube_sqs_autoscaler_tpu.fleet import WorkerPool

    queue = FakeMessageQueue()
    pool = WorkerPool.serving(
        queue, params, model, _config(result_queue_url=""),
        tenancy=TenancyConfig(tenants=("a", "b")),
        min=1, max=2,
    )
    try:
        rng = np.random.default_rng(23)
        for tenant in ("a", "a", "a", "b"):
            _send(queue, tenant, rng.integers(1, 64, 3))
        pool.run_cycle()
        staged = pool.staged_by_tenant()
        # the DRR admitted one of each tenant into the BATCH slots;
        # a's second stayed staged, a's third was handed back at the
        # per-tenant cap; every configured tenant reports (0 included)
        assert staged == {"a": 1, "b": 0}
    finally:
        pool.stop_all()


# ---------------------------------------------------------------------------
# Scenario builders
# ---------------------------------------------------------------------------


def test_zipf_scenario_shape_and_determinism():
    from kube_sqs_autoscaler_tpu.sim.scenarios import zipf_scenario

    scenario = zipf_scenario(tenants=60, heads=2, cycles=20)
    again = zipf_scenario(tenants=60, heads=2, cycles=20)
    assert scenario.schedule() == again.schedule()
    floods = [t for t in scenario.traffics if t.flood]
    assert len(floods) == 2  # the zipf head IS the flood
    assert all(t.tenant.startswith("z") for t in floods)
    victims = [t for t in scenario.traffics
               if not t.flood and t.ttft_slo_s > 0]
    assert victims  # SLO victims trickle through the attack
    # rank-k rate follows ~1/k: rank 2 sends strictly more often than
    # rank 20
    by_name = {t.tenant: t for t in scenario.traffics}
    assert by_name["z2"].every < by_name["z20"].every


def test_flash_crowd_is_one_shot_population_churn():
    from kube_sqs_autoscaler_tpu.sim.scenarios import (
        flash_crowd_scenario,
    )

    scenario = flash_crowd_scenario(crowd=50, crowd_start=3,
                                    crowd_span=2)
    crowd = [t for t in scenario.traffics if t.flood]
    assert len(crowd) == 50
    for t in crowd:
        sends = [t.sends_at(c, scenario.cycles)
                 for c in range(scenario.cycles)]
        assert sum(sends) == 1  # each crowd tenant fires exactly once
        assert 3 <= sends.index(1) < 5


def test_coordinated_flood_windows_align():
    from kube_sqs_autoscaler_tpu.sim.scenarios import (
        coordinated_flood_scenario,
    )

    scenario = coordinated_flood_scenario(floods=3, flood_start=4,
                                          flood_cycles=6)
    floods = [t for t in scenario.traffics if t.flood]
    assert len(floods) == 3
    assert {(t.start_cycle, t.end_cycle) for t in floods} == {(4, 10)}
    assert all(t.ttft_slo_s > 0 for t in scenario.traffics
               if not t.flood)


def test_overload_battery_scales_population_not_intensity():
    from kube_sqs_autoscaler_tpu.sim.scenarios import overload_battery

    full = overload_battery()
    smoke = overload_battery(scale=0.05)
    assert len(full) == len(smoke) == 3
    # thousands of distinct tenants at full scale
    assert sum(len(s.tenants) for s in full) > 2000
    assert sum(len(s.tenants) for s in smoke) < 300
    # the attack intensity survives the shrink (per-cycle flood rate)
    full_flood = [t for t in full[0].traffics if t.flood][0]
    smoke_flood = [t for t in smoke[0].traffics if t.flood][0]
    assert full_flood.per_cycle == smoke_flood.per_cycle


# ---------------------------------------------------------------------------
# CLI rejections for the new knobs
# ---------------------------------------------------------------------------


def test_overload_flag_rejections():
    from kube_sqs_autoscaler_tpu.workloads.__main__ import (
        main as worker_main,
    )

    base = ["--demo", "1", "--continuous", "--generate-tokens", "2"]
    with pytest.raises(SystemExit, match="requires --tenants"):
        worker_main(base + ["--tenant-slos", "0.5"])
    with pytest.raises(SystemExit, match="requires --tenants"):
        worker_main(base + ["--urgency-window", "0.5"])
    with pytest.raises(SystemExit, match="requires --tenants"):
        worker_main(base + ["--shed-tiers", "2"])
    with pytest.raises(SystemExit, match="counts must match"):
        worker_main(base + ["--tenants", "a,b",
                            "--tenant-slos", "0.5"])
    with pytest.raises(SystemExit, match=">= 0"):
        worker_main(base + ["--tenants", "a",
                            "--tenant-slos", "-0.5"])
    with pytest.raises(SystemExit, match="floats"):
        worker_main(base + ["--tenants", "a",
                            "--tenant-slos", "fast"])
    with pytest.raises(SystemExit, match="positive --tenant-slos"):
        worker_main(base + ["--tenants", "a",
                            "--urgency-window", "0.5"])
    with pytest.raises(SystemExit, match="\\[0, 3\\]"):
        worker_main(base + ["--tenants", "a", "--shed-tiers", "4"])
    with pytest.raises(SystemExit, match="must be >= 0"):
        worker_main(base + ["--tenants", "a",
                            "--tenant-slos", "0.5",
                            "--urgency-window", "-1"])


# ---------------------------------------------------------------------------
# The overload bench: tier-1 smoke, full battery slow
# ---------------------------------------------------------------------------


def test_overload_bench_smoke(tmp_path):
    import bench

    out = tmp_path / "BENCH_overload.json"
    summary = bench.run_overload_suite(
        output=str(out), scale=0.05, timing_gates=False,
    )
    assert summary["metric"] == "overload_victim_ttft_p99_improvement"
    artifact = json.loads(out.read_text())
    assert artifact["suite"] == "overload"
    for name, episode in artifact["episodes"].items():
        for mode in ("baseline", "deadline"):
            row = episode[mode]
            assert row["answered"] == row["requests"], (name, mode)
            assert row["duplicates"] == 0
    deadline_flood = artifact["episodes"]["coordinated-flood"]["deadline"]
    assert deadline_flood["shed_by_reason"]["pressure"] > 0
    assert deadline_flood["urgent_picks"] > 0
    parity = artifact["slo_free_parity"]
    assert parity["deadline-armed"]["ladder_transitions"] == 0
    assert parity["deadline-armed"]["urgent_picks"] == 0
    assert (parity["pr10"]["insert_dispatches"]
            == parity["deadline-armed"]["insert_dispatches"])


@pytest.mark.slow
def test_overload_bench_full_battery(tmp_path):
    import bench

    out = tmp_path / "BENCH_overload_full.json"
    summary = bench.run_overload_suite(output=str(out))
    assert summary["vs_baseline"] > 1.0
    artifact = json.loads(out.read_text())
    for name in ("coordinated-flood", "zipf"):
        episode = artifact["episodes"][name]
        assert (episode["deadline"]["victim_ttft_p99_s"]
                < episode["baseline"]["victim_ttft_p99_s"])
        assert (episode["deadline"]["victim_time_over_slo_s"]
                < episode["baseline"]["victim_time_over_slo_s"])
