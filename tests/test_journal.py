"""Flight recorder: tick ring, JSONL journal, schema versioning.

The journal is the controller's black box: every tick record appended as
one JSON line under a schema-versioned header, crash-safe line-at-a-time,
rotated by size.  These tests pin the wire format — `sim/replay.py`
re-drives episodes from these files, so a silent format drift would
corrupt postmortems rather than crash them.
"""

import json
import os

import pytest

from kube_sqs_autoscaler_tpu.core.clock import FakeClock
from kube_sqs_autoscaler_tpu.core.events import (
    MultiObserver,
    TickRecord,
)
from kube_sqs_autoscaler_tpu.core.policy import Gate
from kube_sqs_autoscaler_tpu.obs.journal import (
    JOURNAL_SCHEMA_VERSION,
    JournalSchemaError,
    TickJournal,
    TickRing,
    read_journal,
)


def make_record(i: int = 0, **overrides) -> TickRecord:
    defaults = dict(
        start=5.0 * (i + 1),
        duration=0.01,
        num_messages=100 + i,
        decision_messages=100 + i,
        up=Gate.FIRE,
        down=Gate.IDLE,
        observe_s=0.004,
        decide_s=0.001,
        actuate_s=0.005,
    )
    defaults.update(overrides)
    return TickRecord(**defaults)


# --- record serialization ---------------------------------------------------


def test_record_roundtrips_through_dict():
    record = make_record(3, up_error="Failed to scale up", forecast_error=2.5)
    assert TickRecord.from_dict(record.to_dict()) == record


def test_record_dict_omits_none_and_serializes_gates_as_strings():
    record = TickRecord(start=1.0, metric_error="boom")
    data = record.to_dict()
    assert data["up"] == "skipped" and data["down"] == "skipped"
    assert "num_messages" not in data and "decision_messages" not in data
    json.dumps(data)  # every value JSON-serializable


def test_record_from_dict_ignores_unknown_keys():
    data = make_record().to_dict()
    data["added_in_some_future_minor_version"] = {"x": 1}
    assert TickRecord.from_dict(data) == make_record()


# --- ring -------------------------------------------------------------------


def test_ring_keeps_only_the_newest_capacity_records():
    ring = TickRing(capacity=3)
    for i in range(5):
        ring.on_tick(make_record(i))
    assert len(ring) == 3
    assert [r.start for r in ring.snapshot()] == [15.0, 20.0, 25.0]
    assert [r.start for r in ring.snapshot(last=2)] == [20.0, 25.0]


def test_ring_rejects_zero_capacity():
    with pytest.raises(ValueError):
        TickRing(capacity=0)


# --- journal writer/reader --------------------------------------------------


def test_journal_roundtrip_records_and_meta(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    meta = {"poll_interval": 5.0, "policy": "reactive"}
    with TickJournal(path, meta=meta) as journal:
        for i in range(4):
            journal.on_tick(make_record(i))
    read_meta, records = read_journal(path)
    assert read_meta == meta
    assert records == [make_record(i) for i in range(4)]


def test_journal_lines_are_flushed_per_tick(tmp_path):
    """Crash-safety: every completed tick is on disk before the next —
    reading mid-run (no close) sees all records written so far."""
    path = str(tmp_path / "journal.jsonl")
    journal = TickJournal(path, meta={})
    journal.on_tick(make_record(0))
    journal.on_tick(make_record(1))
    _, records = read_journal(path)  # journal still open
    assert len(records) == 2
    journal.close()


def test_journal_header_carries_schema_version(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    TickJournal(path, meta={"a": 1}).close()
    header = json.loads(open(path).read().splitlines()[0])
    assert header["kind"] == "header"
    assert header["schema"] == JOURNAL_SCHEMA_VERSION


def test_schema_version_is_pinned():
    """Tier-1 guard: bumping the schema must be a deliberate act that also
    updates the reader/replayer (see obs/journal.py docstring)."""
    assert JOURNAL_SCHEMA_VERSION == 1


def test_reader_rejects_wrong_schema_version(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    with open(path, "w") as fh:
        fh.write(json.dumps({"kind": "header", "schema": 999, "meta": {}}))
        fh.write("\n")
    with pytest.raises(JournalSchemaError):
        read_journal(path)


def test_reader_rejects_headerless_file(tmp_path):
    path = str(tmp_path / "not-a-journal.jsonl")
    with open(path, "w") as fh:
        fh.write(json.dumps(make_record().to_dict()) + "\n")
    with pytest.raises(JournalSchemaError):
        read_journal(path)


def test_reader_tolerates_torn_final_line(tmp_path):
    """A crash mid-write leaves a partial last line; the journal contract
    is 'lose at most the tick in flight', not 'refuse the whole file'."""
    path = str(tmp_path / "journal.jsonl")
    with TickJournal(path, meta={}) as journal:
        journal.on_tick(make_record(0))
        journal.on_tick(make_record(1))
    with open(path, "a") as fh:
        fh.write('{"kind":"tick","start":15.0,"num_mes')  # torn write
    _, records = read_journal(path)
    assert records == [make_record(0), make_record(1)]


def test_reader_rejects_corruption_before_the_end(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    with TickJournal(path, meta={}) as journal:
        journal.on_tick(make_record(0))
    with open(path, "a") as fh:
        fh.write("garbage-not-json\n")
        fh.write(json.dumps({"kind": "tick", **make_record(1).to_dict()}) + "\n")
    with pytest.raises(JournalSchemaError):
        read_journal(path)


def test_journal_restart_appends_new_header_first_meta_wins(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    with TickJournal(path, meta={"run": 1}) as journal:
        journal.on_tick(make_record(0))
    with TickJournal(path, meta={"run": 2}) as journal:
        journal.on_tick(make_record(1))
    meta, records = read_journal(path)
    assert meta == {"run": 1}
    assert len(records) == 2


def test_journal_rotates_by_size(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    journal = TickJournal(path, meta={"big": "x" * 100}, max_bytes=4096)
    for i in range(200):  # each line ~150 bytes: several rotations
        journal.on_tick(make_record(i))
    journal.close()
    assert os.path.exists(path + ".1")
    assert os.path.getsize(path) <= 4096
    assert os.path.getsize(path + ".1") <= 4096
    # both generations are valid journals (fresh header after rotation);
    # the live file's header is marked as a rotation CONTINUATION — its
    # ticks continue the same episode, they are not a controller restart
    meta, newest = read_journal(path)
    assert meta["big"] == "x" * 100
    assert meta["_continuation"] is True
    _, previous = read_journal(path + ".1")
    assert newest and previous
    # newest file continues exactly where the rotated one left off
    assert newest[0].start - previous[-1].start == pytest.approx(5.0)


def test_journal_observer_survives_close():
    """A closed journal drops ticks instead of raising — shutdown order
    (server/journal/loop) must not matter."""
    import tempfile

    with tempfile.TemporaryDirectory() as tmp:
        journal = TickJournal(os.path.join(tmp, "j.jsonl"), meta={})
        journal.close()
        journal.on_tick(make_record())  # no raise


# --- fan-out ----------------------------------------------------------------


def test_ring_and_journal_fan_out_from_one_loop(tmp_path):
    """The production wiring: Prometheus + ring + journal behind one
    MultiObserver on the loop's single observer slot."""
    from kube_sqs_autoscaler_tpu.core.loop import ControlLoop, LoopConfig
    from kube_sqs_autoscaler_tpu.core.policy import PolicyConfig
    from kube_sqs_autoscaler_tpu.metrics import (
        FakeQueueService,
        QueueMetricSource,
    )
    from kube_sqs_autoscaler_tpu.obs import ControllerMetrics
    from kube_sqs_autoscaler_tpu.scale import FakeDeploymentAPI, PodAutoScaler

    path = str(tmp_path / "journal.jsonl")
    metrics = ControllerMetrics()
    ring = TickRing(capacity=2)
    journal = TickJournal(path, meta={"poll_interval": 1.0})
    api = FakeDeploymentAPI.with_deployments("ns", 3, "deploy")
    loop = ControlLoop(
        PodAutoScaler(
            client=api, max=5, min=1, scale_up_pods=1, scale_down_pods=1,
            deployment="deploy", namespace="ns",
        ),
        QueueMetricSource(
            client=FakeQueueService.with_depths(100, 100, 100),
            queue_url="example.com",
        ),
        LoopConfig(poll_interval=1.0, policy=PolicyConfig(
            scale_up_messages=100, scale_down_messages=3,
            scale_up_cooldown=1.0, scale_down_cooldown=1.0,
        )),
        clock=FakeClock(),
        observer=MultiObserver([metrics, ring, journal]),
    )
    loop.run(max_ticks=5)
    journal.close()
    assert "kube_sqs_autoscaler_ticks_total 5" in metrics.render()
    assert len(ring) == 2  # bounded
    _, records = read_journal(path)
    assert len(records) == 5  # unbounded (until rotation)
    assert records[-1] == ring.snapshot()[-1]


# --- restart episodes + mid-file schema (review findings) -------------------


def test_reader_rejects_wrong_schema_in_a_restart_header(tmp_path):
    """A restart header from a foreign build must fail loudly — its tick
    lines must never be silently parsed under this build's schema."""
    path = str(tmp_path / "journal.jsonl")
    with TickJournal(path, meta={}) as journal:
        journal.on_tick(make_record(0))
    with open(path, "a") as fh:
        fh.write(json.dumps({"kind": "header", "schema": 2, "meta": {}}) + "\n")
        fh.write(json.dumps({"kind": "tick", "start": 10.0}) + "\n")
    with pytest.raises(JournalSchemaError):
        read_journal(path)


def test_read_journal_episodes_splits_on_restart_headers(tmp_path):
    from kube_sqs_autoscaler_tpu.obs.journal import read_journal_episodes

    path = str(tmp_path / "journal.jsonl")
    with TickJournal(path, meta={"run": 1}) as journal:
        journal.on_tick(make_record(0))
        journal.on_tick(make_record(1))
    with TickJournal(path, meta={"run": 2}) as journal:
        journal.on_tick(make_record(0))
    episodes = read_journal_episodes(path)
    assert [meta["run"] for meta, _ in episodes] == [1, 2]
    assert [len(records) for _, records in episodes] == [2, 1]


def test_failed_rotation_does_not_kill_the_recorder(tmp_path, monkeypatch):
    """A transient filesystem error during rotation must degrade to
    appending in place, not silently drop every subsequent tick."""
    path = str(tmp_path / "journal.jsonl")
    journal = TickJournal(path, meta={}, max_bytes=4096)
    monkeypatch.setattr(
        os, "replace", lambda *a: (_ for _ in ()).throw(OSError("read-only"))
    )
    for i in range(60):  # crosses the rotation threshold several times
        journal.on_tick(make_record(i))
    monkeypatch.undo()
    journal.close()
    assert not os.path.exists(path + ".1")  # rotation never succeeded
    _, records = read_journal(path)
    assert len(records) == 60  # ...but no tick was lost


def test_reader_handles_non_dict_json_lines(tmp_path):
    """Valid-JSON-but-not-an-object corruption raises the typed error
    mid-file and is tolerated as a torn tail on the final line."""
    path = str(tmp_path / "journal.jsonl")
    with TickJournal(path, meta={}) as journal:
        journal.on_tick(make_record(0))
    with open(path, "a") as fh:
        fh.write("0\n")  # corrupt but json.loads-able
        fh.write(json.dumps({"kind": "tick", **make_record(1).to_dict()}) + "\n")
    with pytest.raises(JournalSchemaError):
        read_journal(path)
    # same corruption as the very last line: tolerated like a torn tail
    path2 = str(tmp_path / "journal2.jsonl")
    with TickJournal(path2, meta={}) as journal:
        journal.on_tick(make_record(0))
    with open(path2, "a") as fh:
        fh.write("[]\n")
    _, records = read_journal(path2)
    assert records == [make_record(0)]


def test_failed_header_write_after_rotation_recovers(tmp_path, monkeypatch):
    """ENOSPC between the rotation rename and the continuation header must
    not leave the live file headerless (permanently unreadable): tick
    lines are held back until the header lands."""
    path = str(tmp_path / "journal.jsonl")
    journal = TickJournal(path, meta={}, max_bytes=4096)
    filler = 0
    while os.path.getsize(path) < 3900:
        journal.on_tick(make_record(filler))
        filler += 1
    original = TickJournal._write_line
    failures = {"left": 1}
    def flaky(self, line):
        if '"kind":"header"' in line and failures["left"]:
            failures["left"] -= 1
            raise OSError("ENOSPC")
        return original(self, line)
    monkeypatch.setattr(TickJournal, "_write_line", flaky)
    journal.on_tick(make_record(filler))  # trips rotation; header fails once
    journal.close()
    assert os.path.exists(path + ".1")
    meta, records = read_journal(path)  # live file MUST still be a journal
    assert meta["_continuation"] is True
    assert records  # the post-rotation tick landed after the header retry


def test_rotation_threshold_counts_bytes_not_characters(tmp_path):
    """Non-ASCII content (AWS error strings, unicode deployment names) is
    multi-byte in the UTF-8 file; rotation must trigger on bytes."""
    path = str(tmp_path / "journal.jsonl")
    journal = TickJournal(path, meta={}, max_bytes=4096)
    for i in range(80):
        journal.on_tick(make_record(i, up_error="münchen-ü" * 10))
    journal.close()
    assert os.path.exists(path + ".1")
    assert os.path.getsize(path) <= 4096
    assert os.path.getsize(path + ".1") <= 4096


def test_failed_reopen_after_rotation_recovers_on_later_ticks(
    tmp_path, monkeypatch
):
    """If even reopening the live file fails mid-rotation, recording must
    resume once the filesystem recovers — never die permanently."""
    import builtins

    path = str(tmp_path / "journal.jsonl")
    journal = TickJournal(path, meta={}, max_bytes=4096)
    filler = 0
    while os.path.getsize(path) < 3900:
        journal.on_tick(make_record(filler))
        filler += 1
    original_open = builtins.open
    failures = {"left": 2}
    def flaky_open(file, *args, **kwargs):
        if file == path and failures["left"]:
            failures["left"] -= 1
            raise OSError("EACCES")
        return original_open(file, *args, **kwargs)
    monkeypatch.setattr(builtins, "open", flaky_open)
    # rotation: rename ok, open fails, immediate reopen fails too — this
    # tick is dropped and the journal is left with no live file handle
    journal.on_tick(make_record(filler))
    monkeypatch.undo()
    journal.on_tick(make_record(filler + 1))  # filesystem recovered
    journal.on_tick(make_record(filler + 2))
    journal.close()
    meta, records = read_journal(path)  # live file is a valid journal again
    assert meta["_continuation"] is True
    assert len(records) == 2  # only the failure-window tick was dropped


def test_restart_onto_crash_torn_journal_keeps_both_episodes(tmp_path):
    """Crash mid-write, then restart onto the same --journal-path: the new
    run's header must NOT merge with the torn fragment into one corrupt
    line that makes the whole file unreadable (the crash-postmortem case
    the journal exists for)."""
    path = str(tmp_path / "journal.jsonl")
    with TickJournal(path, meta={"run": 1}) as journal:
        journal.on_tick(make_record(0))
    with open(path, "a") as fh:
        fh.write('{"kind":"tick","start":10.0,"num_mes')  # crash mid-write
    with TickJournal(path, meta={"run": 2}) as journal:  # restart
        journal.on_tick(make_record(5))
    from kube_sqs_autoscaler_tpu.obs.journal import read_journal_episodes

    episodes = read_journal_episodes(path)
    assert [meta["run"] for meta, _ in episodes] == [1, 2]
    assert [len(records) for _, records in episodes] == [1, 1]
    # only the in-flight tick was lost — the contract held
    assert episodes[0][1][0] == make_record(0)
    assert episodes[1][1][0] == make_record(5)
