"""Request-lifecycle tracing: the phase-chain registry, its Perfetto
flow export, the journal's ``kind="request"`` sidecar records under
rotation/torn tails, and the ``bench.py --suite obs`` battery smoke.

The registry's contract (obs/lifecycle.py) is audit-grade: every
answered request carries a gap-free monotone chain with exactly ONE
reply stamp, duplicates close without one, restored registries bump
their flow-id epoch so post-restart ids can never collide with
pre-crash ones, and tracing-off means no registry at all (byte-identity
is pinned by the bench, not here).
"""

import json
import os

import pytest

from kube_sqs_autoscaler_tpu.obs import (
    ControllerMetrics,
    LifecycleRegistry,
    ObservabilityServer,
    WorkloadMetrics,
)
from kube_sqs_autoscaler_tpu.obs.journal import (
    TickJournal,
    read_journal_events,
)
from kube_sqs_autoscaler_tpu.obs.lifecycle import (
    RequestTrace,
    phase_durations,
    request_key,
    validate_chain,
)
from kube_sqs_autoscaler_tpu.obs.trace import (
    request_trace_events,
    to_chrome_trace,
    track_for,
    track_metadata_events,
)


class Clock:
    def __init__(self, t=0.0):
        self.t = t

    def now(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


def make_registry(clock, **kwargs):
    return LifecycleRegistry(now_fn=clock.now, **kwargs)


def drive(reg, clock, rid, tenant="a", staged=True, handoff=True,
          tokens=3, step=0.01):
    """One full request through every seam on the virtual clock."""
    reg.arrival(rid, tenant=tenant)
    if staged:
        clock.advance(step)
        reg.stamp(rid, "staged")
        clock.advance(step)
        reg.stamp(rid, "picked")
    clock.advance(step)
    reg.stamp(rid, "admitted")
    reg.stamp(rid, "prefill")
    clock.advance(step)
    reg.stamp(rid, "first_token")
    reg.token(rid)
    if handoff:
        clock.advance(step)
        reg.stamp(rid, "handoff")
    for _ in range(max(0, tokens - 1)):
        clock.advance(step)
        reg.token(rid)
    reg.stamp(rid, "completed")
    clock.advance(step)
    reg.settle(rid)


# -- trace keys and chain validation ------------------------------------


def test_request_key_prefers_message_id():
    assert request_key({"MessageId": "m-1", "ReceiptHandle": "rh"}) == "m-1"
    assert request_key({"ReceiptHandle": "rh"}) == "rh"
    assert request_key({"MessageId": ""}) is None
    assert request_key("not-a-message") is None
    assert request_key(None) is None


def test_full_chain_validates_gap_free():
    clock = Clock()
    reg = make_registry(clock)
    drive(reg, clock, "r1")
    (trace,) = reg.done_traces()
    assert validate_chain(
        trace, require_staged=True, require_handoff=True
    ) == []
    assert trace.count("reply") == 1
    assert trace.total_s() == pytest.approx(0.08)
    assert reg.replies == 1 and reg.open_count == 0


def test_validate_chain_flags_missing_phases_and_double_reply():
    trace = RequestTrace(rid="r", flow_id=1)
    trace.stamps = [("arrival", 0.0), ("reply", 1.0), ("reply", 2.0)]
    problems = validate_chain(trace)
    assert any("exactly one reply" in p for p in problems)
    assert any("missing admitted" in p for p in problems)
    assert any("missing first_token" in p for p in problems)


def test_validate_chain_flags_non_monotone_first_occurrences():
    trace = RequestTrace(rid="r", flow_id=1)
    trace.stamps = [
        ("arrival", 1.0), ("admitted", 0.5), ("prefill", 0.6),
        ("first_token", 0.7), ("completed", 0.8), ("reply", 0.9),
    ]
    assert any(
        "non-monotone" in p for p in validate_chain(trace)
    )


def test_restamps_after_redispatch_keep_the_chain_valid():
    # re-dispatch re-stamps admitted/prefill LATER; validation takes
    # first occurrences, so the chain stays monotone
    clock = Clock()
    reg = make_registry(clock)
    drive(reg, clock, "r1", staged=False, handoff=False)
    (trace,) = reg.done_traces()
    trace.stamps.append(("admitted", clock.advance(0.01)))
    assert validate_chain(trace) == []


def test_error_reply_needs_only_arrival_and_reply():
    clock = Clock()
    reg = make_registry(clock)
    reg.arrival("r1", tenant="a")
    clock.advance(0.01)
    reg.settle("r1", error="shed: queue TTL exceeded")
    (trace,) = reg.done_traces()
    assert trace.error is not None
    assert validate_chain(trace) == []


def test_arrival_is_idempotent_and_backdates_to_sent():
    clock = Clock(10.0)
    reg = make_registry(clock)
    reg.arrival("r1", sent=4.5)
    reg.arrival("r1")  # redelivered copy of the still-open request
    (trace,) = reg.open_traces()
    assert trace.count("arrival") == 1
    assert trace.first("arrival") == 4.5


def test_duplicate_closes_without_a_reply_stamp():
    clock = Clock()
    reg = make_registry(clock)
    drive(reg, clock, "r1")
    # the redelivered copy re-opens, then the dedup path consumes it
    reg.arrival("r1")
    reg.duplicate("r1")
    copies = reg.traces_of("r1")
    assert len(copies) == 2
    dup = [t for t in copies if t.notes.get("duplicate")]
    assert len(dup) == 1
    assert dup[0].count("reply") == 0
    assert reg.duplicates == 1
    assert sum(t.count("reply") for t in copies) == 1


def test_capacity_eviction_bounds_open_traces():
    clock = Clock()
    reg = make_registry(clock, capacity=2)
    for i in range(4):
        reg.arrival(f"r{i}")
    assert reg.open_count == 2
    assert reg.evicted == 2
    evicted = [t for t in reg.done_traces() if t.notes.get("evicted")]
    assert {t.rid for t in evicted} == {"r0", "r1"}


# -- the critical-path decomposition ------------------------------------


def test_phase_durations_decompose_the_chain():
    trace = RequestTrace(rid="r", flow_id=1)
    trace.stamps = [
        ("arrival", 0.0), ("admitted", 0.3), ("prefill", 0.3),
        ("first_token", 0.5), ("handoff", 0.6), ("completed", 1.0),
        ("reply", 1.1),
    ]
    durations = phase_durations(trace)
    assert durations["queue"] == pytest.approx(0.3)
    assert durations["prefill"] == pytest.approx(0.2)
    assert durations["handoff"] == pytest.approx(0.1)
    assert durations["decode"] == pytest.approx(0.4)
    assert durations["settle"] == pytest.approx(0.1)


def test_inter_token_and_tpot():
    trace = RequestTrace(rid="r", flow_id=1)
    trace.token_times = [1.0, 1.0, 1.2, 1.5]
    assert trace.inter_token_s() == pytest.approx([0.0, 0.2, 0.3])
    assert trace.tpot_s() == pytest.approx(0.5 / 3)
    assert RequestTrace(rid="r", flow_id=1).tpot_s() is None


def test_attribute_slo_names_the_dominant_phase():
    clock = Clock()
    reg = make_registry(clock)
    # r-queue waits 1.0s before admission, decodes instantly
    reg.arrival("r-queue")
    clock.advance(1.0)
    for phase in ("admitted", "prefill", "first_token", "completed"):
        reg.stamp("r-queue", phase)
    reg.settle("r-queue")
    # r-decode admits instantly, decodes for 2.0s
    reg.arrival("r-decode")
    reg.stamp("r-decode", "admitted")
    reg.stamp("r-decode", "prefill")
    reg.stamp("r-decode", "first_token")
    clock.advance(2.0)
    reg.stamp("r-decode", "completed")
    reg.settle("r-decode")
    report = reg.attribute_slo(0.0)
    assert report["requests"] == 2
    assert report["over_slo"] == 2
    assert report["by_phase"] == {"decode": 1, "queue": 1}
    assert report["worst"][0]["rid"] == "r-decode"
    assert report["worst"][0]["dominant"] == "decode"
    assert report["worst"][1]["dominant"] == "queue"
    # under a lenient SLO nothing attributes
    assert reg.attribute_slo(10.0)["over_slo"] == 0


# -- restart: epochs, flow ids, restored notes --------------------------


def test_import_bumps_epoch_so_flow_ids_never_collide():
    clock = Clock()
    reg = make_registry(clock)
    drive(reg, clock, "done-1")
    reg.arrival("open-1")
    before = {t.flow_id for t in reg.done_traces() + reg.open_traces()}
    state = reg.export_state()

    fresh = make_registry(clock)
    recovered = fresh.import_state(state, now=clock.now())
    assert recovered >= 2
    assert fresh.epoch == reg.epoch + 1
    (restored,) = fresh.open_traces()
    assert restored.rid == "open-1"
    assert restored.notes.get("restored") == 1
    drive(fresh, clock, "post-restart")
    after = {
        t.flow_id
        for t in fresh.done_traces() + fresh.open_traces()
        if t.rid == "post-restart"
    }
    assert not (before & after)
    assert all(fid >> 32 == fresh.epoch for fid in after)


def test_import_counters_survive_and_stale_open_traces_age_out():
    clock = Clock(100.0)
    reg = make_registry(clock)
    drive(reg, clock, "r1")
    reg.arrival("stale")
    clock.advance(50.0)
    reg.arrival("fresh")
    state = reg.export_state()

    fresh = make_registry(clock)
    fresh.import_state(state, now=clock.now(), max_age_s=10.0)
    assert {t.rid for t in fresh.open_traces()} == {"fresh"}
    assert fresh.replies == reg.replies
    assert fresh.created == reg.created


# -- histogram export ----------------------------------------------------


def test_export_metrics_renders_cumulative_phase_histograms():
    clock = Clock()
    reg = make_registry(clock)
    drive(reg, clock, "r1", tenant="tenant-a")
    metrics = WorkloadMetrics()
    reg.export_metrics(metrics)
    body = metrics.render()
    assert 'request_phase_seconds_bucket{phase="queue",le="' in body
    assert 'request_phase_seconds_bucket{phase="decode",le="' in body
    assert 'tenant_inter_token_seconds_bucket{tenant="tenant-a"' in body
    assert (
        'tenant_time_per_output_token_seconds_bucket{tenant="tenant-a"'
        in body
    )
    q99 = metrics.histogram_quantile(
        "request_phase_seconds", 0.99, labels=(("phase", "queue"),)
    )
    assert q99 is not None and q99 > 0
    # drained: a second export adds nothing
    count_before = body.count("request_phase_seconds_bucket")
    reg.export_metrics(metrics)
    assert (
        metrics.render().count("request_phase_seconds_bucket")
        == count_before
    )


def test_tenant_histogram_series_are_bounded():
    clock = Clock()
    reg = make_registry(clock)
    reg.MAX_TENANT_SERIES = 2
    for i in range(4):
        drive(reg, clock, f"r{i}", tenant=f"tenant-{i}")
    metrics = WorkloadMetrics()
    reg.export_metrics(metrics)
    body = metrics.render()
    assert 'tenant="tenant-0"' in body
    assert 'tenant="tenant-1"' in body
    assert 'tenant="tenant-2"' not in body
    assert f'tenant="{reg.OTHER_TENANTS}"' in body


# -- journal sidecar records: rotation and torn tails -------------------


def test_settle_journals_request_records(tmp_path):
    clock = Clock()
    path = str(tmp_path / "ticks.jsonl")
    journal = TickJournal(path, meta={"run": "t"})
    reg = make_registry(clock, journal=journal)
    drive(reg, clock, "r1", tenant="a")
    journal.close()
    (event,) = read_journal_events(path, "request")
    assert event["rid"] == "r1"
    restored = RequestTrace.from_dict(event)
    assert validate_chain(
        restored, require_staged=True, require_handoff=True
    ) == []


def test_request_records_survive_rotation_with_rejoin(tmp_path):
    clock = Clock()
    path = str(tmp_path / "ticks.jsonl")
    journal = TickJournal(path, meta={"run": "t"}, max_bytes=4096)
    reg = make_registry(clock, journal=journal)
    for i in range(30):
        drive(reg, clock, f"r{i}", tenant="a")
    journal.close()
    assert os.path.exists(path + ".1"), "episode never rotated"
    live_only = [
        e["rid"] for e in read_journal_events(path, "request")
    ]
    rejoined = [
        e["rid"]
        for e in read_journal_events(path, "request", rejoin=True)
    ]
    assert len(live_only) < 30
    # rejoin recovers the one kept rotated generation on top of the
    # live file: a contiguous, in-order suffix of the stream ending at
    # the newest record (older generations age out — the flight
    # recorder keeps recent history, not an archive)
    assert len(rejoined) > len(live_only)
    assert rejoined[-len(live_only):] == live_only
    first = int(rejoined[0][1:])
    assert rejoined == [f"r{i}" for i in range(first, 30)]


def test_torn_tail_does_not_lose_earlier_request_records(tmp_path):
    clock = Clock()
    path = str(tmp_path / "ticks.jsonl")
    journal = TickJournal(path, meta={"run": "t"})
    reg = make_registry(clock, journal=journal)
    drive(reg, clock, "r1")
    drive(reg, clock, "r2")
    journal.close()
    with open(path, "a", encoding="utf-8") as fh:
        fh.write('{"kind":"request","rid":"torn","sta')  # crash mid-line
    events = read_journal_events(path, "request", rejoin=True)
    assert [e["rid"] for e in events] == ["r1", "r2"]


def test_flow_ids_do_not_collide_across_journal_restart_episodes(tmp_path):
    clock = Clock()
    path = str(tmp_path / "ticks.jsonl")
    journal = TickJournal(path, meta={"run": "t"})
    reg = make_registry(clock, journal=journal)
    for i in range(3):
        drive(reg, clock, f"pre-{i}")
    state = reg.export_state()
    journal.close()
    # the controller restarts: a fresh journal handle appends a new
    # episode header onto the same path, and the rehydrated registry
    # mints flow ids one epoch up
    journal2 = TickJournal(path, meta={"run": "t"})
    reg2 = make_registry(clock, journal=journal2)
    reg2.import_state(state, now=clock.now())
    for i in range(3):
        drive(reg2, clock, f"post-{i}")
    journal2.close()
    events = read_journal_events(path, "request", rejoin=True)
    flow_ids = [e["flow_id"] for e in events]
    assert len(flow_ids) == 6
    assert len(set(flow_ids)) == 6
    epochs = {fid >> 32 for fid in flow_ids}
    assert epochs == {0, 1}


# -- Perfetto export: pinned tracks, flow arrows ------------------------


def test_track_assignments_are_pinned():
    # keyed by category, never discovery order: the same event lands on
    # the same lane across restarts and rotation rejoins
    assert track_for("tick") == (1, 1)
    assert track_for("fleet") == (2, 1)
    assert track_for("shard") == (2, 2)
    assert track_for("restart") == (2, 3)
    assert track_for("knob") == (2, 4)
    assert track_for("overload") == (3, 1)
    assert track_for("prefix") == (3, 2)
    assert track_for("plane") == (3, 3)
    assert track_for("request") == (4, 1)
    assert track_for("never-heard-of-it") == track_for("fleet")


def test_track_metadata_names_the_request_phase_lanes():
    events = track_metadata_events()
    assert all(e["ph"] == "M" for e in events)
    processes = {
        e["pid"]: e["args"]["name"]
        for e in events if e["name"] == "process_name"
    }
    assert processes[4] == "requests"
    lanes = {
        (e["pid"], e["tid"]): e["args"]["name"]
        for e in events if e["name"] == "thread_name"
    }
    assert lanes[(4, 1)] == "queue"
    assert lanes[(4, 2)] == "prefill"
    assert lanes[(4, 3)] == "kv-handoff"
    assert lanes[(4, 4)] == "decode"
    assert lanes[(4, 5)] == "settle"
    # one metadata entry per track, no duplicates
    names = [(e["name"], e["pid"], e["tid"]) for e in events]
    assert len(names) == len(set(names))


def test_request_trace_events_render_flow_linked_phase_spans():
    clock = Clock(50.0)
    reg = make_registry(clock)
    drive(reg, clock, "r1", tenant="a")
    drive(reg, clock, "r2", tenant="b")
    events = request_trace_events(reg.done_traces())
    spans = [e for e in events if e["ph"] == "X"]
    flows = [e for e in events if e["ph"] in ("s", "t", "f")]
    assert spans and flows
    assert all(e["cat"] == "request" for e in events)
    assert all(e["pid"] == 4 for e in events)
    by_lane = {e["tid"] for e in spans}
    assert by_lane == {1, 2, 3, 4, 5}  # every phase got its own lane
    # zero-based on the first arrival despite the epoch-50 clock
    assert min(e["ts"] for e in events) == 0
    for rid in ("r1", "r2"):
        chain = [
            e for e in flows
            if e["id"] in {
                t.flow_id for t in reg.done_traces() if t.rid == rid
            }
        ]
        assert [e["ph"] for e in chain[:1]] == ["s"]
        assert chain[-1]["ph"] == "f"
        assert chain[-1]["bp"] == "e"
        assert all(e["ph"] == "t" for e in chain[1:-1])
    # two requests, two distinct flow arrows
    assert len({e["id"] for e in flows}) == 2


def test_request_trace_events_skip_unarrived_and_render_errors():
    assert request_trace_events([]) == []
    never_arrived = RequestTrace(rid="r", flow_id=1)
    never_arrived.stamps = [("admitted", 1.0)]
    assert request_trace_events([never_arrived]) == []
    clock = Clock()
    reg = make_registry(clock)
    reg.arrival("shed-1")
    clock.advance(0.25)
    reg.stamp("shed-1", "admitted")
    reg.settle("shed-1", error="shed")
    events = request_trace_events(reg.done_traces())
    spans = [e for e in events if e["ph"] == "X"]
    assert spans and all(e["args"]["error"] == "shed" for e in spans)


def test_chrome_trace_merges_request_spans_without_tick_records():
    clock = Clock()
    reg = make_registry(clock)
    drive(reg, clock, "r1")
    trace = to_chrome_trace(
        [], extra_events=request_trace_events(reg.done_traces())
    )
    cats = {e.get("cat") for e in trace["traceEvents"]}
    assert "request" in cats
    assert any(e["ph"] == "M" for e in trace["traceEvents"])
    # still byte-empty with nothing recorded
    assert to_chrome_trace([], extra_events=[])["traceEvents"] == []


# -- the /debug/requests endpoint ---------------------------------------


def test_debug_requests_endpoint_serves_snapshot_and_attribution():
    import urllib.request

    clock = Clock()
    reg = make_registry(clock)
    drive(reg, clock, "r1", tenant="a")
    reg.arrival("still-open")
    metrics = ControllerMetrics()
    server = ObservabilityServer(
        metrics, host="127.0.0.1", port=0, lifecycle=reg
    )
    server.start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        body = json.loads(
            urllib.request.urlopen(
                f"{base}/debug/requests?n=10&slo=0.0"
            ).read().decode()
        )
        assert body["replies"] == 1 and body["open"] == 1
        assert [t["rid"] for t in body["requests"]] == ["r1"]
        assert [t["rid"] for t in body["open_requests"]] == ["still-open"]
        assert body["attribution"]["over_slo"] == 1
        assert body["attribution"]["dominant"] in (
            "queue", "prefill", "handoff", "decode", "settle"
        )
    finally:
        server.stop()


def test_debug_requests_404_without_a_registry():
    import urllib.error
    import urllib.request

    server = ObservabilityServer(
        ControllerMetrics(), host="127.0.0.1", port=0
    )
    server.start()
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/debug/requests"
            )
        assert err.value.code == 404
    finally:
        server.stop()


# -- the bench battery ---------------------------------------------------


def test_obs_bench_smoke(tmp_path):
    """Tier-1: the full episode set with the timing gate off (virtual
    clocks make everything else deterministic) — completeness,
    dispatch-count parity, restart epochs, dedup, and both SLO
    attributions must all hold."""
    import bench

    out = tmp_path / "BENCH_obs.json"
    # any failed gate raises SystemExit(2) before returning
    summary = bench.run_obs_suite(output=str(out), timing_gates=False)
    assert summary["metric"] == "obs_complete_chains"
    assert summary["value"] > 0
    artifact = json.loads(out.read_text())
    comp = artifact["completeness"]
    assert comp["on"]["chains_ok"] == comp["on"]["audited"]
    assert comp["chaos"]["chains_ok"] == comp["chaos"]["audited"]
    assert comp["registry"]["duplicates"] >= 1
    assert artifact["restart"]["epoch"] == 1
    assert artifact["attribution"]["prefill_starved"]["dominant"] == "queue"
    assert artifact["attribution"]["decode_contended"]["dominant"] in (
        "decode", "handoff"
    )


@pytest.mark.slow
def test_obs_bench_full_battery(tmp_path):
    import bench

    out = tmp_path / "BENCH_obs_full.json"
    summary = bench.run_obs_suite(output=str(out))
    assert summary["metric"] == "obs_complete_chains"
    artifact = json.loads(out.read_text())
    assert (
        artifact["overhead"]["tokens_per_second_ratio"]
        >= artifact["overhead"]["floor"]
    )
    assert artifact["overhead"]["counters_on"] == (
        artifact["overhead"]["counters_off"]
    )
