"""Multi-tenant fair admission: DRR invariants, the prefix pool, sticky
routing, and the tenancy-off reference path.

Tier-1 (tiny model, CPU).  The deficit-round-robin properties the
module docstring promises are pinned here as property tests (seeded
mini-hypothesis via tests/proptest.py): work conservation (no idle
slot while any tenant queue is non-empty), bounded deficit (no tenant
banks credit past ``quantum * weight + 1``), and deterministic
admission order.  The engine-level tests pin the perf contract: one
insert dispatch per refill whatever the tenant mix, pool hits that
skip the shared-prefix prefill, sticky routing that keeps a tenant on
its home shard and yields under imbalance, and byte-identity to the
reference engine when tenancy is off (single default tenant).
"""

from __future__ import annotations

import json

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from tests.proptest import given, settings, st  # noqa: E402

from kube_sqs_autoscaler_tpu.core.clock import FakeClock  # noqa: E402
from kube_sqs_autoscaler_tpu.metrics.fake import FakeMessageQueue  # noqa: E402
from kube_sqs_autoscaler_tpu.workloads.continuous import (  # noqa: E402
    ContinuousBatcher,
    ContinuousWorker,
)
from kube_sqs_autoscaler_tpu.workloads.model import (  # noqa: E402
    ModelConfig,
    init_params,
)
from kube_sqs_autoscaler_tpu.workloads.service import (  # noqa: E402
    ServiceConfig,
    collect_replies,
    parse_tenant_request,
    tenant_completions,
)
from kube_sqs_autoscaler_tpu.workloads.tenancy import (  # noqa: E402
    DeficitRoundRobin,
    FairAdmission,
    PrefixPool,
    TenancyConfig,
    prefix_pool_key,
)

BATCH, PROMPT, PREFIX, TOKENS, BLOCK = 2, 4, 6, 8, 2


@pytest.fixture(scope="module")
def model():
    return ModelConfig(
        vocab_size=64, d_model=16, n_heads=2, n_layers=2, d_ff=32,
        max_seq_len=PREFIX + PROMPT + TOKENS, dtype=jnp.float32,
    )


@pytest.fixture(scope="module")
def params(model):
    return init_params(jax.random.key(0), model)


def _config(**overrides):
    base = dict(
        queue_url="t://q", batch_size=BATCH, seq_len=PROMPT,
        generate_tokens=TOKENS, decode_block=BLOCK,
        result_queue_url="t://r",
    )
    base.update(overrides)
    return ServiceConfig(**base)


# ---------------------------------------------------------------------------
# TenancyConfig: the policy surface validates at construction
# ---------------------------------------------------------------------------


def test_tenancy_config_rejections():
    with pytest.raises(ValueError, match="at least one tenant"):
        TenancyConfig(tenants=())
    with pytest.raises(ValueError, match="duplicate"):
        TenancyConfig(tenants=("a", "a"))
    with pytest.raises(ValueError, match="non-empty"):
        TenancyConfig(tenants=("a", ""))
    with pytest.raises(ValueError, match="counts must match"):
        TenancyConfig(tenants=("a", "b"), weights=(1.0,))
    with pytest.raises(ValueError, match=">= 0.01"):
        TenancyConfig(tenants=("a",), weights=(0.0,))
    with pytest.raises(ValueError, match=">= 0.01"):
        TenancyConfig(tenants=("a",), weights=(-2.0,))
    with pytest.raises(ValueError, match=">= 0.01"):
        # a vanishing weight would spin the DRR ~1/(quantum*weight)
        # rounds per admitted request inside the refill loop
        TenancyConfig(tenants=("a",), weights=(1e-9,))
    with pytest.raises(ValueError, match="prefix_pool"):
        TenancyConfig(tenants=("a",), prefix_pool=-1)
    with pytest.raises(ValueError, match="quantum"):
        TenancyConfig(tenants=("a",), quantum=0.0)
    with pytest.raises(ValueError, match="quantum \\* min"):
        # the two floors compose: the PRODUCT quantum*weight is what a
        # round earns, so both at the floor would still spin ~10,000
        # rounds per admitted request
        TenancyConfig(tenants=("a",), weights=(0.01,), quantum=0.01)
    with pytest.raises(ValueError, match="TTFT SLO"):
        TenancyConfig(tenants=("a", "b"), ttft_slo_s=(1.0,))
    with pytest.raises(ValueError, match=">= 0"):
        TenancyConfig(tenants=("a",), ttft_slo_s=(-1.0,))


def test_tenancy_config_unregistered_tenant_defaults():
    # fairness must not require pre-registration: unknown tenants serve
    # at weight 1.0 with no SLO
    cfg = TenancyConfig(tenants=("a", "b"), weights=(3.0, 1.0),
                        ttft_slo_s=(0.5, 0.25))
    assert cfg.weight_of("a") == 3.0
    assert cfg.weight_of("stranger") == 1.0
    assert cfg.slo_of("b") == 0.25
    assert cfg.slo_of("stranger") == 0.0


# ---------------------------------------------------------------------------
# DRR property tests: the three invariants
# ---------------------------------------------------------------------------


def _replay_stream(stream, weights, quantum=1.0):
    """Push a (tenant, pick_k) stream through a fresh DRR, returning the
    concatenated pick order and a per-pick invariant audit."""
    drr = DeficitRoundRobin(
        weight_of=lambda t: weights.get(t, 1.0), quantum=quantum
    )
    picks = []
    for op, value in stream:
        if op == "push":
            drr.push(value, f"{value}#{drr.staged}")
        else:
            staged_before = drr.staged
            out = drr.pick(value)
            # work conservation: a pick never leaves requests staged
            # while it has room (no idle slot with a non-empty queue)
            assert len(out) == min(value, staged_before)
            # bounded deficit: no tenant banks more than one visit's
            # earnings past a whole request
            for tenant in weights:
                assert drr.deficit(tenant) <= quantum * weights[tenant] + 1.0
                if drr.depth(tenant) == 0:
                    assert drr.deficit(tenant) == 0.0
            picks.extend(out)
    return picks


@settings(max_examples=40, deadline=None)
@given(
    ops=st.lists(
        st.one_of(
            st.builds(
                lambda t: ("push", t),
                t=st.sampled_from(("a", "b", "c")),
            ),
            st.builds(lambda k: ("pick", k), k=st.integers(0, 5)),
        ),
        min_size=1, max_size=60,
    ),
    wa=st.floats(0.25, 4.0),
    wb=st.floats(0.25, 4.0),
)
def test_drr_invariants_hold_on_random_streams(ops, wa, wb):
    weights = {"a": wa, "b": wb, "c": 1.0}
    first = _replay_stream(ops, weights)
    # deterministic admission order: the same stream picks identically
    # on a fresh scheduler (no randomness, no hash-order dependence)
    assert first == _replay_stream(ops, weights)


def test_drr_weight_proportional_shares():
    # both tenants backlogged: each round hands a floor(2x) what it
    # hands b — the weight-proportional share, exactly
    drr = DeficitRoundRobin(
        weight_of=lambda t: {"a": 2.0, "b": 1.0}[t]
    )
    for i in range(60):
        drr.push("a", f"a{i}")
        drr.push("b", f"b{i}")
    counts = {"a": 0, "b": 0}
    for _ in range(15):
        for tenant, _item in drr.pick(3):
            counts[tenant] += 1
    assert counts == {"a": 30, "b": 15}


def test_drr_weighted_shares_survive_small_picks():
    # the review regression: a pick truncated by k must RESUME spending
    # the banked deficit, not earn another round's quantum — otherwise
    # deficits grow without bound and 3:1 weights collapse to ~1:1
    # whenever the per-refill pick is smaller than a round's quantum
    # (e.g. --tenant-weights 3.0,1.0 with --batch-size 2)
    weights = {"a": 3.0, "b": 1.0}
    drr = DeficitRoundRobin(weight_of=weights.get)
    for i in range(150):
        drr.push("a", f"a{i}")
        drr.push("b", f"b{i}")
    counts = {"a": 0, "b": 0}
    for _ in range(50):
        for tenant, _item in drr.pick(2):
            counts[tenant] += 1
        for tenant, weight in weights.items():
            assert drr.deficit(tenant) <= weight + 1.0
    assert counts["a"] + counts["b"] == 100
    # weight-proportional within one round's slack
    assert 70 <= counts["a"] <= 80


def test_drr_flood_cannot_starve_victim():
    # the starvation bound in its smallest form: one tenant floods 100
    # requests, the victim stages a handful — EVERY pick that has room
    # for two still serves the victim while it has anything staged
    drr = DeficitRoundRobin()
    for i in range(100):
        drr.push("flood", f"f{i}")
    for i in range(6):
        drr.push("victim", f"v{i}")
    while drr.depth("victim"):
        picked = [t for t, _ in drr.pick(2)]
        assert "victim" in picked
    assert drr.staged > 80  # the flood is still mostly queued


def test_drr_small_picks_rotate_the_cursor():
    # pick(1) repeatedly must alternate equal-weight tenants, not pin
    # the first-seen one (the cursor rotation)
    drr = DeficitRoundRobin()
    for i in range(8):
        drr.push("a", f"a{i}")
        drr.push("b", f"b{i}")
    order = [drr.pick(1)[0][0] for _ in range(8)]
    assert order == ["a", "b", "a", "b", "a", "b", "a", "b"]


def test_drr_fifo_mode_is_global_arrival_order():
    drr = DeficitRoundRobin()
    arrivals = [("a", "a0"), ("b", "b0"), ("b", "b1"), ("a", "a1"),
                ("c", "c0")]
    for tenant, item in arrivals:
        drr.push(tenant, item)
    assert drr.pick(5, fair=False) == arrivals


def test_drr_emptied_queue_banks_nothing():
    # bounded deficit: a drained tenant re-arriving starts from 0
    # credit — absence never accumulates priority
    drr = DeficitRoundRobin(weight_of=lambda t: 8.0)
    drr.push("a", "a0")
    assert drr.pick(4) == [("a", "a0")]
    assert drr.deficit("a") == 0.0


# ---------------------------------------------------------------------------
# EDF-blended DRR: deadline jumps under a bounded urgency budget
# ---------------------------------------------------------------------------


def _edf_drr(window=1.0, budget=2.0, weights=None, quantum=1.0):
    weights = weights or {}
    return DeficitRoundRobin(
        weight_of=lambda t: weights.get(t, 1.0), quantum=quantum,
        urgency_window_s=window, urgency_budget=budget,
    )


def test_edf_jump_charges_deficit_and_respects_budget():
    # the worked example the module docstring promises, pinned: tenant
    # "slo" has 4 staged requests all near deadline; tenant "bulk" has
    # 6.  With budget 2, slo jumps exactly 2 requests ahead of fair
    # order, then falls back into the rotation to repay.
    drr = _edf_drr(window=1.0, budget=2.0)
    for i in range(6):
        drr.push("bulk", f"b{i}")
    for i in range(4):
        drr.push("slo", f"s{i}", deadline=100.0 + i)
    picked = [item for _, item in drr.pick(4, now=100.0)]
    # EDF phase: s0, s1 jump (deficit -> -2, the cap); fair rounds then
    # resume at the cursor: bulk earns 1.0 and pops b0; slo is in debt
    # (earns 1.0 -> -1.0, cannot pop); next round bulk pops b1
    assert picked == ["s0", "s1", "b0", "b1"]
    assert drr.deficit("slo") == pytest.approx(-1.0)  # repaying
    assert drr.deficit("bulk") == pytest.approx(0.0)
    assert drr.urgent_picks == 2


def test_edf_deadline_outside_window_does_not_jump():
    drr = _edf_drr(window=0.5, budget=2.0)
    drr.push("bulk", "b0")
    drr.push("slo", "s0", deadline=200.0)  # 100 s away: not urgent
    assert [i for _, i in drr.pick(2, now=100.0)] == ["b0", "s0"]
    assert drr.urgent_picks == 0


def test_edf_without_now_or_window_is_pure_drr():
    # pick(now=None) and window=0 both disarm the EDF phase even with
    # deadlines staged
    for drr in (_edf_drr(window=0.0), _edf_drr(window=5.0)):
        drr.push("bulk", "b0")
        drr.push("slo", "s0", deadline=100.0)
        now = None if drr.urgency_window_s else 100.0
        assert [i for _, i in drr.pick(2, now=now)] == ["b0", "s0"]
        assert drr.urgent_picks == 0


def test_edf_slo_free_stream_is_byte_identical_to_pure_drr():
    # the dormancy contract: an ARMED scheduler fed a deadline-free
    # stream picks exactly what the PR 10 scheduler picks
    rng = np.random.default_rng(61)
    ops = []
    for _ in range(120):
        if rng.random() < 0.6:
            ops.append(("push", rng.choice(["a", "b", "c"])))
        else:
            ops.append(("pick", int(rng.integers(0, 5))))
    plain = DeficitRoundRobin()
    armed = _edf_drr(window=2.0, budget=3.0)
    plain_picks, armed_picks = [], []
    for n, (op, value) in enumerate(ops):
        if op == "push":
            plain.push(value, n)
            armed.push(value, n)  # no deadline
        else:
            plain_picks += plain.pick(value)
            armed_picks += armed.pick(value, now=1000.0 + n)
    assert plain_picks == armed_picks
    assert armed.urgent_picks == 0


@settings(max_examples=30, deadline=None)
@given(
    ops=st.lists(
        st.one_of(
            st.builds(
                lambda t, d: ("push", t, d),
                t=st.sampled_from(("slo1", "slo2", "bulk")),
                d=st.floats(0.0, 3.0),
            ),
            st.builds(lambda k: ("pick", k, 0), k=st.integers(0, 5)),
        ),
        min_size=1, max_size=80,
    ),
    w1=st.floats(0.25, 4.0),
    budget=st.floats(0.0, 4.0),
)
def test_edf_combined_invariants_on_random_streams(ops, w1, budget):
    # the combined fairness+urgency invariant on random deadline
    # streams: deficits stay within [-budget, quantum*weight + 1],
    # work conservation holds with jumps in play, and the whole thing
    # is deterministic
    weights = {"slo1": w1, "slo2": 1.0, "bulk": 1.0}

    def run():
        drr = DeficitRoundRobin(
            weight_of=weights.get, quantum=1.0,
            urgency_window_s=1.0, urgency_budget=budget,
        )
        picks = []
        t = 100.0
        for op, value, extra in ops:
            t += 0.01
            if op == "push":
                # deadlines only for the slo tenants (bulk = no SLO)
                deadline = t + extra if value != "bulk" else None
                drr.push(value, f"{value}#{drr.staged}",
                         deadline=deadline)
            else:
                staged_before = drr.staged
                out = drr.pick(value, now=t)
                # work conservation survives deadline jumps
                assert len(out) == min(value, staged_before)
                for tenant, weight in weights.items():
                    d = drr.deficit(tenant)
                    assert d <= 1.0 * weight + 1.0 + 1e-9
                    assert d >= -budget - 1e-9
                picks.extend(out)
        return picks

    assert run() == run()


def test_edf_jump_never_starves_compliant_tenant():
    # a continuous stream of always-urgent requests cannot lock out a
    # compliant (no-SLO) backlogged tenant: the urgency budget bounds
    # the borrow, and the fair rounds keep serving the victim
    drr = _edf_drr(window=10.0, budget=2.0)
    for i in range(50):
        drr.push("bulk", f"b{i}")
    served_bulk = 0
    t = 0.0
    for round_ in range(30):
        # two fresh urgent requests arrive every pick
        drr.push("urgent", f"u{round_}a", deadline=t + 0.1)
        drr.push("urgent", f"u{round_}b", deadline=t + 0.1)
        picked = [tenant for tenant, _ in drr.pick(2, now=t)]
        served_bulk += picked.count("bulk")
        t += 1.0
    # bulk holds (close to) its fair half share despite every urgent
    # request being inside the window — the budget repayment math
    assert served_bulk >= 25


def test_refund_restores_urgency_credit_for_urgent_picks():
    # the review regression: a shed URGENT pick must give back the
    # urgency credit it spent, or a flood of expired/redelivered
    # copies strips an SLO tenant's jump budget permanently — while a
    # shed FAIR pick must not mint credit it never spent, even when
    # the SAME pick also contained an admitted urgent jump
    drr = _edf_drr(window=5.0, budget=1.0)
    drr.push("slo", "s0", deadline=100.0)
    drr.push("slo", "s1", deadline=101.0)
    (tenant, item), = drr.pick(1, now=100.0)
    assert tenant == "slo" and drr.urgent_picks == 1
    assert drr._credit["slo"] == pytest.approx(0.0)
    drr.refund("slo", item)
    assert drr._credit["slo"] == pytest.approx(1.0)  # jump re-armed
    # refunding the same item twice cannot mint a second credit
    drr._credit["slo"] = 0.0
    drr.refund("slo", item)
    assert drr._credit["slo"] == pytest.approx(0.0)
    # a mixed pick: the urgent jump is ADMITTED, the fair pick of the
    # SAME tenant is shed — the fair item's refund must not return the
    # credit the admitted jump legitimately spent (credit refunds are
    # attributed to the exact item, not a per-tenant count)
    drr2 = DeficitRoundRobin(
        keep=("slo",), urgency_window_s=5.0, urgency_budget=2.0,
    )
    drr2.push("slo", "u0", deadline=100.0)
    drr2.push("slo", "f0")  # no deadline: picked by the fair rounds
    picked = drr2.pick(2, now=100.0)
    assert [i for _, i in picked] == ["u0", "f0"]
    # pin a mid-stream credit level and freeze the lazy refill so the
    # assertions see refund() alone
    drr2._credit["slo"] = 0.5
    drr2._credit_round["slo"] = drr2._rounds
    drr2.refund("slo", picked[1][1])  # shed the FAIR item
    assert drr2._credit["slo"] == pytest.approx(0.5)  # untouched
    drr2.refund("slo", picked[0][1])  # shed the URGENT item
    assert drr2._credit["slo"] == pytest.approx(1.5)  # exactly one back


def test_refund_restores_charge_only_with_backlog():
    drr = DeficitRoundRobin(weight_of=lambda t: 2.0)
    for i in range(4):
        drr.push("a", f"a{i}")
    drr.pick(1)
    charged = drr.deficit("a")
    drr.refund("a")
    assert drr.deficit("a") == pytest.approx(charged + 1.0)
    # bounded: the refund returned exactly what the pick charged
    assert drr.deficit("a") <= 2.0 + 1.0
    # a drained tenant's refund is moot (deficit already reset)
    drr2 = DeficitRoundRobin()
    drr2.push("a", "a0")
    drr2.pick(1)
    drr2.refund("a")
    assert drr2.deficit("a") == 0.0


def test_pop_over_deadline_and_pop_tail():
    drr = _edf_drr()
    drr.push("a", "a0", deadline=10.0)
    drr.push("a", "a1", deadline=11.0)
    drr.push("b", "b0", deadline=5.0)
    drr.push("c", "c0")  # no deadline: never past due
    # most-over-SLO first (b0 at 5.0 beats a0 at 10.0)
    assert drr.pop_over_deadline(now=20.0) == ("b", "b0")
    # eligibility filter skips ineligible tenants
    assert drr.pop_over_deadline(now=20.0, eligible={"c"}) is None
    assert drr.pop_over_deadline(now=20.0) == ("a", "a0")
    assert drr.pop_over_deadline(now=9.0) is None  # nothing past due
    # pop_tail takes the NEWEST staged item
    drr.push("a", "a2", deadline=12.0)
    assert drr.pop_tail("a") == "a2"
    assert drr.pop_tail("missing") is None


# ---------------------------------------------------------------------------
# OverloadLadder: hysteretic tiers, smoothing, trace instants
# ---------------------------------------------------------------------------


def test_ladder_enters_highest_cleared_tier_and_exits_stepwise():
    from kube_sqs_autoscaler_tpu.workloads.tenancy import OverloadLadder

    ladder = OverloadLadder(3, smoothing=1.0)  # no smoothing: raw
    assert ladder.update(0.2) == 0
    assert ladder.update(0.95) == 3  # a cliff jumps straight to 3
    # hysteresis: inside the band (>= exit 0.75) tier 3 holds
    assert ladder.update(0.8) == 3
    # below tier 3's exit but above tier 2's (0.6): steps down ONE
    assert ladder.update(0.7) == 2
    assert ladder.update(0.1) == 0  # below every exit: all the way
    # 0->3, 3->2, 2->0: the full descent is one transition event
    assert ladder.transitions == 3
    assert ladder.entered_total[3] == 1


def test_ladder_tier_cap_and_validation():
    from kube_sqs_autoscaler_tpu.workloads.tenancy import OverloadLadder

    ladder = OverloadLadder(1, smoothing=1.0)
    assert ladder.update(1.0) == 1  # capped at tiers=1
    with pytest.raises(ValueError, match="tiers"):
        OverloadLadder(0)
    with pytest.raises(ValueError, match="tiers"):
        OverloadLadder(4)
    with pytest.raises(ValueError, match="smoothing"):
        OverloadLadder(2, smoothing=0.0)


def test_ladder_smoothing_rides_through_dips():
    from kube_sqs_autoscaler_tpu.workloads.tenancy import OverloadLadder

    ladder = OverloadLadder(3, smoothing=0.5)
    for _ in range(6):
        ladder.update(1.0)
    assert ladder.tier == 3
    # a one-cycle dip (shed just drained staging) must not exit
    ladder.update(0.55)
    assert ladder.tier == 3
    assert ladder.transitions == 1


def test_ladder_trace_instants_land_in_overload_category():
    from kube_sqs_autoscaler_tpu.workloads.tenancy import OverloadLadder

    ladder = OverloadLadder(3, smoothing=1.0)
    ladder.update(0.95, now=1.0)
    ladder.update(0.1, now=2.0)
    names = [e.name for e in ladder.events]
    assert names[0] == "overload-enter"
    assert "overload-exit" in names
    events = ladder.trace_events(time_origin=0.0)
    assert all(e["cat"] == "overload" and e["ph"] == "i"
               for e in events)
    assert events[0]["args"]["to"] == 3


def test_prefix_pool_evict_cold_reuses_slots_without_collision(
    model, params,
):
    # the slot-accounting regression: after evict_cold frees arbitrary
    # slots, installs must reuse THOSE slots — deriving the slot from
    # len(lru) would collide with a surviving entry's row (silent
    # cross-tenant KV sharing)
    pool = _pool(model, params, entries=3)
    keys = [prefix_pool_key("t", _prefix(i)) for i in range(4)]
    rows = [pool.acquire(0, keys[i], _prefix(i)) for i in range(3)]
    assert pool.evict_cold(keep=1) == 2  # keeps only keys[2] (MRU)
    assert pool.resident(0, keys[2])
    assert not pool.resident(0, keys[0])
    row3 = pool.acquire(0, keys[3], _prefix(3))
    # the new install landed in a FREED slot, never on keys[2]'s row
    assert row3 != rows[2]
    assert row3 in rows[:2]
    assert pool.acquire(0, keys[2], _prefix(2)) == rows[2]  # intact
    assert pool.evict_cold(keep=3) == 0  # idempotent at/below keep
    with pytest.raises(ValueError, match="keep"):
        pool.evict_cold(keep=-1)


# ---------------------------------------------------------------------------
# The offered-rate flood classifier
# ---------------------------------------------------------------------------


def test_over_share_classifies_sustained_flood_not_trickler():
    fair = FairAdmission(
        TenancyConfig(tenants=("victim",), ttft_slo_s=(0.5,)),
        per_tenant_limit=8, total_limit=64,
    )
    for cycle in range(12):
        fair.note_cycle()
        for i in range(4):  # flood: 4 new messages every cycle
            fair.stage("flood", f"f{cycle}:{i}",
                       message_id=f"mf{cycle}:{i}")
        if cycle % 3 == 0:  # victim: one every third cycle
            fair.stage("victim", f"v{cycle}", message_id=f"mv{cycle}")
        fair.drr.pick(4)  # drain so caps never interfere
    assert fair.over_share() == {"flood"}


def test_over_share_counts_unique_messages_once():
    # redeliveries of the SAME message are not offered load: a victim
    # whose backlog redelivers every cycle must not read as a flood
    fair = FairAdmission(
        TenancyConfig(tenants=("v",)), per_tenant_limit=2,
        total_limit=4,
    )
    for cycle in range(10):
        fair.note_cycle()
        for i in range(4):  # same four messages re-offered every cycle
            fair.stage("v", f"item{i}", message_id=f"m{i}")
    assert fair.arrival_rate.get("v", 0.0) < fair.OVER_SHARE_MIN_RATE


def test_over_share_counts_per_tenant_cap_hits():
    # a flooder saturating its own staging cap still classifies: the
    # cap-hit rejections carry the offered-load signal its throttled
    # stages cannot
    fair = FairAdmission(
        TenancyConfig(tenants=("victim",)), per_tenant_limit=2,
        total_limit=32,
    )
    n = 0
    for cycle in range(10):
        fair.note_cycle()
        for _ in range(5):
            fair.stage("flood", f"f{n}", message_id=f"m{n}")
            n += 1
        fair.stage("victim", f"v{cycle}", message_id=f"mv{cycle}")
        # nothing drains: flood pinned at its cap of 2
    assert fair.drr.depth("flood") == 2
    assert fair.over_share() == {"flood"}


def test_arrival_rate_decays_out_and_stays_bounded():
    fair = FairAdmission(
        TenancyConfig(tenants=("a",)), per_tenant_limit=4,
        total_limit=64,
    )
    for i in range(40):
        fair.stage(f"ghost{i}", i, message_id=f"g{i}")
    assert len(fair.arrival_rate) == 40
    for _ in range(20):
        fair.note_cycle()
    assert not fair.arrival_rate  # fully decayed out


# ---------------------------------------------------------------------------
# FairAdmission: bounded staging with hand-back overflow
# ---------------------------------------------------------------------------


def test_fair_admission_caps_and_overflow():
    fair = FairAdmission(
        TenancyConfig(tenants=("a", "b")),
        per_tenant_limit=2, total_limit=3,
    )
    assert fair.stage("a", 1) and fair.stage("a", 2)
    assert not fair.stage("a", 3)  # per-tenant cap: hand back
    assert fair.stage("b", 1)
    assert not fair.stage("b", 2)  # total cap
    # stage() itself never counts: overflow_total records messages the
    # WORKER actually handed back, not cap hits
    assert fair.overflow_total == 0
    assert fair.room == 0
    assert fair.depths() == {"a": 2, "b": 1}


def test_drr_prunes_drained_unknown_tenants():
    # unknown labels come from untrusted bodies: a drained unknown
    # tenant's scheduler entry is removed (bounded state under
    # adversarial unique labels), while configured tenants keep their
    # (empty) registration; a re-arrival re-registers cleanly
    drr = DeficitRoundRobin(keep=("a",))
    drr.push("a", "a0")
    for i in range(3):
        drr.push(f"evil{i}", i)
    assert drr.pick(4, fair=True)  # drains everything
    assert drr.depths() == {"a": 0}  # evil* pruned, a kept at 0
    drr.push("evil0", "again")
    assert drr.pick(1) == [("evil0", "again")]
    assert drr.depths() == {"a": 0}


def test_fair_admission_depths_include_idle_tenants():
    fair = FairAdmission(
        TenancyConfig(tenants=("a", "b")),
        per_tenant_limit=4, total_limit=8,
    )
    fair.stage("a", 1)
    # a tenant that never sent still gauges 0 (the Prometheus family
    # must not drop series when a tenant goes quiet)
    assert fair.depths() == {"a": 1, "b": 0}


def test_fair_admission_fifo_toggle_degrades_to_arrival_order():
    fair = FairAdmission(
        TenancyConfig(tenants=("a", "b"), fair=False),
        per_tenant_limit=8, total_limit=16,
    )
    for item, tenant in enumerate(("a", "b", "b", "a")):
        fair.stage(tenant, item)
    assert [item for _, item in fair.pick(4)] == [0, 1, 2, 3]


# ---------------------------------------------------------------------------
# The tenancy envelope parser and reply-side per-tenant accounting
# ---------------------------------------------------------------------------


def test_parse_tenant_request_envelope():
    tenant, prefix, ids = parse_tenant_request(
        json.dumps({"tenant": "acme", "prefix": [9, 8], "ids": [1, 2, 3]})
    )
    assert tenant == "acme"
    assert prefix.tolist() == [9, 8]
    assert ids.tolist() == [1, 2, 3]


def test_parse_tenant_request_plain_body_lands_on_default():
    # today's traffic (a bare JSON id list) parses unchanged onto the
    # default tenant — the single-default-tenant reference path
    tenant, prefix, ids = parse_tenant_request(
        json.dumps([4, 5, 6]), default_tenant="default"
    )
    assert tenant == "default" and prefix is None
    assert ids.tolist() == [4, 5, 6]


def test_parse_tenant_request_malformed_ids_is_a_drop():
    tenant, prefix, ids = parse_tenant_request(
        json.dumps({"tenant": "acme", "ids": ["not", "ints"]})
    )
    assert tenant == "acme" and ids is None


def test_parse_tenant_request_envelope_without_prefix():
    tenant, prefix, ids = parse_tenant_request(
        json.dumps({"tenant": "t", "ids": [7]})
    )
    assert (tenant, prefix) == ("t", None) and ids.tolist() == [7]


def test_tenant_completions_counts_deduped_replies_once():
    # the latent FIFO assumption fixed: completions count collect_replies
    # output (deduped by request id), never raw queue messages — a
    # redelivered reply copy contributes exactly one per-tenant count
    results = FakeMessageQueue()
    for _ in range(2):  # two replicas answered the same request
        results.send_message("t://r", json.dumps(
            {"request_id": "m-1", "tenant": "acme", "tokens": [1]}
        ))
    results.send_message("t://r", json.dumps(
        {"request_id": "m-2", "tokens": [2]}  # pre-tenancy reply
    ))
    results.send_message("t://r", json.dumps(
        # an answered TTL shed: labeled, but NOT a completion (the
        # worker-side completed_by_tenant excludes it too — the bench
        # gates the two counts equal)
        {"request_id": "m-3", "tenant": "acme", "error": "expired"}
    ))
    replies, duplicates = collect_replies(results, "t://r")
    assert duplicates == 1
    assert tenant_completions(replies) == {"acme": 1, "": 1}


# ---------------------------------------------------------------------------
# PrefixPool: LRU residency, one-time installs, trace instants
# ---------------------------------------------------------------------------


def _pool(model, params, *, entries=2, shards=1):
    return PrefixPool(params, model, entries=entries, prefix_len=PREFIX,
                      shards=shards)


def _prefix(seed):
    rng = np.random.default_rng(seed)
    return rng.integers(1, 64, PREFIX).astype(np.int32)


def test_prefix_pool_hit_skips_reinstall(model, params):
    pool = _pool(model, params)
    key = prefix_pool_key("a", _prefix(1))
    row = pool.acquire(0, key, _prefix(1))
    assert (pool.hits, pool.misses, pool.installs) == (0, 1, 1)
    assert pool.acquire(0, key, _prefix(1)) == row  # stable row
    assert (pool.hits, pool.misses, pool.installs) == (1, 1, 1)
    assert pool.resident(0, key)


def test_prefix_pool_lru_evicts_oldest(model, params):
    pool = _pool(model, params, entries=2)
    keys = [prefix_pool_key("a", _prefix(i)) for i in range(3)]
    pool.acquire(0, keys[0], _prefix(0))
    pool.acquire(0, keys[1], _prefix(1))
    pool.acquire(0, keys[0], _prefix(0))  # touch: k0 newest
    pool.acquire(0, keys[2], _prefix(2))  # evicts k1, not k0
    assert pool.evictions == 1
    assert pool.resident(0, keys[0]) and pool.resident(0, keys[2])
    assert not pool.resident(0, keys[1])


def test_prefix_pool_partitions_are_per_shard(model, params):
    pool = _pool(model, params, entries=1, shards=2)
    key = prefix_pool_key("a", _prefix(3))
    row0 = pool.acquire(0, key, _prefix(3))
    row1 = pool.acquire(1, key, _prefix(3))
    # same key, different shard = a separate residency (its HBM, its
    # LRU) in a distinct global row
    assert row0 != row1
    assert pool.installs == 2
    assert pool.stats()["resident"] == [1, 1]


def test_prefix_pool_keys_are_per_tenant():
    ids = _prefix(4)
    # byte-identical prefixes, different tenants: distinct entries —
    # residency is a per-tenant resource
    assert prefix_pool_key("a", ids) != prefix_pool_key("b", ids)
    assert prefix_pool_key("a", ids) == prefix_pool_key("a", ids.copy())


def test_prefix_pool_rejects_off_bucket_prefix(model, params):
    pool = _pool(model, params)
    key = prefix_pool_key("a", _prefix(5)[:3])
    with pytest.raises(ValueError, match="static"):
        pool.acquire(0, key, _prefix(5)[:3])


def test_prefix_pool_trace_instants(model, params):
    pool = _pool(model, params, entries=1)
    pool.acquire(0, prefix_pool_key("a", _prefix(6)), _prefix(6))
    pool.acquire(0, prefix_pool_key("b", _prefix(7)), _prefix(7))
    names = [e.name for e in pool.events]
    assert names == ["prefix-install", "prefix-evict", "prefix-install"]
    events = pool.trace_events(time_origin=0.0)
    # install/evict land in their own trace category, on the same
    # timeline shape as the fleet's supervisor instants
    assert all(e["cat"] == "prefix" and e["ph"] == "i" for e in events)
    assert events[1]["args"]["tenant"] == "a"  # the evictee


# ---------------------------------------------------------------------------
# Engine-level: fair refill, pool parity, dispatch accounting
# ---------------------------------------------------------------------------


def _send(queue, tenant, ids, prefix=None, url="t://q"):
    payload = {"tenant": tenant, "ids": np.asarray(ids).tolist()}
    if prefix is not None:
        payload["prefix"] = np.asarray(prefix).tolist()
    return queue.send_message(url, json.dumps(payload))


def _drain(worker, total, max_cycles=4000):
    cycles = 0
    while worker.processed < total:
        worker.run_once()
        cycles += 1
        assert cycles < max_cycles, "worker did not drain"


def test_single_default_tenant_is_reference_path(model, params):
    # tenancy off vs single-default-tenant tenancy on the same preloaded
    # queue: byte-identical outputs AND identical dispatch/transfer
    # counts — the seam costs nothing when it is not exercised
    rng = np.random.default_rng(11)
    bodies = [
        json.dumps(rng.integers(1, 64, int(n)).tolist())
        for n in rng.integers(2, PROMPT + 1, 5)
    ]
    runs = {}
    for label, tenancy in (
        ("off", None),
        ("default", TenancyConfig(tenants=("default",))),
    ):
        queue = FakeMessageQueue()
        results = FakeMessageQueue()
        sent = [queue.send_message("t://q", b) for b in bodies]
        worker = ContinuousWorker(
            queue, params, model, _config(), result_queue=results,
            tenancy=tenancy,
        )
        _drain(worker, len(bodies))
        replies, duplicates = collect_replies(results, "t://r")
        assert duplicates == 0
        runs[label] = (
            [replies[mid]["tokens"] for mid in sent],
            worker.batcher.insert_dispatches,
            worker.batcher.decode_dispatches,
            worker.batcher.host_transfers,
        )
    assert runs["off"] == runs["default"]


def test_fair_refill_is_work_conserving_and_single_insert(model, params):
    # a flooding tenant plus a trickle victim: every refill cycle that
    # admits anything issues exactly ONE insert dispatch (the DRR pick
    # is host bookkeeping), and no cycle leaves a slot idle while
    # requests are staged
    queue = FakeMessageQueue()
    results = FakeMessageQueue()
    tenancy = TenancyConfig(tenants=("victim", "flood"))
    worker = ContinuousWorker(
        queue, params, model, _config(), result_queue=results,
        tenancy=tenancy,
    )
    rng = np.random.default_rng(13)
    total = 0
    for i in range(8):
        _send(queue, "flood", rng.integers(1, 64, 3))
        total += 1
    for i in range(2):
        _send(queue, "victim", rng.integers(1, 64, 3))
        total += 1
    cycles = 0
    while worker.processed < total:
        before = worker.batcher.insert_dispatches
        worker._refill()
        # one [M, P] insert per refill, whatever the tenant mix (the
        # DRR pick is host bookkeeping, never a device dispatch)
        assert worker.batcher.insert_dispatches - before <= 1
        if worker._fair.staged:
            # work conservation at the engine: staged requests while a
            # slot sits free means the pick under-served
            assert not worker.batcher.free_slots
        worker.run_once()
        cycles += 1
        assert cycles < 4000
    replies, duplicates = collect_replies(results, "t://r")
    assert len(replies) == total and duplicates == 0
    assert worker.completed_by_tenant == {"flood": 8, "victim": 2}
    assert tenant_completions(replies) == {"flood": 8, "victim": 2}


def test_pooled_admission_matches_prefix_prepended_reference(model, params):
    # the cache-hit claim, gated at byte level: pooled decode (prefix KV
    # gathered from the pool) == the plain engine decoding the
    # prefix-PREPENDED prompt, while hits really skip the install
    rng = np.random.default_rng(17)
    prefixes = {t: rng.integers(1, 64, PREFIX) for t in ("a", "b")}
    sends = [("a", rng.integers(1, 64, PROMPT)) for _ in range(3)]
    sends += [("b", rng.integers(1, 64, PROMPT)) for _ in range(3)]

    queue = FakeMessageQueue()
    results = FakeMessageQueue()
    tenancy = TenancyConfig(tenants=("a", "b"), prefix_pool=2,
                            prefix_len=PREFIX)
    worker = ContinuousWorker(
        queue, params, model, _config(), result_queue=results,
        tenancy=tenancy,
    )
    sent = [
        _send(queue, tenant, ids, prefix=prefixes[tenant])
        for tenant, ids in sends
    ]
    _drain(worker, len(sends))
    replies, _ = collect_replies(results, "t://r")
    pooled = [replies[mid]["tokens"] for mid in sent]
    pool = worker.batcher.prefix_pool
    assert pool.installs == 2  # one per tenant, ever
    assert pool.hits == 4  # every reuse skipped the prefix prefill

    ref_queue = FakeMessageQueue()
    ref_results = FakeMessageQueue()
    ref = ContinuousWorker(
        ref_queue, params, model,
        _config(seq_len=PREFIX + PROMPT), result_queue=ref_results,
    )
    ref_sent = [
        ref_queue.send_message("t://q", json.dumps(
            np.concatenate([prefixes[tenant], ids]).tolist()
        ))
        for tenant, ids in sends
    ]
    _drain(ref, len(sends))
    ref_replies, _ = collect_replies(ref_results, "t://r")
    assert pooled == [ref_replies[mid]["tokens"] for mid in ref_sent]


def test_off_bucket_prefix_falls_back_to_prepend(model, params):
    # a prefix that does not fit the pool's static bucket still decodes
    # correctly (prepended, uncached) — the pool is an optimization,
    # never a correctness gate
    rng = np.random.default_rng(19)
    short_prefix = rng.integers(1, 64, 2)  # off the static PREFIX bucket
    ids = rng.integers(1, 64, PROMPT - 2)  # prepended they fill the bucket
    queue = FakeMessageQueue()
    results = FakeMessageQueue()
    tenancy = TenancyConfig(tenants=("a",), prefix_pool=2,
                            prefix_len=PREFIX)
    worker = ContinuousWorker(
        queue, params, model, _config(), result_queue=results,
        tenancy=tenancy,
    )
    mid = _send(queue, "a", ids, prefix=short_prefix)
    _drain(worker, 1)
    assert worker.batcher.prefix_pool.installs == 0  # never touched
    replies, _ = collect_replies(results, "t://r")

    ref_queue = FakeMessageQueue()
    ref_results = FakeMessageQueue()
    ref = ContinuousWorker(
        ref_queue, params, model, _config(), result_queue=ref_results,
    )
    ref_mid = ref_queue.send_message("t://q", json.dumps(
        np.concatenate([short_prefix, ids]).tolist()
    ))
    _drain(ref, 1)
    ref_replies, _ = collect_replies(ref_results, "t://r")
    assert replies[mid]["tokens"] == ref_replies[ref_mid]["tokens"]


def test_oversize_prefix_is_shed_with_error_not_truncated(model, params):
    # a prepended prefix+prompt that exceeds the prompt bucket must be
    # answered with an explicit error — _pad_prompt would otherwise
    # silently truncate away the user's actual prompt
    rng = np.random.default_rng(47)
    queue = FakeMessageQueue()
    results = FakeMessageQueue()
    worker = ContinuousWorker(
        queue, params, model, _config(), result_queue=results,
        tenancy=TenancyConfig(tenants=("a",)),  # pool off: prepend path
    )
    mid = _send(queue, "a", rng.integers(1, 64, PROMPT),
                prefix=rng.integers(1, 64, PREFIX))  # PREFIX+PROMPT > bucket
    worker.run_once()  # shed at admission: the error reply is immediate
    replies, _ = collect_replies(results, "t://r")
    assert "prompt bucket" in replies[mid]["error"]
    assert worker.completed_by_tenant == {}  # an error is not a completion
    from kube_sqs_autoscaler_tpu.workloads.service import (
        tenant_completions as tc,
    )
    assert tc(replies) == {}


def test_tenancy_rejects_non_plain_paths(model, params):
    with pytest.raises(ValueError, match="plain continuous decode"):
        ContinuousBatcher(
            params, model, batch_size=BATCH, prompt_len=PROMPT,
            generate_tokens=TOKENS, beams=2,
            tenancy=TenancyConfig(tenants=("a",)),
        )
    from kube_sqs_autoscaler_tpu.workloads.decode import prefill_prefix

    broadcast = prefill_prefix(
        params, np.arange(1, PREFIX + 1, dtype=np.int32), model
    )
    with pytest.raises(ValueError, match="mutually exclusive"):
        ContinuousBatcher(
            params, model, batch_size=BATCH, prompt_len=PROMPT,
            generate_tokens=TOKENS, prefix_cache=broadcast,
            tenancy=TenancyConfig(tenants=("a",), prefix_pool=BATCH,
                                  prefix_len=PREFIX),
        )


def test_pool_smaller_than_slots_is_rejected(model, params):
    # one admission batch can hold shard_slots distinct prefixes: a
    # pool smaller than that could LRU-evict an entry another row of
    # the SAME batched insert still references — silent cross-tenant
    # KV corruption, so it is a construction-time error
    with pytest.raises(ValueError, match="per-shard slot count"):
        ContinuousBatcher(
            params, model, batch_size=2, prompt_len=PROMPT,
            generate_tokens=TOKENS,
            tenancy=TenancyConfig(tenants=("a", "b"), prefix_pool=1,
                                  prefix_len=PREFIX),
        )


def test_overflow_counts_only_actual_handbacks(model, params):
    # a tenant flooding past its staging cap: the overflow messages are
    # handed back to the queue (visible again immediately) and ONLY
    # those hand-backs count in overflow_total
    queue = FakeMessageQueue()
    worker = ContinuousWorker(
        queue, params, model, _config(result_queue_url=""),
        tenancy=TenancyConfig(tenants=("a",)),
    )
    rng = np.random.default_rng(43)
    for _ in range(5):
        _send(queue, "a", rng.integers(1, 64, 3))
    worker._refill()  # room 4: stages 2 (cap), hands 2 back, 1 unseen
    assert worker._fair.overflow_total == 2
    attrs = queue.get_queue_attributes("t://q", ())
    # 2 handed back + 1 never received are visible again
    assert attrs["ApproximateNumberOfMessages"] == "3"


def test_tenant_attribution_cardinality_is_bounded():
    from kube_sqs_autoscaler_tpu.workloads.continuous import (
        MAX_TENANT_SERIES,
        OTHER_TENANTS,
        _bounded_tenant_key,
    )

    table = {f"t{i}": i for i in range(MAX_TENANT_SERIES)}
    assert _bounded_tenant_key("t3", table) == "t3"  # existing rows keep
    assert _bounded_tenant_key("fresh", table) == OTHER_TENANTS
    table[OTHER_TENANTS] = 0
    assert _bounded_tenant_key("another", table) == OTHER_TENANTS


def test_take_inflight_evacuates_staged_messages(model, params):
    # fair-admission staging holds messages with live receipt handles:
    # when a replica dies, they must fail over with its busy slots
    # instead of stranding until the visibility timeout
    from kube_sqs_autoscaler_tpu.fleet.worker import FleetWorker

    queue = FakeMessageQueue()
    worker = FleetWorker(
        queue, params, model, _config(result_queue_url=""),
        tenancy=TenancyConfig(tenants=("a", "b")),
    )
    rng = np.random.default_rng(41)
    for tenant in ("a", "a", "b", "b"):
        _send(queue, tenant, rng.integers(1, 64, 3))
    worker.run_once()  # 2 admitted (batch), 2 staged
    assert worker.batcher.active == 2 and worker.staged == 2
    messages = worker.take_inflight()
    assert len(messages) == 4
    assert worker.staged == 0 and worker.batcher.active == 0
    assert all("ReceiptHandle" in m for m in messages)


# ---------------------------------------------------------------------------
# Sticky routing on the sharded plane
# ---------------------------------------------------------------------------


def _sharded_worker(model, params, *, sticky, shards=2,
                    sticky_imbalance=0):
    tenancy = TenancyConfig(
        tenants=("a", "b"), prefix_pool=2, prefix_len=PREFIX,
        sticky=sticky, sticky_imbalance=sticky_imbalance,
    )
    return ContinuousWorker(
        FakeMessageQueue(), params, model,
        _config(shards=shards, result_queue_url=""),
        tenancy=tenancy, sharded=True,
    )


def test_sticky_routing_keeps_tenant_on_home_shard(model, params):
    worker = _sharded_worker(model, params, sticky=True)
    batcher = worker.batcher
    rng = np.random.default_rng(23)
    prefix = rng.integers(1, 64, PREFIX)
    req = lambda: ("a", prefix, rng.integers(1, 64, PROMPT), {})
    (r1,) = batcher.submit_many_prefixed([req()])
    assert r1 // BATCH == 0  # freest tie-break: lowest shard
    # shard 1 is now freest (2 free vs 1) — but home wins under the
    # auto threshold (yield only when home is full)
    (r2,) = batcher.submit_many_prefixed([req()])
    assert r2 // BATCH == 0
    # home full: stickiness yields, the spill lands on the freest —
    # and the home assignment does NOT move
    (r3,) = batcher.submit_many_prefixed([req()])
    assert r3 // BATCH == 1
    pool = batcher.prefix_pool
    assert pool.installs == 2  # home install + one spill install
    assert pool.hits == 1  # r2 reused the home entry


def test_freest_routing_scatters_and_reinstalls(model, params):
    worker = _sharded_worker(model, params, sticky=False)
    batcher = worker.batcher
    rng = np.random.default_rng(29)
    prefix = rng.integers(1, 64, PREFIX)
    req = lambda: ("a", prefix, rng.integers(1, 64, PROMPT), {})
    (r1,) = batcher.submit_many_prefixed([req()])
    (r2,) = batcher.submit_many_prefixed([req()])
    # freest-first scatters the same tenant across shards, paying a
    # second install for the same prefix — the locality cost sticky
    # routing exists to avoid
    assert {r1 // BATCH, r2 // BATCH} == {0, 1}
    assert batcher.prefix_pool.installs == 2
    assert batcher.prefix_pool.hits == 0


def test_sticky_imbalance_threshold_controls_yield(model, params):
    # threshold 1: the moment the freest shard leads home by one free
    # slot, stickiness yields (even though home still has room)
    worker = _sharded_worker(model, params, sticky=True,
                             sticky_imbalance=1)
    batcher = worker.batcher
    rng = np.random.default_rng(31)
    prefix = rng.integers(1, 64, PREFIX)
    req = lambda: ("a", prefix, rng.integers(1, 64, PROMPT), {})
    (r1,) = batcher.submit_many_prefixed([req()])
    assert r1 // BATCH == 0
    (r2,) = batcher.submit_many_prefixed([req()])
    assert r2 // BATCH == 1  # 2 free vs 1: lead >= 1, yield


# ---------------------------------------------------------------------------
# Per-tenant observability
# ---------------------------------------------------------------------------


def test_tenant_gauges_and_prefix_counters_render(model, params):
    from kube_sqs_autoscaler_tpu.obs import WorkloadMetrics

    queue = FakeMessageQueue()
    results = FakeMessageQueue()
    tenancy = TenancyConfig(tenants=("a", "b"), prefix_pool=2,
                            prefix_len=PREFIX)
    worker = ContinuousWorker(
        queue, params, model, _config(), result_queue=results,
        tenancy=tenancy,
    )
    metrics = WorkloadMetrics()
    worker.attach_metrics(metrics)
    rng = np.random.default_rng(37)
    prefix = rng.integers(1, 64, PREFIX)
    for _ in range(2):
        _send(queue, "a", rng.integers(1, 64, PROMPT), prefix=prefix)
    _drain(worker, 2)
    text = metrics.render()
    prefix = "kube_sqs_autoscaler_workload"
    assert f'{prefix}_tenant_tokens_per_second{{tenant="a"}}' in text
    assert f'{prefix}_tenant_queue_depth{{tenant="a"}}' in text
    assert f'{prefix}_tenant_ttft_seconds{{tenant="a"}}' in text
    # configured-but-quiet tenants keep a 0 series (no vanishing labels)
    assert f'{prefix}_tenant_queue_depth{{tenant="b"}} 0.0' in text
    assert f"# TYPE {prefix}_prefix_cache_hits_total counter" in text
    assert f"{prefix}_prefix_cache_hits_total 1.0" in text
    assert f"{prefix}_prefix_cache_misses_total 1.0" in text


def test_unknown_tenant_gauge_series_is_bounded_and_resets(model, params):
    # raw staged labels pass through the bounded persistent registry
    # before minting Prometheus series (set_gauge keeps every labeled
    # row forever), and every registered label re-exports each cycle —
    # so a drained-and-pruned unknown tenant's depth reads 0, never a
    # stale last value
    from kube_sqs_autoscaler_tpu.obs import WorkloadMetrics

    queue = FakeMessageQueue()
    results = FakeMessageQueue()
    worker = ContinuousWorker(
        queue, params, model, _config(), result_queue=results,
        tenancy=TenancyConfig(tenants=("a",)),
    )
    metrics = WorkloadMetrics()
    worker.attach_metrics(metrics)
    rng = np.random.default_rng(53)
    _send(queue, "ghost", rng.integers(1, 64, 3))  # unregistered tenant
    _drain(worker, 1)
    text = metrics.render()
    prefix = "kube_sqs_autoscaler_workload"
    # drained + pruned from the DRR, but the series reads 0 — exported
    # from the persistent registry, not from the pruned depths map
    assert f'{prefix}_tenant_queue_depth{{tenant="ghost"}} 0.0' in text
    assert f'{prefix}_tenant_tokens_per_second{{tenant="ghost"}}' in text
    assert set(worker._gauge_tenants) >= {"a", "ghost"}


def test_build_info_stamps_tenancy_labels():
    from kube_sqs_autoscaler_tpu.obs import WorkloadMetrics

    metrics = WorkloadMetrics()
    metrics.set_build_info("1.2.3", tenants="a,b", prefix_pool=4)
    text = metrics.render()
    assert 'build_info{version="1.2.3"' in text
    assert 'prefix_pool="4"' in text and 'tenants="a,b"' in text


# ---------------------------------------------------------------------------
# CLI: usage errors at startup, journal meta stamps the tenancy knobs
# ---------------------------------------------------------------------------


def test_tenant_flag_rejections():
    from kube_sqs_autoscaler_tpu.workloads.__main__ import main as worker_main

    with pytest.raises(SystemExit, match="--continuous"):
        worker_main(["--demo", "1", "--generate-tokens", "2",
                     "--tenants", "a,b"])
    with pytest.raises(SystemExit, match="plain continuous decode"):
        worker_main(["--demo", "1", "--continuous", "--generate-tokens",
                     "2", "--tenants", "a", "--beams", "2"])
    with pytest.raises(SystemExit, match="counts must match"):
        worker_main(["--demo", "1", "--continuous", "--generate-tokens",
                     "2", "--tenants", "a,b", "--tenant-weights", "1.0"])
    with pytest.raises(SystemExit, match="0.01"):
        worker_main(["--demo", "1", "--continuous", "--generate-tokens",
                     "2", "--tenants", "a", "--tenant-weights", "-1"])
    with pytest.raises(SystemExit, match="requires --tenants"):
        worker_main(["--demo", "1", "--continuous", "--generate-tokens",
                     "2", "--tenant-weights", "1.0"])
    with pytest.raises(SystemExit, match="requires --tenants"):
        worker_main(["--demo", "1", "--continuous", "--generate-tokens",
                     "2", "--prefix-pool", "2"])
    with pytest.raises(SystemExit, match="mutually exclusive"):
        worker_main(["--demo", "1", "--continuous", "--generate-tokens",
                     "2", "--tenants", "a", "--prefix-pool", "2",
                     "--prefix-ids", "1,2"])
    with pytest.raises(SystemExit, match="batch-size"):
        worker_main(["--demo", "1", "--continuous", "--generate-tokens",
                     "2", "--tenants", "a", "--prefix-pool", "2",
                     "--batch-size", "4"])
    with pytest.raises(SystemExit, match="--fleet-max-replicas"):
        worker_main(["--demo", "1", "--continuous", "--generate-tokens",
                     "2", "--journal-path", "/tmp/never-written.jsonl"])


@pytest.mark.slow
def test_worker_binary_tenants_demo():
    # the tenancy refill path end to end through the binary: demo
    # bodies are plain id lists, so they land on the default tenant —
    # the reference-path envelope
    from kube_sqs_autoscaler_tpu.workloads.__main__ import main as worker_main

    worker_main(["--demo", "4", "--continuous", "--batch-size", "2",
                 "--seq-len", "12", "--generate-tokens", "3",
                 "--tenants", "default,premium",
                 "--tenant-weights", "1.0,3.0"])


@pytest.mark.slow
def test_worker_binary_prefix_pool_composes_with_model_parallel():
    # the PR 18 lift: the pooled prefix cache on a tensor-parallel
    # mesh through the binary (previously a SystemExit; divisibility
    # is validated at batcher construction instead).  conftest forks 8
    # host devices, so the mesh is real.
    from kube_sqs_autoscaler_tpu.workloads.__main__ import main as worker_main

    worker_main(["--demo", "4", "--continuous", "--batch-size", "4",
                 "--seq-len", "12", "--generate-tokens", "3",
                 "--model-parallel", "2",
                 "--tenants", "a,b", "--prefix-pool", "4"])


@pytest.mark.slow
def test_fleet_demo_journal_stamps_tenancy_meta(tmp_path):
    from kube_sqs_autoscaler_tpu.workloads.__main__ import main as worker_main

    journal = tmp_path / "fleet.jsonl"
    worker_main(["--demo", "4", "--continuous", "--batch-size", "2",
                 "--seq-len", "12", "--generate-tokens", "3",
                 "--fleet-max-replicas", "2",
                 "--tenants", "a,b", "--tenant-weights", "2.0,1.0",
                 "--journal-path", str(journal)])
    lines = journal.read_text().strip().splitlines()
    header = json.loads(lines[0])
    meta = header["meta"]
    assert meta["source"] == "serving-fleet"
    assert meta["tenancy"]["tenants"] == ["a", "b"]
    assert meta["tenancy"]["weights"] == [2.0, 1.0]
    assert meta["tenancy"]["fair"] is True
    assert len(lines) > 1  # ticks followed the header


# ---------------------------------------------------------------------------
# The tenants bench: tier-1 smoke (timing gates off), full battery slow
# ---------------------------------------------------------------------------


def _run_tenants(tmp_path, **kwargs):
    import bench

    out = tmp_path / "BENCH_tenants.json"
    summary = bench.run_tenants_suite(output=str(out), **kwargs)
    return summary, json.loads(out.read_text())


def test_tenants_bench_smoke(tmp_path):
    # small flood + prefix-share episodes with the wall-clock gates off:
    # every deterministic gate (exactly-once, DRR==FIFO outputs, pooled
    # parity vs the prefix-prepended reference, strictly-fewer sticky
    # installs, tenancy-off byte-identity) still gates hard
    summary, artifact = _run_tenants(
        tmp_path,
        prompt_len=4, prefix_len=6, generate_tokens=6, batch_size=2,
        shards=2, decode_block=2, pool_entries=2,
        flood_per_cycle=3, flood_cycles=4, victims=1,
        sticky_tenants=3, sticky_cycles=8,
        timing_gates=False, timed_repeats=1,
    )
    assert summary["metric"] == "tenants_sticky_tokens_per_sec"
    assert artifact["suite"] == "tenants"
    flood = artifact["flood"]
    for mode in ("drr", "fifo", "control"):
        assert flood[mode]["answered"] == flood[mode]["requests"]
        assert flood[mode]["duplicates"] == 0
    sticky = artifact["sticky"]
    assert sticky["sticky"]["prefix_installs"] < \
        sticky["freest"]["prefix_installs"]
    off = artifact["off_parity"]
    assert off["off"]["insert_dispatches"] == \
        off["single-default"]["insert_dispatches"]


@pytest.mark.slow
def test_tenants_bench_full_battery(tmp_path):
    summary, artifact = _run_tenants(tmp_path)
    assert summary["vs_baseline"] > 1.0  # sticky beats freest-first
    for victim, row in artifact["flood"]["isolation"].items():
        assert row["ttft_p99_flood_s"] <= row["bound_s"]
