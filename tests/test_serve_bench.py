"""Serving hot-path gates (tier-1 smoke + slow full bench).

The smoke run pins the serve suite's whole contract on a tiny config —
blocked engine vs single-step engine on the same seeded queue, gated on
byte-identical greedy outputs (``min_speedup=0`` keeps the throughput
gate out of the fast tier, where a loaded CI host would make it flaky) —
plus the serving gauges the worker publishes.  The full decode-bound
bench (the committed ``BENCH_r10.json`` numbers, >= 1.3x gate) runs in
the slow tier.
"""

import json

import pytest

from bench import run_serve_suite


def test_serve_suite_smoke_parity_block4(tmp_path):
    out = tmp_path / "bench_serve.json"
    headline = run_serve_suite(
        str(out), messages=6, prompt_len=8, generate_tokens=8,
        batch_size=2, decode_block=4, min_speedup=0.0,
    )
    artifact = json.loads(out.read_text())
    assert artifact["parity"]["divergences"] == 0
    assert artifact["parity"]["requests"] == 6
    # every request generated its full budget on both engines
    assert artifact["single_step"]["tokens"] == 6 * 8
    assert artifact["blocked"]["tokens"] == 6 * 8
    assert 0.0 < artifact["blocked"]["block_utilization"] <= 1.0
    assert artifact["single_step"]["block_utilization"] is None
    assert "0 parity divergences" in headline["unit"]


@pytest.mark.slow
def test_serve_suite_full_gate(tmp_path):
    # the committed-artifact configuration: decode-bound model, >=1.3x
    # throughput gate AND exact greedy parity (SystemExit(2) otherwise)
    out = tmp_path / "bench_r10.json"
    headline = run_serve_suite(str(out))
    artifact = json.loads(out.read_text())
    assert artifact["speedup"] >= 1.3
    assert artifact["parity"]["divergences"] == 0
    assert headline["vs_baseline"] >= 1.3


def test_continuous_worker_serving_gauges(tmp_path):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from kube_sqs_autoscaler_tpu.metrics.fake import FakeMessageQueue
    from kube_sqs_autoscaler_tpu.obs import WorkloadMetrics
    from kube_sqs_autoscaler_tpu.workloads.continuous import ContinuousWorker
    from kube_sqs_autoscaler_tpu.workloads.model import (
        ModelConfig,
        init_params,
    )
    from kube_sqs_autoscaler_tpu.workloads.service import ServiceConfig

    config = ModelConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
        max_seq_len=32, dtype=jnp.float32,
    )
    params = init_params(jax.random.key(0), config)
    queue = FakeMessageQueue()
    rng = np.random.default_rng(3)
    for _ in range(3):
        queue.send_message(
            "fake://jobs", json.dumps(rng.integers(1, 64, 5).tolist())
        )
    worker = ContinuousWorker(
        queue, params, config,
        ServiceConfig(queue_url="fake://jobs", batch_size=2, seq_len=8,
                      generate_tokens=4, decode_block=2),
    )
    metrics = WorkloadMetrics()
    worker.attach_metrics(metrics)
    assert worker.drain(total=3, max_cycles=200) == 3
    text = metrics.render()
    prefix = "kube_sqs_autoscaler_workload"
    for name in ("tokens_per_second", "time_to_first_token_seconds",
                 "active_slots", "decode_block_utilization"):
        assert f"# TYPE {prefix}_{name} gauge" in text, name
    # 3 requests x 4 tokens drained: throughput and TTFT are live numbers
    gauges = {
        line.split(" ")[0]: float(line.split(" ")[1])
        for line in text.splitlines()
        if line.startswith(prefix) and " " in line and "{" not in line
    }
    assert gauges[f"{prefix}_tokens_per_second"] > 0
    assert gauges[f"{prefix}_time_to_first_token_seconds"] > 0
    assert gauges[f"{prefix}_active_slots"] == 0  # drained
    assert 0 < gauges[f"{prefix}_decode_block_utilization"] <= 1


def test_decode_block_flag_rejections():
    from kube_sqs_autoscaler_tpu.workloads.__main__ import main as worker_main

    with pytest.raises(SystemExit, match="--continuous"):
        worker_main(["--demo", "1", "--generate-tokens", "2",
                     "--decode-block", "4"])
    with pytest.raises(SystemExit, match="plain continuous decode"):
        worker_main(["--demo", "1", "--continuous", "--generate-tokens",
                     "2", "--decode-block", "4", "--beams", "2"])
    with pytest.raises(SystemExit, match="must be >= 1"):
        worker_main(["--demo", "1", "--continuous", "--generate-tokens",
                     "2", "--decode-block", "0"])


def test_service_config_rejects_bad_decode_block():
    from kube_sqs_autoscaler_tpu.workloads.service import ServiceConfig

    with pytest.raises(ValueError, match="decode_block"):
        ServiceConfig(queue_url="fake://x", decode_block=0)


def test_batcher_rejects_decode_block_combos():
    import jax
    import jax.numpy as jnp

    from kube_sqs_autoscaler_tpu.workloads.continuous import (
        ContinuousBatcher,
    )
    from kube_sqs_autoscaler_tpu.workloads.model import (
        ModelConfig,
        init_params,
    )

    config = ModelConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
        max_seq_len=32, dtype=jnp.float32,
    )
    params = init_params(jax.random.key(0), config)
    with pytest.raises(ValueError, match="decode_block"):
        ContinuousBatcher(params, config, batch_size=2, prompt_len=8,
                          generate_tokens=4, decode_block=0)
    with pytest.raises(ValueError, match="plain decode path"):
        ContinuousBatcher(params, config, batch_size=2, prompt_len=8,
                          generate_tokens=4, decode_block=4, beams=2)
    with pytest.raises(ValueError, match="plain decode path"):
        ContinuousBatcher(params, config, batch_size=2, prompt_len=8,
                          generate_tokens=4, decode_block=4,
                          draft_layers=1)
