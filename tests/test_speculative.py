"""Speculative decoding: the draft-and-verify loop must be an exact
greedy decoder — same tokens as decode.generate for ANY draft model
(speculative.py module docstring) — and chunk_decode must equal the
sequential decode steps it batches."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kube_sqs_autoscaler_tpu.workloads.decode import (
    chunk_decode,
    decode_step,
    generate,
    prefill,
)
from kube_sqs_autoscaler_tpu.workloads.model import ModelConfig, init_params
from kube_sqs_autoscaler_tpu.workloads.speculative import (
    speculative_generate,
    speculative_generate_jit,
)

TARGET = ModelConfig(
    vocab_size=128, d_model=64, n_heads=4, n_layers=2, d_ff=128,
    max_seq_len=96,
)
DRAFT = ModelConfig(
    vocab_size=128, d_model=32, n_heads=2, n_layers=1, d_ff=64,
    max_seq_len=96,
)


@pytest.fixture(scope="module")
def models():
    return (
        init_params(jax.random.key(0), TARGET),
        init_params(jax.random.key(9), DRAFT),
    )


def prompt_tokens(batch=3, length=6, seed=1):
    return jax.random.randint(
        jax.random.key(seed), (batch, length), 0, TARGET.vocab_size,
        jnp.int32,
    )


def test_chunk_decode_equals_sequential_steps(models):
    params, _ = models
    prompt = prompt_tokens(batch=2, length=5)
    lengths = jnp.asarray([3, 5], jnp.int32)  # ragged
    _, cache_a = prefill(params, prompt, TARGET, lengths=lengths)
    _, cache_b = prefill(params, prompt, TARGET, lengths=lengths)

    chunk = jax.random.randint(jax.random.key(2), (2, 4), 0,
                               TARGET.vocab_size, jnp.int32)
    step_logits = []
    for t in range(4):
        logits, cache_a = decode_step(params, cache_a, chunk[:, t], TARGET)
        step_logits.append(logits)
    got, cache_b = chunk_decode(params, cache_b, chunk, TARGET)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(jnp.stack(step_logits, axis=1)),
        rtol=2e-4, atol=2e-4,
    )
    np.testing.assert_array_equal(
        np.asarray(cache_a["length"]), np.asarray(cache_b["length"])
    )


@pytest.mark.parametrize("k", [1, 3, 5])
def test_speculative_equals_greedy_for_independent_draft(models, k):
    params_t, params_d = models
    prompt = prompt_tokens()
    ref = np.asarray(generate(params_t, prompt, 12, TARGET))
    got = np.asarray(
        speculative_generate(params_t, TARGET, params_d, DRAFT, prompt, 12,
                             draft_tokens=k)
    )
    np.testing.assert_array_equal(got, ref)


def test_speculative_with_self_draft_fully_accepts(models):
    # draft == target: every round accepts all k proposals, and the output
    # is still exactly the greedy sequence
    params_t, _ = models
    prompt = prompt_tokens(seed=4)
    ref = np.asarray(generate(params_t, prompt, 12, TARGET))
    got = np.asarray(
        speculative_generate(params_t, TARGET, params_t, TARGET, prompt, 12,
                             draft_tokens=4)
    )
    np.testing.assert_array_equal(got, ref)


def test_speculative_eos_equals_greedy_generate_with_eos(models):
    # the eos contract rides the speculative loop: identical to plain
    # greedy generate with the same eos, padding included
    params_t, params_d = models
    prompt = prompt_tokens(seed=6)
    plain = np.asarray(generate(params_t, prompt, 12, TARGET))
    eos = int(plain[0, 2])  # fires early for row 0 by construction
    ref = np.asarray(generate(params_t, prompt, 12, TARGET, eos_id=eos))
    got = np.asarray(
        speculative_generate(params_t, TARGET, params_d, DRAFT, prompt, 12,
                             draft_tokens=3, eos_id=eos)
    )
    np.testing.assert_array_equal(got, ref)


def test_speculative_eos_freezes_rows_early(models):
    # a row whose eos fires at its FIRST token must stop costing rounds:
    # with draft == target (full acceptance) and eos = row 0's first
    # token, row 0's round count stays at the minimum while other rows
    # keep going
    params_t, _ = models
    prompt = prompt_tokens(seed=7)
    plain = np.asarray(generate(params_t, prompt, 16, TARGET))
    eos = int(plain[0, 0])
    _, stats = speculative_generate(
        params_t, TARGET, params_t, TARGET, prompt, 16,
        draft_tokens=2, eos_id=eos, return_stats=True,
    )
    rounds = np.asarray(stats["rounds"])
    # row 0 froze before its first round (pending == eos at loop entry)
    assert rounds[0] == 0
    assert rounds[1:].max() > 0


def test_speculative_ragged_prompts(models):
    params_t, params_d = models
    prompt = prompt_tokens()
    lengths = jnp.asarray([3, 6, 4], jnp.int32)
    ref = np.asarray(generate(params_t, prompt, 10, TARGET, lengths=lengths))
    got = np.asarray(
        speculative_generate(params_t, TARGET, params_d, DRAFT, prompt, 10,
                             draft_tokens=3, lengths=lengths)
    )
    np.testing.assert_array_equal(got, ref)


def test_speculative_stats(models):
    params_t, params_d = models
    prompt = prompt_tokens()
    tokens, stats = speculative_generate(
        params_t, TARGET, params_d, DRAFT, prompt, 12, draft_tokens=3,
        return_stats=True,
    )
    ref = np.asarray(generate(params_t, prompt, 12, TARGET))
    np.testing.assert_array_equal(np.asarray(tokens), ref)
    rounds = np.asarray(stats["rounds"])
    rate = np.asarray(stats["acceptance_rate"])
    # each round emits 1..k+1 tokens: rounds bounded by [ceil(12/4), 12]
    assert (rounds >= 3).all() and (rounds <= 12).all()
    assert (rate >= 0).all() and (rate <= 1).all()
    # self-draft accepts everything: minimal rounds, rate 1
    tokens, stats = speculative_generate(
        params_t, TARGET, params_t, TARGET, prompt, 12, draft_tokens=3,
        return_stats=True,
    )
    np.testing.assert_array_equal(np.asarray(tokens), ref)
    assert (np.asarray(stats["acceptance_rate"]) == 1.0).all()
    assert (np.asarray(stats["rounds"]) == 3).all()  # ceil(12 / 4)


def test_speculative_jit_compiled_path(models):
    params_t, params_d = models
    prompt = prompt_tokens(seed=7)
    ref = np.asarray(generate(params_t, prompt, 8, TARGET))
    got = np.asarray(
        speculative_generate_jit(params_t, TARGET, params_d, DRAFT, prompt,
                                 8, 3)
    )
    np.testing.assert_array_equal(got, ref)


def test_speculative_llama_family():
    """Family dispatch: a llama target (GQA cache, RoPE chunk positions,
    llama_chunk_decode verify) with a llama draft reproduces the llama
    greedy sequence; chunk verify equals sequential llama decode."""
    from kube_sqs_autoscaler_tpu.workloads.llama import (
        LlamaConfig,
        init_llama_params,
        llama_chunk_decode,
        llama_decode_step,
        llama_generate,
        llama_prefill,
    )

    tcfg = LlamaConfig(vocab_size=128, d_model=64, n_heads=4, n_kv_heads=2,
                       n_layers=2, d_ff=96, max_seq_len=96)
    dcfg = LlamaConfig(vocab_size=128, d_model=32, n_heads=2, n_kv_heads=1,
                       n_layers=1, d_ff=64, max_seq_len=96)
    params_t = init_llama_params(jax.random.key(31), tcfg)
    params_d = init_llama_params(jax.random.key(32), dcfg)
    prompt = jax.random.randint(jax.random.key(33), (2, 6), 0, 128,
                                jnp.int32)

    # chunk verify == sequential decode steps
    _, cache_a = llama_prefill(params_t, prompt, tcfg)
    _, cache_b = llama_prefill(params_t, prompt, tcfg)
    chunk = jax.random.randint(jax.random.key(34), (2, 3), 0, 128,
                               jnp.int32)
    seq_logits = []
    for t in range(3):
        logits, cache_a = llama_decode_step(params_t, cache_a, chunk[:, t],
                                            tcfg)
        seq_logits.append(logits)
    got, cache_b = llama_chunk_decode(params_t, cache_b, chunk, tcfg)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(jnp.stack(seq_logits, axis=1)),
        rtol=2e-4, atol=2e-4,
    )

    ref = np.asarray(llama_generate(params_t, prompt, 10, tcfg))
    got = np.asarray(
        speculative_generate(params_t, tcfg, params_d, dcfg, prompt, 10,
                             draft_tokens=3)
    )
    np.testing.assert_array_equal(got, ref)

    # the int8 GQA caches through the same loop: identical to plain
    # llama quantized greedy decode
    qref = np.asarray(llama_generate(params_t, prompt, 10, tcfg,
                                     quantized_cache=True))
    qgot = np.asarray(
        speculative_generate(params_t, tcfg, params_d, dcfg, prompt, 10,
                             draft_tokens=3, quantized_cache=True)
    )
    np.testing.assert_array_equal(qgot, qref)


def test_speculative_untied_readout_llama():
    """An HF-imported llama with a separate lm_head speculates correctly
    (the chunk verify reads readout_weights, not the tied embedding)."""
    torch = pytest.importorskip("torch")
    pytest.importorskip("transformers")
    from transformers import LlamaConfig as HFConfig, LlamaForCausalLM

    from kube_sqs_autoscaler_tpu.workloads.hf_convert import load_hf_llama
    from kube_sqs_autoscaler_tpu.workloads.llama import llama_generate

    torch.manual_seed(0)
    hf = LlamaForCausalLM(HFConfig(
        vocab_size=128, hidden_size=64, intermediate_size=96,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=96, tie_word_embeddings=False,
        attn_implementation="eager",
    ))
    from dataclasses import replace

    config, params = load_hf_llama(hf, dtype=jnp.float32)
    assert "lm_head" in params
    dcfg = replace(config, n_layers=2)
    dparams = dict(params, layers=params["layers"][:2])
    prompt = jax.random.randint(jax.random.key(41), (2, 8), 0, 128,
                                jnp.int32)
    ref = np.asarray(llama_generate(params, prompt, 12, config))
    got = np.asarray(
        speculative_generate(params, config, dparams, dcfg, prompt, 12,
                             draft_tokens=3)
    )
    np.testing.assert_array_equal(got, ref)


def test_speculative_tight_budget_with_uneven_acceptance():
    """Rows that finish early freeze instead of marching their cache past
    max_seq_len: with a small vocab (high random acceptance variance) and
    max_seq_len at exactly the validated budget, the output still equals
    greedy decoding for every row."""
    vocab = 16
    num, k, prompt_len = 20, 4, 4
    tight = prompt_len + num + 2 * k  # exactly the documented budget
    tcfg = ModelConfig(vocab_size=vocab, d_model=32, n_heads=2, n_layers=2,
                       d_ff=64, max_seq_len=tight)
    dcfg = ModelConfig(vocab_size=vocab, d_model=32, n_heads=2, n_layers=1,
                       d_ff=64, max_seq_len=tight)
    params_t = init_params(jax.random.key(21), tcfg)
    params_d = init_params(jax.random.key(22), dcfg)
    prompt = jax.random.randint(jax.random.key(23), (4, prompt_len), 0,
                                vocab, jnp.int32)
    ref = np.asarray(generate(params_t, prompt, num, tcfg))
    got = np.asarray(
        speculative_generate(params_t, tcfg, params_d, dcfg, prompt, num,
                             draft_tokens=k)
    )
    np.testing.assert_array_equal(got, ref)


def test_rejection_rule_marginal_is_the_warped_target_distribution():
    """The speculative-sampling acceptance rule: over 10^5 i.i.d. rows,
    the emitted position's empirical distribution equals the warped
    target softmax — min(p,q) + (1-Σmin)·(q-p)+/Z == q, measured."""
    from kube_sqs_autoscaler_tpu.workloads.speculative import (
        _accept_and_fixup,
        _warp,
    )

    B, k, V = 100_000, 1, 5
    draft_logits = jnp.asarray([0.1, 1.0, -0.4, 0.7, 0.2], jnp.float32)
    target_logits = jnp.asarray([0.9, -0.2, 0.5, 0.0, -1.0], jnp.float32)
    draft_w = jnp.broadcast_to(_warp(draft_logits, 0.8, 0, 1.0), (B, k, V))
    target_w = jnp.broadcast_to(
        _warp(target_logits, 0.8, 0, 1.0), (B, k + 1, V)
    )
    kd, ka = jax.random.split(jax.random.key(0))
    drafts = jax.random.categorical(
        kd, jnp.broadcast_to(draft_w[:, 0], (B, V))
    )[:, None]
    n, fixup = _accept_and_fixup(ka, drafts, draft_w, target_w)
    emitted = np.where(
        np.asarray(n) >= 1, np.asarray(drafts[:, 0]), np.asarray(fixup)
    )
    empirical = np.bincount(emitted, minlength=V) / B
    expected = np.asarray(jax.nn.softmax(_warp(target_logits, 0.8, 0, 1.0)))
    np.testing.assert_allclose(empirical, expected, atol=0.012)


def test_speculative_sampling_end_to_end(models):
    """Sampled speculative decoding: deterministic per key, key-sensitive,
    in-vocab, and the greedy path is untouched by the new arguments."""
    params_t, params_d = models
    prompt = prompt_tokens()
    a = speculative_generate(params_t, TARGET, params_d, DRAFT, prompt, 12,
                             draft_tokens=3, temperature=0.9,
                             rng=jax.random.key(7), top_k=8)
    b = speculative_generate(params_t, TARGET, params_d, DRAFT, prompt, 12,
                             draft_tokens=3, temperature=0.9,
                             rng=jax.random.key(7), top_k=8)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert a.shape == (3, 12)
    assert 0 <= int(a.min()) and int(a.max()) < TARGET.vocab_size
    c = speculative_generate(params_t, TARGET, params_d, DRAFT, prompt, 12,
                             draft_tokens=3, temperature=0.9,
                             rng=jax.random.key(8), top_k=8)
    assert not np.array_equal(np.asarray(a), np.asarray(c))
    with pytest.raises(ValueError, match="rng"):
        speculative_generate(params_t, TARGET, params_d, DRAFT, prompt, 4,
                             temperature=0.5)


def test_quantized_chunk_decode_equals_quantized_steps(models):
    # per-position quantization: the chunk-wide verify writes IDENTICAL
    # codes to T sequential quantized steps, so logits agree
    from kube_sqs_autoscaler_tpu.workloads.decode import (
        quantized_chunk_decode,
        quantized_decode_step,
        quantized_prefill,
    )

    params_t, _ = models
    prompt = prompt_tokens(seed=11)
    _, chunk_cache = quantized_prefill(params_t, prompt, TARGET)
    _, step_cache = quantized_prefill(params_t, prompt, TARGET)
    chunk = jax.random.randint(jax.random.key(12), (3, 4), 0,
                               TARGET.vocab_size, jnp.int32)
    chunk_logits, chunk_cache = quantized_chunk_decode(
        params_t, chunk_cache, chunk, TARGET
    )
    for t in range(4):
        step_logits, step_cache = quantized_decode_step(
            params_t, step_cache, chunk[:, t], TARGET
        )
        np.testing.assert_allclose(
            np.asarray(chunk_logits[:, t]), np.asarray(step_logits),
            rtol=1e-4, atol=1e-4, err_msg=f"position {t}",
        )
    for a, b in zip(jax.tree.leaves(chunk_cache),
                    jax.tree.leaves(step_cache)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_quantized_speculative_equals_quantized_greedy(models):
    # the int8-cache draft-and-verify loop: identical to plain quantized
    # greedy generate (draft only buys throughput), eos included
    from kube_sqs_autoscaler_tpu.workloads.decode import generate as _gen

    params_t, params_d = models
    prompt = prompt_tokens(seed=13)
    ref = np.asarray(_gen(params_t, prompt, 12, TARGET,
                          quantized_cache=True))
    got = np.asarray(speculative_generate(
        params_t, TARGET, params_d, DRAFT, prompt, 12, draft_tokens=3,
        quantized_cache=True,
    ))
    np.testing.assert_array_equal(got, ref)

    eos = int(ref[0, 2])
    ref_eos = np.asarray(_gen(params_t, prompt, 12, TARGET,
                              quantized_cache=True, eos_id=eos))
    got_eos = np.asarray(speculative_generate(
        params_t, TARGET, params_d, DRAFT, prompt, 12, draft_tokens=3,
        quantized_cache=True, eos_id=eos,
    ))
    np.testing.assert_array_equal(got_eos, ref_eos)


def test_speculative_tp_sharded_matches_single_chip(models):
    # the last sharded-serving composition hole: draft-and-verify over a
    # (data, model) mesh, identical greedy outputs to single-chip
    from kube_sqs_autoscaler_tpu.workloads.speculative import (
        make_speculative_serving_fn,
    )
    from kube_sqs_autoscaler_tpu.workloads.train import (
        make_mesh,
        param_shardings,
    )

    params_t, _ = models
    mesh = make_mesh(jax.devices()[:4], model_parallel=2, seq_parallel=1)
    placed = jax.device_put(params_t, param_shardings(mesh, params_t))
    # early-exit self-draft: the target's own first layer
    draft_cfg = ModelConfig(
        vocab_size=TARGET.vocab_size, d_model=TARGET.d_model,
        n_heads=TARGET.n_heads, n_layers=1, d_ff=TARGET.d_ff,
        max_seq_len=TARGET.max_seq_len,
    )
    prompt = prompt_tokens(batch=4)
    lengths = jnp.full((4,), prompt.shape[1], jnp.int32)
    single = np.asarray(speculative_generate(
        params_t, TARGET, dict(params_t, layers=params_t["layers"][:1]),
        draft_cfg, prompt, 10, draft_tokens=3,
    ))

    run = make_speculative_serving_fn(mesh, TARGET, placed, draft_cfg,
                                      draft_tokens=3)
    sharded = np.asarray(run(
        placed, dict(placed, layers=placed["layers"][:1]), prompt,
        lengths, jax.random.key(0), 10,
    ))
    np.testing.assert_array_equal(sharded, single)


def test_serve_binary_speculative_flag():
    """--speculative-draft-layers end to end for both families, plus the
    fail-fast guards (sampling, layer bound)."""
    from kube_sqs_autoscaler_tpu.workloads.__main__ import main

    main(["--demo", "2", "--batch-size", "1", "--seq-len", "8",
          "--generate-tokens", "4", "--speculative-draft-layers", "2"])
    main(["--family", "llama", "--demo", "2", "--batch-size", "1",
          "--seq-len", "8", "--generate-tokens", "4",
          "--speculative-draft-layers", "1"])
    # temperature > 0 runs speculative SAMPLING through the same flag
    main(["--demo", "2", "--batch-size", "1", "--seq-len", "8",
          "--generate-tokens", "4", "--speculative-draft-layers", "2",
          "--temperature", "0.8", "--top-k", "8"])
    # eos rides the draft-and-verify loop (VERDICT r3 composition hole)
    main(["--demo", "2", "--batch-size", "1", "--seq-len", "8",
          "--generate-tokens", "4", "--speculative-draft-layers", "2",
          "--eos-id", "5"])
    # tp-sharded speculative serving (the last sharded-serving hole)
    import os

    if "xla_force_host_platform_device_count" in os.environ.get(
            "XLA_FLAGS", ""):
        main(["--demo", "4", "--batch-size", "4", "--seq-len", "8",
              "--generate-tokens", "4", "--speculative-draft-layers", "2",
              "--model-parallel", "2"])
    # int8 caches through the draft-and-verify loop
    main(["--demo", "2", "--batch-size", "1", "--seq-len", "8",
          "--generate-tokens", "4", "--speculative-draft-layers", "2",
          "--quantize-kv", "--eos-id", "5"])
    with pytest.raises(SystemExit, match="n_layers"):
        main(["--demo", "1", "--generate-tokens", "4",
              "--speculative-draft-layers", "99"])
    with pytest.raises(SystemExit, match="n_layers"):
        main(["--demo", "1", "--generate-tokens", "4",
              "--speculative-draft-layers", "-1"])
    with pytest.raises(SystemExit, match="draft-tokens"):
        main(["--demo", "1", "--generate-tokens", "4",
              "--speculative-draft-layers", "1",
              "--speculative-draft-tokens", "0"])


def test_speculative_validation(models):
    params_t, params_d = models
    prompt = prompt_tokens()
    with pytest.raises(ValueError, match="vocab"):
        bad = ModelConfig(vocab_size=64, d_model=32, n_heads=2, n_layers=1,
                          d_ff=64, max_seq_len=96)
        speculative_generate(params_t, TARGET, init_params(
            jax.random.key(2), bad), bad, prompt, 4)
    with pytest.raises(ValueError, match="draft_tokens"):
        speculative_generate(params_t, TARGET, params_d, DRAFT, prompt, 4,
                             draft_tokens=0)
    with pytest.raises(ValueError, match="max_seq_len"):
        speculative_generate(params_t, TARGET, params_d, DRAFT, prompt, 96)


def test_speculative_tp_sharded_prefix_and_int8(models):
    # the remaining serve-side fail-fasts (VERDICT r4 weak #3): the
    # sharded speculative factory now takes a pinned prefix (the
    # self-draft's prefix is the free layer slice) and streams int8
    # caches — both pinned bitwise-equal to their single-chip runs
    from kube_sqs_autoscaler_tpu.workloads.decode import prefill_prefix
    from kube_sqs_autoscaler_tpu.workloads.speculative import (
        draft_prefix_from_target,
        make_speculative_serving_fn,
    )
    from kube_sqs_autoscaler_tpu.workloads.train import (
        make_mesh,
        param_shardings,
    )

    params_t, _ = models
    mesh = make_mesh(jax.devices()[:4], model_parallel=2, seq_parallel=1)
    placed = jax.device_put(params_t, param_shardings(mesh, params_t))
    draft_cfg = ModelConfig(
        vocab_size=TARGET.vocab_size, d_model=TARGET.d_model,
        n_heads=TARGET.n_heads, n_layers=1, d_ff=TARGET.d_ff,
        max_seq_len=TARGET.max_seq_len,
    )
    draft = dict(params_t, layers=params_t["layers"][:1])
    prompt = prompt_tokens(batch=4)
    lengths = jnp.full((4,), prompt.shape[1], jnp.int32)

    prefix = jnp.arange(1, 7, dtype=jnp.int32)
    pc = prefill_prefix(params_t, prefix, TARGET)
    single_p = np.asarray(speculative_generate(
        params_t, TARGET, draft, draft_cfg, prompt, 8, draft_tokens=2,
        prefix_cache=pc,
        draft_prefix_cache=draft_prefix_from_target(pc, 1),
    ))
    run_p = make_speculative_serving_fn(
        mesh, TARGET, placed, draft_cfg, draft_tokens=2, prefix_cache=pc
    )
    sharded_p = np.asarray(run_p(
        placed, dict(placed, layers=placed["layers"][:1]), prompt,
        lengths, jax.random.key(0), 8,
    ))
    np.testing.assert_array_equal(sharded_p, single_p)

    single_q = np.asarray(speculative_generate(
        params_t, TARGET, draft, draft_cfg, prompt, 8, draft_tokens=2,
        quantized_cache=True,
    ))
    run_q = make_speculative_serving_fn(
        mesh, TARGET, placed, draft_cfg, draft_tokens=2,
        quantized_cache=True,
    )
    sharded_q = np.asarray(run_q(
        placed, dict(placed, layers=placed["layers"][:1]), prompt,
        lengths, jax.random.key(0), 8,
    ))
    np.testing.assert_array_equal(sharded_q, single_q)
