"""Property-based tests (hypothesis): the policy engine against its
reference predicates for arbitrary inputs, and episode-level invariants of
the full loop under arbitrary workload traces — bounds are never violated
and cooldowns always separate actuations.
"""

from tests.proptest import given, settings, st

from kube_sqs_autoscaler_tpu.core.clock import FakeClock
from kube_sqs_autoscaler_tpu.core.loop import ControlLoop, LoopConfig
from kube_sqs_autoscaler_tpu.core.policy import (
    Gate,
    PolicyConfig,
    PolicyState,
    gate_down,
    gate_up,
    plan_tick,
)
from kube_sqs_autoscaler_tpu.metrics import FakeQueueService, QueueMetricSource
from kube_sqs_autoscaler_tpu.scale import FakeDeploymentAPI, PodAutoScaler

configs = st.builds(
    PolicyConfig,
    scale_up_messages=st.integers(0, 1000),
    scale_down_messages=st.integers(0, 1000),
    scale_up_cooldown=st.floats(0, 100, allow_nan=False),
    scale_down_cooldown=st.floats(0, 100, allow_nan=False),
)
states = st.builds(
    PolicyState,
    last_scale_up=st.floats(-100, 100, allow_nan=False),
    last_scale_down=st.floats(-100, 100, allow_nan=False),
)


@given(
    n=st.integers(0, 2000),
    now=st.floats(-100, 200, allow_nan=False),
    config=configs,
    state=states,
)
def test_gates_match_reference_predicates(n, now, config, state):
    # main.go:51-52: inclusive threshold, strictly-After cooldown
    up = gate_up(n, now, config, state)
    if n >= config.scale_up_messages:
        expected = (
            Gate.COOLING
            if state.last_scale_up + config.scale_up_cooldown > now
            else Gate.FIRE
        )
    else:
        expected = Gate.IDLE
    assert up is expected

    down = gate_down(n, now, config, state)
    if n <= config.scale_down_messages:
        expected = (
            Gate.COOLING
            if state.last_scale_down + config.scale_down_cooldown > now
            else Gate.FIRE
        )
    else:
        expected = Gate.IDLE
    assert down is expected

    # composed plan: up-cooling always skips the down branch (main.go:54)
    plan = plan_tick(n, now, config, state)
    assert plan.up is up
    assert plan.down is (Gate.SKIPPED if up is Gate.COOLING else down)


@settings(max_examples=60, deadline=None)
@given(
    depths=st.lists(st.integers(0, 500), min_size=1, max_size=60),
    up=st.integers(50, 300),
    down=st.integers(0, 49),
    up_cool=st.floats(0, 30, allow_nan=False),
    down_cool=st.floats(0, 30, allow_nan=False),
    min_pods=st.integers(1, 3),
    extra=st.integers(0, 10),
    init_offset=st.integers(0, 5),
    step=st.integers(1, 5),
)
def test_episode_invariants(
    depths, up, down, up_cool, down_cool, min_pods, extra, init_offset, step
):
    max_pods = min_pods + extra
    init = min(min_pods + init_offset, max_pods)
    api = FakeDeploymentAPI.with_deployments("ns", init, "deploy")
    scaler = PodAutoScaler(
        client=api, max=max_pods, min=min_pods, scale_up_pods=step,
        scale_down_pods=step, deployment="deploy", namespace="ns",
    )
    queue = FakeQueueService.with_depths(depths[0])
    clock = FakeClock()
    loop = ControlLoop(
        scaler,
        QueueMetricSource(client=queue, queue_url="q"),
        LoopConfig(
            poll_interval=1.0,
            policy=PolicyConfig(
                scale_up_messages=up, scale_down_messages=down,
                scale_up_cooldown=up_cool, scale_down_cooldown=down_cool,
            ),
        ),
        clock=clock,
    )
    # feed the depth trace: depth[i] becomes visible at t=i
    for i, depth in enumerate(depths):
        clock.at(float(i), lambda d=depth: queue.set_depths(d))

    observations: list[tuple[float, int]] = []  # (t, replicas after tick)
    original_tick = loop.tick

    def recording_tick(state):
        new_state = original_tick(state)
        observations.append((clock.now(), api.replicas("deploy")))
        return new_state

    loop.tick = recording_tick
    loop.run(max_ticks=len(depths))

    # invariant 1: replica count always within [init-clamped bounds]
    low = min(min_pods, init)
    high = max(max_pods, init)
    assert all(low <= r <= high for _, r in observations)

    # invariant 2: successive increases are separated by >= up_cool
    # (and decreases by >= down_cool)
    last_up_time = None
    last_down_time = None
    prev = init
    for t, replicas in observations:
        if replicas > prev:
            if last_up_time is not None:
                assert t - last_up_time >= up_cool - 1e-6
            last_up_time = t
        elif replicas < prev:
            if last_down_time is not None:
                assert t - last_down_time >= down_cool - 1e-6
            last_down_time = t
        prev = replicas


@settings(max_examples=25, deadline=None)
@given(
    depths=st.lists(st.integers(0, 500), min_size=1, max_size=40),
    up=st.integers(50, 300),
    down=st.integers(0, 49),
    up_cool=st.floats(0, 30, allow_nan=False),
    down_cool=st.floats(0, 30, allow_nan=False),
    min_pods=st.integers(1, 3),
    extra=st.integers(0, 10),
    init_offset=st.integers(0, 5),
    step=st.integers(1, 5),
    forecaster_name=st.sampled_from(["ewma", "holt", "lstsq"]),
    horizon=st.floats(0, 120, allow_nan=False),
    conservative=st.booleans(),
)
def test_predictive_episode_invariants(
    depths, up, down, up_cool, down_cool, min_pods, extra, init_offset, step,
    forecaster_name, horizon, conservative,
):
    """The predictive policy sits *before* the unchanged gates, so whatever
    a forecaster hallucinates, an episode must uphold exactly the
    invariants the reactive episode does: replica bounds are never
    violated and actuations in one direction are always separated by that
    direction's cooldown."""
    from kube_sqs_autoscaler_tpu.forecast import (
        DepthHistory,
        PredictivePolicy,
        make_forecaster,
    )

    max_pods = min_pods + extra
    init = min(min_pods + init_offset, max_pods)
    api = FakeDeploymentAPI.with_deployments("ns", init, "deploy")
    scaler = PodAutoScaler(
        client=api, max=max_pods, min=min_pods, scale_up_pods=step,
        scale_down_pods=step, deployment="deploy", namespace="ns",
    )
    queue = FakeQueueService.with_depths(depths[0])
    clock = FakeClock()
    policy = PredictivePolicy(
        make_forecaster(forecaster_name),
        DepthHistory(capacity=16),
        horizon=horizon,
        conservative=conservative,
    )
    loop = ControlLoop(
        scaler,
        QueueMetricSource(client=queue, queue_url="q"),
        LoopConfig(
            poll_interval=1.0,
            policy=PolicyConfig(
                scale_up_messages=up, scale_down_messages=down,
                scale_up_cooldown=up_cool, scale_down_cooldown=down_cool,
            ),
        ),
        clock=clock,
        observer=policy.history,
        depth_policy=policy,
    )
    for i, depth in enumerate(depths):
        clock.at(float(i), lambda d=depth: queue.set_depths(d))

    observations: list[tuple[float, int]] = []
    original_tick = loop.tick

    def recording_tick(state):
        new_state = original_tick(state)
        observations.append((clock.now(), api.replicas("deploy")))
        return new_state

    loop.tick = recording_tick
    loop.run(max_ticks=len(depths))

    low = min(min_pods, init)
    high = max(max_pods, init)
    assert all(low <= r <= high for _, r in observations)

    last_up_time = None
    last_down_time = None
    prev = init
    for t, replicas in observations:
        if replicas > prev:
            if last_up_time is not None:
                assert t - last_up_time >= up_cool - 1e-6
            last_up_time = t
        elif replicas < prev:
            if last_down_time is not None:
                assert t - last_down_time >= down_cool - 1e-6
            last_down_time = t
        prev = replicas


@settings(max_examples=15, deadline=None)
@given(
    depths=st.lists(st.integers(0, 500), min_size=1, max_size=40),
    up=st.integers(50, 300),
    down=st.integers(0, 49),
    up_cool=st.floats(0, 30, allow_nan=False),
    down_cool=st.floats(0, 30, allow_nan=False),
    min_pods=st.integers(1, 3),
    extra=st.integers(0, 10),
    init_offset=st.integers(0, 5),
    step=st.integers(1, 5),
    theta_seed=st.integers(0, 1000),
)
def test_learned_episode_invariants(
    depths, up, down, up_cool, down_cool, min_pods, extra, init_offset, step,
    theta_seed,
):
    """The learned policy also sits *before* the unchanged gates, so
    whatever a random (untrained) network decides, an episode must uphold
    exactly the reactive episode's invariants: replica bounds are never
    violated and same-direction actuations are separated by that
    direction's cooldown."""
    from kube_sqs_autoscaler_tpu.forecast import DepthHistory
    from kube_sqs_autoscaler_tpu.learn import LearnedPolicy, PolicyCheckpoint
    from kube_sqs_autoscaler_tpu.learn.network import init_params

    max_pods = min_pods + extra
    init = min(min_pods + init_offset, max_pods)
    api = FakeDeploymentAPI.with_deployments("ns", init, "deploy")
    scaler = PodAutoScaler(
        client=api, max=max_pods, min=min_pods, scale_up_pods=step,
        scale_down_pods=step, deployment="deploy", namespace="ns",
    )
    queue = FakeQueueService.with_depths(depths[0])
    clock = FakeClock()
    config = PolicyConfig(
        scale_up_messages=up, scale_down_messages=down,
        scale_up_cooldown=up_cool, scale_down_cooldown=down_cool,
    )
    policy = LearnedPolicy(
        PolicyCheckpoint(theta=init_params(theta_seed)),
        policy=config,
        poll_interval=1.0,
        max_pods=max_pods,
        min_pods=min_pods,
        scale_up_pods=step,
        scale_down_pods=step,
        initial_replicas=init,
        history=DepthHistory(capacity=16),
    )
    loop = ControlLoop(
        scaler,
        QueueMetricSource(client=queue, queue_url="q"),
        LoopConfig(poll_interval=1.0, policy=config),
        clock=clock,
        observer=policy,
        depth_policy=policy,
    )
    for i, depth in enumerate(depths):
        clock.at(float(i), lambda d=depth: queue.set_depths(d))

    observations: list[tuple[float, int]] = []
    original_tick = loop.tick

    def recording_tick(state):
        new_state = original_tick(state)
        observations.append((clock.now(), api.replicas("deploy")))
        return new_state

    loop.tick = recording_tick
    loop.run(max_ticks=len(depths))

    low = min(min_pods, init)
    high = max(max_pods, init)
    assert all(low <= r <= high for _, r in observations)

    last_up_time = None
    last_down_time = None
    prev = init
    for t, replicas in observations:
        if replicas > prev:
            if last_up_time is not None:
                assert t - last_up_time >= up_cool - 1e-6
            last_up_time = t
        elif replicas < prev:
            if last_down_time is not None:
                assert t - last_down_time >= down_cool - 1e-6
            last_down_time = t
        prev = replicas


@settings(max_examples=25, deadline=None)
@given(
    depths=st.lists(st.integers(0, 400), min_size=3, max_size=30),
    up=st.integers(50, 300),
    horizon=st.floats(0, 120, allow_nan=False),
    forecaster_name=st.sampled_from(["ewma", "holt", "lstsq"]),
)
def test_conservative_policy_effective_depth_dominates_observed(
    depths, up, horizon, forecaster_name
):
    """conservative=True thresholds on max(observed, forecast): the up gate
    can only ever see a depth >= the reactive gate's — it fires no later —
    and the down gate needs both signals below threshold."""
    from kube_sqs_autoscaler_tpu.forecast import (
        DepthHistory,
        PredictivePolicy,
        make_forecaster,
    )

    policy = PredictivePolicy(
        make_forecaster(forecaster_name),
        DepthHistory(capacity=8),
        horizon=horizon,
        conservative=True,
    )
    for i, depth in enumerate(depths):
        effective = policy.effective_messages(float(i), depth)
        assert effective >= depth
        policy.history.observe(float(i), float(depth))
