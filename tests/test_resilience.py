"""Resilience-layer tests: retries, deadlines, breaker, stale hold.

Everything runs on a FakeClock — backoff sleeps, deadline measurement,
breaker reset windows, and stale TTLs are all virtual time, no wall-clock
sleeps anywhere (the acceptance contract).  The loop-level tests drive the
REAL ControlLoop with scripted sources/scalers, so what is covered is the
wiring the flags actually enable, not the pieces in isolation only.
"""

import logging
import time
import urllib.request

import pytest

from kube_sqs_autoscaler_tpu.core.clock import FakeClock
from kube_sqs_autoscaler_tpu.core.events import MultiObserver, TickRecord
from kube_sqs_autoscaler_tpu.core.loop import ControlLoop, LoopConfig
from kube_sqs_autoscaler_tpu.core.policy import Gate, PolicyConfig
from kube_sqs_autoscaler_tpu.core.resilience import (
    CircuitBreaker,
    DeadlineExceeded,
    ResilienceConfig,
    RetryPolicy,
    call_with_deadline,
)
from kube_sqs_autoscaler_tpu.core.types import MetricError, ScaleError


class ScriptedSource:
    """Metric source driven by a list of outcomes.

    Each item is an int (depth), an exception instance (raised), or
    ``("slow", seconds, outcome)`` which consumes virtual clock time
    before resolving ``outcome``.  The script end repeats the last plain
    depth (or 0).
    """

    def __init__(self, clock, outcomes):
        self.clock = clock
        self.outcomes = list(outcomes)
        self.calls = 0
        self._last_depth = 0

    def num_messages(self) -> int:
        self.calls += 1
        item = self.outcomes.pop(0) if self.outcomes else self._last_depth
        if isinstance(item, tuple) and item[0] == "slow":
            _, seconds, item = item
            self.clock.sleep(seconds)
        if isinstance(item, BaseException):
            raise item
        self._last_depth = int(item)
        return self._last_depth


class ScriptedScaler:
    """Scaler whose up-calls follow a script of outcomes.

    Items: ``None`` (success), an exception instance (raised), or
    ``("slow", seconds)`` (consume clock, then succeed).  Script end
    repeats success.  Down-calls always succeed.
    """

    def __init__(self, clock, up_outcomes=()):
        self.clock = clock
        self.up_outcomes = list(up_outcomes)
        self.up_calls = 0
        self.down_calls = 0

    def scale_up(self) -> None:
        self.up_calls += 1
        item = self.up_outcomes.pop(0) if self.up_outcomes else None
        if isinstance(item, tuple) and item[0] == "slow":
            self.clock.sleep(item[1])
            item = None
        if isinstance(item, BaseException):
            raise item

    def scale_down(self) -> None:
        self.down_calls += 1


class RecordingObserver:
    def __init__(self):
        self.records = []

    def on_tick(self, record):
        self.records.append(record)


def make_loop(
    source_outcomes,
    resilience,
    *,
    up_outcomes=(),
    poll=5.0,
    up_msgs=100,
    down_msgs=0,
    up_cool=0.0,
    down_cool=1e9,
):
    """Real ControlLoop on a FakeClock with scripted seams.

    Defaults neutralize the down gate (threshold 0, huge cooldown) so
    tests reason about the up path only.
    """
    clock = FakeClock()
    source = ScriptedSource(clock, source_outcomes)
    scaler = ScriptedScaler(clock, up_outcomes)
    observer = RecordingObserver()
    loop = ControlLoop(
        scaler,
        source,
        LoopConfig(
            poll_interval=poll,
            policy=PolicyConfig(
                scale_up_messages=up_msgs,
                scale_down_messages=down_msgs,
                scale_up_cooldown=up_cool,
                scale_down_cooldown=down_cool,
            ),
        ),
        clock=clock,
        observer=observer,
        resilience=resilience,
    )
    return loop, source, scaler, clock, observer


# --- config gating ---------------------------------------------------------


def test_default_config_disables_the_layer():
    # all-defaults config: the loop must keep the reference code path
    assert not ResilienceConfig().enabled
    loop, _, _, _, _ = make_loop([1], ResilienceConfig())
    assert loop.resilience is None


def test_any_optin_enables_the_layer():
    for kwargs in (
        {"metric_retries": 1},
        {"metric_timeout": 1.0},
        {"scaler_retries": 1},
        {"scaler_timeout": 1.0},
        {"breaker_failures": 1},
        {"stale_depth_ttl": 1.0},
    ):
        assert ResilienceConfig(**kwargs).enabled, kwargs


def test_reference_parity_when_disabled(caplog):
    # resilience=None and resilience=defaults produce identical records
    # on an eventful script (failure, observation, scale-up)
    script = lambda: [MetricError("down"), 200, 200]  # noqa: E731

    def run(resilience):
        loop, _, scaler, _, observer = make_loop(script(), resilience)
        loop.run(max_ticks=3)
        return observer.records, scaler.up_calls

    ref_records, ref_ups = run(None)
    cfg_records, cfg_ups = run(ResilienceConfig())
    assert ref_ups == cfg_ups
    for a, b in zip(ref_records, cfg_records):
        assert a == b


# --- RetryPolicy -----------------------------------------------------------


def test_backoff_deterministic_and_bounded():
    a = RetryPolicy(5, base_delay=0.2, max_delay=2.0, jitter=0.5, seed=7)
    b = RetryPolicy(5, base_delay=0.2, max_delay=2.0, jitter=0.5, seed=7)
    delays_a = [a.delay(i) for i in range(6)]
    delays_b = [b.delay(i) for i in range(6)]
    assert delays_a == delays_b  # seeded: reproducible
    for i, d in enumerate(delays_a):
        ceiling = min(2.0, 0.2 * 2**i)
        assert 0.5 * ceiling <= d <= ceiling  # jitter only shrinks


def test_zero_jitter_is_pure_exponential():
    p = RetryPolicy(5, base_delay=0.5, max_delay=4.0, jitter=0.0, seed=0)
    assert [p.delay(i) for i in range(5)] == [0.5, 1.0, 2.0, 4.0, 4.0]


def test_retry_run_recovers_and_counts():
    clock = FakeClock()
    attempts = []
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise MetricError("blip")
        return 42

    policy = RetryPolicy(3, base_delay=1.0, jitter=0.0, seed=0)
    value, extra = policy.run(
        flaky, clock, on_attempts=attempts.append
    )
    assert value == 42 and extra == 2
    assert calls["n"] == 3
    assert attempts == [0, 1, 2]
    assert clock.now() == pytest.approx(1.0 + 2.0)  # two backoffs


def test_retry_run_respects_budget_deadline():
    clock = FakeClock()
    policy = RetryPolicy(10, base_delay=2.0, max_delay=2.0, jitter=0.0, seed=0)

    def always_fails():
        raise MetricError("dead")

    # deadline at t=2.5: first backoff (to t=2.0) fits, the second (to
    # t=4.0) would overshoot -> the original error surfaces
    with pytest.raises(MetricError):
        policy.run(always_fails, clock, deadline=2.5)
    assert clock.now() == pytest.approx(2.0)


def test_retry_does_not_catch_base_exceptions():
    clock = FakeClock()
    policy = RetryPolicy(5, seed=0)
    calls = {"n": 0}

    def interrupted():
        calls["n"] += 1
        raise KeyboardInterrupt()

    with pytest.raises(KeyboardInterrupt):
        policy.run(interrupted, clock)
    assert calls["n"] == 1  # not retried
    assert clock.sleeps == []  # no backoff consumed


# --- call_with_deadline ----------------------------------------------------


def test_deadline_converts_slow_into_failure():
    clock = FakeClock()

    def slow():
        clock.sleep(3.0)
        return "late"

    with pytest.raises(DeadlineExceeded):
        call_with_deadline(slow, clock, timeout=2.0)


def test_deadline_boundary_and_disabled():
    clock = FakeClock()

    def exactly():
        clock.sleep(2.0)
        return "on time"

    # boundary-exact fires like the gates' boundary convention
    assert call_with_deadline(exactly, clock, timeout=2.0) == "on time"

    def very_slow():
        clock.sleep(100.0)
        return "fine"

    assert call_with_deadline(very_slow, clock, timeout=0.0) == "fine"


# --- loop integration: metric retries + timeout ----------------------------


def test_metric_retry_recovers_within_tick():
    loop, source, _, clock, observer = make_loop(
        [MetricError("a"), MetricError("b"), 42],
        ResilienceConfig(metric_retries=2),
    )
    loop.run(max_ticks=1)
    record = observer.records[0]
    assert record.num_messages == 42
    assert record.metric_error is None
    assert record.metric_retries == 2
    assert source.calls == 3
    assert len(clock.sleeps) == 3  # the poll sleep + two backoffs


def test_metric_retry_exhaustion_falls_back_to_reference(caplog):
    loop, source, _, _, observer = make_loop(
        [MetricError("x")] * 3,
        ResilienceConfig(metric_retries=2),
    )
    with caplog.at_level(logging.ERROR):
        loop.run(max_ticks=1)
    record = observer.records[0]
    assert record.metric_error == "x"
    assert record.metric_retries == 2  # the attempts are still ledgered
    assert source.calls == 3
    assert any("Failed to get SQS messages" in r.message for r in caplog.records)


def test_metric_timeout_converts_slow_poll_to_failure():
    loop, _, _, _, observer = make_loop(
        [("slow", 5.0, 42)],
        ResilienceConfig(metric_timeout=2.0),
    )
    loop.run(max_ticks=1)
    record = observer.records[0]
    assert record.metric_error is not None
    assert "deadline" in record.metric_error
    assert record.num_messages is None


def test_retry_budget_is_within_poll_interval():
    # base 2s/no-jitter backoffs against a 5s poll with the default 0.5
    # budget: only ONE backoff (to t~2) fits under the 2.5s budget
    loop, source, _, clock, observer = make_loop(
        [MetricError("x")] * 10,
        ResilienceConfig(
            metric_retries=8,
            retry_base_delay=2.0,
            retry_max_delay=2.0,
            retry_jitter=0.0,
        ),
        poll=5.0,
    )
    loop.run(max_ticks=1)
    assert source.calls == 2  # first try + the single budgeted retry
    assert observer.records[0].metric_retries == 1
    # the next tick still starts on cadence: 5s sleep + 2s backoff + 5s sleep
    assert clock.now() == pytest.approx(5.0 + 2.0)


# --- loop integration: stale-depth hold ------------------------------------


def test_stale_hold_keeps_scaling_through_outage(caplog):
    loop, _, scaler, _, observer = make_loop(
        [200, MetricError("dark"), MetricError("dark")],
        ResilienceConfig(stale_depth_ttl=60.0),
    )
    with caplog.at_level(logging.WARNING):
        loop.run(max_ticks=3)
    fresh, stale1, stale2 = observer.records
    assert not fresh.stale and fresh.num_messages == 200
    for record in (stale1, stale2):
        assert record.stale is True
        assert record.num_messages == 200  # the held depth
        assert record.metric_error is None  # the tick proceeded
        assert record.up is Gate.FIRE
    assert stale1.stale_age_s == pytest.approx(5.0)
    assert stale2.stale_age_s == pytest.approx(10.0)
    assert scaler.up_calls == 3
    assert any("holding last good depth 200" in r.message for r in caplog.records)


def test_stale_ttl_expiry_goes_fail_static():
    loop, _, scaler, _, observer = make_loop(
        [200] + [MetricError("dark")] * 3,
        ResilienceConfig(stale_depth_ttl=8.0),
        poll=5.0,
    )
    loop.run(max_ticks=4)
    _, stale, static1, static2 = observer.records
    assert stale.stale is True  # age 5 <= 8
    for record in (static1, static2):  # ages 10, 15 > 8: reference path
        assert record.metric_error == "dark"
        assert record.stale is None
        assert record.up is Gate.SKIPPED
    assert scaler.up_calls == 2  # fresh + one held tick only


def test_stale_hold_without_prior_observation_fails_static():
    loop, _, scaler, _, observer = make_loop(
        [MetricError("dark")],
        ResilienceConfig(stale_depth_ttl=60.0),
    )
    loop.run(max_ticks=1)
    assert observer.records[0].metric_error == "dark"
    assert scaler.up_calls == 0


def test_stale_ticks_never_feed_forecaster_history():
    from kube_sqs_autoscaler_tpu.forecast.history import DepthHistory

    history = DepthHistory(capacity=8)
    clock = FakeClock()
    source = ScriptedSource(clock, [200, MetricError("dark"), 300])
    scaler = ScriptedScaler(clock)
    loop = ControlLoop(
        scaler,
        source,
        LoopConfig(poll_interval=5.0),
        clock=clock,
        observer=history,
        resilience=ResilienceConfig(stale_depth_ttl=60.0),
    )
    loop.run(max_ticks=3)
    times, depths, n = history.snapshot()
    assert n == 2  # the stale tick is absent
    assert list(depths[:2]) == [200.0, 300.0]


def test_stale_tick_bypasses_depth_policy():
    calls = []

    class CountingPolicy:
        def effective_messages(self, now, num_messages):
            calls.append(num_messages)
            return num_messages

    clock = FakeClock()
    source = ScriptedSource(clock, [200, MetricError("dark"), 300])
    scaler = ScriptedScaler(clock)
    loop = ControlLoop(
        scaler,
        source,
        LoopConfig(poll_interval=5.0),
        clock=clock,
        depth_policy=CountingPolicy(),
        resilience=ResilienceConfig(stale_depth_ttl=60.0),
    )
    loop.run(max_ticks=3)
    assert calls == [200, 300]  # not consulted on the stale tick


# --- loop integration: circuit breaker -------------------------------------


def test_breaker_opens_and_fails_fast():
    loop, _, scaler, _, observer = make_loop(
        [500],
        ResilienceConfig(breaker_failures=2, breaker_reset=60.0),
        up_outcomes=[ScaleError("api down")] * 10,
    )
    loop.run(max_ticks=4)
    r1, r2, r3, r4 = observer.records
    assert r1.up_error == "api down" and r1.breaker_state == "closed"
    assert r2.up_error == "api down" and r2.breaker_state == "open"
    for record in (r3, r4):  # rejected without touching the scaler
        assert "circuit breaker open" in record.up_error
        assert record.breaker_state == "open"
    assert scaler.up_calls == 2


def test_breaker_half_open_probe_success_closes():
    # failures at t=5,10 open the breaker at t=10; reset 12s makes the
    # t=25 tick the first eligible probe (15 and 20 are rejected), and
    # the scaler script has recovered by then -> closed, scaling resumes
    loop, _, scaler, _, observer = make_loop(
        [500],
        ResilienceConfig(breaker_failures=2, breaker_reset=12.0),
        up_outcomes=[ScaleError("down"), ScaleError("down")],
    )
    loop.run(max_ticks=5)
    records = observer.records
    assert [r.breaker_state for r in records] == [
        "closed", "open", "open", "open", "closed"
    ]
    assert records[4].scaled("up")  # the successful probe
    assert scaler.up_calls == 3  # 2 failures + the probe


def test_breaker_half_open_probe_failure_reopens():
    loop, _, scaler, _, observer = make_loop(
        [500],
        ResilienceConfig(breaker_failures=2, breaker_reset=12.0),
        up_outcomes=[ScaleError("down")] * 3 + [None],
    )
    loop.run(max_ticks=8)
    states = [r.breaker_state for r in observer.records]
    # open at t=10; probe at t=25 fails -> re-open (reset restarts from
    # the failed probe); next probe at t=40 succeeds
    assert states == ["closed", "open", "open", "open", "open",
                      "open", "open", "closed"]
    assert scaler.up_calls == 4  # 2 + failed probe + successful probe
    assert observer.records[7].scaled("up")


def test_breaker_unit_transitions():
    breaker = CircuitBreaker(failure_threshold=2, reset_timeout=10.0)
    assert breaker.allow(0.0) and breaker.state == "closed"
    breaker.record_failure(0.0)
    assert breaker.state == "closed" and breaker.failures == 1
    breaker.record_failure(1.0)
    assert breaker.state == "open"
    assert not breaker.allow(5.0)
    assert breaker.seconds_until_probe(5.0) == pytest.approx(6.0)
    assert breaker.allow(11.0)  # boundary-inclusive probe
    assert breaker.state == "half_open"
    breaker.record_failure(11.0)  # probe fails: re-open, reset restarts
    assert breaker.state == "open"
    assert not breaker.allow(20.0)
    assert breaker.allow(21.0)
    breaker.record_success()
    assert breaker.state == "closed" and breaker.failures == 0
    assert breaker.seconds_until_probe(99.0) == 0.0


def test_failed_breaker_rejection_does_not_advance_cooldown():
    # An open-breaker rejection is an actuation failure: the cooldown
    # timestamp must stay put (main.go:57-60 semantics).  With cooldown
    # 6s, if the t=10 RPC failure OR the t=15 breaker rejection had
    # advanced the timestamp, the following gate would read COOLING
    # instead of firing — so the observed FIRE/FIRE/FIRE tail proves
    # neither failure path touched policy state.
    loop, _, scaler, _, observer = make_loop(
        [500],
        ResilienceConfig(breaker_failures=1, breaker_reset=7.0),
        up_outcomes=[ScaleError("down")],
        up_cool=6.0,
    )
    loop.run(max_ticks=4)
    r1, r2, r3, r4 = observer.records
    assert r1.up is Gate.COOLING  # t=5: startup grace (0 + 6 > 5)
    assert r2.up is Gate.FIRE and r2.up_error == "down"  # opens at t=10
    assert r3.up is Gate.FIRE  # cooldown NOT advanced by the failure
    assert "circuit breaker open" in r3.up_error  # 10 + 7 > 15
    assert r4.up is Gate.FIRE  # nor by the rejection: probe at t=20
    assert r4.scaled("up")
    assert scaler.up_calls == 2  # the t=10 failure + the t=20 probe


# --- scaler retries + timeout ----------------------------------------------


def test_scaler_retry_recovers_within_tick():
    loop, _, scaler, _, observer = make_loop(
        [500],
        ResilienceConfig(scaler_retries=1),
        up_outcomes=[ScaleError("conflict"), None],
    )
    loop.run(max_ticks=1)
    record = observer.records[0]
    assert record.scaled("up")
    assert record.scaler_retries == 1
    assert scaler.up_calls == 2


def test_scaler_timeout_feeds_the_breaker():
    # slow-but-successful actuations: the deadline turns them into
    # failures and the breaker opens on consecutive timeouts
    loop, _, scaler, _, observer = make_loop(
        [500],
        ResilienceConfig(scaler_timeout=1.0, breaker_failures=2),
        up_outcomes=[("slow", 3.0), ("slow", 3.0), ("slow", 3.0)],
    )
    loop.run(max_ticks=3)
    r1, r2, r3 = observer.records
    assert "deadline" in r1.up_error
    assert r2.breaker_state == "open"
    assert "circuit breaker open" in r3.up_error
    assert scaler.up_calls == 2


# --- BaseException hygiene (satellite) --------------------------------------


@pytest.mark.parametrize("resilience", [None, ResilienceConfig(
    metric_retries=3, stale_depth_ttl=60.0)])
def test_keyboard_interrupt_from_metric_source_propagates(resilience):
    loop, source, _, _, _ = make_loop([KeyboardInterrupt()], resilience)
    with pytest.raises(KeyboardInterrupt):
        loop.run(max_ticks=1)
    assert source.calls == 1  # never retried, never stale-held


@pytest.mark.parametrize("resilience", [None, ResilienceConfig(
    scaler_retries=3, breaker_failures=5)])
def test_system_exit_from_scaler_propagates(resilience):
    loop, _, scaler, _, _ = make_loop(
        [500], resilience, up_outcomes=[SystemExit(3)]
    )
    with pytest.raises(SystemExit):
        loop.run(max_ticks=1)
    assert scaler.up_calls == 1  # never retried


def test_keyboard_interrupt_from_observer_propagates():
    class InterruptingObserver:
        def on_tick(self, record):
            raise KeyboardInterrupt()

    clock = FakeClock()
    loop = ControlLoop(
        ScriptedScaler(clock),
        ScriptedSource(clock, [1]),
        LoopConfig(poll_interval=1.0),
        clock=clock,
        observer=InterruptingObserver(),
    )
    with pytest.raises(KeyboardInterrupt):
        loop.run(max_ticks=1)


def test_keyboard_interrupt_through_multi_observer_propagates():
    seen = RecordingObserver()

    class InterruptingObserver:
        def on_tick(self, record):
            raise KeyboardInterrupt()

    clock = FakeClock()
    loop = ControlLoop(
        ScriptedScaler(clock),
        ScriptedSource(clock, [1]),
        LoopConfig(poll_interval=1.0),
        clock=clock,
        observer=MultiObserver([seen, InterruptingObserver()]),
    )
    with pytest.raises(KeyboardInterrupt):
        loop.run(max_ticks=1)
    assert len(seen.records) == 1  # earlier observers already ran


def test_ordinary_observer_exception_still_swallowed(caplog):
    class FailingObserver:
        def on_tick(self, record):
            raise RuntimeError("boom")

    clock = FakeClock()
    loop = ControlLoop(
        ScriptedScaler(clock),
        ScriptedSource(clock, [1, 2]),
        LoopConfig(poll_interval=1.0),
        clock=clock,
        observer=FailingObserver(),
    )
    with caplog.at_level(logging.ERROR):
        loop.run(max_ticks=2)  # the loop survives both ticks
    assert loop.ticks == 2


# --- record round-trip ------------------------------------------------------


def test_resilience_fields_roundtrip_and_stay_lean():
    record = TickRecord(
        start=1.0,
        num_messages=7,
        stale=True,
        stale_age_s=12.5,
        metric_retries=2,
        scaler_retries=1,
        breaker_state="half_open",
    )
    data = record.to_dict()
    assert data["stale"] is True and data["breaker_state"] == "half_open"
    assert TickRecord.from_dict(data) == record
    # a reference tick serializes exactly as before: no resilience keys
    plain = TickRecord(start=0.0, num_messages=3).to_dict()
    for key in ("stale", "stale_age_s", "metric_retries", "scaler_retries",
                "breaker_state"):
        assert key not in plain


def test_stale_record_journals_and_reads_back(tmp_path):
    from kube_sqs_autoscaler_tpu.obs.journal import TickJournal, read_journal

    path = str(tmp_path / "journal.jsonl")
    record = TickRecord(start=5.0, num_messages=200, stale=True,
                        stale_age_s=5.0, breaker_state="open")
    with TickJournal(path, meta={"resilience": {"stale_depth_ttl": 60.0}}) as j:
        j.on_tick(record)
    meta, records = read_journal(path)
    assert meta["resilience"]["stale_depth_ttl"] == 60.0
    assert records[0].stale is True
    assert records[0].breaker_state == "open"


# --- observability ----------------------------------------------------------


def _tick(start, **kwargs):
    return TickRecord(start=start, **kwargs)


def test_prometheus_resilience_metrics_render():
    from kube_sqs_autoscaler_tpu.obs.prometheus import ControllerMetrics

    metrics = ControllerMetrics(version="test")
    base = metrics.render()
    # counters render at zero; state/timestamp gauges wait for a value
    assert "stale_ticks_total 0" in base
    assert 'retries_total{call="metric"} 0' in base
    assert "consecutive_metric_failures 0" in base
    assert "breaker_state\n" not in base.replace("# TYPE", "#T")

    metrics.on_tick(_tick(0.0, num_messages=5, metric_retries=2,
                          breaker_state="closed"))
    metrics.on_tick(_tick(5.0, num_messages=5, stale=True, stale_age_s=5.0,
                          breaker_state="open", scaler_retries=1))
    metrics.on_tick(_tick(10.0, metric_error="dark", breaker_state="open"))
    text = metrics.render()
    assert "stale_ticks_total 1" in text
    assert 'retries_total{call="metric"} 2' in text
    assert 'retries_total{call="scaler"} 1' in text
    assert "breaker_state 2" in text  # open
    assert "consecutive_metric_failures 2" in text  # stale + fail-static
    assert "last_successful_poll_timestamp" in text
    # a fresh observation resets the consecutive gauge
    metrics.on_tick(_tick(15.0, num_messages=9, breaker_state="closed"))
    text = metrics.render()
    assert "consecutive_metric_failures 0" in text
    assert "breaker_state 0" in text


def test_prometheus_consecutive_scale_failures():
    from kube_sqs_autoscaler_tpu.obs.prometheus import ControllerMetrics

    metrics = ControllerMetrics(version="test")
    metrics.on_tick(_tick(0.0, num_messages=500, up=Gate.FIRE,
                          up_error="down"))
    metrics.on_tick(_tick(5.0, num_messages=500, up=Gate.FIRE,
                          up_error="down"))
    assert "consecutive_scale_failures 2" in metrics.render()
    metrics.on_tick(_tick(10.0, num_messages=500, up=Gate.FIRE))
    text = metrics.render()
    assert "consecutive_scale_failures 0" in text
    assert "last_successful_scale_timestamp" in text


def test_stale_ticks_do_not_count_as_observations():
    from kube_sqs_autoscaler_tpu.obs.prometheus import ControllerMetrics

    metrics = ControllerMetrics(version="test")
    metrics.on_tick(_tick(0.0, num_messages=100, stale=True))
    assert not metrics.ready  # a held depth is not a successful read
    assert "queue_messages 100" not in metrics.render()
    metrics.on_tick(_tick(5.0, num_messages=42))
    assert metrics.ready
    assert "queue_messages 42" in metrics.render()


def test_healthz_turns_503_when_ticks_stall():
    from kube_sqs_autoscaler_tpu.obs.prometheus import ControllerMetrics
    from kube_sqs_autoscaler_tpu.obs.server import ObservabilityServer

    metrics = ControllerMetrics(version="test")
    server = ObservabilityServer(
        metrics, host="127.0.0.1", port=0, unhealthy_after=30.0
    )
    server.start()
    try:
        url = f"http://127.0.0.1:{server.port}/healthz"
        with urllib.request.urlopen(url) as reply:
            assert reply.status == 200  # fresh registry: not yet stalled
        # simulate a wedged loop: last tick 100 wall-seconds ago
        metrics._last_tick_monotonic = time.monotonic() - 100.0
        with pytest.raises(urllib.error.HTTPError) as excinfo:
            urllib.request.urlopen(url)
        assert excinfo.value.code == 503
        assert "no tick progress" in excinfo.value.read().decode()
        metrics.on_tick(_tick(0.0, num_messages=1))  # progress: healthy again
        with urllib.request.urlopen(url) as reply:
            assert reply.status == 200
    finally:
        server.stop()


def test_healthz_threshold_zero_is_always_healthy():
    from kube_sqs_autoscaler_tpu.obs.prometheus import ControllerMetrics
    from kube_sqs_autoscaler_tpu.obs.server import ObservabilityServer

    metrics = ControllerMetrics(version="test")
    metrics._last_tick_monotonic = time.monotonic() - 1e6
    server = ObservabilityServer(metrics, host="127.0.0.1", port=0)
    server.start()
    try:
        url = f"http://127.0.0.1:{server.port}/healthz"
        with urllib.request.urlopen(url) as reply:
            assert reply.status == 200
    finally:
        server.stop()


# --- CLI --------------------------------------------------------------------


def test_cli_defaults_keep_reference_behavior():
    from kube_sqs_autoscaler_tpu.cli import build_parser, resilience_from_args

    args = build_parser().parse_args([])
    config = resilience_from_args(args)
    assert not config.enabled
    assert args.healthz_stale_after == 0.0


def test_cli_resilience_flags_parse_and_wire():
    from kube_sqs_autoscaler_tpu.cli import build_parser, resilience_from_args

    args = build_parser().parse_args([
        "--metric-retries", "3",
        "--metric-timeout", "2s",
        "--scaler-retries", "1",
        "--scaler-timeout", "1500ms",
        "--breaker-failures", "5",
        "--breaker-reset", "45s",
        "--stale-depth-ttl", "2m",
        "--healthz-stale-after", "1m",
    ])
    config = resilience_from_args(args)
    assert config.enabled
    assert config.metric_retries == 3
    assert config.metric_timeout == 2.0
    assert config.scaler_retries == 1
    assert config.scaler_timeout == 1.5
    assert config.breaker_failures == 5
    assert config.breaker_reset == 45.0
    assert config.stale_depth_ttl == 120.0
    assert args.healthz_stale_after == 60.0


def test_cli_rejects_negative_retries(capsys):
    from kube_sqs_autoscaler_tpu.cli import build_parser

    with pytest.raises(SystemExit):
        build_parser().parse_args(["--metric-retries", "-1"])
    assert "must be >= 0" in capsys.readouterr().err


def test_cli_healthz_threshold_must_exceed_poll_period(capsys):
    # sleep-first loop: at most one tick per poll period, so a staleness
    # threshold <= the period would 503 a healthy controller between
    # ticks — reject the combination at startup
    from kube_sqs_autoscaler_tpu.cli import (
        build_parser,
        validate_flag_interactions,
    )

    parser = build_parser()
    bad = parser.parse_args(
        ["--poll-period", "5m", "--healthz-stale-after", "60s"]
    )
    with pytest.raises(SystemExit):
        validate_flag_interactions(parser, bad)
    assert "must exceed --poll-period" in capsys.readouterr().err
    good = parser.parse_args(
        ["--poll-period", "5s", "--healthz-stale-after", "60s"]
    )
    validate_flag_interactions(parser, good)  # no error
    validate_flag_interactions(parser, parser.parse_args([]))  # defaults off
