"""Pipeline parallelism correctness: the pp-sharded stack must reproduce
the plain dense forward exactly, obey the GPipe schedule, and the pp x dp
train step must compile over the mesh and learn.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kube_sqs_autoscaler_tpu.workloads.model import (
    ModelConfig,
    forward,
    init_params,
)
from kube_sqs_autoscaler_tpu.workloads.pipeline import (
    PipelineConfig,
    init_pipeline_params,
    init_pipeline_train_state,
    make_pipeline_mesh,
    make_pipeline_train_step,
    pipeline_batch_sharding,
    pipeline_forward,
    pipeline_loss_fn,
    place_pipeline_state,
    stack_layers,
)
from kube_sqs_autoscaler_tpu.workloads.train import TrainConfig

# fp32 so the pipeline/dense comparison is exact (no bf16 rounding skew)
TINY = ModelConfig(
    vocab_size=256, d_model=64, n_heads=4, n_layers=4, d_ff=128,
    max_seq_len=64, dtype=jnp.float32,
)


def microtokens(m=4, bm=2, seq=16, seed=1):
    # bm must be divisible by the mesh's "data" axis size
    return jax.random.randint(
        jax.random.key(seed), (m, bm, seq), 0, TINY.vocab_size, jnp.int32
    )


def as_pipeline_params(params):
    stacked = dict(params)
    stacked["stages"] = stack_layers(params)
    del stacked["layers"]
    return stacked


@pytest.mark.parametrize("pipe", [2, 4])
def test_pipeline_forward_matches_dense(pipe):
    mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=pipe)
    params = init_params(jax.random.key(0), TINY)
    bm = mesh.shape["data"]
    tokens = microtokens(bm=bm)
    dense = forward(params, tokens.reshape(4 * bm, 16), TINY)

    pcfg = PipelineConfig(n_microbatches=4)
    piped = jax.jit(
        lambda p, t: pipeline_forward(p, t, TINY, pcfg, mesh)
    )(as_pipeline_params(params), jax.device_put(tokens, pipeline_batch_sharding(mesh)))
    np.testing.assert_allclose(
        np.asarray(dense),
        np.asarray(piped).reshape(4 * bm, 16, TINY.vocab_size),
        rtol=1e-4, atol=1e-4,
    )


def test_pipeline_forward_matches_dense_pp2_sp2():
    # ring attention inside the pipeline stages: pp x dp x sp
    mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=2,
                              seq_parallel=2)
    params = init_params(jax.random.key(0), TINY)
    bm = mesh.shape["data"]
    tokens = microtokens(bm=bm)
    dense = forward(params, tokens.reshape(4 * bm, 16), TINY)

    pcfg = PipelineConfig(n_microbatches=4)
    piped = jax.jit(
        lambda p, t: pipeline_forward(p, t, TINY, pcfg, mesh)
    )(as_pipeline_params(params),
      jax.device_put(tokens, pipeline_batch_sharding(mesh)))
    np.testing.assert_allclose(
        np.asarray(dense),
        np.asarray(piped).reshape(4 * bm, 16, TINY.vocab_size),
        rtol=1e-4, atol=1e-4,
    )


def test_1f1b_grads_match_gpipe_autodiff_pp2_sp2():
    # 1F1B composes with sequence parallelism: ring attention inside the
    # stage fwd/bwd and the sequence-sharded loss head must reproduce
    # autodiff of the GPipe loss on the same pp2 x dp2 x sp2 mesh exactly
    # (fp32 so equality is tight)
    from kube_sqs_autoscaler_tpu.workloads.pipeline import (
        one_f_one_b_value_and_grad,
    )

    mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=2,
                              seq_parallel=2)
    params = as_pipeline_params(init_params(jax.random.key(0), TINY))
    pcfg = PipelineConfig(n_microbatches=4, schedule="1f1b")
    tokens = jax.device_put(
        microtokens(bm=mesh.shape["data"]), pipeline_batch_sharding(mesh)
    )

    gpipe_cfg = PipelineConfig(n_microbatches=4)
    ref_loss, ref_grads = jax.jit(
        jax.value_and_grad(
            lambda p, t: pipeline_loss_fn(p, t, TINY, gpipe_cfg, mesh)
        )
    )(params, tokens)
    loss, grads = jax.jit(
        lambda p, t: one_f_one_b_value_and_grad(p, t, TINY, pcfg, mesh)
    )(params, tokens)

    assert float(loss) == pytest.approx(float(ref_loss), rel=1e-5)
    flat_ref = jax.tree_util.tree_leaves_with_path(ref_grads)
    flat = dict(
        (jax.tree_util.keystr(k), v)
        for k, v in jax.tree_util.tree_leaves_with_path(grads)
    )
    for key, ref in flat_ref:
        name = jax.tree_util.keystr(key)
        np.testing.assert_allclose(
            np.asarray(flat[name], np.float32), np.asarray(ref, np.float32),
            rtol=2e-4, atol=2e-6, err_msg=name,
        )


def test_1f1b_sp_trains_from_the_trainer():
    # the flag composition end to end: pp2 x sp2 x 1f1b learns
    from kube_sqs_autoscaler_tpu.workloads.trainer import main

    result = main([
        "--vocab-size", "256", "--d-model", "64", "--n-heads", "4",
        "--n-layers", "4", "--d-ff", "128", "--seq-len", "32",
        "--batch-size", "8", "--learning-rate", "1e-2", "--log-every", "1",
        "--pipe-parallel", "2", "--pipe-microbatches", "2",
        "--pipe-schedule", "1f1b", "--seq-parallel", "2",
        "--steps", "4", "--overfit",
    ])
    assert result["final_step"] == 4
    losses = result["losses"]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_zigzag_pipeline_loss_equals_plain():
    # pp x zigzag: the load-balanced permuted-order objective inside the
    # GPipe stages is the SAME loss as the natural-order pipeline loss
    # (the permutation reorders terms of one mean) — both families
    from kube_sqs_autoscaler_tpu.workloads.llama import (
        LlamaConfig,
        init_llama_params,
    )
    from kube_sqs_autoscaler_tpu.workloads.pipeline import (
        as_llama_pipeline_params,
        llama_pipeline_loss_fn,
        zigzag_pipeline_loss_fn,
    )

    mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=2,
                              seq_parallel=2)
    pcfg = PipelineConfig(n_microbatches=2)
    tokens = jax.device_put(
        microtokens(m=2, bm=mesh.shape["data"]),
        pipeline_batch_sharding(mesh),
    )
    params = as_pipeline_params(init_params(jax.random.key(0), TINY))
    plain = float(jax.jit(
        lambda p, t: pipeline_loss_fn(p, t, TINY, pcfg, mesh)
    )(params, tokens))
    zz = float(jax.jit(
        lambda p, t: zigzag_pipeline_loss_fn(p, t, TINY, pcfg, mesh)
    )(params, tokens))
    assert zz == pytest.approx(plain, rel=1e-5)

    lt = LlamaConfig(
        vocab_size=256, d_model=64, n_heads=4, n_kv_heads=2, n_layers=4,
        d_ff=128, max_seq_len=64, dtype=jnp.float32,
    )
    lp = as_llama_pipeline_params(init_llama_params(jax.random.key(0), lt))
    lplain = float(jax.jit(
        lambda p, t: llama_pipeline_loss_fn(p, t, lt, pcfg, mesh)
    )(lp, tokens))
    lzz = float(jax.jit(
        lambda p, t: zigzag_pipeline_loss_fn(p, t, lt, pcfg, mesh,
                                             llama=True)
    )(lp, tokens))
    assert lzz == pytest.approx(lplain, rel=1e-5)


def test_zigzag_pipeline_trains_from_the_trainer():
    # the flag composition end to end: pp2 x sp2 x zigzag learns, evals
    from kube_sqs_autoscaler_tpu.workloads.trainer import main

    result = main([
        "--vocab-size", "256", "--d-model", "64", "--n-heads", "4",
        "--n-layers", "4", "--d-ff", "128", "--seq-len", "32",
        "--batch-size", "8", "--learning-rate", "1e-2", "--log-every", "1",
        "--pipe-parallel", "2", "--pipe-microbatches", "2",
        "--seq-parallel", "2", "--zigzag",
        "--steps", "4", "--overfit",
        "--eval-every", "4", "--eval-batches", "2",
    ])
    assert result["final_step"] == 4
    losses = result["losses"]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]

    # the combos the objective cannot express fail fast
    import pytest as _pytest
    base = ["--vocab-size", "256", "--d-model", "64", "--n-heads", "4",
            "--n-layers", "4", "--d-ff", "128", "--seq-len", "32",
            "--batch-size", "8", "--steps", "1",
            "--pipe-parallel", "2", "--zigzag"]
    with _pytest.raises(SystemExit, match="seq-parallel"):
        main(base)
    with _pytest.raises(SystemExit, match="moe"):
        main(base + ["--seq-parallel", "2", "--moe"])
    # round-5 lift: --zigzag --pipe-schedule 1f1b trains (the explicit
    # backward with the permuted-validity loss seam; pinned equal to
    # GPipe zig-zag in test_pipeline_4axis)
    result = main(base + ["--seq-parallel", "2", "--pipe-schedule",
                          "1f1b", "--pipe-microbatches", "2",
                          "--overfit", "--learning-rate", "1e-2"])
    assert result["final_step"] == 1


def test_pipeline_microbatches_are_independent():
    # perturbing microbatch 3 must not change microbatch 0's logits
    mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=4)
    params = as_pipeline_params(init_params(jax.random.key(0), TINY))
    pcfg = PipelineConfig(n_microbatches=4)
    fn = jax.jit(lambda p, t: pipeline_forward(p, t, TINY, pcfg, mesh))
    tokens = microtokens()
    base = np.asarray(fn(params, tokens))
    perturbed = tokens.at[3].set((tokens[3] + 1) % TINY.vocab_size)
    pert = np.asarray(fn(params, perturbed))
    np.testing.assert_array_equal(base[0], pert[0])
    assert not np.allclose(base[3], pert[3])


def test_stage_assignment_is_contiguous_layer_order():
    params = init_pipeline_params(jax.random.key(0), TINY, n_stages=2)
    unstacked = init_params(jax.random.key(0), TINY)
    # stacked[i] must be layer i — pipeline placement depends on the order;
    # the stage layout splits the fused wqkv into wq/wk/wv (column blocks)
    for i in range(TINY.n_layers):
        fused = np.asarray(unstacked["layers"][i]["wqkv"])
        d = TINY.d_model
        np.testing.assert_array_equal(
            np.asarray(params["stages"]["wq"][i]), fused[:, :d]
        )
        np.testing.assert_array_equal(
            np.asarray(params["stages"]["wk"][i]), fused[:, d:2 * d]
        )
        np.testing.assert_array_equal(
            np.asarray(params["stages"]["wv"][i]), fused[:, 2 * d:]
        )


def test_layers_must_divide_stages():
    cfg = ModelConfig(n_layers=3)
    with pytest.raises(ValueError, match="divisible"):
        init_pipeline_params(jax.random.key(0), cfg, n_stages=2)


def test_microbatch_count_mismatch_raises():
    mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=2)
    params = as_pipeline_params(init_params(jax.random.key(0), TINY))
    with pytest.raises(ValueError, match="microbatches"):
        pipeline_forward(
            params, microtokens(m=2), TINY, PipelineConfig(n_microbatches=4),
            mesh,
        )


def test_pipeline_train_step_learns_pp4_dp2():
    mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=4)
    assert mesh.shape == {"pipe": 4, "data": 2}
    pcfg = PipelineConfig(n_microbatches=4)
    train_config = TrainConfig(learning_rate=1e-2)
    state = place_pipeline_state(
        mesh,
        init_pipeline_train_state(jax.random.key(0), TINY, train_config,
                                  n_stages=4),
    )
    step_fn = make_pipeline_train_step(mesh, TINY, pcfg, train_config, state)
    tokens = jax.device_put(microtokens(), pipeline_batch_sharding(mesh))
    losses = []
    for _ in range(4):
        state, loss = step_fn(state, tokens)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_pipeline_loss_matches_dense_loss():
    from kube_sqs_autoscaler_tpu.workloads.train import loss_fn

    mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=2)
    params = init_params(jax.random.key(0), TINY)
    tokens = microtokens(bm=4)
    dense = float(loss_fn(params, tokens.reshape(16, 16), TINY))
    piped = float(
        pipeline_loss_fn(
            as_pipeline_params(params), tokens, TINY,
            PipelineConfig(n_microbatches=4), mesh,
        )
    )
    assert piped == pytest.approx(dense, rel=1e-5)


def test_pipeline_remat_matches_plain_loss_and_learns():
    # TrainConfig(remat=True) is honored (per-layer jax.checkpoint inside
    # the stage scan): same loss values as the plain step
    mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=2)  # data=4
    pcfg = PipelineConfig(n_microbatches=2)
    tokens = jax.device_put(microtokens(m=2, bm=4),
                            pipeline_batch_sharding(mesh))

    losses = {}
    for remat in (False, True):
        train_config = TrainConfig(learning_rate=1e-2, remat=remat)
        state = place_pipeline_state(
            mesh,
            init_pipeline_train_state(jax.random.key(0), TINY, train_config,
                                      n_stages=2),
        )
        step_fn = make_pipeline_train_step(mesh, TINY, pcfg, train_config,
                                           state)
        run = []
        for _ in range(2):
            state, loss = step_fn(state, tokens)
            run.append(float(loss))
        losses[remat] = run
    np.testing.assert_allclose(losses[False], losses[True], rtol=1e-5)


# bf16 is the PRODUCTION dtype (ModelConfig default) — the round-2
# regression aborted XLA only at bf16, which an fp32-only suite never saw.
# Every schedule must compile, run, and learn at both dtypes.
TINY_BF16 = ModelConfig(
    vocab_size=256, d_model=64, n_heads=4, n_layers=4, d_ff=128,
    max_seq_len=64,
)
assert TINY_BF16.dtype == jnp.bfloat16


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
@pytest.mark.parametrize("cfg", [TINY, TINY_BF16], ids=["fp32", "bf16"])
def test_pipeline_train_step_learns_both_dtypes(schedule, cfg):
    mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=2)
    pcfg = PipelineConfig(n_microbatches=4, schedule=schedule)
    train_config = TrainConfig(learning_rate=1e-2)
    state = place_pipeline_state(
        mesh,
        init_pipeline_train_state(jax.random.key(0), cfg, train_config,
                                  n_stages=2),
    )
    step_fn = make_pipeline_train_step(mesh, cfg, pcfg, train_config, state)
    tokens = jax.device_put(microtokens(bm=4), pipeline_batch_sharding(mesh))
    losses = []
    for _ in range(4):
        state, loss = step_fn(state, tokens)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


# ---------------------------------------------------------------- 1F1B


def _check_schedule_tables(n_stages, n_micro):
    from kube_sqs_autoscaler_tpu.workloads.pipeline import one_f_one_b_schedule

    fwd, bwd = one_f_one_b_schedule(n_stages, n_micro)
    assert fwd.shape == bwd.shape
    T = fwd.shape[0]
    fwd_done = np.full((n_stages, n_micro), -1)
    bwd_done = np.full((n_stages, n_micro), -1)
    for t in range(T):
        for s in range(n_stages):
            m = fwd[t, s]
            if m >= 0:
                assert fwd_done[s, m] == -1, "fwd ran twice"
                # in order per stage
                assert (fwd_done[s, :m] >= 0).all()
                if s > 0:
                    assert 0 <= fwd_done[s - 1, m] < t, "fwd dep violated"
                fwd_done[s, m] = t
            m = bwd[t, s]
            if m >= 0:
                assert bwd_done[s, m] == -1, "bwd ran twice"
                assert (bwd_done[s, :m] >= 0).all()
                assert 0 <= fwd_done[s, m] <= t, "bwd before own fwd"
                if s < n_stages - 1:
                    assert 0 <= bwd_done[s + 1, m] < t, "bwd dep violated"
                bwd_done[s, m] = t
        # 1F1B memory discipline: per stage, in-flight microbatches
        # (forwarded but not yet backwarded) never exceed min(M, P - s)
        for s in range(n_stages):
            in_flight = ((fwd_done[s] >= 0) & (bwd_done[s] == -1)).sum()
            assert in_flight <= min(n_micro, n_stages - s)
    assert (fwd_done >= 0).all(), "some fwd never ran"
    assert (bwd_done >= 0).all(), "some bwd never ran"


@pytest.mark.parametrize(
    "n_stages,n_micro",
    [(4, 2), (4, 4), (4, 8), (2, 1), (2, 6), (8, 3)],
    ids=["M<P", "M=P", "M>P", "m1", "p2m6", "p8m3"],
)
def test_1f1b_schedule_table_properties(n_stages, n_micro):
    _check_schedule_tables(n_stages, n_micro)


@pytest.mark.parametrize("pipe,bm", [(2, 4), (4, 2)])
def test_1f1b_grads_match_gpipe_autodiff(pipe, bm):
    # the claim in one_f_one_b_value_and_grad's docstring: gradient-equal
    # to jax.value_and_grad(pipeline_loss_fn).  fp32 so equality is tight.
    from kube_sqs_autoscaler_tpu.workloads.pipeline import (
        one_f_one_b_value_and_grad,
    )

    mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=pipe)
    params = as_pipeline_params(init_params(jax.random.key(0), TINY))
    pcfg = PipelineConfig(n_microbatches=4, schedule="1f1b")
    tokens = jax.device_put(microtokens(bm=bm), pipeline_batch_sharding(mesh))

    ref_loss, ref_grads = jax.jit(
        jax.value_and_grad(
            lambda p, t: pipeline_loss_fn(p, t, TINY, pcfg, mesh)
        )
    )(params, tokens)
    loss, grads = jax.jit(
        lambda p, t: one_f_one_b_value_and_grad(p, t, TINY, pcfg, mesh)
    )(params, tokens)

    assert float(loss) == pytest.approx(float(ref_loss), rel=1e-5)
    flat_ref = jax.tree_util.tree_leaves_with_path(ref_grads)
    flat = dict(
        (jax.tree_util.keystr(k), v)
        for k, v in jax.tree_util.tree_leaves_with_path(grads)
    )
    for key, ref in flat_ref:
        name = jax.tree_util.keystr(key)
        np.testing.assert_allclose(
            np.asarray(flat[name], np.float32), np.asarray(ref, np.float32),
            rtol=2e-4, atol=2e-6, err_msg=name,
        )


# ------------------------------------------------------- pp x dp x tp


def test_pipeline_forward_matches_dense_pp2_tp2():
    # fully-manual Megatron tp inside the pipeline body: pp2 x dp2 x tp2
    mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=2,
                              model_parallel=2)
    assert mesh.shape == {"pipe": 2, "data": 2, "model": 2}
    params = init_params(jax.random.key(0), TINY)
    tokens = microtokens(bm=2)
    dense = forward(params, tokens.reshape(8, 16), TINY)
    pcfg = PipelineConfig(n_microbatches=4)
    piped = jax.jit(
        lambda p, t: pipeline_forward(p, t, TINY, pcfg, mesh)
    )(as_pipeline_params(params),
      jax.device_put(tokens, pipeline_batch_sharding(mesh)))
    np.testing.assert_allclose(
        np.asarray(dense),
        np.asarray(piped).reshape(8, 16, TINY.vocab_size),
        rtol=1e-4, atol=1e-4,
    )


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_pipeline_train_step_learns_pp2_tp2_bf16(schedule):
    mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=2,
                              model_parallel=2)
    pcfg = PipelineConfig(n_microbatches=2, schedule=schedule)
    train_config = TrainConfig(learning_rate=1e-2)
    state = place_pipeline_state(
        mesh,
        init_pipeline_train_state(jax.random.key(0), TINY_BF16, train_config,
                                  n_stages=2),
    )
    step_fn = make_pipeline_train_step(
        mesh, TINY_BF16, pcfg, train_config, state
    )
    tokens = jax.device_put(microtokens(m=2, bm=2),
                            pipeline_batch_sharding(mesh))
    losses = []
    for _ in range(4):
        state, loss = step_fn(state, tokens)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_1f1b_grads_match_autodiff_pp2_tp2():
    from kube_sqs_autoscaler_tpu.workloads.pipeline import (
        one_f_one_b_value_and_grad,
    )

    mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=2,
                              model_parallel=2)
    params = as_pipeline_params(init_params(jax.random.key(0), TINY))
    pcfg = PipelineConfig(n_microbatches=2, schedule="1f1b")
    tokens = jax.device_put(microtokens(m=2, bm=2),
                            pipeline_batch_sharding(mesh))
    ref_loss, ref_grads = jax.jit(
        jax.value_and_grad(
            lambda p, t: pipeline_loss_fn(p, t, TINY, pcfg, mesh)
        )
    )(params, tokens)
    loss, grads = jax.jit(
        lambda p, t: one_f_one_b_value_and_grad(p, t, TINY, pcfg, mesh)
    )(params, tokens)
    assert float(loss) == pytest.approx(float(ref_loss), rel=1e-5)
    ref_leaves = jax.tree_util.tree_leaves_with_path(ref_grads)
    got = dict(
        (jax.tree_util.keystr(k), v)
        for k, v in jax.tree_util.tree_leaves_with_path(grads)
    )
    for key, ref in ref_leaves:
        name = jax.tree_util.keystr(key)
        np.testing.assert_allclose(
            np.asarray(got[name], np.float32), np.asarray(ref, np.float32),
            rtol=2e-4, atol=2e-6, err_msg=name,
        )


def test_gpipe_tp_grads_match_no_tp_truth():
    # differentiating the fully-manual tp body must give the SAME grads as
    # the well-tested pp-only mesh (guards the boundary-conjugate
    # conventions in pipeline._gpipe_tp_boundary against jax changes)
    pcfg = PipelineConfig(n_microbatches=2)
    params = as_pipeline_params(init_params(jax.random.key(0), TINY))
    tokens = microtokens(m=2, bm=2)

    mesh_truth = make_pipeline_mesh(jax.devices()[:4], pipe_parallel=2)
    mesh_tp = make_pipeline_mesh(jax.devices(), pipe_parallel=2,
                                 model_parallel=2)
    grads = {}
    for tag, mesh in [("truth", mesh_truth), ("tp", mesh_tp)]:
        t = jax.device_put(tokens, pipeline_batch_sharding(mesh))
        _, g = jax.jit(
            jax.value_and_grad(
                lambda p, tt, mesh=mesh: pipeline_loss_fn(
                    p, tt, TINY, pcfg, mesh
                )
            )
        )(params, t)
        grads[tag] = g
    flat_truth = jax.tree_util.tree_leaves_with_path(grads["truth"])
    flat_tp = dict(
        (jax.tree_util.keystr(k), v)
        for k, v in jax.tree_util.tree_leaves_with_path(grads["tp"])
    )
    for key, ref in flat_truth:
        name = jax.tree_util.keystr(key)
        np.testing.assert_allclose(
            np.asarray(flat_tp[name], np.float32),
            np.asarray(ref, np.float32),
            rtol=2e-4, atol=2e-6, err_msg=name,
        )


def test_1f1b_remat_matches_plain_loss_and_learns():
    # remat through the explicitly-scheduled backward: same losses as the
    # non-remat 1F1B step (stage-granular recompute changes memory only)
    mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=2)
    pcfg = PipelineConfig(n_microbatches=2, schedule="1f1b")
    tokens = jax.device_put(microtokens(m=2, bm=4),
                            pipeline_batch_sharding(mesh))
    losses = {}
    for remat in (False, True):
        train_config = TrainConfig(learning_rate=1e-2, remat=remat)
        state = place_pipeline_state(
            mesh,
            init_pipeline_train_state(jax.random.key(0), TINY, train_config,
                                      n_stages=2),
        )
        step_fn = make_pipeline_train_step(mesh, TINY, pcfg, train_config,
                                           state)
        run = []
        for _ in range(2):
            state, loss = step_fn(state, tokens)
            run.append(float(loss))
        losses[remat] = run
    np.testing.assert_allclose(losses[False], losses[True], rtol=1e-5)


def test_pipeline_with_flash_kernel_stage_attention():
    # the stage_attention seam: run the Pallas kernel (interpret mode) as
    # the per-stage attention inside BOTH pipelined bodies on CPU — the
    # combination that otherwise only exists on real TPU
    import functools

    from kube_sqs_autoscaler_tpu.workloads.flash import flash_attention
    from kube_sqs_autoscaler_tpu.workloads.pipeline import (
        one_f_one_b_value_and_grad,
    )

    flash_interpret = functools.partial(flash_attention, interpret=True)
    mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=2)
    params = as_pipeline_params(init_params(jax.random.key(0), TINY))
    pcfg = PipelineConfig(n_microbatches=2)
    tokens = jax.device_put(microtokens(m=2, bm=4),
                            pipeline_batch_sharding(mesh))

    dense_loss, dense_grads = jax.jit(
        jax.value_and_grad(
            lambda p, t: pipeline_loss_fn(p, t, TINY, pcfg, mesh)
        )
    )(params, tokens)
    flash_loss, flash_grads = jax.jit(
        jax.value_and_grad(
            lambda p, t: pipeline_loss_fn(
                p, t, TINY, pcfg, mesh, stage_attention=flash_interpret
            )
        )
    )(params, tokens)
    assert float(flash_loss) == pytest.approx(float(dense_loss), rel=1e-5)
    for (k1, g), (k2, e) in zip(
        sorted(
            (jax.tree_util.keystr(k), v) for k, v in
            jax.tree_util.tree_leaves_with_path(flash_grads)
        ),
        sorted(
            (jax.tree_util.keystr(k), v) for k, v in
            jax.tree_util.tree_leaves_with_path(dense_grads)
        ),
    ):
        np.testing.assert_allclose(
            np.asarray(g, np.float32), np.asarray(e, np.float32),
            rtol=5e-4, atol=1e-5, err_msg=k1,
        )

    # the explicitly-scheduled 1F1B backward through the kernel's custom
    # vjp (and its remat recompute) agrees too
    fcfg = PipelineConfig(n_microbatches=2, schedule="1f1b")
    loss_1f1b, grads_1f1b = jax.jit(
        lambda p, t: one_f_one_b_value_and_grad(
            p, t, TINY, fcfg, mesh, remat=True,
            stage_attention=flash_interpret,
        )
    )(params, tokens)
    assert float(loss_1f1b) == pytest.approx(float(dense_loss), rel=1e-5)
