"""Pipeline parallelism correctness: the pp-sharded stack must reproduce
the plain dense forward exactly, obey the GPipe schedule, and the pp x dp
train step must compile over the mesh and learn.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kube_sqs_autoscaler_tpu.workloads.model import (
    ModelConfig,
    forward,
    init_params,
)
from kube_sqs_autoscaler_tpu.workloads.pipeline import (
    PipelineConfig,
    init_pipeline_params,
    init_pipeline_train_state,
    make_pipeline_mesh,
    make_pipeline_train_step,
    pipeline_batch_sharding,
    pipeline_forward,
    pipeline_loss_fn,
    place_pipeline_state,
    stack_layers,
)
from kube_sqs_autoscaler_tpu.workloads.train import TrainConfig

# fp32 so the pipeline/dense comparison is exact (no bf16 rounding skew)
TINY = ModelConfig(
    vocab_size=256, d_model=64, n_heads=4, n_layers=4, d_ff=128,
    max_seq_len=64, dtype=jnp.float32,
)


def microtokens(m=4, bm=2, seq=16, seed=1):
    # bm must be divisible by the mesh's "data" axis size
    return jax.random.randint(
        jax.random.key(seed), (m, bm, seq), 0, TINY.vocab_size, jnp.int32
    )


def as_pipeline_params(params):
    stacked = dict(params)
    stacked["stages"] = stack_layers(params)
    del stacked["layers"]
    return stacked


@pytest.mark.parametrize("pipe", [2, 4])
def test_pipeline_forward_matches_dense(pipe):
    mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=pipe)
    params = init_params(jax.random.key(0), TINY)
    bm = mesh.shape["data"]
    tokens = microtokens(bm=bm)
    dense = forward(params, tokens.reshape(4 * bm, 16), TINY)

    pcfg = PipelineConfig(n_microbatches=4)
    piped = jax.jit(
        lambda p, t: pipeline_forward(p, t, TINY, pcfg, mesh)
    )(as_pipeline_params(params), jax.device_put(tokens, pipeline_batch_sharding(mesh)))
    np.testing.assert_allclose(
        np.asarray(dense),
        np.asarray(piped).reshape(4 * bm, 16, TINY.vocab_size),
        rtol=1e-4, atol=1e-4,
    )


def test_pipeline_microbatches_are_independent():
    # perturbing microbatch 3 must not change microbatch 0's logits
    mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=4)
    params = as_pipeline_params(init_params(jax.random.key(0), TINY))
    pcfg = PipelineConfig(n_microbatches=4)
    fn = jax.jit(lambda p, t: pipeline_forward(p, t, TINY, pcfg, mesh))
    tokens = microtokens()
    base = np.asarray(fn(params, tokens))
    perturbed = tokens.at[3].set((tokens[3] + 1) % TINY.vocab_size)
    pert = np.asarray(fn(params, perturbed))
    np.testing.assert_array_equal(base[0], pert[0])
    assert not np.allclose(base[3], pert[3])


def test_stage_assignment_is_contiguous_layer_order():
    params = init_pipeline_params(jax.random.key(0), TINY, n_stages=2)
    unstacked = init_params(jax.random.key(0), TINY)
    # stacked[i] must be layer i — pipeline placement depends on the order
    for i in range(TINY.n_layers):
        np.testing.assert_array_equal(
            np.asarray(params["stages"]["wqkv"][i]),
            np.asarray(unstacked["layers"][i]["wqkv"]),
        )


def test_layers_must_divide_stages():
    cfg = ModelConfig(n_layers=3)
    with pytest.raises(ValueError, match="divisible"):
        init_pipeline_params(jax.random.key(0), cfg, n_stages=2)


def test_microbatch_count_mismatch_raises():
    mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=2)
    params = as_pipeline_params(init_params(jax.random.key(0), TINY))
    with pytest.raises(ValueError, match="microbatches"):
        pipeline_forward(
            params, microtokens(m=2), TINY, PipelineConfig(n_microbatches=4),
            mesh,
        )


def test_pipeline_train_step_learns_pp4_dp2():
    mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=4)
    assert mesh.shape == {"pipe": 4, "data": 2}
    pcfg = PipelineConfig(n_microbatches=4)
    train_config = TrainConfig(learning_rate=1e-2)
    state = place_pipeline_state(
        mesh,
        init_pipeline_train_state(jax.random.key(0), TINY, train_config,
                                  n_stages=4),
    )
    step_fn = make_pipeline_train_step(mesh, TINY, pcfg, train_config, state)
    tokens = jax.device_put(microtokens(), pipeline_batch_sharding(mesh))
    losses = []
    for _ in range(4):
        state, loss = step_fn(state, tokens)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_pipeline_loss_matches_dense_loss():
    from kube_sqs_autoscaler_tpu.workloads.train import loss_fn

    mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=2)
    params = init_params(jax.random.key(0), TINY)
    tokens = microtokens(bm=4)
    dense = float(loss_fn(params, tokens.reshape(16, 16), TINY))
    piped = float(
        pipeline_loss_fn(
            as_pipeline_params(params), tokens, TINY,
            PipelineConfig(n_microbatches=4), mesh,
        )
    )
    assert piped == pytest.approx(dense, rel=1e-5)


def test_pipeline_remat_matches_plain_loss_and_learns():
    # TrainConfig(remat=True) is honored (per-layer jax.checkpoint inside
    # the stage scan): same loss values as the plain step
    mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=2)  # data=4
    pcfg = PipelineConfig(n_microbatches=2)
    tokens = jax.device_put(microtokens(m=2, bm=4),
                            pipeline_batch_sharding(mesh))

    losses = {}
    for remat in (False, True):
        train_config = TrainConfig(learning_rate=1e-2, remat=remat)
        state = place_pipeline_state(
            mesh,
            init_pipeline_train_state(jax.random.key(0), TINY, train_config,
                                      n_stages=2),
        )
        step_fn = make_pipeline_train_step(mesh, TINY, pcfg, train_config,
                                           state)
        run = []
        for _ in range(2):
            state, loss = step_fn(state, tokens)
            run.append(float(loss))
        losses[remat] = run
    np.testing.assert_allclose(losses[False], losses[True], rtol=1e-5)
