"""The trainer binary end-to-end on the virtual mesh: losses fall,
checkpoints land, resume continues the step count, and the zig-zag /
remat / accumulation flags all drive the same loop.
"""

import numpy as np
import pytest

from kube_sqs_autoscaler_tpu.workloads.trainer import main

TINY_FLAGS = [
    "--vocab-size", "256", "--d-model", "64", "--n-heads", "4",
    "--n-layers", "2", "--d-ff", "128", "--seq-len", "32",
    "--batch-size", "8", "--learning-rate", "1e-2", "--log-every", "1",
]


def test_trainer_runs_and_learns():
    # --overfit repeats one batch: on fresh random batches the loss floor
    # is log(vocab) (nothing to learn), so learning is only observable by
    # memorization — the standard stack smoke test
    result = main(TINY_FLAGS + ["--steps", "6", "--model-parallel", "2",
                                "--seq-parallel", "2", "--overfit"])
    assert result["final_step"] == 6
    losses = result["losses"]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_trainer_checkpoints_and_resumes(tmp_path):
    ckpt = str(tmp_path / "ckpt")
    first = main(TINY_FLAGS + ["--steps", "4", "--checkpoint-dir", ckpt,
                               "--checkpoint-every", "2"])
    assert first["final_step"] == 4

    resumed = main(TINY_FLAGS + ["--steps", "3", "--checkpoint-dir", ckpt,
                                 "--resume"])
    assert resumed["final_step"] == 7  # continued, not restarted

    fresh = main(TINY_FLAGS + ["--steps", "2", "--checkpoint-dir",
                               str(tmp_path / "other")])
    assert fresh["final_step"] == 2

    # dirty dir without --resume fails fast, before any training
    with pytest.raises(SystemExit, match="--resume"):
        main(TINY_FLAGS + ["--steps", "2", "--checkpoint-dir", ckpt])


def test_trainer_zigzag_remat_accum_flags():
    result = main(
        TINY_FLAGS
        + ["--steps", "4", "--seq-parallel", "4", "--zigzag", "--remat",
           "--grad-accum", "2", "--warmup-steps", "1", "--decay-steps", "10"]
    )
    assert result["final_step"] == 4
    assert all(np.isfinite(result["losses"]))


def test_trainer_llama_family_learns():
    result = main(TINY_FLAGS + ["--steps", "5", "--family", "llama",
                                "--n-kv-heads", "2", "--model-parallel", "2",
                                "--overfit", "--remat"])
    assert result["final_step"] == 5
    assert all(np.isfinite(result["losses"]))
    assert result["losses"][-1] < result["losses"][0]


def test_trainer_llama_seq_parallel_trains():
    # GQA ring attention from the binary: llama + sp2 x tp2 on the
    # virtual mesh learns under --overfit
    result = main(TINY_FLAGS + ["--steps", "4", "--family", "llama",
                                "--model-parallel", "2",
                                "--seq-parallel", "2", "--overfit"])
    assert result["final_step"] == 4
    losses = result["losses"]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_trainer_llama_zigzag_trains():
    # balanced zig-zag schedule with GQA (compact k/v rotation): llama +
    # --zigzag learns under --overfit
    result = main(TINY_FLAGS + ["--steps", "4", "--family", "llama",
                                "--model-parallel", "2",
                                "--seq-parallel", "2", "--zigzag",
                                "--overfit"])
    assert result["final_step"] == 4
    losses = result["losses"]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_trainer_profile_writes_trace(tmp_path):
    result = main(TINY_FLAGS + ["--steps", "2",
                                "--profile-dir", str(tmp_path)])
    assert result["final_step"] == 2
    assert any(p.is_file() for p in tmp_path.rglob("*")), "no trace written"


def test_trainer_pipeline_gpipe_learns():
    # pp from the binary: pp2 x dp4 mesh, GPipe schedule
    result = main(TINY_FLAGS + ["--steps", "4", "--pipe-parallel", "2",
                                "--pipe-microbatches", "2", "--overfit"])
    assert result["final_step"] == 4
    losses = result["losses"]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_trainer_pipeline_1f1b_with_tp_learns():
    # pp2 x dp2 x tp2 + the explicitly-scheduled 1F1B backward
    result = main(TINY_FLAGS + ["--steps", "4", "--pipe-parallel", "2",
                                "--model-parallel", "2",
                                "--pipe-schedule", "1f1b",
                                "--pipe-microbatches", "2", "--overfit"])
    assert result["final_step"] == 4
    losses = result["losses"]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_trainer_llama_pipeline_learns():
    # the modern family pipelined from the binary: llama x pp2, 1F1B,
    # with gradient accumulation over the batch axis
    # batch 16: 2 pipeline microbatches x 2 accum chunks x dp4
    result = main(TINY_FLAGS + ["--steps", "4", "--family", "llama",
                                "--n-kv-heads", "2", "--pipe-parallel", "2",
                                "--pipe-schedule", "1f1b",
                                "--pipe-microbatches", "2",
                                "--batch-size", "16",
                                "--grad-accum", "2", "--overfit"])
    assert result["final_step"] == 4
    losses = result["losses"]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_trainer_pipeline_checkpoints_and_resumes(tmp_path):
    # stage-stacked states restore with the pipeline sharding rules (the
    # flat PARAM_AXES rules would mis-place the leading layer axis)
    ckpt = str(tmp_path / "ckpt")
    pp = ["--pipe-parallel", "2", "--pipe-microbatches", "2"]
    first = main(TINY_FLAGS + pp + ["--steps", "4", "--checkpoint-dir",
                                    ckpt, "--checkpoint-every", "2"])
    assert first["final_step"] == 4
    resumed = main(TINY_FLAGS + pp + ["--steps", "3", "--checkpoint-dir",
                                      ckpt, "--resume"])
    assert resumed["final_step"] == 7


def test_trainer_pipeline_seq_parallel_learns():
    # pp x sp from the binary: ring attention inside the stages (the
    # 1f1b schedule composes too — tests/test_pipeline.py runs it; here
    # the gpipe default plus the tp/sp exclusivity check)
    result = main(TINY_FLAGS + ["--steps", "4", "--pipe-parallel", "2",
                                "--pipe-microbatches", "2",
                                "--seq-parallel", "2", "--overfit"])
    assert result["final_step"] == 4
    losses = result["losses"]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]

    # round-5 lift: --pipe-parallel takes --model-parallel AND
    # --seq-parallel together (the 4-axis mesh; trained end to end by
    # test_pipeline_4axis::test_trainer_binary_4axis)


def test_trainer_pipeline_topology_mesh_learns():
    # pp over the topology-ordered ("pipe","data") mesh: stage i and
    # stage i+1 as physical neighbors (trivial on the CPU mesh, but the
    # construction path is the same one TPU hardware takes)
    result = main(TINY_FLAGS + ["--steps", "4", "--pipe-parallel", "2",
                                "--pipe-microbatches", "2",
                                "--topology-mesh", "--overfit"])
    assert result["final_step"] == 4
    losses = result["losses"]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_trainer_pipeline_flag_conflicts_fail_fast():
    with pytest.raises(SystemExit, match="--zigzag"):
        main(TINY_FLAGS + ["--steps", "1", "--pipe-parallel", "2",
                           "--seq-parallel", "1", "--zigzag"])
    # moe x pp x tp composes since round 5 (tests/test_moe.py trains
    # it end to end); the microbatch divisibility check still fails fast
    with pytest.raises(SystemExit, match="not divisible"):
        main(TINY_FLAGS + ["--steps", "1", "--pipe-parallel", "2",
                           "--pipe-microbatches", "3"])


def test_trainer_moe_learns():
    # ep from the binary: top-2 routed expert MLP over the data axis
    result = main(TINY_FLAGS + ["--steps", "4", "--moe",
                                "--moe-experts", "4", "--moe-top-k", "2",
                                "--model-parallel", "2", "--overfit"])
    assert result["final_step"] == 4
    losses = result["losses"]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_trainer_eval_loop(caplog):
    """--eval-every evaluates a fixed held-out set (no update) for both
    the full and LoRA paths."""
    import logging

    with caplog.at_level(logging.INFO):
        result = main(TINY_FLAGS + ["--steps", "4", "--eval-every", "2",
                                    "--eval-batches", "2"])
    assert result["final_step"] == 4
    evals = [r for r in caplog.records if "eval_loss" in r.getMessage()]
    assert len(evals) == 2  # steps 2 and 4

    caplog.clear()
    with caplog.at_level(logging.INFO):
        main(TINY_FLAGS + ["--steps", "2", "--eval-every", "2",
                           "--lora-rank", "2"])
    assert any("eval_loss" in r.getMessage() for r in caplog.records)

    with pytest.raises(SystemExit, match="eval-batches"):
        main(TINY_FLAGS + ["--steps", "1", "--eval-every", "1",
                           "--eval-batches", "0"])


@pytest.mark.parametrize(
    "extra",
    [
        ["--moe"],
        ["--seq-parallel", "2", "--zigzag"],
        ["--pipe-parallel", "2", "--pipe-microbatches", "2"],
        ["--family", "llama", "--n-kv-heads", "2", "--pipe-parallel", "2",
         "--pipe-microbatches", "2", "--pipe-schedule", "1f1b"],
        ["--family", "llama", "--n-kv-heads", "2", "--moe"],
        ["--family", "llama", "--n-kv-heads", "2", "--seq-parallel", "2",
         "--zigzag"],
    ],
    ids=["moe", "zigzag", "pp", "llama-pp-1f1b", "llama-moe",
         "llama-zigzag"],
)
def test_trainer_eval_under_every_layout(extra, caplog):
    """VERDICT r3 #7: --eval-every works for moe/zigzag/pp (both
    families) — an eval loss only dense configs can compute cannot steer
    the configs that matter."""
    import logging

    with caplog.at_level(logging.INFO):
        result = main(TINY_FLAGS + ["--steps", "2", "--eval-every", "2",
                                    "--eval-batches", "2"] + extra)
    assert result["final_step"] == 2
    evals = [r for r in caplog.records if "eval_loss" in r.getMessage()]
    assert len(evals) == 1
    # the eval loss is a real finite number
    assert "eval_loss nan" not in evals[0].getMessage()
