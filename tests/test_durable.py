"""Durable control-plane state (ISSUE 14, ``core/durable.py``).

Covers the snapshot store's crash-safety edges (torn/corrupt/future-
schema fallback-to-cold, wall-clock TTL expiry, atomic replace), the
time-rebasing arithmetic across a monotonic-clock reset, the write-ahead
actuation intent, every subsystem's export/import round trip (reply
registry bitwise, resilience/breaker, forecaster ring, DRR/EDF
accounting, flood classifier, overload ladder, sticky homes, learned
mirror), the loop integration (snapshot-per-tick, byte-identity with
durability off, crash points), journal restart-header stitching, the
/healthz rehydrating state, and the restart bench smoke.
"""

from __future__ import annotations

import dataclasses
import json
import os

import pytest

from kube_sqs_autoscaler_tpu.core.clock import FakeClock
from kube_sqs_autoscaler_tpu.core.durable import (
    SNAPSHOT_SCHEMA_VERSION,
    ControllerCrash,
    DurableStateStore,
    _content_hash,
)
from kube_sqs_autoscaler_tpu.core.events import TickRecord
from kube_sqs_autoscaler_tpu.core.loop import ControlLoop, LoopConfig
from kube_sqs_autoscaler_tpu.core.policy import Gate, PolicyConfig, PolicyState
from kube_sqs_autoscaler_tpu.core.resilience import (
    ResilienceConfig,
    ResiliencePolicy,
)
from kube_sqs_autoscaler_tpu.forecast.history import DepthHistory
from kube_sqs_autoscaler_tpu.metrics.fake import FakeQueueService
from kube_sqs_autoscaler_tpu.metrics.queue import QueueMetricSource
from kube_sqs_autoscaler_tpu.scale.actuator import PodAutoScaler
from kube_sqs_autoscaler_tpu.scale.fake import FakeDeploymentAPI
from kube_sqs_autoscaler_tpu.sim.faults import (
    CRASH_AFTER_ACTUATE,
    CRASH_AFTER_DECIDE,
    CRASH_AFTER_OBSERVE,
    CRASH_POINTS,
    CRASH_TORN_JOURNAL,
    CrashingJournal,
    CrashingMetricSource,
    CrashingScaler,
    CrashPlan,
)


def _store(path, clock, **kwargs) -> DurableStateStore:
    return DurableStateStore(str(path), wall_clock=clock.now, **kwargs)


class _DictProvider:
    """Minimal StateProvider for store-level tests."""

    def __init__(self, payload=None, records=1):
        self.payload = dict(payload or {})
        self.records = records
        self.imported = None
        self.import_kwargs = None

    def export_state(self):
        return {"records": self.records, **self.payload}

    def import_state(self, state, *, rebase=0.0, now=None, max_age_s=0.0):
        self.imported = dict(state)
        self.import_kwargs = {
            "rebase": rebase, "now": now, "max_age_s": max_age_s
        }
        return int(state.get("records", 0))


# ---------------------------------------------------------------------------
# Store crash-safety edges
# ---------------------------------------------------------------------------


def test_snapshot_round_trip_warm(tmp_path):
    clock = FakeClock(100.0)
    store = _store(tmp_path / "s.json", clock)
    provider = _DictProvider({"x": 7}, records=3)
    store.register("sec", provider)
    store.snapshot(
        clock_now=clock.now(),
        policy_state=PolicyState(last_scale_up=90.0, last_scale_down=40.0),
    )
    assert store.snapshots_written == 1
    assert store.snapshot_hash
    assert not os.path.exists(str(tmp_path / "s.json") + ".tmp")

    clock.advance(25.0)  # downtime on the shared wall clock
    boot2 = _store(tmp_path / "s.json", clock)
    p2 = _DictProvider()
    boot2.register("sec", p2)
    report = boot2.rehydrate(clock.now())
    assert not report.cold_start
    assert report.records_recovered == 3
    assert report.records_expired == 0
    assert report.snapshot_age_s == pytest.approx(25.0)
    assert report.restarts == 1
    assert p2.imported["x"] == 7
    # shared continuing clock: zero rebase, stamps stay absolute
    assert p2.import_kwargs["rebase"] == pytest.approx(0.0)
    state = boot2.restored_policy_state()
    assert state == PolicyState(last_scale_up=90.0, last_scale_down=40.0)
    # memoized: one boot rehydrates once
    assert boot2.rehydrate(clock.now()) is report


def test_rebase_across_monotonic_reset(tmp_path):
    # boot 1 runs on a clock at 500; boot 2's monotonic clock restarts
    # at 3 — only the shared wall clock knows 20s of downtime passed
    wall = FakeClock(1000.0)
    store = DurableStateStore(str(tmp_path / "s.json"), wall_clock=wall.now)
    history = DepthHistory(capacity=8)
    history.observe(490.0, 50.0)
    history.observe(495.0, 60.0)
    store.register("hist", history)
    store.snapshot(
        clock_now=500.0,
        policy_state=PolicyState(last_scale_up=480.0, last_scale_down=470.0),
    )
    wall.advance(20.0)  # the pod was down 20 wall seconds
    boot2 = DurableStateStore(str(tmp_path / "s.json"), wall_clock=wall.now)
    h2 = DepthHistory(capacity=8)
    boot2.register("hist", h2)
    boot2.rehydrate(3.0)  # fresh monotonic clock
    # rebase = (3 - 20) - 500 = -517: t=480 -> -37 (37s before "now - 20s
    # ago" ... i.e. the stamp is 20 + (500-480) = 40s in the past)
    state = boot2.restored_policy_state()
    assert state.last_scale_up == pytest.approx(3.0 - 20.0 - 20.0)
    assert state.last_scale_down == pytest.approx(3.0 - 20.0 - 30.0)
    times, depths, n = h2.snapshot()
    assert n == 2
    # the newest sample was 5s old at save + 20s downtime = 25s old
    assert times[1] == pytest.approx(3.0 - 25.0)
    assert depths[1] == pytest.approx(60.0)


@pytest.mark.parametrize("corruption", [
    "torn", "not-json", "wrong-kind", "future-schema", "hash-mismatch",
])
def test_refusals_cold_start_never_raise(tmp_path, corruption):
    clock = FakeClock(10.0)
    path = tmp_path / "s.json"
    store = _store(path, clock)
    store.snapshot(clock_now=10.0,
                   policy_state=PolicyState(5.0, 5.0))
    raw = path.read_text()
    if corruption == "torn":
        path.write_text(raw[: len(raw) // 2])
    elif corruption == "not-json":
        path.write_text("!!not json!!")
    elif corruption == "wrong-kind":
        path.write_text('{"kind": "something-else", "schema": 1}')
    elif corruption == "future-schema":
        body = json.loads(raw)
        body["schema"] = SNAPSHOT_SCHEMA_VERSION + 3
        body["hash"] = _content_hash(body)
        path.write_text(json.dumps(body))
    elif corruption == "hash-mismatch":
        body = json.loads(raw)
        body["policy"]["last_scale_up"] = 999.0  # tampered, hash stale
        path.write_text(json.dumps(body))
    boot2 = _store(path, clock)
    report = boot2.rehydrate(clock.now())
    assert report.cold_start
    assert report.reason  # every refusal names itself
    assert boot2.restored_policy_state() is None
    # a refused file still counts the restart (the pod DID come back)
    assert report.restarts == 1


def test_refused_snapshot_still_counts_the_restart_chain(tmp_path):
    # a corrupt predecessor must not reset restart monotonicity: the
    # cold boot's own snapshots carry restarts=1, so the NEXT restart
    # reports #2, not #1 again
    clock = FakeClock(0.0)
    path = tmp_path / "s.json"
    path.write_text("!!corrupt!!")
    boot = _store(path, clock)
    assert boot.rehydrate(clock.now()).restarts == 1
    boot.snapshot(clock_now=0.0, policy_state=PolicyState(0.0, 0.0))
    boot2 = _store(path, clock)
    assert boot2.rehydrate(clock.now()).restarts == 2


def test_second_episode_gets_fresh_grace_not_restored_stamps(tmp_path):
    # run() -> stop -> run() on a durable loop: the restored stamps
    # belong to the FIRST post-boot episode only; a second episode is
    # fresh (reference startup grace), per run()'s contract
    clock = FakeClock(0.0)
    store = _store(tmp_path / "s.json", clock)
    store.snapshot(clock_now=0.0,
                   policy_state=PolicyState(-100.0, -100.0))
    clock.advance(5.0)
    boot2 = _store(tmp_path / "s.json", clock)
    loop, _, api = _scripted_loop(tmp_path, clock, durable=False)
    loop.durable = boot2
    first = loop.initial_policy_state()
    assert first == PolicyState(-100.0, -100.0)  # restored, expired stamps
    second = loop.initial_policy_state()
    assert second == PolicyState(clock.now(), clock.now())  # fresh grace


def test_missing_snapshot_is_silent_cold_start(tmp_path):
    clock = FakeClock()
    store = _store(tmp_path / "absent.json", clock)
    report = store.rehydrate(clock.now())
    assert report.cold_start
    assert report.reason is None
    assert report.restarts == 0


def test_whole_snapshot_max_age_cold_start(tmp_path):
    clock = FakeClock(0.0)
    store = _store(tmp_path / "s.json", clock, max_age_s=60.0)
    store.snapshot(clock_now=0.0, policy_state=PolicyState(0.0, 0.0))
    clock.advance(61.0)
    boot2 = _store(tmp_path / "s.json", clock, max_age_s=60.0)
    report = boot2.rehydrate(clock.now())
    assert report.cold_start
    assert "old" in report.reason


def test_snapshot_older_than_every_section_ttl_expires_everything(tmp_path):
    clock = FakeClock(0.0)
    store = _store(tmp_path / "s.json", clock)
    store.register("a", _DictProvider(records=4), ttl_s=30.0)
    store.register("b", _DictProvider(records=2), ttl_s=50.0)
    store.snapshot(clock_now=0.0, policy_state=PolicyState(0.0, 0.0))
    clock.advance(120.0)  # past BOTH TTLs
    boot2 = _store(tmp_path / "s.json", clock)
    pa, pb = _DictProvider(), _DictProvider()
    boot2.register("a", pa, ttl_s=30.0)
    boot2.register("b", pb, ttl_s=50.0)
    report = boot2.rehydrate(clock.now())
    assert not report.cold_start  # the snapshot itself is fine
    assert report.records_recovered == 0
    assert report.records_expired == 6
    assert sorted(report.sections_expired) == ["a", "b"]
    assert pa.imported is None and pb.imported is None
    # ... and the cooldown stamps still rebased (they expire through the
    # ordinary gate arithmetic, not a TTL)
    assert boot2.restored_policy_state() is not None


def test_broken_exporter_does_not_kill_snapshot(tmp_path):
    class Broken:
        def export_state(self):
            raise RuntimeError("boom")

    clock = FakeClock(5.0)
    store = _store(tmp_path / "s.json", clock)
    store.register("broken", Broken())
    store.register("ok", _DictProvider(records=1))
    store.snapshot(clock_now=5.0, policy_state=PolicyState(1.0, 1.0))
    boot2 = _store(tmp_path / "s.json", clock)
    ok = _DictProvider()
    boot2.register("ok", ok)
    report = boot2.rehydrate(clock.now())
    assert not report.cold_start
    assert ok.imported is not None


def test_duplicate_section_and_bad_ttl_rejected(tmp_path):
    clock = FakeClock()
    store = _store(tmp_path / "s.json", clock)
    store.register("a", _DictProvider())
    with pytest.raises(ValueError):
        store.register("a", _DictProvider())
    with pytest.raises(ValueError):
        store.register("b", _DictProvider(), ttl_s=-1.0)


# ---------------------------------------------------------------------------
# Write-ahead actuation intent
# ---------------------------------------------------------------------------


def test_unresolved_intent_advances_stamp(tmp_path):
    clock = FakeClock(0.0)
    store = _store(tmp_path / "s.json", clock)
    clock.advance(50.0)
    store.snapshot(clock_now=50.0, policy_state=PolicyState(30.0, 10.0))
    clock.advance(5.0)  # the crashed tick ran at t=55
    store.note_intent("up", 55.0)
    clock.advance(10.0)  # downtime
    boot2 = _store(tmp_path / "s.json", clock)
    report = boot2.rehydrate(clock.now())
    assert report.intent_applied == "up"
    state = boot2.restored_policy_state()
    assert state.last_scale_up == pytest.approx(55.0)  # advanced
    assert state.last_scale_down == pytest.approx(10.0)  # untouched
    # NOT consumed yet: the advanced stamp is only in memory until this
    # boot's first snapshot — a second crash before that must find the
    # intent again (double-crash window)
    assert os.path.exists(store.intent_path)
    clock.advance(1.0)
    boot2.snapshot(clock_now=clock.now(), policy_state=state)
    assert not os.path.exists(store.intent_path)  # now covered


def test_intent_survives_a_double_crash(tmp_path):
    # boot 1 actuates at t=55 and dies with only the intent as
    # evidence; boot 2 rehydrates but dies BEFORE its first snapshot;
    # boot 3 must still see the intent and keep the stamp at 55
    clock = FakeClock(0.0)
    store = _store(tmp_path / "s.json", clock)
    clock.advance(50.0)
    store.snapshot(clock_now=50.0, policy_state=PolicyState(30.0, 10.0))
    clock.advance(5.0)
    store.note_intent("up", 55.0)
    clock.advance(10.0)
    boot2 = _store(tmp_path / "s.json", clock)
    assert boot2.rehydrate(clock.now()).intent_applied == "up"
    # boot 2 dies here: no tick, no snapshot
    clock.advance(10.0)
    boot3 = _store(tmp_path / "s.json", clock)
    assert boot3.rehydrate(clock.now()).intent_applied == "up"
    assert boot3.restored_policy_state().last_scale_up == pytest.approx(55.0)


def test_snapshot_clears_intent(tmp_path):
    clock = FakeClock(20.0)
    store = _store(tmp_path / "s.json", clock)
    store.note_intent("down", 20.0)
    assert os.path.exists(store.intent_path)
    clock.advance(1.0)
    store.snapshot(clock_now=21.0, policy_state=PolicyState(21.0, 21.0))
    assert not os.path.exists(store.intent_path)


def test_stale_intent_ignored(tmp_path):
    # an intent OLDER than the snapshot was resolved by it; a leftover
    # file (failed remove) must not advance anything
    clock = FakeClock(0.0)
    store = _store(tmp_path / "s.json", clock)
    store.note_intent("up", 5.0)  # wall 0
    clock.advance(30.0)
    # snapshot at wall 30 — strictly newer than the intent's wall 0
    body_state = PolicyState(8.0, 8.0)
    store.snapshot(clock_now=30.0, policy_state=body_state)
    # resurrect a stale intent file bitwise (snapshot removed it)
    with open(store.intent_path, "w") as fh:
        json.dump({"kind": "actuation-intent", "direction": "up",
                   "clock": 5.0, "wall": 0.0}, fh)
    boot2 = _store(tmp_path / "s.json", clock)
    report = boot2.rehydrate(clock.now())
    assert report.intent_applied is None
    assert boot2.restored_policy_state() == body_state


def test_intent_rejects_bad_direction(tmp_path):
    store = _store(tmp_path / "s.json", FakeClock())
    with pytest.raises(ValueError):
        store.note_intent("sideways", 0.0)


# ---------------------------------------------------------------------------
# Subsystem providers
# ---------------------------------------------------------------------------


def test_reply_registry_round_trip_bitwise():
    from kube_sqs_autoscaler_tpu.fleet.pool import FleetPoolBase

    a = FleetPoolBase(clock=FakeClock(), replied_capacity=8)
    for i in range(12):  # overflow the bound: 4 oldest evicted
        a.mark_replied(f"req-{i}")
    a.note_duplicate("req-11")
    exported = a.export_state()
    assert exported["records"] == 8

    b = FleetPoolBase(clock=FakeClock(), replied_capacity=8)
    assert b.import_state(exported) == 8
    assert b.export_state() == exported  # bitwise

    # continuation equivalence: adding the same new ids to both yields
    # the same membership and eviction state as never having restarted
    for pool in (a, b):
        for i in range(12, 15):
            pool.mark_replied(f"req-{i}")
    assert a.export_state() == b.export_state()
    assert not b.already_replied("req-6")  # evicted on both
    assert b.already_replied("req-14")


def test_resilience_provider_round_trip_and_breaker_rebase():
    clock = FakeClock(100.0)
    config = ResilienceConfig(breaker_failures=2, breaker_reset=40.0,
                              stale_depth_ttl=30.0)
    policy = ResiliencePolicy(config, clock, poll_interval=5.0)
    policy._last_good = (95.0, 123)
    policy.breaker.record_failure(90.0)
    policy.breaker.record_failure(96.0)  # opens at 96
    assert policy.breaker_state == "open"
    exported = policy.export_state()
    assert exported["records"] == 2

    clock2 = FakeClock(7.0)  # monotonic reset; 10s downtime -> rebase
    restored = ResiliencePolicy(config, clock2, poll_interval=5.0)
    rebase = (7.0 - 10.0) - 100.0
    assert restored.import_state(exported, rebase=rebase, now=7.0) == 2
    assert restored.breaker_state == "open"
    # opened 4s before save + 10s downtime = 14s ago; reset 40 -> probe
    # in 26s on the new clock
    assert restored.breaker.seconds_until_probe(7.0) == pytest.approx(26.0)
    held = restored.stale_depth(7.0)
    assert held is not None
    depth, age = held
    assert depth == 123
    assert age == pytest.approx(15.0)  # 5s old at save + 10s downtime
    # ... and past the TTL it expires through the ordinary check
    assert restored.stale_depth(7.0 + 16.0) is None


def test_resilience_refuses_open_breaker_without_timestamp():
    clock = FakeClock()
    config = ResilienceConfig(breaker_failures=2)
    policy = ResiliencePolicy(config, clock, poll_interval=5.0)
    restored = policy.import_state(
        {"breaker": {"state": "open", "failures": 3, "opened_at": None}}
    )
    assert restored == 0
    assert policy.breaker_state == "closed"


def test_history_provider_max_age_drops_stale_samples():
    h = DepthHistory(capacity=8)
    h.observe(10.0, 1.0)
    h.observe(50.0, 2.0)
    exported = h.export_state()
    h2 = DepthHistory(capacity=8)
    # now=100, max_age 60: the t=10 sample is 90s old -> dropped
    assert h2.import_state(exported, rebase=0.0, now=100.0,
                           max_age_s=60.0) == 1
    times, depths, n = h2.snapshot()
    assert n == 1 and depths[0] == 2.0


def test_drr_accounting_round_trip():
    from kube_sqs_autoscaler_tpu.workloads.tenancy import DeficitRoundRobin

    drr = DeficitRoundRobin(weight_of=lambda t: 2.0, quantum=1.0,
                            keep=("a", "b"), urgency_window_s=1.0,
                            urgency_budget=3.0)
    for i in range(5):
        drr.push("a", f"a{i}", deadline=0.5)
        drr.push("b", f"b{i}")
    drr.pick(3, now=0.0)  # spends credit + deficit
    exported = drr.export_state()
    assert exported["records"] >= 2

    drr2 = DeficitRoundRobin(weight_of=lambda t: 2.0, quantum=1.0,
                             keep=("a", "b"), urgency_window_s=1.0,
                             urgency_budget=3.0)
    assert drr2.import_state(exported) >= 2
    for t in ("a", "b"):
        assert drr2.deficit(t) == pytest.approx(drr.deficit(t))
        assert drr2._credit[t] == pytest.approx(drr._credit[t])
    assert drr2._cursor == drr._cursor
    assert drr2._rounds == pytest.approx(drr._rounds)
    # the restored scheduler picks identically on identical new streams
    for d in (drr, drr2):
        # fresh staged work (the old queues died with the process)
        for q in d._queues.values():
            q.clear()
        for i in range(4):
            d.push("a", f"na{i}")
            d.push("b", f"nb{i}")
    assert ([t for t, _ in drr.pick(4)]
            == [t for t, _ in drr2.pick(4)])


def test_fair_admission_flood_classification_survives_restart():
    from kube_sqs_autoscaler_tpu.workloads.tenancy import (
        FairAdmission,
        TenancyConfig,
    )

    tenancy = TenancyConfig(tenants=("flood", "victim"))
    fair = FairAdmission(tenancy, per_tenant_limit=8, total_limit=16)
    # a sustained flood: high unique-id offered rate
    for i in range(30):
        fair.stage("flood", f"item{i}", message_id=f"m{i}")
    fair.stage("victim", "v0", message_id="v0")
    assert "flood" in fair.over_share()
    exported = fair.export_state()

    restarted = FairAdmission(tenancy, per_tenant_limit=8, total_limit=16)
    assert restarted.import_state(exported) > 0
    # staging is EMPTY after restart (receipt handles died with the
    # process) — the restored classification must survive the
    # redelivery window regardless
    assert "flood" in restarted.over_share()
    # redelivered copies of already-counted messages are still deduped
    restarted._note_offered("flood", "m3")
    assert restarted.arrival_rate["flood"] == pytest.approx(
        fair.arrival_rate["flood"]
    )
    # the grace decays; with no backlog and a decayed rate the
    # classification eventually drops, exactly like a live drain
    for _ in range(restarted.STICKY_RESTORE_GRACE + 1):
        restarted.note_cycle()
    assert "flood" not in restarted.over_share()


def test_overload_ladder_round_trip():
    from kube_sqs_autoscaler_tpu.workloads.tenancy import OverloadLadder

    ladder = OverloadLadder(3)
    for pressure in (0.6, 0.8, 0.95, 0.97):
        ladder.update(pressure, now=0.0)
    assert ladder.tier >= 2
    exported = ladder.export_state()
    restored = OverloadLadder(3)
    assert restored.import_state(exported) == 1
    assert restored.tier == ladder.tier
    assert restored._ewma == pytest.approx(ladder._ewma)
    assert restored.entered_total == ladder.entered_total
    # hysteresis continues from the restored EWMA, not from scratch
    assert restored.update(ladder.last_pressure, now=0.0) == ladder.tier


def test_tenant_homes_round_trip_drops_out_of_range_shards():
    from collections import OrderedDict

    from kube_sqs_autoscaler_tpu.workloads.tenancy import (
        export_tenant_homes,
        import_tenant_homes,
    )

    homes = OrderedDict()
    homes[("acme", 123)] = 1
    homes[("globex", 456)] = 3
    exported = export_tenant_homes(homes)
    assert exported["records"] == 2

    restored = OrderedDict()
    # the restarted plane has only 2 shards: globex's home is gone
    assert import_tenant_homes(restored, exported, shards=2) == 1
    assert restored == OrderedDict({("acme", 123): 1})


def test_learned_mirror_round_trip_and_reconcile(tmp_path):
    pytest.importorskip("jax")
    from kube_sqs_autoscaler_tpu.learn.checkpoint import PolicyCheckpoint
    from kube_sqs_autoscaler_tpu.learn.network import param_count
    from kube_sqs_autoscaler_tpu.learn.policy import LearnedPolicy

    import numpy as np

    theta = np.zeros(param_count(8), dtype=np.float32)
    checkpoint = PolicyCheckpoint(theta=theta, hidden=8)
    policy_config = PolicyConfig()

    def make():
        return LearnedPolicy(
            checkpoint, policy=policy_config, poll_interval=5.0,
            max_pods=10, min_pods=1, initial_replicas=1,
        )

    a = make()
    a.replicas = 4
    a._last_up, a._last_down = 80.0, 60.0
    a.history.observe(70.0, 11.0)
    exported = a.export_state()

    b = make()
    assert b.import_state(exported, rebase=-10.0, now=90.0) >= 1
    assert b.replicas == 4
    assert b._last_up == pytest.approx(70.0)
    assert len(b.history) == 1
    # the observed world outranks the remembered trajectory
    b.reconcile_observed(2)
    assert b.replicas == 2
    b.reconcile_observed(99)
    assert b.replicas == 10  # clamped to max_pods

    # foreign weights: refuse the whole mirror
    other = PolicyCheckpoint(
        theta=np.ones(param_count(8), dtype=np.float32), hidden=8
    )
    c = LearnedPolicy(
        other, policy=policy_config, poll_interval=5.0,
        max_pods=10, min_pods=1, initial_replicas=1,
    )
    assert c.import_state(exported) == 0
    assert c.replicas == 1


# ---------------------------------------------------------------------------
# Loop integration
# ---------------------------------------------------------------------------


class _Collector:
    def __init__(self):
        self.records = []

    def on_tick(self, record):
        self.records.append(record.to_dict())


def _scripted_loop(tmp_path, clock, *, durable, collector=None,
                   depth=5000, suffix="s", api=None, queue=None):
    if api is None:
        api = FakeDeploymentAPI.with_deployments("default", 1, "workers")
    if queue is None:
        queue = FakeQueueService.with_depths(depth)
    store = None
    if durable:
        store = DurableStateStore(
            str(tmp_path / f"{suffix}.json"), wall_clock=clock.now
        )
    loop = ControlLoop(
        PodAutoScaler(client=api, max=10, min=1, scale_up_pods=1,
                      scale_down_pods=1, deployment="workers",
                      namespace="default"),
        QueueMetricSource(queue, "q://x",
                          ("ApproximateNumberOfMessages",)),
        LoopConfig(poll_interval=5.0, policy=PolicyConfig(
            scale_up_messages=100, scale_down_messages=-1,
            scale_up_cooldown=30.0, scale_down_cooldown=60.0,
        )),
        clock=clock,
        observer=collector,
        durable=store,
    )
    return loop, store, api


def test_loop_byte_identity_with_durability_off(tmp_path):
    runs = {}
    for durable in (False, True):
        clock = FakeClock()
        collector = _Collector()
        loop, _, _ = _scripted_loop(
            tmp_path, clock, durable=durable, collector=collector,
            suffix=f"ident-{durable}",
        )
        state = loop.initial_policy_state()
        for _ in range(10):
            clock.advance(5.0)
            state = loop.tick(state)
        runs[durable] = collector.records
    assert runs[True] == runs[False]


def test_loop_snapshots_every_tick_and_warm_restart(tmp_path):
    clock = FakeClock()
    loop, store, api = _scripted_loop(tmp_path, clock, durable=True)
    state = loop.initial_policy_state()
    for _ in range(7):  # ticks 5..35: fires at t=30 (grace end)
        clock.advance(5.0)
        state = loop.tick(state)
    assert store.snapshots_written == 7
    assert api.replicas("workers") == 2

    clock.advance(13.0)  # downtime
    loop2, store2, _ = _scripted_loop(tmp_path, clock, durable=True,
                                      api=api)
    state2 = loop2.initial_policy_state()
    assert not store2.last_report.cold_start
    # the restored stamp (t=30) cools the up gate until t=60: the tick
    # at t=53 must NOT fire despite the huge backlog
    clock.advance(5.0)  # t=53
    state2 = loop2.tick(state2)
    assert api.replicas("workers") == 2
    clock.advance(7.0)  # t=60: boundary fires
    loop2.tick(state2)
    assert api.replicas("workers") == 3


def test_crash_skips_observer_journal_and_snapshot(tmp_path):
    from kube_sqs_autoscaler_tpu.obs.journal import (
        TickJournal,
        read_journal_episodes,
    )

    clock = FakeClock()
    collector = _Collector()
    loop, store, api = _scripted_loop(
        tmp_path, clock, durable=True, collector=collector
    )
    plan = CrashPlan(crashes=((2, CRASH_AFTER_OBSERVE),))
    tick = {"i": -1}
    loop.metric_source = CrashingMetricSource(
        loop.metric_source, plan, lambda: tick["i"]
    )
    state = loop.initial_policy_state()
    for i in range(3):
        clock.advance(5.0)
        tick["i"] = i
        if i == 2:
            with pytest.raises(ControllerCrash):
                loop.tick(state)
        else:
            state = loop.tick(state)
    assert len(collector.records) == 2  # the crashed tick left nothing
    assert store.snapshots_written == 2

    # torn journal: the tick's record tears mid-line, the snapshot that
    # would follow never happens, and the next boot heals the tail
    journal = TickJournal(str(tmp_path / "j.jsonl"), meta={"m": 1})
    plan2 = CrashPlan(crashes=((0, CRASH_TORN_JOURNAL),))
    crasher = CrashingJournal(journal, plan2, lambda: 0)
    record = TickRecord(start=1.0, num_messages=5, up=Gate.IDLE,
                        down=Gate.IDLE)
    with pytest.raises(ControllerCrash):
        crasher.on_tick(record)
    journal.close()
    journal2 = TickJournal(str(tmp_path / "j.jsonl"), meta={"m": 2})
    journal2.on_tick(record)
    journal2.close()
    episodes = read_journal_episodes(str(tmp_path / "j.jsonl"))
    assert len(episodes) == 2  # torn fragment healed, both headers live
    assert len(episodes[1][1]) == 1


@pytest.mark.parametrize("point", [
    CRASH_AFTER_DECIDE, CRASH_AFTER_ACTUATE,
])
def test_actuation_crash_points_never_double_scale(tmp_path, point):
    clock = FakeClock()
    loop, store, api = _scripted_loop(tmp_path, clock, durable=True)
    plan = CrashPlan(crashes=((11, point),))  # t=60, a firing tick
    tick = {"i": -1}
    loop.scaler = CrashingScaler(loop.scaler, plan, lambda: tick["i"])
    scale_times = []
    real_update = api.update

    def tracked(deployment):
        scale_times.append(clock.now())
        return real_update(deployment)

    api.update = tracked
    state = loop.initial_policy_state()
    crashed = False
    for i in range(20):
        clock.advance(5.0)
        tick["i"] = i
        try:
            state = loop.tick(state)
        except ControllerCrash:
            crashed = True
            clock.advance(7.0)
            # the restarted boot actuates the SAME world (same recorder)
            loop, store, _api2 = _scripted_loop(tmp_path, clock,
                                                durable=True, api=api)
            state = loop.initial_policy_state()
    assert crashed
    gaps = [b - a for a, b in zip(scale_times, scale_times[1:])]
    assert all(g >= 30.0 - 1e-9 for g in gaps), gaps
    if point == CRASH_AFTER_ACTUATE:
        assert 60.0 in scale_times  # the crash tick really actuated
    else:
        assert 60.0 not in scale_times  # after-decide dies before it


def test_crash_plan_validation():
    with pytest.raises(ValueError):
        CrashPlan(crashes=((0, "nonsense"),))
    with pytest.raises(ValueError):
        CrashPlan(crashes=((-1, CRASH_AFTER_OBSERVE),))
    plan = CrashPlan(crashes=((3, CRASH_AFTER_OBSERVE),))
    assert plan.point_at(3) == CRASH_AFTER_OBSERVE
    assert plan.point_at(4) is None
    assert not plan.boundary_crash(3)


# ---------------------------------------------------------------------------
# Journal restart headers + stitching
# ---------------------------------------------------------------------------


def test_restart_journal_meta_and_stitch(tmp_path):
    from kube_sqs_autoscaler_tpu.obs.journal import TickJournal
    from kube_sqs_autoscaler_tpu.sim.replay import stitch_restart_episodes

    clock = FakeClock(0.0)
    path = str(tmp_path / "j.jsonl")
    store = _store(tmp_path / "s.json", clock, journal_path=path)
    journal = TickJournal(path, meta={"source": "test"})
    record = TickRecord(start=5.0, num_messages=500,
                        up=Gate.FIRE, down=Gate.SKIPPED)
    journal.on_tick(record)
    store.snapshot(clock_now=5.0, policy_state=PolicyState(5.0, 5.0),
                   last_tick_start=5.0)
    journal.close()

    clock.advance(9.0)
    boot2 = _store(tmp_path / "s.json", clock, journal_path=path)
    report = boot2.rehydrate(clock.now())
    assert not report.cold_start
    meta = boot2.restart_journal_meta()
    assert meta["snapshot_hash"] == report.snapshot_hash
    journal2 = TickJournal(path, meta={"source": "test", "restart": meta})
    journal2.on_tick(TickRecord(start=14.0, num_messages=480))
    journal2.close()

    stitches = stitch_restart_episodes(path)
    assert len(stitches) == 1
    stitch = stitches[0]
    assert stitch["snapshot_hash"] == report.snapshot_hash
    assert stitch["prior_ticks"] == 1
    assert stitch["prior_scaled_up"] == 1
    assert stitch["post_ticks"] == 1
    assert stitch["cold_start"] is False


def test_journal_tail_rehydration_advances_stamp(tmp_path):
    # the journal is one tick AHEAD of the snapshot (the snapshot write
    # crashed): the tail's successful scale-up must advance the stamp
    from kube_sqs_autoscaler_tpu.obs.journal import TickJournal

    clock = FakeClock(0.0)
    path = str(tmp_path / "j.jsonl")
    store = _store(tmp_path / "s.json", clock, journal_path=path)
    clock.advance(50.0)
    store.snapshot(clock_now=50.0, policy_state=PolicyState(30.0, 20.0),
                   last_tick_start=50.0)
    journal = TickJournal(path, meta={})
    journal.on_tick(TickRecord(start=55.0, num_messages=500,
                               up=Gate.FIRE, down=Gate.SKIPPED))
    journal.close()
    clock.advance(10.0)
    boot2 = _store(tmp_path / "s.json", clock, journal_path=path)
    report = boot2.rehydrate(clock.now())
    assert report.journal_tail_ticks == 1
    assert boot2.restored_policy_state().last_scale_up == pytest.approx(55.0)


# ---------------------------------------------------------------------------
# /healthz rehydrating + restart metrics
# ---------------------------------------------------------------------------


def test_metrics_rehydrating_and_restart_gauges():
    from kube_sqs_autoscaler_tpu.core.durable import RehydrationReport
    from kube_sqs_autoscaler_tpu.obs.prometheus import ControllerMetrics

    metrics = ControllerMetrics(version="t", policy="reactive")
    assert not metrics.rehydrating
    metrics.begin_rehydration()
    assert metrics.rehydrating
    metrics.set_rehydration(RehydrationReport(
        cold_start=False, snapshot_age_s=12.5, records_recovered=42,
        records_expired=3, restarts=2, duration_s=0.004,
    ))
    text = metrics.render()
    assert "controller_restarts_total 2" in text
    assert "snapshot_age_seconds 12.5" in text
    assert "state_records_recovered 42" in text
    assert "state_records_expired 3" in text
    assert "rehydration_duration_seconds 0.004" in text
    # the first completed tick clears the rehydrating state
    metrics.on_tick(TickRecord(start=0.0, num_messages=1))
    assert not metrics.rehydrating


def test_healthz_503_while_rehydrating():
    import urllib.error
    import urllib.request

    from kube_sqs_autoscaler_tpu.obs.prometheus import ControllerMetrics
    from kube_sqs_autoscaler_tpu.obs.server import ObservabilityServer

    metrics = ControllerMetrics(version="t")
    metrics.begin_rehydration()
    server = ObservabilityServer(metrics, host="127.0.0.1", port=0)
    server.start()
    try:
        url = f"http://127.0.0.1:{server.port}/healthz"
        ready_url = f"http://127.0.0.1:{server.port}/readyz"
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(url)
        assert err.value.code == 503
        assert "rehydrating" in err.value.read().decode()
        # readiness (the routing gate) names rehydration too
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(ready_url)
        assert err.value.code == 503
        assert "rehydrating" in err.value.read().decode()
        metrics.on_tick(TickRecord(start=0.0, num_messages=1))
        with urllib.request.urlopen(url) as response:
            assert response.status == 200
    finally:
        server.stop()


def test_debug_trace_serves_restart_instants(tmp_path):
    # the store's restart instants must actually REACH /debug/trace
    # (trace_sources wiring), in their own "restart" category
    import urllib.request

    from kube_sqs_autoscaler_tpu.obs.journal import TickRing
    from kube_sqs_autoscaler_tpu.obs.prometheus import ControllerMetrics
    from kube_sqs_autoscaler_tpu.obs.server import ObservabilityServer

    clock = FakeClock(2.0)
    store = _store(tmp_path / "s.json", clock)
    store.rehydrate(clock.now())
    ring = TickRing(capacity=8)
    ring.on_tick(TickRecord(start=5.0, num_messages=1))
    server = ObservabilityServer(
        ControllerMetrics(version="t"), host="127.0.0.1", port=0,
        ring=ring, trace_sources=(store,),
    )
    server.start()
    try:
        url = f"http://127.0.0.1:{server.port}/debug/trace"
        with urllib.request.urlopen(url) as response:
            trace = json.loads(response.read())
    finally:
        server.stop()
    restart_events = [
        e for e in trace["traceEvents"] if e.get("cat") == "restart"
    ]
    assert {e["name"] for e in restart_events} == {
        "restart-detected", "restart-rehydrated"
    }


def test_store_trace_events_have_restart_category(tmp_path):
    from kube_sqs_autoscaler_tpu.obs.trace import instant_trace_events

    clock = FakeClock(3.0)
    store = _store(tmp_path / "s.json", clock)
    store.rehydrate(clock.now())
    events = instant_trace_events(store.events)
    assert events
    assert {e["cat"] for e in events} == {"restart"}
    assert {e["name"] for e in events} == {
        "restart-detected", "restart-rehydrated"
    }


# ---------------------------------------------------------------------------
# CLI flags
# ---------------------------------------------------------------------------


def test_cli_state_flags():
    from kube_sqs_autoscaler_tpu.cli import (
        build_parser,
        validate_flag_interactions,
    )

    parser = build_parser()
    args = parser.parse_args(["--state-path", "/tmp/x.state",
                              "--state-max-age", "1h"])
    validate_flag_interactions(parser, args)
    assert args.state_path == "/tmp/x.state"
    assert args.state_max_age == 3600.0

    bad = parser.parse_args(["--state-max-age", "1h"])
    with pytest.raises(SystemExit):
        validate_flag_interactions(parser, bad)


# ---------------------------------------------------------------------------
# The restart bench: tier-1 smoke, full battery slow
# ---------------------------------------------------------------------------


def test_restart_bench_smoke(tmp_path):
    import bench

    out = tmp_path / "BENCH_restart.json"
    summary = bench.run_restart_suite(
        output=str(out),
        control_points=(CRASH_AFTER_ACTUATE,),
        fleet_points=(CRASH_AFTER_ACTUATE,),
    )
    assert summary["metric"] == "restart_duplicate_replies_prevented"
    artifact = json.loads(out.read_text())
    assert artifact["suite"] == "restart"
    battery = artifact["crash_battery"][CRASH_AFTER_ACTUATE]
    assert battery["crashes"] == 1
    assert battery["warm"]
    assert all(g >= 30.0 for g in battery["cooldown_gaps"])
    fleet = artifact["fleet"]["episodes"][CRASH_AFTER_ACTUATE]
    assert fleet["duplicate_replies"] == 0
    assert fleet["lost"] == 0
    assert artifact["fleet"]["cold_contrast"]["duplicate_replies"] >= 1
    assert artifact["warm_vs_cold"]["byte_identical_when_off"]


@pytest.mark.slow
def test_restart_bench_full_battery(tmp_path):
    import bench

    out = tmp_path / "BENCH_restart_full.json"
    summary = bench.run_restart_suite(output=str(out))
    assert summary["value"] >= 1  # the cold contrast really duplicates
    artifact = json.loads(out.read_text())
    assert set(artifact["crash_battery"]) == set(CRASH_POINTS)
    assert set(artifact["fleet"]["episodes"]) == set(CRASH_POINTS)
    for point, episode in artifact["fleet"]["episodes"].items():
        assert episode["duplicate_replies"] == 0, point
        assert episode["lost"] == 0, point
        assert episode["crashes"] == 1, point
    forecaster = artifact["forecaster"]
    assert (forecaster["warm"]["post_restart_max_depth"]
            < forecaster["cold"]["post_restart_max_depth"])
