"""Llama family: RoPE math, GQA equivalence with MHA, RMSNorm/SwiGLU
forward, sharded training, and GQA KV-cache decode matching the full
forward position by position.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kube_sqs_autoscaler_tpu.workloads.llama import (
    LlamaConfig,
    apply_rope,
    init_llama_params,
    init_llama_train_state,
    llama_decode_step,
    llama_forward,
    llama_generate_jit,
    llama_loss_fn,
    llama_prefill,
    make_llama_train_step,
    repeat_kv,
)
from kube_sqs_autoscaler_tpu.workloads.train import (
    TrainConfig,
    batch_sharding,
    make_mesh,
    place_state,
)

TINY = LlamaConfig(
    vocab_size=256, d_model=64, n_heads=4, n_kv_heads=2, n_layers=2,
    d_ff=128, max_seq_len=64, dtype=jnp.float32,
)


def tokens_batch(batch=2, seq=16, seed=1):
    return jax.random.randint(
        jax.random.key(seed), (batch, seq), 0, TINY.vocab_size, jnp.int32
    )


def test_config_validation():
    with pytest.raises(ValueError, match="n_kv_heads"):
        LlamaConfig(n_heads=8, n_kv_heads=3)
    with pytest.raises(ValueError, match="divisible"):
        LlamaConfig(d_model=100, n_heads=8, n_kv_heads=2)


def test_rope_preserves_norm_and_relative_phase():
    x = jax.random.normal(jax.random.key(0), (1, 2, 8, 16), jnp.float32)
    positions = jnp.arange(8)
    rotated = apply_rope(x, positions, 10_000.0)
    # rotation is norm-preserving per pair
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(x), axis=-1),
        np.linalg.norm(np.asarray(rotated), axis=-1),
        rtol=1e-5,
    )
    # position 0 is the identity rotation
    np.testing.assert_allclose(
        np.asarray(x[:, :, 0]), np.asarray(rotated[:, :, 0]), rtol=1e-6
    )
    # relative property: dot(q_i, k_j) depends only on i-j after rotation
    q = jax.random.normal(jax.random.key(1), (1, 1, 1, 16), jnp.float32)
    k = jax.random.normal(jax.random.key(2), (1, 1, 1, 16), jnp.float32)
    def score(qpos, kpos):
        qr = apply_rope(q, jnp.array([qpos]), 10_000.0)
        kr = apply_rope(k, jnp.array([kpos]), 10_000.0)
        return float(jnp.sum(qr * kr))
    assert score(5, 3) == pytest.approx(score(9, 7), rel=1e-4)
    assert score(5, 3) != pytest.approx(score(5, 4), rel=1e-3)


def test_gqa_with_equal_heads_is_mha():
    # n_kv_heads == n_heads makes repeat_kv the identity
    x = jax.random.normal(jax.random.key(0), (2, 4, 8, 16), jnp.float32)
    np.testing.assert_array_equal(np.asarray(repeat_kv(x, 1)), np.asarray(x))
    r = repeat_kv(x[:, :2], 2)
    assert r.shape == (2, 4, 8, 16)
    np.testing.assert_array_equal(np.asarray(r[:, 0]), np.asarray(r[:, 1]))


def test_forward_shapes_finite_and_causal():
    params = init_llama_params(jax.random.key(0), TINY)
    tokens = tokens_batch()
    logits = llama_forward(params, tokens, TINY)
    assert logits.shape == (2, 16, TINY.vocab_size)
    assert logits.dtype == jnp.float32
    assert np.isfinite(np.asarray(logits)).all()
    # causality: perturbing the last token leaves earlier logits unchanged
    perturbed = tokens.at[:, -1].set((tokens[:, -1] + 1) % TINY.vocab_size)
    logits2 = llama_forward(params, perturbed, TINY)
    np.testing.assert_array_equal(
        np.asarray(logits[:, :-1]), np.asarray(logits2[:, :-1])
    )


def test_rope_makes_token_order_matter():
    # swap two earlier tokens: a position-blind (bag-of-words) attention
    # would produce identical later logits; RoPE must distinguish order
    params = init_llama_params(jax.random.key(0), TINY)
    tokens = tokens_batch(batch=1, seq=8)
    swapped = tokens.at[:, 0].set(tokens[:, 1]).at[:, 1].set(tokens[:, 0])
    assert not np.array_equal(np.asarray(tokens), np.asarray(swapped))
    a = np.asarray(llama_forward(params, tokens, TINY))
    b = np.asarray(llama_forward(params, swapped, TINY))
    assert not np.allclose(a[0, 5], b[0, 5], atol=1e-5)


def test_train_step_learns_dp_tp():
    mesh = make_mesh(jax.devices(), model_parallel=2, seq_parallel=1)
    train_config = TrainConfig(learning_rate=1e-2)
    state = place_state(
        mesh, init_llama_train_state(jax.random.key(0), TINY, train_config)
    )
    step_fn = make_llama_train_step(mesh, TINY, train_config, state)
    tokens = jax.device_put(tokens_batch(batch=4, seq=32),
                            batch_sharding(mesh))
    losses = []
    for _ in range(4):
        state, loss = step_fn(state, tokens)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_gqa_cache_has_fewer_heads():
    from kube_sqs_autoscaler_tpu.workloads.llama import init_llama_cache

    cache = init_llama_cache(TINY, batch=2)
    assert cache["layers"][0]["k"].shape == (2, 2, 64, 16)  # n_kv_heads=2


def test_decode_matches_forward_position_by_position():
    # teacher-forcing equivalence: decode_step logits at position t must
    # equal the full forward's logits at position t
    params = init_llama_params(jax.random.key(0), TINY)
    tokens = tokens_batch(batch=2, seq=10)
    full = np.asarray(llama_forward(params, tokens, TINY))

    logits, cache = llama_prefill(params, tokens[:, :4], TINY)
    np.testing.assert_allclose(logits, full[:, 3], rtol=2e-4, atol=2e-4)
    for t in range(4, 10):
        logits, cache = llama_decode_step(params, cache, tokens[:, t], TINY)
        np.testing.assert_allclose(
            np.asarray(logits), full[:, t], rtol=2e-4, atol=2e-4
        )


def test_generate_greedy_matches_manual_argmax_rollout():
    params = init_llama_params(jax.random.key(0), TINY)
    prompt = tokens_batch(batch=2, seq=6)
    out = llama_generate_jit(params, prompt, 5, TINY)
    assert out.shape == (2, 5)

    # manual rollout through the full forward (no cache)
    seq = prompt
    expected = []
    for _ in range(5):
        logits = llama_forward(params, seq, TINY)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        expected.append(nxt)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(
        np.asarray(out), np.asarray(jnp.stack(expected, axis=1))
    )


def test_llama_attention_fn_for_selects_and_matches_dense():
    from kube_sqs_autoscaler_tpu.workloads.llama import (
        llama_attention_fn_for,
        llama_forward_jit_with,
    )

    # off TPU (this suite) the selection must be dense-backed and the
    # forward must equal the default path exactly
    params = init_llama_params(jax.random.key(0), TINY)
    tokens = tokens_batch()
    attend = llama_attention_fn_for(TINY, tokens.shape[1])
    np.testing.assert_allclose(
        np.asarray(llama_forward_jit_with(params, tokens, TINY, attend)),
        np.asarray(llama_forward(params, tokens, TINY)),
        rtol=1e-3, atol=1e-5,  # jit fusion reorders fp ops slightly
    )
    # on TPU with a tiling seq_len the flash kernel is selected — and
    # because it is GQA-native it is returned bare (no repeat_kv wrapper),
    # so the compact k/v stream straight into the kernel
    from kube_sqs_autoscaler_tpu.workloads import flash

    tpu_attend = llama_attention_fn_for(TINY, flash.FLASH_MIN_SEQ,
                                        backend="tpu")
    assert tpu_attend is flash.flash_attention


def test_loss_is_finite_and_loss_fn_composes():
    params = init_llama_params(jax.random.key(0), TINY)
    loss = float(llama_loss_fn(params, tokens_batch(), TINY))
    assert np.isfinite(loss)


def test_llama_remat_is_bit_identical():
    params = init_llama_params(jax.random.key(0), TINY)
    tokens = tokens_batch()
    plain_l, plain_g = jax.value_and_grad(llama_loss_fn)(params, tokens, TINY)
    remat_l, remat_g = jax.value_and_grad(llama_loss_fn)(
        params, tokens, TINY, remat=True
    )
    assert float(plain_l) == float(remat_l)
    for a, b in zip(jax.tree.leaves(plain_g), jax.tree.leaves(remat_g)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6)


def test_llama_train_step_seq_parallel_matches_dense():
    """GQA ring attention under sp=2 must train and pin the dense loss."""
    train_config = TrainConfig(learning_rate=1e-2)
    base = init_llama_train_state(jax.random.key(0), TINY, train_config)
    tokens = tokens_batch(batch=4, seq=32)

    mesh_sp = make_mesh(jax.devices(), model_parallel=2, seq_parallel=2)
    state_sp = place_state(mesh_sp, jax.tree.map(jnp.copy, base))
    step_sp = make_llama_train_step(mesh_sp, TINY, train_config, state_sp)
    toks_sp = jax.device_put(tokens, batch_sharding(mesh_sp))

    mesh_dp = make_mesh(jax.devices(), model_parallel=2, seq_parallel=1)
    state_dp = place_state(mesh_dp, base)
    step_dp = make_llama_train_step(mesh_dp, TINY, train_config, state_dp)
    toks_dp = jax.device_put(tokens, batch_sharding(mesh_dp))

    for _ in range(3):
        state_sp, loss_sp = step_sp(state_sp, toks_sp)
        state_dp, loss_dp = step_dp(state_dp, toks_dp)
        np.testing.assert_allclose(
            float(loss_sp), float(loss_dp), rtol=2e-4
        )


def test_llama_param_shardings_are_tensor_parallel_without_importing_llama():
    # the sharding registry lives in model.PARAM_AXES: spawning a process
    # that never imports workloads.llama must still shard wq/wkv/w_gate_up
    import subprocess
    import sys
    from pathlib import Path

    code = (
        "import jax\n"
        "from kube_sqs_autoscaler_tpu.workloads.model import PARAM_AXES\n"
        "assert PARAM_AXES['wkv'] == ('model', 'kv_heads')\n"
        "assert PARAM_AXES['w_gate_up'] == ('model', 'ff2')\n"
        "import sys\n"
        "assert 'kube_sqs_autoscaler_tpu.workloads.llama' not in sys.modules\n"
        "print('ok')\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        cwd=Path(__file__).resolve().parent.parent,
    )
    assert out.returncode == 0, out.stderr
    assert "ok" in out.stdout
