"""Native token-corpus reader: format round-trip, determinism, shard
boundaries, dtype handling, and the trainer's --data-dir path."""

import numpy as np
import pytest

from kube_sqs_autoscaler_tpu.native import NativeUnavailableError

try:
    from kube_sqs_autoscaler_tpu.native.tokenreader import (
        TokenReader,
        load_library,
        write_token_shards,
    )

    load_library()
    NATIVE = True
except NativeUnavailableError:  # pragma: no cover - image always has g++
    NATIVE = False

pytestmark = pytest.mark.skipif(not NATIVE, reason="g++ unavailable")


def make_corpus(tmp_path, n_tokens=10_000, vocab=997, shard_tokens=None,
                dtype="uint16", seed=0):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(0, vocab, n_tokens)
    path = tmp_path / "corpus"
    write_token_shards(path, tokens, vocab, shard_tokens=shard_tokens,
                       dtype=dtype)
    return path, tokens


def test_batches_are_windows_of_the_corpus(tmp_path):
    path, tokens = make_corpus(tmp_path)
    with TokenReader(path, min_window=16) as reader:
        assert reader.total_tokens == len(tokens)
        assert reader.vocab_size == 997
        batch = reader.batch(4, 16, seed=1, step=0)
        assert batch.shape == (4, 16) and batch.dtype == np.int32
        corpus = np.asarray(tokens, np.int32)
        for row in batch:
            # every row must be a contiguous window of the corpus
            starts = np.where(corpus[: len(corpus) - 15] == row[0])[0]
            assert any(
                np.array_equal(corpus[s:s + 16], row) for s in starts
            )


def test_determinism_and_step_variation(tmp_path):
    path, _ = make_corpus(tmp_path)
    with TokenReader(path) as a, TokenReader(path) as b:
        x = a.batch(4, 32, seed=7, step=3)
        y = b.batch(4, 32, seed=7, step=3)
        np.testing.assert_array_equal(x, y)  # pure function of indices
        z = a.batch(4, 32, seed=7, step=4)
        assert not np.array_equal(x, z)
        w = a.batch(4, 32, seed=8, step=3)
        assert not np.array_equal(x, w)
        # prefetch path: sequential steps serve from the double buffer
        # and still equal a fresh reader's answer
        seq_batches = [a.batch(2, 16, seed=1, step=s) for s in range(5)]
        for s, got in enumerate(seq_batches):
            np.testing.assert_array_equal(
                got, b.batch(2, 16, seed=1, step=s)
            )


def test_windows_never_span_shard_boundaries(tmp_path):
    # 10 shards of 1000; a window crossing a boundary would contain a
    # subsequence not present in any single shard
    path, tokens = make_corpus(tmp_path, n_tokens=10_000, shard_tokens=1000)
    shards = [np.asarray(tokens[i:i + 1000], np.int32)
              for i in range(0, 10_000, 1000)]
    with TokenReader(path, min_window=64) as reader:
        for step in range(20):
            for row in reader.batch(4, 64, seed=3, step=step):
                assert any(
                    any(np.array_equal(shard[s:s + 64], row)
                        for s in np.where(shard[:937] == row[0])[0])
                    for shard in shards
                )


def test_int32_corpus_dtype(tmp_path):
    rng = np.random.default_rng(1)
    tokens = rng.integers(0, 70_000, 5000)  # needs 32-bit
    path = tmp_path / "corpus32"
    write_token_shards(path, tokens, 70_000, dtype="int32")
    with TokenReader(path, min_window=8) as reader:
        batch = reader.batch(2, 8, seed=0, step=0)
        assert batch.max() < 70_000 and batch.min() >= 0


def test_uint16_writer_rejects_oversized_vocab(tmp_path):
    with pytest.raises(ValueError, match="uint16"):
        write_token_shards(tmp_path / "c", [1, 2, 3], vocab_size=70_000)
    with pytest.raises(ValueError, match="uint16"):
        write_token_shards(tmp_path / "c", [1, 70_000], vocab_size=65_536)
    with pytest.raises(ValueError, match="negative"):
        write_token_shards(tmp_path / "c", [1, -1], vocab_size=100)
    with pytest.raises(ValueError, match="empty"):
        write_token_shards(tmp_path / "c", [], vocab_size=100)


def test_oversized_window_request_fails_loudly(tmp_path):
    # the native fill rejects seq > smallest shard instead of an OOB read
    path, _ = make_corpus(tmp_path, n_tokens=1000, shard_tokens=100)
    with TokenReader(path) as reader:  # default min_window=1
        with pytest.raises(ValueError, match="smallest shard"):
            reader.batch(2, 512, seed=0, step=0)
        assert reader.batch(2, 100, seed=0, step=0).shape == (2, 100)


def test_open_validation(tmp_path):
    with pytest.raises(FileNotFoundError, match="meta.json"):
        TokenReader(tmp_path / "nope")
    # a shard smaller than one window fails fast with the mapped error
    path, _ = make_corpus(tmp_path, n_tokens=100)
    with pytest.raises(ValueError, match="fewer tokens"):
        TokenReader(path, min_window=1000)


def test_trainer_data_dir_end_to_end(tmp_path):
    """--data-dir through the real trainer binary on the CPU mesh."""
    import os
    import subprocess
    import sys
    from pathlib import Path

    path, _ = make_corpus(tmp_path, n_tokens=50_000, vocab=250,
                          shard_tokens=20_000)
    repo_root = Path(__file__).resolve().parent.parent
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    run = subprocess.run(
        [sys.executable, "-m", "kube_sqs_autoscaler_tpu.workloads.trainer",
         "--data-dir", str(path), "--steps", "4", "--batch-size", "8",
         "--seq-len", "32", "--d-model", "64", "--n-heads", "4",
         "--n-layers", "2", "--d-ff", "128", "--vocab-size", "256",
         "--log-every", "2"],
        capture_output=True, text=True, env=env, cwd=repo_root,
    )
    assert run.returncode == 0, run.stderr[-3000:]
    assert "loss" in run.stderr

    # corpus vocab larger than the model's fails fast
    run = subprocess.run(
        [sys.executable, "-m", "kube_sqs_autoscaler_tpu.workloads.trainer",
         "--data-dir", str(path), "--steps", "1", "--vocab-size", "128"],
        capture_output=True, text=True, env=env, cwd=repo_root,
    )
    assert run.returncode != 0
    assert "vocab" in run.stderr
