"""Workload-layer tests on the virtual 8-device CPU mesh (conftest.py sets
JAX_PLATFORMS=cpu + xla_force_host_platform_device_count=8).

Covers: forward shape/dtype contracts, causality, sharded train-step
execution with loss decrease, sharding placement of params/optimizer state,
and the queue-fed worker/pool plumbing.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kube_sqs_autoscaler_tpu.workloads.model import (
    ModelConfig,
    forward,
    init_params,
    param_count,
)
from kube_sqs_autoscaler_tpu.workloads.train import (
    TrainConfig,
    batch_sharding,
    init_train_state,
    loss_fn,
    make_forward_step,
    make_mesh,
    make_train_step,
    param_shardings,
    place_state,
)
from kube_sqs_autoscaler_tpu.workloads.worker import (
    InferenceWorker,
    WorkItem,
    WorkerPool,
)

TINY = ModelConfig(
    vocab_size=512, d_model=128, n_heads=4, n_layers=2, d_ff=256, max_seq_len=64
)


@pytest.fixture(scope="module")
def tiny_params():
    return init_params(jax.random.key(0), TINY)


def test_forward_shapes_and_dtypes(tiny_params):
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, TINY.vocab_size,
                                jnp.int32)
    logits = forward(tiny_params, tokens, TINY)
    assert logits.shape == (2, 16, TINY.vocab_size)
    assert logits.dtype == jnp.float32  # fp32 logits for stable loss
    assert tiny_params["embed"].dtype == jnp.bfloat16  # bf16 storage


def test_forward_is_causal(tiny_params):
    # changing a future token must not change earlier positions' logits
    tokens = jax.random.randint(jax.random.key(2), (1, 16), 0, TINY.vocab_size,
                                jnp.int32)
    altered = tokens.at[0, 10].set((tokens[0, 10] + 1) % TINY.vocab_size)
    base = forward(tiny_params, tokens, TINY)
    changed = forward(tiny_params, altered, TINY)
    np.testing.assert_allclose(
        np.asarray(base[0, :10]), np.asarray(changed[0, :10]), rtol=1e-5
    )
    assert not np.allclose(np.asarray(base[0, 10:]), np.asarray(changed[0, 10:]))


def test_mlp_weights_are_uncorrelated_at_init(tiny_params):
    # regression: w_up/w_down once shared an RNG key, giving perfectly
    # correlated (reshaped) draws
    up = np.asarray(tiny_params["layers"][0]["w_up"], np.float32).ravel()
    down = np.asarray(tiny_params["layers"][0]["w_down"], np.float32).ravel()
    assert abs(np.corrcoef(up, down)[0, 1]) < 0.05


def test_param_count_is_plausible(tiny_params):
    # embed + pos + 2 layers (qkv, wo, up, down + LNs) + final LN
    assert param_count(tiny_params) > TINY.vocab_size * TINY.d_model


def test_mesh_factory_prefers_small_model_parallel():
    mesh = make_mesh(jax.devices())
    assert mesh.shape == {"data": 2, "seq": 1, "model": 4}
    mesh2 = make_mesh(jax.devices()[:2])
    assert mesh2.shape == {"data": 1, "seq": 1, "model": 2}
    mesh1 = make_mesh(jax.devices()[:1])
    assert mesh1.shape == {"data": 1, "seq": 1, "model": 1}
    mesh3 = make_mesh(jax.devices(), seq_parallel=2)
    assert mesh3.shape == {"data": 1, "seq": 2, "model": 4}


def test_param_shardings_follow_megatron_rules(tiny_params):
    mesh = make_mesh(jax.devices())
    shardings = param_shardings(mesh, tiny_params)
    layer = shardings["layers"][0]
    assert layer["wqkv"].spec == jax.sharding.PartitionSpec(None, "model")
    assert layer["wo"].spec == jax.sharding.PartitionSpec("model", None)
    assert layer["w_up"].spec == jax.sharding.PartitionSpec(None, "model")
    assert layer["w_down"].spec == jax.sharding.PartitionSpec("model", None)
    assert shardings["embed"].spec == jax.sharding.PartitionSpec("model", None)
    assert layer["ln1_scale"].spec == jax.sharding.PartitionSpec(None)


def test_sharded_train_step_runs_and_loss_decreases():
    mesh = make_mesh(jax.devices())
    state = place_state(mesh, init_train_state(jax.random.key(0), TINY,
                                               TrainConfig(learning_rate=1e-2)))
    step_fn = make_train_step(mesh, TINY, TrainConfig(learning_rate=1e-2), state)
    tokens = jax.device_put(
        jax.random.randint(jax.random.key(1), (4, 32), 0, TINY.vocab_size,
                           jnp.int32),
        batch_sharding(mesh),
    )
    losses = []
    for _ in range(5):
        state, loss = step_fn(state, tokens)
        losses.append(float(loss))
    assert int(jax.device_get(state["step"])) == 5
    assert all(np.isfinite(losses))
    # memorizing one small batch: loss must drop
    assert losses[-1] < losses[0]
    # params actually sharded: a tensor-parallel weight lives on 4 shards
    wqkv = state["params"]["layers"][0]["wqkv"]
    assert len(wqkv.sharding.device_set) == 8  # dp replicas x tp shards


def test_sharded_forward_matches_single_device(tiny_params):
    mesh = make_mesh(jax.devices())
    tokens = jax.random.randint(jax.random.key(3), (4, 16), 0, TINY.vocab_size,
                                jnp.int32)
    single = forward(tiny_params, tokens, TINY)
    forward_step = make_forward_step(mesh, TINY, tiny_params)
    sharded_params = jax.device_put(tiny_params, param_shardings(mesh, tiny_params))
    sharded = forward_step(
        sharded_params, jax.device_put(tokens, batch_sharding(mesh))
    )
    np.testing.assert_allclose(
        np.asarray(single), np.asarray(sharded), rtol=2e-2, atol=2e-2
    )


def test_loss_fn_matches_uniform_at_init():
    # with random init and tiny scale, loss ~ log(vocab)
    params = init_params(jax.random.key(7), TINY)
    tokens = jax.random.randint(jax.random.key(8), (2, 32), 0, TINY.vocab_size,
                                jnp.int32)
    loss = float(loss_fn(params, tokens, TINY))
    assert abs(loss - np.log(TINY.vocab_size)) < 1.0


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_fused_nll_matches_unfused_reference(dtype):
    """fused_next_token_nll == next_token_nll(forward(...)) — the loss
    value bit-identically (same einsum + logsumexp reduction), the
    gradients to float-reassociation tolerance (the fused backward
    recomputes the logits and runs its matmuls in the storage dtype)."""
    from kube_sqs_autoscaler_tpu.workloads.train import next_token_nll

    config = ModelConfig(
        vocab_size=128, d_model=64, n_heads=4, n_layers=2, d_ff=128,
        max_seq_len=32, dtype=dtype,
    )
    params = init_params(jax.random.key(11), config)
    tokens = jax.random.randint(jax.random.key(12), (2, 16), 0,
                                config.vocab_size, jnp.int32)

    def ref_loss(params, tokens):
        return next_token_nll(forward(params, tokens, config), tokens)

    l_ref, g_ref = jax.value_and_grad(ref_loss)(params, tokens)
    l_new, g_new = jax.value_and_grad(
        lambda p, t: loss_fn(p, t, config)
    )(params, tokens)
    assert float(l_ref) == float(l_new)  # bit-identical forward
    for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_new)):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            atol=5e-3 if dtype == jnp.bfloat16 else 5e-4,
        )


def test_fused_nll_llama_and_moe_match_reference():
    """Every family's objective routes through the fused CE with the same
    value as the materialized-logits composition."""
    from kube_sqs_autoscaler_tpu.workloads.llama import (
        LlamaConfig,
        init_llama_params,
        llama_forward,
        llama_loss_fn,
    )
    from kube_sqs_autoscaler_tpu.workloads.moe import (
        MoeConfig,
        init_moe_params,
        moe_forward,
        moe_loss_fn,
    )
    from kube_sqs_autoscaler_tpu.workloads.train import next_token_nll

    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, 128,
                                jnp.int32)
    lc = LlamaConfig(vocab_size=128, d_model=64, n_heads=4, n_kv_heads=2,
                     n_layers=2, d_ff=128, max_seq_len=32)
    lp = init_llama_params(jax.random.key(0), lc)
    ref = float(next_token_nll(llama_forward(lp, tokens, lc), tokens))
    assert ref == float(llama_loss_fn(lp, tokens, lc))

    cfg = ModelConfig(vocab_size=128, d_model=64, n_heads=4, n_layers=2,
                      d_ff=128, max_seq_len=32)
    mc = MoeConfig(n_experts=4, top_k=2)
    mp = init_moe_params(jax.random.key(0), cfg, mc)
    logits, aux = moe_forward(mp, tokens, cfg, mc)
    ref = float(next_token_nll(logits, tokens)
                + mc.aux_loss_weight * aux)
    assert abs(ref - float(moe_loss_fn(mp, tokens, cfg, mc))) < 1e-6
    # gradients flow through the fused path for both families
    jax.grad(lambda p: llama_loss_fn(p, tokens, lc))(lp)
    jax.grad(lambda p: moe_loss_fn(p, tokens, cfg, mc))(mp)


def test_inference_worker_processes_items(tiny_params):
    worker = InferenceWorker(tiny_params, TINY)
    tokens = jax.random.randint(jax.random.key(4), (2, 16), 0, TINY.vocab_size,
                                jnp.int32)
    result = worker.process(WorkItem(tokens=tokens, id=7))
    assert result.id == 7
    assert result.next_tokens.shape == (2,)
    assert worker.processed == 1
    assert result.latency_s > 0


def test_worker_pool_drains_queue(tiny_params):
    pool = WorkerPool(
        worker_factory=lambda: InferenceWorker(tiny_params, TINY), size=2
    )
    pool.start()
    tokens = jax.random.randint(jax.random.key(5), (1, 16), 0, TINY.vocab_size,
                                jnp.int32)
    for i in range(6):
        pool.submit(WorkItem(tokens=tokens, id=i))
    results = [pool.results.get(timeout=60) for _ in range(6)]
    pool.stop()
    assert sorted(r.id for r in results) == list(range(6))
    assert pool.depth() == 0


def test_graft_entry_single_chip():
    import __graft_entry__ as graft

    fn, (params, tokens) = graft.entry()
    jitted = jax.jit(fn)
    logits = jitted(params, tokens)
    assert logits.shape == (tokens.shape[0], tokens.shape[1], 8192)
    assert bool(jnp.all(jnp.isfinite(logits)))
