"""The one-scheduler seam (ISSUE 15): event-queue determinism, the
loop/fleet drivers re-expressed as registered events (byte-identical),
the KnobActuator's safe-point engine-knob changes end to end
(journal + snapshot + gauges + trace), the learned knob head's
geometry, and the CLI arming rejections.

The JAX-free half (scheduler, stub-fleet driver equivalence, knob-head
arithmetic-free checks) runs first; real-engine knob mechanics use the
same tiny-model fixtures as the serving test modules.  The full
real-fleet byte-identity and the adaptive-vs-static win are the
``bench.py --suite knobs`` hard gates; the smoke here keeps its
deterministic gates in tier 1.
"""

from __future__ import annotations

import json

import pytest

from kube_sqs_autoscaler_tpu.core.clock import FakeClock
from kube_sqs_autoscaler_tpu.core.loop import ControlLoop, LoopConfig
from kube_sqs_autoscaler_tpu.core.policy import PolicyConfig
from kube_sqs_autoscaler_tpu.sched import (
    EventScheduler,
    PRIORITY_CONTROL,
    PRIORITY_CYCLE,
    drive_loop,
)
from kube_sqs_autoscaler_tpu.sched.knobs import (
    KNOB_DECODE_BLOCK,
    KNOB_SLOT_LIMIT,
    KnobError,
    ReactiveKnobPolicy,
    parse_knob_names,
)


# ---------------------------------------------------------------------------
# EventScheduler: deterministic ordering, anchors, cancellation
# ---------------------------------------------------------------------------


def _build_trace_run():
    clock = FakeClock()
    sched = EventScheduler(clock)
    seen = []
    sched.every("a", 1.0, lambda: seen.append("a"))
    sched.every("b", 1.0, lambda: seen.append("b"))  # ties with a
    sched.every("hi", 2.0, lambda: seen.append("hi"),
                priority=PRIORITY_CONTROL)  # outranks a/b at t=2,4,...
    sched.at("once", 2.5, lambda: seen.append("once"))
    sched.run(max_events=20)
    return list(sched.trace), seen


def test_scheduler_order_is_deterministic_across_runs():
    # same registered events + same FakeClock => identical execution
    # order, twice (there is no other source of order)
    trace1, seen1 = _build_trace_run()
    trace2, seen2 = _build_trace_run()
    assert trace1 == trace2
    assert seen1 == seen2
    # ordering contract: due time first, then priority, then seq
    assert trace1[0] == (1.0, "a") and trace1[1] == (1.0, "b")
    t2 = [name for due, name in trace1 if due == 2.0]
    assert t2 == ["hi", "a", "b"]  # control priority outranks the tie
    assert (2.5, "once") in trace1


def test_scheduler_anchors_grid_vs_after():
    clock = FakeClock()
    sched = EventScheduler(clock)
    fired = []

    def slow_grid():
        fired.append(("grid", clock.now()))

    def slow_after():
        fired.append(("after", clock.now()))
        clock.advance(0.6)  # the body consumes clock time

    sched.every("grid", 1.0, slow_grid, anchor="grid")
    sched.every("after", 1.0, slow_after, anchor="after")
    sched.run(max_events=6)
    grid_times = [t for kind, t in fired if kind == "grid"]
    after_times = [t for kind, t in fired if kind == "after"]
    # grid keeps its cadence; after re-anchors past the consumed time
    assert grid_times[:2] == [1.0, 2.0]
    assert after_times[0] == 1.0
    assert after_times[1] == pytest.approx(2.6)  # 1.0 + 0.6 + 1.0


def test_scheduler_cancel_and_one_shots():
    clock = FakeClock()
    sched = EventScheduler(clock)
    seen = []
    ev = sched.every("rec", 1.0, lambda: seen.append("rec"))
    sched.after("shot", 2.5, lambda: seen.append("shot"))
    sched.run(max_events=2)
    sched.cancel(ev)
    sched.run()
    assert seen == ["rec", "rec", "shot"]
    assert sched.pending == 0


def test_scheduler_rejects_bad_event_args():
    sched = EventScheduler(FakeClock())
    with pytest.raises(ValueError, match="anchor"):
        sched.every("x", 1.0, lambda: None, anchor="sideways")
    with pytest.raises(ValueError, match="period"):
        sched.every("x", -1.0, lambda: None)


# ---------------------------------------------------------------------------
# drive_loop: ControlLoop.run as a registered event, byte-identical
# ---------------------------------------------------------------------------


class _ScriptedSource:
    """Queue depth as a function of the observation index."""

    def __init__(self, depths):
        self.depths = list(depths)
        self.calls = 0

    def num_messages(self) -> int:
        depth = self.depths[min(self.calls, len(self.depths) - 1)]
        self.calls += 1
        return depth


class _RecordingScaler:
    def __init__(self):
        self.calls = []

    def scale_up(self):
        self.calls.append("up")

    def scale_down(self):
        self.calls.append("down")


class _Collector:
    def __init__(self):
        self.records = []

    def on_tick(self, record):
        self.records.append(record)


_DEPTHS = [0, 50, 150, 200, 150, 40, 5, 5, 0, 0, 120, 130, 5, 5]


def _loop_setup():
    clock = FakeClock()
    source = _ScriptedSource(_DEPTHS)
    scaler = _RecordingScaler()
    collector = _Collector()
    loop = ControlLoop(
        scaler, source,
        LoopConfig(poll_interval=5.0, policy=PolicyConfig(
            scale_up_messages=100, scale_down_messages=10,
            scale_up_cooldown=10.0, scale_down_cooldown=20.0,
        )),
        clock=clock, observer=collector,
    )
    return loop, scaler, collector


def test_drive_loop_matches_run_byte_for_byte():
    loop_a, scaler_a, col_a = _loop_setup()
    state_a = loop_a.run(max_ticks=len(_DEPTHS))
    loop_b, scaler_b, col_b = _loop_setup()
    state_b = drive_loop(loop_b, max_ticks=len(_DEPTHS))
    assert col_a.records == col_b.records  # TickRecord is a dataclass
    assert scaler_a.calls == scaler_b.calls
    assert state_a == state_b
    assert loop_b.ticks == len(_DEPTHS)


def test_control_loop_run_delegates_to_scheduler():
    loop_a, scaler_a, col_a = _loop_setup()
    loop_a.run(max_ticks=6)
    loop_b, scaler_b, col_b = _loop_setup()
    loop_b.run(max_ticks=6, scheduler=True)
    assert col_a.records == col_b.records
    assert scaler_a.calls == scaler_b.calls


def test_drive_loop_sticky_stop_and_mid_sleep_stop():
    loop, _, col = _loop_setup()
    loop.stop()  # pre-start stop is sticky, like run()
    drive_loop(loop, max_ticks=4)
    assert col.records == []
    loop.reset()
    # stop scheduled mid-sleep (before the 3rd tick fires): that tick
    # must be skipped, exactly like run()'s mid-sleep check
    loop.clock.at(12.0, loop.stop)
    drive_loop(loop, max_ticks=10)
    assert len(col.records) == 2


# ---------------------------------------------------------------------------
# ScheduledFleetDriver vs FleetDriver on a stub fleet: identical
# interleave (cycles, ticks, trajectory, events), JAX-free
# ---------------------------------------------------------------------------


class _CycleStubBatcher:
    def __init__(self):
        self.active = 0
        self.free_slots = []
        self.tokens_emitted = 0
        self.decode_block = 1
        # the knob surface the actuator reads/writes (stubbed flat)
        self.slots = [None, None]
        self.slot_limit = None
        self.spec_overlap = True
        self._block_engine = False

    def set_slot_limit(self, limit):
        self.slot_limit = limit


class _CycleStubWorker:
    """A stub replica that 'serves' a scripted amount per cycle."""

    def __init__(self, pool):
        self.admitting = True
        self.killed = False
        self.hung = False
        self.processed = 0
        self.batcher = _CycleStubBatcher()
        self._pool = pool

    def run_once(self):
        if self.killed or self.hung or not self.admitting:
            return 0
        self.processed += 1
        self.batcher.tokens_emitted += 3
        return 1

    def stop(self):
        pass

    def kill(self):
        self.killed = True

    def hang(self):
        self.hung = True

    def take_inflight(self):
        return []

    def release_inflight(self):
        return 0

    def _admit(self, messages):
        return len(messages)


def _stub_fleet(driver_cls, depths, **driver_kwargs):
    from kube_sqs_autoscaler_tpu.fleet import WorkerPool

    clock = FakeClock()
    pool = WorkerPool(
        _CycleStubWorker, min=1, max=4, initial=1, clock=clock,
    )
    source = _ScriptedSource(depths)
    collector = _Collector()
    loop = ControlLoop(
        pool, source,
        LoopConfig(poll_interval=0.1, policy=PolicyConfig(
            scale_up_messages=20, scale_down_messages=2,
            scale_up_cooldown=0.2, scale_down_cooldown=0.4,
        )),
        clock=clock, observer=collector,
    )
    driver = driver_cls(pool, loop, cycle_dt=0.05, **driver_kwargs)
    stats = driver.run(max_cycles=60)
    return stats, collector.records, [e.name for e in pool.events], pool


def test_scheduled_fleet_driver_matches_fleet_driver():
    from kube_sqs_autoscaler_tpu.fleet import FleetDriver
    from kube_sqs_autoscaler_tpu.sched import ScheduledFleetDriver

    depths = [40, 60, 80, 60, 40, 1, 1, 1, 1, 0, 0, 0, 50, 60, 1, 1]
    ref_stats, ref_records, ref_events, _ = _stub_fleet(
        FleetDriver, depths
    )
    new_stats, new_records, new_events, _ = _stub_fleet(
        ScheduledFleetDriver, depths
    )
    assert new_records == ref_records
    assert new_events == ref_events
    assert new_stats == ref_stats
    assert ref_stats["replica_trajectory"]  # the episode actually scaled


def test_scheduled_fleet_driver_until_predicate_position():
    # the stop predicate is evaluated at the hand-rolled loop's exact
    # position (after the tick when one fired) — stopping mid-episode
    # must leave identical state behind
    from kube_sqs_autoscaler_tpu.fleet import FleetDriver
    from kube_sqs_autoscaler_tpu.sched import ScheduledFleetDriver

    depths = [40, 60, 80, 60, 40, 1, 1]
    results = []
    for cls in (FleetDriver, ScheduledFleetDriver):
        stats, records, events, pool = _stub_fleet(
            cls, depths,
        )
        results.append((stats["cycles"], len(records), events))
    assert results[0] == results[1]


def test_scheduled_fleet_driver_crash_restart():
    # a ControllerCrash mid-episode restarts through the same factory
    # contract as FleetDriver — the PR 13 battery's machinery works
    # unchanged under the scheduler
    from kube_sqs_autoscaler_tpu.core.durable import ControllerCrash
    from kube_sqs_autoscaler_tpu.fleet import FleetDriver, WorkerPool
    from kube_sqs_autoscaler_tpu.sched import ScheduledFleetDriver

    def run(driver_cls):
        clock = FakeClock()

        def build():
            pool = WorkerPool(
                _CycleStubWorker, min=1, max=3, initial=1, clock=clock,
            )
            loop = ControlLoop(
                pool, _ScriptedSource([50] * 30),
                LoopConfig(poll_interval=0.1, policy=PolicyConfig(
                    scale_up_messages=20, scale_down_messages=2,
                    scale_up_cooldown=0.2, scale_down_cooldown=0.4,
                )),
                clock=clock,
            )
            return pool, loop

        pool, loop = build()
        ticks = {"n": 0}
        real_tick = loop.tick

        def crashing_tick(state):
            ticks["n"] += 1
            if ticks["n"] == 3:
                raise ControllerCrash("boom")
            return real_tick(state)

        loop.tick = crashing_tick
        driver = driver_cls(
            pool, loop, cycle_dt=0.05, restart=build, downtime_s=0.3,
        )
        stats = driver.run(max_cycles=30)
        return stats["crashes"], stats["restarts"], stats["cycles"]

    assert run(FleetDriver) == run(ScheduledFleetDriver)
    crashes, restarts, _ = run(ScheduledFleetDriver)
    assert crashes == 1 and restarts == 1


# ---------------------------------------------------------------------------
# Knob parsing + prune-skip audit (JAX-free)
# ---------------------------------------------------------------------------


def test_parse_knob_names():
    assert parse_knob_names("decode-block, slot-limit") == (
        "decode_block", "slot_limit",
    )
    with pytest.raises(KnobError, match="unknown knob"):
        parse_knob_names("decode-block,warp-factor")
    with pytest.raises(KnobError, match="twice"):
        parse_knob_names("shards,shards")
    with pytest.raises(KnobError, match="empty"):
        parse_knob_names(" , ")


def test_prune_skips_members_scan_while_under_keep():
    # the per-cycle prune pass must not scan members at all while
    # nothing exceeds retired_keep (the counter is maintained at the
    # lifecycle transitions) — a healthy fleet's cycle cost
    from kube_sqs_autoscaler_tpu.fleet import WorkerPool

    class CountingList(list):
        def __init__(self, items=()):
            super().__init__(items)
            self.iterations = 0

        def __iter__(self):
            self.iterations += 1
            return super().__iter__()

    pool = WorkerPool(lambda p: _CycleStubWorker(p), min=1, max=8,
                      initial=2)
    counting = CountingList(pool.members)
    pool.members = counting
    pool.run_cycle()
    healthy_cost = counting.iterations
    # one retired corpse, still under retired_keep: same cycle cost
    pool.scale_up()
    victim = max(
        (r for r in pool.members if r.state == "serving"),
        key=lambda r: r.index,
    )
    pool.kill_worker(victim.index)
    pool.run_cycle()  # declares dead (no prune scan: 1 <= keep)
    counting.iterations = 0
    pool.run_cycle()
    assert counting.iterations <= healthy_cost
    assert pool._retired_members == 1


# ---------------------------------------------------------------------------
# Learned knob head: geometry, spliced-parity, warm-up (JAX)
# ---------------------------------------------------------------------------

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from kube_sqs_autoscaler_tpu.learn.checkpoint import (  # noqa: E402
    CheckpointError,
    PolicyCheckpoint,
    load_checkpoint,
    save_checkpoint,
)
from kube_sqs_autoscaler_tpu.learn.network import (  # noqa: E402
    DEFAULT_HIDDEN,
    N_ACTIONS,
    N_FEATURES,
    N_KNOB_ACTIONS,
    init_params,
    knob_delta_decision,
    param_count,
    policy_logits,
)


def test_knob_head_param_count_and_init():
    hidden = DEFAULT_HIDDEN
    assert param_count(hidden, knob_head=True) == (
        param_count(hidden) + N_KNOB_ACTIONS * hidden + N_KNOB_ACTIONS
    )
    theta = init_params(3, hidden, knob_head=True)
    assert theta.shape == (param_count(hidden, knob_head=True),)


def test_knob_head_replica_logits_spliced_parity():
    # widening the output layer (replica rows first) must not change
    # what the replica head computes: splice a headless theta's output
    # rows into the knob-headed layout and compare logits exactly
    hidden = 8
    rng = np.random.default_rng(0)
    theta = init_params(7, hidden)
    f = N_FEATURES
    cut = hidden * f + hidden
    w2 = theta[cut : cut + N_ACTIONS * hidden].reshape(N_ACTIONS, hidden)
    b2 = theta[cut + N_ACTIONS * hidden :]
    knob_w = rng.standard_normal((N_KNOB_ACTIONS, hidden)).astype(
        np.float32
    )
    knob_b = rng.standard_normal(N_KNOB_ACTIONS).astype(np.float32)
    spliced = np.concatenate([
        theta[:cut],
        np.concatenate([w2, knob_w]).reshape(-1),
        np.concatenate([b2, knob_b]),
    ]).astype(np.float32)
    features = jnp.asarray(
        rng.standard_normal(N_FEATURES), jnp.float32
    )
    plain = policy_logits(jnp.asarray(theta), features, hidden)
    headed = policy_logits(
        jnp.asarray(spliced), features, hidden, knob_head=True
    )
    assert headed.shape == (N_ACTIONS + N_KNOB_ACTIONS,)
    np.testing.assert_array_equal(
        np.asarray(plain), np.asarray(headed[:N_ACTIONS])
    )


def test_knob_delta_decision_warmup_and_range():
    hidden = 8
    theta = jnp.asarray(init_params(1, hidden, knob_head=True))
    times = jnp.zeros(16, jnp.float32)
    depths = jnp.zeros(16, jnp.float32)
    kwargs = dict(
        observed=jnp.int32(50), replicas=jnp.int32(2),
        frac_up32=jnp.float32(0.0), frac_down32=jnp.float32(0.0),
        scale_up_messages=jnp.int32(100), min_samples=jnp.int32(3),
        max_pods=jnp.int32(5), poll32=jnp.float32(5.0),
        alpha32=jnp.float32(0.3), window=jnp.int32(12),
    )
    cold = knob_delta_decision(
        theta, times, depths, jnp.int32(1), hidden=hidden, **kwargs
    )
    assert int(cold) == 0  # below min_samples: hold, never thrash
    warm = knob_delta_decision(
        theta, times, depths, jnp.int32(8), hidden=hidden, **kwargs
    )
    assert int(warm) in (-1, 0, 1)


def test_knob_head_checkpoint_roundtrip_and_seam_rejection(tmp_path):
    theta = init_params(2, 8, knob_head=True)
    checkpoint = PolicyCheckpoint(theta=theta, hidden=8, knob_head=True)
    headless = PolicyCheckpoint(theta=init_params(2, 8), hidden=8)
    assert checkpoint.hash != headless.hash  # geometry is hashed
    path = tmp_path / "knobhead.json"
    save_checkpoint(str(path), checkpoint)
    loaded = load_checkpoint(str(path))
    assert loaded.knob_head is True
    assert loaded.hash == checkpoint.hash
    np.testing.assert_array_equal(loaded.theta, checkpoint.theta)
    # geometry validated: a knob-head flag over a headless vector fails
    with pytest.raises(CheckpointError, match="knob_head"):
        PolicyCheckpoint(theta=init_params(2, 8), hidden=8,
                         knob_head=True)
    # the compiled fluid twin refuses the wider layout loudly
    from kube_sqs_autoscaler_tpu.sim.compiled import SimConfig, encode_config

    with pytest.raises(CheckpointError, match="knob-action head"):
        encode_config(SimConfig(
            arrival_rate=5.0, service_rate_per_replica=2.0,
            duration=60.0, policy="learned",
            learned_checkpoint=checkpoint,
        ))


# ---------------------------------------------------------------------------
# Real-engine knob mechanics (tiny model, CPU)
# ---------------------------------------------------------------------------

from kube_sqs_autoscaler_tpu.metrics.fake import FakeMessageQueue  # noqa: E402
from kube_sqs_autoscaler_tpu.sched.knobs import KnobActuator  # noqa: E402
from kube_sqs_autoscaler_tpu.workloads.continuous import (  # noqa: E402
    ContinuousWorker,
)
from kube_sqs_autoscaler_tpu.workloads.model import (  # noqa: E402
    ModelConfig,
    init_params as init_model_params,
)
from kube_sqs_autoscaler_tpu.workloads.service import (  # noqa: E402
    ServiceConfig,
    collect_replies,
)

BATCH, PROMPT, TOKENS = 2, 4, 12


@pytest.fixture(scope="module")
def model():
    return ModelConfig(
        vocab_size=64, d_model=16, n_heads=2, n_layers=2, d_ff=32,
        max_seq_len=PROMPT + TOKENS, dtype=jnp.float32,
    )


@pytest.fixture(scope="module")
def params(model):
    return init_model_params(jax.random.key(0), model)


def _worker(model, params, *, decode_block=4, batch=BATCH,
            queue=None, results=None, url="sched://q"):
    queue = queue if queue is not None else FakeMessageQueue()
    results = results if results is not None else FakeMessageQueue()
    config = ServiceConfig(
        queue_url=url, batch_size=batch, seq_len=PROMPT,
        generate_tokens=TOKENS, decode_block=decode_block,
        result_queue_url=url + "-r",
    )
    worker = ContinuousWorker(
        queue, params, model, config, result_queue=results,
    )
    return worker, queue, results


def _send(queue, url, n, vocab, seed=0):
    rng = np.random.default_rng(seed)
    ids = []
    for _ in range(n):
        body = rng.integers(0, vocab, PROMPT).tolist()
        ids.append(queue.send_message(url, json.dumps(body)))
    return ids


def test_decode_block_swap_mid_stream_greedy_parity(model, params):
    # reference: block 4 throughout
    ref, ref_q, ref_r = _worker(model, params)
    _send(ref_q, "sched://q", 6, model.vocab_size)
    while ref.processed < 6:
        ref.run_once()
    ref_replies, _ = collect_replies(ref_r, "sched://q-r")

    # live: block 4, swapped to 8 mid-flight, then to 1 — identical
    # replies (the block engine's results are block-size independent)
    live, live_q, live_r = _worker(model, params)
    live.batcher.adopt_engine(ref.batcher)
    _send(live_q, "sched://q", 6, model.vocab_size)
    cycles = 0
    while live.processed < 6:
        live.run_once()
        cycles += 1
        if cycles == 2:
            assert live.batcher.request_decode_block(8)
        if cycles == 6:
            live.batcher.request_decode_block(1)
    live_replies, _ = collect_replies(live_r, "sched://q-r")
    by_rid_ref = {r: p["tokens"] for r, p in ref_replies.items()}
    by_rid_live = {r: p["tokens"] for r, p in live_replies.items()}
    # request ids differ across queues; compare the multisets of
    # continuations (greedy: fully determined by the prompts)
    assert sorted(by_rid_ref.values()) == sorted(by_rid_live.values())
    assert live.batcher.decode_block == 1
    assert live.batcher._pending_decode_block is None


def test_decode_block_swap_applies_at_redispatch_boundary(model, params):
    worker, queue, _ = _worker(model, params)
    _send(queue, "sched://q", 2, model.vocab_size)
    worker.run_once()  # admit + dispatch block 4
    assert worker.batcher._pending_block is not None
    worker.batcher.request_decode_block(8)
    assert worker.batcher.decode_block == 4  # staged, not applied
    worker.run_once()  # settles the in-flight block, skips dispatch
    assert worker.batcher.decode_block == 8  # landed at the boundary
    assert worker.batcher._pending_block is None
    worker.run_once()  # next dispatch runs at the new size
    while worker.processed < 2:
        worker.run_once()


def test_decode_block_swap_on_idle_engine_is_immediate(model, params):
    worker, _, _ = _worker(model, params)
    assert worker.batcher.request_decode_block(16)
    assert worker.batcher.decode_block == 16
    assert worker.batcher.request_decode_block(16) is False


def test_decode_block_knob_needs_block_engine(model, params):
    worker, _, _ = _worker(model, params, decode_block=1)
    with pytest.raises(ValueError, match="block/gang"):
        worker.batcher.request_decode_block(4)


def test_slot_limit_caps_admission_and_drains(model, params):
    worker, queue, _ = _worker(model, params)
    worker.batcher.set_slot_limit(1)
    _send(queue, "sched://q", 4, model.vocab_size)
    worker.run_once()
    assert worker.batcher.active == 1  # capped below batch_size=2
    worker.batcher.set_slot_limit(None)
    worker.run_once()
    assert worker.batcher.active == 2
    with pytest.raises(ValueError, match="slot_limit"):
        worker.batcher.set_slot_limit(99)
    while worker.processed < 4:
        worker.run_once()


def test_sharded_slot_limit_caps_per_shard(model, params):
    from kube_sqs_autoscaler_tpu.workloads.shard_plane import (
        ShardedBatcher,
    )

    batcher = ShardedBatcher(
        params, model, shards=2, shard_slots=2, prompt_len=PROMPT,
        generate_tokens=TOKENS, decode_block=2,
    )
    batcher.set_slot_limit(1)
    assert batcher._free_slot_count() == 2  # one per shard
    rows = batcher.submit_many([
        (np.arange(PROMPT, dtype=np.int32), {"i": i}) for i in range(2)
    ])
    assert sorted(r // 2 for r in rows) == [0, 1]  # spread, one each
    assert batcher._free_slot_count() == 0
    batcher.set_slot_limit(2)
    assert batcher._free_slot_count() == 2


def test_refill_uses_cheap_capacity_not_routed_ordering(model, params):
    # ROADMAP item 1 debt: the refill sizes its receive by the bare
    # count; the routed freest-first ordering is paid only by an
    # admission that actually happens
    from kube_sqs_autoscaler_tpu.workloads.shard_plane import (
        ShardedBatcher,
    )

    queue = FakeMessageQueue()
    config = ServiceConfig(
        queue_url="sched://s", batch_size=2, seq_len=PROMPT,
        generate_tokens=TOKENS, decode_block=2, shards=2,
    )
    worker = ContinuousWorker(queue, params, model, config)
    assert isinstance(worker.batcher, ShardedBatcher)
    _send(queue, "sched://s", 4, model.vocab_size)
    before = worker.batcher.free_slot_scans
    worker.run_once()  # refill admits 4: exactly ONE routed ordering
    assert worker.batcher.free_slot_scans - before == 1
    before = worker.batcher.free_slot_scans
    worker.run_once()  # slots full: refill pays NO routed ordering
    assert worker.batcher.free_slot_scans == before
    while worker.processed < 4:
        worker.run_once()


def test_spec_overlap_toggle_parity(model, params):
    def run(overlap):
        queue = FakeMessageQueue()
        results = FakeMessageQueue()
        config = ServiceConfig(
            queue_url="sched://sp", batch_size=2, seq_len=PROMPT,
            generate_tokens=8, result_queue_url="sched://sp-r",
        )
        worker = ContinuousWorker(
            queue, params, model, config, result_queue=results,
            draft_layers=1, draft_tokens=2,
        )
        worker.batcher.set_speculative(overlap)
        _send(queue, "sched://sp", 3, model.vocab_size)
        steps = 0
        while worker.processed < 3:
            worker.run_once()
            steps += 1
        replies, _ = collect_replies(results, "sched://sp-r")
        return sorted(p["tokens"] for p in replies.values()), steps

    on_tokens, _ = run(True)
    off_tokens, _ = run(False)
    assert on_tokens == off_tokens  # overlap is scheduling, not results


def test_speculative_knob_needs_draft_engine(model, params):
    worker, _, _ = _worker(model, params)
    with pytest.raises(ValueError, match="draft"):
        worker.batcher.set_speculative(False)


def test_prefix_pool_capacity_knob(model, params):
    from kube_sqs_autoscaler_tpu.workloads.tenancy import PrefixPool

    pool = PrefixPool(params, model, entries=4, prefix_len=PROMPT)
    rng = np.random.default_rng(1)

    def acquire(tag):
        ids = rng.integers(0, model.vocab_size, PROMPT)
        return pool.acquire(0, ("t", tag), ids)

    for tag in range(4):
        acquire(tag)
    assert len(pool._lru[0]) == 4
    evicted = pool.set_capacity(2)
    assert evicted == 2 and pool.capacity == 2
    assert len(pool._lru[0]) == 2
    acquire(9)  # install at the ceiling: evicts the LRU victim
    assert len(pool._lru[0]) == 2
    pool.set_capacity(4)  # grow re-opens headroom, evicts nothing
    acquire(10)
    assert len(pool._lru[0]) == 3
    with pytest.raises(ValueError, match="capacity"):
        pool.set_capacity(5)


def test_knob_actuator_end_to_end(model, params, tmp_path):
    from kube_sqs_autoscaler_tpu.obs import TickJournal, WorkloadMetrics
    from kube_sqs_autoscaler_tpu.obs.journal import read_journal_events

    worker, queue, _ = _worker(model, params)
    journal = TickJournal(str(tmp_path / "knobs.jsonl"), meta={"s": 1})
    metrics = WorkloadMetrics()
    actuator = KnobActuator(
        worker, armed=(KNOB_DECODE_BLOCK, KNOB_SLOT_LIMIT),
        journal=journal, metrics=metrics,
    )
    assert actuator.set(KNOB_DECODE_BLOCK, 8)
    assert actuator.set(KNOB_SLOT_LIMIT, 1)
    applied = actuator.apply()
    assert [c["knob"] for c in applied] == [
        KNOB_DECODE_BLOCK, KNOB_SLOT_LIMIT,
    ]
    assert worker.batcher.decode_block == 8
    assert worker.batcher.slot_limit == 1
    # idempotent: re-setting the live value stages nothing
    assert actuator.set(KNOB_DECODE_BLOCK, 8) is False
    assert actuator.apply() == []
    journal.close()
    # every change landed in the journal, its own `knob` line kind
    events = read_journal_events(str(tmp_path / "knobs.jsonl"), "knob")
    assert [(e["knob"], e["value"]) for e in events] == [
        (KNOB_DECODE_BLOCK, 8), (KNOB_SLOT_LIMIT, 1),
    ]
    # ...and in the gauges, labeled per knob
    rendered = metrics.render()
    assert 'engine_knob{knob="decode_block"} 8' in rendered
    assert 'engine_knob{knob="slot_limit"} 1' in rendered
    assert "engine_knob_changes_total 2" in rendered
    # ...and in the trace, its own category
    trace = actuator.trace_events()
    assert trace and all(e["cat"] == "knob" for e in trace)
    # ...and in the durable-state surface: a fresh actuator over a
    # fresh worker re-applies the operating point.  The restarted
    # worker constructs at the actuated block (the actuator keeps
    # worker.config.decode_block in sync exactly so spawns/restarts
    # match the donor's live engine) and adopts compile-free.
    assert worker.config.decode_block == 8
    state = actuator.export_state()
    worker2, _, _ = _worker(model, params, decode_block=8)
    worker2.batcher.adopt_engine(worker.batcher)
    actuator2 = KnobActuator(
        worker2, armed=(KNOB_DECODE_BLOCK, KNOB_SLOT_LIMIT),
    )
    assert actuator2.import_state(state) == 2
    actuator2.apply()
    assert worker2.batcher.decode_block == 8
    assert worker2.batcher.slot_limit == 1


def test_knob_actuator_arm_time_validation(model, params):
    worker, _, _ = _worker(model, params, decode_block=1)
    with pytest.raises(KnobError, match="block/gang"):
        KnobActuator(worker, armed=(KNOB_DECODE_BLOCK,))
    worker4, _, _ = _worker(model, params)
    with pytest.raises(KnobError, match="sharded"):
        KnobActuator(worker4, armed=("shards",))
    with pytest.raises(KnobError, match="draft-and-verify"):
        KnobActuator(worker4, armed=("speculative",))
    with pytest.raises(KnobError, match="prefix pool"):
        KnobActuator(worker4, armed=("prefix_pool",))
    with pytest.raises(KnobError, match="unknown knob"):
        KnobActuator(worker4, armed=("warp",))


def test_shards_knob_through_sharded_pool(model, params):
    from kube_sqs_autoscaler_tpu.fleet.sharded import ShardedWorkerPool
    from kube_sqs_autoscaler_tpu.fleet.worker import FleetWorker

    queue = FakeMessageQueue()
    config = ServiceConfig(
        queue_url="sched://sh", batch_size=2, seq_len=PROMPT,
        generate_tokens=TOKENS, decode_block=2, shards=3,
    )

    def factory(pool):
        return FleetWorker(
            queue, params, model, config, pool=pool,
        )

    pool = ShardedWorkerPool(factory, min=1, max=3, initial=3)
    actuator = KnobActuator(pool, armed=("shards",))
    actuator.set("shards", 1)
    actuator.apply()
    assert pool.replicas == 1
    batcher = pool.worker.batcher
    assert batcher.shard_admitting == [True, False, False]
    actuator.set("shards", 3)
    actuator.apply()
    assert pool.replicas == 3
    with pytest.raises(KnobError, match="shards must be in"):
        actuator.set("shards", 4)


def test_reactive_knob_policy_hysteresis(model, params):
    worker, _, _ = _worker(model, params)
    actuator = KnobActuator(worker, armed=(KNOB_DECODE_BLOCK,))
    depth = {"v": 0}
    policy = ReactiveKnobPolicy(
        actuator, lambda: depth["v"], high=10, low=2,
        block_high=16, block_low=2,
    )
    depth["v"] = 50
    policy.evaluate()
    actuator.apply()
    assert worker.batcher.decode_block == 16
    depth["v"] = 5  # between thresholds: hysteresis holds
    policy.evaluate()
    actuator.apply()
    assert worker.batcher.decode_block == 16
    depth["v"] = 1
    policy.evaluate()
    actuator.apply()
    assert worker.batcher.decode_block == 2


# ---------------------------------------------------------------------------
# CLI arming rejections (args-only: no model is built)
# ---------------------------------------------------------------------------


def test_cli_scheduler_and_knob_rejections():
    from kube_sqs_autoscaler_tpu.workloads.__main__ import main

    base = ["--continuous", "--generate-tokens", "4"]
    with pytest.raises(SystemExit, match="requires --fleet-max-replicas"):
        main(base + ["--scheduler"])
    with pytest.raises(SystemExit, match="requires --continuous"):
        main(["--knobs", "decode-block"])
    with pytest.raises(SystemExit, match="requires --scheduler"):
        main(base + ["--knobs", "decode-block"])
    fleet = base + [
        "--scheduler", "--fleet-max-replicas", "2", "--demo", "1",
        "--decode-block", "4",
    ]
    with pytest.raises(SystemExit, match="unknown knob"):
        main(fleet + ["--knobs", "warp-factor"])
    with pytest.raises(SystemExit, match="does not combine with --beams"):
        main(base + [
            "--scheduler", "--fleet-max-replicas", "2", "--demo", "1",
            "--beams", "2", "--knobs", "speculative",
        ])
    with pytest.raises(
        SystemExit, match="requires --speculative-draft-layers"
    ):
        main(fleet + ["--knobs", "speculative"])
    with pytest.raises(SystemExit, match="block/gang decode"):
        main(base + [
            "--scheduler", "--fleet-max-replicas", "2", "--demo", "1",
            "--knobs", "decode-block",
        ])
    with pytest.raises(
        SystemExit, match="plain continuous decode path"
    ):
        # args-only: rejected BEFORE any model/mesh is built (the
        # pre-existing --decode-block x --speculative check fires
        # first; the knob check backstops the block-engine predicate)
        main(fleet + [
            "--knobs", "decode-block", "--speculative-draft-layers", "1",
        ])
    with pytest.raises(SystemExit, match="sharded plane"):
        main(fleet + ["--knobs", "shards"])
    with pytest.raises(SystemExit, match="requires --prefix-pool"):
        main(fleet + ["--knobs", "prefix-pool"])


def test_knob_actuator_survives_whole_fleet_outage(model, params):
    # all replicas dead between a kill and the loop's respawn: staged
    # changes are KEPT (applied at the next safe point), decisions are
    # skipped, nothing raises — knob actuation must never be the thing
    # that kills a recovering fleet
    from kube_sqs_autoscaler_tpu.fleet import WorkerPool

    pool = WorkerPool(lambda p: _CycleStubWorker(p), min=1, max=2,
                      initial=1)
    actuator = KnobActuator(pool, armed=(KNOB_SLOT_LIMIT,))
    depth = {"v": 0}
    policy = ReactiveKnobPolicy(
        actuator, lambda: depth["v"], high=10, low=2,
    )
    actuator.set(KNOB_SLOT_LIMIT, 1)
    pool.kill_worker(0)
    pool.run_cycle()  # declares the only replica dead
    assert actuator.apply() == []  # kept, not raised, not dropped
    assert actuator.pending == {KNOB_SLOT_LIMIT: 1}
    policy.evaluate()  # skipped, not fatal
    with pytest.raises(KnobError, match="no live workers"):
        actuator.set(KNOB_SLOT_LIMIT, 2)  # direct sets still fail loud
    # the loop respawns a replica: the staged change lands
    pool.scale_up()
    applied = actuator.apply()
    assert [c["knob"] for c in applied] == [KNOB_SLOT_LIMIT]
    assert pool.members[-1].worker.batcher.slot_limit == 1


def test_knob_actuator_retargets_after_crash_restart(model, params):
    # a controller restart replaces the pool: the actuator must
    # actuate the LIVE plane, not the abandoned pre-crash one
    worker_a, _, _ = _worker(model, params)
    worker_b, _, _ = _worker(model, params, decode_block=4)
    worker_b.batcher.adopt_engine(worker_a.batcher)
    actuator = KnobActuator(worker_a, armed=(KNOB_DECODE_BLOCK,))
    actuator.set(KNOB_DECODE_BLOCK, 8)
    actuator.retarget(worker_b)
    actuator.apply()
    assert worker_b.batcher.decode_block == 8
    assert worker_a.batcher.decode_block == 4  # the corpse untouched


def test_knob_reconcile_covers_replicas_spawned_after_change():
    # a replica spawned AFTER a slot_limit change constructs at the
    # default; the per-cycle reconcile pass re-asserts the actuated
    # operating point so the fleet never runs split-brain
    from kube_sqs_autoscaler_tpu.fleet import WorkerPool

    pool = WorkerPool(lambda p: _CycleStubWorker(p), min=1, max=3,
                      initial=1)
    actuator = KnobActuator(pool, armed=(KNOB_SLOT_LIMIT,))
    actuator.set(KNOB_SLOT_LIMIT, 1)
    actuator.apply()
    pool.scale_up()  # fresh replica at the default (None)
    fresh = pool.members[-1].worker.batcher
    assert fresh.slot_limit is None
    assert actuator.apply() == []  # no new change — reconcile only
    assert fresh.slot_limit == 1
    # ...and the journal/change stream records ONE change, not a
    # re-apply per spawn
    assert actuator.changes_total == 1


def test_shards_knob_converges_with_multi_pod_scale_steps(model, params):
    # scale_up_pods/scale_down_pods step toward the clamps; the knob
    # must land EXACTLY on the requested value, not orbit it
    from kube_sqs_autoscaler_tpu.fleet.sharded import ShardedWorkerPool
    from kube_sqs_autoscaler_tpu.fleet.worker import FleetWorker

    queue = FakeMessageQueue()
    config = ServiceConfig(
        queue_url="sched://sh2", batch_size=2, seq_len=PROMPT,
        generate_tokens=TOKENS, decode_block=2, shards=3,
    )
    pool = ShardedWorkerPool(
        lambda p: FleetWorker(queue, params, model, config, pool=p),
        min=1, max=3, initial=1, scale_up_pods=2, scale_down_pods=2,
    )
    actuator = KnobActuator(pool, armed=("shards",))
    actuator.set("shards", 2)
    actuator.apply()
    assert pool.replicas == 2  # exactly, not 1 or 3
    assert (pool.scale_up_pods, pool.scale_down_pods) == (2, 2)


def test_learned_knob_policy_consumes_delta_once(model, params):
    from kube_sqs_autoscaler_tpu.sched.knobs import LearnedKnobPolicy

    worker, _, _ = _worker(model, params)
    actuator = KnobActuator(worker, armed=(KNOB_DECODE_BLOCK,))

    class _Brain:
        # the LearnedPolicy knob-head contract: a delta per DECIDED
        # tick, consumed by take_knob_delta
        last_knob_delta = 1

        def take_knob_delta(self):
            delta, self.last_knob_delta = self.last_knob_delta, None
            return delta

    brain = _Brain()
    policy = LearnedKnobPolicy(actuator, brain, ladder=(2, 4, 8))
    policy.evaluate()  # consumes the +1: one rung up
    actuator.apply()
    assert worker.batcher.decode_block == 8  # 4 -> 8
    policy.evaluate()  # metric-failure tick: no new decision, no step
    actuator.apply()
    assert worker.batcher.decode_block == 8
    # rebind after a restart: the fresh brain's deltas drive the knob
    fresh = _Brain()
    fresh.last_knob_delta = -1
    policy.rebind(fresh)
    policy.evaluate()
    actuator.apply()
    assert worker.batcher.decode_block == 4


def test_learned_policy_take_knob_delta_semantics():
    from kube_sqs_autoscaler_tpu.core.policy import PolicyConfig
    from kube_sqs_autoscaler_tpu.learn.checkpoint import PolicyCheckpoint
    from kube_sqs_autoscaler_tpu.learn.policy import LearnedPolicy

    checkpoint = PolicyCheckpoint(
        theta=init_params(4, 8, knob_head=True), hidden=8,
        knob_head=True,
    )
    policy = LearnedPolicy(
        checkpoint, policy=PolicyConfig(), poll_interval=5.0, max_pods=5,
    )
    policy.last_knob_delta = 1
    assert policy.take_knob_delta() == 1
    assert policy.take_knob_delta() is None  # consumed


def test_drive_loop_fresh_episode_on_shared_scheduler():
    # a previous episode's stop (max_ticks) must not silently zero the
    # next one on the same caller-provided scheduler
    loop, _, col = _loop_setup()
    sched = EventScheduler(loop.clock)
    drive_loop(loop, max_ticks=3, scheduler=sched)
    assert len(col.records) == 3
    drive_loop(loop, max_ticks=2, scheduler=sched)
    assert len(col.records) == 5


# ---------------------------------------------------------------------------
# The knobs bench: tier-1 smoke (timing gates off), full battery slow
# ---------------------------------------------------------------------------


def test_knobs_bench_smoke(tmp_path):
    import bench

    out = tmp_path / "BENCH_knobs.json"
    summary = bench.run_knobs_suite(
        output=str(out), timing_gates=False,
        burst=6, trickle=3, parity_messages=6, batch_size=2,
        base_pace_s=0.0, per_token_pace_s=0.0,
    )
    assert summary["metric"] == "knob_actuation_win"
    artifact = json.loads(out.read_text())
    assert artifact["suite"] == "knobs"
    parity = artifact["parity"]
    assert parity["records_identical"] and parity["replies_identical"]
    assert (parity["cycles"]["fleet-driver"]
            == parity["cycles"]["scheduler"])
    for name, episode in artifact["episodes"].items():
        assert episode["answered"] == episode["requests"], name
        assert episode["duplicates"] == 0, name
    changes = artifact["episodes"]["adaptive"]["knob_changes"]
    values = [c["value"] for c in changes]
    assert 16 in values and 2 in values  # both directions exercised


@pytest.mark.slow
def test_knobs_bench_full_battery(tmp_path):
    import bench

    out = tmp_path / "BENCH_knobs_full.json"
    summary = bench.run_knobs_suite(output=str(out))
    artifact = json.loads(out.read_text())
    win = artifact["win"]
    assert (win["tokens_per_second"]["adaptive"]
            > win["tokens_per_second"]["static-low"])
    assert (win["interactive_over_slo_s"]["adaptive"]
            < win["interactive_over_slo_s"]["static-high"])
    assert win["interactive_over_slo_s"]["static-high"] > 0
    assert summary["vs_baseline"] > 1.0
