"""Topology-aware collective routing (comms/topology, comms/routing):
the link-graph builders, the route planner's size regimes, the
per-link virtual-time ledger's no-oversubscription contract, the
scheduler's routed dispatch order + coalescer seam fix, and the
topology=None byte-identity battery on the real engine.
"""

import json
import time

import pytest

np = pytest.importorskip("numpy")
jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from kube_sqs_autoscaler_tpu.comms import (  # noqa: E402
    EVACUATION_KV,
    SETTLE_PULL,
    SMALL_OP_BYTES,
    CollectiveScheduler,
    RoutePlanner,
    TransferOp,
    assert_no_oversubscription,
    ring_topology,
    simulate_schedule,
    topology_from_geometry,
    two_tier_topology,
)
from kube_sqs_autoscaler_tpu.obs.lifecycle import (  # noqa: E402
    LifecycleRegistry,
)
from kube_sqs_autoscaler_tpu.workloads.model import (  # noqa: E402
    ModelConfig,
    init_params,
)

PROMPT, TOKENS, BLOCK = 8, 5, 2


@pytest.fixture(scope="module")
def tiny():
    config = ModelConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
        max_seq_len=PROMPT + TOKENS, dtype=jnp.float32,
    )
    return init_params(jax.random.key(0), config), config


def prompts_for(n, seed=7, vocab=64):
    rng = np.random.default_rng(seed)
    return [
        rng.integers(1, vocab, rng.integers(2, PROMPT + 1))
        .astype(np.int32)
        for _ in range(n)
    ]


# ---------------------------------------------------------------------------
# Topology builders
# ---------------------------------------------------------------------------


def test_ring_topology_shape():
    topo = ring_topology(8)
    assert topo.kind == "ring"
    assert topo.nodes == sorted(
        [f"shard:{i}" for i in range(8)] + ["host"]
    )
    # 8 bidirectional ring edges + 2 gateway uplinks (shards 0 and 4)
    assert len(topo.links) == 16 + 4
    assert topo.link("shard:0", "host") is not None
    assert topo.link("shard:4", "host") is not None
    assert topo.link("shard:1", "host") is None


def test_torus_topology_shape_and_paths():
    topo = topology_from_geometry("torus", shards=16)
    assert topo.kind == "torus"
    assert len(topo.nodes) == 17
    # every shard has degree 4 on the 4x4 torus + 2 gateway uplinks
    assert len(topo.links) == 16 * 4 + 4
    path = topo.shortest_path("shard:15", "host")
    assert len(path) == 3 and path[-1].dst == "host"
    # exactly as many edge-disjoint routes into staging as gateways
    paths = topo.disjoint_paths("shard:1", "host", k=4)
    assert len(paths) == 2
    gateways = {p[-1].src for p in paths}
    assert gateways == {"shard:0", "shard:8"}


def test_small_torus_does_not_double_wrap():
    # a 2-wide axis must not wrap (the wrap edge would duplicate the
    # mesh edge); shards=2 factors to 1x2
    topo = topology_from_geometry("torus", shards=2)
    assert len(topo.nodes) == 3
    assert len(topo.links) == 2 + 2  # one ICI pair + one gateway pair


def test_two_tier_topology_bridges_over_host():
    topo = two_tier_topology(2, 4)
    assert topo.kind == "two-tier"
    # island rings (8 directed each) + one DCN gateway pair per island
    assert len(topo.links) == 16 + 4
    path = topo.shortest_path("shard:1", "shard:5")
    names = [link.name for link in path]
    assert "host" in {link.src for link in path} | {
        link.dst for link in path
    }, names


def test_ensure_node_wires_unknown_endpoints_to_host():
    topo = ring_topology(4)
    assert topo.shortest_path("prefill", "decode-plane") is not None
    assert topo.link("prefill", "host") is not None


def test_topology_from_geometry_rejects_unknown_kind():
    with pytest.raises(ValueError):
        topology_from_geometry("hypercube", shards=4)


# ---------------------------------------------------------------------------
# Route planner: size regimes
# ---------------------------------------------------------------------------


def test_planner_small_op_takes_single_latency_minimal_path():
    topo = topology_from_geometry("torus", shards=16)
    planner = RoutePlanner(topo)
    plan = planner.plan("shard:5", "host", 1024)
    assert len(plan.chunks) == 1
    assert plan.chunks[0].nbytes == 1024
    assert len(plan.chunks[0].path) == 3  # two ICI hops + the uplink


def test_planner_large_op_chunks_across_disjoint_paths():
    topo = topology_from_geometry("torus", shards=16)
    planner = RoutePlanner(topo)
    nbytes = 8 << 20
    plan = planner.plan("shard:1", "host", nbytes)
    assert sum(c.nbytes for c in plan.chunks) == nbytes
    assert len(plan.paths) == 2  # both gateways used
    # pipelined: no chunk exceeds the pipeline grain
    assert max(c.nbytes for c in plan.chunks) <= planner.pipeline_bytes
    assert len(plan.chunks) >= 8  # 8 MiB / 1 MiB grain


def test_planner_local_and_first_hop():
    topo = ring_topology(4)
    planner = RoutePlanner(topo)
    assert planner.plan("host", "host", 4096).local
    assert planner.first_hop("host", "host", 4096) is None
    assert planner.first_hop("shard:1", "host", 64) in (
        "shard:1->shard:0", "shard:1->shard:2",
    )


# ---------------------------------------------------------------------------
# The ledger: no schedule oversubscribes a link (property test)
# ---------------------------------------------------------------------------


def test_no_schedule_oversubscribes_any_link():
    topo = topology_from_geometry("torus", shards=16)
    topo.ensure_node("prefill")
    topo.ensure_node("decode-plane")
    endpoints = (
        [f"shard:{i}" for i in range(16)]
        + ["host", "prefill", "decode-plane"]
    )
    for seed in (0, 1, 2):
        rng = np.random.default_rng(seed)
        ops = []
        for _ in range(24):
            src, dst = rng.choice(endpoints, size=2, replace=False)
            ops.append({
                "kind": EVACUATION_KV, "source": str(src),
                "destination": str(dst),
                "nbytes": int(rng.integers(1 << 10, 16 << 20)),
            })
        for routed in (True, False):
            result = simulate_schedule(ops, topo, routed=routed)
            assert_no_oversubscription(result.ledger)
            for op in result.ops:
                assert op["finish_s"] >= op["start_s"] >= 0.0
            assert result.makespan == max(
                op["finish_s"] for op in result.ops
            )


def test_contended_torus_routed_beats_when_only():
    # the BENCH_r24 gate episode, pinned deterministically: sources
    # proximal to gateway 0 funnel through one uplink WHEN-only, while
    # routing chunks across both gateways
    topo = topology_from_geometry("torus", shards=16)
    ops = [
        {"kind": EVACUATION_KV, "source": f"shard:{s}",
         "destination": "host", "nbytes": 8 << 20}
        for s in (1, 2, 3, 4, 5, 13)
    ]
    when = simulate_schedule(ops, topo, routed=False)
    routed = simulate_schedule(ops, topo, routed=True)
    assert when.makespan / routed.makespan >= 1.5
    # the schedule exports hop lists and per-link utilization
    assert all(op["hops"] for op in routed.ops)
    assert routed.link_utilization["shard:0->host"] > 0.5


# ---------------------------------------------------------------------------
# Scheduler: the coalescer seam fix (applies with AND without routing)
# ---------------------------------------------------------------------------


def test_coalesce_group_seals_at_small_bytes_threshold():
    comms = CollectiveScheduler()
    for _ in range(5):
        comms.submit(TransferOp(SETTLE_PULL, "host", 20480))
    # 3 x 20 KiB = 60 KiB fits under the 64 KiB threshold; the 4th op
    # would cross it, sealing the group: 2 dispatches, all 5 coalesced
    assert comms.flush() == 2
    cc = comms.counters()
    assert cc["transfer_dispatches"] == 2
    assert cc["coalesced_ops"] == 5
    assert cc["dispatched_ops"] == 5


def test_single_small_op_still_one_dispatch():
    comms = CollectiveScheduler()
    comms.submit(TransferOp(SETTLE_PULL, "host", SMALL_OP_BYTES))
    assert comms.flush() == 1
    assert comms.counters()["coalesced_ops"] == 0


# ---------------------------------------------------------------------------
# Scheduler: routed dispatch + route stamps
# ---------------------------------------------------------------------------


def test_scheduler_routes_flushed_and_recorded_ops():
    reg = LifecycleRegistry(now_fn=time.perf_counter)
    topo = topology_from_geometry("torus", shards=16)
    comms = CollectiveScheduler(lifecycle=reg, topology=topo)
    reg.arrival("rA")
    comms.submit(TransferOp(
        EVACUATION_KV, "host", 8 << 20,
        source="shard:1", rids=("rA",),
    ))
    comms.flush()
    reg.arrival("rB")
    comms.record(
        EVACUATION_KV, "host", 4 << 20,
        source="shard:5", rids=("rB",),
    )
    cc = comms.counters()
    assert cc["routing"]["routed_ops"] == 2
    assert cc["routing"]["route_chunks"] >= 12
    assert cc["routing"]["link_bytes"]["shard:0->host"] > 0
    assert_no_oversubscription(comms.ledger)
    # both traces carry their op's hop lists, zipped onto the spans
    for rid in ("rA", "rB"):
        (trace,) = [t for t in reg.open_traces() if t.rid == rid]
        assert trace.routes and trace.routes[0]
    # sequential flushes never falsely overlap: virtual now advanced
    assert comms.vt_now > 0


def test_scheduler_local_moves_route_as_empty():
    reg = LifecycleRegistry(now_fn=time.perf_counter)
    comms = CollectiveScheduler(
        lifecycle=reg, topology=ring_topology(2),
    )
    reg.arrival("rL")
    comms.record(SETTLE_PULL, "host", 512, source="host", rids=("rL",))
    assert comms.counters()["routing"]["local_ops"] == 1
    (trace,) = [t for t in reg.open_traces() if t.rid == "rL"]
    assert trace.routes == [[]]  # alignment entry, no hops


def test_topology_none_counters_have_no_routing_key():
    comms = CollectiveScheduler()
    comms.submit(TransferOp(SETTLE_PULL, "host", 64))
    comms.flush()
    cc = comms.counters()
    assert "routing" not in cc
    assert comms.topology_snapshot() is None
    op = TransferOp(SETTLE_PULL, "host", 64, source="shard:1")
    assert comms._coalesce_key(op) == op.coalesce_key()


def test_export_gauges_emits_per_link_series():
    from kube_sqs_autoscaler_tpu.obs.prometheus import WorkloadMetrics

    comms = CollectiveScheduler(topology=ring_topology(4))
    comms.record(EVACUATION_KV, "host", 1 << 20, source="shard:1")
    metrics = WorkloadMetrics()
    comms.export_gauges(metrics)
    body = metrics.render()
    assert 'link_bytes_total{link="shard:1->shard:0"}' in body
    assert "link_utilization{" in body
    # no topology, no phantom series
    bare = WorkloadMetrics()
    CollectiveScheduler().export_gauges(bare)
    assert "link_bytes_total" not in bare.render()


# ---------------------------------------------------------------------------
# /debug/topology endpoint
# ---------------------------------------------------------------------------


def test_debug_topology_endpoint_serves_snapshot():
    import urllib.request

    from kube_sqs_autoscaler_tpu.obs.prometheus import ControllerMetrics
    from kube_sqs_autoscaler_tpu.obs.server import ObservabilityServer

    comms = CollectiveScheduler(topology=ring_topology(4))
    comms.record(EVACUATION_KV, "host", 2 << 20, source="shard:2")
    server = ObservabilityServer(
        ControllerMetrics(), host="127.0.0.1", port=0, comms=comms,
    )
    server.start()
    try:
        body = json.loads(
            urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/debug/topology"
            ).read().decode()
        )
        assert body["topology"]["kind"] == "ring"
        assert body["routing"]["routed_ops"] == 1
        assert body["ledger"]["link_bytes"]
    finally:
        server.stop()


def test_debug_topology_404_without_a_topology():
    import urllib.error
    import urllib.request

    from kube_sqs_autoscaler_tpu.obs.prometheus import ControllerMetrics
    from kube_sqs_autoscaler_tpu.obs.server import ObservabilityServer

    server = ObservabilityServer(
        ControllerMetrics(), host="127.0.0.1", port=0,
        comms=CollectiveScheduler(),
    )
    server.start()
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/debug/topology"
            )
        assert err.value.code == 404
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# The real engine: topology=None byte-identity at shards 1/2/4
# ---------------------------------------------------------------------------


def run_evac_episode(tiny, comms, *, lifecycle=None, shards=2):
    from kube_sqs_autoscaler_tpu.workloads.shard_plane import (
        ShardedBatcher,
    )

    params, config = tiny
    plane = ShardedBatcher(
        params, config, shards=shards, shard_slots=2,
        prompt_len=PROMPT, generate_tokens=TOKENS, decode_block=BLOCK,
    )
    plane.lifecycle = lifecycle
    if comms is not None:
        plane.attach_comms(comms)
    prompts = prompts_for(6)
    queue = [(ids, {"MessageId": f"r{i}"})
             for i, ids in enumerate(prompts)]
    replies = []

    def fill():
        n = min(len(queue), len(plane.free_slots))
        if n:
            if lifecycle is not None:
                for _, payload in queue[:n]:
                    lifecycle.arrival(payload["MessageId"])
            plane.submit_many(queue[:n])
            del queue[:n]

    def collect(finished):
        for payload, toks in finished:
            replies.append(
                (payload["MessageId"], tuple(int(t) for t in toks))
            )
            if lifecycle is not None:
                lifecycle.settle(payload["MessageId"])

    fill()
    collect(plane.step())
    collect(plane.step())
    evacuated = plane.take_shard_inflight(shards - 1)
    resumes = [
        (prompts[int(p["MessageId"][1:])], p, produced, budget, t)
        for p, produced, budget, t in evacuated
    ]
    for _ in range(600):
        fill()
        if resumes and plane.free_slots:
            n = min(len(resumes), len(plane.free_slots))
            admitted = plane.submit_resume(resumes[:n])
            del resumes[:len(admitted)]
        collect(plane.step())
        if not queue and not resumes and plane.active == 0:
            break
    return replies, {
        "host_transfers": plane.host_transfers,
        "decode_dispatches": plane.decode_dispatches,
        "insert_dispatches": plane.insert_dispatches,
    }


@pytest.mark.parametrize("shards", (1, 2, 4))
def test_topology_none_byte_identity_on_engine(tiny, shards):
    base_replies, base_counters = run_evac_episode(tiny, None,
                                                  shards=shards)
    assert sorted(r for r, _ in base_replies) == sorted(
        f"r{i}" for i in range(6)
    )
    when_comms = CollectiveScheduler()
    when_replies, when_counters = run_evac_episode(
        tiny, when_comms, shards=shards,
    )
    assert when_replies == base_replies
    when_cc = when_comms.counters()
    assert "routing" not in when_cc

    routed_comms = CollectiveScheduler(
        topology=topology_from_geometry("torus", shards=shards),
    )
    routed_replies, routed_counters = run_evac_episode(
        tiny, routed_comms, shards=shards,
    )
    # routing changes the MODEL, never the math or the engine work
    assert routed_replies == base_replies
    assert routed_counters == when_counters
    routed_cc = routed_comms.counters()
    assert routed_cc["routing"]["routed_ops"] >= 1
    assert_no_oversubscription(routed_comms.ledger)
    # the grouping-independent counter family is byte-identical;
    # only the coalesce grouping (first-hop-aware keys) may differ
    varying = ("transfer_dispatches", "coalesced_ops", "routing")
    assert {
        k: v for k, v in when_cc.items() if k not in varying
    } == {
        k: v for k, v in routed_cc.items() if k not in varying
    }


def test_routes_appear_in_exported_span_args(tiny):
    from kube_sqs_autoscaler_tpu.obs.trace import request_trace_events

    reg = LifecycleRegistry(now_fn=time.perf_counter)
    comms = CollectiveScheduler(
        lifecycle=reg,
        topology=topology_from_geometry("torus", shards=2),
    )
    run_evac_episode(tiny, comms, lifecycle=reg, shards=2)
    traces = reg.done_traces() + reg.open_traces()
    assert any(
        any(hops for hops in getattr(t, "routes", []))
        for t in traces
    )
    events = request_trace_events(traces, time_origin=0.0)
    routed_spans = [
        e for e in events
        if e.get("ph") == "X" and e.get("args", {}).get("route")
    ]
    assert routed_spans
    # hops are link names, multi-hop across the gateway
    assert all(
        "->" in hop
        for e in routed_spans for path in e["args"]["route"]
        for hop in path
    )


# ---------------------------------------------------------------------------
# The routes bench: tier-1 smoke (timing gates off), full battery slow
# ---------------------------------------------------------------------------


def test_routes_bench_smoke(tmp_path):
    import bench

    out = tmp_path / "BENCH_routes.json"
    summary = bench.run_routes_suite(str(out), timing_gates=False)
    assert summary["metric"] == "routes_contended_speedup"
    assert summary["value"] >= 1.5
    artifact = json.loads(out.read_text())
    assert artifact["suite"] == "routes"
    assert artifact["contended"]["speedup"] >= 1.5
    assert artifact["evacuation"]["spans_with_routes"] >= 1
    assert artifact["scaling_curve"] is None  # timing battery slow-tier


@pytest.mark.slow
def test_routes_bench_full_battery(tmp_path):
    import bench

    out = tmp_path / "BENCH_routes_full.json"
    bench.run_routes_suite(str(out))
    artifact = json.loads(out.read_text())
    rates = [p["tokens_per_vs"] for p in artifact["scaling_curve"]]
    assert rates == sorted(rates)
