"""Control-loop behavioral tests.

Ports all six main_test.go scenarios with their exact expected replica
outcomes — but on a FakeClock, so the reference's ~56 s of real sleeps run
in milliseconds (SURVEY.md §4, §7.1 step 6).  Sleep budget maps to tick
count: the reference test sleeps N seconds with poll period P, giving
floor(N/P) loop ticks.  Queue depth is seeded before the run, matching the
reference tests' set-right-after-launch (its first tick happens one full
poll period after launch).

Also covers the wiring the reference never tests: metric failures keeping
the loop alive, failed actuations not advancing cooldowns, and the
up-cooling `continue` skipping scale-down at the loop (not just policy)
level.
"""

import logging

from kube_sqs_autoscaler_tpu.core.clock import FakeClock
from kube_sqs_autoscaler_tpu.core.loop import ControlLoop, LoopConfig
from kube_sqs_autoscaler_tpu.core.policy import PolicyConfig
from kube_sqs_autoscaler_tpu.metrics import FakeQueueService, QueueMetricSource
from kube_sqs_autoscaler_tpu.scale import FakeDeploymentAPI, PodAutoScaler


def make_system(
    *,
    init_pods: int,
    max_pods: int = 5,
    min_pods: int = 1,
    up_pods: int = 1,
    down_pods: int = 1,
    poll: float = 1.0,
    up_cool: float = 1.0,
    down_cool: float = 1.0,
    up_msgs: int = 100,
    down_msgs: int = 3,
    depths: tuple[int, int, int] = (100, 100, 100),
):
    """The reference integration-test fixture (main_test.go:241-304), fast."""
    api = FakeDeploymentAPI.with_deployments(
        "namespace", init_pods, "deploy", "deploy-no-scale"
    )
    scaler = PodAutoScaler(
        client=api, max=max_pods, min=min_pods, scale_up_pods=up_pods,
        scale_down_pods=down_pods, deployment="deploy", namespace="namespace",
    )
    queue = FakeQueueService.with_depths(*depths)
    source = QueueMetricSource(client=queue, queue_url="example.com")
    clock = FakeClock()
    loop = ControlLoop(
        scaler,
        source,
        LoopConfig(
            poll_interval=poll,
            policy=PolicyConfig(
                scale_up_messages=up_msgs,
                scale_down_messages=down_msgs,
                scale_up_cooldown=up_cool,
                scale_down_cooldown=down_cool,
            ),
        ),
        clock=clock,
    )
    return loop, api, queue, clock


def test_run_reach_min_replicas():
    # main_test.go:19-54 — depth 3 (1+1+1), init 3, 10 s @ 1 s poll -> min 1
    loop, api, _, _ = make_system(init_pods=3, depths=(1, 1, 1))
    loop.run(max_ticks=10)
    assert api.replicas("deploy") == 1
    assert api.replicas("deploy-no-scale") == 3


def test_run_reach_max_replicas():
    # main_test.go:56-91 — depth 300, up-threshold 300, init 3 -> max 5
    loop, api, _, _ = make_system(
        init_pods=3, up_msgs=300, down_msgs=10, depths=(100, 100, 100)
    )
    loop.run(max_ticks=10)
    assert api.replicas("deploy") == 5
    assert api.replicas("deploy-no-scale") == 3


def test_run_scale_up_cooldown_limits_growth():
    # main_test.go:93-127 — poll 5 s, cooldowns 10 s, depth 300 >= 300,
    # init 3, 15 s window -> exactly 4 (cooling, fire, cooling)
    loop, api, _, _ = make_system(
        init_pods=3, poll=5.0, up_cool=10.0, down_cool=10.0,
        up_msgs=300, down_msgs=10, depths=(100, 100, 100),
    )
    loop.run(max_ticks=3)
    assert api.replicas("deploy") == 4


def test_run_scale_down_cooldown_limits_shrink():
    # main_test.go:129-163 — depth 3 <= 3, init 3, 15 s window -> exactly 2
    loop, api, _, _ = make_system(
        init_pods=3, poll=5.0, up_cool=10.0, down_cool=10.0,
        up_msgs=100, down_msgs=3, depths=(1, 1, 1),
    )
    loop.run(max_ticks=3)
    assert api.replicas("deploy") == 2


def test_run_reach_min_with_scaling_pod_num():
    # main_test.go:165-201 — step 100 down from 100 pods, 3 s -> clamp to 1
    loop, api, _, _ = make_system(
        init_pods=100, max_pods=100, min_pods=1, up_pods=100, down_pods=100,
        depths=(1, 1, 1),
    )
    loop.run(max_ticks=3)
    assert api.replicas("deploy") == 1


def test_run_reach_max_with_scaling_pod_num():
    # main_test.go:203-239 — step 100 up from 3 pods, 3 s -> clamp to 100
    loop, api, _, _ = make_system(
        init_pods=3, max_pods=100, min_pods=1, up_pods=100, down_pods=100,
        depths=(100, 100, 100),
    )
    loop.run(max_ticks=3)
    assert api.replicas("deploy") == 100


# --- wiring the reference never tests (SURVEY.md §4 gaps) ---


def test_sleep_first_then_poll():
    # main.go:41 — no observation happens before the first full poll period
    loop, _, queue, clock = make_system(init_pods=3)
    loop.run(max_ticks=1)
    assert clock.sleeps == [1.0]
    assert queue.get_calls == 1


def test_metric_failure_skips_tick_and_loop_survives(caplog):
    loop, api, queue, _ = make_system(init_pods=3, depths=(1, 1, 1))
    queue.fail_next_get = ConnectionError("SQS down")
    with caplog.at_level(logging.ERROR):
        loop.run(max_ticks=2)
    # tick 1 failed (no scale), tick 2 scaled down
    assert api.replicas("deploy") == 2
    assert any("Failed to get SQS messages" in r.message for r in caplog.records)


def test_failed_scale_does_not_advance_cooldown(caplog):
    # A failed actuation must leave the timestamp alone (main.go:57-60), so
    # the very next tick retries instead of entering a fresh cooldown.
    loop, api, _, _ = make_system(
        init_pods=3, poll=5.0, up_cool=10.0, down_cool=10.0,
        up_msgs=300, down_msgs=10,
    )
    api.fail_next_update = ConnectionError("conflict")  # poisons tick 2's update
    with caplog.at_level(logging.ERROR):
        loop.run(max_ticks=3)
    # t=5 cooling; t=10 fire -> update fails (timestamp NOT advanced);
    # t=15 fire again (10+10>15 would cool only if the failure had advanced it)
    assert api.replicas("deploy") == 4
    assert any("Failed scaling up" in r.message for r in caplog.records)


def test_up_cooling_skips_down_branch_in_loop(caplog):
    # Overlapping thresholds: up in cooldown + depth in both bands -> the
    # reference `continue`s (main.go:54) without even logging the down skip.
    loop, api, _, _ = make_system(
        init_pods=3, poll=5.0, up_cool=100.0, down_cool=0.0,
        up_msgs=3, down_msgs=1000, depths=(1, 1, 1),
    )
    with caplog.at_level(logging.INFO):
        loop.run(max_ticks=2)
    assert api.replicas("deploy") == 3  # neither direction actuated
    messages = [r.message for r in caplog.records]
    assert any("skipping scale up" in m for m in messages)
    assert not any("skipping scale down" in m for m in messages)


def test_overlapping_thresholds_scale_up_then_down_same_tick():
    # if + if (main.go:51,65): one tick can do both directions
    loop, api, _, _ = make_system(
        init_pods=3, up_cool=0.0, down_cool=0.0,
        up_msgs=3, down_msgs=1000, depths=(1, 1, 1),
    )
    loop.run(max_ticks=1)
    # up fires (3 -> 4), then down fires (4 -> 3)
    assert api.replicas("deploy") == 3
    assert api.update_calls == 2


def test_boundary_noop_refreshes_cooldown():
    # SURVEY §2.2-C2 item 8: a no-op at the max bound returns success, so the
    # timestamp advances and the next tick is in cooldown.
    loop, api, _, _ = make_system(
        init_pods=5, poll=5.0, up_cool=6.0, down_cool=6.0,
        up_msgs=100, down_msgs=10,
    )
    loop.run(max_ticks=3)
    # t=5: grace over (0+6>5 cooling!) — actually 6>5 so cooling; t=10:
    # fire no-op, refresh to 10; t=15: 10+6>15 cooling. get_calls==3 but
    # update never called (always at bound).
    assert api.update_calls == 0
    assert api.replicas("deploy") == 5


def test_stop_exits_run():
    loop, _, _, clock = make_system(init_pods=3)
    clock.at(3.5, loop.stop)  # fires during the 4th sleep: tick 4 is skipped
    loop.run()
    assert loop.ticks == 3
