"""The 4-axis mesh — pp x dp x sp x tp — and MoE x pp x tp.

The flagship large-model pod layout (VERDICT r4 next #5): stages over
``pipe``, Megatron head/ff shards over ``model``, ring attention over
``seq``, batch over ``data``.  The invariant everywhere: adding mesh
axes changes the schedule and the communication pattern, never the math
— losses (and therefore the gradients driving step 2) match the plain
pp x dp truth on the same batch.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kube_sqs_autoscaler_tpu.workloads.llama import LlamaConfig
from kube_sqs_autoscaler_tpu.workloads.model import ModelConfig
from kube_sqs_autoscaler_tpu.workloads.moe import MoeConfig
from kube_sqs_autoscaler_tpu.workloads.pipeline import (
    PipelineConfig,
    init_llama_pipeline_train_state,
    init_moe_pipeline_train_state,
    init_pipeline_train_state,
    make_llama_pipeline_train_step,
    make_moe_pipeline_train_step,
    make_pipeline_mesh,
    make_pipeline_train_step,
    pipeline_batch_sharding,
    place_pipeline_state,
)
from kube_sqs_autoscaler_tpu.workloads.train import TrainConfig

# fp32 so cross-mesh loss comparisons are reduction-order-tight
CFG = ModelConfig(
    vocab_size=256, d_model=64, n_heads=4, n_layers=2, d_ff=128,
    max_seq_len=64, dtype=jnp.float32,
)
LCFG = LlamaConfig(
    vocab_size=256, d_model=64, n_heads=4, n_kv_heads=2, n_layers=2,
    d_ff=128, max_seq_len=64, dtype=jnp.float32,
)


def tokens_for(mesh, vocab=256, seed=1):
    toks = jax.random.randint(jax.random.key(seed), (2, 2, 32), 0, vocab,
                              jnp.int32)
    return jax.device_put(toks, pipeline_batch_sharding(mesh))


def two_losses(mesh, schedule, init_fn, step_factory, seed=0):
    state = place_pipeline_state(mesh, init_fn(jax.random.key(seed)))
    step = step_factory(
        mesh, PipelineConfig(n_microbatches=2, schedule=schedule), state
    )
    toks = tokens_for(mesh)
    state, l1 = step(state, toks)
    state, l2 = step(state, toks)
    return float(l1), float(l2)


def test_4axis_mesh_shape():
    mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=2,
                              model_parallel=2, seq_parallel=2)
    assert dict(mesh.shape) == {"pipe": 2, "data": 1, "seq": 2, "model": 2}


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_4axis_losses_match_plain_pp(schedule):
    def init_fn(key):
        return init_pipeline_train_state(key, CFG, TrainConfig(), n_stages=2)

    def factory(mesh, pcfg, state):
        return make_pipeline_train_step(mesh, CFG, pcfg, TrainConfig(),
                                        state)

    ref_mesh = make_pipeline_mesh(jax.devices()[:4], pipe_parallel=2)
    mesh4 = make_pipeline_mesh(jax.devices(), pipe_parallel=2,
                               model_parallel=2, seq_parallel=2)
    r1, r2 = two_losses(ref_mesh, "gpipe", init_fn, factory)
    g1, g2 = two_losses(mesh4, schedule, init_fn, factory)
    # step 1: identical math (fp32, same batch); step 2 inherits step-1
    # gradients, so agreement pins the backward too
    np.testing.assert_allclose(g1, r1, rtol=2e-5)
    np.testing.assert_allclose(g2, r2, rtol=2e-3)


def test_llama_4axis_1f1b_matches_plain_pp():
    def init_fn(key):
        return init_llama_pipeline_train_state(key, LCFG, TrainConfig(),
                                               n_stages=2)

    def factory(mesh, pcfg, state):
        return make_llama_pipeline_train_step(mesh, LCFG, pcfg,
                                              TrainConfig(), state)

    ref_mesh = make_pipeline_mesh(jax.devices()[:4], pipe_parallel=2)
    mesh4 = make_pipeline_mesh(jax.devices(), pipe_parallel=2,
                               model_parallel=2, seq_parallel=2)
    r1, r2 = two_losses(ref_mesh, "gpipe", init_fn, factory, seed=3)
    f1, f2 = two_losses(mesh4, "1f1b", init_fn, factory, seed=3)
    np.testing.assert_allclose(f1, r1, rtol=2e-5)
    np.testing.assert_allclose(f2, r2, rtol=2e-3)


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_moe_pp_tp_matches_no_tp(schedule):
    # MoE x pp x tp: attention Megatron-sharded, each expert's ff axis
    # carved over "model" (true tensor-parallel expert compute — the
    # router stays replicated and its dispatch/combine cotangents ride
    # the f-operator sync, moe._routed_ffn grad_sync); the first loss
    # must be bitwise-level equal to the (pipe, data) run and the second
    # inherits the corrected gradients
    moe = MoeConfig(n_experts=4, top_k=2)

    def init_fn(key):
        return init_moe_pipeline_train_state(key, CFG, moe, TrainConfig(),
                                             n_stages=2)

    def factory(mesh, pcfg, state):
        return make_moe_pipeline_train_step(mesh, CFG, moe, pcfg,
                                            TrainConfig(), state)

    # both meshes keep data=2 so the per-data-shard routing groups match
    ref_mesh = make_pipeline_mesh(jax.devices()[:4], pipe_parallel=2)
    tp_mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=2,
                                 model_parallel=2)
    r1, r2 = two_losses(ref_mesh, "gpipe", init_fn, factory, seed=5)
    g1, g2 = two_losses(tp_mesh, schedule, init_fn, factory, seed=5)
    np.testing.assert_allclose(g1, r1, rtol=2e-5)
    np.testing.assert_allclose(g2, r2, rtol=2e-3)


def test_llama_moe_pp_tp_runs():
    # llama MoE under pp x tp: the fused SwiGLU expert projection splits
    # into gate/up stacks so each expert's ff columns shard contiguously
    # (pipeline.stack_llama_layers); pin a finite two-step run
    moe = MoeConfig(n_experts=4, top_k=2)
    tp_mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=2,
                                 model_parallel=2)
    state = place_pipeline_state(
        tp_mesh,
        init_moe_pipeline_train_state(jax.random.key(7), LCFG, moe,
                                      TrainConfig(), n_stages=2,
                                      llama=True),
    )
    step = make_moe_pipeline_train_step(
        tp_mesh, LCFG, moe, PipelineConfig(n_microbatches=2),
        TrainConfig(), state, llama=True,
    )
    toks = tokens_for(tp_mesh)
    state, l1 = step(state, toks)
    state, l2 = step(state, toks)
    assert np.isfinite(float(l1)) and np.isfinite(float(l2))
    assert float(l2) < float(l1)  # optimizing


@pytest.mark.parametrize("llama", [False, True])
def test_zigzag_1f1b_matches_zigzag_gpipe(llama):
    # the last training-matrix hole (VERDICT r4 next #9): the zig-zag
    # objective under the explicitly-scheduled 1F1B backward — the
    # permuted layout precomputes its targets outside the body and the
    # sp seams carry the permuted-validity mask, so both schedules
    # compute the same mean (and step-2 agreement pins the gradients)
    from kube_sqs_autoscaler_tpu.workloads.pipeline import (
        make_zigzag_pipeline_train_step,
    )

    cfg = LCFG if llama else CFG
    init = (init_llama_pipeline_train_state if llama
            else init_pipeline_train_state)
    mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=2,
                              seq_parallel=2)

    def two(schedule):
        state = place_pipeline_state(
            mesh, init(jax.random.key(3), cfg, TrainConfig(), n_stages=2)
        )
        step = make_zigzag_pipeline_train_step(
            mesh, cfg, PipelineConfig(n_microbatches=2, schedule=schedule),
            TrainConfig(), state, llama=llama,
        )
        toks = tokens_for(mesh)
        state, l1 = step(state, toks)
        state, l2 = step(state, toks)
        return float(l1), float(l2)

    g1, g2 = two("gpipe")
    f1, f2 = two("1f1b")
    np.testing.assert_allclose(f1, g1, rtol=1e-5)
    np.testing.assert_allclose(f2, g2, rtol=2e-3)


def test_trainer_binary_zigzag_1f1b():
    from kube_sqs_autoscaler_tpu.workloads.trainer import main

    main([
        "--steps", "2", "--batch-size", "4", "--seq-len", "32",
        "--vocab-size", "256", "--d-model", "64", "--n-heads", "4",
        "--n-layers", "2", "--d-ff", "128",
        "--pipe-parallel", "2", "--seq-parallel", "2", "--zigzag",
        "--pipe-schedule", "1f1b", "--pipe-microbatches", "2",
    ])


def test_trainer_binary_4axis():
    # the CLI end to end: --pipe-parallel 2 --model-parallel 2
    # --seq-parallel 2 trains on the 8-device mesh (VERDICT r4 next #5
    # "done" criterion)
    from kube_sqs_autoscaler_tpu.workloads.trainer import main

    main([
        "--steps", "2", "--batch-size", "4", "--seq-len", "32",
        "--vocab-size", "256", "--d-model", "64", "--n-heads", "4",
        "--n-layers", "2", "--d-ff", "128",
        "--pipe-parallel", "2", "--model-parallel", "2",
        "--seq-parallel", "2", "--pipe-microbatches", "2",
    ])
