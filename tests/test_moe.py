"""MoE correctness: a 1-expert MoE must reduce exactly to the dense MLP,
routing must respect top-k and capacity invariants, and the full
dp x sp x tp x ep train step must compile over the mesh and learn.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kube_sqs_autoscaler_tpu.workloads.model import (
    ModelConfig,
    _mlp,
    forward,
    init_params,
)
from kube_sqs_autoscaler_tpu.workloads.moe import (
    MoeConfig,
    _top_k_routing,
    init_moe_params,
    init_moe_train_state,
    make_moe_train_step,
    moe_forward,
    moe_loss_fn,
    moe_mlp,
)
from kube_sqs_autoscaler_tpu.workloads.train import (
    TrainConfig,
    batch_sharding,
    make_mesh,
    place_state,
)

TINY = ModelConfig(
    vocab_size=256, d_model=128, n_heads=8, n_layers=2, d_ff=256, max_seq_len=64
)


def test_single_expert_moe_equals_dense_mlp():
    # E=1, top_k=1, ample capacity: the router has one choice with gate 1,
    # so the sparse layer must reproduce the dense MLP bit-for-bit in fp32
    config = ModelConfig(d_model=64, d_ff=128, dtype=jnp.float32)
    rng = jax.random.key(0)
    w_up = jax.random.normal(rng, (64, 128), jnp.float32) * 0.1
    w_down = jax.random.normal(jax.random.key(1), (128, 64), jnp.float32) * 0.1
    x = jax.random.normal(jax.random.key(2), (2, 16, 64), jnp.float32)

    dense = _mlp(x, {"w_up": w_up, "w_down": w_down})
    layer = {
        "router": jnp.zeros((64, 1), jnp.float32),
        "w_up_experts": w_up[None],
        "w_down_experts": w_down[None],
    }
    moe = MoeConfig(n_experts=1, top_k=1, capacity_factor=4.0)
    sparse, aux = moe_mlp(x, layer, moe)
    np.testing.assert_allclose(
        np.asarray(dense), np.asarray(sparse), rtol=1e-6, atol=1e-6
    )
    assert float(aux) == pytest.approx(1.0)  # balanced by definition


def test_top_k_must_not_exceed_n_experts():
    with pytest.raises(ValueError, match="top_k"):
        MoeConfig(n_experts=2, top_k=3)
    with pytest.raises(ValueError, match="top_k"):
        MoeConfig(n_experts=4, top_k=0)


def test_routing_invariants_with_ample_capacity():
    moe = MoeConfig(n_experts=4, top_k=2, capacity_factor=8.0)
    probs = jax.nn.softmax(
        jax.random.normal(jax.random.key(3), (2, 16, 4), jnp.float32), axis=-1
    )
    capacity = moe.capacity(16)
    dispatch, combine, aux = _top_k_routing(probs, moe, capacity)
    d = np.asarray(dispatch)
    c = np.asarray(combine)
    # every token lands in exactly top_k slots, gates renormalize to 1
    np.testing.assert_array_equal(d.sum(axis=(2, 3)), 2.0)
    np.testing.assert_allclose(c.sum(axis=(2, 3)), 1.0, rtol=1e-6)
    # no expert slot is double-booked within a batch row
    assert d.sum(axis=1).max() <= 1.0 + 1e-6
    assert float(aux) >= 1.0 - 1e-6  # Switch aux loss lower bound


def test_capacity_overflow_drops_tokens_but_stays_finite():
    # capacity 1 with 16 tokens per row: most choices overflow
    moe = MoeConfig(n_experts=2, top_k=2, capacity_factor=1e-6)
    assert moe.capacity(16) == 1
    probs = jax.nn.softmax(
        jax.random.normal(jax.random.key(4), (1, 16, 2), jnp.float32), axis=-1
    )
    dispatch, combine, _ = _top_k_routing(probs, moe, 1)
    d = np.asarray(dispatch)
    assert d.sum() <= 2.0 + 1e-6  # at most E*C=2 slots filled per row
    assert np.isfinite(np.asarray(combine)).all()


def test_moe_forward_shapes_and_finite():
    moe = MoeConfig(n_experts=4, top_k=2)
    params = init_moe_params(jax.random.key(0), TINY, moe)
    tokens = jax.random.randint(
        jax.random.key(1), (2, 32), 0, TINY.vocab_size, jnp.int32
    )
    logits, aux = moe_forward(params, tokens, TINY, moe)
    assert logits.shape == (2, 32, TINY.vocab_size)
    assert logits.dtype == jnp.float32
    assert np.isfinite(np.asarray(logits)).all()
    assert np.isfinite(float(aux))
    # attention path is shared with the dense model: same wqkv/wo names
    assert "w_up" not in params["layers"][0]
    assert params["layers"][0]["w_up_experts"].shape == (4, 128, 256)


def test_moe_train_step_sharded_over_all_four_axes_learns():
    # dp2 x sp2 x tp2 mesh; experts (E=8) shard over "data" (ep=dp)
    mesh = make_mesh(jax.devices(), model_parallel=2, seq_parallel=2)
    moe = MoeConfig(n_experts=8, top_k=2)
    train_config = TrainConfig(learning_rate=1e-2)
    state = place_state(
        mesh, init_moe_train_state(jax.random.key(0), TINY, moe, train_config)
    )
    step_fn = make_moe_train_step(mesh, TINY, moe, train_config, state)
    tokens = jax.device_put(
        jax.random.randint(jax.random.key(1), (4, 32), 0, TINY.vocab_size,
                           jnp.int32),
        batch_sharding(mesh),
    )
    losses = []
    for _ in range(4):
        state, loss = step_fn(state, tokens)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_moe_loss_includes_aux_term():
    moe_on = MoeConfig(n_experts=4, top_k=2, aux_loss_weight=1.0)
    moe_off = MoeConfig(n_experts=4, top_k=2, aux_loss_weight=0.0)
    params = init_moe_params(jax.random.key(0), TINY, moe_on)
    tokens = jax.random.randint(
        jax.random.key(1), (2, 32), 0, TINY.vocab_size, jnp.int32
    )
    with_aux = float(moe_loss_fn(params, tokens, TINY, moe_on))
    without = float(moe_loss_fn(params, tokens, TINY, moe_off))
    _, aux = moe_forward(params, tokens, TINY, moe_on)
    assert with_aux == pytest.approx(without + float(aux), rel=1e-5)


def test_moe_train_step_rejects_remat():
    # the aux-loss closure is incompatible with jax.checkpoint re-tracing;
    # the flag must fail fast, not be silently ignored
    mesh = make_mesh(jax.devices(), model_parallel=2)
    moe = MoeConfig(n_experts=4, top_k=1)
    train_config = TrainConfig(remat=True)
    state = init_moe_train_state(jax.random.key(0), TINY, moe, train_config)
    with pytest.raises(ValueError, match="remat"):
        make_moe_train_step(mesh, TINY, moe, train_config, state)


def test_routing_invariant_to_batch_reshape():
    """Decoupled capacity: the same flattened token stream routes
    identically whether presented as [B, S] or [2B, S/2] — the MLP output
    per token is unchanged (capacity/groups follow the stream, not the
    batch layout)."""
    import jax

    from kube_sqs_autoscaler_tpu.workloads.moe import (
        MoeConfig,
        init_moe_params,
        moe_mlp,
    )

    config = ModelConfig(
        vocab_size=128, d_model=32, n_heads=4, n_layers=1, d_ff=64,
        max_seq_len=32, dtype=jnp.float32,
    )
    moe = MoeConfig(n_experts=4, top_k=2, capacity_factor=1.0)
    params = init_moe_params(jax.random.key(0), config, moe)
    layer = params["layers"][0]
    x = jax.random.normal(jax.random.key(1), (4, 16, 32), jnp.float32)

    out_a, aux_a = moe_mlp(x, layer, moe)
    out_b, aux_b = moe_mlp(x.reshape(8, 8, 32), layer, moe)
    np.testing.assert_allclose(
        np.asarray(out_a).reshape(-1, 32),
        np.asarray(out_b).reshape(-1, 32),
        rtol=1e-6, atol=1e-6,
    )
    assert float(aux_a) == pytest.approx(float(aux_b))


def test_explicit_group_size_routes_per_group():
    import jax

    from kube_sqs_autoscaler_tpu.workloads.moe import (
        MoeConfig,
        init_moe_params,
        moe_mlp,
    )

    config = ModelConfig(
        vocab_size=128, d_model=32, n_heads=4, n_layers=1, d_ff=64,
        max_seq_len=32, dtype=jnp.float32,
    )
    moe = MoeConfig(n_experts=4, top_k=1, capacity_factor=1.0, group_size=16)
    params = init_moe_params(jax.random.key(0), config, moe)
    layer = params["layers"][0]
    x = jax.random.normal(jax.random.key(2), (2, 16, 32), jnp.float32)
    out, aux = moe_mlp(x, layer, moe)
    assert out.shape == (2, 16, 32)
    assert np.isfinite(np.asarray(out)).all()

    # group_size must divide the token count
    with pytest.raises(ValueError, match="divisible"):
        moe_mlp(x[:, :10], layer, MoeConfig(n_experts=4, group_size=16))


# ---------------------------------------------------------------- llama MoE


def test_single_expert_llama_moe_equals_dense_swiglu():
    # E=1, top_k=1, ample capacity: routed SwiGLU == the dense SwiGLU
    import jax

    from kube_sqs_autoscaler_tpu.workloads.llama import _swiglu
    from kube_sqs_autoscaler_tpu.workloads.moe import MoeConfig, llama_moe_mlp

    w_gate_up = jax.random.normal(jax.random.key(0), (64, 256),
                                  jnp.float32) * 0.1
    w_down = jax.random.normal(jax.random.key(1), (128, 64),
                               jnp.float32) * 0.1
    x = jax.random.normal(jax.random.key(2), (2, 16, 64), jnp.float32)

    dense = _swiglu(x, {"w_gate_up": w_gate_up, "w_down": w_down})
    layer = {
        "router": jnp.zeros((64, 1), jnp.float32),
        "w_gate_up_experts": w_gate_up[None],
        "w_down_experts": w_down[None],
    }
    sparse, aux = llama_moe_mlp(
        x, layer, MoeConfig(n_experts=1, top_k=1, capacity_factor=4.0)
    )
    np.testing.assert_allclose(
        np.asarray(dense), np.asarray(sparse), rtol=1e-6, atol=1e-6
    )
    assert float(aux) == pytest.approx(1.0)


def test_llama_moe_train_step_sharded_learns():
    import jax

    from kube_sqs_autoscaler_tpu.workloads.llama import LlamaConfig
    from kube_sqs_autoscaler_tpu.workloads.moe import (
        MoeConfig,
        init_llama_moe_train_state,
        make_llama_moe_train_step,
    )
    from kube_sqs_autoscaler_tpu.workloads.train import (
        TrainConfig,
        batch_sharding,
        make_mesh,
        place_state,
    )

    config = LlamaConfig(
        vocab_size=128, d_model=64, n_heads=4, n_kv_heads=2, n_layers=2,
        d_ff=64, max_seq_len=32, dtype=jnp.float32,
    )
    moe = MoeConfig(n_experts=4, top_k=2)
    mesh = make_mesh(jax.devices(), model_parallel=2, seq_parallel=2)
    train_config = TrainConfig(learning_rate=1e-2)
    state = place_state(
        mesh,
        init_llama_moe_train_state(jax.random.key(0), config, moe,
                                   train_config),
    )
    step_fn = make_llama_moe_train_step(mesh, config, moe, train_config,
                                        state)
    tokens = jax.device_put(
        jax.random.randint(jax.random.key(1), (4, 32), 0, 128, jnp.int32),
        batch_sharding(mesh),
    )
    losses = []
    for _ in range(4):
        state, loss = step_fn(state, tokens)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_zigzag_moe_equals_plain_moe_loss_and_learns():
    # the routed expert MLP through the permuted-order objective: with
    # ample capacity (routing then order-independent) the zig-zag MoE
    # loss equals the plain MoE loss on the same batch, and the step
    # learns on the sp mesh
    import jax

    from kube_sqs_autoscaler_tpu.workloads.model import ModelConfig
    from kube_sqs_autoscaler_tpu.workloads.moe import (
        MoeConfig,
        init_moe_train_state,
        make_zigzag_moe_train_step,
        moe_loss_fn,
    )
    from kube_sqs_autoscaler_tpu.workloads.train import (
        TrainConfig,
        batch_sharding,
        make_mesh,
        place_state,
    )

    config = ModelConfig(
        vocab_size=128, d_model=64, n_heads=4, n_layers=2, d_ff=64,
        max_seq_len=32, dtype=jnp.float32,
    )
    # capacity_factor 4 with 4 experts / top-2: every token always fits,
    # so dispatch (hence nll AND aux) is independent of token order
    moe = MoeConfig(n_experts=4, top_k=2, capacity_factor=4.0)
    mesh = make_mesh(jax.devices(), model_parallel=2, seq_parallel=2)
    train_config = TrainConfig(learning_rate=1e-2)
    state = place_state(
        mesh, init_moe_train_state(jax.random.key(0), config, moe,
                                   train_config),
    )
    tokens = jax.device_put(
        jax.random.randint(jax.random.key(1), (4, 32), 0, 128, jnp.int32),
        batch_sharding(mesh),
    )

    step_fn = make_zigzag_moe_train_step(mesh, config, moe, train_config,
                                         state)
    # loss equality before any update: zig-zag objective vs plain MoE
    plain = float(jax.jit(
        lambda p, t: moe_loss_fn(p, t, config, moe)
    )(state["params"], tokens))
    state2, zz_loss = step_fn(state, tokens)
    assert float(zz_loss) == pytest.approx(plain, rel=1e-4)

    losses = [float(zz_loss)]
    state = state2
    for _ in range(3):
        state, loss = step_fn(state, tokens)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_trainer_zigzag_moe_flags():
    from kube_sqs_autoscaler_tpu.workloads.trainer import main as trainer_main

    base = [
        "--vocab-size", "256", "--d-model", "64", "--n-heads", "4",
        "--n-layers", "2", "--d-ff", "64", "--seq-len", "32",
        "--batch-size", "8", "--learning-rate", "1e-2", "--log-every", "1",
        "--steps", "4", "--moe", "--moe-experts", "4",
        "--seq-parallel", "2", "--zigzag", "--overfit",
    ]
    result = trainer_main(base)
    assert result["final_step"] == 4
    assert all(np.isfinite(result["losses"]))
    assert result["losses"][-1] < result["losses"][0]

    result = trainer_main(base + ["--family", "llama", "--n-kv-heads", "2"])
    assert result["final_step"] == 4
    assert all(np.isfinite(result["losses"]))
    assert result["losses"][-1] < result["losses"][0]


def test_moe_pipeline_equals_flat_moe_loss_and_learns():
    # MoE x pp (GPipe): with ample capacity the pipelined routed loss is
    # pinned equal to the flat MoE loss, and the step learns
    import jax

    from kube_sqs_autoscaler_tpu.workloads.model import ModelConfig
    from kube_sqs_autoscaler_tpu.workloads.moe import (
        MoeConfig,
        moe_loss_fn,
    )
    from kube_sqs_autoscaler_tpu.workloads.pipeline import (
        PipelineConfig,
        init_moe_pipeline_train_state,
        make_moe_pipeline_train_step,
        make_pipeline_mesh,
        moe_pipeline_loss_fn,
        pipeline_batch_sharding,
        place_pipeline_state,
        unstack_layers,
    )
    from kube_sqs_autoscaler_tpu.workloads.train import TrainConfig

    config = ModelConfig(
        vocab_size=128, d_model=64, n_heads=4, n_layers=2, d_ff=64,
        max_seq_len=32, dtype=jnp.float32,
    )
    moe = MoeConfig(n_experts=4, top_k=2, capacity_factor=4.0)
    mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=2)
    train_config = TrainConfig(learning_rate=1e-2)
    pcfg = PipelineConfig(n_microbatches=2)
    state = place_pipeline_state(
        mesh,
        init_moe_pipeline_train_state(jax.random.key(0), config, moe,
                                      train_config, n_stages=2),
    )
    tokens = jax.device_put(
        jax.random.randint(jax.random.key(1), (2, 4, 16), 0, 128,
                           jnp.int32),
        pipeline_batch_sharding(mesh),
    )

    flat = unstack_layers(state["params"])
    plain = float(jax.jit(
        lambda p, t: moe_loss_fn(p, t, config, moe)
    )(flat, tokens.reshape(8, 16)))
    piped = float(jax.jit(
        lambda p, t: moe_pipeline_loss_fn(p, t, config, moe, pcfg, mesh)
    )(state["params"], tokens))
    assert piped == pytest.approx(plain, rel=2e-4)

    step_fn = make_moe_pipeline_train_step(mesh, config, moe, pcfg,
                                           train_config, state)
    losses = []
    for _ in range(4):
        state, loss = step_fn(state, tokens)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_moe_1f1b_grads_match_gpipe_autodiff():
    # MoE x pp x 1F1B: the hand-built backward with the Switch aux term
    # riding each stage vjp as a constant cotangent must be
    # gradient-equal to autodiff of the GPipe MoE objective
    import jax

    from kube_sqs_autoscaler_tpu.workloads.model import ModelConfig
    from kube_sqs_autoscaler_tpu.workloads.moe import MoeConfig
    from kube_sqs_autoscaler_tpu.workloads.pipeline import (
        PipelineConfig,
        init_moe_pipeline_train_state,
        make_pipeline_mesh,
        moe_one_f_one_b_value_and_grad,
        moe_pipeline_loss_fn,
        pipeline_batch_sharding,
        place_pipeline_state,
    )
    from kube_sqs_autoscaler_tpu.workloads.train import TrainConfig

    config = ModelConfig(
        vocab_size=128, d_model=64, n_heads=4, n_layers=2, d_ff=64,
        max_seq_len=32, dtype=jnp.float32,
    )
    moe = MoeConfig(n_experts=4, top_k=2, capacity_factor=4.0)
    mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=2)
    state = place_pipeline_state(
        mesh,
        init_moe_pipeline_train_state(jax.random.key(0), config, moe,
                                      TrainConfig(), n_stages=2),
    )
    params = state["params"]
    tokens = jax.device_put(
        jax.random.randint(jax.random.key(1), (2, 4, 16), 0, 128,
                           jnp.int32),
        pipeline_batch_sharding(mesh),
    )

    gpipe_cfg = PipelineConfig(n_microbatches=2)
    ref_loss, ref_grads = jax.jit(
        jax.value_and_grad(
            lambda p, t: moe_pipeline_loss_fn(p, t, config, moe,
                                              gpipe_cfg, mesh)
        )
    )(params, tokens)
    pcfg = PipelineConfig(n_microbatches=2, schedule="1f1b")
    loss, grads = jax.jit(
        lambda p, t: moe_one_f_one_b_value_and_grad(p, t, config, moe,
                                                    pcfg, mesh)
    )(params, tokens)

    assert float(loss) == pytest.approx(float(ref_loss), rel=1e-5)
    flat_ref = jax.tree_util.tree_leaves_with_path(ref_grads)
    flat = dict(
        (jax.tree_util.keystr(k), v)
        for k, v in jax.tree_util.tree_leaves_with_path(grads)
    )
    for key, ref in flat_ref:
        name = jax.tree_util.keystr(key)
        np.testing.assert_allclose(
            np.asarray(flat[name], np.float32), np.asarray(ref, np.float32),
            rtol=2e-4, atol=2e-6, err_msg=name,
        )


def test_llama_moe_1f1b_pipeline_learns():
    # the modern family: llama MoE through the 1F1B schedule
    import jax

    from kube_sqs_autoscaler_tpu.workloads.llama import LlamaConfig
    from kube_sqs_autoscaler_tpu.workloads.moe import MoeConfig
    from kube_sqs_autoscaler_tpu.workloads.pipeline import (
        PipelineConfig,
        init_moe_pipeline_train_state,
        make_moe_pipeline_train_step,
        make_pipeline_mesh,
        pipeline_batch_sharding,
        place_pipeline_state,
    )
    from kube_sqs_autoscaler_tpu.workloads.train import TrainConfig

    config = LlamaConfig(
        vocab_size=128, d_model=64, n_heads=4, n_kv_heads=2, n_layers=2,
        d_ff=64, max_seq_len=32,
    )
    moe = MoeConfig(n_experts=4, top_k=2, capacity_factor=4.0)
    mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=2)
    train_config = TrainConfig(learning_rate=1e-2)
    pcfg = PipelineConfig(n_microbatches=2, schedule="1f1b")
    state = place_pipeline_state(
        mesh,
        init_moe_pipeline_train_state(jax.random.key(0), config, moe,
                                      train_config, n_stages=2,
                                      llama=True),
    )
    step_fn = make_moe_pipeline_train_step(mesh, config, moe, pcfg,
                                           train_config, state, llama=True)
    tokens = jax.device_put(
        jax.random.randint(jax.random.key(1), (2, 4, 16), 0, 128,
                           jnp.int32),
        pipeline_batch_sharding(mesh),
    )
    losses = []
    for _ in range(4):
        state, loss = step_fn(state, tokens)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_moe_pipeline_rejects_seq_axis():
    import jax

    from kube_sqs_autoscaler_tpu.workloads.model import ModelConfig
    from kube_sqs_autoscaler_tpu.workloads.moe import MoeConfig
    from kube_sqs_autoscaler_tpu.workloads.pipeline import (
        PipelineConfig,
        init_moe_pipeline_train_state,
        make_moe_pipeline_train_step,
        make_pipeline_mesh,
    )
    from kube_sqs_autoscaler_tpu.workloads.train import TrainConfig

    config = ModelConfig(
        vocab_size=128, d_model=64, n_heads=4, n_layers=2, d_ff=64,
        max_seq_len=32, dtype=jnp.float32,
    )
    moe = MoeConfig(n_experts=4, top_k=2)
    tc = TrainConfig()
    state = init_moe_pipeline_train_state(jax.random.key(0), config, moe,
                                          tc, n_stages=2)
    # round-5 lift: moe x pp x TP composes (expert ff carved over
    # "model", router grad-synced) — the step factory now accepts a
    # (pipe, data, model) mesh (pinned loss-equal in
    # test_pipeline_4axis); only the seq axis still fails fast
    sp_mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=2,
                                 seq_parallel=2)
    with pytest.raises(ValueError, match="seq"):
        make_moe_pipeline_train_step(
            sp_mesh, config, moe, PipelineConfig(n_microbatches=2), tc,
            state)


def test_trainer_moe_pipeline_flags(caplog):
    # --moe --pipe-parallel from the binary (both families), with eval
    import logging

    from kube_sqs_autoscaler_tpu.workloads.trainer import main as trainer_main

    base = [
        "--vocab-size", "256", "--d-model", "64", "--n-heads", "4",
        "--n-layers", "2", "--d-ff", "64", "--seq-len", "32",
        "--batch-size", "8", "--learning-rate", "1e-2", "--log-every", "1",
        "--steps", "4", "--moe", "--moe-experts", "4",
        "--pipe-parallel", "2", "--pipe-microbatches", "2", "--overfit",
    ]
    with caplog.at_level(logging.INFO):
        result = trainer_main(base + ["--eval-every", "4",
                                      "--eval-batches", "2"])
    assert result["final_step"] == 4
    assert all(np.isfinite(result["losses"]))
    assert result["losses"][-1] < result["losses"][0]
    assert any("eval_loss" in r.getMessage() for r in caplog.records)

    result = trainer_main(base + ["--family", "llama", "--n-kv-heads", "2"])
    assert result["final_step"] == 4
    assert all(np.isfinite(result["losses"]))
    assert result["losses"][-1] < result["losses"][0]

    # the 1F1B schedule threads the aux term through its hand-built
    # backward, so the flag composition runs (and learns) end to end
    result = trainer_main(base + ["--pipe-schedule", "1f1b"])
    assert result["final_step"] == 4
    assert all(np.isfinite(result["losses"]))
    assert result["losses"][-1] < result["losses"][0]

    # round-5 lift: --moe --pipe-parallel --model-parallel trains
    # (attention AND expert ff Megatron-sharded; pinned equal to the
    # no-tp truth in test_pipeline_4axis)
    result = trainer_main(base + ["--model-parallel", "2"])
    assert result["final_step"] == 4
    assert all(np.isfinite(result["losses"]))


def test_trainer_llama_moe_flag():
    from kube_sqs_autoscaler_tpu.workloads.trainer import main as trainer_main

    result = trainer_main([
        "--vocab-size", "256", "--d-model", "64", "--n-heads", "4",
        "--n-layers", "2", "--d-ff", "64", "--seq-len", "32",
        "--batch-size", "8", "--learning-rate", "1e-2", "--log-every", "1",
        "--steps", "4", "--family", "llama", "--moe", "--moe-experts", "4",
        "--model-parallel", "2", "--overfit",
    ])
    assert result["final_step"] == 4
    losses = result["losses"]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_moe_checkpoint_refuses_to_serve_with_clear_error(tmp_path):
    from kube_sqs_autoscaler_tpu.workloads.checkpoint import (
        TrainCheckpointer,
        load_model_layout,
        load_model_manifest,
    )
    from kube_sqs_autoscaler_tpu.workloads.train import make_mesh
    from kube_sqs_autoscaler_tpu.workloads.trainer import main as trainer_main

    import jax

    ckpt = str(tmp_path / "ckpt")
    trainer_main([
        "--vocab-size", "256", "--d-model", "64", "--n-heads", "4",
        "--n-layers", "2", "--d-ff", "64", "--seq-len", "32",
        "--batch-size", "8", "--steps", "2", "--moe", "--moe-experts", "4",
        "--model-parallel", "2", "--checkpoint-dir", ckpt,
    ])
    layout = load_model_layout(ckpt)
    assert layout["kind"] == "moe"
    family, config = load_model_manifest(ckpt)
    mesh = make_mesh(jax.devices()[:1], model_parallel=1)
    with pytest.raises(ValueError, match="routed-expert"):
        TrainCheckpointer(ckpt).restore_params(mesh, family, config,
                                               layout=layout)


def test_resume_pre_layout_manifest_refuses_with_migration_hint(tmp_path):
    # a manifest with no layout record cannot be told apart from a dense
    # run's: refusing with the migration step beats guessing (a wrong
    # auto-upgrade would corrupt a dense dir's manifest); applying the
    # hinted one-line edit then resumes cleanly
    import json
    from pathlib import Path

    from kube_sqs_autoscaler_tpu.workloads.checkpoint import (
        MODEL_MANIFEST,
        load_model_layout,
    )
    from kube_sqs_autoscaler_tpu.workloads.trainer import main as trainer_main

    flags = [
        "--vocab-size", "256", "--d-model", "64", "--n-heads", "4",
        "--n-layers", "2", "--d-ff", "64", "--seq-len", "32",
        "--batch-size", "8", "--steps", "2", "--moe", "--moe-experts", "4",
        "--model-parallel", "2", "--checkpoint-dir", str(tmp_path / "ckpt"),
    ]
    trainer_main(flags)
    manifest = Path(tmp_path / "ckpt") / MODEL_MANIFEST
    payload = json.loads(manifest.read_text())
    saved_layout = payload.pop("layout")  # simulate a pre-record manifest
    manifest.write_text(json.dumps(payload))

    with pytest.raises(SystemExit, match="model_config.json"):
        trainer_main(flags + ["--resume"])

    # the migration the error describes
    payload["layout"] = saved_layout
    manifest.write_text(json.dumps(payload))
    result = trainer_main(flags + ["--resume"])
    assert result["final_step"] == 4
    assert load_model_layout(tmp_path / "ckpt")["kind"] == "moe"
