"""Worker-service tests, ending in the full-story integration: one queue,
real model compute, autoscaler scaling a fake Deployment, elastic worker
pool following the replica count — queue drains, pool grows then shrinks.
"""

import json
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from kube_sqs_autoscaler_tpu.core.loop import ControlLoop, LoopConfig
from kube_sqs_autoscaler_tpu.core.policy import PolicyConfig
from kube_sqs_autoscaler_tpu.metrics.fake import FakeMessageQueue
from kube_sqs_autoscaler_tpu.metrics.queue import QueueMetricSource
from kube_sqs_autoscaler_tpu.scale import FakeDeploymentAPI, PodAutoScaler
from kube_sqs_autoscaler_tpu.workloads.model import ModelConfig, init_params
from kube_sqs_autoscaler_tpu.workloads.service import (
    ElasticWorkerPool,
    QueueWorker,
    ServiceConfig,
)

TINY = ModelConfig(
    vocab_size=512, d_model=128, n_heads=4, n_layers=2, d_ff=256, max_seq_len=64
)
URL = "fake://jobs"


def send_token_messages(queue, n, seq_len=16, seed=0):
    rng = np.random.default_rng(seed)
    for _ in range(n):
        ids = rng.integers(0, TINY.vocab_size, seq_len).tolist()
        queue.send_message(URL, json.dumps(ids))


def test_fake_message_queue_visibility_semantics():
    now = [0.0]
    queue = FakeMessageQueue(visibility_timeout=10.0, now_fn=lambda: now[0])
    queue.send_message(URL, "a")
    queue.send_message(URL, "b")
    attrs = queue.get_queue_attributes(URL, ())
    assert attrs["ApproximateNumberOfMessages"] == "2"

    batch = queue.receive_messages(URL, max_messages=1)
    assert len(batch) == 1
    attrs = queue.get_queue_attributes(URL, ())
    assert attrs["ApproximateNumberOfMessages"] == "1"
    assert attrs["ApproximateNumberOfMessagesNotVisible"] == "1"

    queue.delete_message(URL, batch[0]["ReceiptHandle"])
    attrs = queue.get_queue_attributes(URL, ())
    assert attrs["ApproximateNumberOfMessagesNotVisible"] == "0"

    # undeleted message reappears after the visibility timeout
    second = queue.receive_messages(URL, max_messages=1)
    now[0] = 11.0
    attrs = queue.get_queue_attributes(URL, ())
    assert attrs["ApproximateNumberOfMessages"] == "1"
    again = queue.receive_messages(URL, max_messages=1)
    assert again[0]["Body"] == second[0]["Body"]
    # fresh receipt handle per delivery: the stale handle from the first
    # delivery must NOT delete the redelivered message (real SQS semantics)
    assert again[0]["ReceiptHandle"] != second[0]["ReceiptHandle"]
    queue.delete_message(URL, second[0]["ReceiptHandle"])  # stale: no-op
    attrs = queue.get_queue_attributes(URL, ())
    assert attrs["ApproximateNumberOfMessagesNotVisible"] == "1"
    queue.delete_message(URL, again[0]["ReceiptHandle"])  # current: works
    attrs = queue.get_queue_attributes(URL, ())
    assert attrs["ApproximateNumberOfMessagesNotVisible"] == "0"


def test_queue_worker_processes_and_deletes():
    queue = FakeMessageQueue()
    send_token_messages(queue, 5)
    params = init_params(jax.random.key(0), TINY)
    worker = QueueWorker(
        queue, params, TINY,
        ServiceConfig(queue_url=URL, batch_size=4, seq_len=16),
    )
    assert worker.run_once() == 4
    assert worker.run_once() == 1
    assert worker.run_once() == 0
    assert worker.processed == 5
    attrs = queue.get_queue_attributes(URL, ())
    assert attrs["ApproximateNumberOfMessages"] == "0"
    assert attrs["ApproximateNumberOfMessagesNotVisible"] == "0"


def test_queue_worker_generate_mode_decodes_and_deletes():
    queue = FakeMessageQueue()
    send_token_messages(queue, 3)
    params = init_params(jax.random.key(0), TINY)
    calls = []

    def spy_generate(params, tokens, n, lengths):
        from kube_sqs_autoscaler_tpu.workloads.decode import generate_jit

        out = generate_jit(params, tokens, n, TINY, lengths=lengths)
        calls.append((tokens.shape, n, out.shape))
        return out

    worker = QueueWorker(
        queue, params, TINY,
        ServiceConfig(queue_url=URL, batch_size=4, seq_len=16, generate_tokens=4),
        generate_fn=spy_generate,
    )
    assert worker.run_once() == 3
    assert worker.processed == 3
    assert calls == [((4, 16), 4, (4, 4))]
    attrs = queue.get_queue_attributes(URL, ())
    assert attrs["ApproximateNumberOfMessages"] == "0"
    assert attrs["ApproximateNumberOfMessagesNotVisible"] == "0"


def test_queue_worker_generate_budget_validated_against_model():
    import pytest

    params = init_params(jax.random.key(0), TINY)
    with pytest.raises(ValueError, match="max_seq_len"):
        QueueWorker(
            FakeMessageQueue(), params, TINY,
            ServiceConfig(queue_url=URL, seq_len=60, generate_tokens=8),
        )


def test_queue_worker_drops_malformed_messages():
    queue = FakeMessageQueue()
    queue.send_message(URL, "not json at all {{{")
    params = init_params(jax.random.key(0), TINY)
    worker = QueueWorker(
        queue, params, TINY, ServiceConfig(queue_url=URL, batch_size=2, seq_len=16)
    )
    assert worker.run_once() == 1  # processed (as padding) and deleted
    attrs = queue.get_queue_attributes(URL, ())
    assert attrs["ApproximateNumberOfMessages"] == "0"


def test_queue_worker_with_flash_attention_forward():
    """The worker drains the queue with the Pallas flash kernel as its
    forward (forced into interpret mode here since this suite runs on CPU;
    on TPU the default forward picks this kernel automatically via
    flash.attention_fn_for whenever seq_len tiles onto the MXU blocks)."""
    import functools

    from kube_sqs_autoscaler_tpu.workloads.flash import (
        attention_fn_for,
        flash_attention,
    )
    from kube_sqs_autoscaler_tpu.workloads.model import forward

    # on TPU the kernel is picked from the measured crossover up, and
    # never below it (where dense measures faster)
    assert attention_fn_for(2048, backend="tpu") is flash_attention
    assert attention_fn_for(128, backend="tpu") is not flash_attention
    config = ModelConfig(
        vocab_size=512, d_model=128, n_heads=4, n_layers=2, d_ff=256,
        max_seq_len=128,
    )
    queue = FakeMessageQueue()
    send_token_messages(queue, 2, seq_len=128)
    params = init_params(jax.random.key(0), config)
    flash_interpret = functools.partial(flash_attention, interpret=True)
    worker = QueueWorker(
        queue, params, config,
        ServiceConfig(queue_url=URL, batch_size=2, seq_len=128),
        forward_fn=lambda p, t: forward(p, t, config, flash_interpret),
    )
    assert worker.run_once() == 2
    attrs = queue.get_queue_attributes(URL, ())
    assert attrs["ApproximateNumberOfMessages"] == "0"


def test_queue_worker_survives_poison_json_bodies():
    """Valid JSON that is not an int array must be dropped, not crash the
    worker — and must be deleted, not redelivered forever."""
    queue = FakeMessageQueue()
    queue.send_message(URL, '"abc"')  # JSON string -> asarray ValueError
    queue.send_message(URL, "5")  # 0-d scalar
    queue.send_message(URL, "[[1, 2], [3, 4]]")  # nested: flattened
    queue.send_message(URL, '["x", "y"]')  # non-int list
    params = init_params(jax.random.key(0), TINY)
    worker = QueueWorker(
        queue, params, TINY, ServiceConfig(queue_url=URL, batch_size=8, seq_len=16)
    )
    assert worker.run_once() == 4  # no crash, all consumed
    attrs = queue.get_queue_attributes(URL, ())
    assert attrs["ApproximateNumberOfMessages"] == "0"
    assert attrs["ApproximateNumberOfMessagesNotVisible"] == "0"


def test_worker_loop_survives_transient_queue_errors():
    """run_forever extends the control loop's never-dies guarantee
    (main.go:43-47) to the worker: a receive error backs off and retries."""
    queue = FakeMessageQueue()
    send_token_messages(queue, 2)
    boom = {"armed": True}
    real_receive = queue.receive_messages

    def flaky_receive(*args, **kwargs):
        if boom["armed"]:
            boom["armed"] = False
            raise RuntimeError("transient network blip")
        return real_receive(*args, **kwargs)

    queue.receive_messages = flaky_receive
    params = init_params(jax.random.key(0), TINY)
    worker = QueueWorker(
        queue, params, TINY,
        ServiceConfig(queue_url=URL, batch_size=4, seq_len=16,
                      idle_sleep_s=0.01, error_backoff_s=0.01),
    )
    thread = threading.Thread(target=worker.run_forever, daemon=True)
    thread.start()
    try:
        deadline = time.time() + 30
        while worker.processed < 2 and time.time() < deadline:
            time.sleep(0.01)
    finally:
        worker.stop()
        thread.join(timeout=10)
    assert worker.processed == 2  # survived the blip and drained the queue


def test_pool_replaces_dead_workers():
    """reconcile must count thread liveness, not list length: a crashed
    worker is pruned (keeping its count) and replaced."""
    queue = FakeMessageQueue()
    api = FakeDeploymentAPI.with_deployments("ns", 2, "workers")
    params = init_params(jax.random.key(0), TINY)
    pool = ElasticWorkerPool(
        api, "workers",
        worker_factory=lambda: QueueWorker(
            queue, params, TINY,
            ServiceConfig(queue_url=URL, batch_size=4, seq_len=16,
                          idle_sleep_s=0.01),
        ),
    )
    try:
        assert pool.reconcile() == 2
        # kill one worker thread by stopping its worker (thread exits)
        victim = pool.workers[0]
        victim.processed = 7  # pretend it did work before dying
        victim.stop()
        deadline = time.time() + 10
        while pool._members[0][1].is_alive() and time.time() < deadline:
            time.sleep(0.01)
        # same replica count: the dead thread is replaced, not double-counted
        assert pool.reconcile() == 2
        assert all(t.is_alive() for _, t in pool._members)
        assert pool.processed == 7  # the dead worker's count was retired
    finally:
        pool.stop_all()
    assert pool.processed == 7  # lifetime count survives stop_all


def test_full_story_queue_autoscaler_elastic_workers():
    """The whole system, live: burst of work -> depth crosses threshold ->
    autoscaler raises replicas -> pool adds workers -> queue drains ->
    autoscaler scales back down -> pool shrinks."""
    queue = FakeMessageQueue(visibility_timeout=60.0)
    send_token_messages(queue, 120)

    api = FakeDeploymentAPI.with_deployments("ns", 1, "workers")
    scaler = PodAutoScaler(
        client=api, max=4, min=1, scale_up_pods=1, scale_down_pods=1,
        deployment="workers", namespace="ns",
    )
    loop = ControlLoop(
        scaler,
        QueueMetricSource(client=queue, queue_url=URL),
        LoopConfig(
            poll_interval=0.05,
            policy=PolicyConfig(
                scale_up_messages=20, scale_down_messages=0,
                scale_up_cooldown=0.1, scale_down_cooldown=0.1,
            ),
        ),
    )
    loop_thread = threading.Thread(target=loop.run, daemon=True)

    params = init_params(jax.random.key(0), TINY)

    from kube_sqs_autoscaler_tpu.workloads.model import forward_jit

    def throttled_forward(params, tokens):
        # simulate heavier inference so draining 120 messages reliably takes
        # longer than the startup grace + one cooldown — otherwise a warm
        # jit cache lets one worker drain the queue before any scale-up
        time.sleep(0.02)
        return forward_jit(params, tokens, TINY)

    pool = ElasticWorkerPool(
        api, "workers",
        worker_factory=lambda: QueueWorker(
            queue, params, TINY,
            ServiceConfig(queue_url=URL, batch_size=4, seq_len=16,
                          idle_sleep_s=0.01),
            forward_fn=throttled_forward,
        ),
    )

    loop_thread.start()
    max_workers = 0
    deadline = time.time() + 60
    try:
        while time.time() < deadline:
            max_workers = max(max_workers, pool.reconcile())
            attrs = queue.get_queue_attributes(URL, ())
            if (
                attrs["ApproximateNumberOfMessages"] == "0"
                and attrs["ApproximateNumberOfMessagesNotVisible"] == "0"
                and api.replicas("workers") == 1
            ):
                break
            time.sleep(0.02)
        else:
            raise AssertionError(
                f"did not settle: depth={attrs}, replicas={api.replicas('workers')}"
            )
    finally:
        loop.stop()
        pool.stop_all()
        loop_thread.join(timeout=10)

    assert max_workers > 1  # burst actually scaled the pool out
    # lifetime count survives scale-down and stop_all: every message that
    # left the queue was counted by some (possibly retired) worker
    assert pool.processed == 120
    # all 120 messages were processed exactly once (none lost, none left)
    attrs = queue.get_queue_attributes(URL, ())
    assert attrs["ApproximateNumberOfMessages"] == "0"
    assert attrs["ApproximateNumberOfMessagesNotVisible"] == "0"


def test_worker_buckets_short_batches():
    """Short bodies run in a small padded bucket (power of two >= longest
    body, floored at MIN_BUCKET), not always the full seq_len."""
    queue = FakeMessageQueue()
    rng = np.random.default_rng(3)
    for n in (3, 7, 5):  # longest body 7 -> bucket 16 (MIN_BUCKET)
        ids = rng.integers(1, TINY.vocab_size, n).tolist()
        queue.send_message(URL, json.dumps(ids))
    params = init_params(jax.random.key(0), TINY)
    shapes = []

    def spy_forward(params, tokens):
        from kube_sqs_autoscaler_tpu.workloads.model import forward_jit

        shapes.append(tokens.shape)
        return forward_jit(params, tokens, TINY)

    worker = QueueWorker(
        queue, params, TINY,
        ServiceConfig(queue_url=URL, batch_size=4, seq_len=64),
        forward_fn=spy_forward,
    )
    assert worker.run_once() == 3
    assert shapes == [(4, 16)]  # bucketed, not (4, 64)

    # a longer body widens the bucket to the next power of two
    queue.send_message(
        URL, json.dumps(rng.integers(1, TINY.vocab_size, 20).tolist())
    )
    assert worker.run_once() == 1
    assert shapes[-1] == (4, 32)


def test_worker_classify_reads_each_rows_last_valid_position():
    """The classify readout must equal running each body alone, unpadded
    — the padded batch never reads a pad slot."""
    from kube_sqs_autoscaler_tpu.workloads.model import forward_jit

    queue = FakeMessageQueue()
    rng = np.random.default_rng(5)
    bodies = [rng.integers(1, TINY.vocab_size, n).tolist() for n in (4, 11)]
    for ids in bodies:
        queue.send_message(URL, json.dumps(ids))
    params = init_params(jax.random.key(0), TINY)
    picked = []

    def spy_forward(p, tokens):
        logits = forward_jit(p, tokens, TINY)
        picked.append(np.asarray(logits))
        return logits

    worker = QueueWorker(
        queue, params, TINY,
        ServiceConfig(queue_url=URL, batch_size=2, seq_len=64),
        forward_fn=spy_forward,
    )
    assert worker.run_once() == 2
    (logits,) = picked
    for i, ids in enumerate(bodies):
        solo = np.asarray(
            forward_jit(params, jnp.asarray([ids], jnp.int32), TINY)
        )
        # row i's readout position (len-1) matches the unpadded run's last
        np.testing.assert_allclose(
            logits[i, len(ids) - 1], solo[0, -1], rtol=1e-3, atol=1e-3
        )


def test_worker_generate_temperature_sampling():
    """ServiceConfig.temperature > 0 samples (reproducible per seed,
    different across batches); 0 stays greedy through one compiled path."""
    from kube_sqs_autoscaler_tpu.workloads.decode import generate_jit

    params = init_params(jax.random.key(0), TINY)
    queue = FakeMessageQueue()
    send_token_messages(queue, 4, seq_len=12)
    worker = QueueWorker(
        queue, params, TINY,
        ServiceConfig(queue_url=URL, batch_size=2, seq_len=12,
                      generate_tokens=4, temperature=0.8, sample_seed=7),
    )
    assert worker.run_once() == 2
    assert worker.run_once() == 2
    # two batches consumed two distinct per-batch keys
    assert worker._generate_batches == 2

    # the default path reproduces generate_jit with the same key/config
    tokens = jnp.zeros((2, 12), jnp.int32)
    lengths = jnp.full((2,), 12, jnp.int32)
    worker2 = QueueWorker(
        queue, params, TINY,
        ServiceConfig(queue_url=URL, batch_size=2, seq_len=12,
                      generate_tokens=4, temperature=0.8, sample_seed=7),
    )
    got = worker2._generate(params, tokens, 4, lengths)
    want = generate_jit(params, tokens, 4, TINY, temperature=0.8,
                        rng=jax.random.key(7), lengths=lengths)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


class StubTokenizer:
    """HF-shaped encode/decode over a byte vocabulary (ids = bytes)."""

    vocab_size = 256

    def encode(self, text):
        return list(text.encode())[:64]

    def decode(self, ids):
        return bytes(int(i) % 256 for i in ids).decode(errors="replace")


def test_result_queue_replies_classify_and_generate():
    """The request/reply loop: one JSON reply per input message, on a
    separate result queue, for both compute modes."""
    params = init_params(jax.random.key(0), TINY)
    for generate_tokens in (0, 4):
        queue, replies = FakeMessageQueue(), FakeMessageQueue()
        send_token_messages(queue, 3)
        config = ServiceConfig(
            queue_url=URL, batch_size=4, seq_len=16,
            generate_tokens=generate_tokens,
            result_queue_url="fake://results",
        )
        worker = QueueWorker(queue, params, TINY, config,
                             result_queue=replies)
        assert worker.run_once() == 3
        out = replies.receive_messages("fake://results", max_messages=10)
        assert len(out) == 3
        for message in out:
            payload = json.loads(message["Body"])
            if generate_tokens:
                assert len(payload["tokens"]) == 4
                assert all(0 <= t < TINY.vocab_size
                           for t in payload["tokens"])
            else:
                assert 0 <= payload["next_token"] < TINY.vocab_size


def test_tokenizer_text_in_text_out():
    """Plain-text and {'text': ...} bodies encode through the tokenizer;
    generate replies carry the decoded continuation."""
    config_model = ModelConfig(vocab_size=256, d_model=64, n_heads=4,
                               n_layers=2, d_ff=128, max_seq_len=64)
    params = init_params(jax.random.key(1), config_model)
    queue, replies = FakeMessageQueue(), FakeMessageQueue()
    queue.send_message(URL, json.dumps({"text": "hello tpu"}))
    queue.send_message(URL, "plain text body")
    queue.send_message(URL, json.dumps([1, 2, 3]))  # ids still work
    config = ServiceConfig(queue_url=URL, batch_size=4, seq_len=16,
                           generate_tokens=3,
                           result_queue_url="fake://results")
    worker = QueueWorker(queue, params, config_model, config,
                         tokenizer=StubTokenizer(), result_queue=replies)
    assert worker.run_once() == 3
    out = replies.receive_messages("fake://results", max_messages=10)
    assert len(out) == 3
    for message in out:
        payload = json.loads(message["Body"])
        assert len(payload["tokens"]) == 3
        assert isinstance(payload["text"], str)


def test_no_result_queue_url_sends_nothing():
    params = init_params(jax.random.key(0), TINY)
    queue = FakeMessageQueue()
    send_token_messages(queue, 2)
    config = ServiceConfig(queue_url=URL, batch_size=4, seq_len=16)
    worker = QueueWorker(queue, params, TINY, config)
    assert worker.run_once() == 2
    assert queue.receive_messages(URL, max_messages=10) == []


def test_result_queue_url_requires_explicit_client():
    # in-memory clients ignore urls, so a silent same-queue default
    # would self-feed replies back as inputs — construction rejects it
    import pytest

    params = init_params(jax.random.key(0), TINY)
    config = ServiceConfig(queue_url=URL, batch_size=2, seq_len=16,
                           result_queue_url="fake://results")
    with pytest.raises(ValueError, match="result_queue"):
        QueueWorker(FakeMessageQueue(), params, TINY, config)


def test_replies_carry_request_ids_and_error_payloads():
    """Replies correlate to inputs by MessageId; malformed bodies get an
    error payload, never a fabricated result."""
    params = init_params(jax.random.key(0), TINY)
    queue, replies = FakeMessageQueue(), FakeMessageQueue()
    good_id = queue.send_message(URL, json.dumps([1, 2, 3]))
    bad_id = queue.send_message(URL, json.dumps("not ids"))
    config = ServiceConfig(queue_url=URL, batch_size=4, seq_len=16,
                           result_queue_url="fake://results")
    worker = QueueWorker(queue, params, TINY, config, result_queue=replies)
    assert worker.run_once() == 2
    out = {
        json.loads(m["Body"])["request_id"]: json.loads(m["Body"])
        for m in replies.receive_messages("fake://results", max_messages=10)
    }
    assert set(out) == {good_id, bad_id}
    assert "next_token" in out[good_id]
    assert out[bad_id] == {"error": "malformed body",
                           "request_id": bad_id}


def test_generate_replies_truncate_at_eos():
    params = init_params(jax.random.key(0), TINY)
    queue, replies = FakeMessageQueue(), FakeMessageQueue()
    send_token_messages(queue, 2)
    # discover an id the model emits for the first message, then serve
    # with it as eos and expect the reply to stop there
    probe_cfg = ServiceConfig(queue_url=URL, batch_size=4, seq_len=16,
                              generate_tokens=6,
                              result_queue_url="fake://results")
    worker = QueueWorker(queue, params, TINY, probe_cfg,
                         result_queue=replies)
    assert worker.run_once() == 2
    probe = json.loads(
        replies.receive_messages("fake://results", 10)[0]["Body"]
    )["tokens"]
    eos = probe[2]

    queue2, replies2 = FakeMessageQueue(), FakeMessageQueue()
    send_token_messages(queue2, 2)
    config = ServiceConfig(queue_url=URL, batch_size=4, seq_len=16,
                           generate_tokens=6, eos_id=eos,
                           result_queue_url="fake://results")
    worker = QueueWorker(queue2, params, TINY, config,
                         result_queue=replies2)
    assert worker.run_once() == 2
    for message in replies2.receive_messages("fake://results", 10):
        payload = json.loads(message["Body"])
        assert eos not in payload["tokens"]
        assert len(payload["tokens"]) <= 6
