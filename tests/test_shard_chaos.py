"""Shard-level chaos: poisoned-shard quarantine, live slot evacuation,
and the serving chaos battery's building blocks.

Tier-1 (tiny model, CPU JAX): the device-side health sentinels (NaN
flag, no-progress stall counter, admission-mask mismatch) and their
zero-extra-transfer accounting, the pool's detect → quarantine →
evacuate → probe → readmit state machine, the evacuation edge cases
(greedy resume parity, visibility-timeout redelivery racing an
evacuated row, no-free-slot queue hand-back), per-request TTL shedding,
the idle-wedge watchdog regression, and the chaos-serve bench smoke.
The full battery (all three fault classes, timing gates — the committed
``BENCH_r13.json``) runs in the slow tier.
"""

from __future__ import annotations

import collections
import json

import numpy as np
import pytest

jax = pytest.importorskip("jax")

import jax.numpy as jnp  # noqa: E402

from kube_sqs_autoscaler_tpu.core.clock import FakeClock  # noqa: E402
from kube_sqs_autoscaler_tpu.fleet import (  # noqa: E402
    DEAD,
    PROBING,
    QUARANTINED,
    SERVING,
    SHARD_HEALTH_CODES,
    SHARD_STATE_CODES,
    ShardedWorkerPool,
    WorkerPool,
)
from kube_sqs_autoscaler_tpu.fleet.worker import FleetWorker  # noqa: E402
from kube_sqs_autoscaler_tpu.metrics.fake import FakeMessageQueue  # noqa: E402
from kube_sqs_autoscaler_tpu.sim.faults import FleetFaultPlan  # noqa: E402
from kube_sqs_autoscaler_tpu.workloads.continuous import (  # noqa: E402
    ContinuousWorker,
)
from kube_sqs_autoscaler_tpu.workloads.model import (  # noqa: E402
    ModelConfig,
    init_params,
)
from kube_sqs_autoscaler_tpu.workloads.service import (  # noqa: E402
    ServiceConfig,
    collect_replies,
)
from kube_sqs_autoscaler_tpu.workloads.shard_plane import (  # noqa: E402
    ShardedBatcher,
)

PROMPT, TOKENS, BLOCK = 8, 8, 2


@pytest.fixture(scope="module")
def tiny():
    config = ModelConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
        max_seq_len=PROMPT + TOKENS, dtype=jnp.float32,
    )
    params = init_params(jax.random.key(0), config)
    return params, config


def make_plane(tiny, *, shards=2, shard_slots=2, donor=None):
    params, config = tiny
    plane = ShardedBatcher(
        params, config, shards=shards, shard_slots=shard_slots,
        prompt_len=PROMPT, generate_tokens=TOKENS, decode_block=BLOCK,
    )
    if donor is not None:
        plane.adopt_engine(donor)
    return plane


@pytest.fixture(scope="module")
def donor22(tiny):
    """One warmed (2 shards x 2 slots) engine the batcher-level tests
    adopt, so the module pays each compiled program once."""
    return make_plane(tiny)


def service_config(**overrides):
    base = dict(
        queue_url="chaos://q", batch_size=2, seq_len=PROMPT,
        generate_tokens=TOKENS, decode_block=BLOCK, shards=2,
        result_queue_url="chaos://r",
    )
    base.update(overrides)
    return ServiceConfig(**base)


def prompts_for(n, seed=7):
    rng = np.random.default_rng(seed)
    return [
        rng.integers(1, 64, rng.integers(2, PROMPT + 1)).astype(np.int32)
        for _ in range(n)
    ]


def submit(plane, prompts, tag=0):
    plane.submit_many([
        (ids, f"req-{tag}-{i}") for i, ids in enumerate(prompts)
    ])


def drain(plane, max_steps=300):
    out = {}
    for _ in range(max_steps):
        for payload, tokens in plane.step():
            out[payload] = list(tokens)
        if plane.active == 0:
            break
    return out


# ---------------------------------------------------------------------------
# FleetFaultPlan: shard-granularity fault scripting
# ---------------------------------------------------------------------------


def test_fault_plan_shard_windows_validate():
    with pytest.raises(ValueError, match="start < end"):
        FleetFaultPlan(shard_poisons=((5, 5, 0),))
    with pytest.raises(ValueError, match="start < end"):
        FleetFaultPlan(shard_wedges=((7, 3, 1),))
    plan = FleetFaultPlan(
        kills=((1, 0),),
        shard_poisons=((1, 4, 0),),
        shard_wedges=((2, 5, 1),),
        shard_mask_corruptions=((3, 2),),
    )
    assert plan.shards() == {0, 1, 2}
    assert plan.indices() == {0}


def test_fault_plan_applies_shard_faults_at_exact_cycles():
    calls = []

    class Recorder:
        def poison_shard(self, shard, poisoned):
            calls.append(("poison", shard, poisoned))

        def wedge_shard(self, shard, wedged):
            calls.append(("wedge", shard, wedged))

        def corrupt_shard_mask(self, shard):
            calls.append(("mask", shard))

    plan = FleetFaultPlan(
        shard_poisons=((2, 4, 1),),
        shard_wedges=((3, 6, 0),),
        shard_mask_corruptions=((5, 1),),
    )
    pool = Recorder()
    for cycle in range(8):
        plan.apply(cycle, pool)
    # windows inject at start, heal at end (end-exclusive); one-shot
    # corruption fires exactly once
    assert calls == [
        ("poison", 1, True),
        ("wedge", 0, True),
        ("poison", 1, False),
        ("mask", 1),
        ("wedge", 0, False),
    ]


# ---------------------------------------------------------------------------
# Device-side health sentinels (batcher level): detection rides the one
# combined settle transfer
# ---------------------------------------------------------------------------


def test_poison_sentinel_flags_shard_and_discards_garbage(tiny, donor22):
    plane = make_plane(tiny, donor=donor22)
    # the supervised contract under test: ShardedWorkerPool opts in so a
    # flagged block is discarded whole (it then quarantines + evacuates
    # the stranded rows; a standalone plane defaults to False)
    plane.discard_bad_blocks = True
    submit(plane, prompts_for(4))  # fills both shards
    plane.step()  # dispatch block 1
    plane.step()  # settle block 1, dispatch block 2
    transfers_before = plane.host_transfers
    plane.inject_poison(1)
    plane.step()  # settles the clean block 2; dispatches poisoned block
    poisoned_rows = [
        list(plane.slots[row].produced) for row in plane.shard_rows(1)
    ]
    healthy_rows = [
        list(plane.slots[row].produced) for row in plane.shard_rows(0)
    ]
    plane.step()  # settles the poisoned block
    assert plane.last_health_bad is not None
    assert bool(plane.last_health_bad[1]) and not bool(
        plane.last_health_bad[0]
    )
    assert (1, "poisoned-logits") in plane.shard_suspects()
    # nothing garbage ever reached a slot: the poisoned shard's rows are
    # exactly where they were before the poisoned block settled...
    assert [
        list(plane.slots[row].produced) for row in plane.shard_rows(1)
    ] == poisoned_rows
    # ...while the healthy shard kept decoding
    assert all(
        len(plane.slots[row].produced) > len(prior)
        for row, prior in zip(plane.shard_rows(0), healthy_rows)
    )
    # zero additional host syncs: detection rode the existing one
    # combined settle transfer per cycle
    assert plane.host_transfers - transfers_before == 2


def test_wedge_sentinel_counts_stalls_and_heals_lossless(tiny, donor22):
    control = make_plane(tiny, donor=donor22)
    submit(control, prompts_for(2, seed=9))
    expected = drain(control)

    plane = make_plane(tiny, donor=donor22)
    submit(plane, prompts_for(2, seed=9))
    out = {}

    def step():
        # the healthy shard keeps finishing requests mid-wedge
        out.update((p, list(t)) for p, t in plane.step())

    step()
    step()
    plane.inject_wedge(1)
    step()  # settles the last clean block
    for _ in range(3):
        step()  # wedged blocks: busy rows, zero tokens back
    assert plane.shard_stall_cycles[1] >= 3
    assert (1, "no-progress") in plane.shard_suspects(stall_grace=3)
    # the healthy shard may read one spurious stall right as its rows
    # complete (busy at dispatch, nothing left to emit) — exactly why
    # the grace floor is >= 2 — but it never reaches the indictment bar
    assert plane.shard_stall_cycles[0] <= 1
    # the freeze is lossless: un-wedging resumes exactly where the rows
    # stopped — outputs byte-identical to the never-wedged control
    plane.inject_wedge(1, False)
    out.update(drain(plane))
    assert out == expected


def test_mask_corruption_sentinel_and_reassert_heals(tiny, donor22):
    plane = make_plane(tiny, donor=donor22)
    # keep the gang busy on shard 0 while shard 1 sits empty
    submit(plane, prompts_for(1, seed=11))
    plane.corrupt_active_mask(1)
    plane.step()
    plane.step()  # the corrupted summary settles: device says 0 free
    assert plane.mask_mismatch[1] and not plane.mask_mismatch[0]
    assert (1, "mask-mismatch") in plane.shard_suspects()
    # re-asserting the mask is the heal; the sentinel holds off for the
    # two settles whose summaries predate the flip, then stays clear
    plane.set_shard_active(1, True)
    for _ in range(3):
        plane.step()
    assert not plane.mask_mismatch[1]


# ---------------------------------------------------------------------------
# Evacuation surface: take_shard_inflight + submit_resume
# ---------------------------------------------------------------------------


def test_resume_parity_greedy(tiny, donor22):
    # a re-prefilled (evacuated) row decodes byte-identically to one
    # that was never interrupted
    ids = prompts_for(1, seed=21)[0]
    control = make_plane(tiny, donor=donor22)
    submit(control, [ids], tag="c")
    expected = drain(control)["req-c-0"]

    plane = make_plane(tiny, donor=donor22)
    submit(plane, [ids], tag="c")
    plane.step()
    plane.step()
    plane.step()  # a few tokens in flight, mid-request
    taken = plane.take_shard_inflight(0)
    assert len(taken) == 1
    payload, produced, budget, submitted_at = taken[0]
    assert 0 < len(produced) < budget
    assert plane.shard_busy(0) == 0  # slots freed
    plane.set_shard_active(0, False)  # quarantine stand-in
    rows = plane.submit_resume(
        [(ids, payload, produced, budget, submitted_at)]
    )
    assert all(row in plane.shard_rows(1) for row in rows)
    out = drain(plane)
    assert out["req-c-0"] == expected


def test_submit_resume_validates(tiny, donor22):
    plane = make_plane(tiny, donor=donor22)
    ids = prompts_for(1)[0]
    with pytest.raises(ValueError, match="does not resume"):
        plane.submit_resume([(ids, "p", list(range(TOKENS)), TOKENS, 0.0)])
    too_many = [
        (ids, f"p{i}", [1], TOKENS, 0.0)
        for i in range(len(plane.slots) + 1)
    ]
    with pytest.raises(RuntimeError, match="no free slot"):
        plane.submit_resume(too_many)


@pytest.mark.parametrize("cut", [BLOCK, TOKENS - 1],
                         ids=["block-boundary", "budget-edge"])
def test_resume_at_block_and_budget_edges(tiny, donor22, cut):
    # the two cut points the greedy-parity test can't hit by stepping:
    # a resume cut exactly at a decode-block boundary, and one token
    # short of the budget — there the resume insert's first token is
    # the request's LAST (remaining block budget zero), so the row must
    # complete straight out of the insert settle
    ids = prompts_for(1, seed=23)[0]
    control = make_plane(tiny, donor=donor22)
    submit(control, [ids], tag="e")
    expected = drain(control)["req-e-0"]
    assert len(expected) == TOKENS

    plane = make_plane(tiny, donor=donor22)
    rows = plane.submit_resume(
        [(ids, "resumed", expected[:cut], TOKENS, 0.0)]
    )
    assert len(rows) == 1
    out = drain(plane)
    assert out["resumed"] == expected


def test_pooled_prefix_row_resume_parity(tiny, donor22):
    # a row admitted through the shared prefix pool evacuates through
    # the PLAIN resume path: the evacuation record carries only the
    # produced tokens, so the resume re-prefills the full concatenated
    # prompt with no pool entry behind it — parity must hold against a
    # control that never touched the pool
    params, config = tiny
    rng = np.random.default_rng(43)
    # the pooled layout spends max_seq_len on prefix + prompt + gen, so
    # the pooled bucket is smaller than the module default — and the
    # full concatenated prompt must fit the PLAIN bucket the resume
    # lands in (the resume path truncates to prompt_len)
    prefix_len, pooled_prompt = 2, 6
    prefix = rng.integers(1, 64, prefix_len).astype(np.int32)
    suffix = rng.integers(1, 64, pooled_prompt - prefix_len).astype(
        np.int32
    )
    full = np.concatenate([prefix, suffix])

    control = ShardedBatcher(
        params, config, shards=2, shard_slots=2,
        prompt_len=pooled_prompt, generate_tokens=TOKENS,
        decode_block=BLOCK,
    )
    control.submit_many([(full, "req-x-0")])
    expected = drain(control)["req-x-0"]

    from kube_sqs_autoscaler_tpu.workloads.tenancy import TenancyConfig

    worker = ContinuousWorker(
        FakeMessageQueue(), params, config,
        service_config(seq_len=pooled_prompt, result_queue_url=""),
        tenancy=TenancyConfig(
            tenants=("a",), prefix_pool=2, prefix_len=prefix_len,
            sticky=True,
        ),
        sharded=True,
    )
    batcher = worker.batcher
    (row,) = batcher.submit_many_prefixed([("a", prefix, suffix, "pp")])
    shard = row // service_config().batch_size
    batcher.step()
    batcher.step()  # a few tokens in, mid-request
    taken = batcher.take_shard_inflight(shard)
    assert len(taken) == 1
    payload, produced, budget, submitted_at = taken[0]
    assert payload == "pp" and 0 < len(produced) < budget
    batcher.submit_resume([(full, payload, produced, budget,
                            submitted_at)])
    out = drain(batcher)
    assert out["pp"] == expected


# ---------------------------------------------------------------------------
# The pool's quarantine state machine: detect -> quarantine -> evacuate
# -> probe -> readmit
# ---------------------------------------------------------------------------


def make_pool(tiny, *, queue_url, batch_size=3, shards=2, visibility=30.0,
              probe_after_cycles=3, hang_grace_cycles=2, donor=None):
    params, config = tiny
    clock = FakeClock()
    queue = FakeMessageQueue(visibility_timeout=visibility,
                             now_fn=clock.now)
    results = FakeMessageQueue(now_fn=clock.now)
    service = service_config(
        queue_url=queue_url, batch_size=batch_size, shards=shards,
        result_queue_url=f"{queue_url}-r",
    )
    pool = ShardedWorkerPool.serving(
        queue, params, config, service, result_queue=results,
        min=shards, max=shards, initial=shards, clock=clock,
        engine_source=donor, now_fn=clock.now,
        probe_after_cycles=probe_after_cycles,
        hang_grace_cycles=hang_grace_cycles,
    )
    return pool, clock, queue, results, service


@pytest.fixture(scope="module")
def pool_donor(tiny):
    """A warmed (2 shards x 3 slots) gang engine for the pool tests."""
    params, config = tiny
    worker = FleetWorker(
        FakeMessageQueue(), params, config,
        service_config(batch_size=3, result_queue_url=""),
        sharded=True,
    )
    return worker.batcher


def drive(pool, clock, queue, *, queue_url, to_send, until,
          on_cycle=None, max_cycles=400, send_every=1):
    sent = []
    for step in range(max_cycles):
        if to_send and step % send_every == 0:
            sent.append(queue.send_message(
                queue_url, json.dumps(to_send.pop(0).tolist())
            ))
        if on_cycle is not None:
            on_cycle(pool.cycle)
        pool.run_cycle()
        clock.advance(0.2)
        if not to_send and until():
            return sent
    raise AssertionError("pool did not converge within the cycle budget")


def test_pool_quarantine_evacuate_probe_readmit(tiny, pool_donor):
    from kube_sqs_autoscaler_tpu.obs import WorkloadMetrics

    pool, clock, queue, results, service = make_pool(
        tiny, queue_url="chaos://loop", donor=pool_donor,
    )
    metrics = WorkloadMetrics()
    pool.attach_metrics(metrics)
    seen = {"quarantined_render": False}

    def on_cycle(cycle):
        # heal two cycles after the quarantine landed; capture the
        # mid-quarantine gauge rendering on the way
        if pool.quarantined_total and not seen["quarantined_render"]:
            seen["quarantined_render"] = True
            text = metrics.render()
            prefix = "kube_sqs_autoscaler_workload"
            assert f'{prefix}_shard_health{{shard="1"}} 2.0' in text
            assert f"# TYPE {prefix}_shard_quarantined_total counter" in text
            assert f"{prefix}_shard_quarantined_total 1" in text
            pool.poison_shard(1, False)
        elif cycle == 4:
            pool.poison_shard(1)

    sent = drive(
        pool, clock, queue, queue_url="chaos://loop",
        to_send=prompts_for(16, seed=31),
        until=lambda: (
            pool.processed >= 16 and pool.idle
            and all(s == SERVING for s in pool.shard_states)
        ),
        on_cycle=on_cycle,
        # half-rate arrivals keep slack on the healthy shard — the
        # regime where evacuation has somewhere to put rows
        send_every=2,
    )
    # the whole loop ran: quarantine with the right cause, live
    # evacuation (shard 0 had exactly one free slot: one row resumed,
    # the rest released to the queue), probe, readmission
    assert pool.quarantined_total == 1
    assert pool.rows_evacuated_total >= 1
    assert pool.readmitted_total == 1
    names = [e.name for e in pool.events]
    assert ["shard-quarantine", "shard-probe", "shard-readmit"] == [
        n for n in names
        if n in ("shard-quarantine", "shard-probe", "shard-readmit")
    ]
    quarantine = next(e for e in pool.events if e.name == "shard-quarantine")
    assert quarantine.args["cause"] == "poisoned-logits"
    assert quarantine.args["evacuated"] + quarantine.args["released"] >= 1
    # exactly-once across evacuation, hand-back, and redelivery
    replies, duplicates = collect_replies(results, service.result_queue_url)
    assert set(replies) == set(sent)
    assert duplicates == 0
    # the chaos instants land on the Chrome-trace timeline under their
    # own shard category
    events = pool.trace_events(time_origin=0.0)
    by_name = {e["name"]: e for e in events}
    for name in ("shard-quarantine", "shard-probe", "shard-readmit"):
        assert by_name[name]["cat"] == "shard"
        assert by_name[name]["ph"] == "i"
    # after recovery the health gauge reads 0 again
    text = metrics.render()
    assert 'shard_health{shard="1"} 0.0' in text
    assert "rows_evacuated_total" in text


def test_failed_probe_requarantines_until_healed(tiny, pool_donor):
    pool, clock, queue, results, service = make_pool(
        tiny, queue_url="chaos://probe", donor=pool_donor,
    )
    state = {"serving_mid_fault": False}

    def on_cycle(cycle):
        if cycle == 4:
            pool.wedge_shard(1)
        elif pool.quarantined_total >= 2:
            # the shard failed its first probe (still wedged) and was
            # re-quarantined: NOW let it heal
            pool.wedge_shard(1, False)
        if (pool.worker.batcher.shard_wedged[1]
                and pool.shard_states[1] == SERVING
                and pool.quarantined_total > 0):
            # a probe must never re-admit a still-faulted shard: an
            # admission-insert first token alone is not evidence the
            # gang decode works
            state["serving_mid_fault"] = True

    sent = drive(
        pool, clock, queue, queue_url="chaos://probe",
        to_send=prompts_for(24, seed=37),
        until=lambda: (
            pool.processed >= 24 and pool.idle
            and all(s == SERVING for s in pool.shard_states)
        ),
        on_cycle=on_cycle,
    )
    assert pool.quarantined_total >= 2  # first detection + failed probe
    assert pool.readmitted_total == 1
    assert not state["serving_mid_fault"]
    causes = [
        e.args["cause"] for e in pool.events
        if e.name == "shard-quarantine"
    ]
    assert all(cause == "no-progress" for cause in causes)
    replies, duplicates = collect_replies(results, service.result_queue_url)
    assert set(replies) == set(sent)
    assert duplicates == 0


def test_mask_corruption_quarantines_and_recovers(tiny, pool_donor):
    pool, clock, queue, results, service = make_pool(
        tiny, queue_url="chaos://mask", donor=pool_donor,
    )

    def on_cycle(cycle):
        if cycle == 5:
            pool.corrupt_shard_mask(1)

    sent = drive(
        pool, clock, queue, queue_url="chaos://mask",
        to_send=prompts_for(16, seed=41),
        until=lambda: (
            pool.processed >= 16 and pool.idle
            and all(s == SERVING for s in pool.shard_states)
        ),
        on_cycle=on_cycle,
    )
    causes = [
        e.args["cause"] for e in pool.events
        if e.name == "shard-quarantine"
    ]
    assert causes == ["mask-mismatch"]
    # the quarantine's mask write re-asserted the device bit; the probe
    # then re-admitted a healthy shard (corruption is one-shot)
    assert pool.readmitted_total == 1
    replies, duplicates = collect_replies(results, service.result_queue_url)
    assert set(replies) == set(sent)
    assert duplicates == 0


def test_redelivery_racing_evacuated_row_stays_exactly_once(
    tiny, pool_donor,
):
    # a visibility-timeout redelivery races the evacuated row's resumed
    # twin: the moment the quarantine lands, the victims' original
    # messages are forced back to visible (exactly what an expiring
    # visibility window does), so the queue re-dispatches them while
    # their resumed twins decode on healthy shards — one reply each,
    # not two
    pool, clock, queue, results, service = make_pool(
        tiny, queue_url="chaos://race", donor=pool_donor,
    )
    batcher = pool.worker.batcher
    state = {"victims": [], "redelivered": False}

    def on_cycle(cycle):
        if not state["victims"] and batcher.shard_busy(1) > 0:
            # the first cycle shard 1 holds work: poison it, and note
            # which requests are about to be evacuated
            pool.poison_shard(1)
            state["victims"] = [
                batcher.slots[row].payload
                for row in batcher.shard_rows(1)
                if batcher.slots[row].busy
            ]
        elif pool.quarantined_total and not state["redelivered"]:
            state["redelivered"] = True
            pool.poison_shard(1, False)
            for payload in state["victims"]:
                # stale handles (already settled / already handed back)
                # are no-ops, like real SQS
                queue.change_message_visibility(
                    "chaos://race", payload["ReceiptHandle"], 0
                )

    def queue_drained():
        attrs = queue.get_queue_attributes("chaos://race", ["All"])
        return (attrs["ApproximateNumberOfMessages"] == "0"
                and attrs["ApproximateNumberOfMessagesNotVisible"] == "0")

    sent = drive(
        pool, clock, queue, queue_url="chaos://race",
        to_send=prompts_for(12, seed=43),
        until=lambda: (
            pool.processed >= 12 and pool.idle and queue_drained()
            and all(s == SERVING for s in pool.shard_states)
        ),
        on_cycle=on_cycle,
        send_every=2,
    )
    assert pool.quarantined_total >= 1
    assert pool.rows_evacuated_total >= 1
    # the redelivered copies were consumed without a second reply...
    assert pool.duplicates_suppressed > 0
    # ...and every request was still answered exactly once
    replies, duplicates = collect_replies(results, service.result_queue_url)
    assert set(replies) == set(sent)
    assert duplicates == 0


def test_evacuation_without_free_slots_hands_back_to_queue(
    tiny, pool_donor,
):
    # every slot on every shard full at quarantine time: evacuation
    # finds no healthy free slot, so the sick shard's rows go back
    # through the queue instead (slower, never lost)
    pool, clock, queue, results, service = make_pool(
        tiny, queue_url="chaos://full", donor=pool_donor,
    )
    sent = [
        queue.send_message("chaos://full", json.dumps(ids.tolist()))
        for ids in prompts_for(6, seed=47)
    ]
    pool.run_cycle()  # one refill admits all six: both shards full
    clock.advance(0.2)
    batcher = pool.worker.batcher
    assert batcher.shard_busy(0) == 3 and batcher.shard_busy(1) == 3
    pool.poison_shard(1)
    for _ in range(6):
        pool.run_cycle()
        clock.advance(0.2)
        if pool.quarantined_total:
            break
    assert pool.quarantined_total == 1
    assert pool.rows_evacuated_total == 0  # nowhere to put them live
    assert pool.released_total >= 1
    pool.poison_shard(1, False)
    for _ in range(200):
        pool.run_cycle()
        clock.advance(0.2)
        if (pool.processed >= len(sent) and pool.idle
                and all(s == SERVING for s in pool.shard_states)):
            break
    replies, duplicates = collect_replies(results, service.result_queue_url)
    assert set(replies) == set(sent)  # slower, never lost
    assert duplicates == 0


def test_scale_up_never_resurrects_a_quarantined_shard(tiny, pool_donor):
    params, config = tiny
    clock = FakeClock()
    queue = FakeMessageQueue(now_fn=clock.now)
    results = FakeMessageQueue(now_fn=clock.now)
    pool = ShardedWorkerPool.serving(
        queue, params, config,
        service_config(queue_url="chaos://up", batch_size=3,
                       result_queue_url="chaos://up-r"),
        result_queue=results, min=1, max=2, initial=2, clock=clock,
        engine_source=pool_donor, probe_after_cycles=50,
    )
    queue.send_message(
        "chaos://up", json.dumps(prompts_for(1, seed=51)[0].tolist())
    )
    pool.run_cycle()  # shard 0 takes the request; shard 1 sits idle
    pool.poison_shard(1)
    # force work onto shard 1 so the sentinel can see it
    queue.send_message(
        "chaos://up", json.dumps(prompts_for(1, seed=52)[0].tolist())
    )
    for _ in range(10):
        pool.run_cycle()
        clock.advance(0.2)
        if pool.quarantined_total:
            break
    assert pool.shard_states[1] == QUARANTINED
    replicas_before = pool.replicas
    pool.scale_up()  # must NOT flip the sick shard's mask back on
    assert pool.shard_states[1] == QUARANTINED
    assert not pool.worker.batcher.shard_admitting[1]
    assert pool.replicas == replicas_before


def test_quarantined_draining_shard_resumes_drain_after_probe(
    tiny, pool_donor,
):
    # a scale_down the Scaler ordered must survive a quarantine: the
    # passed probe resumes the drain (shard retires to inactive) rather
    # than silently re-admitting the shard to SERVING
    from kube_sqs_autoscaler_tpu.fleet import DRAINING, INACTIVE

    pool, clock, queue, results, service = make_pool(
        tiny, queue_url="chaos://drain", donor=pool_donor,
    )
    pool.min = 1  # allow the scale_down
    sent = [
        queue.send_message("chaos://drain", json.dumps(ids.tolist()))
        for ids in prompts_for(4, seed=53)
    ]
    pool.run_cycle()  # 2 rows per shard in flight
    clock.advance(0.2)
    assert pool.worker.batcher.shard_busy(1) == 2
    pool.scale_down()
    assert pool.shard_states[1] == DRAINING
    assert pool.replicas == 1
    pool.poison_shard(1)  # the draining shard falls sick mid-drain
    for _ in range(6):
        pool.run_cycle()
        clock.advance(0.2)
        if pool.quarantined_total:
            break
    assert pool.shard_states[1] == QUARANTINED
    pool.poison_shard(1, False)
    # keep a trickle of traffic flowing so the probe gets its request
    extra = drive(
        pool, clock, queue, queue_url="chaos://drain",
        to_send=prompts_for(12, seed=54),
        until=lambda: (
            pool.readmitted_total > 0
            and pool.shard_states[1] == INACTIVE and pool.idle
        ),
        send_every=2,
    )
    # the probe passed, but the shard resumed its drain: it was never
    # re-admitted to SERVING and the actuated replica count held
    assert pool.readmitted_total == 1
    readmit = next(e for e in pool.events if e.name == "shard-readmit")
    assert readmit.args["resumed_drain"] is True
    assert pool.replicas == 1
    # drive the rest of the traffic home on the surviving shard
    for _ in range(300):
        pool.run_cycle()
        clock.advance(0.2)
        if pool.processed >= len(sent) + len(extra) and pool.idle:
            break
    replies, duplicates = collect_replies(results, service.result_queue_url)
    assert set(replies) == set(sent) | set(extra)
    assert duplicates == 0
    assert pool.shard_states[1] == INACTIVE


def test_budget_one_traffic_never_trips_the_stall_sentinel(tiny):
    # generate_tokens=1 rows are never live in any gang block — their
    # single token arrives via the deferred-firsts settle.  That settle
    # must count as shard progress, or a perfectly healthy plane under
    # steady budget-1 traffic reads as stalled and quarantines itself.
    params, config = tiny
    clock = FakeClock()
    queue = FakeMessageQueue(now_fn=clock.now)
    results = FakeMessageQueue(now_fn=clock.now)
    pool = ShardedWorkerPool.serving(
        queue, params, config,
        service_config(queue_url="chaos://b1", generate_tokens=1,
                       result_queue_url="chaos://b1-r"),
        result_queue=results, min=2, max=2, initial=2, clock=clock,
        now_fn=clock.now, hang_grace_cycles=2,
    )
    prompts = prompts_for(20, seed=61)
    sent = []
    for _ in range(80):
        if prompts:
            sent.append(queue.send_message(
                "chaos://b1", json.dumps(prompts.pop(0).tolist())
            ))
        pool.run_cycle()
        clock.advance(0.2)
        if not prompts and pool.processed >= len(sent) and pool.idle:
            break
    assert pool.quarantined_total == 0
    assert all(state == SERVING for state in pool.shard_states)
    replies, duplicates = collect_replies(results, "chaos://b1-r")
    assert set(replies) == set(sent)
    assert duplicates == 0


def test_budget_one_probe_readmits_on_completion_evidence(tiny):
    # budget-1 rows never enter a gang block, so a probing shard can
    # never show gang progress — the probe verdict must accept the
    # probe request COMPLETING as the shard's proof of health, or a
    # budget-1 plane could never leave quarantine
    params, config = tiny
    clock = FakeClock()
    queue = FakeMessageQueue(now_fn=clock.now)
    results = FakeMessageQueue(now_fn=clock.now)
    pool = ShardedWorkerPool.serving(
        queue, params, config,
        service_config(queue_url="chaos://b1p", generate_tokens=1,
                       result_queue_url="chaos://b1p-r"),
        result_queue=results, min=2, max=2, initial=2, clock=clock,
        now_fn=clock.now, hang_grace_cycles=2, probe_after_cycles=2,
    )
    prompts = prompts_for(40, seed=67)
    sent, corrupted = [], False
    for _ in range(200):
        # 3 arrivals per cycle: budget-1 requests turn over fast and the
        # freest-first router orders the probing shard's single slot
        # LAST, so the refill must out-size the healthy shard's free
        # slots for any probe traffic to spill over at all
        for _ in range(3):
            if prompts:
                sent.append(queue.send_message(
                    "chaos://b1p", json.dumps(prompts.pop(0).tolist())
                ))
        if len(sent) == 6 and not corrupted:
            corrupted = True
            pool.corrupt_shard_mask(1)  # one-shot fault, heals on quarantine
        pool.run_cycle()
        clock.advance(0.2)
        if (not prompts and pool.processed >= len(sent) and pool.idle
                and all(s == SERVING for s in pool.shard_states)):
            break
    assert pool.quarantined_total == 1
    assert pool.readmitted_total == 1
    assert all(state == SERVING for state in pool.shard_states)
    replies, duplicates = collect_replies(results, "chaos://b1p-r")
    assert set(replies) == set(sent)
    assert duplicates == 0


def test_stop_all_clears_probe_capacity_cap(tiny, pool_donor):
    # a pool stopped while a shard is PROBING must not leave the
    # half-open one-slot router cap armed for the next scale_up
    pool, clock, queue, results, service = make_pool(
        tiny, queue_url="chaos://stop", donor=pool_donor,
        probe_after_cycles=2,
    )

    def on_cycle(cycle):
        if cycle == 3:
            pool.poison_shard(1)

    try:
        drive(
            pool, clock, queue, queue_url="chaos://stop",
            to_send=prompts_for(8, seed=71),
            until=lambda: pool.shard_states[1] == PROBING,
            on_cycle=on_cycle, max_cycles=60,
        )
    except AssertionError:
        pass  # remaining traffic is irrelevant — we only need PROBING
    assert pool.shard_states[1] == PROBING
    assert pool.worker.batcher.shard_probing[1]
    pool.stop_all()
    assert not any(pool.worker.batcher.shard_probing)
    assert pool._quarantined_at == {}


def test_shard_health_codes_cover_every_state():
    assert set(SHARD_HEALTH_CODES) == set(SHARD_STATE_CODES)
    assert SHARD_HEALTH_CODES[QUARANTINED] == 2
    assert SHARD_HEALTH_CODES[PROBING] == 1
    assert SHARD_HEALTH_CODES[SERVING] == 0


def test_pool_validates_chaos_knobs(tiny, pool_donor):
    params, config = tiny
    with pytest.raises(ValueError, match="hang_grace_cycles"):
        make_pool(tiny, queue_url="chaos://bad", donor=pool_donor,
                  hang_grace_cycles=1)
    with pytest.raises(ValueError, match="probe_after_cycles"):
        make_pool(tiny, queue_url="chaos://bad", donor=pool_donor,
                  probe_after_cycles=0)


# ---------------------------------------------------------------------------
# Trace polish: shard-domain instants carry their own category
# ---------------------------------------------------------------------------


def test_shard_events_get_shard_trace_category():
    from kube_sqs_autoscaler_tpu.obs.trace import instant_trace_events

    Event = collections.namedtuple("Event", "name t args")
    events = instant_trace_events([
        Event("replica-kill", 1.0, {"cause": "hung"}),
        Event("shard-quarantine", 2.0, {"shard": 1}),
        Event("shard-readmit", 3.0, {"shard": 1}),
    ], time_origin=0.0)
    assert [e["cat"] for e in events] == ["fleet", "shard", "shard"]


# ---------------------------------------------------------------------------
# Satellite: per-request deadline / TTL at admission
# ---------------------------------------------------------------------------


def test_request_ttl_sheds_expired_with_explicit_reply(tiny):
    params, config = tiny
    clock = FakeClock()
    queue = FakeMessageQueue(now_fn=clock.now)
    results = FakeMessageQueue(now_fn=clock.now)
    service = service_config(
        queue_url="ttl://q", shards=1, request_ttl_s=5.0,
        result_queue_url="ttl://r",
    )
    worker = ContinuousWorker(
        queue, params, config, service, result_queue=results,
        now_fn=clock.now,
    )
    stale = queue.send_message(
        "ttl://q", json.dumps(prompts_for(1)[0].tolist())
    )
    clock.advance(10.0)  # now older than the TTL
    fresh = queue.send_message(
        "ttl://q", json.dumps(prompts_for(1, seed=3)[0].tolist())
    )
    for _ in range(40):
        worker.run_once()
        if worker.processed >= 1 and worker.batcher.active == 0:
            break
    assert worker.shed == 1
    replies, duplicates = collect_replies(results, "ttl://r")
    # shed is answered, never silently dropped: an explicit expired
    # reply for the stale request, a normal reply for the fresh one
    assert replies[stale]["error"] == "expired"
    assert "tokens" not in replies[stale]
    assert replies[fresh]["tokens"]
    assert duplicates == 0
    attrs = queue.get_queue_attributes("ttl://q", ["All"])
    assert attrs["ApproximateNumberOfMessages"] == "0"
    assert attrs["ApproximateNumberOfMessagesNotVisible"] == "0"
    # the counter reaches Prometheus
    from kube_sqs_autoscaler_tpu.obs import WorkloadMetrics

    metrics = WorkloadMetrics()
    worker.attach_metrics(metrics)
    text = metrics.render()
    prefix = "kube_sqs_autoscaler_workload"
    assert f"# TYPE {prefix}_requests_shed_total counter" in text
    assert f"{prefix}_requests_shed_total 1" in text


def test_request_ttl_shed_registers_in_reply_registry(tiny):
    # on the fleet substrate a shed still counts toward exactly-once: a
    # redelivered copy of an expired-and-answered request is suppressed
    params, config = tiny

    class Registry:
        def __init__(self):
            self.replied = set()
            self.dups = 0

        def already_replied(self, rid):
            return rid in self.replied

        def mark_replied(self, rid):
            self.replied.add(rid)

        def note_duplicate(self, rid):
            self.dups += 1

    clock = FakeClock()
    queue = FakeMessageQueue(now_fn=clock.now, visibility_timeout=1.0)
    results = FakeMessageQueue(now_fn=clock.now)
    registry = Registry()
    worker = FleetWorker(
        queue, params, config,
        service_config(queue_url="ttl://f", shards=1, request_ttl_s=5.0,
                       result_queue_url="ttl://f-r"),
        result_queue=results, pool=registry, now_fn=clock.now,
    )
    mid = queue.send_message(
        "ttl://f", json.dumps(prompts_for(1)[0].tolist())
    )
    clock.advance(10.0)
    worker.run_once()
    assert registry.already_replied(mid)
    assert worker.processed == 0  # sheds never count as completions


def test_request_ttl_validates():
    with pytest.raises(ValueError, match="request_ttl_s"):
        service_config(request_ttl_s=-1.0)
    # messages without a SentTimestamp never expire
    assert service_config(request_ttl_s=0.0).request_ttl_s == 0.0


def test_cli_rejects_ttl_without_continuous():
    from kube_sqs_autoscaler_tpu.workloads.__main__ import main

    with pytest.raises(SystemExit, match="requires --continuous"):
        main(["--demo", "1", "--generate-tokens", "2",
              "--request-ttl", "5"])
    with pytest.raises(SystemExit, match="must be >= 0"):
        main(["--demo", "1", "--continuous", "--generate-tokens", "2",
              "--request-ttl", "-1"])


# ---------------------------------------------------------------------------
# Satellite: the idle-wedge watchdog (PR 6 blind-spot regression)
# ---------------------------------------------------------------------------


def test_idle_wedged_replica_declared_dead(tiny):
    # an idle replica that wedges (hung with ZERO in flight) used to be
    # invisible to the progress watchdog — only the router's next
    # orphan dispatch would have surfaced it.  The refill-liveness
    # counter closes that: a healthy idle replica bumps it every cycle,
    # a wedged one freezes it.
    params, config = tiny
    plain = service_config(queue_url="idle://q", shards=1,
                           result_queue_url="idle://r")
    queue = FakeMessageQueue()
    results = FakeMessageQueue()
    donor = FleetWorker(
        FakeMessageQueue(), params, config,
        service_config(queue_url="idle://d", shards=1,
                       result_queue_url=""),
    ).batcher
    pool = WorkerPool.serving(
        queue, params, config, plain, result_queue=results,
        min=1, max=2, initial=2, engine_source=donor,
        hang_grace_cycles=3,
    )
    for _ in range(6):
        pool.run_cycle()
    # no false positive: both replicas idle and healthy, both serving
    assert [r.state for r in pool.members] == [SERVING, SERVING]
    pool.hang_worker(1)
    for _ in range(5):
        pool.run_cycle()
    assert pool.members[1].state == DEAD
    kill = next(e for e in pool.events if e.name == "replica-kill")
    assert kill.args["cause"] == "hung-idle"
    # the survivor still serves traffic
    mid = queue.send_message(
        "idle://q", json.dumps(prompts_for(1)[0].tolist())
    )
    for _ in range(60):
        pool.run_cycle()
        if pool.processed >= 1 and pool.idle:
            break
    replies, _ = collect_replies(results, "idle://r")
    assert mid in replies


# ---------------------------------------------------------------------------
# The chaos-serve suite: tier-1 smoke + full battery (slow)
# ---------------------------------------------------------------------------


def test_chaos_serve_suite_smoke(tmp_path):
    from bench import run_chaos_serve_suite

    out = tmp_path / "bench_chaos_serve.json"
    headline = run_chaos_serve_suite(
        str(out), messages=24, episodes=("poison",), timing_gates=False,
    )
    artifact = json.loads(out.read_text())
    episode = artifact["report"]["poison"]
    # the acceptance gates the suite enforces (it exits 2 otherwise):
    # exactly-once, >=1 quarantined and re-admitted via probe, rows
    # rescued, replies byte-identical to the no-fault control, and the
    # sentinels riding the one combined settle transfer
    assert episode["lost"] == 0 and episode["duplicate_replies"] == 0
    assert episode["quarantined"] >= 1
    assert episode["readmitted"] >= 1
    assert episode["rows_evacuated"] + episode["rows_released"] >= 1
    assert artifact["parity_divergences"]["poison"] == 0
    assert episode["decode_dispatches"] == episode["gang_cycles"]
    assert (episode["host_transfers"]
            <= episode["cycles"] + episode["quarantined"] + 1)
    assert all(state == "serving" for state in episode["final_states"])
    assert "0 parity divergences" in headline["unit"]


@pytest.mark.slow
def test_chaos_serve_full_battery(tmp_path):
    # the committed-artifact configuration: all three fault classes,
    # timing gates on (healthy-shard TTFT p99 + post-readmit recovery)
    from bench import run_chaos_serve_suite

    out = tmp_path / "bench_r13.json"
    run_chaos_serve_suite(str(out))
    artifact = json.loads(out.read_text())
    for name in ("poison", "wedge", "mask"):
        assert artifact["report"][name]["lost"] == 0
        assert artifact["report"][name]["readmitted"] >= 1
        assert artifact["parity_divergences"][name] == 0
