"""Pure-policy tests: every SURVEY.md §2.2-C2 subtlety, exercised directly.

The reference never tests its policy in isolation (it can't — the policy is
welded to the loop at ``main.go:35-80``); these tests pin the factored-out
semantics so the loop tests (test_loop.py) only need to cover wiring.
"""

import random

from kube_sqs_autoscaler_tpu.core.policy import (
    Gate,
    PolicyConfig,
    PolicyState,
    initial_state,
    mark_scaled_down,
    mark_scaled_up,
    plan_tick,
)

CFG = PolicyConfig(
    scale_up_messages=100,
    scale_down_messages=10,
    scale_up_cooldown=10.0,
    scale_down_cooldown=30.0,
)

COLD = PolicyState(last_scale_up=-1e9, last_scale_down=-1e9)  # cooldowns long past


def test_up_threshold_is_inclusive():
    # main.go:51 `numMessages >= scaleUpMessages`
    assert plan_tick(100, 0.0, CFG, COLD).up is Gate.FIRE
    assert plan_tick(99, 0.0, CFG, COLD).up is Gate.IDLE
    assert plan_tick(101, 0.0, CFG, COLD).up is Gate.FIRE


def test_down_threshold_is_inclusive():
    # main.go:65 `numMessages <= scaleDownMessages`
    assert plan_tick(10, 0.0, CFG, COLD).down is Gate.FIRE
    assert plan_tick(11, 0.0, CFG, COLD).down is Gate.IDLE
    assert plan_tick(9, 0.0, CFG, COLD).down is Gate.FIRE


def test_startup_grace_blocks_both_directions():
    # main.go:37-38: timestamps initialized to now at boot.
    state = initial_state(0.0)
    assert plan_tick(1000, 5.0, CFG, state).up is Gate.COOLING
    assert plan_tick(0, 5.0, CFG, state).down is Gate.COOLING
    # up grace ends at t=10, down grace at t=30
    assert plan_tick(1000, 10.0, CFG, state).up is Gate.FIRE
    assert plan_tick(0, 10.0, CFG, state).down is Gate.COOLING
    assert plan_tick(0, 30.0, CFG, state).down is Gate.FIRE


def test_cooldown_boundary_fires_exactly_at_expiry():
    # main.go:52: cooling iff last+cool is strictly After(now).
    state = PolicyState(last_scale_up=0.0, last_scale_down=-1e9)
    assert plan_tick(100, 9.999, CFG, state).up is Gate.COOLING
    assert plan_tick(100, 10.0, CFG, state).up is Gate.FIRE


def test_cooling_up_skips_down_branch_entirely():
    # The `continue` at main.go:54: with overlapping thresholds, an up-cooling
    # tick must not evaluate (let alone fire) the down branch.
    cfg = PolicyConfig(
        scale_up_messages=5,
        scale_down_messages=50,  # overlapping: 5..50 triggers both
        scale_up_cooldown=10.0,
        scale_down_cooldown=0.0,
    )
    state = PolicyState(last_scale_up=0.0, last_scale_down=-1e9)
    plan = plan_tick(20, 5.0, cfg, state)
    assert plan.up is Gate.COOLING
    assert plan.down is Gate.SKIPPED


def test_overlapping_thresholds_can_fire_both_in_one_tick():
    # main.go:51,65 are `if` + `if`, not `else if`.
    cfg = PolicyConfig(
        scale_up_messages=5,
        scale_down_messages=50,
        scale_up_cooldown=0.0,
        scale_down_cooldown=0.0,
    )
    plan = plan_tick(20, 100.0, cfg, COLD)
    assert plan.up is Gate.FIRE
    assert plan.down is Gate.FIRE


def test_idle_band_between_thresholds():
    plan = plan_tick(50, 0.0, CFG, COLD)
    assert plan.up is Gate.IDLE
    assert plan.down is Gate.IDLE


def test_mark_helpers_touch_only_their_own_timestamp():
    state = PolicyState(last_scale_up=1.0, last_scale_down=2.0)
    up = mark_scaled_up(state, 7.0)
    assert (up.last_scale_up, up.last_scale_down) == (7.0, 2.0)
    down = mark_scaled_down(state, 9.0)
    assert (down.last_scale_up, down.last_scale_down) == (1.0, 9.0)


def test_plan_is_pure():
    state = PolicyState(last_scale_up=0.0, last_scale_down=0.0)
    a = plan_tick(100, 5.0, CFG, state)
    b = plan_tick(100, 5.0, CFG, state)
    assert a == b
    assert state == PolicyState(last_scale_up=0.0, last_scale_down=0.0)


def test_property_up_gate_matches_reference_predicate():
    # Randomized check of the exact reference predicates (main.go:51-52,65-66).
    rng = random.Random(1234)
    for _ in range(2000):
        cfg = PolicyConfig(
            scale_up_messages=rng.randint(0, 50),
            scale_down_messages=rng.randint(0, 50),
            scale_up_cooldown=rng.choice([0.0, 1.0, 10.0]),
            scale_down_cooldown=rng.choice([0.0, 1.0, 10.0]),
        )
        state = PolicyState(
            last_scale_up=rng.uniform(-20, 20), last_scale_down=rng.uniform(-20, 20)
        )
        now = rng.uniform(0, 40)
        n = rng.randint(0, 60)
        plan = plan_tick(n, now, cfg, state)

        if n >= cfg.scale_up_messages:
            expect_up = (
                Gate.COOLING
                if state.last_scale_up + cfg.scale_up_cooldown > now
                else Gate.FIRE
            )
        else:
            expect_up = Gate.IDLE
        assert plan.up is expect_up

        if expect_up is Gate.COOLING:
            assert plan.down is Gate.SKIPPED
        elif n <= cfg.scale_down_messages:
            expect_down = (
                Gate.COOLING
                if state.last_scale_down + cfg.scale_down_cooldown > now
                else Gate.FIRE
            )
            assert plan.down is expect_down
        else:
            assert plan.down is Gate.IDLE


def test_gate_code_is_the_gates_shared_core():
    # gate_up/gate_down delegate to the branchless gate_code (the compiled
    # simulator runs the same function inside lax.scan); sweep random and
    # boundary cases to pin the delegation
    from kube_sqs_autoscaler_tpu.core.policy import (
        GATE_BY_CODE,
        gate_code,
        gate_down,
        gate_up,
    )

    rng = random.Random(13)
    for _ in range(500):
        num = rng.choice([0, 9, 10, 11, 99, 100, 101, rng.randrange(0, 500)])
        now = rng.uniform(0.0, 200.0)
        if rng.random() < 0.3:  # land exactly on cooldown boundaries too
            now = round(now)
        state = PolicyState(
            last_scale_up=now - rng.choice([0.0, 5.0, 10.0, 50.0]),
            last_scale_down=now - rng.choice([0.0, 15.0, 30.0, 90.0]),
        )
        up_code = gate_code(
            num >= CFG.scale_up_messages, now, state.last_scale_up,
            CFG.scale_up_cooldown,
        )
        down_code = gate_code(
            num <= CFG.scale_down_messages, now, state.last_scale_down,
            CFG.scale_down_cooldown,
        )
        assert gate_up(num, now, CFG, state) is GATE_BY_CODE[int(up_code)]
        assert gate_down(num, now, CFG, state) is GATE_BY_CODE[int(down_code)]


def test_gate_code_works_elementwise_on_arrays():
    # the scan-ability contract: numpy arrays in, coded outcomes out
    import numpy as np

    from kube_sqs_autoscaler_tpu.core.policy import (
        GATE_COOLING,
        GATE_FIRE,
        GATE_IDLE,
        gate_code,
    )

    nums = np.array([50, 150, 150])
    met = nums >= 100
    last = np.array([0.0, 0.0, 95.0])
    codes = gate_code(met, 100.0, last, 10.0)
    assert codes.tolist() == [GATE_IDLE, GATE_FIRE, GATE_COOLING]
