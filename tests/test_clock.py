"""FakeClock determinism: ordered event delivery, sleep-advance semantics."""

from kube_sqs_autoscaler_tpu.core.clock import Clock, FakeClock, SystemClock


def test_sleep_advances_time():
    clock = FakeClock()
    clock.sleep(5.0)
    assert clock.now() == 5.0
    clock.sleep(0.5)
    assert clock.now() == 5.5
    assert clock.sleeps == [5.0, 0.5]


def test_scheduled_events_fire_in_order_at_their_instants():
    clock = FakeClock()
    seen = []
    clock.at(3.0, lambda: seen.append(("a", clock.now())))
    clock.at(1.0, lambda: seen.append(("b", clock.now())))
    clock.at(1.0, lambda: seen.append(("c", clock.now())))  # FIFO tie-break
    clock.advance(2.0)
    assert seen == [("b", 1.0), ("c", 1.0)]
    clock.advance(2.0)
    assert seen == [("b", 1.0), ("c", 1.0), ("a", 3.0)]
    assert clock.now() == 4.0


def test_event_scheduled_in_past_fires_on_next_advance():
    clock = FakeClock(start=10.0)
    seen = []
    clock.at(1.0, lambda: seen.append(clock.now()))
    clock.advance(0.0)
    assert seen == [10.0]


def test_events_can_schedule_events():
    clock = FakeClock()
    seen = []
    clock.at(1.0, lambda: clock.after(1.0, lambda: seen.append(clock.now())))
    clock.advance(5.0)
    assert seen == [2.0]


def test_protocol_conformance():
    assert isinstance(SystemClock(), Clock)
    assert isinstance(FakeClock(), Clock)


def test_system_clock_monotonic_and_sleeps():
    clock = SystemClock()
    t0 = clock.now()
    clock.sleep(0.01)
    assert clock.now() >= t0 + 0.009
    clock.sleep(-1.0)  # negative sleep is a no-op, not an error
