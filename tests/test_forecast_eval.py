"""Scenario-battery acceptance: the headline claim of the predictive
subsystem, asserted end to end — on the ramp and diurnal scenarios the
best forecaster strictly reduces max queue depth vs. the reactive policy
without blowing the churn budget.
"""

import json
import subprocess
import sys

import pytest

from kube_sqs_autoscaler_tpu.sim.evaluate import (
    default_battery,
    evaluate_battery,
    run_episode,
    summarize,
)

TARGETS = ("ramp", "diurnal")


@pytest.fixture(scope="module")
def target_report():
    battery = tuple(s for s in default_battery() if s.name in TARGETS)
    return evaluate_battery(scenarios=battery)


def test_best_forecaster_beats_reactive_on_ramp_and_diurnal(target_report):
    summary = summarize(target_report, target_scenarios=TARGETS)
    winner = summary["winner"]
    assert summary["candidates"][winner]["within_churn_budget"]
    for scenario in TARGETS:
        reactive = target_report[scenario]["reactive"]
        predictive = target_report[scenario][winner]
        # strictly lower worst backlog...
        assert predictive["max_depth"] < reactive["max_depth"], scenario
        # ...within the +25% churn budget
        assert predictive["replica_changes"] <= 1.25 * max(
            reactive["replica_changes"], 1
        ), scenario


def test_predictive_never_worsens_time_over_slo_on_targets(target_report):
    summary = summarize(target_report, target_scenarios=TARGETS)
    winner = summary["winner"]
    for scenario in TARGETS:
        assert (
            target_report[scenario][winner]["time_over_slo_s"]
            <= target_report[scenario]["reactive"]["time_over_slo_s"]
        ), scenario


def test_episodes_are_deterministic(target_report):
    battery = {s.name: s for s in default_battery()}
    again = run_episode(battery["ramp"], policy="predictive", forecaster="holt")
    assert again == target_report["ramp"]["predictive:holt"]


@pytest.mark.slow
def test_bench_forecast_suite_emits_artifact(tmp_path):
    out_path = tmp_path / "BENCH_forecast.json"
    run = subprocess.run(
        [sys.executable, "bench.py", "--suite", "forecast",
         "--output", str(out_path)],
        capture_output=True, text=True, timeout=300,
    )
    assert run.returncode == 0, run.stderr[-2000:]
    lines = run.stdout.strip().splitlines()
    assert len(lines) == 1  # the one-JSON-line stdout contract holds
    headline = json.loads(lines[0])
    assert set(headline) == {"metric", "value", "unit", "vs_baseline"}
    assert headline["metric"] == "forecast_target_max_depth"
    assert headline["vs_baseline"] > 1.0  # predictive beats reactive
    artifact = json.loads(out_path.read_text())
    assert artifact["suite"] == "forecast"
    assert set(artifact["report"]) == {"step", "ramp", "diurnal", "burst"}
    assert artifact["summary"]["winner"].startswith("predictive:")
    assert artifact["elapsed_s"] < 60.0
