"""Property-testing front end: hypothesis when installed, a seeded
deterministic fallback otherwise.

The image this suite runs on does not ship ``hypothesis`` and nothing may
be pip-installed, but the property tests are tier-1 — so this module
re-exports the real library when available and otherwise provides a
minimal drop-in subset (``given``/``settings``/``strategies``) backed by a
per-test seeded ``random.Random``.  The fallback is deliberately small:
only the strategy combinators this suite uses, no shrinking — a failing
example is reported verbatim in the assertion chain instead.
"""

from __future__ import annotations

try:  # pragma: no cover - exercised only where hypothesis is installed
    from hypothesis import given, settings, strategies as st  # noqa: F401

    HAVE_HYPOTHESIS = True
except ModuleNotFoundError:
    HAVE_HYPOTHESIS = False

    import random
    import zlib

    _DEFAULT_MAX_EXAMPLES = 100

    class _Strategy:
        """A value generator: ``example(rng)`` draws one value."""

        def __init__(self, draw):
            self._draw = draw

        def example(self, rng):
            return self._draw(rng)

        def map(self, fn):
            return _Strategy(lambda rng: fn(self._draw(rng)))

    class _Strategies:
        """The ``hypothesis.strategies`` subset this suite draws from.

        Numeric strategies bias ~1/4 of draws to the interval endpoints —
        threshold/cooldown boundaries are exactly where the reference
        semantics have their subtleties (inclusive gates, strictly-After
        cooldowns), and uniform sampling almost never lands on them.
        """

        @staticmethod
        def integers(min_value, max_value):
            def draw(rng):
                if rng.random() < 0.25:
                    return rng.choice((min_value, max_value))
                return rng.randint(min_value, max_value)

            return _Strategy(draw)

        @staticmethod
        def floats(min_value, max_value, **_kwargs):
            def draw(rng):
                if rng.random() < 0.25:
                    return float(rng.choice((min_value, max_value)))
                return rng.uniform(min_value, max_value)

            return _Strategy(draw)

        @staticmethod
        def booleans():
            return _Strategy(lambda rng: rng.random() < 0.5)

        @staticmethod
        def sampled_from(elements):
            items = list(elements)
            return _Strategy(lambda rng: rng.choice(items))

        @staticmethod
        def lists(elements, min_size=0, max_size=10):
            def draw(rng):
                size = rng.randint(min_size, max_size)
                return [elements.example(rng) for _ in range(size)]

            return _Strategy(draw)

        @staticmethod
        def builds(target, **kwargs):
            def draw(rng):
                return target(**{k: s.example(rng) for k, s in kwargs.items()})

            return _Strategy(draw)

        @staticmethod
        def one_of(*strategies):
            return _Strategy(lambda rng: rng.choice(strategies).example(rng))

    st = _Strategies()

    def settings(max_examples=_DEFAULT_MAX_EXAMPLES, **_kwargs):
        """Applied *outside* ``given``: stamps the example budget on the
        already-wrapped test; the wrapper reads it at call time."""

        def decorate(fn):
            fn._proptest_max_examples = max_examples
            return fn

        return decorate

    def given(**strategies):
        def decorate(fn):
            # NOT functools.wraps: that sets __wrapped__, making pytest see
            # the original signature and demand fixtures for every
            # strategy-filled parameter.  The wrapper must look zero-arg.
            def wrapper(*args, **kwargs):
                # Seed from the test name: deterministic across runs and
                # processes (unlike hash()), distinct across tests.
                rng = random.Random(zlib.crc32(fn.__qualname__.encode()))
                budget = getattr(
                    wrapper, "_proptest_max_examples", _DEFAULT_MAX_EXAMPLES
                )
                for i in range(budget):
                    example = {k: s.example(rng) for k, s in strategies.items()}
                    try:
                        fn(*args, **example, **kwargs)
                    except Exception as err:
                        raise AssertionError(
                            f"falsifying example #{i}: {example!r}"
                        ) from err

            wrapper.__name__ = fn.__name__
            wrapper.__qualname__ = fn.__qualname__
            wrapper.__doc__ = fn.__doc__
            return wrapper

        return decorate
