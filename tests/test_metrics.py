"""Metric-source tests: the reference's sqs_test.go scenarios plus the
error paths (missing attribute, garbage value, transport failure) that the
reference leaves untested — including the nil-deref fixed per SURVEY §2.2-C3.
"""

import pytest

from kube_sqs_autoscaler_tpu.core.types import MetricError, MetricSource
from kube_sqs_autoscaler_tpu.metrics import (
    DEFAULT_ATTRIBUTE_NAMES,
    FakeQueueService,
    QueueMetricSource,
    parse_attribute_names,
)
from kube_sqs_autoscaler_tpu.metrics.queue import DEFAULT_ATTRIBUTE_NAMES_CSV


def test_constructor_fields():
    # sqs/sqs_test.go:11-17
    source = QueueMetricSource(
        client=FakeQueueService.with_depths(0),
        queue_url="queue",
        attribute_names=DEFAULT_ATTRIBUTE_NAMES,
    )
    assert source.queue_url == "queue"
    assert source.attribute_names == DEFAULT_ATTRIBUTE_NAMES


def test_num_messages_sums_all_three_attributes():
    # sqs/sqs_test.go:19-25 — 10+10+10 == 30
    source = QueueMetricSource(
        client=FakeQueueService.with_depths(10, 10, 10), queue_url="example.com"
    )
    assert source.num_messages() == 30


def test_default_attribute_names_match_reference():
    # sqs/sqs.go:28-33 and main.go:28
    assert DEFAULT_ATTRIBUTE_NAMES == (
        "ApproximateNumberOfMessages",
        "ApproximateNumberOfMessagesDelayed",
        "ApproximateNumberOfMessagesNotVisible",
    )
    assert DEFAULT_ATTRIBUTE_NAMES_CSV == (
        "ApproximateNumberOfMessages,ApproximateNumberOfMessagesDelayed,"
        "ApproximateNumberOfMessagesNotVisible"
    )


def test_subset_of_attributes_only_sums_requested():
    source = QueueMetricSource(
        client=FakeQueueService.with_depths(7, 5, 3),
        queue_url="q",
        attribute_names=("ApproximateNumberOfMessages",),
    )
    assert source.num_messages() == 7


def test_missing_attribute_is_explicit_error_not_crash():
    # The reference nil-derefs at sqs/sqs.go:58; we raise MetricError instead.
    source = QueueMetricSource(
        client=FakeQueueService({"ApproximateNumberOfMessages": "5"}),
        queue_url="q",
        attribute_names=("ApproximateNumberOfMessages", "NoSuchAttribute"),
    )
    with pytest.raises(MetricError, match="'NoSuchAttribute'"):
        source.num_messages()


def test_non_integer_value_is_metric_error_with_reference_context():
    source = QueueMetricSource(
        client=FakeQueueService({"ApproximateNumberOfMessages": "not-a-number"}),
        queue_url="q",
        attribute_names=("ApproximateNumberOfMessages",),
    )
    with pytest.raises(
        MetricError,
        match="Failed to get 'ApproximateNumberOfMessages' number of messages",
    ):
        source.num_messages()


def test_transport_failure_wraps_reference_context():
    fake = FakeQueueService.with_depths(10)
    fake.fail_next_get = ConnectionError("SQS unreachable")
    source = QueueMetricSource(client=fake, queue_url="q")
    with pytest.raises(MetricError, match="Failed to get messages in SQS"):
        source.num_messages()
    # next call succeeds again (error was one-shot)
    assert source.num_messages() == 10


def test_set_queue_attributes_seam_changes_depth_mid_run():
    # main_test.go:46-49 — the mock's write side
    fake = FakeQueueService.with_depths(100, 100, 100)
    source = QueueMetricSource(client=fake, queue_url="q")
    assert source.num_messages() == 300
    fake.set_depths(1, 1, 1)
    assert source.num_messages() == 3


def test_parse_attribute_names_default_fast_path_and_override():
    # main.go:103-110
    assert parse_attribute_names(DEFAULT_ATTRIBUTE_NAMES_CSV) is DEFAULT_ATTRIBUTE_NAMES
    assert parse_attribute_names("A, B ,C") == ("A", "B", "C")
    assert parse_attribute_names("ApproximateNumberOfMessages") == (
        "ApproximateNumberOfMessages",
    )


def test_protocol_conformance():
    assert isinstance(
        QueueMetricSource(client=FakeQueueService.with_depths(0), queue_url="q"),
        MetricSource,
    )
