"""Multi-host helpers, topology meshes, LR schedules, and the prefetching
input pipeline.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kube_sqs_autoscaler_tpu.workloads.data import (
    prefetch_to_mesh,
    synthetic_token_stream,
)
from kube_sqs_autoscaler_tpu.workloads.distributed import (
    initialize_from_env,
    make_hybrid_mesh,
    make_topology_mesh,
)
from kube_sqs_autoscaler_tpu.workloads.model import ModelConfig
from kube_sqs_autoscaler_tpu.workloads.train import (
    TrainConfig,
    batch_sharding,
    init_train_state,
    make_train_step,
    place_state,
)

TINY = ModelConfig(
    vocab_size=256, d_model=64, n_heads=4, n_layers=2, d_ff=128,
    max_seq_len=64, dtype=jnp.float32,
)


def test_initialize_from_env_is_noop_single_process(monkeypatch):
    for var in ("COORDINATOR_ADDRESS", "JAX_COORDINATOR_ADDRESS",
                "JAX_NUM_PROCESSES", "KSAT_DISTRIBUTED"):
        monkeypatch.delenv(var, raising=False)
    assert initialize_from_env() is False


def test_topology_mesh_runs_the_train_step():
    mesh = make_topology_mesh(model_parallel=2, seq_parallel=2)
    assert mesh.shape == {"data": 2, "seq": 2, "model": 2}
    config = TrainConfig(learning_rate=1e-2)
    state = place_state(mesh, init_train_state(jax.random.key(0), TINY, config))
    step_fn = make_train_step(mesh, TINY, config, state)
    tokens = jax.device_put(
        jax.random.randint(jax.random.key(1), (4, 32), 0, TINY.vocab_size,
                           jnp.int32),
        batch_sharding(mesh),
    )
    state, loss = step_fn(state, tokens)
    assert np.isfinite(float(loss))


def test_topology_mesh_validates_divisibility():
    with pytest.raises(ValueError, match="divisible"):
        make_topology_mesh(model_parallel=3)


def test_hybrid_mesh_single_slice_degenerates_to_topology():
    mesh = make_hybrid_mesh(dcn_data_parallel=1, model_parallel=2,
                            seq_parallel=1)
    assert mesh.shape == {"data": 4, "seq": 1, "model": 2}


def test_hybrid_mesh_multi_slice_requires_multiple_processes():
    # all 8 virtual CPU devices live in one process, so asking for a
    # 2-slice DCN axis must fail loudly rather than mis-assign
    with pytest.raises(Exception):
        make_hybrid_mesh(dcn_data_parallel=2, model_parallel=2,
                         seq_parallel=1)


def test_lr_schedule_warmup_cosine_shape():
    config = TrainConfig(learning_rate=1e-3, warmup_steps=10, decay_steps=90)
    sched = config.schedule()
    assert float(sched(0)) == pytest.approx(0.0)
    assert float(sched(10)) == pytest.approx(1e-3, rel=1e-6)
    assert float(sched(100)) == pytest.approx(1e-4, rel=1e-3)
    # monotone decay after warmup
    assert float(sched(50)) < float(sched(10))
    # warmup-only variant ramps then holds
    warm = TrainConfig(learning_rate=1e-3, warmup_steps=5).schedule()
    assert float(warm(5)) == pytest.approx(1e-3, rel=1e-6)
    assert float(warm(50)) == pytest.approx(1e-3, rel=1e-6)
    # constant variant is a plain float
    assert TrainConfig(learning_rate=1e-3).schedule() == 1e-3


def test_scheduled_train_step_learns():
    mesh = make_topology_mesh(model_parallel=2, seq_parallel=1)
    config = TrainConfig(learning_rate=1e-2, warmup_steps=2, decay_steps=20)
    state = place_state(mesh, init_train_state(jax.random.key(0), TINY, config))
    step_fn = make_train_step(mesh, TINY, config, state)
    tokens = jax.device_put(
        jax.random.randint(jax.random.key(1), (4, 32), 0, TINY.vocab_size,
                           jnp.int32),
        batch_sharding(mesh),
    )
    losses = []
    for _ in range(5):
        state, loss = step_fn(state, tokens)
        losses.append(float(loss))
    # step 0 has lr=0 (warmup), so compare later steps
    assert losses[-1] < losses[1]


def test_synthetic_stream_is_deterministic():
    a = synthetic_token_stream(100, 2, 8, seed=7)
    b = synthetic_token_stream(100, 2, 8, seed=7)
    for _ in range(3):
        np.testing.assert_array_equal(next(a), next(b))


@pytest.mark.parametrize("depth", [0, 1, 2])
def test_prefetch_preserves_order_values_and_sharding(depth):
    mesh = make_topology_mesh(model_parallel=2, seq_parallel=1)
    sharding = batch_sharding(mesh)
    source = [np.full((4, 8), i, dtype=np.int32) for i in range(5)]
    out = list(prefetch_to_mesh(iter(source), sharding, depth=depth))
    assert len(out) == 5
    for i, batch in enumerate(out):
        np.testing.assert_array_equal(np.asarray(batch), source[i])
        assert batch.sharding.is_equivalent_to(sharding, batch.ndim)


def test_prefetch_runs_ahead_of_consumption():
    mesh = make_topology_mesh(model_parallel=1, seq_parallel=1)
    sharding = batch_sharding(mesh)
    pulled = []

    def source():
        for i in range(6):
            pulled.append(i)
            yield np.full((8, 8), i, dtype=np.int32)

    it = prefetch_to_mesh(source(), sharding, depth=2)
    first = next(it)
    # after one yield, the pipeline has pulled the yielded batch plus
    # depth+1 staged transfers
    assert len(pulled) >= 3
    np.testing.assert_array_equal(np.asarray(first), 0)
    assert sum(1 for _ in it) == 5  # drains cleanly


def test_prefetch_validates_depth():
    mesh = make_topology_mesh(model_parallel=1, seq_parallel=1)
    with pytest.raises(ValueError, match="depth"):
        list(prefetch_to_mesh(iter([]), batch_sharding(mesh), depth=-1))


def test_prefetch_feeds_the_train_step():
    mesh = make_topology_mesh(model_parallel=2, seq_parallel=2)
    config = TrainConfig(learning_rate=1e-2)
    state = place_state(mesh, init_train_state(jax.random.key(0), TINY, config))
    step_fn = make_train_step(mesh, TINY, config, state)
    stream = synthetic_token_stream(TINY.vocab_size, 4, 32, seed=3)
    batches = prefetch_to_mesh(stream, batch_sharding(mesh), depth=2)
    losses = []
    for _, tokens in zip(range(4), batches):
        state, loss = step_fn(state, tokens)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
