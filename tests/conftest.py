"""Test configuration.

JAX-based workload tests run on a virtual 8-device CPU mesh (no TPU needed):
the env vars must be set before the first ``import jax`` anywhere in the
process, which is why they live here at conftest import time.

The controller-side tests (policy/loop/actuator/metrics/cli) import no JAX
at all — mirroring the layering: the control plane is plain Python.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
