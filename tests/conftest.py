"""Test configuration.

JAX-based workload tests run on a virtual 8-device CPU mesh (no TPU
needed).  Two quirks of this image make the setup more than env vars:

- ``XLA_FLAGS=--xla_force_host_platform_device_count=8`` must be set before
  the CPU backend initializes (done below; the backend only initializes on
  first ``jax.devices()``).
- The image's ``sitecustomize`` registers a TPU-tunnel PJRT plugin in every
  Python process and calls ``jax.config.update("jax_platforms", "axon,cpu")``,
  which *overrides* the ``JAX_PLATFORMS`` env var.  Re-apply the env choice
  via ``jax.config`` so tests run on the virtual CPU mesh even when the
  tunnel is unreachable.

The controller-side tests (policy/loop/actuator/metrics/cli) import no JAX
at all — mirroring the layering: the control plane is plain Python.
"""

import os

# Force CPU: this suite targets the virtual 8-device mesh, and the image's
# global env carries JAX_PLATFORMS=axon (the TPU tunnel), so setdefault is
# not enough.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()


def _pin_jax_platform() -> None:
    # jax may already be imported (sitecustomize); pin config to the env var.
    # Guarded: jax is an optional extra — without it the controller tests
    # must still collect and run.
    import importlib.util

    if importlib.util.find_spec("jax") is None:
        return
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])


_pin_jax_platform()


def _jax_available() -> bool:
    import importlib.util

    return importlib.util.find_spec("jax") is not None


import pytest  # noqa: E402


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """Drop compiled-executable caches after each test module.

    The suite compiles hundreds of XLA CPU programs across ~40 modules
    in one process; letting them all stay resident has produced
    late-run crashes (a SIGSEGV at 91% and a SIGABRT at 65% on
    otherwise-green tests that pass standalone — accumulated backend
    state, not test bugs).  Modules rarely share shapes, so clearing
    between modules costs little recompilation and bounds the resident
    executable count.
    """
    yield
    if _jax_available():
        import jax

        jax.clear_caches()
