"""Test configuration.

JAX-based workload tests run on a virtual 8-device CPU mesh (no TPU
needed).  Two quirks of this image make the setup more than env vars:

- ``XLA_FLAGS=--xla_force_host_platform_device_count=8`` must be set before
  the CPU backend initializes (done below; the backend only initializes on
  first ``jax.devices()``).
- The image's ``sitecustomize`` registers a TPU-tunnel PJRT plugin in every
  Python process and calls ``jax.config.update("jax_platforms", "axon,cpu")``,
  which *overrides* the ``JAX_PLATFORMS`` env var.  Re-apply the env choice
  via ``jax.config`` so tests run on the virtual CPU mesh even when the
  tunnel is unreachable.

The controller-side tests (policy/loop/actuator/metrics/cli) import no JAX
at all — mirroring the layering: the control plane is plain Python.
"""

import os

# Force CPU: this suite targets the virtual 8-device mesh, and the image's
# global env carries JAX_PLATFORMS=axon (the TPU tunnel), so setdefault is
# not enough.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()


def _pin_jax_platform() -> None:
    # jax may already be imported (sitecustomize); pin config to the env var.
    # Guarded: jax is an optional extra — without it the controller tests
    # must still collect and run.
    import importlib.util

    if importlib.util.find_spec("jax") is None:
        return
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])


_pin_jax_platform()


def _jax_available() -> bool:
    import importlib.util

    return importlib.util.find_spec("jax") is not None


import pytest  # noqa: E402

# Model/mesh-heavy workload modules — the `slow` tier.  The default gate
# (`make test` = `pytest -m "not slow"`) runs the controller layer plus
# the light workload smokes in well under 10 minutes; `make test-all`
# runs everything (CI runs both).  The suite passed 48 minutes
# single-process in round 4 and was still growing — without a tier the
# green gate itself becomes flaky-by-timeout on the driver host.
SLOW_MODULES = {
    "test_beam", "test_checkpoint", "test_continuous", "test_decode",
    "test_distributed_data", "test_flash", "test_hf_convert",
    "test_llama", "test_lora", "test_lora_pipeline", "test_moe",
    "test_multihost", "test_pipeline", "test_pipeline_4axis",
    "test_pipeline_llama", "test_prefix_cache", "test_quantize",
    "test_ring", "test_service", "test_sliding_window",
    "test_speculative", "test_train_options", "test_train_serve",
    "test_trainer", "test_workloads", "test_zigzag",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        if item.fspath.purebasename in SLOW_MODULES:
            item.add_marker(pytest.mark.slow)


def drain_batcher(batcher, requests, max_steps=300):
    """Feed ``requests`` (list of token arrays) through a
    ContinuousBatcher keeping slots full, collecting finished outputs by
    submit order — the one drain loop the continuous/prefix/speculative
    batcher tests share.  Returns ``{index: tokens}``."""
    results = {}
    queue = list(enumerate(requests))
    for _ in range(max_steps):
        while queue and batcher.free_slots:
            idx, ids = queue.pop(0)
            batcher.submit(ids, payload=idx)
        for idx, tokens in batcher.step():
            results[idx] = tokens
        if not queue and batcher.active == 0:
            break
    return results


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """Drop compiled-executable caches after each test module.

    The suite compiles hundreds of XLA CPU programs across ~40 modules
    in one process; letting them all stay resident has produced
    late-run crashes (a SIGSEGV at 91% and a SIGABRT at 65% on
    otherwise-green tests that pass standalone — accumulated backend
    state, not test bugs).  Modules rarely share shapes, so clearing
    between modules costs little recompilation and bounds the resident
    executable count.
    """
    yield
    if _jax_available():
        import jax

        jax.clear_caches()
