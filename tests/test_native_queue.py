"""Native local-queue broker: SQS-shaped semantics (visibility timeouts,
delay, redelivery, attribute counts) under a manual clock, thread safety,
and the full controller+worker closed loop running against it.
"""

import threading

import pytest

from kube_sqs_autoscaler_tpu.native import (
    LocalQueue,
    NativeUnavailableError,
    native_available,
)

pytestmark = pytest.mark.skipif(
    not native_available(), reason="g++ unavailable; native queue not built"
)


def make_queue(**kw):
    kw.setdefault("visibility_timeout", 30.0)
    kw.setdefault("manual_clock", True)
    return LocalQueue(**kw)


def depth3(q):
    attrs = q.get_queue_attributes()
    return tuple(
        int(attrs[k])
        for k in (
            "ApproximateNumberOfMessages",
            "ApproximateNumberOfMessagesDelayed",
            "ApproximateNumberOfMessagesNotVisible",
        )
    )


def test_send_receive_delete_roundtrip():
    with make_queue() as q:
        q.send_message(body="hello")
        q.send_message(body="world")
        assert depth3(q) == (2, 0, 0)
        msgs = q.receive_messages(max_messages=2)
        assert [m["Body"] for m in msgs] == ["hello", "world"]
        assert depth3(q) == (0, 0, 2)  # in flight
        for m in msgs:
            q.delete_message(receipt_handle=m["ReceiptHandle"])
        assert depth3(q) == (0, 0, 0)


def test_visibility_timeout_redelivers():
    with make_queue(visibility_timeout=30.0) as q:
        q.send_message(body="task")
        (msg,) = q.receive_messages()
        assert q.receive_messages() == []  # invisible while in flight
        q.advance(29.0)
        assert q.receive_messages() == []
        q.advance(1.0)  # deadline hits exactly at 30s
        (redelivered,) = q.receive_messages()
        assert redelivered["Body"] == "task"
        # the old receipt is dead after redelivery
        q.delete_message(receipt_handle=msg["ReceiptHandle"])
        assert depth3(q) == (0, 0, 1)


def test_delay_parks_message_as_delayed():
    with make_queue() as q:
        q.send_message(body="later", delay_s=10.0)
        assert depth3(q) == (0, 1, 0)
        assert q.receive_messages() == []
        q.advance(10.0)
        assert depth3(q) == (1, 0, 0)
        (msg,) = q.receive_messages()
        assert msg["Body"] == "later"


def test_change_visibility_zero_returns_message():
    with make_queue() as q:
        q.send_message(body="retry me")
        (msg,) = q.receive_messages()
        assert q.change_message_visibility(msg["ReceiptHandle"], 0.0)
        assert depth3(q) == (1, 0, 0)
        assert not q.change_message_visibility("rh-99999", 0.0)


def test_controller_metric_source_reads_native_queue():
    from kube_sqs_autoscaler_tpu.metrics.queue import QueueMetricSource

    with make_queue() as q:
        for i in range(5):
            q.send_message(body=f"m{i}")
        q.send_message(body="delayed", delay_s=60.0)
        q.receive_messages()  # one in flight
        metric = QueueMetricSource(client=q, queue_url="local://q")
        # visible(4) + delayed(1) + not-visible(1), like sqs/sqs.go:28-33
        assert metric.num_messages() == 6


def test_unicode_and_large_bodies_roundtrip():
    with make_queue() as q:
        body = "tpu-über-" + "x" * 100_000
        q.send_message(body=body)
        (msg,) = q.receive_messages()
        assert msg["Body"] == body


def test_concurrent_producers_consumers_lose_nothing():
    q = LocalQueue(visibility_timeout=60.0)  # real clock: exercise blocking
    total = 400
    received = []
    lock = threading.Lock()

    def produce(base):
        for i in range(total // 4):
            q.send_message(body=f"{base + i}")

    def consume():
        while True:
            msgs = q.receive_messages(max_messages=10, wait_time_s=1)
            if not msgs:
                return
            with lock:
                received.extend(int(m["Body"]) for m in msgs)
            for m in msgs:
                q.delete_message(receipt_handle=m["ReceiptHandle"])

    producers = [
        threading.Thread(target=produce, args=(k * (total // 4),))
        for k in range(4)
    ]
    consumers = [threading.Thread(target=consume) for _ in range(4)]
    for t in producers + consumers:
        t.start()
    for t in producers + consumers:
        t.join()
    assert sorted(received) == list(range(total))
    assert depth3(q) == (0, 0, 0)
    q.close()


def test_closed_loop_autoscaler_scales_on_native_backlog():
    # the whole production controller stack watching the native broker:
    # backlog above threshold -> scale up; drained queue -> scale down
    from kube_sqs_autoscaler_tpu.core.clock import FakeClock
    from kube_sqs_autoscaler_tpu.core.loop import ControlLoop, LoopConfig
    from kube_sqs_autoscaler_tpu.core.policy import PolicyConfig
    from kube_sqs_autoscaler_tpu.metrics.queue import QueueMetricSource
    from kube_sqs_autoscaler_tpu.scale.actuator import PodAutoScaler
    from kube_sqs_autoscaler_tpu.scale.fake import FakeDeploymentAPI

    with make_queue() as q:
        for i in range(150):
            q.send_message(body=f"req-{i}")

        clock = FakeClock()
        api = FakeDeploymentAPI.with_deployments("default", 1, "workers")
        loop = ControlLoop(
            PodAutoScaler(client=api, max=5, min=1, scale_up_pods=1,
                          scale_down_pods=1, deployment="workers",
                          namespace="default"),
            QueueMetricSource(client=q, queue_url="local://q"),
            LoopConfig(
                poll_interval=5.0,
                policy=PolicyConfig(scale_up_messages=100,
                                    scale_down_messages=10,
                                    scale_up_cooldown=0.0,
                                    scale_down_cooldown=0.0),
            ),
            clock=clock,
        )
        loop.run(max_ticks=3)
        assert api.replicas("workers") == 4  # 1 -> 2 -> 3 -> 4 on backlog

        # drain the queue, then the loop scales back down
        while True:
            msgs = q.receive_messages(max_messages=10)
            if not msgs:
                break
            for m in msgs:
                q.delete_message(receipt_handle=m["ReceiptHandle"])
        loop.reset()
        loop.run(max_ticks=3)
        assert api.replicas("workers") == 1


def test_close_releases_blocked_long_poller_and_guards_reuse():
    # close() while a receiver long-polls must wake it (not UB on a
    # destroyed mutex), and any use after close is a Python error, not a
    # NULL-pointer segfault
    import time

    q = LocalQueue(visibility_timeout=30.0)  # real clock: actually blocks
    t = threading.Thread(target=lambda: q.receive_messages(wait_time_s=5))
    t.start()
    time.sleep(0.2)
    q.close()
    t.join(timeout=3)
    assert not t.is_alive()
    with pytest.raises(ValueError, match="closed"):
        q.send_message(body="x")
    with pytest.raises(ValueError, match="closed"):
        q.get_queue_attributes()
    q.close()  # idempotent


def test_close_waits_for_inflight_native_calls():
    # the active-call refcount: close() must not free the C++ object while
    # another thread is inside a native entry (it had passed the handle
    # check before close nulled it)
    import time

    q = LocalQueue(visibility_timeout=30.0)
    for _ in range(20):
        q.send_message(body="x")
    stop = threading.Event()
    errors = []

    def hammer():
        try:
            while not stop.is_set():
                try:
                    q.get_queue_attributes()
                    msgs = q.receive_messages(max_messages=2)
                    for m in msgs:
                        q.delete_message(receipt_handle=m["ReceiptHandle"])
                except ValueError:
                    return  # closed — the expected exit
        except Exception as err:  # pragma: no cover - the bug under test
            errors.append(err)

    threads = [threading.Thread(target=hammer) for _ in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.1)
    q.close()
    stop.set()
    for t in threads:
        t.join(timeout=3)
        assert not t.is_alive()
    assert not errors


def test_malformed_receipt_handle_fails_like_unknown():
    with LocalQueue() as q:
        q.send_message(body="x")
        # neither form may raise; both must leave the message in flight
        q.delete_message(receipt_handle="rh-abc")
        q.delete_message(receipt_handle="bogus")
        assert not q.change_message_visibility("rh-12notanint", 0.0)
        (msg,) = q.receive_messages()
        q.delete_message(receipt_handle=msg["ReceiptHandle"])
        assert q.get_queue_attributes()["ApproximateNumberOfMessages"] == "0"


def test_full_story_on_native_broker_with_llama_workers():
    """The whole system against the NATIVE C++ broker, serving the llama
    family: burst -> depth crosses threshold -> autoscaler raises
    replicas -> elastic pool adds workers -> queue drains -> scale-down
    -> pool shrinks.  (The fake-queue twin lives in test_service.py.)"""
    import json
    import time

    import jax
    import numpy as np

    from kube_sqs_autoscaler_tpu.core.loop import ControlLoop, LoopConfig
    from kube_sqs_autoscaler_tpu.core.policy import PolicyConfig
    from kube_sqs_autoscaler_tpu.metrics.queue import QueueMetricSource
    from kube_sqs_autoscaler_tpu.scale.actuator import PodAutoScaler
    from kube_sqs_autoscaler_tpu.scale.fake import FakeDeploymentAPI
    from kube_sqs_autoscaler_tpu.workloads.llama import (
        LlamaConfig,
        init_llama_params,
        llama_forward_jit,
    )
    from kube_sqs_autoscaler_tpu.workloads.service import (
        ElasticWorkerPool,
        QueueWorker,
        ServiceConfig,
    )

    tiny = LlamaConfig(
        vocab_size=512, d_model=128, n_heads=4, n_kv_heads=2, n_layers=2,
        d_ff=256, max_seq_len=64,
    )
    queue = LocalQueue(visibility_timeout=60.0)  # real clock, real blocking
    rng = np.random.default_rng(0)
    for _ in range(120):
        queue.send_message(
            body=json.dumps(rng.integers(0, tiny.vocab_size, 16).tolist())
        )

    api = FakeDeploymentAPI.with_deployments("ns", 1, "workers")
    loop = ControlLoop(
        PodAutoScaler(client=api, max=4, min=1, scale_up_pods=1,
                      scale_down_pods=1, deployment="workers",
                      namespace="ns"),
        QueueMetricSource(client=queue, queue_url="local://jobs"),
        LoopConfig(
            poll_interval=0.05,
            policy=PolicyConfig(scale_up_messages=20, scale_down_messages=0,
                                scale_up_cooldown=0.1,
                                scale_down_cooldown=0.1),
        ),
    )
    loop_thread = threading.Thread(target=loop.run, daemon=True)

    params = init_llama_params(jax.random.key(0), tiny)

    def throttled_forward(p, t):
        time.sleep(0.02)  # keep the drain slower than startup grace
        return llama_forward_jit(p, t, tiny)

    pool = ElasticWorkerPool(
        api, "workers",
        worker_factory=lambda: QueueWorker(
            queue, params, tiny,
            ServiceConfig(queue_url="local://jobs", batch_size=4, seq_len=16,
                          idle_sleep_s=0.01),
            forward_fn=throttled_forward,
        ),
    )
    loop_thread.start()
    max_workers = 0
    deadline = time.time() + 60
    try:
        while time.time() < deadline:
            max_workers = max(max_workers, pool.reconcile())
            if depth3(queue) == (0, 0, 0) and api.replicas("workers") == 1:
                break
            time.sleep(0.02)
        else:
            raise AssertionError(
                f"did not settle: depth={depth3(queue)}, "
                f"replicas={api.replicas('workers')}"
            )
    finally:
        loop.stop()
        pool.stop_all()
        loop_thread.join(timeout=10)

    assert max_workers > 1  # the burst actually scaled the pool out
    assert pool.processed == 120  # every message processed exactly once
    assert depth3(queue) == (0, 0, 0)
    queue.close()


def test_jax_queue_worker_drains_native_queue():
    # the real TPU inference worker consuming from the native broker:
    # receive -> batch -> jitted forward -> delete, queue fully acked
    import json

    import jax
    import numpy as np

    from kube_sqs_autoscaler_tpu.workloads.model import ModelConfig, init_params
    from kube_sqs_autoscaler_tpu.workloads.service import (
        QueueWorker,
        ServiceConfig,
    )

    tiny = ModelConfig(
        vocab_size=512, d_model=128, n_heads=4, n_layers=2, d_ff=256,
        max_seq_len=64,
    )
    with make_queue() as q:
        rng = np.random.default_rng(0)
        for _ in range(5):
            q.send_message(
                body=json.dumps(rng.integers(0, tiny.vocab_size, 16).tolist())
            )
        worker = QueueWorker(
            q, init_params(jax.random.key(0), tiny), tiny,
            ServiceConfig(queue_url="local://q", batch_size=4, seq_len=16),
        )
        assert worker.run_once() == 4
        assert worker.run_once() == 1
        assert worker.run_once() == 0
        assert worker.processed == 5
        assert depth3(q) == (0, 0, 0)
