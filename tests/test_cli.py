"""CLI parity tests: all 14 reference flags, names and defaults verbatim
(main.go:83-97), Go duration syntax, and the attribute-override path the
reference wires in main() (main.go:103-110).
"""

import pytest

from kube_sqs_autoscaler_tpu.cli import build_parser, config_from_args
from kube_sqs_autoscaler_tpu.metrics.queue import (
    DEFAULT_ATTRIBUTE_NAMES,
    DEFAULT_ATTRIBUTE_NAMES_CSV,
)
from kube_sqs_autoscaler_tpu.metrics import parse_attribute_names


def test_all_fourteen_flags_exist_with_reference_defaults():
    args = build_parser().parse_args([])
    assert args.poll_period == 5.0
    assert args.scale_down_cool_down == 30.0
    assert args.scale_up_cool_down == 10.0
    assert args.scale_up_messages == 100
    assert args.scale_down_messages == 10
    assert args.scale_up_pods == 1
    assert args.scale_down_pods == 1
    assert args.max_pods == 5
    assert args.min_pods == 1
    assert args.aws_region == ""
    assert args.attribute_names == DEFAULT_ATTRIBUTE_NAMES_CSV
    assert args.sqs_queue_url == ""
    assert args.kubernetes_deployment == ""
    assert args.kubernetes_namespace == "default"


def test_flag_equals_value_style_from_reference_manifest():
    # README.md:39-53 passes --flag=value args; durations use Go syntax
    args = build_parser().parse_args(
        [
            "--sqs-queue-url=https://sqs.us-east-1.amazonaws.com/123/q",
            "--kubernetes-deployment=workers",
            "--kubernetes-namespace=prod",
            "--aws-region=us-east-1",
            "--poll-period=5s",
            "--scale-down-cool-down=30s",
            "--scale-up-cool-down=5m",
            "--scale-up-messages=100",
            "--scale-down-messages=10",
            "--scale-up-pods=1",
            "--scale-down-pods=1",
            "--max-pods=5",
            "--min-pods=1",
            "--attribute-names=ApproximateNumberOfMessages",
        ]
    )
    assert args.scale_up_cool_down == 300.0
    assert args.kubernetes_deployment == "workers"
    assert parse_attribute_names(args.attribute_names) == (
        "ApproximateNumberOfMessages",
    )


def test_invalid_duration_is_a_usage_error():
    with pytest.raises(SystemExit):
        build_parser().parse_args(["--poll-period=10"])  # Go rejects unitless too


def test_required_by_doc_flags_are_not_validated():
    # Reference quirk preserved (SURVEY §2.2-C1): empty required flags parse
    # fine and only fail at RPC time.
    args = build_parser().parse_args([])
    assert args.kubernetes_deployment == ""
    assert args.sqs_queue_url == ""


def test_config_from_args_maps_to_loop_and_policy():
    args = build_parser().parse_args(
        ["--poll-period=1s", "--scale-up-cool-down=2s", "--scale-down-cool-down=3s",
         "--scale-up-messages=7", "--scale-down-messages=2"]
    )
    config = config_from_args(args)
    assert config.poll_interval == 1.0
    assert config.policy.scale_up_cooldown == 2.0
    assert config.policy.scale_down_cooldown == 3.0
    assert config.policy.scale_up_messages == 7
    assert config.policy.scale_down_messages == 2


def test_default_attribute_names_round_trip():
    args = build_parser().parse_args([])
    assert parse_attribute_names(args.attribute_names) is DEFAULT_ATTRIBUTE_NAMES


def test_forecast_flags_default_to_reference_reactive_behavior():
    args = build_parser().parse_args([])
    assert args.policy == "reactive"
    assert args.forecaster == "holt"
    assert args.forecast_horizon == 60.0
    assert args.forecast_history == 128


def test_predictive_policy_flags_parse_with_go_durations():
    args = build_parser().parse_args(
        ["--policy=predictive", "--forecaster=lstsq",
         "--forecast-horizon=2m", "--forecast-history=64"]
    )
    assert args.policy == "predictive"
    assert args.forecaster == "lstsq"
    assert args.forecast_horizon == 120.0
    assert args.forecast_history == 64


def test_unknown_policy_or_forecaster_is_a_usage_error():
    with pytest.raises(SystemExit):
        build_parser().parse_args(["--policy=quantum"])
    with pytest.raises(SystemExit):
        build_parser().parse_args(["--forecaster=arima"])


def test_too_small_forecast_history_is_a_usage_error():
    # not a raw DepthHistory ValueError traceback later in main()
    with pytest.raises(SystemExit):
        build_parser().parse_args(["--forecast-history=1"])
