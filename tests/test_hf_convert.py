"""HF Llama checkpoint import: converted weights must reproduce the
``transformers`` forward — the proof that the layout transposes, the
k/v / gate/up fusions, and the RoPE half-split -> interleaved channel
permutation are all exactly right (hf_convert module docstring).

Runs fully offline: tiny randomly-initialized ``LlamaForCausalLM``
instances (config-only construction, no downloads), fp32 everywhere so
the comparison tolerance is float-reassociation, not quantization.
"""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax  # noqa: E402

from kube_sqs_autoscaler_tpu.workloads.hf_convert import (  # noqa: E402
    llama_config_from_hf,
    llama_params_from_hf,
    load_hf_llama,
)
from kube_sqs_autoscaler_tpu.workloads.llama import (  # noqa: E402
    llama_forward,
    llama_generate,
)


def make_hf_llama(tie: bool, rms_eps: float = 1e-6, seed: int = 0):
    from transformers import LlamaConfig, LlamaForCausalLM

    torch.manual_seed(seed)
    config = LlamaConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=96,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,  # GQA
        max_position_embeddings=64,
        rope_theta=10000.0,
        rms_norm_eps=rms_eps,
        tie_word_embeddings=tie,
        attn_implementation="eager",
    )
    model = LlamaForCausalLM(config)
    model.eval()
    return model


def hf_logits(model, tokens_np):
    with torch.no_grad():
        out = model(torch.from_numpy(tokens_np).long())
    return out.logits.float().numpy()


@pytest.mark.parametrize("tie", [True, False])
def test_converted_logits_match_transformers(tie):
    model = make_hf_llama(tie=tie, rms_eps=1e-5 if not tie else 1e-6)
    config, params = load_hf_llama(model, dtype=jnp.float32)
    assert config.rms_eps == model.config.rms_norm_eps
    assert ("lm_head" in params) == (not tie)

    tokens = np.random.default_rng(1).integers(
        0, config.vocab_size, (2, 12)
    ).astype(np.int32)
    ours = np.asarray(llama_forward(params, jnp.asarray(tokens), config))
    theirs = hf_logits(model, tokens)
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


def test_converted_greedy_generation_matches_transformers():
    model = make_hf_llama(tie=True, seed=3)
    config, params = load_hf_llama(model, dtype=jnp.float32)
    prompt = np.random.default_rng(2).integers(
        0, config.vocab_size, (2, 8)
    ).astype(np.int32)

    ours = np.asarray(llama_generate(params, jnp.asarray(prompt), 8, config))
    with torch.no_grad():
        theirs = model.generate(
            torch.from_numpy(prompt).long(), max_new_tokens=8,
            do_sample=False, num_beams=1, pad_token_id=0,
        )[:, prompt.shape[1]:].numpy()
    np.testing.assert_array_equal(ours, theirs)


def test_state_dict_conversion_accepts_numpy():
    model = make_hf_llama(tie=True, seed=5)
    config = llama_config_from_hf(model.config, dtype=jnp.float32)
    state = {
        k: v.detach().float().numpy() for k, v in model.state_dict().items()
        if k != "lm_head.weight"
    }
    params = llama_params_from_hf(state, config, dtype=jnp.float32)
    tokens = np.zeros((1, 4), np.int32)
    ours = np.asarray(llama_forward(params, jnp.asarray(tokens), config))
    np.testing.assert_allclose(
        ours, hf_logits(model, tokens), rtol=2e-4, atol=2e-4
    )


def test_serve_binary_runs_an_hf_checkpoint(tmp_path):
    """--hf-checkpoint end to end: save_pretrained directory -> serve
    binary demo mode generates from the imported weights."""
    from kube_sqs_autoscaler_tpu.workloads.__main__ import main

    model = make_hf_llama(tie=True, seed=11)
    ckpt = tmp_path / "hf_llama"
    model.save_pretrained(ckpt)
    main([
        "--hf-checkpoint", str(ckpt), "--demo", "2", "--batch-size", "1",
        "--seq-len", "8", "--generate-tokens", "4", "--temperature", "0.8",
        "--top-k", "8",
    ])


def test_export_round_trips_the_imported_state_dict():
    """hf_state_dict_from_llama is the exact inverse of the import:
    every tensor of the original HF model comes back bit-for-bit."""
    from kube_sqs_autoscaler_tpu.workloads.hf_convert import (
        hf_state_dict_from_llama,
    )

    model = make_hf_llama(tie=False, seed=13)
    config, params = load_hf_llama(model, dtype=jnp.float32)
    back = hf_state_dict_from_llama(params, config)
    for key, value in model.state_dict().items():
        np.testing.assert_allclose(
            value.float().numpy(), back[key], atol=1e-6, err_msg=key
        )


def test_exported_model_matches_our_forward(tmp_path):
    """Export our randomly-initialized llama, reload it via transformers
    from_pretrained, and compare logits — the ecosystem round trip."""
    from transformers import LlamaForCausalLM

    from kube_sqs_autoscaler_tpu.workloads.hf_convert import save_hf_llama
    from kube_sqs_autoscaler_tpu.workloads.llama import (
        LlamaConfig as OurConfig,
        init_llama_params,
    )

    config = OurConfig(vocab_size=128, d_model=64, n_heads=4, n_kv_heads=2,
                       n_layers=2, d_ff=96, max_seq_len=64,
                       dtype=jnp.float32)
    params = init_llama_params(jax.random.key(5), config)
    out_dir = tmp_path / "exported"
    save_hf_llama(params, config, out_dir)
    reloaded = LlamaForCausalLM.from_pretrained(out_dir)
    reloaded.eval()
    tokens = np.random.default_rng(3).integers(
        0, config.vocab_size, (2, 12)
    ).astype(np.int32)
    ours = np.asarray(llama_forward(params, jnp.asarray(tokens), config))
    with torch.no_grad():
        theirs = reloaded(
            torch.from_numpy(tokens).long()
        ).logits.float().numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


def test_trainer_hf_export_flag(tmp_path):
    """--hf-export through the real binary: train a tiny llama, export,
    and transformers loads the directory."""
    import os
    import subprocess
    import sys
    from pathlib import Path

    out = tmp_path / "hf_out"
    repo_root = Path(__file__).resolve().parent.parent
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    base_args = [
        sys.executable, "-m", "kube_sqs_autoscaler_tpu.workloads.trainer",
        "--family", "llama", "--steps", "2", "--batch-size", "8",
        "--seq-len", "16", "--d-model", "64", "--n-heads", "4",
        "--n-kv-heads", "2", "--n-layers", "2", "--vocab-size", "128",
        "--log-every", "1",
    ]
    run = subprocess.run(
        base_args + ["--hf-export", str(out)],
        capture_output=True, text=True, env=env, cwd=repo_root,
    )
    assert run.returncode == 0, run.stderr[-3000:]
    assert "Exported transformers checkpoint" in run.stderr
    from transformers import LlamaForCausalLM

    model = LlamaForCausalLM.from_pretrained(out)
    assert model.config.num_hidden_layers == 2

    # pipeline-trained weights export too (the stage stack unstacks to
    # the flat layout the converter writes)
    pp_out = tmp_path / "hf_out_pp"
    run = subprocess.run(
        base_args + ["--pipe-parallel", "2", "--pipe-microbatches", "2",
                     "--hf-export", str(pp_out)],
        capture_output=True, text=True,
        env=dict(env, XLA_FLAGS="--xla_force_host_platform_device_count=8"),
        cwd=repo_root,
    )
    assert run.returncode == 0, run.stderr[-3000:]
    model = LlamaForCausalLM.from_pretrained(pp_out)
    assert model.config.num_hidden_layers == 2


def test_converted_params_shard_on_the_mesh():
    """The imported pytree (incl. the untied lm_head) places onto a
    (data, model) mesh under the PARAM_AXES rules and serves sharded."""
    from kube_sqs_autoscaler_tpu.workloads.train import (
        make_mesh,
        param_shardings,
    )

    model = make_hf_llama(tie=False, seed=7)
    config, params = load_hf_llama(model, dtype=jnp.float32)
    mesh = make_mesh(jax.devices()[:4], model_parallel=2, seq_parallel=1)
    shardings = param_shardings(mesh, params)
    placed = jax.tree.map(jax.device_put, params, shardings)
    tokens = np.random.default_rng(4).integers(
        0, config.vocab_size, (4, 8)
    ).astype(np.int32)
    ours = np.asarray(llama_forward(placed, jnp.asarray(tokens), config))
    np.testing.assert_allclose(
        ours, hf_logits(model, tokens), rtol=2e-4, atol=2e-4
    )
