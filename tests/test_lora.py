"""LoRA adapters: zero-init equivalence, adapter-only training, merge
semantics, mesh execution, and the HF-import composition."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kube_sqs_autoscaler_tpu.workloads.lora import (
    LoraConfig,
    apply_lora,
    init_lora_params,
    init_lora_train_state,
    lora_param_count,
    make_lora_train_step,
    merge_lora,
)
from kube_sqs_autoscaler_tpu.workloads.model import (
    ModelConfig,
    init_params,
    param_count,
)
from kube_sqs_autoscaler_tpu.workloads.train import (
    TrainConfig,
    batch_sharding,
    loss_fn,
    make_mesh,
    param_shardings,
)

TINY = ModelConfig(
    vocab_size=256, d_model=64, n_heads=4, n_layers=2, d_ff=128,
    max_seq_len=64,
)


@pytest.fixture(scope="module")
def base_params():
    return init_params(jax.random.key(0), TINY)


def tokens_batch(batch=8, seq=32, seed=1):
    return jax.random.randint(
        jax.random.key(seed), (batch, seq), 0, TINY.vocab_size, jnp.int32
    )


def test_zero_init_is_identity(base_params):
    lora = LoraConfig(rank=4)
    adapters = init_lora_params(jax.random.key(1), base_params, lora)
    adapted = apply_lora(base_params, adapters, lora)
    for a, b in zip(jax.tree.leaves(base_params), jax.tree.leaves(adapted)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_adapter_size_is_a_fraction_of_the_base(base_params):
    lora = LoraConfig(rank=4)
    adapters = init_lora_params(jax.random.key(1), base_params, lora)
    assert lora_param_count(adapters) < 0.25 * param_count(base_params)
    # every 2-D layer weight of the gpt family is covered
    assert set(adapters["layers"][0]) == {"wqkv", "wo", "w_up", "w_down"}


def test_lora_training_moves_loss_and_only_adapters(base_params):
    mesh = make_mesh(jax.devices(), model_parallel=2, seq_parallel=1)
    lora = LoraConfig(rank=4)
    tc = TrainConfig(learning_rate=3e-2)
    frozen = jax.device_put(base_params,
                            param_shardings(mesh, base_params))
    state = init_lora_train_state(jax.random.key(2), base_params, lora, tc)
    step = make_lora_train_step(mesh, TINY, tc, frozen, state, lora)
    tokens = jax.device_put(tokens_batch(), batch_sharding(mesh))

    base_loss = float(loss_fn(base_params, tokens, TINY))
    losses = []
    for _ in range(5):
        state, loss = step(state, tokens)
        losses.append(float(loss))
    # step 0's loss is the frozen model's loss (B = 0 start)
    assert losses[0] == pytest.approx(base_loss, abs=1e-5)
    assert losses[-1] < losses[0]
    # the base stayed frozen; the adapters moved
    for a, b in zip(jax.tree.leaves(base_params), jax.tree.leaves(frozen)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    b_leaf = state["adapters"]["layers"][0]["wqkv"]["b"]
    assert float(jnp.abs(b_leaf).max()) > 0


def test_merge_equals_adapted_forward(base_params):
    lora = LoraConfig(rank=4)
    adapters = init_lora_params(jax.random.key(3), base_params, lora)
    # make the delta nonzero
    adapters = jax.tree.map(
        lambda x: x + 0.01 if x.ndim == 2 else x, adapters
    )
    tokens = tokens_batch(batch=2, seq=16)
    adapted = float(loss_fn(apply_lora(base_params, adapters, lora),
                            tokens, TINY))
    merged = float(loss_fn(merge_lora(base_params, adapters, lora),
                           tokens, TINY))
    assert adapted == pytest.approx(merged, rel=1e-6)
    assert adapted != pytest.approx(
        float(loss_fn(base_params, tokens, TINY)), abs=1e-4
    )


def test_lora_on_hf_imported_llama():
    """The headline composition: import an HF Llama, LoRA-adapt it, and
    the llama objective trains adapter-only on the mesh."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    from transformers import LlamaConfig as HFConfig, LlamaForCausalLM

    from kube_sqs_autoscaler_tpu.workloads.hf_convert import load_hf_llama
    from kube_sqs_autoscaler_tpu.workloads.llama import llama_loss_fn

    torch.manual_seed(0)
    hf = LlamaForCausalLM(HFConfig(
        vocab_size=128, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, tie_word_embeddings=True,
        attn_implementation="eager",
    ))
    config, params = load_hf_llama(hf, dtype=jnp.float32)
    assert set(
        init_lora_params(jax.random.key(0), params, LoraConfig())
        ["layers"][0]
    ) == {"wq", "wkv", "wo", "w_gate_up", "w_down"}

    mesh = make_mesh(jax.devices()[:2], model_parallel=1, seq_parallel=1)
    lora = LoraConfig(rank=2)
    tc = TrainConfig(learning_rate=3e-2)
    frozen = jax.device_put(params, param_shardings(mesh, params))
    state = init_lora_train_state(jax.random.key(4), params, lora, tc)

    def loss(p, tokens, attention_fn=None):
        return llama_loss_fn(p, tokens, config, attention_fn=None)

    step = make_lora_train_step(mesh, config, tc, frozen, state, lora,
                                loss=loss)
    tokens = jax.device_put(
        jax.random.randint(jax.random.key(5), (4, 16), 0, 128, jnp.int32),
        batch_sharding(mesh),
    )
    losses = []
    for _ in range(5):
        state, loss_v = step(state, tokens)
        losses.append(float(loss_v))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_rank_validation():
    with pytest.raises(ValueError, match="rank"):
        LoraConfig(rank=0)


def test_trainer_binary_lora_on_hf_base_serves_merged(tmp_path):
    """The whole fine-tuning story through the real binaries: HF llama
    directory -> trainer --hf-checkpoint --lora-rank (merged-weights
    checkpoint + manifest) -> serve binary generates from it."""
    import os
    import subprocess
    import sys

    torch = pytest.importorskip("torch")
    pytest.importorskip("transformers")
    from transformers import LlamaConfig as HFConfig, LlamaForCausalLM

    torch.manual_seed(0)
    hf = LlamaForCausalLM(HFConfig(
        vocab_size=128, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, tie_word_embeddings=True,
        attn_implementation="eager",
    ))
    from pathlib import Path

    repo_root = Path(__file__).resolve().parent.parent
    hf_dir, ckpt = tmp_path / "hf", tmp_path / "trained"
    hf.save_pretrained(hf_dir)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    run = subprocess.run(
        [sys.executable, "-m", "kube_sqs_autoscaler_tpu.workloads.trainer",
         "--hf-checkpoint", str(hf_dir), "--lora-rank", "4",
         "--steps", "3", "--batch-size", "8", "--seq-len", "16",
         "--checkpoint-dir", str(ckpt), "--checkpoint-every", "0",
         "--log-every", "2"],
        capture_output=True, text=True, env=env, cwd=repo_root,
    )
    assert run.returncode == 0, run.stderr[-3000:]
    assert "LoRA: rank 4" in run.stderr
    serve = subprocess.run(
        [sys.executable, "-m", "kube_sqs_autoscaler_tpu.workloads",
         "--checkpoint-dir", str(ckpt), "--family", "llama", "--demo", "2",
         "--batch-size", "1", "--seq-len", "8", "--generate-tokens", "4"],
        capture_output=True, text=True, env=env, cwd=repo_root,
    )
    assert serve.returncode == 0, serve.stderr[-3000:]
    assert "Processed 2 messages" in serve.stderr


TRAINER_LORA_FLAGS = [
    "--vocab-size", "256", "--d-model", "64", "--n-heads", "4",
    "--n-layers", "2", "--d-ff", "128", "--seq-len", "32",
    "--batch-size", "8", "--learning-rate", "1e-2", "--log-every", "1",
    "--lora-rank", "4",
]


def test_lora_trainer_resume_equals_uninterrupted(tmp_path):
    # the invariant test_checkpoint pins for full training, for LoRA:
    # interrupt/resume must replay exactly (adapter state + step come
    # back from the checkpoint; the frozen base is rebuilt from the
    # same seed)
    from kube_sqs_autoscaler_tpu.workloads.trainer import main

    full_dir = str(tmp_path / "full")
    split_dir = str(tmp_path / "split")
    full = main(TRAINER_LORA_FLAGS + ["--steps", "6",
                                      "--checkpoint-dir", full_dir])
    main(TRAINER_LORA_FLAGS + ["--steps", "4", "--checkpoint-dir",
                               split_dir, "--checkpoint-every", "2"])
    resumed = main(TRAINER_LORA_FLAGS + ["--steps", "2",
                                         "--checkpoint-dir", split_dir,
                                         "--resume"])
    assert resumed["final_step"] == 6
    np.testing.assert_allclose(
        resumed["losses"], full["losses"][4:], rtol=1e-6
    )
    # and the final MERGED weights on disk are identical
    from kube_sqs_autoscaler_tpu.workloads.checkpoint import (
        TrainCheckpointer,
        load_model_layout,
        load_model_manifest,
    )
    from kube_sqs_autoscaler_tpu.workloads.train import make_mesh

    mesh = make_mesh(jax.devices()[:1], model_parallel=1)
    family, config = load_model_manifest(full_dir)
    assert load_model_layout(full_dir) == {
        "kind": "lora", "rank": 4, "seed": 0, "base": "",
    }
    # a different seed would rebuild a DIFFERENT frozen base — the
    # layout record makes that resume fail loudly instead of silently
    # fine-tuning against the wrong base
    with pytest.raises(SystemExit, match="layout"):
        main(TRAINER_LORA_FLAGS + ["--steps", "1", "--checkpoint-dir",
                                   split_dir, "--resume", "--seed", "1"])
    a = TrainCheckpointer(full_dir).restore_params(
        mesh, family, config, layout=load_model_layout(full_dir))
    b = TrainCheckpointer(split_dir).restore_params(
        mesh, family, config, layout=load_model_layout(split_dir))
    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y)
        ),
        a, b,
    )


def test_lora_grad_accum_matches_single_pass():
    # accumulated adapter GRADIENTS == whole-batch gradients (comparing
    # post-Adam states would be sign-unstable: Adam normalizes near-zero
    # grads to ±lr, so fp reassociation noise flips update signs)
    from functools import partial

    from kube_sqs_autoscaler_tpu.workloads.train import (
        accumulate_value_and_grad,
        loss_fn,
    )

    # fp32 base so the comparison is numerical, not bf16 reassociation
    fp32 = ModelConfig(
        vocab_size=256, d_model=64, n_heads=4, n_layers=2, d_ff=128,
        max_seq_len=64, dtype=jnp.float32,
    )
    base = init_params(jax.random.key(0), fp32)
    lora = LoraConfig(rank=4)
    adapters = init_lora_params(jax.random.key(2), base, lora)
    batch = tokens_batch(batch=8)
    loss = partial(loss_fn, config=fp32)

    def adapter_loss(ad, tokens):
        return loss(apply_lora(base, ad, lora), tokens)

    vag = jax.jit(jax.value_and_grad(adapter_loss))
    loss1, grads1 = vag(adapters, batch)
    loss2, grads2 = jax.jit(
        accumulate_value_and_grad(vag, 2)
    )(adapters, batch)
    assert float(loss2) == pytest.approx(float(loss1), rel=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=1e-3, atol=1e-6,
        ),
        grads1, grads2,
    )


def test_lora_trainer_grad_accum_learns():
    # the flag composition end to end: --lora-rank + --grad-accum
    from kube_sqs_autoscaler_tpu.workloads.trainer import main

    result = main(TRAINER_LORA_FLAGS + ["--steps", "4", "--grad-accum", "2",
                                        "--overfit"])
    assert result["final_step"] == 4
    losses = result["losses"]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_lora_moe_adapters_cover_expert_stacks():
    # lora x moe: 3-D expert stacks get per-expert factors; the router
    # stays frozen (no adapter); zero-init is still the identity
    from kube_sqs_autoscaler_tpu.workloads.moe import (
        MoeConfig,
        init_moe_params,
    )

    moe = MoeConfig(n_experts=4, top_k=2)
    params = init_moe_params(jax.random.key(0), TINY, moe)
    lora = LoraConfig(rank=4)
    adapters = init_lora_params(jax.random.key(1), params, lora)
    layer0 = adapters["layers"][0]
    assert "router" not in layer0
    assert layer0["w_up_experts"]["a"].shape == (4, TINY.d_model, 4)
    assert layer0["w_up_experts"]["b"].shape == (4, 4, TINY.d_ff)
    assert layer0["w_down_experts"]["a"].shape == (4, TINY.d_ff, 4)
    adapted = apply_lora(params, adapters, lora)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(adapted)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_lora_moe_trainer_learns_and_evals(caplog):
    # --lora-rank + --moe end to end: adapter-only fine-tuning of a
    # frozen routed base (both families), with held-out eval
    import logging

    from kube_sqs_autoscaler_tpu.workloads.trainer import main

    # mp2 -> data axis 4, so the 4 experts divide it (the ep=dp layout)
    base = TRAINER_LORA_FLAGS + [
        "--steps", "4", "--moe", "--moe-experts", "4", "--overfit",
        "--model-parallel", "2",
    ]
    with caplog.at_level(logging.INFO):
        result = main(base + ["--eval-every", "4", "--eval-batches", "2"])
    assert result["final_step"] == 4
    losses = result["losses"]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]
    assert any("eval_loss" in r.getMessage() for r in caplog.records)

    result = main(base + ["--family", "llama", "--n-kv-heads", "2"])
    assert result["final_step"] == 4
    assert all(np.isfinite(result["losses"]))
    assert result["losses"][-1] < result["losses"][0]

    with pytest.raises(SystemExit, match="zigzag"):
        main(base + ["--seq-parallel", "2", "--zigzag"])
    # round-5 lift: lora x moe x pipeline composes (per-expert 4-D
    # stage-stacked factors; pinned schedule-equal in
    # test_lora_pipeline) — drop --model-parallel: the lora pipe mesh
    # takes pipe x data here
    result = main(TRAINER_LORA_FLAGS + [
        "--steps", "4", "--moe", "--moe-experts", "4", "--overfit",
        "--pipe-parallel", "2", "--pipe-microbatches", "2",
    ])
    assert result["final_step"] == 4
    assert all(np.isfinite(result["losses"]))


def test_lora_moe_resume_equals_uninterrupted(tmp_path):
    # the LoRA lifecycle invariant for the routed base: interrupt and
    # resume replays exactly (per-expert adapter factors + step from the
    # checkpoint; the frozen routed base rebuilt from the same seed),
    # and a different rank fails loudly via the layout record
    from kube_sqs_autoscaler_tpu.workloads.trainer import main

    base = TRAINER_LORA_FLAGS + [
        "--moe", "--moe-experts", "4", "--model-parallel", "2", "--overfit",
    ]
    full_dir = str(tmp_path / "full")
    split_dir = str(tmp_path / "split")
    full = main(base + ["--steps", "4", "--checkpoint-dir", full_dir])
    main(base + ["--steps", "2", "--checkpoint-dir", split_dir,
                 "--checkpoint-every", "2"])
    resumed = main(base + ["--steps", "2", "--checkpoint-dir", split_dir,
                           "--resume"])
    assert resumed["final_step"] == 4
    np.testing.assert_allclose(resumed["losses"], full["losses"][2:],
                               rtol=1e-6)
    # a different rank would resume DIFFERENT adapter shapes against the
    # recorded layout — rejected before any restore
    bumped = list(base)
    bumped[bumped.index("--lora-rank") + 1] = "8"
    with pytest.raises(SystemExit, match="layout"):
        main(bumped + ["--steps", "1", "--checkpoint-dir", split_dir,
                       "--resume"])


def test_lora_zigzag_trains_and_evals(caplog):
    # adapters wrap flat params, so the permuted-order zig-zag objective
    # composes: --lora-rank + --zigzag learns and evaluates
    import logging

    from kube_sqs_autoscaler_tpu.workloads.trainer import main

    with caplog.at_level(logging.INFO):
        result = main(TRAINER_LORA_FLAGS + [
            "--steps", "4", "--seq-parallel", "2", "--zigzag", "--overfit",
            "--eval-every", "4", "--eval-batches", "2",
        ])
    assert result["final_step"] == 4
    losses = result["losses"]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]
    assert any("eval_loss" in r.getMessage() for r in caplog.records)


def test_lora_windowed_llama_trains_under_sp():
    # a Mistral-style base fine-tunes WINDOWED (the lora step threads
    # config.sliding_window through the attention seam), including on a
    # seq mesh via the windowed ring schedule
    from kube_sqs_autoscaler_tpu.workloads.trainer import main

    result = main([
        "--vocab-size", "256", "--d-model", "64", "--n-heads", "4",
        "--n-layers", "2", "--d-ff", "128", "--seq-len", "32",
        "--batch-size", "8", "--learning-rate", "1e-2", "--log-every", "1",
        "--steps", "4", "--family", "llama", "--n-kv-heads", "2",
        "--sliding-window", "8", "--lora-rank", "4",
        "--seq-parallel", "2", "--overfit",
    ])
    assert result["final_step"] == 4
    assert all(np.isfinite(result["losses"]))
    assert result["losses"][-1] < result["losses"][0]


def test_dense_resume_of_lora_dir_fails_loudly(tmp_path):
    from kube_sqs_autoscaler_tpu.workloads.trainer import main

    ckpt = str(tmp_path / "ckpt")
    main(TRAINER_LORA_FLAGS + ["--steps", "2", "--checkpoint-dir", ckpt])
    assert TRAINER_LORA_FLAGS[-2:] == ["--lora-rank", "4"]
    dense_flags = TRAINER_LORA_FLAGS[:-2]
    with pytest.raises(SystemExit, match="layout"):
        main(dense_flags + ["--steps", "1", "--checkpoint-dir", ckpt,
                            "--resume"])


def test_trainer_rejects_lora_with_incompatible_flags():
    # flat and pipelined moe compose now; only the moe x zigzag lora
    # combo stays out of scope and fails fast
    from kube_sqs_autoscaler_tpu.workloads.trainer import build_parser, train

    args = build_parser().parse_args(
        ["--lora-rank", "4", "--moe", "--seq-parallel", "2", "--zigzag",
         "--steps", "1"]
    )
    with pytest.raises(SystemExit, match="zigzag"):
        train(args)
