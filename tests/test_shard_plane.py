"""Sharded serving plane: gang-step parity, cross-shard admission,
host-sync accounting, and the mask-flip scale path.

Tier-1 (CPU JAX, tiny model).  The fast scale-suite smoke pins the
whole bench contract — parity vs independent engines plus the
one-dispatch-per-cycle gate — at shards (1, 2); the full decode-bound
curve (the committed ``BENCH_r12.json``, monotone gate) runs in the
slow tier.  The host-transfer/dispatch counter tests also retro-pin
PR 5's zero-per-request-sync claim on the single-plane engine.
"""

import json

import pytest

jax = pytest.importorskip("jax")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from kube_sqs_autoscaler_tpu.workloads.continuous import (  # noqa: E402
    ContinuousBatcher,
)
from kube_sqs_autoscaler_tpu.workloads.model import (  # noqa: E402
    ModelConfig,
    init_params,
)
from kube_sqs_autoscaler_tpu.workloads.shard_plane import (  # noqa: E402
    ShardedBatcher,
)


@pytest.fixture(scope="module")
def tiny():
    config = ModelConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
        max_seq_len=32, dtype=jnp.float32,
    )
    params = init_params(jax.random.key(0), config)
    return params, config


def make_plane(tiny, *, shards=2, shard_slots=2, generate_tokens=6,
               decode_block=2, **kwargs):
    params, config = tiny
    return ShardedBatcher(
        params, config, shards=shards, shard_slots=shard_slots,
        prompt_len=8, generate_tokens=generate_tokens,
        decode_block=decode_block, **kwargs,
    )


def prompts_for(n, seed=3):
    rng = np.random.default_rng(seed)
    return [
        rng.integers(1, 64, rng.integers(2, 9)).astype(np.int32)
        for _ in range(n)
    ]


def drain(batcher, max_steps=200):
    out = {}
    for _ in range(max_steps):
        for payload, tokens in batcher.step():
            out[payload] = tokens
        if batcher.active == 0:
            # drained (a dispatch-ahead block may stay pending forever:
            # step() early-returns on idle and only a busy cycle swaps
            # it out — its frozen rows emit nothing either way)
            break
    return out


# ---------------------------------------------------------------------------
# The scale-suite smoke: bench gates (parity + dispatch counters) tier-1
# ---------------------------------------------------------------------------


def test_scale_suite_smoke_parity_and_dispatch(tmp_path):
    from bench import run_scale_suite

    out = tmp_path / "bench_scale.json"
    headline = run_scale_suite(
        str(out), messages=6, prompt_len=8, generate_tokens=8,
        batch_size=2, shard_counts=(1, 2), decode_blocks=(2,),
        require_monotone=False,
    )
    artifact = json.loads(out.read_text())
    assert len(artifact["curve"]) == 2
    for point in artifact["curve"]:
        assert point["parity_divergences"] == 0
        # THE tentpole invariant: one gang decode dispatch per busy
        # cycle, whatever the shard count
        assert point["sharded"]["dispatches_per_cycle"] == 1.0
        assert (point["sharded"]["summary_transfers"]
                <= point["sharded"]["busy_cycles"])
        # every request generated its full budget on both planes, in
        # every one of the best-of-N timed repeats
        repeats = len(point["sharded"]["rates_per_repeat"])
        assert repeats >= 1
        assert point["sharded"]["tokens"] == repeats * 6 * 8
        assert point["independent"]["tokens"] == repeats * 6 * 8
    two = artifact["curve"][1]
    assert two["shards"] == 2
    # the independent baseline pays MORE dispatches than the gang plane
    assert (two["independent"]["decode_dispatches"]
            > two["sharded"]["decode_dispatches"])
    assert "0 parity divergences" in headline["unit"]


@pytest.mark.slow
def test_scale_suite_full_gate(tmp_path):
    # the committed-artifact configuration: decode-bound curve, monotone
    # + parity + dispatch gates (SystemExit(2) otherwise)
    from bench import run_scale_suite

    out = tmp_path / "bench_r12.json"
    run_scale_suite(str(out))
    artifact = json.loads(out.read_text())
    rates = artifact["monotone"]["tokens_per_second_by_shards"]
    assert rates["4"] > rates["2"] > rates["1"]


# ---------------------------------------------------------------------------
# Host-sync counters (also retro-pins PR 5's zero-per-request-sync claim)
# ---------------------------------------------------------------------------


def test_sharded_cycle_costs_one_dispatch_one_transfer(tiny):
    plane = make_plane(tiny, shards=3, shard_slots=2, generate_tokens=6,
                       decode_block=2)
    reqs = prompts_for(6)
    # admission: ONE insert dispatch and ZERO host transfers for the
    # whole 6-request refill, however many shards it splits across
    plane.submit_many([(ids, i) for i, ids in enumerate(reqs)])
    assert plane.insert_dispatches == 1
    assert plane.host_transfers == 0
    # every stepping cycle: exactly one gang decode dispatch and at most
    # one combined settle transfer — independent of the shard count
    for _ in range(10):
        before = (plane.decode_dispatches, plane.host_transfers,
                  plane.gang_cycles)
        plane.step()
        after = (plane.decode_dispatches, plane.host_transfers,
                 plane.gang_cycles)
        assert after[0] - before[0] <= 1
        assert after[1] - before[1] <= 1
        assert after[0] - before[0] == after[2] - before[2]
        if plane.active == 0:
            break
    assert plane.decode_dispatches == plane.gang_cycles
    assert plane.summary_transfers >= 1
    assert plane.last_free_summary is not None
    assert list(plane.last_free_summary) == [2, 2, 2]  # all drained free


def test_single_plane_admission_is_one_dispatch_zero_transfers(tiny):
    # PR 5's batched-admission claim, now pinned by counters: submit_many
    # of M requests = ONE compiled insert, no blocking sync; the first
    # tokens settle later in one deferred batched transfer
    params, config = tiny
    batcher = ContinuousBatcher(params, config, batch_size=4,
                                prompt_len=8, generate_tokens=4,
                                decode_block=2)
    batcher.submit_many([(ids, i) for i, ids in enumerate(prompts_for(4))])
    assert batcher.insert_dispatches == 1
    assert batcher.host_transfers == 0
    batcher.step()
    # the settle consumed the deferred firsts (1) and no block had
    # settled yet (dispatch-ahead): bounded, never per-request
    assert batcher.host_transfers == 1
    assert batcher.decode_dispatches == 1
    drain(batcher)
    # block cycles: one dispatch + one combined transfer each — total
    # transfers stay O(cycles), not O(requests x tokens)
    assert batcher.host_transfers <= batcher.decode_dispatches + 2


# ---------------------------------------------------------------------------
# Cross-shard admission edges
# ---------------------------------------------------------------------------


def test_refill_larger_than_any_shard_splits_across_shards(tiny):
    plane = make_plane(tiny, shards=2, shard_slots=2)
    # 3 requests, no shard has 3 free slots: must split 2 + 1
    rows = plane.submit_many([(ids, i) for i, ids in
                              enumerate(prompts_for(3))])
    shards_hit = {row // plane.shard_slots for row in rows}
    assert shards_hit == {0, 1}
    assert plane.shard_busy(0) + plane.shard_busy(1) == 3
    out = drain(plane)
    assert sorted(out) == [0, 1, 2]


def test_all_shards_full_rejects(tiny):
    plane = make_plane(tiny, shards=2, shard_slots=1)
    plane.submit_many([(ids, i) for i, ids in enumerate(prompts_for(2))])
    assert plane.free_slots == []
    with pytest.raises(RuntimeError, match="no free slot"):
        plane.submit(prompts_for(1)[0], payload=99)


def test_freest_first_tie_break_is_deterministic(tiny):
    plane = make_plane(tiny, shards=3, shard_slots=2)
    # equal depths everywhere: the router must fill in shard-index order,
    # one slot per shard per round (freest-first with lowest-index ties)
    order = [row // plane.shard_slots for row in plane.free_slots]
    assert order == [0, 1, 2, 0, 1, 2]
    # unequal depths: the freest shard leads until depths equalize
    plane.submit(prompts_for(1)[0], payload=0)  # lands on shard 0
    order = [row // plane.shard_slots for row in plane.free_slots]
    assert order == [1, 2, 0, 1, 2]


def test_deactivated_shard_gets_no_admits_but_finishes_inflight(tiny):
    plane = make_plane(tiny, shards=2, shard_slots=2, generate_tokens=4)
    reqs = prompts_for(4)
    plane.submit_many([(reqs[0], 0)])  # shard 0 (freest tie-break)
    plane.set_shard_active(1, False)
    # the router now offers only shard 0's remaining slot
    assert [r // plane.shard_slots for r in plane.free_slots] == [0]
    plane.submit_many([(reqs[1], 1)])
    with pytest.raises(RuntimeError, match="no free slot"):
        plane.submit_many([(reqs[2], 2), (reqs[3], 3)])
    out = drain(plane)  # in-flight rows decode to completion regardless
    assert sorted(out) == [0, 1]
    # reactivation is the same O(1) flip back
    plane.set_shard_active(1, True)
    assert {r // plane.shard_slots for r in plane.free_slots} == {0, 1}
    with pytest.raises(ValueError, match="out of range"):
        plane.set_shard_active(7, True)


# ---------------------------------------------------------------------------
# Parity beyond the bench smoke: slot reuse + eos across shard boundaries
# ---------------------------------------------------------------------------


def test_gang_parity_with_slot_reuse_and_eos(tiny):
    params, config = tiny
    reqs = prompts_for(10, seed=9)
    eos = 7  # small vocab: greedy decode hits it naturally for some rows

    def outputs(batcher_factory):
        batcher = batcher_factory()
        out, queue = {}, list(enumerate(reqs))
        for _ in range(300):
            while queue and batcher.free_slots:
                idx, ids = queue.pop(0)
                batcher.submit(ids, payload=idx)
            for idx, tokens in batcher.step():
                out[idx] = tokens.tolist()
            if not queue and batcher.active == 0:
                break
        return out

    sharded = outputs(lambda: ShardedBatcher(
        params, config, shards=2, shard_slots=2, prompt_len=8,
        generate_tokens=6, decode_block=3, eos_id=eos,
    ))
    single = outputs(lambda: ContinuousBatcher(
        params, config, batch_size=2, prompt_len=8, generate_tokens=6,
        decode_block=1, eos_id=eos,
    ))
    assert sharded == single


@pytest.mark.slow
def test_gang_parity_under_mesh(tiny):
    from jax.sharding import Mesh

    params, config = tiny
    devices = np.array(jax.devices()[:4]).reshape(2, 2)
    mesh = Mesh(devices, ("data", "model"))
    reqs = prompts_for(8, seed=11)

    def outputs(batcher):
        out, queue = {}, list(enumerate(reqs))
        for _ in range(300):
            while queue and batcher.free_slots:
                idx, ids = queue.pop(0)
                batcher.submit(ids, payload=idx)
            for idx, tokens in batcher.step():
                out[idx] = tokens.tolist()
            if not queue and batcher.active == 0:
                break
        return out

    sharded = outputs(ShardedBatcher(
        params, config, shards=2, shard_slots=2, prompt_len=8,
        generate_tokens=6, decode_block=2, mesh=mesh,
    ))
    single = outputs(ContinuousBatcher(
        params, config, batch_size=2, prompt_len=8, generate_tokens=6,
        decode_block=2,
    ))
    assert sharded == single


# ---------------------------------------------------------------------------
# Construction / validation
# ---------------------------------------------------------------------------


def test_sharded_rejects_non_plain_paths(tiny):
    params, config = tiny
    with pytest.raises(ValueError, match="plain continuous decode"):
        ShardedBatcher(params, config, shards=2, shard_slots=2,
                       prompt_len=8, generate_tokens=4, beams=2)
    with pytest.raises(ValueError, match="plain continuous decode"):
        ShardedBatcher(params, config, shards=2, shard_slots=2,
                       prompt_len=8, generate_tokens=4, draft_layers=1)
    with pytest.raises(ValueError, match="shards"):
        ShardedBatcher(params, config, shards=0, shard_slots=2,
                       prompt_len=8, generate_tokens=4)


def test_service_config_and_cli_reject_bad_shards():
    from kube_sqs_autoscaler_tpu.workloads.__main__ import main as worker_main
    from kube_sqs_autoscaler_tpu.workloads.service import ServiceConfig

    with pytest.raises(ValueError, match="shards"):
        ServiceConfig(queue_url="fake://x", shards=0)
    with pytest.raises(SystemExit, match="--continuous"):
        worker_main(["--demo", "1", "--generate-tokens", "2",
                     "--shards", "2"])
    with pytest.raises(SystemExit, match="plain continuous decode"):
        worker_main(["--demo", "1", "--continuous", "--generate-tokens",
                     "2", "--shards", "2", "--beams", "2"])
    with pytest.raises(SystemExit, match="must be >= 1"):
        worker_main(["--demo", "1", "--continuous", "--generate-tokens",
                     "2", "--shards", "0"])


def test_adopt_engine_requires_sharded_donor_with_same_layout(tiny):
    params, config = tiny
    a = make_plane(tiny, shards=2, shard_slots=2)
    b = make_plane(tiny, shards=2, shard_slots=2)
    b.adopt_engine(a)
    assert b._gang_fn is a._gang_fn
    assert b._insert_many is a._insert_many
    plain = ContinuousBatcher(params, config, batch_size=4, prompt_len=8,
                              generate_tokens=6, decode_block=2)
    with pytest.raises(ValueError, match="sharded donor"):
        b.adopt_engine(plain)
    other = make_plane(tiny, shards=4, shard_slots=1)
    with pytest.raises(ValueError, match="engine mismatch"):
        other.adopt_engine(a)


# ---------------------------------------------------------------------------
# ShardedWorkerPool over the real plane (scale path + exactly-once)
# ---------------------------------------------------------------------------


def test_sharded_pool_serves_scales_and_drains(tiny):
    from kube_sqs_autoscaler_tpu.fleet import (
        DRAINING,
        INACTIVE,
        SERVING,
        ShardedWorkerPool,
    )
    from kube_sqs_autoscaler_tpu.metrics.fake import FakeMessageQueue
    from kube_sqs_autoscaler_tpu.obs import WorkloadMetrics
    from kube_sqs_autoscaler_tpu.workloads.service import (
        ServiceConfig,
        collect_replies,
    )

    params, config = tiny
    queue, results = FakeMessageQueue(), FakeMessageQueue()
    service = ServiceConfig(
        queue_url="fake://scale", batch_size=2, seq_len=8,
        generate_tokens=4, decode_block=2, shards=3,
        result_queue_url="fake://scale-results",
    )
    pool = ShardedWorkerPool.serving(
        queue, params, config, service, result_queue=results,
        min=1, max=3, initial=1,
    )
    metrics = WorkloadMetrics()
    pool.attach_metrics(metrics)
    reqs = prompts_for(8, seed=4)
    sent = [queue.send_message("fake://scale", json.dumps(ids.tolist()))
            for ids in reqs]
    pool.scale_up()
    pool.scale_up()
    assert pool.replicas == 3
    cycles = 0
    while pool.processed < len(reqs) and cycles < 300:
        pool.run_cycle()
        cycles += 1
    assert pool.processed == len(reqs)
    # scale down: the shard drains (replicas drop instantly, admission
    # stops) and retires to inactive on the next cycle once empty
    pool.scale_down()
    assert pool.replicas == 2
    assert pool.shard_states == [SERVING, SERVING, DRAINING]
    pool.run_cycle()
    assert pool.shard_states == [SERVING, SERVING, INACTIVE]
    assert "shard-deactivate" in [e.name for e in pool.events]
    replies, duplicates = collect_replies(results, "fake://scale-results")
    assert len(replies) == len(sent)
    assert set(replies) == set(sent)  # zero lost
    assert duplicates == 0  # zero duplicated
    # per-shard gauges render as labeled families
    text = metrics.render()
    prefix = "kube_sqs_autoscaler_workload"
    for name in ("shard_active", "shard_active_slots",
                 "shard_tokens_per_second"):
        assert f"# TYPE {prefix}_{name} gauge" in text, name
    assert f'{prefix}_shard_active{{shard="2"}} 0.0' in text
    assert f'{prefix}_shard_active{{shard="0"}} 1.0' in text
    # shard activate/drain instants land on the Chrome-trace timeline
    events = pool.trace_events(time_origin=0.0)
    names = [e["name"] for e in events]
    assert "shard-activate" in names and "shard-drain-start" in names
    assert "shard-deactivate" in names
    assert all(e["ph"] == "i" for e in events)
    pool.stop_all()
    assert all(state == INACTIVE for state in pool.shard_states)
    assert DRAINING not in pool.shard_states


def test_sharded_pool_works_pinned_to_one_shard(tiny):
    # a one-shard plane is legal (min=max=1): the worker must build the
    # gang engine (sharded=True forces it past the shards>1 auto-pick),
    # not the plain batcher with no shard surface to actuate
    from kube_sqs_autoscaler_tpu.fleet import ShardedWorkerPool
    from kube_sqs_autoscaler_tpu.metrics.fake import FakeMessageQueue
    from kube_sqs_autoscaler_tpu.workloads.service import ServiceConfig

    params, config = tiny
    queue, results = FakeMessageQueue(), FakeMessageQueue()
    service = ServiceConfig(
        queue_url="fake://one", batch_size=2, seq_len=8,
        generate_tokens=4, decode_block=2, shards=1,
        result_queue_url="fake://one-results",
    )
    pool = ShardedWorkerPool.serving(
        queue, params, config, service, result_queue=results, min=1, max=1,
    )
    assert isinstance(pool.worker.batcher, ShardedBatcher)
    assert pool.worker.batcher.shards == 1
    assert pool.replicas == 1
    pool.scale_up()  # boundary no-op is success
    assert pool.replicas == 1
    queue.send_message("fake://one", json.dumps(prompts_for(1)[0].tolist()))
    cycles = 0
    while pool.processed < 1 and cycles < 100:
        pool.run_cycle()
        cycles += 1
    assert pool.processed == 1
    # the settled [S] summary surfaces as the device-confirmed depth
    stats = pool.worker.batcher.shard_stats()
    assert stats[0]["device_free"] == 2


def test_sharded_pool_drain_finishes_inflight_and_redelivery_dedups(tiny):
    from kube_sqs_autoscaler_tpu.fleet import DRAINING, ShardedWorkerPool
    from kube_sqs_autoscaler_tpu.metrics.fake import FakeMessageQueue
    from kube_sqs_autoscaler_tpu.workloads.service import (
        ServiceConfig,
        collect_replies,
    )

    params, config = tiny
    # tiny visibility timeout: the queue redelivers every in-flight
    # message that is not settled fast — the registry must keep replies
    # exactly-once anyway
    queue = FakeMessageQueue(visibility_timeout=0.0)
    results = FakeMessageQueue()
    service = ServiceConfig(
        queue_url="fake://drain", batch_size=1, seq_len=8,
        generate_tokens=4, decode_block=2, shards=2,
        result_queue_url="fake://drain-results",
    )
    pool = ShardedWorkerPool.serving(
        queue, params, config, service, result_queue=results,
        min=1, max=2, initial=2,
    )
    reqs = prompts_for(4, seed=6)
    sent = [queue.send_message("fake://drain", json.dumps(ids.tolist()))
            for ids in reqs]
    pool.run_cycle()  # admit across both shards
    busy_before = pool.worker.batcher.shard_busy(1)
    assert busy_before > 0
    pool.scale_down()  # shard 1 drains with work in flight
    assert pool.shard_states[1] == DRAINING
    cycles = 0
    while pool.processed < len(reqs) and cycles < 300:
        pool.run_cycle()
        cycles += 1
    replies, duplicates = collect_replies(results, "fake://drain-results")
    assert set(replies) == set(sent)
    assert duplicates == 0
    assert pool.worker.batcher.shard_busy(1) == 0

# ---------------------------------------------------------------------------
# The per-refill admission-availability cache (hot-path audit)
# ---------------------------------------------------------------------------


def _counting_plane(tiny, **kwargs):
    """A plane whose availability COMPUTES (cache misses) are counted,
    while reads stay unlimited — the counting-audit pattern of
    test_pool_cycle_cost_flat_under_retired_history."""
    params, config = tiny

    class CountingPlane(ShardedBatcher):
        computes = 0

        def _admission_rows_by_shard(self):
            if self._avail_cache is None:
                CountingPlane.computes += 1
            return super()._admission_rows_by_shard()

    plane = CountingPlane(
        params, config, shards=2, shard_slots=2, prompt_len=8,
        generate_tokens=4, decode_block=2, **kwargs,
    )
    return plane, CountingPlane


def test_admission_availability_scanned_once_per_cycle(tiny):
    plane, cls = _counting_plane(tiny)
    prompts = prompts_for(16)
    sent = iter(range(1000))
    cycles = 12
    reads_per_cycle = 3
    for _ in range(cycles):
        # a worker cycle reads availability several times: the refill's
        # capacity probe, the router, and a pressure probe
        free = plane.free_slots
        plane._free_slot_count()
        len(plane.free_slots)
        k = min(2, len(free))
        if k:
            plane.submit_many(
                [(prompts[next(sent) % 16], f"r{next(sent)}")
                 for _ in range(k)]
            )
        plane.step()
    drain(plane)
    # one scan per refill, plus at most one after each step's
    # slot-freeing settle — NOT reads x cycles
    assert cls.computes <= 2 * cycles + 2, cls.computes
    assert cls.computes < reads_per_cycle * cycles


def test_admission_cache_invalidates_on_every_eligibility_change(tiny):
    plane, _ = _counting_plane(tiny)
    assert len(plane.free_slots) == 4
    # mask flip: a drained shard's slots vanish from the SAME cycle's
    # next read
    plane.set_shard_active(1, False)
    assert len(plane.free_slots) == 2
    plane.set_shard_active(1, True)
    assert len(plane.free_slots) == 4
    # probing cap: the in-place list write the pool performs must be
    # visible immediately (the _ProbingFlags invalidation hook)
    plane.shard_probing[1] = True
    assert len(plane.free_slots) == 3
    plane.shard_probing[1] = False
    assert len(plane.free_slots) == 4
    # admission consumes rows; settle/finish returns them
    rows = plane.submit_many([(prompts_for(1)[0], "a")])
    assert len(plane.free_slots) == 3
    drain(plane)
    assert len(plane.free_slots) == 4
    # evacuation frees rows too
    plane.submit_many([(prompts_for(1)[0], "b")])
    taken = plane.take_shard_inflight(rows[0] // plane.shard_slots)
    assert len(plane.free_slots) == 4
    assert len(taken) <= 1
