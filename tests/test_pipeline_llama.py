"""Llama-family pipeline parallelism: the pp-sharded RoPE/GQA/RMSNorm/
SwiGLU stack must reproduce the plain llama forward exactly, learn under
both schedules in bf16, and the 1F1B hand-built backward must be
gradient-equal to autodiff.  Plus the gradient-accumulation composition
the pipelined batch type needs (``accum_axis=1``).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kube_sqs_autoscaler_tpu.workloads.llama import (
    LlamaConfig,
    init_llama_params,
    llama_forward,
)
from kube_sqs_autoscaler_tpu.workloads.pipeline import (
    PipelineConfig,
    init_llama_pipeline_params,
    init_llama_pipeline_train_state,
    llama_one_f_one_b_value_and_grad,
    llama_pipeline_forward,
    llama_pipeline_loss_fn,
    make_llama_pipeline_train_step,
    make_pipeline_mesh,
    pipeline_batch_sharding,
    place_pipeline_state,
    stack_llama_layers,
    unstack_llama_layers,
)
from kube_sqs_autoscaler_tpu.workloads.train import TrainConfig

# fp32 so the pipeline/dense comparison is exact (no bf16 rounding skew)
TINY = LlamaConfig(
    vocab_size=256, d_model=64, n_heads=4, n_kv_heads=2, n_layers=4,
    d_ff=128, max_seq_len=64, dtype=jnp.float32,
)
TINY_BF16 = LlamaConfig(
    vocab_size=256, d_model=64, n_heads=4, n_kv_heads=2, n_layers=4,
    d_ff=128, max_seq_len=64,
)


def microtokens(m=4, bm=2, seq=16, seed=1):
    return jax.random.randint(
        jax.random.key(seed), (m, bm, seq), 0, TINY.vocab_size, jnp.int32
    )


def as_pipeline_params(params):
    stacked = dict(params)
    stacked["stages"] = stack_llama_layers(params)
    del stacked["layers"]
    return stacked


def test_stack_unstack_roundtrip():
    params = init_llama_params(jax.random.key(0), TINY)
    roundtrip = unstack_llama_layers(as_pipeline_params(params))
    flat = jax.tree_util.tree_leaves_with_path(params)
    back = dict(
        (jax.tree_util.keystr(k), v)
        for k, v in jax.tree_util.tree_leaves_with_path(roundtrip)
    )
    assert len(flat) == len(back)
    for key, leaf in flat:
        np.testing.assert_array_equal(
            np.asarray(leaf), np.asarray(back[jax.tree_util.keystr(key)]),
            err_msg=jax.tree_util.keystr(key),
        )


def test_stage_stack_splits_fused_projections():
    params = init_llama_params(jax.random.key(0), TINY)
    stages = stack_llama_layers(params)
    kv_dim = TINY.n_kv_heads * TINY.head_dim
    for i in range(TINY.n_layers):
        fused_kv = np.asarray(params["layers"][i]["wkv"])
        np.testing.assert_array_equal(
            np.asarray(stages["wk"][i]), fused_kv[:, :kv_dim]
        )
        np.testing.assert_array_equal(
            np.asarray(stages["wv"][i]), fused_kv[:, kv_dim:]
        )
        fused_gu = np.asarray(params["layers"][i]["w_gate_up"])
        np.testing.assert_array_equal(
            np.asarray(stages["w_gate"][i]), fused_gu[:, : TINY.d_ff]
        )
        np.testing.assert_array_equal(
            np.asarray(stages["w_up"][i]), fused_gu[:, TINY.d_ff:]
        )


@pytest.mark.parametrize("pipe", [2, 4])
def test_llama_pipeline_forward_matches_dense(pipe):
    mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=pipe)
    params = init_llama_params(jax.random.key(0), TINY)
    bm = mesh.shape["data"]
    tokens = microtokens(bm=bm)
    dense = llama_forward(params, tokens.reshape(4 * bm, 16), TINY)

    pcfg = PipelineConfig(n_microbatches=4)
    piped = jax.jit(
        lambda p, t: llama_pipeline_forward(p, t, TINY, pcfg, mesh)
    )(
        as_pipeline_params(params),
        jax.device_put(tokens, pipeline_batch_sharding(mesh)),
    )
    np.testing.assert_allclose(
        np.asarray(dense),
        np.asarray(piped).reshape(4 * bm, 16, TINY.vocab_size),
        rtol=1e-4, atol=1e-4,
    )


def test_llama_pipeline_forward_matches_dense_pp2_tp2():
    # the llama block's Megatron reduce/promote seams inside the
    # fully-manual pp x dp x tp body
    mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=2,
                              model_parallel=2)
    params = init_llama_params(jax.random.key(0), TINY)
    bm = mesh.shape["data"] * 2
    tokens = microtokens(bm=bm)
    dense = llama_forward(params, tokens.reshape(4 * bm, 16), TINY)

    pcfg = PipelineConfig(n_microbatches=4)
    piped = jax.jit(
        lambda p, t: llama_pipeline_forward(p, t, TINY, pcfg, mesh)
    )(
        as_pipeline_params(params),
        jax.device_put(tokens, pipeline_batch_sharding(mesh)),
    )
    np.testing.assert_allclose(
        np.asarray(dense),
        np.asarray(piped).reshape(4 * bm, 16, TINY.vocab_size),
        rtol=1e-4, atol=1e-4,
    )


def test_llama_windowed_pipeline_forward_matches_dense():
    # sliding_window rides the per-stage kernel pick (windowed dense on
    # CPU) — the pipelined windowed forward must equal the flat one
    cfg = LlamaConfig(
        vocab_size=256, d_model=64, n_heads=4, n_kv_heads=2, n_layers=4,
        d_ff=128, max_seq_len=64, sliding_window=8, dtype=jnp.float32,
    )
    mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=2)
    params = init_llama_params(jax.random.key(2), cfg)
    bm = mesh.shape["data"]
    tokens = microtokens(bm=bm)
    dense = llama_forward(params, tokens.reshape(4 * bm, 16), cfg)

    pcfg = PipelineConfig(n_microbatches=4)
    piped = jax.jit(
        lambda p, t: llama_pipeline_forward(p, t, cfg, pcfg, mesh)
    )(
        as_pipeline_params(params),
        jax.device_put(tokens, pipeline_batch_sharding(mesh)),
    )
    np.testing.assert_allclose(
        np.asarray(dense),
        np.asarray(piped).reshape(4 * bm, 16, cfg.vocab_size),
        rtol=1e-4, atol=1e-4,
    )


def test_llama_pipeline_forward_matches_dense_pp2_sp2():
    # ring attention inside the pipeline stages (pp x dp x sp): GQA k/v
    # rotate over "seq" within each stage while activations flow over
    # "pipe" — and RoPE rotates by GLOBAL positions per shard
    mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=2,
                              seq_parallel=2)
    params = init_llama_params(jax.random.key(0), TINY)
    bm = mesh.shape["data"]
    tokens = microtokens(bm=bm)
    dense = llama_forward(params, tokens.reshape(4 * bm, 16), TINY)

    pcfg = PipelineConfig(n_microbatches=4)
    piped = jax.jit(
        lambda p, t: llama_pipeline_forward(p, t, TINY, pcfg, mesh)
    )(
        as_pipeline_params(params),
        jax.device_put(tokens, pipeline_batch_sharding(mesh)),
    )
    np.testing.assert_allclose(
        np.asarray(dense),
        np.asarray(piped).reshape(4 * bm, 16, TINY.vocab_size),
        rtol=1e-4, atol=1e-4,
    )


def test_llama_windowed_pipeline_sp_matches_dense():
    # the full stack: sliding window x sequence parallelism x pipeline
    cfg = LlamaConfig(
        vocab_size=256, d_model=64, n_heads=4, n_kv_heads=2, n_layers=4,
        d_ff=128, max_seq_len=64, sliding_window=5, dtype=jnp.float32,
    )
    mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=2,
                              seq_parallel=2)
    params = init_llama_params(jax.random.key(4), cfg)
    bm = mesh.shape["data"]
    tokens = microtokens(bm=bm)
    dense = llama_forward(params, tokens.reshape(4 * bm, 16), cfg)

    pcfg = PipelineConfig(n_microbatches=4)
    piped = jax.jit(
        lambda p, t: llama_pipeline_forward(p, t, cfg, pcfg, mesh)
    )(
        as_pipeline_params(params),
        jax.device_put(tokens, pipeline_batch_sharding(mesh)),
    )
    np.testing.assert_allclose(
        np.asarray(dense),
        np.asarray(piped).reshape(4 * bm, 16, cfg.vocab_size),
        rtol=1e-4, atol=1e-4,
    )


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_llama_pipeline_sp_train_step_learns(schedule):
    # pp x dp x sp in production bf16, BOTH schedules: ring attention
    # inside the stages (1F1B runs the compute-always uniform slot so
    # the ring's collectives stay uniform across stages)
    mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=2,
                              seq_parallel=2)
    pcfg = PipelineConfig(n_microbatches=2, schedule=schedule)
    train_config = TrainConfig(learning_rate=1e-2)
    state = place_pipeline_state(
        mesh,
        init_llama_pipeline_train_state(jax.random.key(0), TINY_BF16,
                                        train_config, n_stages=2),
    )
    step_fn = make_llama_pipeline_train_step(mesh, TINY_BF16, pcfg,
                                             train_config, state)
    tokens = jax.device_put(
        microtokens(m=2, bm=4), pipeline_batch_sharding(mesh)
    )
    losses = []
    for _ in range(4):
        state, loss = step_fn(state, tokens)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_llama_1f1b_grads_match_gpipe_autodiff_pp2_sp2():
    # 1F1B x sp, llama: GQA ring attention in the stage fwd/bwd, global
    # RoPE offsets per seq shard, sequence-sharded loss head — must be
    # gradient-equal to autodiff of the GPipe loss on the same mesh
    mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=2,
                              seq_parallel=2)
    params = as_pipeline_params(init_llama_params(jax.random.key(0), TINY))
    tokens = jax.device_put(
        microtokens(bm=mesh.shape["data"]), pipeline_batch_sharding(mesh)
    )

    gpipe_cfg = PipelineConfig(n_microbatches=4)
    ref_loss, ref_grads = jax.jit(
        jax.value_and_grad(
            lambda p, t: llama_pipeline_loss_fn(p, t, TINY, gpipe_cfg, mesh)
        )
    )(params, tokens)
    pcfg = PipelineConfig(n_microbatches=4, schedule="1f1b")
    loss, grads = jax.jit(
        lambda p, t: llama_one_f_one_b_value_and_grad(p, t, TINY, pcfg,
                                                      mesh)
    )(params, tokens)

    assert float(loss) == pytest.approx(float(ref_loss), rel=1e-5)
    _grads_allclose(grads, ref_grads)


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
@pytest.mark.parametrize("cfg", [TINY, TINY_BF16], ids=["fp32", "bf16"])
def test_llama_pipeline_train_step_learns(schedule, cfg):
    mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=2)
    pcfg = PipelineConfig(n_microbatches=4, schedule=schedule)
    train_config = TrainConfig(learning_rate=1e-2)
    state = place_pipeline_state(
        mesh,
        init_llama_pipeline_train_state(jax.random.key(0), cfg, train_config,
                                        n_stages=2),
    )
    step_fn = make_llama_pipeline_train_step(mesh, cfg, pcfg, train_config,
                                             state)
    tokens = jax.device_put(microtokens(bm=4), pipeline_batch_sharding(mesh))
    losses = []
    for _ in range(4):
        state, loss = step_fn(state, tokens)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def _grads_allclose(grads, ref_grads, rtol=2e-4, atol=2e-6):
    flat_ref = jax.tree_util.tree_leaves_with_path(ref_grads)
    flat = dict(
        (jax.tree_util.keystr(k), v)
        for k, v in jax.tree_util.tree_leaves_with_path(grads)
    )
    assert len(flat_ref) == len(flat)
    for key, ref in flat_ref:
        name = jax.tree_util.keystr(key)
        np.testing.assert_allclose(
            np.asarray(flat[name], np.float32), np.asarray(ref, np.float32),
            rtol=rtol, atol=atol, err_msg=name,
        )


@pytest.mark.parametrize("pipe,bm", [(2, 4), (4, 2)])
def test_llama_1f1b_grads_match_gpipe_autodiff(pipe, bm):
    # the claim in llama_one_f_one_b_value_and_grad's docstring:
    # gradient-equal to jax.value_and_grad(llama_pipeline_loss_fn)
    mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=pipe)
    params = as_pipeline_params(init_llama_params(jax.random.key(0), TINY))
    pcfg = PipelineConfig(n_microbatches=4, schedule="1f1b")
    tokens = jax.device_put(microtokens(bm=bm), pipeline_batch_sharding(mesh))

    ref_loss, ref_grads = jax.jit(
        jax.value_and_grad(
            lambda p, t: llama_pipeline_loss_fn(p, t, TINY, pcfg, mesh)
        )
    )(params, tokens)
    loss, grads = jax.jit(
        lambda p, t: llama_one_f_one_b_value_and_grad(p, t, TINY, pcfg, mesh)
    )(params, tokens)

    assert float(loss) == pytest.approx(float(ref_loss), rel=1e-5)
    _grads_allclose(grads, ref_grads)


def test_llama_1f1b_untied_readout_grads():
    # an untied lm_head (the HF-import layout) gets its own gradient and
    # leaves the embedding gradient to the lookup path alone
    params = as_pipeline_params(init_llama_params(jax.random.key(0), TINY))
    params["lm_head"] = jax.random.normal(
        jax.random.key(9), (TINY.vocab_size, TINY.d_model), jnp.float32
    ) * 0.02
    mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=2)
    pcfg = PipelineConfig(n_microbatches=4, schedule="1f1b")
    tokens = jax.device_put(microtokens(bm=4), pipeline_batch_sharding(mesh))

    ref_loss, ref_grads = jax.jit(
        jax.value_and_grad(
            lambda p, t: llama_pipeline_loss_fn(p, t, TINY, pcfg, mesh)
        )
    )(params, tokens)
    loss, grads = jax.jit(
        lambda p, t: llama_one_f_one_b_value_and_grad(p, t, TINY, pcfg, mesh)
    )(params, tokens)

    assert float(loss) == pytest.approx(float(ref_loss), rel=1e-5)
    assert "lm_head" in grads
    _grads_allclose(grads, ref_grads)


# ------------------------------------------------ grad accumulation


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_llama_pipeline_grad_accum_matches_single(schedule):
    # one step with grad_accum=2 must equal one step on the same total
    # batch with grad_accum=1 (fp32; the accumulation axis is the batch
    # axis of the [M, B_m, S] pipelined batch, not the microbatch axis)
    mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=2)
    pcfg = PipelineConfig(n_microbatches=4, schedule=schedule)
    # bm=8: each accum=2 chunk keeps 4 rows — divisible by the dp axis
    tokens = jax.device_put(microtokens(bm=8), pipeline_batch_sharding(mesh))

    def one_step(accum):
        train_config = TrainConfig(learning_rate=1e-2, grad_accum=accum)
        state = place_pipeline_state(
            mesh,
            init_llama_pipeline_train_state(
                jax.random.key(0), TINY, train_config, n_stages=2
            ),
        )
        step_fn = make_llama_pipeline_train_step(
            mesh, TINY, pcfg, train_config, state
        )
        state, loss = step_fn(state, tokens)
        return state, float(loss)

    state1, loss1 = one_step(1)
    state2, loss2 = one_step(2)
    assert loss2 == pytest.approx(loss1, rel=1e-5)
    # fp32 reassociation (chunked grad sums + Adam's rsqrt) leaves a few
    # ulp-level stragglers; the math is the same
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=1e-3, atol=1e-4,
        ),
        state1["params"], state2["params"],
    )


def test_gpt_pipeline_grad_accum_learns():
    # the gpt family through the same accum_axis=1 path
    from kube_sqs_autoscaler_tpu.workloads.model import ModelConfig
    from kube_sqs_autoscaler_tpu.workloads.pipeline import (
        init_pipeline_train_state,
        make_pipeline_train_step,
    )

    cfg = ModelConfig(
        vocab_size=256, d_model=64, n_heads=4, n_layers=4, d_ff=128,
        max_seq_len=64,
    )
    mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=2)
    pcfg = PipelineConfig(n_microbatches=2, schedule="1f1b")
    train_config = TrainConfig(learning_rate=1e-2, grad_accum=2)
    state = place_pipeline_state(
        mesh,
        init_pipeline_train_state(jax.random.key(0), cfg, train_config,
                                  n_stages=2),
    )
    step_fn = make_pipeline_train_step(mesh, cfg, pcfg, train_config, state)
    tokens = jax.device_put(
        jax.random.randint(jax.random.key(1), (2, 8, 16), 0, 256, jnp.int32),
        pipeline_batch_sharding(mesh),
    )
    losses = []
    for _ in range(4):
        state, loss = step_fn(state, tokens)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_hf_checkpoint_pipelines(tmp_path):
    # fine-tune an imported HF llama THROUGH the pipeline (untied
    # lm_head riding the 1F1B head), then serve the pp-trained
    # checkpoint flat — the full hf -> pp-train -> serve loop
    import pytest

    torch = pytest.importorskip("torch")
    pytest.importorskip("transformers")
    from transformers import LlamaConfig as HFConfig, LlamaForCausalLM

    from kube_sqs_autoscaler_tpu.workloads.trainer import main as trainer_main

    torch.manual_seed(0)
    hf = LlamaForCausalLM(HFConfig(
        vocab_size=128, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, tie_word_embeddings=False,
        attn_implementation="eager",
    ))
    hf_dir, ckpt = tmp_path / "hf", tmp_path / "trained"
    hf.save_pretrained(hf_dir)
    result = trainer_main([
        "--hf-checkpoint", str(hf_dir), "--pipe-parallel", "2",
        "--pipe-microbatches", "2", "--pipe-schedule", "1f1b",
        "--steps", "4", "--batch-size", "8", "--seq-len", "16",
        "--learning-rate", "1e-2", "--log-every", "1", "--overfit",
        "--checkpoint-dir", str(ckpt), "--checkpoint-every", "0",
    ])
    assert result["final_step"] == 4
    losses = result["losses"]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]

    # the untied readout survives the train->serve handoff: a fresh-init
    # reference has no lm_head, so restore_params must discover it from
    # the on-disk structure (silently dropping it would serve the tied
    # embedding as the readout — wrong logits, no error)
    from kube_sqs_autoscaler_tpu.workloads.checkpoint import (
        TrainCheckpointer,
        load_model_layout,
        load_model_manifest,
    )
    from kube_sqs_autoscaler_tpu.workloads.train import make_mesh

    smesh = make_mesh(jax.devices()[:1], model_parallel=1)
    family, config = load_model_manifest(str(ckpt))
    served = TrainCheckpointer(str(ckpt)).restore_params(
        smesh, family, config, layout=load_model_layout(str(ckpt))
    )
    assert "lm_head" in served
    assert served["lm_head"].shape == (config.vocab_size, config.d_model)

    from kube_sqs_autoscaler_tpu.workloads.__main__ import main as worker_main

    worker_main(["--checkpoint-dir", str(ckpt), "--demo", "2",
                 "--batch-size", "1", "--seq-len", "8",
                 "--generate-tokens", "3"])

    # same guarantee for the FLAT layout (no pp): an untied fine-tune
    # checkpoint restores with its lm_head
    flat_ckpt = tmp_path / "flat"
    trainer_main([
        "--hf-checkpoint", str(hf_dir), "--steps", "2", "--batch-size",
        "8", "--seq-len", "16", "--log-every", "1",
        "--checkpoint-dir", str(flat_ckpt), "--checkpoint-every", "0",
    ])
    family2, config2 = load_model_manifest(str(flat_ckpt))
    flat_served = TrainCheckpointer(str(flat_ckpt)).restore_params(
        smesh, family2, config2, layout=load_model_layout(str(flat_ckpt))
    )
    assert "lm_head" in flat_served


def test_pipeline_grad_accum_requires_divisible_batch():
    from kube_sqs_autoscaler_tpu.workloads.model import ModelConfig
    from kube_sqs_autoscaler_tpu.workloads.pipeline import (
        init_pipeline_train_state,
        make_pipeline_train_step,
    )

    cfg = ModelConfig(
        vocab_size=256, d_model=64, n_heads=4, n_layers=4, d_ff=128,
        max_seq_len=64,
    )
    mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=2)
    pcfg = PipelineConfig(n_microbatches=2)
    train_config = TrainConfig(grad_accum=3)
    state = place_pipeline_state(
        mesh,
        init_pipeline_train_state(jax.random.key(0), cfg, train_config,
                                  n_stages=2),
    )
    step_fn = make_pipeline_train_step(mesh, cfg, pcfg, train_config, state)
    tokens = jax.device_put(
        jax.random.randint(jax.random.key(1), (2, 4, 16), 0, 256, jnp.int32),
        pipeline_batch_sharding(mesh),
    )
    with pytest.raises(ValueError, match="not divisible"):
        step_fn(state, tokens)
