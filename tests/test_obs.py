"""Observability extension: tick records, Prometheus registry, HTTP probes.

The reference has no metrics endpoint, no Prometheus, and no
health/readiness probes (SURVEY.md §5); these tests cover the opt-in
extension and — critically — that plugging it in changes nothing about loop
behavior (same replica outcomes, observer failures swallowed).
"""

import http.client
import re

from kube_sqs_autoscaler_tpu.cli import build_parser
from kube_sqs_autoscaler_tpu.core.clock import FakeClock
from kube_sqs_autoscaler_tpu.core.events import TickRecord
from kube_sqs_autoscaler_tpu.core.loop import ControlLoop, LoopConfig
from kube_sqs_autoscaler_tpu.core.policy import Gate, PolicyConfig
from kube_sqs_autoscaler_tpu.metrics import FakeQueueService, QueueMetricSource
from kube_sqs_autoscaler_tpu.obs import ControllerMetrics, ObservabilityServer
from kube_sqs_autoscaler_tpu.scale import FakeDeploymentAPI, PodAutoScaler
from kube_sqs_autoscaler_tpu.core.types import MetricError, ScaleError


class RecordingObserver:
    def __init__(self):
        self.records: list[TickRecord] = []

    def on_tick(self, record: TickRecord) -> None:
        self.records.append(record)


def make_system(observer, *, depths=(100, 100, 100), init_pods=3, **policy):
    api = FakeDeploymentAPI.with_deployments("ns", init_pods, "deploy")
    scaler = PodAutoScaler(
        client=api, max=5, min=1, scale_up_pods=1, scale_down_pods=1,
        deployment="deploy", namespace="ns",
    )
    queue = FakeQueueService.with_depths(*depths)
    source = QueueMetricSource(client=queue, queue_url="example.com")
    loop = ControlLoop(
        scaler,
        source,
        LoopConfig(
            poll_interval=1.0,
            policy=PolicyConfig(
                scale_up_messages=policy.get("up_msgs", 100),
                scale_down_messages=policy.get("down_msgs", 3),
                scale_up_cooldown=policy.get("up_cool", 1.0),
                scale_down_cooldown=policy.get("down_cool", 1.0),
            ),
        ),
        clock=FakeClock(),
        observer=observer,
    )
    return loop, api, queue


# --- tick records -----------------------------------------------------------


def test_observer_sees_one_record_per_tick_with_gate_outcomes():
    obs = RecordingObserver()
    loop, _, _ = make_system(obs, depths=(100, 100, 100))  # 300 >= 100: up
    loop.run(max_ticks=3)
    assert len(obs.records) == 3
    assert all(r.num_messages == 300 for r in obs.records)
    assert all(r.up is Gate.FIRE for r in obs.records)
    assert all(r.down is Gate.IDLE for r in obs.records)
    assert obs.records[0].scaled("up") and not obs.records[0].scaled("down")


def test_record_on_metric_failure_skips_gates():
    obs = RecordingObserver()
    loop, _, queue = make_system(obs)
    queue.fail_next_get = MetricError("boom")
    loop.run(max_ticks=1)
    (record,) = obs.records
    # the metric source wraps with the reference's context string
    # ("Failed to get messages in SQS", sqs/sqs.go:53)
    assert record.metric_error == "Failed to get messages in SQS"
    assert record.num_messages is None
    assert record.up is Gate.SKIPPED and record.down is Gate.SKIPPED


def test_record_up_cooling_marks_down_skipped():
    obs = RecordingObserver()
    # up_cool=2, poll=1: tick1 (t=1) is in startup grace -> COOLING,
    # tick2 (t=2) fires, tick3 (t=3, last=2) -> COOLING again
    loop, _, _ = make_system(obs, up_cool=2.0)
    loop.run(max_ticks=3)
    assert [r.up for r in obs.records] == [Gate.COOLING, Gate.FIRE, Gate.COOLING]
    assert obs.records[0].down is Gate.SKIPPED  # the reference's `continue`
    assert obs.records[2].down is Gate.SKIPPED


def test_record_actuation_failure_sets_error_not_scaled():
    obs = RecordingObserver()
    loop, api, _ = make_system(obs)
    api.fail_next_update = ScaleError("apiserver 500")
    loop.run(max_ticks=1)
    (record,) = obs.records
    assert record.up is Gate.FIRE
    # the actuator raises the reference's context string (scale/scale.go:57)
    assert record.up_error == "Failed to scale up"
    assert not record.scaled("up")


def test_observer_exception_does_not_kill_loop():
    class Exploding:
        def on_tick(self, record):
            raise RuntimeError("observer bug")

    loop, api, _ = make_system(Exploding())
    loop.run(max_ticks=3)
    assert api.replicas("deploy") == 5  # 3→4→5 with up_cool=1.0 = poll


def test_loop_behavior_identical_with_and_without_observer():
    plain, plain_api, _ = make_system(None, depths=(1, 1, 1))
    observed, obs_api, _ = make_system(
        ControllerMetrics(), depths=(1, 1, 1)
    )
    plain.run(max_ticks=10)
    observed.run(max_ticks=10)
    assert plain_api.replicas("deploy") == obs_api.replicas("deploy") == 1


# --- Prometheus registry ----------------------------------------------------


def test_registry_counts_full_episode():
    metrics = ControllerMetrics()
    loop, _, queue = make_system(metrics, up_cool=2.0)
    queue.fail_next_get = MetricError("transient")
    # tick1 (t=1): metric failure; tick2 (t=2): cooldown expired, scale up;
    # tick3 (t=3): up cooling (down skipped)
    loop.run(max_ticks=3)
    text = metrics.render()
    assert "kube_sqs_autoscaler_ticks_total 3" in text
    assert "kube_sqs_autoscaler_metric_failures_total 1" in text
    assert "kube_sqs_autoscaler_observations_total 2" in text
    assert "kube_sqs_autoscaler_queue_messages 300" in text
    assert 'kube_sqs_autoscaler_scale_events_total{direction="up"} 1' in text
    assert 'kube_sqs_autoscaler_scale_events_total{direction="down"} 0' in text
    assert 'kube_sqs_autoscaler_cooldown_skips_total{direction="up"} 1' in text
    assert "kube_sqs_autoscaler_tick_duration_seconds_count 3" in text


def test_registry_counts_scale_failures():
    metrics = ControllerMetrics()
    loop, api, _ = make_system(metrics)
    api.fail_next_update = ScaleError("conflict")
    loop.run(max_ticks=1)
    text = metrics.render()
    assert 'kube_sqs_autoscaler_scale_failures_total{direction="up"} 1' in text
    assert 'kube_sqs_autoscaler_scale_events_total{direction="up"} 0' in text


def test_queue_messages_gauge_absent_until_first_observation():
    metrics = ControllerMetrics()
    sample_lines = [
        line
        for line in metrics.render().splitlines()
        if line.startswith("kube_sqs_autoscaler_queue_messages")
    ]
    assert sample_lines == []  # HELP/TYPE only, no sample yet
    assert "# TYPE kube_sqs_autoscaler_queue_messages gauge" in metrics.render()


def test_forecast_gauges_render_from_tick_records():
    from kube_sqs_autoscaler_tpu.core.events import TickRecord

    metrics = ControllerMetrics()
    # reactive-shaped tick: decision only, no forecast sample
    metrics.on_tick(
        TickRecord(start=0.0, num_messages=80, decision_messages=80)
    )
    text = metrics.render()
    assert "kube_sqs_autoscaler_decision_messages 80" in text
    assert "# TYPE kube_sqs_autoscaler_predicted_queue_messages gauge" in text
    assert not [  # no forecast sample yet: HELP/TYPE only
        line for line in text.splitlines()
        if line.startswith("kube_sqs_autoscaler_predicted_queue_messages")
    ]
    # predictive-shaped tick: forecast + matured error
    metrics.on_tick(
        TickRecord(
            start=5.0, num_messages=90, decision_messages=150,
            predicted_messages=150, forecast_error=12.5,
        )
    )
    text = metrics.render()
    assert "kube_sqs_autoscaler_decision_messages 150" in text
    assert "kube_sqs_autoscaler_predicted_queue_messages 150" in text
    assert "kube_sqs_autoscaler_forecast_abs_error 12.5" in text
    # a forecast-less tick (failing or warm-up policy) CLEARS the gauges:
    # latching would export an arbitrarily stale forecast as live
    metrics.on_tick(
        TickRecord(start=10.0, num_messages=95, decision_messages=95)
    )
    text = metrics.render()
    assert "kube_sqs_autoscaler_decision_messages 95" in text
    for gauge in ("predicted_queue_messages", "forecast_abs_error"):
        assert not [
            line for line in text.splitlines()
            if line.startswith(f"kube_sqs_autoscaler_{gauge} ")
        ], gauge


# --- HTTP endpoints ---------------------------------------------------------


def _get(port: int, path: str) -> tuple[int, str]:
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
    try:
        conn.request("GET", path)
        response = conn.getresponse()
        return response.status, response.read().decode()
    finally:
        conn.close()


def test_http_endpoints_health_ready_metrics_404():
    metrics = ControllerMetrics()
    server = ObservabilityServer(metrics, host="127.0.0.1", port=0)
    server.start()
    try:
        assert _get(server.port, "/healthz") == (200, "ok\n")
        status, _ = _get(server.port, "/readyz")
        assert status == 503  # no observation yet
        metrics.on_tick(TickRecord(start=0.0, num_messages=42))
        assert _get(server.port, "/readyz") == (200, "ok\n")
        status, body = _get(server.port, "/metrics")
        assert status == 200
        assert "kube_sqs_autoscaler_queue_messages 42" in body
        status, _ = _get(server.port, "/nope")
        assert status == 404
    finally:
        server.stop()


def test_http_server_serves_registry_fed_by_live_loop():
    metrics = ControllerMetrics()
    server = ObservabilityServer(metrics, host="127.0.0.1", port=0)
    server.start()
    try:
        loop, _, _ = make_system(metrics)
        loop.run(max_ticks=5)
        _, body = _get(server.port, "/metrics")
        assert "kube_sqs_autoscaler_ticks_total 5" in body
    finally:
        server.stop()


# --- CLI wiring -------------------------------------------------------------


def test_metrics_port_flag_defaults_to_disabled():
    args = build_parser().parse_args([])
    assert args.metrics_port == 0


def test_metrics_render_is_prometheus_parseable():
    """Every non-comment line is `name{labels}? value` with a float value."""
    metrics = ControllerMetrics()
    metrics.on_tick(TickRecord(start=0.0, duration=0.25, num_messages=7))
    sample = re.compile(
        r'^kube_sqs_autoscaler_[a-z_]+(\{[a-zA-Z_]+="[^"]*"'
        r'(,[a-zA-Z_]+="[^"]*")*\})?'
        r" -?[0-9.eE+-]+$"
    )
    for line in metrics.render().strip().splitlines():
        if line.startswith("#"):
            continue
        assert sample.match(line), line
        float(line.rsplit(" ", 1)[1])  # value must parse


def test_workload_metrics_gauges_and_timer_summaries():
    from kube_sqs_autoscaler_tpu.obs import WorkloadMetrics
    from kube_sqs_autoscaler_tpu.utils.profiling import SpanTimer

    metrics = WorkloadMetrics()
    assert not metrics.ready  # nothing recorded yet

    metrics.set_gauge("train_tokens_per_sec", 81234.5, "Trainer throughput.")
    metrics.set_gauge("train_mfu", 0.35)
    timer = SpanTimer()
    for _ in range(3):
        with timer.span("cycle"):
            pass
    metrics.attach_timer("worker", timer)

    assert metrics.ready
    text = metrics.render()
    assert "kube_sqs_autoscaler_workload_train_tokens_per_sec 81234.5" in text
    assert "kube_sqs_autoscaler_workload_train_mfu 0.35" in text
    assert 'kube_sqs_autoscaler_workload_worker_cycle_seconds{quantile="0.5"}' in text
    assert 'quantile="0.99"' in text
    assert "kube_sqs_autoscaler_workload_worker_cycle_seconds_count 3" in text


def test_workload_metrics_served_over_http():
    import urllib.request

    from kube_sqs_autoscaler_tpu.obs import (
        ObservabilityServer,
        WorkloadMetrics,
    )

    metrics = WorkloadMetrics()
    server = ObservabilityServer(metrics, host="127.0.0.1", port=0)
    server.start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        # not ready until a sample lands
        try:
            urllib.request.urlopen(f"{base}/readyz")
            raise AssertionError("expected 503 before first sample")
        except urllib.error.HTTPError as err:
            assert err.code == 503
        metrics.set_gauge("train_loss", 3.25)
        body = urllib.request.urlopen(f"{base}/metrics").read().decode()
        assert "kube_sqs_autoscaler_workload_train_loss 3.25" in body
        assert urllib.request.urlopen(f"{base}/readyz").status == 200
    finally:
        server.stop()


def test_trainer_metrics_port_exposes_training_gauges(tmp_path):
    """--metrics-port on the trainer binary: /metrics shows the trainer's
    own tokens/s + loss gauges while it runs (VERDICT round-2 item 7)."""
    import threading
    import urllib.request

    from kube_sqs_autoscaler_tpu.workloads.trainer import main as trainer_main

    # run the trainer in a thread so we can scrape mid-run; port=0 is not
    # knowable from outside, so grab a free port first
    import socket

    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.close()

    seen: dict = {}

    def scrape():
        # poll until the trainer publishes its first interval
        import time as _t

        # generous window: the first step is behind XLA compilation
        for _ in range(1200):
            try:
                body = urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=1
                ).read().decode()
                if "workload_train_loss" in body:
                    seen["body"] = body
                    return
            except Exception:
                pass
            _t.sleep(0.05)

    scraper = threading.Thread(target=scrape)
    scraper.start()
    trainer_main([
        "--vocab-size", "256", "--d-model", "64", "--n-heads", "4",
        "--n-layers", "2", "--d-ff", "128", "--seq-len", "32",
        "--batch-size", "8", "--steps", "8", "--log-every", "1",
        "--metrics-port", str(port),
    ])
    scraper.join(timeout=30)
    assert "body" in seen, "never scraped a train_loss gauge mid-run"
    assert "kube_sqs_autoscaler_workload_train_loss" in seen["body"]
    assert "kube_sqs_autoscaler_workload_train_step" in seen["body"]


# --- tick-duration histogram (ISSUE 2 satellite) ----------------------------


def test_tick_duration_is_a_cumulative_histogram():
    from kube_sqs_autoscaler_tpu.obs.prometheus import TICK_DURATION_BUCKETS

    metrics = ControllerMetrics()
    for duration in (0.0005, 0.03, 0.03, 0.7, 20.0):
        metrics.on_tick(TickRecord(start=0.0, duration=duration, num_messages=1))
    text = metrics.render()
    assert "# TYPE kube_sqs_autoscaler_tick_duration_seconds histogram" in text
    # cumulative: every bucket counts all observations <= its bound
    assert 'tick_duration_seconds_bucket{le="0.001"} 1' in text
    assert 'tick_duration_seconds_bucket{le="0.05"} 3' in text
    assert 'tick_duration_seconds_bucket{le="1"} 4' in text
    assert 'tick_duration_seconds_bucket{le="10"} 4' in text  # 20 s overflows
    assert 'tick_duration_seconds_bucket{le="+Inf"} 5' in text
    # _sum/_count names unchanged from the old summary (dashboards survive)
    assert "kube_sqs_autoscaler_tick_duration_seconds_count 5" in text
    assert "kube_sqs_autoscaler_tick_duration_seconds_sum" in text
    # monotone non-decreasing across the rendered bucket sequence
    counts = [
        int(line.rsplit(" ", 1)[1])
        for line in text.splitlines()
        if line.startswith("kube_sqs_autoscaler_tick_duration_seconds_bucket")
    ]
    assert len(counts) == len(TICK_DURATION_BUCKETS) + 1
    assert counts == sorted(counts)


# --- build_info + uptime (ISSUE 2 satellite) --------------------------------


def test_build_info_gauge_carries_version_policy_forecaster():
    metrics = ControllerMetrics(
        version="1.2.3", policy="predictive", forecaster="holt"
    )
    text = metrics.render()
    assert (
        'kube_sqs_autoscaler_build_info{version="1.2.3",'
        'policy="predictive",forecaster="holt"} 1' in text
    )


def test_build_info_defaults_to_package_version_and_reactive():
    from kube_sqs_autoscaler_tpu import __version__

    text = ControllerMetrics().render()
    assert (
        f'kube_sqs_autoscaler_build_info{{version="{__version__}",'
        'policy="reactive",forecaster=""} 1' in text
    )


def test_process_uptime_gauge_advances():
    import time as _time

    metrics = ControllerMetrics()
    first = float(
        next(
            line for line in metrics.render().splitlines()
            if line.startswith("kube_sqs_autoscaler_process_uptime_seconds")
        ).rsplit(" ", 1)[1]
    )
    assert first >= 0.0
    _time.sleep(0.02)
    second = float(
        next(
            line for line in metrics.render().splitlines()
            if line.startswith("kube_sqs_autoscaler_process_uptime_seconds")
        ).rsplit(" ", 1)[1]
    )
    assert second > first


# --- exposition escaping (ISSUE 2 satellite) --------------------------------


def test_workload_help_text_newlines_and_backslashes_are_escaped():
    from kube_sqs_autoscaler_tpu.obs import WorkloadMetrics

    metrics = WorkloadMetrics()
    metrics.set_gauge("g", 1.0, "line one\nline two \\ backslash")
    text = metrics.render()
    assert (
        "# HELP kube_sqs_autoscaler_workload_g"
        " line one\\nline two \\\\ backslash" in text
    )
    # the exposition stays line-oriented: every line still starts with a
    # comment marker or a metric name
    for line in text.strip().splitlines():
        assert line.startswith("#") or line.startswith("kube_sqs_")


def test_build_info_label_values_are_escaped():
    metrics = ControllerMetrics(
        version='1.0"evil\nname\\', policy="reactive", forecaster=""
    )
    text = metrics.render()
    assert '\\"evil\\nname\\\\' in text
    assert "\nname" not in text.replace("\\nname", "")  # no raw newline leaked


def test_escape_helpers_are_prometheus_spec_order():
    from kube_sqs_autoscaler_tpu.obs.prometheus import (
        escape_help,
        escape_label_value,
    )

    assert escape_help("a\\b\nc") == "a\\\\b\\nc"
    assert escape_label_value('a"b\nc\\d') == 'a\\"b\\nc\\\\d'


# --- observer fan-out isolation (ISSUE 2 satellite) -------------------------


def test_multi_observer_exception_does_not_starve_later_observers():
    from kube_sqs_autoscaler_tpu.core.events import MultiObserver

    class Exploding:
        calls = 0

        def on_tick(self, record):
            type(self).calls += 1
            raise RuntimeError("observer bug")

    first_bad = Exploding()
    after = RecordingObserver()
    loop, api, _ = make_system(MultiObserver([first_bad, after]))
    loop.run(max_ticks=3)
    # the raising observer ran every tick, the one after it saw every tick,
    # and the loop itself kept scaling
    assert Exploding.calls == 3
    assert len(after.records) == 3
    assert api.replicas("deploy") == 5


def test_multi_observer_all_members_see_identical_record():
    from kube_sqs_autoscaler_tpu.core.events import MultiObserver

    a, b = RecordingObserver(), RecordingObserver()
    loop, _, _ = make_system(MultiObserver([a, b]))
    loop.run(max_ticks=2)
    assert a.records == b.records
    assert a.records[0] is b.records[0]  # same record object, no copies


# --- concurrent scrape-while-writing (ISSUE 2 satellite) --------------------


def test_concurrent_scrapes_while_loop_writes():
    """HTTP scrapes racing the loop thread's registry writes must always
    see a complete, parseable exposition (the registry lock's contract)."""
    import threading

    metrics = ControllerMetrics()
    server = ObservabilityServer(metrics, host="127.0.0.1", port=0)
    server.start()
    failures: list = []

    def hammer():
        try:
            for _ in range(50):
                status, body = _get(server.port, "/metrics")
                assert status == 200
                # ticks_total must always be present and integral
                line = next(
                    ln for ln in body.splitlines()
                    if ln.startswith("kube_sqs_autoscaler_ticks_total")
                )
                int(line.rsplit(" ", 1)[1])
        except Exception as err:  # pragma: no cover - failure path
            failures.append(err)

    scrapers = [threading.Thread(target=hammer) for _ in range(4)]
    try:
        for t in scrapers:
            t.start()
        loop, _, _ = make_system(metrics)
        for _ in range(10):
            loop.run(max_ticks=20)
            loop.reset()
    finally:
        for t in scrapers:
            t.join(timeout=30)
        server.stop()
    assert not failures
    assert "kube_sqs_autoscaler_ticks_total 200" in metrics.render()


# --- /debug flight-recorder endpoints (ISSUE 2 tentpole) --------------------


def test_debug_endpoints_404_without_a_ring():
    metrics = ControllerMetrics()
    server = ObservabilityServer(metrics, host="127.0.0.1", port=0)
    server.start()
    try:
        assert _get(server.port, "/debug/ticks")[0] == 404
        assert _get(server.port, "/debug/trace")[0] == 404
    finally:
        server.stop()


def test_debug_ticks_serves_last_n_records_as_json():
    import json

    from kube_sqs_autoscaler_tpu.obs import JOURNAL_SCHEMA_VERSION, TickRing
    from kube_sqs_autoscaler_tpu.core.events import MultiObserver

    metrics = ControllerMetrics()
    ring = TickRing(capacity=64)
    server = ObservabilityServer(metrics, host="127.0.0.1", port=0, ring=ring)
    server.start()
    try:
        loop, _, _ = make_system(MultiObserver([metrics, ring]))
        loop.run(max_ticks=7)
        status, body = _get(server.port, "/debug/ticks")
        assert status == 200
        payload = json.loads(body)
        assert payload["schema"] == JOURNAL_SCHEMA_VERSION
        assert len(payload["ticks"]) == 7
        assert payload["ticks"][-1]["num_messages"] == 300
        status, body = _get(server.port, "/debug/ticks?n=3")
        assert len(json.loads(body)["ticks"]) == 3
        # bad n falls back to the default instead of erroring
        status, _ = _get(server.port, "/debug/ticks?n=bogus")
        assert status == 200
    finally:
        server.stop()


def test_debug_trace_serves_valid_chrome_trace_json():
    import json

    from kube_sqs_autoscaler_tpu.obs import TickRing
    from kube_sqs_autoscaler_tpu.core.events import MultiObserver

    metrics = ControllerMetrics()
    ring = TickRing()
    server = ObservabilityServer(metrics, host="127.0.0.1", port=0, ring=ring)
    server.start()
    try:
        loop, _, _ = make_system(MultiObserver([metrics, ring]))
        loop.run(max_ticks=4)
        status, body = _get(server.port, "/debug/trace")
        assert status == 200
        trace = json.loads(body)  # the ISSUE's validity bar
        names = {e["name"] for e in trace["traceEvents"]}
        assert "tick" in names and "scale-up" in names
        assert len([e for e in trace["traceEvents"] if e["name"] == "tick"]) == 4
    finally:
        server.stop()


def test_journal_flag_defaults():
    args = build_parser().parse_args([])
    assert args.journal_path == ""
    assert args.journal_ring == 256
    assert args.journal_max_bytes == 64 * 1024 * 1024


def test_workload_metrics_serving_gauges():
    from kube_sqs_autoscaler_tpu.obs import WorkloadMetrics

    metrics = WorkloadMetrics()
    metrics.set_serving_gauges(
        tokens_per_second=1234.5,
        time_to_first_token_seconds=0.01,
        active_slots=3,
        decode_block_utilization=0.75,
    )
    text = metrics.render()
    prefix = "kube_sqs_autoscaler_workload"
    assert f"{prefix}_tokens_per_second 1234.5" in text
    assert f"{prefix}_time_to_first_token_seconds 0.01" in text
    assert f"{prefix}_active_slots 3.0" in text
    assert f"{prefix}_decode_block_utilization 0.75" in text
    # each carries HELP text (escaped by the registry)
    for name in ("tokens_per_second", "time_to_first_token_seconds",
                 "active_slots", "decode_block_utilization"):
        assert f"# HELP {prefix}_{name} " in text, name
