"""LoRA x pipeline parallelism: stage-stacked adapters must start at the
base exactly, train adapter-only through the GPipe schedule, merge to the
flat serving layout, and compose with resume/eval through the trainer.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kube_sqs_autoscaler_tpu.workloads.lora import (
    LoraConfig,
    apply_pipeline_lora,
    init_pipeline_lora_params,
    init_pipeline_lora_train_state,
    lora_pipeline_checkpoint_state,
    make_lora_pipeline_train_step,
)
from kube_sqs_autoscaler_tpu.workloads.model import (
    ModelConfig,
    forward,
    init_params,
)
from kube_sqs_autoscaler_tpu.workloads.pipeline import (
    PipelineConfig,
    as_pipeline_params,
    make_pipeline_mesh,
    pipeline_batch_sharding,
    pipeline_forward,
    pipeline_loss_fn,
    pipeline_param_shardings,
)
from kube_sqs_autoscaler_tpu.workloads.train import TrainConfig

# fp32 so pipeline/dense comparisons are exact (no bf16 rounding skew)
TINY = ModelConfig(
    vocab_size=256, d_model=64, n_heads=4, n_layers=4, d_ff=128,
    max_seq_len=64, dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def stacked_base():
    return as_pipeline_params(init_params(jax.random.key(0), TINY))


def microtokens(m=4, bm=2, seq=16, seed=1):
    return jax.random.randint(
        jax.random.key(seed), (m, bm, seq), 0, TINY.vocab_size, jnp.int32
    )


def test_zero_init_is_identity(stacked_base):
    lora = LoraConfig(rank=4)
    adapters = init_pipeline_lora_params(jax.random.key(1), stacked_base,
                                         lora)
    adapted = apply_pipeline_lora(stacked_base, adapters, lora)
    for a, b in zip(jax.tree.leaves(stacked_base),
                    jax.tree.leaves(adapted)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_adapters_cover_every_stacked_matmul(stacked_base):
    lora = LoraConfig(rank=4)
    adapters = init_pipeline_lora_params(jax.random.key(1), stacked_base,
                                         lora)
    # the split projections adapt individually (wqkv -> wq/wk/wv)
    assert sorted(adapters["stages"]) == sorted(
        ["wq", "wk", "wv", "wo", "w_up", "w_down"]
    )
    for name, ab in adapters["stages"].items():
        w = stacked_base["stages"][name]
        assert ab["a"].shape == (w.shape[0], w.shape[1], 4)
        assert ab["b"].shape == (w.shape[0], 4, w.shape[2])


def test_merged_unstacked_equals_adapted_pipeline_forward(stacked_base):
    # nonzero adapters: the pipelined adapted forward and the FLAT dense
    # forward of the merged-unstacked weights (the checkpoint/serving
    # layout) must be the same model
    lora = LoraConfig(rank=4)
    adapters = init_pipeline_lora_params(jax.random.key(1), stacked_base,
                                         lora)
    adapters = jax.tree.map(
        lambda x: x + 0.05 * jnp.ones_like(x), adapters
    )
    mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=2)
    bm = mesh.shape["data"]
    tokens = microtokens(bm=bm)
    pcfg = PipelineConfig(n_microbatches=4)

    piped = jax.jit(
        lambda ad, t: pipeline_forward(
            apply_pipeline_lora(stacked_base, ad, lora), t, TINY, pcfg, mesh
        )
    )(adapters, jax.device_put(tokens, pipeline_batch_sharding(mesh)))

    state = {"adapters": adapters, "opt_state": None,
             "step": jnp.zeros((), jnp.int32)}
    flat = lora_pipeline_checkpoint_state(stacked_base, state, lora)["params"]
    dense = forward(flat, tokens.reshape(4 * bm, 16), TINY)
    np.testing.assert_allclose(
        np.asarray(dense),
        np.asarray(piped).reshape(4 * bm, 16, TINY.vocab_size),
        rtol=1e-4, atol=1e-4,
    )


def test_training_moves_loss_and_only_adapters(stacked_base):
    mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=2)
    lora = LoraConfig(rank=4)
    train_config = TrainConfig(learning_rate=3e-2)
    frozen = jax.device_put(
        stacked_base, pipeline_param_shardings(mesh, stacked_base)
    )
    state = init_pipeline_lora_train_state(
        jax.random.key(1), frozen, lora, train_config
    )
    pcfg = PipelineConfig(n_microbatches=4)
    step_fn = make_lora_pipeline_train_step(
        mesh, TINY, pcfg, train_config, frozen, state, lora
    )
    tokens = jax.device_put(
        microtokens(bm=mesh.shape["data"]), pipeline_batch_sharding(mesh)
    )
    # step 0's loss is the frozen model's loss (B = 0 start)
    base_loss = float(pipeline_loss_fn(stacked_base, microtokens(
        bm=mesh.shape["data"]), TINY, pcfg, mesh))
    adapters0 = jax.tree.map(np.asarray, state["adapters"])
    losses = []
    for _ in range(8):
        state, loss = step_fn(state, tokens)
        losses.append(float(loss))
    assert losses[0] == pytest.approx(base_loss, abs=1e-5)
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]
    changed = jax.tree.leaves(jax.tree.map(
        lambda a, b: float(np.abs(np.asarray(a) - b).max()),
        state["adapters"], adapters0,
    ))
    assert max(changed) > 0  # adapters moved; the base cannot (closed over)


def test_grad_accum_matches_single_pass(stacked_base):
    # same invariant the flat LoRA pins: accumulated adapter steps ==
    # whole-batch steps (fp32 end to end, loss compared after one step)
    mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=2)
    lora = LoraConfig(rank=4)
    frozen = jax.device_put(
        stacked_base, pipeline_param_shardings(mesh, stacked_base)
    )
    # bm=8: each accum chunk of 4 rows still fills the data axis (4)
    pcfg = PipelineConfig(n_microbatches=2)
    tokens = jax.device_put(
        microtokens(m=2, bm=8), pipeline_batch_sharding(mesh)
    )
    losses = {}
    for accum in (1, 2):
        train_config = TrainConfig(learning_rate=1e-2, grad_accum=accum)
        state = init_pipeline_lora_train_state(
            jax.random.key(1), frozen, lora, train_config
        )
        step_fn = make_lora_pipeline_train_step(
            mesh, TINY, pcfg, train_config, frozen, state, lora
        )
        state, loss = step_fn(state, tokens)
        _, loss2 = step_fn(state, tokens)
        losses[accum] = (float(loss), float(loss2))
    assert losses[1][0] == pytest.approx(losses[2][0], rel=1e-5)
    assert losses[1][1] == pytest.approx(losses[2][1], rel=1e-3)


def test_1f1b_adapter_grads_match_gpipe_autodiff(stacked_base):
    # lora x pp x 1F1B: the chain rule over the hand-built backward's
    # stage-weight gradients must reproduce autodiff of the GPipe
    # adapter loss (fp32, nonzero adapters so both factors get signal)
    from kube_sqs_autoscaler_tpu.workloads.lora import (
        lora_pipeline_value_and_grad,
    )

    mesh = make_pipeline_mesh(jax.devices(), pipe_parallel=2)
    lora = LoraConfig(rank=4)
    frozen = jax.device_put(
        stacked_base, pipeline_param_shardings(mesh, stacked_base)
    )
    adapters = init_pipeline_lora_params(jax.random.key(1), frozen, lora)
    adapters = jax.tree.map(lambda x: x + 0.03 * jnp.ones_like(x), adapters)
    tokens = jax.device_put(
        microtokens(bm=mesh.shape["data"]), pipeline_batch_sharding(mesh)
    )

    gpipe_vag = jax.jit(lora_pipeline_value_and_grad(
        mesh, TINY, PipelineConfig(n_microbatches=4), frozen, lora
    ))
    f1b_vag = jax.jit(lora_pipeline_value_and_grad(
        mesh, TINY, PipelineConfig(n_microbatches=4, schedule="1f1b"),
        frozen, lora,
    ))
    ref_loss, ref_grads = gpipe_vag(adapters, tokens)
    loss, grads = f1b_vag(adapters, tokens)
    assert float(loss) == pytest.approx(float(ref_loss), rel=1e-5)
    jax.tree.map(
        lambda g, r: np.testing.assert_allclose(
            np.asarray(g, np.float32), np.asarray(r, np.float32),
            rtol=2e-4, atol=2e-6,
        ),
        grads, ref_grads,
    )


TRAINER_FLAGS = [
    "--vocab-size", "256", "--d-model", "64", "--n-heads", "4",
    "--n-layers", "4", "--d-ff", "128", "--seq-len", "32",
    "--batch-size", "8", "--learning-rate", "1e-2", "--log-every", "1",
    "--lora-rank", "4", "--pipe-parallel", "2", "--pipe-microbatches", "2",
]


def test_trainer_resume_equals_uninterrupted(tmp_path):
    # the LoRA lifecycle invariant, through the pipeline: interrupt and
    # resume replays exactly (stacked adapters + step from the
    # checkpoint, the frozen stage stacks rebuilt from the same seed)
    from kube_sqs_autoscaler_tpu.workloads.checkpoint import (
        TrainCheckpointer,
        load_model_layout,
        load_model_manifest,
    )
    from kube_sqs_autoscaler_tpu.workloads.train import make_mesh
    from kube_sqs_autoscaler_tpu.workloads.trainer import main

    full_dir = str(tmp_path / "full")
    split_dir = str(tmp_path / "split")
    full = main(TRAINER_FLAGS + ["--steps", "6",
                                 "--checkpoint-dir", full_dir])
    main(TRAINER_FLAGS + ["--steps", "4", "--checkpoint-dir", split_dir,
                          "--checkpoint-every", "2"])
    resumed = main(TRAINER_FLAGS + ["--steps", "2", "--checkpoint-dir",
                                    split_dir, "--resume"])
    assert resumed["final_step"] == 6
    np.testing.assert_allclose(
        resumed["losses"], full["losses"][4:], rtol=1e-6
    )
    assert load_model_layout(full_dir) == {
        "kind": "lora", "rank": 4, "seed": 0, "base": "",
        "pipeline_stages": 2,
    }
    # merged weights on disk are FLAT (kind "lora", not "pipeline"):
    # the serving restore reads them with no unstacking step
    mesh = make_mesh(jax.devices()[:1], model_parallel=1)
    family, config = load_model_manifest(full_dir)
    a = TrainCheckpointer(full_dir).restore_params(
        mesh, family, config, layout=load_model_layout(full_dir))
    b = TrainCheckpointer(split_dir).restore_params(
        mesh, family, config, layout=load_model_layout(split_dir))
    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y)
        ),
        a, b,
    )


def test_trainer_llama_pipeline_lora_learns_and_evals(caplog):
    # the modern family end to end: --family llama --pipe-parallel
    # --lora-rank (+ grad-accum + eval) through the trainer binary
    import logging

    from kube_sqs_autoscaler_tpu.workloads.trainer import main

    with caplog.at_level(logging.INFO):
        result = main([
            "--family", "llama", "--vocab-size", "256", "--d-model", "64",
            "--n-heads", "4", "--n-kv-heads", "2", "--n-layers", "4",
            "--d-ff", "128", "--seq-len", "32", "--batch-size", "16",
            "--learning-rate", "1e-2", "--log-every", "1",
            "--lora-rank", "4", "--pipe-parallel", "2",
            "--pipe-microbatches", "2", "--grad-accum", "2",
            "--steps", "4", "--overfit",
            "--eval-every", "4", "--eval-batches", "2",
        ])
    assert result["final_step"] == 4
    losses = result["losses"]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]
    assert any("eval_loss" in r.getMessage() for r in caplog.records)


def test_trainer_1f1b_lora_learns():
    # the flag composition end to end: --lora-rank + --pipe-schedule 1f1b
    from kube_sqs_autoscaler_tpu.workloads.trainer import main

    result = main(TRAINER_FLAGS + ["--steps", "4", "--overfit",
                                   "--pipe-schedule", "1f1b"])
    assert result["final_step"] == 4
    losses = result["losses"]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_lora_moe_pipeline_both_schedules():
    # the last lora-matrix hole (VERDICT r4 next #9): adapter-only
    # fine-tuning of a frozen ROUTED base through the pipeline — expert
    # stacks get per-expert stage-stacked factors (4-D), the router
    # stays frozen, and the 1F1B chain-ruled adapter grads must match
    # GPipe autodiff of the same routed objective
    from kube_sqs_autoscaler_tpu.workloads.moe import MoeConfig
    from kube_sqs_autoscaler_tpu.workloads.pipeline import (
        init_moe_pipeline_train_state,
        place_pipeline_state,
    )

    moe = MoeConfig(n_experts=4, top_k=2)
    mesh = make_pipeline_mesh(jax.devices()[:4], pipe_parallel=2)
    base_state = place_pipeline_state(
        mesh,
        init_moe_pipeline_train_state(jax.random.key(11), TINY, moe,
                                      TrainConfig(), n_stages=2),
    )
    frozen = base_state["params"]
    lora = LoraConfig(rank=2)
    tokens = jax.device_put(microtokens(m=2, seed=12),
                            pipeline_batch_sharding(mesh))

    # expert adapters exist in the 4-D per-expert stage-stacked shape
    adapters = init_pipeline_lora_params(jax.random.key(13), frozen, lora)
    assert adapters["stages"]["w_up_experts"]["a"].shape == (
        TINY.n_layers, moe.n_experts, TINY.d_model, lora.rank
    )
    assert adapters["stages"]["w_up_experts"]["b"].shape == (
        TINY.n_layers, moe.n_experts, lora.rank, TINY.d_ff
    )

    def two(schedule):
        st = init_pipeline_lora_train_state(
            jax.random.key(14), frozen, lora, TrainConfig()
        )
        step = make_lora_pipeline_train_step(
            mesh, TINY, PipelineConfig(n_microbatches=2,
                                       schedule=schedule),
            TrainConfig(), frozen, st, lora,
            moe=moe,
        )
        st, l1 = step(st, tokens)
        st, l2 = step(st, tokens)
        return float(l1), float(l2)

    g1, g2 = two("gpipe")
    f1, f2 = two("1f1b")
    np.testing.assert_allclose(f1, g1, rtol=1e-5)
    np.testing.assert_allclose(f2, g2, rtol=2e-3)
    assert g2 < g1  # adapters actually optimize the routed objective


def test_trainer_binary_lora_moe_pipeline():
    from kube_sqs_autoscaler_tpu.workloads.trainer import main

    main([
        "--steps", "2", "--batch-size", "8", "--seq-len", "16",
        "--vocab-size", "256", "--d-model", "64", "--n-heads", "4",
        "--n-layers", "2", "--d-ff", "128",
        "--pipe-parallel", "2", "--pipe-microbatches", "2",
        "--moe", "--moe-experts", "4", "--lora-rank", "2",
    ])
