"""Sharded admission plane: consistent-hash stability, cross-shard
credit borrowing invariants, kill/rehydrate crash tolerance, gossip
partitions, the FleetFaultPlan admission faults, the CLI knobs, and the
admission-scale bench smoke (full 100k-1M battery marked slow).
"""

import json

import pytest

from kube_sqs_autoscaler_tpu.workloads.admission_shards import (
    AdmissionCoordinator,
    HashRing,
    ShardedAdmission,
)
from kube_sqs_autoscaler_tpu.workloads.tenancy import (
    FairAdmission,
    TenancyConfig,
)


def _plane(shards=4, tenants=("a", "b", "c", "d"), **overrides):
    config = dict(
        tenants=tenants, admission_shards=shards,
        staging_per_tenant=8, staging_total=32,
    )
    config.update(overrides)
    return ShardedAdmission(
        TenancyConfig(**config), per_tenant_limit=8, total_limit=32,
    )


def _item(tenant, index):
    # the worker stages (tenant, prefix_ids, ids, message) — item[3]
    # is the raw queue message the kill path hands back
    return (tenant, None, (1, 2, 3),
            {"MessageId": f"{tenant}-{index}",
             "ReceiptHandle": f"rh-{tenant}-{index}",
             "Body": "{}"})


# ---------------------------------------------------------------------------
# Consistent hashing: stability, determinism, failover
# ---------------------------------------------------------------------------


def test_hash_ring_moves_about_one_over_n_when_growing():
    tenants = [f"t{i}" for i in range(10_000)]
    four = HashRing(4)
    five = HashRing(5)
    moved = sum(
        1 for t in tenants if four.shard_of(t) != five.shard_of(t)
    )
    # ideal is 1/5 = 0.2; the virtual-node ring lands close to it —
    # the point is that it is nowhere near the 0.8 a mod-N hash moves
    assert 0.10 < moved / len(tenants) < 0.30


def test_hash_ring_is_deterministic_across_instances():
    tenants = [f"t{i}" for i in range(500)]
    a, b = HashRing(4), HashRing(4)
    assert [a.shard_of(t) for t in tenants] == \
        [b.shard_of(t) for t in tenants]
    # every shard owns a non-trivial slice
    owners = {a.shard_of(t) for t in tenants}
    assert owners == {0, 1, 2, 3}


def test_hash_ring_failover_walks_past_dead_owner():
    ring = HashRing(4)
    tenant = "victim-tenant"
    home = ring.shard_of(tenant)
    alive = {s for s in range(4) if s != home}
    rerouted = ring.shard_of(tenant, alive=alive)
    assert rerouted != home
    assert rerouted in alive
    # tenants whose owner is alive do not move
    for t in (f"t{i}" for i in range(200)):
        if ring.shard_of(t) != home:
            assert ring.shard_of(t, alive=alive) == ring.shard_of(t)


# ---------------------------------------------------------------------------
# Sticky homes: survive rehydration, pin across failover
# ---------------------------------------------------------------------------


def test_sticky_home_survives_export_import():
    plane = _plane()
    tenants = [f"t{i}" for i in range(64)]
    for i, tenant in enumerate(tenants):
        plane.stage(tenant, _item(tenant, 0),
                    message_id=f"{tenant}-m0")
    homes = {t: plane.shard_of(t).index for t in tenants}

    fresh = _plane()
    fresh.import_state(plane.export_state())
    assert {t: fresh.shard_of(t).index for t in tenants} == homes


def test_sticky_home_survives_kill_and_restart():
    plane = _plane()
    tenant = "sticky-tenant"
    plane.stage(tenant, _item(tenant, 0), message_id="m0")
    home = plane.shard_of(tenant).index

    handed = []
    plane.kill_shard(home, handback=handed.append)
    assert [m["MessageId"] for m in handed] == [f"{tenant}-0"]
    # while dead the tenant fails over to a surviving shard and the
    # home RE-PINS there (deterministic, no flapping)...
    failover = plane.shard_of(tenant).index
    assert failover != home
    plane.restart_shard(home)
    # ...so the restart does not bounce it back: sticky means stable
    assert plane.shard_of(tenant).index == failover


# ---------------------------------------------------------------------------
# Cross-shard credit borrowing: debt bound, no starvation
# ---------------------------------------------------------------------------


def test_coordinator_debt_never_exceeds_borrow_cap():
    coordinator = AdmissionCoordinator(4)
    demands = [40, 1, 0, 3]
    weights = [1.0, 1.0, 0.0, 2.0]
    for cycle in range(300):
        demands[1] = cycle % 3  # flickering busy period
        grants = coordinator.allocate(4, demands, weights)
        assert sum(grants) <= min(4, sum(demands))
        assert all(g >= 0 for g in grants)
        for s in range(4):
            assert coordinator.debt(s) <= coordinator.BORROW_CAP + 1e-9


def test_coordinator_never_starves_a_busy_peer():
    # shard 0 has a bottomless backlog; shard 1 trickles — equal
    # weights must still earn shard 1 about half the slots while it
    # has demand, no matter how hungry shard 0 is
    coordinator = AdmissionCoordinator(2)
    granted = [0, 0]
    offered = 0
    for cycle in range(200):
        demands = [1000, 2 if cycle % 2 else 0]
        if demands[1]:
            offered += 1
        grants = coordinator.allocate(4, demands, [1.0, 1.0])
        granted[0] += grants[0]
        granted[1] += grants[1]
    # shard 1 was busy half the time at demand 2 of k=4: its earned
    # share alone is ~2 per busy cycle; borrowing by shard 0 may not
    # eat into it
    assert granted[1] >= offered
    # and work conservation actually used the leftover capacity
    assert granted[0] > granted[1]


def test_coordinator_state_round_trips():
    coordinator = AdmissionCoordinator(3)
    for cycle in range(20):
        coordinator.allocate(4, [5, 3, 1], [1.0, 2.0, 1.0])
    state = coordinator.export_state()
    fresh = AdmissionCoordinator(3)
    fresh.import_state(state)
    assert fresh.export_state() == state
    assert fresh.borrows_total == coordinator.borrows_total


# ---------------------------------------------------------------------------
# The plane: pick caps, kill/rehydrate, gossip partitions
# ---------------------------------------------------------------------------


def test_pick_never_exceeds_free_slots_under_banked_credit():
    plane = _plane()
    staged = sum(
        1 for i in range(24)
        if plane.stage(f"burst{i}", _item(f"burst{i}", i),
                       message_id=f"b{i}")
    )
    assert staged >= 16  # some shard slices fill first; most land
    # several under-granted cycles bank fractional credit; a later
    # pick must still cap at k (the engine's free slots), not spill
    for k in (1, 1, 1, 4, 4, 8):
        plane.note_cycle()
        assert len(plane.pick(k, now=None)) <= k


def test_kill_hands_back_staged_and_rehydrates_from_tombstone():
    plane = _plane()
    staged = 0
    for i in range(12):
        tenant = f"t{i}"
        if plane.stage(tenant, _item(tenant, i), message_id=f"m{i}"):
            staged += 1
    victim = max(range(4), key=lambda s: plane.shards[s].fair.staged)
    before = plane.shards[victim].fair.staged
    assert before >= 1

    handed = []
    released = plane.kill_shard(victim, handback=handed.append)
    assert released == before == len(handed)
    assert not plane.shards[victim].alive
    assert plane.staged == staged - released

    # the next cycle's supervisor auto-restart rehydrates accounting
    plane.note_cycle()
    shard = plane.shards[victim]
    assert shard.alive
    assert shard.rehydrations == 1
    assert shard.rehydrated_records >= 1  # tombstone, not cold
    # the handed-back work is NOT re-driven from state: it redelivers
    # through the queue, so the restarted shard starts empty
    assert shard.fair.staged == 0


def test_killed_shard_tombstone_carries_flood_state_to_restart():
    plane = _plane()
    plane.shards[1].fair._flood_sticky.add("coalition")
    plane.kill_shard(1)
    plane.restart_shard(1)
    assert "coalition" in plane.shards[1].fair._flood_sticky


def test_gossip_unions_flood_state_except_across_partitions():
    plane = _plane(shards=3, tenants=("a", "b", "c"))
    plane.partition_shard(2, True)
    plane.shards[0].fair._flood_sticky.add("mob")
    plane.gossip()
    assert "mob" in plane.shards[1].fair._flood_sticky
    assert "mob" not in plane.shards[2].fair._flood_sticky
    plane.partition_shard(2, False)
    plane.gossip()
    assert "mob" in plane.shards[2].fair._flood_sticky


def test_restarted_shard_adopts_peer_flood_gossip():
    plane = _plane()
    plane.kill_shard(0)
    plane.shards[1].fair._flood_sticky.add("mob")
    plane.restart_shard(0)
    assert "mob" in plane.shards[0].fair._flood_sticky


def test_adopt_flood_arms_sticky_grace():
    fair = FairAdmission(
        TenancyConfig(tenants=("a",)), per_tenant_limit=4,
        total_limit=8,
    )
    fair.adopt_flood({"mob"})
    assert "mob" in fair._flood_sticky
    assert fair._sticky_grace["mob"] == fair.STICKY_RESTORE_GRACE
    # adopting again is idempotent (no grace reset churn on re-gossip)
    fair._sticky_grace["mob"] = 3
    fair.adopt_flood({"mob"})
    assert fair._sticky_grace["mob"] == 3


def test_single_shard_config_builds_the_plain_plane():
    with pytest.raises(ValueError, match="admission_shards"):
        ShardedAdmission(
            TenancyConfig(tenants=("a",), admission_shards=1),
            per_tenant_limit=4, total_limit=8,
        )
    with pytest.raises(ValueError, match="admission_shards"):
        TenancyConfig(tenants=("a",), admission_shards=0)
    with pytest.raises(ValueError, match="decode_slo_s"):
        TenancyConfig(tenants=("a",), decode_slo_s=-0.1)


# ---------------------------------------------------------------------------
# FleetFaultPlan: admission kills + gossip partitions
# ---------------------------------------------------------------------------


def test_fault_plan_validates_admission_partition_windows():
    from kube_sqs_autoscaler_tpu.sim.faults import FleetFaultPlan

    with pytest.raises(ValueError, match="admission_partitions"):
        FleetFaultPlan(admission_partitions=((5, 5, 0),))
    with pytest.raises(ValueError, match="admission_partitions"):
        FleetFaultPlan(admission_partitions=((8, 2, 1),))
    plan = FleetFaultPlan(
        admission_kills=((3, 1),),
        admission_partitions=((2, 6, 0),),
    )
    assert plan.admission_shards() == {0, 1}


def test_fault_plan_dispatches_admission_faults_by_cycle():
    from kube_sqs_autoscaler_tpu.sim.faults import FleetFaultPlan

    calls = []

    class Pool:
        def kill_admission_shard(self, shard):
            calls.append(("kill", shard))

        def partition_admission_shard(self, shard, partitioned=True):
            calls.append(("partition", shard, partitioned))

    plan = FleetFaultPlan(
        admission_kills=((3, 1),),
        admission_partitions=((2, 5, 0),),
    )
    pool = Pool()
    for cycle in range(7):
        plan.apply(cycle, pool)
    assert calls == [
        ("partition", 0, True),
        ("kill", 1),
        ("partition", 0, False),
    ]


# ---------------------------------------------------------------------------
# CLI knobs
# ---------------------------------------------------------------------------


def test_admission_shard_flag_rejections():
    from kube_sqs_autoscaler_tpu.workloads.__main__ import (
        main as worker_main,
    )

    base = ["--demo", "1", "--continuous", "--generate-tokens", "2"]
    with pytest.raises(SystemExit, match="requires --tenants"):
        worker_main(base + ["--admission-shards", "2"])
    with pytest.raises(SystemExit, match="requires --tenants"):
        worker_main(base + ["--decode-slo-budget", "0.5"])
    with pytest.raises(SystemExit, match="must be >= 1"):
        worker_main(base + ["--tenants", "a", "--admission-shards", "0"])
    with pytest.raises(SystemExit, match="must be >= 0"):
        worker_main(base + ["--tenants", "a",
                            "--decode-slo-budget", "-1"])


# ---------------------------------------------------------------------------
# Per-shard observability: the three gauges render per shard
# ---------------------------------------------------------------------------


def test_per_shard_gauges_render():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from kube_sqs_autoscaler_tpu.metrics.fake import FakeMessageQueue
    from kube_sqs_autoscaler_tpu.obs import WorkloadMetrics
    from kube_sqs_autoscaler_tpu.workloads.continuous import (
        ContinuousWorker,
    )
    from kube_sqs_autoscaler_tpu.workloads.model import (
        ModelConfig,
        init_params,
    )
    from kube_sqs_autoscaler_tpu.workloads.service import ServiceConfig

    model = ModelConfig(
        vocab_size=64, d_model=16, n_heads=2, n_layers=2, d_ff=32,
        max_seq_len=16, dtype=jnp.float32,
    )
    params = init_params(jax.random.key(0), model)
    queue = FakeMessageQueue()
    results = FakeMessageQueue()
    worker = ContinuousWorker(
        queue, params, model,
        ServiceConfig(
            queue_url="t://q", batch_size=2, seq_len=4,
            generate_tokens=4, decode_block=2,
            result_queue_url="t://r",
        ),
        result_queue=results,
        tenancy=TenancyConfig(tenants=("a", "b"), admission_shards=4),
    )
    metrics = WorkloadMetrics()
    worker.attach_metrics(metrics)
    rng = np.random.default_rng(41)
    for index in range(3):
        queue.send_message("t://q", json.dumps(
            {"tenant": ("a", "b")[index % 2],
             "ids": rng.integers(1, 64, 3).tolist()},
        ))
    cycles = 0
    while worker.processed < 3:
        worker.run_once()
        cycles += 1
        assert cycles < 200, "worker did not drain"
    text = metrics.render()
    prefix = "kube_sqs_autoscaler_workload"
    for shard in range(4):
        label = f'{{shard="{shard}"}}'
        assert f"{prefix}_admission_shard_staged{label}" in text
        assert f"{prefix}_admission_shard_tenants{label}" in text
        # every shard is alive and unpartitioned: state reads 2
        assert f"{prefix}_admission_shard_state{label} 2.0" in text
    # a killed shard reads 0 on the next rendered cycle (it rehydrates
    # the cycle after, so pause auto-restart by not calling note_cycle)
    worker._fair.kill_shard(1)
    worker._update_metrics()
    text = metrics.render()
    assert f'{prefix}_admission_shard_state{{shard="1"}} 0.0' in text


# ---------------------------------------------------------------------------
# The admission-scale bench: tier-1 smoke, full battery slow
# ---------------------------------------------------------------------------


def test_admission_scale_bench_smoke(tmp_path):
    import bench

    out = tmp_path / "BENCH_admission.json"
    summary = bench.run_admission_scale_suite(
        output=str(out), scale=0.002, timing_gates=False,
    )
    assert summary["metric"] == \
        "admission_scale_victim_ttft_p99_improvement"
    artifact = json.loads(out.read_text())
    assert artifact["suite"] == "admission-scale"
    for name, episode in artifact["episodes"].items():
        for key in ("n1", "n4"):
            row = episode[key]
            assert row["answered"] == row["requests"], (name, key)
            assert row["duplicates"] == 0
    chaos = artifact["chaos"]
    assert chaos["answered"] == chaos["requests"]
    assert chaos["duplicates"] == 0
    assert chaos["kill"]["handed_back"] >= 1
    assert chaos["kill"]["rehydrated_records"] >= 1
    decode = artifact["decode_deadline"]
    assert decode["shed_by_reason"]["decode_deadline"] >= 1
    assert decode["decode_deadline_replies"] >= 1
    parity = artifact["parity"]
    for label in ("single-shard", "decode-armed-dormant"):
        assert parity[label]["single_plane"]
        assert (parity[label]["insert_dispatches"]
                == parity["pr11"]["insert_dispatches"])


@pytest.mark.slow
def test_admission_scale_bench_full_battery(tmp_path):
    import bench

    out = tmp_path / "BENCH_admission_full.json"
    summary = bench.run_admission_scale_suite(output=str(out))
    assert summary["vs_baseline"] > 1.0
    artifact = json.loads(out.read_text())
    for name, episode in artifact["episodes"].items():
        assert (episode["n4"]["victim_ttft_p99_s"]
                < episode["n1"]["victim_ttft_p99_s"]), name
        assert (episode["n4"]["tokens_per_virtual_s"]
                > episode["n1"]["tokens_per_virtual_s"]), name
