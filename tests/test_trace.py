"""Chrome trace-event export of tick records.

The acceptance bar (ISSUE 2): exported trace JSON is valid Chrome
trace-event format — loads via ``json.loads``, spans carry integer
microsecond ``ts``/``dur``, instant events mark gate fires, cooldown
skips, and metric failures.
"""

import json

from kube_sqs_autoscaler_tpu.core.events import TickRecord
from kube_sqs_autoscaler_tpu.core.policy import Gate
from kube_sqs_autoscaler_tpu.obs.trace import (
    render_chrome_trace,
    to_chrome_trace,
    trace_events,
)


def _records():
    return [
        TickRecord(
            start=100.0, duration=0.05, num_messages=150,
            decision_messages=150, up=Gate.FIRE, down=Gate.IDLE,
            observe_s=0.03, decide_s=0.005, actuate_s=0.015,
        ),
        TickRecord(
            start=105.0, duration=0.02, num_messages=150,
            decision_messages=150, up=Gate.COOLING, down=Gate.SKIPPED,
            observe_s=0.02, decide_s=0.0,
        ),
        TickRecord(start=110.0, duration=0.01, metric_error="boom",
                   observe_s=0.01),
        TickRecord(
            start=115.0, duration=0.03, num_messages=2,
            decision_messages=2, up=Gate.IDLE, down=Gate.FIRE,
            down_error="Failed to scale down",
            observe_s=0.02, decide_s=0.005, actuate_s=0.005,
        ),
    ]


def test_trace_round_trips_as_json_with_expected_top_level_shape():
    body = render_chrome_trace(_records(), meta={"source": "test"})
    trace = json.loads(body)  # the ISSUE's validity bar
    assert isinstance(trace["traceEvents"], list) and trace["traceEvents"]
    assert trace["displayTimeUnit"] == "ms"
    assert trace["otherData"] == {"source": "test"}


def test_every_event_is_well_formed():
    for event in trace_events(_records()):
        assert event["ph"] in ("X", "i")
        assert isinstance(event["ts"], int) and event["ts"] >= 0
        assert isinstance(event["pid"], int) and isinstance(event["tid"], int)
        if event["ph"] == "X":
            assert isinstance(event["dur"], int) and event["dur"] >= 0
        else:
            assert event["s"] == "t"


def test_timestamps_are_microseconds_from_first_record():
    events = trace_events(_records())
    ticks = [e for e in events if e["name"] == "tick"]
    assert [e["ts"] for e in ticks] == [0, 5_000_000, 10_000_000, 15_000_000]
    assert ticks[0]["dur"] == 50_000  # 0.05 s


def test_phase_spans_tile_the_tick():
    events = trace_events(_records())
    observe = next(e for e in events if e["name"] == "observe")
    decide = next(e for e in events if e["name"] == "decide")
    actuate = next(e for e in events if e["name"] == "actuate")
    assert observe["ts"] == 0 and observe["dur"] == 30_000
    assert decide["ts"] == 30_000 and decide["dur"] == 5_000
    assert actuate["ts"] == 35_000 and actuate["dur"] == 15_000


def test_instant_events_mark_the_postmortem_moments():
    events = trace_events(_records())
    by_name = {}
    for e in events:
        if e["ph"] == "i":
            by_name.setdefault(e["name"], []).append(e)
    assert by_name["scale-up"][0]["args"] == {"direction": "up", "ok": True}
    assert by_name["cooldown-skip"][0]["args"] == {"direction": "up"}
    assert by_name["metric-failure"][0]["args"] == {"error": "boom"}
    (down,) = by_name["scale-down"]
    assert down["args"]["ok"] is False
    assert down["args"]["error"] == "Failed to scale down"


def test_ticks_without_span_fields_export_without_phase_spans():
    """Pre-PR-2 records (or observers that never saw spans) still trace."""
    record = TickRecord(start=0.0, duration=0.01, num_messages=5,
                        up=Gate.IDLE, down=Gate.FIRE)
    names = {e["name"] for e in trace_events([record])}
    assert "tick" in names and "scale-down" in names
    assert not {"observe", "decide", "actuate"} & names


def test_empty_record_list_exports_an_empty_trace():
    assert trace_events([]) == []
    assert json.loads(render_chrome_trace([]))["traceEvents"] == []


def test_live_loop_records_export_directly():
    """End to end: real loop on a FakeClock → ring → trace."""
    from kube_sqs_autoscaler_tpu.core.clock import FakeClock
    from kube_sqs_autoscaler_tpu.core.loop import ControlLoop, LoopConfig
    from kube_sqs_autoscaler_tpu.core.policy import PolicyConfig
    from kube_sqs_autoscaler_tpu.metrics import (
        FakeQueueService,
        QueueMetricSource,
    )
    from kube_sqs_autoscaler_tpu.obs.journal import TickRing
    from kube_sqs_autoscaler_tpu.scale import FakeDeploymentAPI, PodAutoScaler

    ring = TickRing()
    api = FakeDeploymentAPI.with_deployments("ns", 1, "deploy")
    loop = ControlLoop(
        PodAutoScaler(client=api, max=5, min=1, scale_up_pods=1,
                      scale_down_pods=1, deployment="deploy", namespace="ns"),
        QueueMetricSource(client=FakeQueueService.with_depths(200),
                          queue_url="example.com"),
        LoopConfig(poll_interval=5.0, policy=PolicyConfig(
            scale_up_cooldown=1.0, scale_down_cooldown=1.0)),
        clock=FakeClock(),
        observer=ring,
    )
    loop.run(max_ticks=4)
    trace = json.loads(render_chrome_trace(ring.snapshot()))
    ticks = [e for e in trace["traceEvents"] if e["name"] == "tick"]
    assert len(ticks) == 4
    # FakeClock ticks are instantaneous: spans exist and are zero-length
    observes = [e for e in trace["traceEvents"] if e["name"] == "observe"]
    assert len(observes) == 4 and all(e["dur"] == 0 for e in observes)
    assert any(e["name"] == "scale-up" for e in trace["traceEvents"])
