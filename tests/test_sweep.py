"""Autotuning sweep driver: grid/sample construction, Pareto math, and
end-to-end scoring through the compiled simulator."""

import pytest

from kube_sqs_autoscaler_tpu.core.loop import LoopConfig
from kube_sqs_autoscaler_tpu.core.policy import PolicyConfig
from kube_sqs_autoscaler_tpu.sim.evaluate import Scenario
from kube_sqs_autoscaler_tpu.sim.scenarios import RampArrival, StepArrival
from kube_sqs_autoscaler_tpu.sim.sweep import (
    SweepPoint,
    SweepReport,
    SweepSpec,
    pareto_front,
    run_sweep,
)


def test_grid_size_and_reactive_dedupe():
    spec = SweepSpec(
        scale_up_messages=(50, 100),
        scale_down_messages=(10,),
        scale_up_cooldown=(10.0,),
        scale_down_cooldown=(30.0,),
        scale_up_pods=(1,),
        policies=("reactive", "holt"),
        horizons=(15.0, 45.0),
        histories=(128,),
    )
    grid = spec.grid()
    # 2 gate combos x (1 reactive + 2 holt horizons) = 6 — reactive must
    # NOT be multiplied by the horizon axis it ignores
    assert len(grid) == 6
    reactive = [p for p in grid if p.policy == "reactive"]
    assert len(reactive) == 2
    assert len(set(grid)) == len(grid)  # frozen dataclass: dedupe-able


def test_sample_is_seeded_and_subsets_the_grid():
    spec = SweepSpec()
    a = spec.sample(10, seed=7)
    b = spec.sample(10, seed=7)
    assert a == b
    assert len(a) == 10
    assert set(a) <= set(spec.grid())
    # asking for more than the grid returns the whole grid
    assert len(spec.sample(10_000)) == len(spec.grid())


def test_point_label_distinguishes_policies():
    reactive = SweepPoint(policy="reactive")
    holt = SweepPoint(policy="holt", horizon=45.0)
    assert "reactive" in reactive.label()
    assert "holt@45s" in holt.label()
    assert reactive.label() != holt.label()


def test_point_to_config_wires_gates_and_forecast():
    scenario = Scenario(
        name="t", arrival=StepArrival(before=5.0, after=50.0, at=60.0),
        duration=300.0,
    )
    point = SweepPoint(
        scale_up_messages=77, scale_up_pods=3, policy="lstsq",
        horizon=20.0, history=64,
    )
    config = point.to_config(scenario)
    assert config.loop.policy.scale_up_messages == 77
    assert config.scale_up_pods == 3
    assert config.policy == "predictive"
    assert config.forecaster == "lstsq"
    assert config.forecast_horizon == 20.0
    assert config.forecast_history == 64
    reactive_config = SweepPoint(policy="reactive").to_config(scenario)
    assert reactive_config.policy == "reactive"


def test_pareto_front_keeps_only_nondominated():
    #     y
    #  4  a          a dominated by c (worse on both)
    #  2      c   .  c, d, e on the front; b dominated by d
    #  1        d b
    #  0          e
    points = [(3.0, 4.0), (4.0, 1.0), (2.0, 2.0), (3.0, 1.0), (4.0, 0.0)]
    front = pareto_front(points)
    assert front == [2, 3, 4]


def test_pareto_front_keeps_duplicate_optima():
    points = [(1.0, 1.0), (1.0, 1.0), (2.0, 2.0)]
    assert pareto_front(points) == [0, 1]


def _tiny_scenarios():
    loop = LoopConfig(
        poll_interval=5.0,
        policy=PolicyConfig(
            scale_up_messages=100, scale_down_messages=10,
            scale_up_cooldown=10.0, scale_down_cooldown=30.0,
        ),
    )
    return (
        Scenario(
            name="mini-step",
            arrival=StepArrival(before=20.0, after=120.0, at=60.0),
            duration=200.0, max_pods=15, loop=loop,
        ),
        Scenario(
            name="mini-ramp",
            arrival=RampArrival(
                start_rate=10.0, end_rate=120.0, t_start=30.0, t_end=180.0
            ),
            duration=200.0, max_pods=15, loop=loop,
        ),
    )


def _tiny_spec():
    return SweepSpec(
        scale_up_messages=(50, 100),
        scale_down_messages=(10,),
        scale_up_cooldown=(10.0,),
        scale_down_cooldown=(30.0,),
        scale_up_pods=(1,),
        policies=("reactive", "holt"),
        horizons=(30.0,),
        histories=(64,),
    )


def test_run_sweep_scores_every_scenario_point_pair():
    scenarios = _tiny_scenarios()
    report = run_sweep(_tiny_spec(), scenarios)
    assert report.points == 2 * 4  # 2 scenarios x 4 grid points
    names = {row["scenario"] for row in report.rows}
    assert names == {"mini-step", "mini-ramp"}
    for row in report.rows:
        assert set(row["score"]) >= {
            "max_depth", "time_over_slo_s", "replica_changes",
        }


def test_run_sweep_summary_has_best_and_pareto_per_scenario():
    report = run_sweep(_tiny_spec(), _tiny_scenarios())
    summary = report.summary()
    assert summary["points"] == report.points
    assert set(summary["best"]) == {"mini-step", "mini-ramp"}
    for name, front in summary["pareto"].items():
        assert front, name
        best = summary["best"][name]
        # the best config is on its scenario's Pareto front by definition
        assert best["config"] in {row["config"] for row in front}


def test_best_ranking_prefers_depth_then_churn():
    report = SweepReport(rows=[
        {"scenario": "s", "label": "deep", "point": {},
         "score": {"max_depth": 500.0, "replica_changes": 1,
                   "time_over_slo_s": 0.0}},
        {"scenario": "s", "label": "calm", "point": {},
         "score": {"max_depth": 100.0, "replica_changes": 9,
                   "time_over_slo_s": 0.0}},
        {"scenario": "s", "label": "churny", "point": {},
         "score": {"max_depth": 100.0, "replica_changes": 30,
                   "time_over_slo_s": 0.0}},
    ])
    assert report.best_per_scenario()["s"]["label"] == "calm"


def test_run_sweep_rejects_empty_points():
    with pytest.raises(ValueError):
        run_sweep([], _tiny_scenarios())


def test_run_sweep_groups_mixed_histories_into_separate_batches():
    # Points with different history capacities cannot share one compiled
    # batch (the capacity is a compiled shape); the driver must group
    # them transparently rather than error.
    points = [
        SweepPoint(policy="holt", history=32),
        SweepPoint(policy="holt", history=64),
        SweepPoint(policy="reactive"),
    ]
    report = run_sweep(points, _tiny_scenarios()[:1])
    assert report.points == 3


@pytest.mark.slow
def test_default_grid_full_battery_sweep():
    # The bench-sweep operating point: the full default grid over the full
    # battery, >= 100 scenario-config points, one compiled batch.
    report = run_sweep(SweepSpec())
    assert report.points >= 100
    summary = report.summary()
    assert set(summary["best"]) == {"step", "ramp", "diurnal", "burst"}
    # a tuned configuration must never lose to every other point: each
    # scenario's best is on that scenario's Pareto front
    for name, front in summary["pareto"].items():
        assert summary["best"][name]["config"] in {
            row["config"] for row in front
        }
