"""KV-cache decode correctness on the virtual 8-device CPU mesh.

The decode path must be *numerically equivalent* to running the full
forward at every step (the naive no-cache decoder): same logits (fp
tolerance), same greedy tokens.  Also covers cache bookkeeping, capacity
guards, sampling determinism, and the mesh-sharded serving compilation.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kube_sqs_autoscaler_tpu.workloads.decode import (
    cache_shardings,
    decode_step,
    generate,
    generate_jit,
    init_cache,
    make_serving_fns,
    prefill,
)
from kube_sqs_autoscaler_tpu.workloads.model import (
    ModelConfig,
    forward,
    init_params,
)
from kube_sqs_autoscaler_tpu.workloads.train import make_mesh

# fp32 end to end so the cached and uncached paths agree to tight tolerance
TINY = ModelConfig(
    vocab_size=128, d_model=64, n_heads=4, n_layers=2, d_ff=128,
    max_seq_len=32, dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.key(0), TINY)


def prompt_tokens(batch=2, length=5, seed=1):
    return jax.random.randint(
        jax.random.key(seed), (batch, length), 0, TINY.vocab_size, jnp.int32
    )


def naive_greedy(params, prompt, num_tokens):
    """Reference decoder: full forward each step, no cache."""
    tokens = prompt
    out = []
    for _ in range(num_tokens):
        logits = forward(params, tokens, TINY)[:, -1]
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        out.append(nxt)
        tokens = jnp.concatenate([tokens, nxt[:, None]], axis=1)
    return jnp.stack(out, axis=1)


def test_prefill_matches_full_forward_last_position(params):
    prompt = prompt_tokens()
    logits, cache = prefill(params, prompt, TINY)
    expected = forward(params, prompt, TINY)[:, -1]
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(expected), rtol=1e-5, atol=1e-5
    )
    assert int(cache["length"]) == prompt.shape[1]
    assert cache["layers"][0]["k"].shape == (
        2, TINY.n_heads, TINY.max_seq_len, TINY.head_dim
    )


def test_decode_step_matches_full_forward(params):
    prompt = prompt_tokens()
    logits, cache = prefill(params, prompt, TINY)
    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    step_logits, cache = decode_step(params, cache, nxt, TINY)
    full = jnp.concatenate([prompt, nxt[:, None]], axis=1)
    expected = forward(params, full, TINY)[:, -1]
    np.testing.assert_allclose(
        np.asarray(step_logits), np.asarray(expected), rtol=1e-5, atol=1e-5
    )
    assert int(cache["length"]) == prompt.shape[1] + 1


def test_generate_greedy_matches_naive_decoder(params):
    prompt = prompt_tokens()
    got = generate(params, prompt, 8, TINY)
    expected = naive_greedy(params, prompt, 8)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))
    assert got.dtype == jnp.int32 and got.shape == (2, 8)


def test_generate_jit_single_token_and_compiled_path(params):
    prompt = prompt_tokens()
    got = generate_jit(params, prompt, 1, TINY)
    expected = naive_greedy(params, prompt, 1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))


def test_generate_rejects_zero_tokens(params):
    with pytest.raises(ValueError, match="num_tokens"):
        generate(params, prompt_tokens(), 0, TINY)


def test_prefill_through_flash_attention_seam_matches_dense(params):
    import functools

    from kube_sqs_autoscaler_tpu.workloads.flash import flash_attention

    flash = functools.partial(flash_attention, interpret=True)
    prompt = prompt_tokens(length=16)  # tiles onto 16-wide blocks
    got, _ = prefill(params, prompt, TINY, attention_fn=flash)
    expected, _ = prefill(params, prompt, TINY)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expected), rtol=1e-4, atol=1e-4
    )


def test_generate_capacity_guard(params):
    prompt = prompt_tokens(length=30)
    with pytest.raises(ValueError, match="exceeds"):
        generate(params, prompt, 3, TINY)  # 30 + 3 > 32
    with pytest.raises(ValueError, match="exceeds"):
        prefill(params, prompt_tokens(length=33), TINY)


def test_sampling_is_deterministic_given_key_and_requires_rng(params):
    prompt = prompt_tokens()
    a = generate(params, prompt, 6, TINY, temperature=0.8,
                 rng=jax.random.key(7))
    b = generate(params, prompt, 6, TINY, temperature=0.8,
                 rng=jax.random.key(7))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert ((a >= 0) & (a < TINY.vocab_size)).all()
    with pytest.raises(ValueError, match="rng"):
        generate(params, prompt, 2, TINY, temperature=0.8)


def test_cache_positions_beyond_length_do_not_affect_logits(params):
    # garbage in unwritten cache slots must be fully masked out
    prompt = prompt_tokens()
    logits, cache = prefill(params, prompt, TINY)
    poisoned = {
        "layers": [
            {"k": lc["k"].at[:, :, -1].set(1e4), "v": lc["v"].at[:, :, -1].set(1e4)}
            for lc in cache["layers"]
        ],
        "length": cache["length"],
    }
    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    clean, _ = decode_step(params, cache, nxt, TINY)
    dirty, _ = decode_step(params, poisoned, nxt, TINY)
    np.testing.assert_allclose(np.asarray(clean), np.asarray(dirty))


def test_sharded_serving_matches_single_device(params):
    mesh = make_mesh(jax.devices()[:4], model_parallel=2, seq_parallel=1)
    prefill_fn, decode_fn, generate_fn = make_serving_fns(mesh, TINY, params)
    prompt = prompt_tokens(batch=4)

    expected = naive_greedy(params, prompt, 6)
    got = generate_fn(params, prompt, jax.random.key(0), 6)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))

    # sampling through the same compiled path: deterministic per key
    # (all args positional: pjit rejects kwargs when in_shardings is set)
    a = generate_fn(params, prompt, jax.random.key(3), 6, 0.9)
    b = generate_fn(params, prompt, jax.random.key(3), 6, 0.9)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    logits, cache = prefill_fn(params, prompt)
    ref_logits = forward(params, prompt, TINY)[:, -1]
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(ref_logits), rtol=1e-4, atol=1e-4
    )
    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    step_logits, cache = decode_fn(params, cache, nxt)
    full = jnp.concatenate([prompt, nxt[:, None]], axis=1)
    np.testing.assert_allclose(
        np.asarray(step_logits),
        np.asarray(forward(params, full, TINY)[:, -1]),
        rtol=1e-4, atol=1e-4,
    )


def test_serving_mesh_rejects_seq_axis(params):
    mesh = make_mesh(jax.devices(), model_parallel=2, seq_parallel=2)
    with pytest.raises(ValueError, match="seq"):
        make_serving_fns(mesh, TINY, params)
