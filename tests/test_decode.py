"""KV-cache decode correctness on the virtual 8-device CPU mesh.

The decode path must be *numerically equivalent* to running the full
forward at every step (the naive no-cache decoder): same logits (fp
tolerance), same greedy tokens.  Also covers cache bookkeeping, capacity
guards, sampling determinism, and the mesh-sharded serving compilation.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kube_sqs_autoscaler_tpu.workloads.decode import (
    cache_shardings,
    decode_step,
    generate,
    generate_jit,
    init_cache,
    make_serving_fns,
    prefill,
)
from kube_sqs_autoscaler_tpu.workloads.model import (
    ModelConfig,
    forward,
    init_params,
)
from kube_sqs_autoscaler_tpu.workloads.train import make_mesh

# fp32 end to end so the cached and uncached paths agree to tight tolerance
TINY = ModelConfig(
    vocab_size=128, d_model=64, n_heads=4, n_layers=2, d_ff=128,
    max_seq_len=32, dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.key(0), TINY)


def prompt_tokens(batch=2, length=5, seed=1):
    return jax.random.randint(
        jax.random.key(seed), (batch, length), 0, TINY.vocab_size, jnp.int32
    )


def naive_greedy(params, prompt, num_tokens):
    """Reference decoder: full forward each step, no cache."""
    tokens = prompt
    out = []
    for _ in range(num_tokens):
        logits = forward(params, tokens, TINY)[:, -1]
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        out.append(nxt)
        tokens = jnp.concatenate([tokens, nxt[:, None]], axis=1)
    return jnp.stack(out, axis=1)


def test_prefill_matches_full_forward_last_position(params):
    prompt = prompt_tokens()
    logits, cache = prefill(params, prompt, TINY)
    expected = forward(params, prompt, TINY)[:, -1]
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(expected), rtol=1e-5, atol=1e-5
    )
    assert cache["length"].shape == (prompt.shape[0],)
    assert np.asarray(cache["length"]).tolist() == [prompt.shape[1]] * 2
    assert cache["layers"][0]["k"].shape == (
        2, TINY.n_heads, TINY.max_seq_len, TINY.head_dim
    )


def test_decode_step_matches_full_forward(params):
    prompt = prompt_tokens()
    logits, cache = prefill(params, prompt, TINY)
    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    step_logits, cache = decode_step(params, cache, nxt, TINY)
    full = jnp.concatenate([prompt, nxt[:, None]], axis=1)
    expected = forward(params, full, TINY)[:, -1]
    np.testing.assert_allclose(
        np.asarray(step_logits), np.asarray(expected), rtol=1e-5, atol=1e-5
    )
    assert np.asarray(cache["length"]).tolist() == [prompt.shape[1] + 1] * 2


def test_generate_greedy_matches_naive_decoder(params):
    prompt = prompt_tokens()
    got = generate(params, prompt, 8, TINY)
    expected = naive_greedy(params, prompt, 8)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))
    assert got.dtype == jnp.int32 and got.shape == (2, 8)


def test_generate_jit_single_token_and_compiled_path(params):
    prompt = prompt_tokens()
    got = generate_jit(params, prompt, 1, TINY)
    expected = naive_greedy(params, prompt, 1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))


def test_generate_rejects_zero_tokens(params):
    with pytest.raises(ValueError, match="num_tokens"):
        generate(params, prompt_tokens(), 0, TINY)


def test_top_k_one_equals_greedy(params):
    # the top-1 truncation leaves only the argmax, so sampling at any
    # temperature reproduces the greedy sequence key-independently
    prompt = prompt_tokens()
    greedy = generate(params, prompt, 6, TINY)
    for seed in (0, 1):
        sampled = generate(
            params, prompt, 6, TINY, temperature=1.7,
            rng=jax.random.key(seed), top_k=1,
        )
        np.testing.assert_array_equal(np.asarray(sampled), np.asarray(greedy))


def test_top_p_tiny_equals_greedy(params):
    # nucleus with p -> 0 keeps only the highest-probability token
    prompt = prompt_tokens()
    greedy = generate(params, prompt, 6, TINY)
    sampled = generate(
        params, prompt, 6, TINY, temperature=1.3,
        rng=jax.random.key(3), top_p=1e-6,
    )
    np.testing.assert_array_equal(np.asarray(sampled), np.asarray(greedy))


def test_top_k_top_p_masks():
    from kube_sqs_autoscaler_tpu.workloads.decode import (
        _mask_top_k,
        _mask_top_p,
    )

    logits = jnp.log(jnp.array([[0.5, 0.25, 0.15, 0.1]], jnp.float32))
    # top-2 keeps exactly the two largest
    kept = np.isfinite(np.asarray(_mask_top_k(logits, 2)))
    assert kept.tolist() == [[True, True, False, False]]
    # p=0.7: {0.5} reaches only 0.5 < 0.7, so the second token is needed
    kept = np.isfinite(np.asarray(_mask_top_p(logits, 0.7)))
    assert kept.tolist() == [[True, True, False, False]]
    # p=1.0 keeps everything; surviving logits are untouched
    full = np.asarray(_mask_top_p(logits, 1.0))
    np.testing.assert_array_equal(full, np.asarray(logits))
    # the top token always survives even with p ~ 0
    kept = np.isfinite(np.asarray(_mask_top_p(logits, 1e-9)))
    assert kept.tolist() == [[True, False, False, False]]


def test_sampling_param_validation(params):
    from kube_sqs_autoscaler_tpu.workloads.decode import _pick

    logits = jnp.zeros((1, 8), jnp.float32)
    with pytest.raises(ValueError, match="top_p"):
        _pick(logits, jax.random.key(0), 1.0, top_p=0.0)
    with pytest.raises(ValueError, match="top_k"):
        _pick(logits, jax.random.key(0), 1.0, top_k=-1)
    # top_k past the vocab clamps to "keep everything" instead of crashing
    out = _pick(logits, jax.random.key(0), 1.0, top_k=100000)
    assert out.shape == (1,)

    # the serving surfaces fail fast, before any batch is traced: the
    # config at construction, the binary at flag-parse time
    from kube_sqs_autoscaler_tpu.workloads.__main__ import main
    from kube_sqs_autoscaler_tpu.workloads.service import ServiceConfig

    with pytest.raises(ValueError, match="top_p"):
        ServiceConfig(queue_url="q", top_p=0.0)
    with pytest.raises(ValueError, match="top_k"):
        ServiceConfig(queue_url="q", top_k=-1)
    with pytest.raises(SystemExit, match="top-p"):
        main(["--demo", "1", "--top-p", "0.0"])


def test_sampled_support_respects_top_k(params):
    # with temperature sampling over k=2, every generated token must come
    # from that step's two most likely tokens — check the first step
    prompt = prompt_tokens()
    logits, _ = prefill(params, prompt, TINY)
    top2 = np.asarray(jax.lax.top_k(logits, 2)[1])
    for seed in range(4):
        first = np.asarray(
            generate(params, prompt, 1, TINY, temperature=2.0,
                     rng=jax.random.key(seed), top_k=2)
        )[:, 0]
        for row in range(first.shape[0]):
            assert first[row] in top2[row]


def test_prefill_through_flash_attention_seam_matches_dense(params):
    import functools

    from kube_sqs_autoscaler_tpu.workloads.flash import flash_attention

    flash = functools.partial(flash_attention, interpret=True)
    prompt = prompt_tokens(length=16)  # tiles onto 16-wide blocks
    got, _ = prefill(params, prompt, TINY, attention_fn=flash)
    expected, _ = prefill(params, prompt, TINY)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expected), rtol=1e-4, atol=1e-4
    )


def test_generate_capacity_guard(params):
    prompt = prompt_tokens(length=30)
    with pytest.raises(ValueError, match="exceeds"):
        generate(params, prompt, 3, TINY)  # 30 + 3 > 32
    with pytest.raises(ValueError, match="exceeds"):
        prefill(params, prompt_tokens(length=33), TINY)


def test_sampling_is_deterministic_given_key_and_requires_rng(params):
    prompt = prompt_tokens()
    a = generate(params, prompt, 6, TINY, temperature=0.8,
                 rng=jax.random.key(7))
    b = generate(params, prompt, 6, TINY, temperature=0.8,
                 rng=jax.random.key(7))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert ((a >= 0) & (a < TINY.vocab_size)).all()
    with pytest.raises(ValueError, match="rng"):
        generate(params, prompt, 2, TINY, temperature=0.8)


def test_cache_positions_beyond_length_do_not_affect_logits(params):
    # garbage in unwritten cache slots must be fully masked out
    prompt = prompt_tokens()
    logits, cache = prefill(params, prompt, TINY)
    poisoned = {
        "layers": [
            {"k": lc["k"].at[:, :, -1].set(1e4), "v": lc["v"].at[:, :, -1].set(1e4)}
            for lc in cache["layers"]
        ],
        "length": cache["length"],
    }
    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    clean, _ = decode_step(params, cache, nxt, TINY)
    dirty, _ = decode_step(params, poisoned, nxt, TINY)
    np.testing.assert_allclose(np.asarray(clean), np.asarray(dirty))


def test_sharded_serving_matches_single_device(params):
    mesh = make_mesh(jax.devices()[:4], model_parallel=2, seq_parallel=1)
    prefill_fn, decode_fn, generate_fn = make_serving_fns(mesh, TINY, params)
    prompt = prompt_tokens(batch=4)

    lengths = jnp.full((prompt.shape[0],), prompt.shape[1], jnp.int32)
    expected = naive_greedy(params, prompt, 6)
    got = generate_fn(params, prompt, jax.random.key(0), lengths, 6)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))

    # sampling through the same compiled path: deterministic per key
    # (all args positional: pjit rejects kwargs when in_shardings is set)
    a = generate_fn(params, prompt, jax.random.key(3), lengths, 6, 0.9)
    b = generate_fn(params, prompt, jax.random.key(3), lengths, 6, 0.9)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # top-k/top-p ride the sharded contract too: top_k=1 is greedy
    k1 = generate_fn(params, prompt, jax.random.key(4), lengths, 6, 0.9, 1)
    np.testing.assert_array_equal(np.asarray(k1), np.asarray(expected))

    logits, cache = prefill_fn(params, prompt)
    ref_logits = forward(params, prompt, TINY)[:, -1]
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(ref_logits), rtol=1e-4, atol=1e-4
    )
    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    step_logits, cache = decode_fn(params, cache, nxt)
    full = jnp.concatenate([prompt, nxt[:, None]], axis=1)
    np.testing.assert_allclose(
        np.asarray(step_logits),
        np.asarray(forward(params, full, TINY)[:, -1]),
        rtol=1e-4, atol=1e-4,
    )


def test_sharded_generate_eos_matches_single_device(params):
    # eos through the sharded contract: identical to single-chip generate
    # with the same eos, finished rows pinned to the id (VERDICT r3 #4:
    # eos was previously rejected under --model-parallel)
    mesh = make_mesh(jax.devices()[:4], model_parallel=2, seq_parallel=1)
    _, _, generate_fn = make_serving_fns(mesh, TINY, params)
    prompt = prompt_tokens(batch=4)
    lengths = jnp.full((prompt.shape[0],), prompt.shape[1], jnp.int32)

    plain = np.asarray(
        generate_fn(params, prompt, jax.random.key(0), lengths, 6)
    )
    eos = int(plain[0, 1])  # fires early for row 0 by construction
    expected = np.asarray(generate(
        params, prompt, 6, TINY, eos_id=eos
    ))
    got = np.asarray(generate_fn(
        params, prompt, jax.random.key(0), lengths, 6, 0.0, 0, 1.0, eos
    ))
    np.testing.assert_array_equal(got, expected)
    row = got[0]
    hits = np.flatnonzero(row == eos)
    assert hits.size and (row[hits[0]:] == eos).all()


def test_serving_mesh_rejects_seq_axis(params):
    mesh = make_mesh(jax.devices(), model_parallel=2, seq_parallel=2)
    with pytest.raises(ValueError, match="seq"):
        make_serving_fns(mesh, TINY, params)


def test_sharded_int8_kv_generate_matches_single_chip(params):
    # the int8 cache's codes/scales shard by head over "model" exactly
    # like the bf16 cache (cache_shardings is layout-agnostic), so the
    # sharded quantized generate must be bitwise the single-chip
    # quantized generate (VERDICT r4 missing #3)
    mesh = make_mesh(jax.devices()[:4], model_parallel=2, seq_parallel=1)
    _, _, gen = make_serving_fns(mesh, TINY, params, quantized_cache=True)
    prompt = prompt_tokens(batch=4)
    lengths = jnp.full((prompt.shape[0],), prompt.shape[1], jnp.int32)
    got = np.asarray(gen(params, prompt, jax.random.key(0), lengths, 6,
                         0.0, 0, 1.0, 7))
    expected = np.asarray(generate_jit(
        params, prompt, 6, TINY, eos_id=7, quantized_cache=True,
        lengths=lengths,
    ))
    np.testing.assert_array_equal(got, expected)


def test_sharded_prefix_generate_matches_single_chip(params):
    # the shared prefix pins into the compiled sharded generate (heads
    # over "model", batch replicated); outputs must be bitwise the
    # single-chip prefix generate — and the int8 + prefix composition
    # holds too (VERDICT r4 missing #3)
    from kube_sqs_autoscaler_tpu.workloads.decode import (
        prefill_prefix,
        quantized_prefill_prefix,
    )

    mesh = make_mesh(jax.devices()[:4], model_parallel=2, seq_parallel=1)
    prompt = prompt_tokens(batch=4)
    lengths = jnp.full((prompt.shape[0],), prompt.shape[1], jnp.int32)
    prefix = jnp.arange(1, 9, dtype=jnp.int32)

    pc = prefill_prefix(params, prefix, TINY)
    _, _, gen = make_serving_fns(mesh, TINY, params, prefix_cache=pc)
    got = np.asarray(gen(params, prompt, jax.random.key(0), lengths, 6,
                         0.0, 0, 1.0, 7))
    expected = np.asarray(generate_jit(
        params, prompt, 6, TINY, eos_id=7, prefix_cache=pc,
        lengths=lengths,
    ))
    np.testing.assert_array_equal(got, expected)

    pc_q = quantized_prefill_prefix(params, prefix, TINY)
    _, _, gen_q = make_serving_fns(
        mesh, TINY, params, quantized_cache=True, prefix_cache=pc_q
    )
    got_q = np.asarray(gen_q(params, prompt, jax.random.key(0), lengths,
                             6, 0.0, 0, 1.0, 7))
    expected_q = np.asarray(generate_jit(
        params, prompt, 6, TINY, eos_id=7, quantized_cache=True,
        prefix_cache=pc_q, lengths=lengths,
    ))
    np.testing.assert_array_equal(got_q, expected_q)


def test_serving_factory_rejects_prefix_layout_mismatch(params):
    from kube_sqs_autoscaler_tpu.workloads.decode import prefill_prefix

    mesh = make_mesh(jax.devices()[:4], model_parallel=2, seq_parallel=1)
    pc = prefill_prefix(params, jnp.arange(1, 5, dtype=jnp.int32), TINY)
    with pytest.raises(ValueError, match="layout mismatch"):
        make_serving_fns(mesh, TINY, params, quantized_cache=True,
                         prefix_cache=pc)


def test_generate_rejects_attention_fn_with_prefix(params):
    # the prefix path prefills through the chunk decoder, which has no
    # attention override — passing both must raise, not silently ignore
    # the kernel pick (ADVICE r4)
    from kube_sqs_autoscaler_tpu.workloads.decode import prefill_prefix
    from kube_sqs_autoscaler_tpu.workloads.model import _dense_attention

    pc = prefill_prefix(params, jnp.arange(1, 5, dtype=jnp.int32), TINY)
    with pytest.raises(ValueError, match="attention_fn"):
        generate(params, prompt_tokens(), 4, TINY,
                 attention_fn=_dense_attention, prefix_cache=pc)


def test_ragged_prefill_readout_equals_unpadded(params):
    """The padded-batch contract: each right-padded row's prefill readout
    equals running that row alone, unpadded."""
    rng = jax.random.key(9)
    full = jax.random.randint(rng, (3, 16), 1, TINY.vocab_size, jnp.int32)
    lengths = jnp.asarray([5, 16, 9], jnp.int32)
    mask = jnp.arange(16)[None, :] < lengths[:, None]
    padded = jnp.where(mask, full, 0)

    batch_logits, cache = prefill(params, padded, TINY, lengths=lengths)
    assert cache["length"].shape == (3,)
    np.testing.assert_array_equal(np.asarray(cache["length"]),
                                  np.asarray(lengths))
    for i, n in enumerate([5, 16, 9]):
        solo_logits, _ = prefill(params, padded[i:i + 1, :n], TINY)
        np.testing.assert_allclose(
            np.asarray(batch_logits[i]), np.asarray(solo_logits[0]),
            rtol=1e-5, atol=1e-5,
        )


def test_ragged_generate_equals_unpadded(params):
    """Generate on a ragged padded batch == each prompt generated alone,
    unpadded — pads never attend, rows continue at their own positions."""
    rng = jax.random.key(11)
    full = jax.random.randint(rng, (3, 12), 1, TINY.vocab_size, jnp.int32)
    lengths = jnp.asarray([4, 12, 7], jnp.int32)
    mask = jnp.arange(12)[None, :] < lengths[:, None]
    padded = jnp.where(mask, full, 0)

    batch_out = generate(params, padded, 6, TINY, lengths=lengths)
    for i, n in enumerate([4, 12, 7]):
        solo = generate(params, padded[i:i + 1, :n], 6, TINY)
        np.testing.assert_array_equal(
            np.asarray(batch_out[i]), np.asarray(solo[0])
        )


def test_ragged_generate_llama_equals_unpadded():
    from kube_sqs_autoscaler_tpu.workloads.llama import (
        LlamaConfig,
        init_llama_params,
        llama_generate,
    )

    config = LlamaConfig(
        vocab_size=128, d_model=64, n_heads=4, n_kv_heads=2, n_layers=2,
        d_ff=128, max_seq_len=32, dtype=jnp.float32,
    )
    lparams = init_llama_params(jax.random.key(0), config)
    full = jax.random.randint(jax.random.key(13), (2, 10), 1, 128, jnp.int32)
    lengths = jnp.asarray([3, 10], jnp.int32)
    mask = jnp.arange(10)[None, :] < lengths[:, None]
    padded = jnp.where(mask, full, 0)

    batch_out = llama_generate(lparams, padded, 5, config, lengths=lengths)
    for i, n in enumerate([3, 10]):
        solo = llama_generate(lparams, padded[i:i + 1, :n], 5, config)
        np.testing.assert_array_equal(
            np.asarray(batch_out[i]), np.asarray(solo[0])
        )


def test_eos_stops_generation_and_pads(params):
    """Once a row emits eos_id, every later position is eos_id; rows
    that never emit it are unaffected (identical to the eos-free run)."""
    prompt = prompt_tokens()
    free = np.asarray(generate(params, prompt, 10, TINY))
    eos = int(free[0, 4])  # an id the model actually emits mid-sequence
    out = np.asarray(generate(params, prompt, 10, TINY, eos_id=eos))
    for row_free, row in zip(free, out):
        ids = row.tolist()
        if eos in ids:
            first = ids.index(eos)
            assert all(x == eos for x in ids[first:])
            # the prefix before the first eos matches the free run
            assert ids[:first] == row_free.tolist()[:first]
        else:
            assert ids == row_free.tolist()


def test_block_decode_matches_stepwise_with_masks(params):
    # block_decode's scan must equal a hand loop of decode_step + _pick
    # for live rows, freeze done/out-of-budget rows (length restored, no
    # budget spent), and report contiguous per-row emission counts
    from kube_sqs_autoscaler_tpu.workloads.decode import _pick, block_decode

    rng = np.random.default_rng(5)
    prompt = jnp.asarray(rng.integers(1, TINY.vocab_size, (3, 6)),
                         jnp.int32)
    logits, cache = prefill(params, prompt, TINY)
    first = _pick(logits, None, 0.0)
    # row 0: live with plenty of budget; row 1: one token left;
    # row 2: frozen from the start (done)
    done = jnp.asarray([False, False, True])
    remaining = jnp.asarray([4, 1, 4], jnp.int32)
    keys = jnp.zeros((3, 2), jnp.uint32)
    out_cache, current, out_done, out_remaining, tokens, counts = (
        block_decode(params, cache, first, done, remaining, keys, TINY)
    )
    np.testing.assert_array_equal(np.asarray(counts), [3, 1, 0])
    # reference: sequential single steps on a row-0-only view is
    # equivalent because rows never interact — walk the full batch but
    # only check live rows' tokens
    ref_cache, token = cache, first
    ref_tokens = []
    for _ in range(3):
        step_logits, ref_cache = decode_step(params, ref_cache, token, TINY)
        token = _pick(step_logits, None, 0.0)
        ref_tokens.append(np.asarray(token))
    np.testing.assert_array_equal(
        np.asarray(tokens)[:, 0], [t[0] for t in ref_tokens]
    )
    np.testing.assert_array_equal(np.asarray(tokens)[0, 1],
                                  ref_tokens[0][1])
    # frozen rows: length restored, budget unspent, current unchanged
    assert int(out_cache["length"][2]) == int(cache["length"][2])
    assert int(out_remaining[2]) == 4
    assert int(current[2]) == int(first[2])
    # row 1 spent its single token then froze one step later
    assert int(out_cache["length"][1]) == int(cache["length"][1]) + 1
    assert int(out_remaining[1]) == 0
    assert not bool(out_done[0]) and bool(out_done[2])


def test_block_decode_eos_freezes_row(params):
    from kube_sqs_autoscaler_tpu.workloads.decode import _pick, block_decode

    rng = np.random.default_rng(6)
    prompt = jnp.asarray(rng.integers(1, TINY.vocab_size, (2, 5)),
                         jnp.int32)
    logits, cache = prefill(params, prompt, TINY)
    first = _pick(logits, None, 0.0)
    # choose row 0's second greedy token as eos: it must emit eos (kept)
    # then freeze, while row 1 runs the full block
    probe = block_decode(
        params, cache, first, jnp.zeros((2,), bool),
        jnp.full((2,), 4, jnp.int32), jnp.zeros((4, 2), jnp.uint32), TINY,
    )
    probe_row0 = [int(t) for t in np.asarray(probe[4])[:, 0]]
    eos = probe_row0[1]
    # greedy chains repeat; the row freezes at the FIRST occurrence
    hits = probe_row0.index(eos) + 1
    _, _, done, remaining, tokens, counts = block_decode(
        params, cache, first, jnp.zeros((2,), bool),
        jnp.full((2,), 4, jnp.int32), jnp.zeros((4, 2), jnp.uint32), TINY,
        eos_id=eos,
    )
    counts = np.asarray(counts)
    # pre-eos tokens plus the eos itself — both kept, nothing after
    assert counts[0] == hits < 4
    assert int(np.asarray(tokens)[hits - 1, 0]) == eos
    assert bool(done[0])
    # remaining keeps the unspent budget
    assert int(remaining[0]) == 4 - hits
