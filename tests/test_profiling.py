"""Profiling tools: the no-op path costs nothing and imports no JAX, the
trace path writes an XLA trace, and span timing aggregates correctly on
an injected clock.
"""

import json
import subprocess
import sys
import threading

import pytest

from kube_sqs_autoscaler_tpu.utils.profiling import SpanTimer, maybe_trace


class ManualClock:
    def __init__(self):
        self.t = 0.0

    def monotonic(self):
        return self.t


def test_span_timer_aggregates_on_injected_clock():
    clock = ManualClock()
    timer = SpanTimer(clock=clock)
    for dt in (0.1, 0.3, 0.2):
        with timer.span("tick"):
            clock.t += dt
    s = timer.summary()["tick"]
    assert s["count"] == 3
    assert s["total_s"] == pytest.approx(0.6)
    assert s["mean_s"] == pytest.approx(0.2)
    assert s["p50_s"] == pytest.approx(0.2)
    assert s["max_s"] == pytest.approx(0.3)
    timer.reset()
    assert timer.summary() == {}


def test_span_timer_records_even_on_exception():
    clock = ManualClock()
    timer = SpanTimer(clock=clock)
    with pytest.raises(RuntimeError):
        with timer.span("bad"):
            clock.t += 1.0
            raise RuntimeError("boom")
    assert timer.summary()["bad"]["count"] == 1


def test_maybe_trace_none_is_noop_without_jax():
    # the controller-safe path: no profile dir, no jax import
    code = (
        "import sys\n"
        "base = 'jax' in sys.modules\n"
        "from kube_sqs_autoscaler_tpu.utils.profiling import maybe_trace\n"
        "with maybe_trace(None):\n"
        "    pass\n"
        "with maybe_trace(''):\n"
        "    pass\n"
        "assert ('jax' in sys.modules) == base, 'maybe_trace imported jax'\n"
        "print('ok')\n"
    )
    from pathlib import Path

    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        cwd=Path(__file__).resolve().parent.parent,
    )
    assert out.returncode == 0, out.stderr
    assert "ok" in out.stdout


def test_maybe_trace_writes_a_trace(tmp_path):
    import jax
    import jax.numpy as jnp

    with maybe_trace(str(tmp_path)):
        jnp.ones((8, 8)).sum().block_until_ready()
    written = list(tmp_path.rglob("*"))
    assert any(p.is_file() for p in written), "no trace files written"


def test_worker_profile_dir_traces_serve_loop(tmp_path):
    import jax
    import numpy as np

    from kube_sqs_autoscaler_tpu.metrics.fake import FakeMessageQueue
    from kube_sqs_autoscaler_tpu.workloads.model import (
        ModelConfig,
        init_params,
    )
    from kube_sqs_autoscaler_tpu.workloads.service import (
        QueueWorker,
        ServiceConfig,
    )

    tiny = ModelConfig(
        vocab_size=512, d_model=128, n_heads=4, n_layers=2, d_ff=256,
        max_seq_len=64,
    )
    queue = FakeMessageQueue()
    rng = np.random.default_rng(0)
    for _ in range(3):
        queue.send_message(
            "fake://q", json.dumps(rng.integers(0, 512, 16).tolist())
        )
    worker = QueueWorker(
        queue, init_params(jax.random.key(0), tiny), tiny,
        ServiceConfig(queue_url="fake://q", batch_size=4, seq_len=16,
                      profile_dir=str(tmp_path)),
    )
    t = threading.Thread(target=worker.run_forever)
    t.start()
    for _ in range(200):
        if worker.processed >= 3:
            break
        threading.Event().wait(0.05)
    worker.stop()
    t.join(timeout=10)
    assert worker.processed >= 3
    assert any(p.is_file() for p in tmp_path.rglob("*")), "no trace written"
    # cycle spans were recorded through the timer
    assert worker.timer.summary()["cycle"]["count"] >= 1


def test_two_profiled_workers_both_survive(tmp_path):
    # JAX allows one profiler session per process; the loser must log and
    # keep serving unprofiled (never-dies guarantee), not crash-loop
    import jax
    import numpy as np

    from kube_sqs_autoscaler_tpu.metrics.fake import FakeMessageQueue
    from kube_sqs_autoscaler_tpu.workloads.model import (
        ModelConfig,
        init_params,
    )
    from kube_sqs_autoscaler_tpu.workloads.service import (
        QueueWorker,
        ServiceConfig,
    )

    tiny = ModelConfig(
        vocab_size=512, d_model=128, n_heads=4, n_layers=2, d_ff=256,
        max_seq_len=64,
    )
    queue = FakeMessageQueue()
    rng = np.random.default_rng(1)
    for _ in range(8):
        queue.send_message(
            "fake://q", json.dumps(rng.integers(0, 512, 16).tolist())
        )
    params = init_params(jax.random.key(0), tiny)
    workers = [
        QueueWorker(
            queue, params, tiny,
            ServiceConfig(queue_url="fake://q", batch_size=2, seq_len=16,
                          profile_dir=str(tmp_path / f"w{i}")),
        )
        for i in range(2)
    ]
    threads = [threading.Thread(target=w.run_forever) for w in workers]
    for t in threads:
        t.start()
    for _ in range(200):
        if sum(w.processed for w in workers) >= 8:
            break
        threading.Event().wait(0.05)
    for w in workers:
        w.stop()
    for t in threads:
        t.join(timeout=10)
        assert not t.is_alive()
    assert sum(w.processed for w in workers) >= 8
