"""Closed-loop simulation tests: the autoscaler's actual raison d'être —
scale up under load, hold, scale back down after drain — asserted on
deterministic dynamics.
"""

import json
import subprocess
import sys

from kube_sqs_autoscaler_tpu.core.loop import LoopConfig
from kube_sqs_autoscaler_tpu.core.policy import PolicyConfig
from kube_sqs_autoscaler_tpu.sim import SimConfig, Simulation


def fast_policy(up=100, down=10, up_cool=10.0, down_cool=30.0, poll=5.0):
    return LoopConfig(
        poll_interval=poll,
        policy=PolicyConfig(
            scale_up_messages=up, scale_down_messages=down,
            scale_up_cooldown=up_cool, scale_down_cooldown=down_cool,
        ),
    )


def test_overloaded_queue_scales_up_to_capacity():
    # 50 msg/s in, 10 msg/s per replica: needs 5 replicas to keep up.
    sim = Simulation(
        SimConfig(
            arrival_rate=50.0, service_rate_per_replica=10.0, duration=600.0,
            initial_replicas=1, max_pods=8, loop=fast_policy(),
        )
    )
    result = sim.run()
    assert result.final_replicas >= 5
    # once at capacity the queue must stop growing
    assert result.final_depth < result.max_depth


def test_idle_queue_scales_down_to_min():
    sim = Simulation(
        SimConfig(
            arrival_rate=0.0, service_rate_per_replica=10.0, duration=600.0,
            initial_depth=0.0, initial_replicas=6, max_pods=8, min_pods=1,
            loop=fast_policy(),
        )
    )
    result = sim.run()
    assert result.final_replicas == 1
    assert result.final_depth == 0.0


def test_burst_then_drain_full_episode():
    # Burst for the first phase (high arrival), then arrivals stop by making
    # the arrival rate low relative to capacity: the pool should grow, drain
    # the backlog, then shrink back toward min.
    sim = Simulation(
        SimConfig(
            arrival_rate=8.0, service_rate_per_replica=10.0, duration=1200.0,
            initial_depth=5000.0, initial_replicas=1, max_pods=10,
            loop=fast_policy(),
        )
    )
    result = sim.run()
    replicas_over_time = [r for (_, _, r) in result.timeline]
    assert max(replicas_over_time) > 3  # grew under backlog
    assert result.final_depth == 0.0  # backlog fully drained
    assert result.final_replicas == 1  # shrank back to min afterwards


def test_cooldowns_bound_scaling_rate():
    # With a 10 s up-cooldown and 5 s poll, replica count can grow at most
    # once per 10 s: after 60 s from a huge backlog, <= 1 + 6 replicas.
    sim = Simulation(
        SimConfig(
            arrival_rate=1000.0, service_rate_per_replica=1.0, duration=60.0,
            initial_replicas=1, max_pods=50,
            loop=fast_policy(up_cool=10.0),
        )
    )
    result = sim.run()
    assert result.final_replicas <= 7


def test_replica_changes_counts_and_is_cached():
    sim = Simulation(
        SimConfig(
            arrival_rate=50.0, service_rate_per_replica=10.0, duration=300.0,
            initial_replicas=1, max_pods=8, loop=fast_policy(),
        )
    )
    result = sim.run()
    recount = sum(
        1
        for (_, _, a), (_, _, b) in zip(result.timeline, result.timeline[1:])
        if a != b
    )
    assert result.replica_changes == recount
    assert recount > 0  # the overloaded world must actually have scaled
    # cached_property contract: the first read is the answer — sweep
    # scoring reads it once per config and results are frozen once built
    result.timeline = []
    assert result.replica_changes == recount


def test_bench_prints_single_json_line():
    out = subprocess.run(
        [sys.executable, "bench.py"], capture_output=True, text=True,
        timeout=300, check=True, env=None,
    ).stdout.strip().splitlines()
    assert len(out) == 1
    payload = json.loads(out[0])
    assert set(payload) == {"metric", "value", "unit", "vs_baseline"}
    assert payload["metric"] == "controller_ticks_per_sec"
    assert payload["value"] > 100  # sanity: thousands expected, 100 is floor
    assert abs(payload["vs_baseline"] - payload["value"] / 0.2) < 1.0
