"""Fault-process + chaos-battery tests (sim/faults.py, sim/evaluate.py).

Everything is FakeClock-deterministic: the fault processes are pure
functions of virtual time, the injected episodes run the real
ControlLoop, and the battery assertions are exact re-runs of what
``bench.py --suite chaos`` gates on.
"""

import pytest

from kube_sqs_autoscaler_tpu.core.clock import FakeClock
from kube_sqs_autoscaler_tpu.core.resilience import ResilienceConfig
from kube_sqs_autoscaler_tpu.core.types import MetricError, ScaleError
from kube_sqs_autoscaler_tpu.sim.faults import (
    OK,
    Blackout,
    BurstyOutage,
    FaultyMetricSource,
    FaultyScaler,
    FlakyCalls,
    LatencySpikes,
    compose,
)
from kube_sqs_autoscaler_tpu.sim.scenarios import StepArrival
from kube_sqs_autoscaler_tpu.sim.simulator import SimConfig, Simulation


# --- fault processes --------------------------------------------------------


def test_blackout_window_half_open():
    fault = Blackout(start=10.0, duration=5.0, metric=True, scale=False)
    assert fault.metric_fault(9.99) is OK
    assert fault.metric_fault(10.0).error is not None
    assert fault.metric_fault(14.99).error is not None
    assert fault.metric_fault(15.0) is OK  # [start, start+duration)
    assert fault.scale_fault(12.0) is OK  # unaffected surface


def test_blackout_correlated_and_latency():
    fault = Blackout(start=0.0, duration=10.0, metric=True, scale=True,
                     latency=3.0)
    m, s = fault.metric_fault(5.0), fault.scale_fault(5.0)
    assert m.error is not None and s.error is not None
    assert m.latency == 3.0 and s.latency == 3.0


def test_bursty_outage_periodicity():
    fault = BurstyOutage(period=100.0, outage_len=20.0, first=50.0)
    assert fault.metric_fault(40.0) is OK  # before first
    assert fault.metric_fault(55.0).error is not None
    assert fault.metric_fault(75.0) is OK
    assert fault.metric_fault(155.0).error is not None  # next period
    with pytest.raises(ValueError):
        BurstyOutage(period=10.0, outage_len=20.0)


def test_flaky_calls_deterministic_per_instant():
    fault = FlakyCalls(failure_rate=0.5, seed=3)
    outcomes = [fault.metric_fault(t).error for t in range(100)]
    again = [fault.metric_fault(t).error for t in range(100)]
    assert outcomes == again  # pure function of (seed, surface, t)
    failures = sum(1 for e in outcomes if e is not None)
    assert 25 <= failures <= 75  # seeded Bernoulli near the rate
    # different instants draw independently (a retry gets a fresh draw)
    assert len({e is None for e in outcomes}) == 2


def test_flaky_calls_rate_extremes_and_validation():
    assert FlakyCalls(failure_rate=0.0).metric_fault(1.0) is OK
    assert FlakyCalls(failure_rate=1.0).metric_fault(1.0).error is not None
    with pytest.raises(ValueError):
        FlakyCalls(failure_rate=1.5)


def test_flaky_scale_surface_independent_of_metric():
    fault = FlakyCalls(failure_rate=0.5, seed=3, metric=True, scale=True)
    metric = [fault.metric_fault(t).error is None for t in range(200)]
    scale = [fault.scale_fault(t).error is None for t in range(200)]
    assert metric != scale  # the surfaces hash separately


def test_latency_spikes_succeed_slowly():
    fault = LatencySpikes(period=100.0, spike_len=10.0, delay=2.5)
    inside, outside = fault.metric_fault(5.0), fault.metric_fault(50.0)
    assert inside.error is None and inside.latency == 2.5
    assert outside is OK


def test_compose_merges_latency_and_first_error():
    both = compose(
        LatencySpikes(period=100.0, spike_len=100.0, delay=1.5),
        Blackout(start=0.0, duration=50.0, latency=2.0),
    )
    fault = both.metric_fault(10.0)
    assert fault.latency == 3.5  # latencies add
    assert "outage" in fault.error
    assert both.metric_fault(60.0).latency == 1.5  # spike only
    assert both.metric_fault(60.0).error is None


# --- injection wrappers -----------------------------------------------------


class _Inner:
    def __init__(self):
        self.polls = 0
        self.ups = 0

    def num_messages(self):
        self.polls += 1
        return 7

    def scale_up(self):
        self.ups += 1

    def scale_down(self):
        pass


def test_faulty_metric_source_raises_and_advances_world():
    clock = FakeClock()
    inner = _Inner()
    advanced = []
    source = FaultyMetricSource(
        inner,
        Blackout(start=0.0, duration=10.0, latency=2.0),
        clock,
        on_failure=lambda: advanced.append(clock.now()),
    )
    with pytest.raises(MetricError):
        source.num_messages()
    assert inner.polls == 0  # never reached the real source
    assert clock.now() == 2.0  # the failing call still cost its latency
    assert advanced == [2.0]  # world sampled at failure time
    clock.advance(10.0)
    assert source.num_messages() == 7  # healthy after the window


def test_faulty_scaler_raises_scale_error():
    clock = FakeClock()
    inner = _Inner()
    scaler = FaultyScaler(
        inner, Blackout(start=0.0, duration=5.0, metric=False, scale=True),
        clock,
    )
    with pytest.raises(ScaleError):
        scaler.scale_up()
    assert inner.ups == 0
    clock.advance(6.0)
    scaler.scale_up()
    assert inner.ups == 1


# --- closed-loop chaos episodes ---------------------------------------------


def _blackout_config(resilience):
    """Small fast blackout world: demand steps up, then the metric dies."""
    return SimConfig(
        arrival_rate=StepArrival(before=20.0, after=120.0, at=60.0),
        service_rate_per_replica=10.0,
        duration=400.0,
        initial_replicas=2,
        max_pods=20,
        faults=Blackout(start=90.0, duration=150.0, metric=True),
        resilience=resilience,
    )


def test_reference_freezes_during_blackout_resilient_does_not():
    reference = Simulation(_blackout_config(None)).run()
    resilient = Simulation(
        _blackout_config(ResilienceConfig(stale_depth_ttl=200.0))
    ).run()
    # the reference cannot scale while blind; the stale hold keeps
    # climbing toward the last observed backlog
    assert resilient.max_depth < reference.max_depth
    # replica trajectory during the outage window: frozen vs climbing
    def replicas_at(result, t):
        return max(r for (when, _, r) in result.timeline if when <= t)

    assert replicas_at(reference, 230.0) == replicas_at(reference, 95.0)
    assert replicas_at(resilient, 230.0) > replicas_at(resilient, 95.0)


def test_sim_timeline_tracks_unobserved_backlog():
    # even while every poll fails, the world keeps being sampled so
    # max_depth reflects the backlog the controller could not see
    result = Simulation(_blackout_config(None)).run()
    in_window = [d for (t, d, _) in result.timeline if 90.0 <= t < 240.0]
    assert in_window and max(in_window) > 0
    assert result.max_depth >= max(in_window)


def test_sim_config_defaults_keep_seed_behavior():
    # faults=None/resilience=None: byte-identical to the pre-chaos sim
    plain = Simulation(SimConfig(duration=100.0)).run()
    explicit = Simulation(
        SimConfig(duration=100.0, faults=None, resilience=None)
    ).run()
    assert plain.timeline == explicit.timeline
    assert plain.max_depth == explicit.max_depth


# --- the battery -------------------------------------------------------------


def test_chaos_battery_shape_and_verdicts():
    from kube_sqs_autoscaler_tpu.sim.evaluate import (
        chaos_battery,
        evaluate_chaos,
        summarize_chaos,
    )

    report = evaluate_chaos()
    names = {s.name for s in chaos_battery()}
    assert set(report) == names
    for row in report.values():
        for kind in ("reference", "resilient"):
            assert {"max_depth", "time_over_slo_s", "replica_changes",
                    "stale_ticks", "retries", "fail_static_ticks",
                    "breaker_open_ticks"} <= set(row[kind])
    summary = summarize_chaos(report)
    # the acceptance criteria, verbatim: at least one outage win, zero
    # no-fault regressions
    assert "metric-blackout" in summary["resilient_wins"]
    assert summary["no_fault_regressions"] == []
    # and the blackout win is substantial, not a rounding artifact
    blackout = report["metric-blackout"]
    assert blackout["resilient"]["max_depth"] < (
        0.5 * blackout["reference"]["max_depth"]
    )
    assert blackout["resilient"]["stale_ticks"] > 0
    assert blackout["reference"]["stale_ticks"] == 0


def test_chaos_calm_scenario_identical():
    from kube_sqs_autoscaler_tpu.sim.evaluate import (
        chaos_battery,
        run_chaos_episode,
        default_resilience,
    )

    calm = next(s for s in chaos_battery() if s.name == "calm")
    reference = run_chaos_episode(calm, resilience=None)
    resilient = run_chaos_episode(calm, resilience=default_resilience())
    assert reference == resilient  # invisible on a healthy world


def test_breaker_engages_in_actuation_outage():
    from kube_sqs_autoscaler_tpu.sim.evaluate import (
        chaos_battery,
        run_chaos_episode,
        default_resilience,
    )

    scenario = next(
        s for s in chaos_battery() if s.name == "actuation-outage"
    )
    row = run_chaos_episode(scenario, resilience=default_resilience())
    assert row["breaker_open_ticks"] > 0


# --- make chaos-demo ---------------------------------------------------------


def test_chaos_demo_exits_zero(capsys):
    import json

    from kube_sqs_autoscaler_tpu.sim.faults import main

    assert main([]) == 0
    verdict = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert verdict["ok"] is True
    assert verdict["stale_ticks"] > 0
    assert verdict["fail_static_ticks"] > 0
    assert verdict["breaker_transitions"][0] == "closed"
    assert "open" in verdict["breaker_transitions"]
    assert verdict["breaker_transitions"][-1] == "closed"


def test_chaos_demo_detects_bad_trajectory():
    # hand the checker a trajectory with no stale ticks: it must complain
    from kube_sqs_autoscaler_tpu.core.events import TickRecord
    from kube_sqs_autoscaler_tpu.sim.faults import _check_demo
    from kube_sqs_autoscaler_tpu.sim.simulator import SimResult

    records = [TickRecord(start=float(i) * 5.0, num_messages=1)
               for i in range(10)]
    result = SimResult(
        timeline=[(float(i) * 5.0, 1, 1) for i in range(10)],
        final_replicas=1, final_depth=0.0, max_depth=1.0, ticks=10,
    )
    problems = _check_demo(records, result)
    assert any("stale" in p for p in problems)
    assert any("breaker" in p for p in problems)


def test_summarize_chaos_identifies_controls_by_fault_provenance():
    # a custom battery whose healthy control is NOT named "calm": the
    # summary must still treat it as a control (regression check), never
    # as a resilience win
    from kube_sqs_autoscaler_tpu.sim.evaluate import summarize_chaos

    report = {
        "baseline": {
            "reference": {"max_depth": 10.0, "time_over_slo_s": 0.0,
                          "replica_changes": 2, "faulted": False},
            "resilient": {"max_depth": 8.0, "time_over_slo_s": 0.0,
                          "replica_changes": 2, "faulted": False},
        },
        "outage": {
            "reference": {"max_depth": 100.0, "time_over_slo_s": 50.0,
                          "replica_changes": 2, "faulted": True},
            "resilient": {"max_depth": 40.0, "time_over_slo_s": 10.0,
                          "replica_changes": 3, "faulted": True},
        },
    }
    summary = summarize_chaos(report)
    assert summary["no_fault_scenarios"] == ["baseline"]
    assert summary["no_fault_regressions"] == ["baseline"]  # it changed!
    assert summary["resilient_wins"] == ["outage"]


def test_fleet_fault_plan_applies_at_scheduled_cycles():
    from kube_sqs_autoscaler_tpu.sim.faults import FleetFaultPlan

    class _PoolSpy:
        def __init__(self):
            self.killed = []
            self.hung = []

        def kill_worker(self, index):
            self.killed.append(index)

        def hang_worker(self, index):
            self.hung.append(index)

    plan = FleetFaultPlan(kills=((3, 1), (5, 0)), hangs=((3, 2),))
    assert plan.indices() == {0, 1, 2}
    pool = _PoolSpy()
    for cycle in range(7):
        plan.apply(cycle, pool)
    assert pool.killed == [1, 0]
    assert pool.hung == [2]


def test_fleet_fault_plan_is_deterministic_and_frozen():
    import dataclasses

    from kube_sqs_autoscaler_tpu.sim.faults import FleetFaultPlan

    plan = FleetFaultPlan(kills=((1, 0),))
    assert dataclasses.is_dataclass(plan)
    with __import__("pytest").raises(dataclasses.FrozenInstanceError):
        plan.kills = ()
