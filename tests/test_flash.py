"""Pallas flash-attention kernel vs the dense reference implementation.

Runs the real kernel code path in Pallas interpret mode on CPU (conftest
pins JAX_PLATFORMS=cpu), so these tests validate the exact kernel that
compiles on TPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kube_sqs_autoscaler_tpu.workloads.flash import (
    attention_fn_for,
    flash_attention,
)
from kube_sqs_autoscaler_tpu.workloads.model import (
    ModelConfig,
    _dense_attention,
    forward,
    init_params,
)


def make_qkv(batch, heads, seq, dim, dtype, seed=0):
    keys = jax.random.split(jax.random.key(seed), 3)
    shape = (batch, heads, seq, dim)
    return tuple(
        (jax.random.normal(key, shape, jnp.float32) / dim**0.25).astype(dtype)
        for key in keys
    )


@pytest.mark.parametrize("seq,block_q,block_k", [
    (128, 128, 128),
    (256, 128, 128),
    (256, 64, 128),
    (256, 128, 64),
    (192, 64, 64),  # q/k blocks that don't divide each other's diagonal
])
def test_flash_matches_dense_fp32(seq, block_q, block_k):
    q, k, v = make_qkv(2, 2, seq, 64, jnp.float32)
    expected = _dense_attention(q, k, v)
    got = flash_attention(q, k, v, block_q=block_q, block_k=block_k)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               atol=1e-5, rtol=1e-5)


def test_flash_matches_dense_bf16():
    q, k, v = make_qkv(2, 4, 256, 64, jnp.bfloat16)
    expected = _dense_attention(q, k, v).astype(jnp.float32)
    got = flash_attention(q, k, v).astype(jnp.float32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               atol=3e-2, rtol=3e-2)


def test_flash_is_causal():
    """Output at position t must not depend on tokens after t."""
    q, k, v = make_qkv(1, 1, 128, 64, jnp.float32)
    out = flash_attention(q, k, v)
    # perturb the second half of k/v: first half of output must not move
    k2 = k.at[:, :, 64:, :].set(k[:, :, 64:, :] * -3.0 + 1.0)
    v2 = v.at[:, :, 64:, :].set(v[:, :, 64:, :] * 5.0 - 2.0)
    out2 = flash_attention(q, k2, v2)
    np.testing.assert_array_equal(
        np.asarray(out[:, :, :64, :]), np.asarray(out2[:, :, :64, :])
    )
    assert not np.allclose(
        np.asarray(out[:, :, 64:, :]), np.asarray(out2[:, :, 64:, :])
    )


def test_flash_non_causal_attends_everywhere():
    q, k, v = make_qkv(1, 2, 128, 64, jnp.float32)
    head_dim = q.shape[-1]
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / head_dim**0.5
    probs = jax.nn.softmax(scores, axis=-1)
    expected = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
    got = flash_attention(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               atol=1e-5, rtol=1e-5)


def test_flash_rejects_non_tiling_seq():
    q, k, v = make_qkv(1, 1, 96, 64, jnp.float32)
    with pytest.raises(ValueError, match="not divisible"):
        flash_attention(q, k, v, block_q=64, block_k=64)


def test_attention_fn_for_dispatch():
    from kube_sqs_autoscaler_tpu.workloads.flash import FLASH_MIN_SEQ

    assert attention_fn_for(FLASH_MIN_SEQ, backend="tpu") is flash_attention
    assert attention_fn_for(4096, backend="tpu") is flash_attention
    # below the measured crossover dense wins: never pick the kernel there
    assert attention_fn_for(FLASH_MIN_SEQ // 2,
                            backend="tpu") is _dense_attention
    assert attention_fn_for(64, backend="tpu") is _dense_attention  # small
    assert attention_fn_for(1200, backend="tpu") is _dense_attention  # odd
    # off TPU the kernel would run in the Python-speed interpreter: never
    # auto-dispatch it onto a serving hot path
    assert attention_fn_for(FLASH_MIN_SEQ, backend="cpu") is _dense_attention
    assert attention_fn_for(FLASH_MIN_SEQ) is _dense_attention  # CPU suite


def test_block_auto_selection():
    from kube_sqs_autoscaler_tpu.workloads.flash import _pick_block

    assert _pick_block(4096, None) == 1024  # long S: the fast v5e tile
    assert _pick_block(2048, None) == 1024
    assert _pick_block(640, None) == 128  # halves until it divides S
    assert _pick_block(384, None) == 128  # power-of-two only above 128
    assert _pick_block(256, None) == 256
    assert _pick_block(96, None) == 96  # short S: clamp to S itself
    assert _pick_block(64, None) == 64
    # non-dividing S -> 128, so flash_attention raises its clean ValueError
    assert _pick_block(136, None) == 128
    assert _pick_block(2048, 128) == 128  # explicit request wins
    assert _pick_block(64, 128) == 64  # ...clamped to S


def make_gqa_qkv(batch, heads, kv_heads, seq, dim, dtype, seed=0):
    keys = jax.random.split(jax.random.key(seed), 3)
    shapes = [(batch, heads, seq, dim)] + [(batch, kv_heads, seq, dim)] * 2
    return tuple(
        (jax.random.normal(key, s, jnp.float32) / dim**0.25).astype(dtype)
        for key, s in zip(keys, shapes)
    )


def test_flash_gqa_matches_broadcast_dense():
    """GQA-native kernel path == repeat_kv + dense (the claim in
    llama.py that the compact k/v stream straight into the kernel)."""
    from kube_sqs_autoscaler_tpu.workloads.llama import repeat_kv

    q, k, v = make_gqa_qkv(2, 8, 2, 256, 64, jnp.float32)
    expected = _dense_attention(q, repeat_kv(k, 4), repeat_kv(v, 4))
    got = flash_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               atol=1e-5, rtol=1e-5)


def test_flash_rejects_non_dividing_kv_heads():
    q, k, v = make_gqa_qkv(1, 8, 3, 128, 64, jnp.float32)
    with pytest.raises(ValueError, match="kv heads"):
        flash_attention(q, k, v)


def test_flash_grad_matches_dense_grad():
    """The Pallas backward kernels (dq, dk/dv) against autodiff through
    the dense path — what makes flash usable on the training hot path."""
    q, k, v = make_qkv(1, 2, 128, 64, jnp.float32)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(_dense_attention(q, k, v) ** 2)

    got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    expected = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for g, e in zip(got, expected):
        np.testing.assert_allclose(np.asarray(g), np.asarray(e),
                                   atol=5e-4, rtol=1e-3)


def test_flash_grad_gqa_accumulates_groups():
    """dk/dv must sum over the query heads of each group (the folded
    grid axis in the dkv kernel) — checked against the broadcast path."""
    from kube_sqs_autoscaler_tpu.workloads.llama import repeat_kv

    q, k, v = make_gqa_qkv(1, 4, 2, 128, 64, jnp.float32)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(_dense_attention(q, repeat_kv(k, 2), repeat_kv(v, 2)) ** 2)

    got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    expected = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for g, e in zip(got, expected):
        assert g.shape == e.shape  # dk/dv stay compact [B, H_kv, S, D]
        np.testing.assert_allclose(np.asarray(g), np.asarray(e),
                                   atol=5e-4, rtol=1e-3)


def test_flash_grad_non_causal_and_uneven_blocks():
    q, k, v = make_qkv(1, 2, 192, 64, jnp.float32)

    def loss_flash(q, k, v):
        out = flash_attention(
            q, k, v, block_q=64, block_k=64, causal=False
        )
        return jnp.sum(out * jnp.arange(64.0))

    def loss_dense(q, k, v):
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / 8.0
        probs = jax.nn.softmax(scores, -1)
        return jnp.sum(
            jnp.einsum("bhqk,bhkd->bhqd", probs, v) * jnp.arange(64.0)
        )

    got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    expected = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for g, e in zip(got, expected):
        np.testing.assert_allclose(np.asarray(g), np.asarray(e),
                                   atol=5e-4, rtol=1e-3)


def test_sharded_attention_matches_dense_on_mesh():
    """make_sharded_attention (the train-path dispatcher) == dense, for
    both MHA and GQA shapes, on the virtual 8-device mesh."""
    from kube_sqs_autoscaler_tpu.workloads.flash import make_sharded_attention
    from kube_sqs_autoscaler_tpu.workloads.llama import repeat_kv
    from kube_sqs_autoscaler_tpu.workloads.train import make_mesh

    mesh = make_mesh(jax.devices(), model_parallel=2, seq_parallel=1)
    attend = make_sharded_attention(mesh)
    assert attend.gqa_native

    q, k, v = make_qkv(4, 2, 128, 64, jnp.float32)
    np.testing.assert_allclose(
        np.asarray(attend(q, k, v)), np.asarray(_dense_attention(q, k, v)),
        atol=1e-5, rtol=1e-5,
    )
    q, k, v = make_gqa_qkv(4, 4, 2, 128, 64, jnp.float32)
    expected = _dense_attention(q, repeat_kv(k, 2), repeat_kv(v, 2))
    np.testing.assert_allclose(
        np.asarray(attend(q, k, v)), np.asarray(expected),
        atol=1e-5, rtol=1e-5,
    )
    # non-dividing shapes fall back to the plain XLA path (batch 3 does
    # not divide the data axis) rather than failing shard_map's check
    q, k, v = make_qkv(3, 2, 64, 16, jnp.float32)
    np.testing.assert_allclose(
        np.asarray(attend(q, k, v)), np.asarray(_dense_attention(q, k, v)),
        atol=1e-5, rtol=1e-5,
    )


def test_forward_with_flash_matches_dense_forward():
    """End-to-end through the model's attention_fn seam."""
    config = ModelConfig(
        vocab_size=512, d_model=128, n_heads=2, n_layers=2, d_ff=256,
        max_seq_len=128,
    )
    params = init_params(jax.random.key(0), config)
    tokens = jax.random.randint(jax.random.key(1), (2, 128), 0, 512, jnp.int32)
    dense_logits = forward(params, tokens, config)
    flash_logits = forward(params, tokens, config, attention_fn=flash_attention)
    np.testing.assert_allclose(
        np.asarray(flash_logits), np.asarray(dense_logits), atol=0.5, rtol=3e-2
    )
    # same greedy decode — the observable behavior of the worker service
    np.testing.assert_array_equal(
        np.asarray(jnp.argmax(dense_logits[:, -1, :], -1)),
        np.asarray(jnp.argmax(flash_logits[:, -1, :], -1)),
    )


# ------------------------------------------------- composable (out, lse)


def _ref_attention_lse(q, k, v, mask=None):
    """Pure-jnp reference: softmax attention + per-row logsumexp."""
    scores = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) / (q.shape[-1] ** 0.5)
    if mask is not None:
        scores = jnp.where(mask, scores, -jnp.inf)
    lse = jax.scipy.special.logsumexp(scores, axis=-1)
    probs = jnp.exp(scores - lse[..., None]).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v), lse


def _rand_qkv(key, batch=2, heads=2, q_len=16, k_len=16, dim=8):
    ks = jax.random.split(key, 3)
    q = jax.random.normal(ks[0], (batch, heads, q_len, dim), jnp.float32)
    k = jax.random.normal(ks[1], (batch, heads, k_len, dim), jnp.float32)
    v = jax.random.normal(ks[2], (batch, heads, k_len, dim), jnp.float32)
    return q, k, v


def test_flash_lse_matches_reference_full_and_causal():
    from kube_sqs_autoscaler_tpu.workloads.flash import flash_attention_lse

    q, k, v = _rand_qkv(jax.random.key(0))
    out, lse = flash_attention_lse(q, k, v, causal=False, interpret=True)
    ref_out, ref_lse = _ref_attention_lse(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse),
                               rtol=1e-5, atol=1e-5)

    causal = jnp.tril(jnp.ones((16, 16), bool))
    out, lse = flash_attention_lse(q, k, v, causal=True, interpret=True)
    ref_out, ref_lse = _ref_attention_lse(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse),
                               rtol=1e-5, atol=1e-5)


def test_flash_lse_rectangular_with_q_shift():
    from kube_sqs_autoscaler_tpu.workloads.flash import flash_attention_lse

    # q rows sit at causal positions 8..15 against 16 keys (the ring
    # "later queries attend both chunks" shape)
    q, k, v = _rand_qkv(jax.random.key(1), q_len=8, k_len=16)
    out, lse = flash_attention_lse(q, k, v, causal=True, q_shift=8,
                                   interpret=True)
    rows = jnp.arange(8)[:, None] + 8
    cols = jnp.arange(16)[None, :]
    ref_out, ref_lse = _ref_attention_lse(q, k, v, rows >= cols)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse),
                               rtol=1e-5, atol=1e-5)


def test_merge_partials_reconstructs_full_attention():
    from kube_sqs_autoscaler_tpu.workloads.flash import (
        MERGE_NEG_INF,
        flash_attention_lse,
        merge_attention_partials,
    )

    q, k, v = _rand_qkv(jax.random.key(2), q_len=16, k_len=32)
    # split keys in half, compute two rectangular partials, merge
    out_a, lse_a = flash_attention_lse(q, k[:, :, :16], v[:, :, :16],
                                       causal=False, interpret=True)
    out_b, lse_b = flash_attention_lse(q, k[:, :, 16:], v[:, :, 16:],
                                       causal=False, interpret=True)
    acc = jnp.zeros(q.shape, jnp.float32)
    acc_lse = jnp.full(lse_a.shape, MERGE_NEG_INF)
    acc, acc_lse = merge_attention_partials(acc, acc_lse, out_a, lse_a)
    acc, acc_lse = merge_attention_partials(acc, acc_lse, out_b, lse_b)

    ref_out, ref_lse = _ref_attention_lse(q, k, v)
    np.testing.assert_allclose(np.asarray(acc), np.asarray(ref_out),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(acc_lse), np.asarray(ref_lse),
                               rtol=1e-5, atol=1e-5)


def test_flash_lse_gradients_match_reference_through_merge():
    from kube_sqs_autoscaler_tpu.workloads.flash import (
        MERGE_NEG_INF,
        flash_attention_lse,
        merge_attention_partials,
    )

    q, k, v = _rand_qkv(jax.random.key(3), q_len=16, k_len=32)

    def merged_loss(q, k, v):
        out_a, lse_a = flash_attention_lse(q, k[:, :, :16], v[:, :, :16],
                                           causal=False, interpret=True)
        out_b, lse_b = flash_attention_lse(q, k[:, :, 16:], v[:, :, 16:],
                                           causal=False, interpret=True)
        acc = jnp.zeros(q.shape, jnp.float32)
        acc_lse = jnp.full(lse_a.shape, MERGE_NEG_INF)
        acc, acc_lse = merge_attention_partials(acc, acc_lse, out_a, lse_a)
        acc, acc_lse = merge_attention_partials(acc, acc_lse, out_b, lse_b)
        return jnp.mean(acc**2)

    def ref_loss(q, k, v):
        out, _ = _ref_attention_lse(q, k, v)
        return jnp.mean(out.astype(jnp.float32) ** 2)

    got = jax.grad(merged_loss, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for g, w, name in zip(got, want, "qkv"):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), rtol=1e-4, atol=1e-5,
            err_msg=f"d{name}",
        )
