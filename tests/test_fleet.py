"""Fleet tests: crash-safe serving replicas behind the actuator seam.

Tier-1 (tiny model, CPU): spin-up weight/engine sharing, kill →
re-dispatch losslessness, reply dedup under visibility-timeout
redelivery, graceful drain + drain-timeout release, hang detection,
the ContinuousWorker lifecycle hardening pins, and the fleet
observability surfaces (labeled gauges, trace instants).
"""

from __future__ import annotations

import json
import threading
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from kube_sqs_autoscaler_tpu.core.clock import FakeClock  # noqa: E402
from kube_sqs_autoscaler_tpu.fleet import (  # noqa: E402
    DEAD,
    DRAINING,
    SERVING,
    STOPPED,
    FleetDriver,
    WorkerPool,
)
from kube_sqs_autoscaler_tpu.fleet.worker import FleetWorker  # noqa: E402
from kube_sqs_autoscaler_tpu.metrics.fake import FakeMessageQueue  # noqa: E402
from kube_sqs_autoscaler_tpu.sim.faults import FleetFaultPlan  # noqa: E402
from kube_sqs_autoscaler_tpu.workloads.continuous import (  # noqa: E402
    ContinuousBatcher,
    ContinuousWorker,
)
from kube_sqs_autoscaler_tpu.workloads.model import (  # noqa: E402
    ModelConfig,
    init_params,
)
from kube_sqs_autoscaler_tpu.workloads.service import (  # noqa: E402
    ServiceConfig,
    collect_replies,
)

BATCH, PROMPT, TOKENS, BLOCK = 2, 4, 8, 2


@pytest.fixture(scope="module")
def model():
    return ModelConfig(
        vocab_size=64, d_model=16, n_heads=2, n_layers=2, d_ff=32,
        max_seq_len=PROMPT + TOKENS, dtype=jnp.float32,
    )


@pytest.fixture(scope="module")
def params(model):
    return init_params(jax.random.key(0), model)


def _config(**overrides):
    base = dict(
        queue_url="t://q", batch_size=BATCH, seq_len=PROMPT,
        generate_tokens=TOKENS, decode_block=BLOCK,
        result_queue_url="t://r",
    )
    base.update(overrides)
    return ServiceConfig(**base)


@pytest.fixture(scope="module")
def donor(model, params):
    """One warmed engine the whole module's pools adopt — every test
    shares a single set of compiled programs."""
    worker = FleetWorker(
        FakeMessageQueue(), params, model, _config(result_queue_url="")
    )
    return worker.batcher


def make_fleet(model, params, donor, *, messages, min=1, max=2,
               initial=None, clock=None, visibility=30.0, **pool_kwargs):
    now_fn = clock.now if clock is not None else None
    queue = FakeMessageQueue(visibility_timeout=visibility, now_fn=now_fn)
    results = FakeMessageQueue(now_fn=now_fn)
    rng = np.random.default_rng(3)
    sent = [
        queue.send_message(
            "t://q", json.dumps(rng.integers(1, 64, 3).tolist())
        )
        for _ in range(messages)
    ]
    pool = WorkerPool.serving(
        queue, params, model, _config(), result_queue=results,
        min=min, max=max, initial=initial, engine_source=donor,
        clock=clock, **pool_kwargs,
    )
    return pool, queue, results, sent


def drive(pool, *, until, max_cycles=400, after_cycle=None):
    for _ in range(max_cycles):
        pool.run_cycle()
        if after_cycle is not None:
            after_cycle()
        if until():
            return
    raise AssertionError("fleet did not converge within the cycle budget")


def all_done(pool, sent):
    return pool.processed >= len(sent) and pool.idle


# ---------------------------------------------------------------------------
# Spin-up: shared weights, adopted engine (no rebuild, no recompile)
# ---------------------------------------------------------------------------


def test_spinup_shares_params_and_engine(model, params, donor):
    pool, _, _, _ = make_fleet(model, params, donor, messages=0,
                               min=1, max=3)
    pool.scale_up()
    pool.scale_up()
    assert pool.replicas == 3
    batchers = [r.worker.batcher for r in pool.members]
    assert all(b.params is params for b in batchers)  # shared, not rebuilt
    assert all(b._insert_many is donor._insert_many for b in batchers)
    assert all(b._block_fn is donor._block_fn for b in batchers)
    # per-replica state is NOT shared: each replica owns its cache
    assert len({id(b.cache["length"]) for b in batchers}) == 3


def test_replicas_get_distinct_sample_seeds(model, params, donor):
    # one shared seed would make every sampled replica replay the same
    # PRNG stream; spin-up derives sample_seed + spawn_ordinal instead
    pool, _, _, _ = make_fleet(model, params, donor, messages=0,
                               min=1, max=3, initial=3)
    seeds = [r.worker.config.sample_seed for r in pool.members]
    assert len(set(seeds)) == 3


def test_adopt_engine_rejects_mismatched_knobs(model, params, donor):
    other = ContinuousBatcher(
        params, model, batch_size=BATCH, prompt_len=PROMPT,
        generate_tokens=TOKENS - 1, decode_block=BLOCK,
    )
    with pytest.raises(ValueError, match="engine mismatch"):
        other.adopt_engine(donor)


def test_adopt_engine_rejects_beam_paths(model, params, donor):
    beam = ContinuousBatcher(
        params, model, batch_size=BATCH, prompt_len=PROMPT,
        generate_tokens=TOKENS, beams=2,
    )
    with pytest.raises(ValueError, match="plain decode path"):
        beam.adopt_engine(donor)


# ---------------------------------------------------------------------------
# Kill → re-dispatch: lossless failover
# ---------------------------------------------------------------------------


def test_kill_redispatches_inflight_lossless(model, params, donor):
    pool, _, results, sent = make_fleet(
        model, params, donor, messages=6, min=1, max=2, initial=2,
    )
    pool.run_cycle()  # both replicas admit a full batch
    victim = pool.members[1]
    assert victim.worker.batcher.active > 0
    pool.kill_worker(1)
    drive(pool, until=lambda: all_done(pool, sent))
    assert victim.state == DEAD
    assert pool.redispatched_total > 0
    # failover freed the dead replica's slots: its orphaned requests
    # must not keep counting as active anywhere but the survivor
    assert victim.worker.batcher.active == 0
    replies, duplicates = collect_replies(results, "t://r")
    assert set(replies) == set(sent)  # zero lost
    assert duplicates == 0  # zero duplicated
    # degraded, not stalled: the fleet kept serving with fewer replicas
    assert pool.replicas == 1
    events = [e.name for e in pool.events]
    assert "replica-kill" in events and "redispatch" in events


def test_fault_plan_drives_kill_deterministically(model, params, donor):
    pool, _, results, sent = make_fleet(
        model, params, donor, messages=6, min=1, max=2, initial=2,
    )
    plan = FleetFaultPlan(kills=((1, 0),))
    driver = FleetDriver(pool, fault_plan=plan)
    driver.run(until=lambda: all_done(pool, sent), max_cycles=400)
    assert pool.members[0].state == DEAD
    replies, duplicates = collect_replies(results, "t://r")
    assert set(replies) == set(sent)
    assert duplicates == 0


def test_hang_detection_declares_dead_and_recovers(model, params, donor):
    pool, _, results, sent = make_fleet(
        model, params, donor, messages=6, min=1, max=2, initial=2,
        hang_grace_cycles=3,
    )
    pool.run_cycle()
    pool.hang_worker(1)
    drive(pool, until=lambda: all_done(pool, sent))
    assert pool.members[1].state == DEAD
    kill_events = [e for e in pool.events if e.name == "replica-kill"]
    assert kill_events and kill_events[0].args["cause"] == "hung"
    replies, duplicates = collect_replies(results, "t://r")
    assert set(replies) == set(sent)
    assert duplicates == 0


# ---------------------------------------------------------------------------
# Reply dedup: visibility-timeout redelivery can never double-count
# ---------------------------------------------------------------------------


def test_redelivered_request_answered_exactly_once(model, params, donor):
    clock = FakeClock()
    pool, queue, results, sent = make_fleet(
        model, params, donor, messages=1, min=1, max=1,
        clock=clock, visibility=0.5,
    )
    pool.run_cycle()  # admit the request (in-flight deadline now+0.5)
    clock.advance(1.0)  # expire its visibility mid-service: redelivery
    drive(
        pool,
        until=lambda: pool.idle and queue.get_queue_attributes("t://q", [])
        ["ApproximateNumberOfMessages"] == "0",
    )
    # both copies were served; exactly one reply reached the consumer
    assert pool.duplicates_suppressed >= 1
    replies, duplicates = collect_replies(results, "t://r")
    assert set(replies) == set(sent)
    assert duplicates == 0
    # suppressed duplicates do not count as settled work: completion
    # criteria count UNIQUE requests answered
    assert pool.processed == len(sent)


def test_collect_replies_dedups_redelivered_reply(model, params):
    # satellite regression: a reply RECEIVED but not deleted (a consumer
    # crash) reappears after the visibility timeout; collection must
    # count it once — and delete it so it can never reappear again
    clock = FakeClock()
    results = FakeMessageQueue(visibility_timeout=1.0, now_fn=clock.now)
    results.send_message(
        "t://r", json.dumps({"request_id": "m-1", "tokens": [1, 2]})
    )
    first = results.receive_messages("t://r", max_messages=1)
    assert first and not results.receive_messages("t://r")  # in flight
    clock.advance(2.0)  # crashed consumer: the reply redelivers
    replies, duplicates = collect_replies(results, "t://r")
    assert list(replies) == ["m-1"]
    assert duplicates == 0
    clock.advance(5.0)
    assert results.receive_messages("t://r") == []  # deleted for good


def test_collect_replies_counts_true_duplicates():
    results = FakeMessageQueue()
    for _ in range(2):  # two replicas answered the same request
        results.send_message(
            "t://r", json.dumps({"request_id": "m-7", "tokens": [3]})
        )
    replies, duplicates = collect_replies(results, "t://r")
    assert list(replies) == ["m-7"]
    assert duplicates == 1


def test_redelivered_tenant_request_counted_once_per_tenant(
    model, params, donor
):
    # the PR 6 redelivery episode with tenant labels: per-tenant
    # completion counts must stay exactly-once on the at-least-once
    # substrate — the pool registry suppresses the redelivered twin
    # BEFORE the worker's tenant counter, and the reply-side
    # tenant_completions counts deduped replies, never raw messages
    from kube_sqs_autoscaler_tpu.workloads.service import (
        tenant_completions,
    )
    from kube_sqs_autoscaler_tpu.workloads.tenancy import TenancyConfig

    clock = FakeClock()
    queue = FakeMessageQueue(visibility_timeout=0.5, now_fn=clock.now)
    results = FakeMessageQueue(now_fn=clock.now)
    rng = np.random.default_rng(7)
    sent = {}
    for tenant in ("alpha", "beta"):
        mid = queue.send_message("t://q", json.dumps(
            {"tenant": tenant, "ids": rng.integers(1, 64, 3).tolist()}
        ))
        sent[mid] = tenant
    pool = WorkerPool.serving(
        queue, params, model, _config(), result_queue=results,
        min=1, max=1, engine_source=donor, clock=clock,
        tenancy=TenancyConfig(tenants=("alpha", "beta")),
    )
    pool.run_cycle()  # admit both (visibility deadline now + 0.5)
    clock.advance(1.0)  # expire mid-service: both copies redeliver
    drive(
        pool,
        until=lambda: pool.idle and queue.get_queue_attributes("t://q", [])
        ["ApproximateNumberOfMessages"] == "0",
    )
    assert pool.duplicates_suppressed >= 1
    replies, duplicates = collect_replies(results, "t://r")
    assert set(replies) == set(sent)
    assert duplicates == 0
    assert pool.completed_by_tenant == {"alpha": 1, "beta": 1}
    assert tenant_completions(replies) == {"alpha": 1, "beta": 1}
    assert pool.processed == len(sent)


# ---------------------------------------------------------------------------
# Graceful drain: finish in-flight, hand back what can't finish
# ---------------------------------------------------------------------------


def test_scale_down_drains_then_retires(model, params, donor):
    pool, _, results, sent = make_fleet(
        model, params, donor, messages=4, min=1, max=2, initial=2,
    )
    pool.run_cycle()
    draining_worker = pool.members[1].worker
    assert draining_worker.batcher.active > 0
    pool.scale_down()
    assert pool.replicas == 1
    assert pool.members[1].state == DRAINING
    assert draining_worker.admitting is False
    drive(pool, until=lambda: all_done(pool, sent))
    assert pool.members[1].state == STOPPED
    replies, duplicates = collect_replies(results, "t://r")
    assert set(replies) == set(sent)
    assert duplicates == 0
    events = [e.name for e in pool.events]
    assert "replica-drain-start" in events
    assert "replica-drain-done" in events


def test_drain_timeout_releases_inflight_to_survivors(model, params, donor):
    pool, queue, results, sent = make_fleet(
        model, params, donor, messages=4, min=1, max=2, initial=2,
        drain_timeout_cycles=2, hang_grace_cycles=10,
    )
    pool.run_cycle()
    pool.hang_worker(1)  # this drain can never finish on its own
    pool.scale_down()
    drive(pool, until=lambda: all_done(pool, sent))
    assert pool.members[1].state == STOPPED
    assert pool.released_total > 0  # handed back, not lost
    replies, duplicates = collect_replies(results, "t://r")
    assert set(replies) == set(sent)
    assert duplicates == 0


def test_stop_all_releases_and_retires(model, params, donor):
    pool, queue, _, _ = make_fleet(
        model, params, donor, messages=4, min=1, max=2, initial=2,
    )
    pool.run_cycle()
    inflight = sum(r.worker.batcher.active for r in pool.members)
    assert inflight > 0
    pool.stop_all()
    assert all(r.state == STOPPED for r in pool.members)
    assert pool.released_total == inflight
    # released requests became visible again: shutdown loses nothing
    depth = int(
        queue.get_queue_attributes("t://q", [])
        ["ApproximateNumberOfMessages"]
    )
    assert depth == 4  # everything un-replied is visible again


# ---------------------------------------------------------------------------
# ContinuousWorker lifecycle hardening (satellite pins)
# ---------------------------------------------------------------------------


def _worker(model, params, donor, queue=None):
    worker = ContinuousWorker(
        queue or FakeMessageQueue(), params, model,
        _config(result_queue_url=""),
    )
    worker.batcher.adopt_engine(donor)
    return worker


def test_worker_stop_is_idempotent_and_sticky(model, params, donor):
    worker = _worker(model, params, donor)
    worker.stop()
    worker.stop()  # idempotent
    # sticky: a stop BEFORE run_forever must prevent the loop (the old
    # lazily-created event silently dropped pre-start stops)
    worker.run_forever()  # returns immediately instead of serving


def test_worker_double_start_raises(model, params, donor):
    worker = _worker(model, params, donor)
    started = threading.Event()
    original = worker.run_once

    def run_once():
        started.set()
        return original()

    worker.run_once = run_once
    thread = threading.Thread(target=worker.run_forever, daemon=True)
    thread.start()
    assert started.wait(5.0)
    with pytest.raises(RuntimeError, match="already running"):
        worker.run_forever()
    worker.stop()
    thread.join(timeout=5.0)
    assert not thread.is_alive()
    # after a clean exit the worker may serve again
    worker.run_forever()  # stop flag still set: returns immediately


def test_drain_timeout_returns_when_engine_stalls(model, params, donor):
    worker = _worker(model, params, donor)
    worker.batcher.submit(np.asarray([1, 2], np.int32),
                          payload={"ReceiptHandle": "rh", "Body": "[1,2]"})
    worker.batcher.step = lambda: []  # wedge the engine: no progress ever
    start = time.monotonic()
    processed = worker.drain(total=1, timeout_s=0.2)
    elapsed = time.monotonic() - start
    assert processed == 0
    assert 0.15 <= elapsed < 5.0  # returned on the timeout, no hang


# ---------------------------------------------------------------------------
# Observability: labeled gauges + trace instants
# ---------------------------------------------------------------------------


def test_fleet_prometheus_gauges(model, params, donor):
    from kube_sqs_autoscaler_tpu.obs.prometheus import WorkloadMetrics

    pool, _, results, sent = make_fleet(
        model, params, donor, messages=6, min=1, max=2, initial=2,
    )
    metrics = WorkloadMetrics()
    pool.attach_metrics(metrics)
    pool.run_cycle()  # both replicas admit a full batch
    pool.scale_down()  # replica 1 drains with work in flight
    pool.run_cycle()
    text = metrics.render()
    assert 'fleet_replica_state{replica="0"} 0.0' in text  # serving
    assert 'fleet_replica_state{replica="1"} 1.0' in text  # draining
    assert "fleet_replicas_draining 1.0" in text
    assert 'fleet_replica_active_slots{replica="0"}' in text
    assert 'fleet_replica_tokens_per_second{replica="0"}' in text
    assert (
        "# TYPE kube_sqs_autoscaler_workload_fleet_requests_"
        "redispatched_total counter" in text
    )
    # HELP/TYPE emitted once per family even with several labeled series
    assert text.count(
        "# TYPE kube_sqs_autoscaler_workload_fleet_replica_state gauge"
    ) == 1
    pool.kill_worker(0)
    pool.run_cycle()
    text = metrics.render()
    assert 'fleet_replica_state{replica="0"} 2.0' in text  # dead
    pool.scale_up()  # respawn so the orphans have a survivor to land on
    drive(pool, until=lambda: all_done(pool, sent))
    replies, duplicates = collect_replies(results, "t://r")
    assert set(replies) == set(sent) and duplicates == 0
    text = metrics.render()
    assert "fleet_requests_redispatched_total 2.0" in text


def test_labeled_and_unlabeled_gauges_coexist():
    from kube_sqs_autoscaler_tpu.obs.prometheus import WorkloadMetrics

    metrics = WorkloadMetrics()
    metrics.set_gauge("tokens_per_second", 5.0, "Plain gauge.")
    metrics.set_gauge("thing", 1.0, "Labeled.", labels=(("shard", "a"),))
    metrics.set_gauge("thing", 2.0, "Labeled.", labels=(("shard", "b"),))
    text = metrics.render()
    assert "kube_sqs_autoscaler_workload_tokens_per_second 5.0" in text
    assert 'kube_sqs_autoscaler_workload_thing{shard="a"} 1.0' in text
    assert 'kube_sqs_autoscaler_workload_thing{shard="b"} 2.0' in text


def test_fleet_trace_instants(model, params, donor):
    from kube_sqs_autoscaler_tpu.obs.trace import (
        to_chrome_trace,
        track_metadata_events,
    )

    pool, _, _, sent = make_fleet(
        model, params, donor, messages=2, min=1, max=2, initial=1,
    )
    pool.scale_up()
    pool.run_cycle()
    pool.kill_worker(1)
    pool.run_cycle()
    events = pool.trace_events(time_origin=0.0)
    names = {e["name"] for e in events}
    assert {"replica-spawn", "replica-kill"} <= names
    assert all(e["ph"] == "i" and e["cat"] == "fleet" for e in events)
    trace = to_chrome_trace([], extra_events=events)
    # non-empty traces lead with the track-naming metadata, then the
    # events verbatim
    assert trace["traceEvents"] == track_metadata_events() + events
