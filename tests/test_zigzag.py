"""Zig-zag ring attention: the balanced half-compute schedule must
reproduce dense causal attention exactly on zig-zag-ordered inputs, and
the permuted-order LM loss must equal the natural-order loss.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kube_sqs_autoscaler_tpu.workloads.model import ModelConfig, init_params
from kube_sqs_autoscaler_tpu.workloads.ring import dense_causal_attention
from kube_sqs_autoscaler_tpu.workloads.train import make_mesh
from kube_sqs_autoscaler_tpu.workloads.zigzag import (
    inverse_permutation,
    make_zigzag_ring_attention,
    zigzag_loss_fn,
    zigzag_permutation,
)

TINY = ModelConfig(
    vocab_size=256, d_model=64, n_heads=4, n_layers=2, d_ff=128,
    max_seq_len=64, dtype=jnp.float32,
)


def qkv(batch=4, heads=4, seq=32, dim=16, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    shape = (batch, heads, seq, dim)
    return tuple(jax.random.normal(k, shape, jnp.float32) for k in ks)


def test_permutation_is_a_bijection_with_device_chunks():
    perm = zigzag_permutation(32, 4)
    assert sorted(perm.tolist()) == list(range(32))
    # device 0 owns chunks 0 and 7 (size 4 each)
    np.testing.assert_array_equal(perm[:8], [0, 1, 2, 3, 28, 29, 30, 31])
    inv = inverse_permutation(perm)
    np.testing.assert_array_equal(perm[inv], np.arange(32))


def test_permutation_requires_divisibility():
    with pytest.raises(ValueError, match="divisible"):
        zigzag_permutation(30, 4)


@pytest.mark.parametrize("seq_parallel", [2, 4, 8])
def test_zigzag_matches_dense_causal(seq_parallel):
    mesh = make_mesh(jax.devices(), model_parallel=1, seq_parallel=seq_parallel)
    q, k, v = qkv()
    expected = dense_causal_attention(q, k, v)

    perm = zigzag_permutation(32, seq_parallel)
    zz = jax.jit(make_zigzag_ring_attention(mesh))
    actual_zz = zz(q[:, :, perm], k[:, :, perm], v[:, :, perm])
    # output comes back in zig-zag order; unpermute to compare
    inv = inverse_permutation(perm)
    np.testing.assert_allclose(
        np.asarray(expected), np.asarray(actual_zz)[:, :, inv],
        rtol=1e-5, atol=1e-5,
    )


def test_zigzag_with_tp_and_dp_axes():
    mesh = make_mesh(jax.devices(), model_parallel=2, seq_parallel=2)
    q, k, v = qkv(batch=4, heads=4, seq=16, dim=8, seed=3)
    expected = dense_causal_attention(q, k, v)
    perm = zigzag_permutation(16, 2)
    inv = inverse_permutation(perm)
    actual = jax.jit(make_zigzag_ring_attention(mesh))(
        q[:, :, perm], k[:, :, perm], v[:, :, perm]
    )
    np.testing.assert_allclose(
        np.asarray(expected), np.asarray(actual)[:, :, inv],
        rtol=1e-5, atol=1e-5,
    )


def test_zigzag_is_causal():
    # perturbing the last natural position must not change any earlier
    # position's output, wherever the zig-zag layout placed them
    mesh = make_mesh(jax.devices(), model_parallel=1, seq_parallel=4)
    perm = zigzag_permutation(32, 4)
    inv = inverse_permutation(perm)
    fn = jax.jit(make_zigzag_ring_attention(mesh))
    q, k, v = qkv(seed=5)
    qz, kz, vz = q[:, :, perm], k[:, :, perm], v[:, :, perm]
    base = np.asarray(fn(qz, kz, vz))[:, :, inv]
    last = int(inv[31])
    k2 = kz.at[:, :, last].add(1.0)
    v2 = vz.at[:, :, last].add(1.0)
    pert = np.asarray(fn(qz, k2, v2))[:, :, inv]
    np.testing.assert_array_equal(base[:, :, :31], pert[:, :, :31])
    assert not np.allclose(base[:, :, 31], pert[:, :, 31])


def test_zigzag_requires_nontrivial_seq_axis():
    mesh = make_mesh(jax.devices(), model_parallel=1, seq_parallel=1)
    with pytest.raises(ValueError, match="P >= 2"):
        make_zigzag_ring_attention(mesh)


def test_zigzag_loss_rejects_natural_order_attention():
    # injecting plain ring attention (e.g. via make_train_step's loss
    # seam) would compute a wrong-but-finite loss; it must fail loudly
    from kube_sqs_autoscaler_tpu.workloads.ring import make_ring_attention

    mesh = make_mesh(jax.devices(), model_parallel=1, seq_parallel=4)
    params = init_params(jax.random.key(0), TINY)
    tokens = jax.random.randint(
        jax.random.key(1), (2, 32), 0, TINY.vocab_size, jnp.int32
    )
    with pytest.raises(ValueError, match="zig-zag"):
        zigzag_loss_fn(params, tokens, TINY, mesh,
                       attention_fn=make_ring_attention(mesh))


def test_zigzag_remat_is_bit_identical():
    mesh = make_mesh(jax.devices(), model_parallel=1, seq_parallel=4)
    params = init_params(jax.random.key(0), TINY)
    tokens = jax.random.randint(
        jax.random.key(1), (2, 32), 0, TINY.vocab_size, jnp.int32
    )
    plain = float(zigzag_loss_fn(params, tokens, TINY, mesh))
    remat = float(zigzag_loss_fn(params, tokens, TINY, mesh, remat=True))
    assert plain == remat


def test_zigzag_loss_matches_natural_order_loss():
    from kube_sqs_autoscaler_tpu.workloads.train import loss_fn
    from kube_sqs_autoscaler_tpu.workloads.zigzag import (
        permute_batch,
        zigzag_loss_from_permuted,
    )

    mesh = make_mesh(jax.devices(), model_parallel=1, seq_parallel=4)
    params = init_params(jax.random.key(0), TINY)
    tokens = jax.random.randint(
        jax.random.key(1), (2, 32), 0, TINY.vocab_size, jnp.int32
    )
    natural = float(loss_fn(params, tokens, TINY))
    # in-program permute form
    permuted = float(zigzag_loss_fn(params, tokens, TINY, mesh))
    assert permuted == pytest.approx(natural, rel=1e-5)
    # host-side pre-permuted production form
    tz, gz, valid = permute_batch(np.asarray(tokens), 4)
    pre = float(
        zigzag_loss_from_permuted(
            params, jnp.asarray(tz), jnp.asarray(gz), jnp.asarray(valid),
            TINY, mesh,
        )
    )
    assert pre == pytest.approx(natural, rel=1e-5)


def test_zigzag_train_step_learns_on_full_mesh():
    from kube_sqs_autoscaler_tpu.workloads.train import (
        TrainConfig,
        batch_sharding,
        init_train_state,
        place_state,
    )
    from kube_sqs_autoscaler_tpu.workloads.zigzag import make_zigzag_train_step

    mesh = make_mesh(jax.devices(), model_parallel=2, seq_parallel=2)
    train_config = TrainConfig(learning_rate=1e-2)
    state = place_state(mesh, init_train_state(jax.random.key(0), TINY,
                                               train_config))
    step_fn = make_zigzag_train_step(mesh, TINY, train_config, state)
    tokens = jax.device_put(
        jax.random.randint(jax.random.key(1), (4, 32), 0, TINY.vocab_size,
                           jnp.int32),
        batch_sharding(mesh),
    )
    losses = []
    for _ in range(4):
        state, loss = step_fn(state, tokens)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_zigzag_gqa_matches_broadcast_dense():
    """Compact GQA k/v through the zig-zag schedule == repeat_kv + dense
    causal (in zig-zag layout, compared chunk-for-chunk)."""
    from kube_sqs_autoscaler_tpu.workloads.llama import repeat_kv
    from kube_sqs_autoscaler_tpu.workloads.zigzag import (
        make_zigzag_ring_attention,
        zigzag_permutation,
    )

    mesh = make_mesh(jax.devices(), model_parallel=2, seq_parallel=2)
    perm = zigzag_permutation(32, 2)
    keys = jax.random.split(jax.random.key(9), 3)
    q = jax.random.normal(keys[0], (2, 4, 32, 16), jnp.float32)
    k = jax.random.normal(keys[1], (2, 2, 32, 16), jnp.float32)
    v = jax.random.normal(keys[2], (2, 2, 32, 16), jnp.float32)
    # dense reference in natural order, then permute to zig-zag layout
    expected = dense_causal_attention(q, repeat_kv(k, 2), repeat_kv(v, 2))
    zz_fn = make_zigzag_ring_attention(mesh)
    assert zz_fn.gqa_native
    got = zz_fn(q[:, :, perm], k[:, :, perm], v[:, :, perm])
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expected[:, :, perm]),
        rtol=2e-5, atol=2e-5,
    )


def test_llama_zigzag_loss_matches_llama_dense_loss():
    """The llama family through the zig-zag schedule (GQA compact
    rotation, RoPE with permuted positions) pins the natural-order dense
    loss."""
    from kube_sqs_autoscaler_tpu.workloads.llama import (
        LlamaConfig,
        init_llama_params,
        llama_forward,
        llama_loss_fn,
    )
    from kube_sqs_autoscaler_tpu.workloads.zigzag import zigzag_loss_fn

    config = LlamaConfig(
        vocab_size=256, d_model=64, n_heads=4, n_kv_heads=2, n_layers=2,
        d_ff=128, max_seq_len=64, dtype=jnp.float32,
    )
    mesh = make_mesh(jax.devices(), model_parallel=2, seq_parallel=2)
    params = init_llama_params(jax.random.key(0), config)
    tokens = jax.random.randint(
        jax.random.key(1), (2, 32), 0, config.vocab_size, jnp.int32
    )
    dense = float(llama_loss_fn(params, tokens, config))
    zz = float(
        zigzag_loss_fn(params, tokens, config, mesh,
                       forward_fn=llama_forward)
    )
    np.testing.assert_allclose(zz, dense, rtol=2e-5)


def test_zigzag_matches_dense_bf16():
    # bf16 MXU convention (storage-dtype score matmuls, fp32 stats) must
    # keep zig-zag == dense within bf16 rounding
    import jax.numpy as jnp

    from kube_sqs_autoscaler_tpu.workloads.ring import dense_causal_attention
    from kube_sqs_autoscaler_tpu.workloads.zigzag import (
        inverse_permutation,
        make_zigzag_ring_attention,
        zigzag_permutation,
    )

    mesh = make_mesh(jax.devices(), model_parallel=1, seq_parallel=4)
    keys = jax.random.split(jax.random.key(7), 3)
    q, k, v = (
        jax.random.normal(kk, (2, 4, 32, 16), jnp.bfloat16) for kk in keys
    )
    perm = zigzag_permutation(32, 4)
    inv = inverse_permutation(perm)
    expected = dense_causal_attention(q, k, v)
    zz = jax.jit(make_zigzag_ring_attention(mesh))(
        q[:, :, perm], k[:, :, perm], v[:, :, perm]
    )
    actual = zz[:, :, inv]
    assert actual.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(expected, np.float32), np.asarray(actual, np.float32),
        rtol=2e-2, atol=2e-2,
    )


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16],
                         ids=["fp32", "bf16"])
def test_zigzag_kernel_path_matches_dense(dtype):
    # the flash kernel as the per-hop local op: three rectangular kernel
    # calls (diag lo-causal + hi-shifted, earlier, later) merged via
    # (out, lse) partials — must equal dense causal attention exactly
    from kube_sqs_autoscaler_tpu.workloads.zigzag import (
        inverse_permutation,
        make_zigzag_ring_attention,
        zigzag_permutation,
    )

    mesh = make_mesh(jax.devices(), model_parallel=1, seq_parallel=4)
    keys = jax.random.split(jax.random.key(9), 3)
    q, k, v = (jax.random.normal(kk, (2, 4, 32, 16), dtype) for kk in keys)
    perm = zigzag_permutation(32, 4)
    inv = inverse_permutation(perm)
    expected = dense_causal_attention(q, k, v)
    zz_fn = make_zigzag_ring_attention(mesh, use_kernel=True, interpret=True)
    zz = jax.jit(zz_fn)(q[:, :, perm], k[:, :, perm], v[:, :, perm])
    actual = zz[:, :, inv]
    tol = 1e-5 if dtype == jnp.float32 else 2e-2
    np.testing.assert_allclose(
        np.asarray(expected, np.float32), np.asarray(actual, np.float32),
        rtol=tol, atol=tol,
    )


def test_zigzag_kernel_path_grads_match_einsum_path():
    from kube_sqs_autoscaler_tpu.workloads.zigzag import (
        make_zigzag_ring_attention,
        zigzag_permutation,
    )

    mesh = make_mesh(jax.devices(), model_parallel=1, seq_parallel=2)
    keys = jax.random.split(jax.random.key(11), 3)
    q, k, v = (
        jax.random.normal(kk, (4, 4, 32, 16), jnp.float32) for kk in keys
    )
    perm = zigzag_permutation(32, 2)
    qz, kz, vz = q[:, :, perm], k[:, :, perm], v[:, :, perm]

    kernel_fn = make_zigzag_ring_attention(mesh, use_kernel=True,
                                           interpret=True)
    einsum_fn = make_zigzag_ring_attention(mesh, use_kernel=False)

    def loss(fn):
        return lambda q, k, v: jnp.mean(fn(q, k, v).astype(jnp.float32) ** 2)

    got = jax.jit(jax.grad(loss(kernel_fn), argnums=(0, 1, 2)))(qz, kz, vz)
    want = jax.jit(jax.grad(loss(einsum_fn), argnums=(0, 1, 2)))(qz, kz, vz)
    for g, w, name in zip(got, want, "qkv"):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), rtol=1e-4, atol=1e-5,
            err_msg=f"d{name}",
        )
