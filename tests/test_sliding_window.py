"""Sliding-window attention: the flash kernel's windowed block-skip must
equal the windowed dense mask (values AND gradients), the llama family
must reproduce transformers' Mistral forward on converted weights, and
decode must respect the window through the cache masks."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kube_sqs_autoscaler_tpu.workloads.flash import flash_attention
from kube_sqs_autoscaler_tpu.workloads.model import _dense_attention


def qkv(batch=2, heads=4, seq=256, dim=32, seed=0):
    keys = jax.random.split(jax.random.key(seed), 3)
    return tuple(
        (jax.random.normal(key, (batch, heads, seq, dim), jnp.float32)
         / dim**0.25)
        for key in keys
    )


@pytest.mark.parametrize("window", [1, 7, 128, 300])
def test_flash_window_matches_dense_window(window):
    q, k, v = qkv()

    def loss_flash(q, k, v):
        return jnp.mean(flash_attention(q, k, v, window=window) ** 2)

    def loss_dense(q, k, v):
        return jnp.mean(_dense_attention(q, k, v, window=window) ** 2)

    out_f = flash_attention(q, k, v, window=window)
    out_d = _dense_attention(q, k, v, window=window)
    np.testing.assert_allclose(
        np.asarray(out_f), np.asarray(out_d), rtol=2e-5, atol=2e-5
    )
    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5
        )


def test_window_at_least_seq_equals_full_causal():
    q, k, v = qkv(seq=128)
    full = flash_attention(q, k, v)
    windowed = flash_attention(q, k, v, window=128)
    np.testing.assert_allclose(
        np.asarray(full), np.asarray(windowed), rtol=1e-6, atol=1e-6
    )


def test_flash_window_gqa_compact_kv():
    q, _, _ = qkv(heads=4)
    _, k, v = qkv(heads=2, seed=5)
    from kube_sqs_autoscaler_tpu.workloads.llama import repeat_kv

    out = flash_attention(q, k, v, window=9)
    ref = _dense_attention(q, repeat_kv(k, 2), repeat_kv(v, 2), window=9)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_window_validation():
    q, k, v = qkv(seq=128)
    with pytest.raises(ValueError, match="causal"):
        flash_attention(q, k, v, causal=False, window=4)
    with pytest.raises(ValueError, match="window"):
        flash_attention(q, k, v, window=0)


# ---------------------------------------------------------------------------
# Mistral parity through hf_convert
# ---------------------------------------------------------------------------


def make_hf_mistral(sliding_window, seed=0):
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    from transformers import MistralConfig, MistralForCausalLM

    torch.manual_seed(seed)
    model = MistralForCausalLM(MistralConfig(
        vocab_size=128, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, sliding_window=sliding_window,
        attn_implementation="eager", tie_word_embeddings=False,
    ))
    model.eval()
    return model


def test_converted_mistral_matches_transformers():
    torch = pytest.importorskip("torch")
    from kube_sqs_autoscaler_tpu.workloads.hf_convert import load_hf_llama
    from kube_sqs_autoscaler_tpu.workloads.llama import llama_forward

    model = make_hf_mistral(sliding_window=8)
    config, params = load_hf_llama(model, dtype=jnp.float32)
    assert config.sliding_window == 8

    tokens = np.random.default_rng(1).integers(0, 128, (2, 24)).astype(
        np.int32
    )  # 24 > window so the mask really bites
    ours = np.asarray(llama_forward(params, jnp.asarray(tokens), config))
    with torch.no_grad():
        theirs = model(torch.from_numpy(tokens).long()).logits.float().numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


def test_converted_mistral_greedy_generation_matches():
    torch = pytest.importorskip("torch")
    from kube_sqs_autoscaler_tpu.workloads.hf_convert import load_hf_llama
    from kube_sqs_autoscaler_tpu.workloads.llama import llama_generate

    model = make_hf_mistral(sliding_window=6, seed=3)
    config, params = load_hf_llama(model, dtype=jnp.float32)
    prompt = np.random.default_rng(2).integers(0, 128, (2, 10)).astype(
        np.int32
    )
    ours = np.asarray(llama_generate(params, jnp.asarray(prompt), 12,
                                     config))
    with torch.no_grad():
        theirs = model.generate(
            torch.from_numpy(prompt).long(), max_new_tokens=12,
            do_sample=False, num_beams=1, pad_token_id=0,
        )[:, prompt.shape[1]:].numpy()
    np.testing.assert_array_equal(ours, theirs)


def test_serve_path_prefill_kernel_carries_the_window():
    """The serve binary's generate lambda passes an explicit prefill
    kernel; llama_attention_fn_for must carry the window so a Mistral
    prompt longer than its window prefills windowed (a bare
    flash.attention_fn_for pick would not)."""
    torch = pytest.importorskip("torch")
    from kube_sqs_autoscaler_tpu.workloads.hf_convert import load_hf_llama
    from kube_sqs_autoscaler_tpu.workloads.llama import (
        llama_attention_fn_for,
        llama_generate_jit,
    )

    model = make_hf_mistral(sliding_window=6, seed=9)
    config, params = load_hf_llama(model, dtype=jnp.float32)
    prompt = np.random.default_rng(4).integers(0, 128, (2, 16)).astype(
        np.int32
    )  # 16 > window=6: prefill masking matters
    ours = np.asarray(llama_generate_jit(
        params, jnp.asarray(prompt), 8, config,
        prompt_attention=llama_attention_fn_for(config, prompt.shape[1]),
    ))
    with torch.no_grad():
        theirs = model.generate(
            torch.from_numpy(prompt).long(), max_new_tokens=8,
            do_sample=False, num_beams=1, pad_token_id=0,
        )[:, prompt.shape[1]:].numpy()
    np.testing.assert_array_equal(ours, theirs)


def test_mesh_forward_step_carries_the_window():
    """make_forward_step (the sharded classify path) reads
    sliding_window off the config — sharded logits must equal the
    windowed single-chip forward, not the full-causal one."""
    from kube_sqs_autoscaler_tpu.workloads.llama import (
        LlamaConfig,
        init_llama_params,
        llama_forward,
    )
    from kube_sqs_autoscaler_tpu.workloads.train import (
        batch_sharding,
        make_forward_step,
        make_mesh,
        param_shardings,
    )

    config = LlamaConfig(vocab_size=128, d_model=64, n_heads=4,
                         n_kv_heads=2, n_layers=2, d_ff=96, max_seq_len=64,
                         sliding_window=8, dtype=jnp.float32)
    params = init_llama_params(jax.random.key(0), config)
    mesh = make_mesh(jax.devices()[:4], model_parallel=2, seq_parallel=1)
    placed = jax.device_put(params, param_shardings(mesh, params))
    step = make_forward_step(mesh, config, placed, forward_fn=llama_forward)
    tokens = jax.random.randint(jax.random.key(1), (4, 32), 0, 128,
                                jnp.int32)
    sharded = step(placed, jax.device_put(tokens, batch_sharding(mesh)))
    reference = llama_forward(params, tokens, config)  # windowed default
    np.testing.assert_allclose(
        np.asarray(sharded), np.asarray(reference), rtol=2e-4, atol=2e-4
    )


def test_rolling_cache_equals_full_cache_decode():
    """The O(window) ring cache decodes the exact sequence the full
    O(max_seq_len) cache does — long prompts, multiple ring wraps,
    ragged warm-up rows — and is window-sized in memory."""
    from kube_sqs_autoscaler_tpu.workloads.llama import (
        LlamaConfig,
        init_llama_params,
        init_llama_rolling_cache,
        llama_generate,
    )

    cfg = LlamaConfig(vocab_size=128, d_model=64, n_heads=4, n_kv_heads=2,
                      n_layers=2, d_ff=96, max_seq_len=96,
                      sliding_window=6, dtype=jnp.float32)
    params = init_llama_params(jax.random.key(0), cfg)
    prompt = jax.random.randint(jax.random.key(1), (3, 12), 0, 128,
                                jnp.int32)

    full = np.asarray(llama_generate(params, prompt, 20, cfg))
    roll = np.asarray(llama_generate(params, prompt, 20, cfg,
                                     rolling=True))
    np.testing.assert_array_equal(full, roll)

    lengths = jnp.asarray([3, 12, 7], jnp.int32)  # warm-up + wrapped rows
    full = np.asarray(llama_generate(params, prompt, 15, cfg,
                                     lengths=lengths))
    roll = np.asarray(llama_generate(params, prompt, 15, cfg,
                                     lengths=lengths, rolling=True))
    np.testing.assert_array_equal(full, roll)

    cache = init_llama_rolling_cache(cfg, batch=3)
    assert cache["layers"][0]["k"].shape == (3, 2, 6, 16)  # W, not S_max

    with pytest.raises(ValueError, match="sliding_window"):
        init_llama_rolling_cache(
            LlamaConfig(vocab_size=128, d_model=64, n_heads=4,
                        n_kv_heads=2, n_layers=2, d_ff=96, max_seq_len=96),
            batch=1,
        )

    # a full-size cache handed to the rolling step fails loudly instead
    # of silently scoring mostly-zero slots
    from kube_sqs_autoscaler_tpu.workloads.llama import (
        init_llama_cache,
        llama_rolling_decode_step,
    )

    full_cache = init_llama_cache(cfg, batch=1)
    with pytest.raises(ValueError, match="window-sized"):
        llama_rolling_decode_step(
            params, full_cache, jnp.zeros((1,), jnp.int32), cfg
        )


def test_mistral_export_round_trip(tmp_path):
    """save_hf_llama's Mistral branch: a windowed config exports as a
    transformers Mistral checkpoint whose from_pretrained logits match
    our windowed forward."""
    torch = pytest.importorskip("torch")
    from transformers import MistralForCausalLM

    from kube_sqs_autoscaler_tpu.workloads.hf_convert import save_hf_llama
    from kube_sqs_autoscaler_tpu.workloads.llama import (
        LlamaConfig,
        init_llama_params,
        llama_forward,
    )

    config = LlamaConfig(vocab_size=128, d_model=64, n_heads=4,
                         n_kv_heads=2, n_layers=2, d_ff=96, max_seq_len=64,
                         sliding_window=8, dtype=jnp.float32)
    params = init_llama_params(jax.random.key(17), config)
    out = tmp_path / "mistral"
    save_hf_llama(params, config, out)
    reloaded = MistralForCausalLM.from_pretrained(out)
    reloaded.eval()
    assert reloaded.config.sliding_window == 8
    tokens = np.random.default_rng(5).integers(0, 128, (2, 20)).astype(
        np.int32
    )  # 20 > window so the mask bites
    ours = np.asarray(llama_forward(params, jnp.asarray(tokens), config))
    with torch.no_grad():
        theirs = reloaded(
            torch.from_numpy(tokens).long()
        ).logits.float().numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


def test_windowed_llama_trains_on_the_mesh():
    from kube_sqs_autoscaler_tpu.workloads.llama import (
        LlamaConfig,
        init_llama_train_state,
        make_llama_train_step,
    )
    from kube_sqs_autoscaler_tpu.workloads.train import (
        TrainConfig,
        batch_sharding,
        make_mesh,
        place_state,
    )

    config = LlamaConfig(vocab_size=128, d_model=64, n_heads=4,
                         n_kv_heads=2, n_layers=2, d_ff=96, max_seq_len=64,
                         sliding_window=8, dtype=jnp.float32)
    tc = TrainConfig(learning_rate=1e-2)
    mesh = make_mesh(jax.devices()[:4], model_parallel=2, seq_parallel=1)
    state = place_state(
        mesh, init_llama_train_state(jax.random.key(0), config, tc)
    )
    step = make_llama_train_step(mesh, config, tc, state)
    tokens = jax.device_put(
        jax.random.randint(jax.random.key(1), (4, 32), 0, 128, jnp.int32),
        batch_sharding(mesh),
    )
    losses = []
    for _ in range(4):
        state, loss = step(state, tokens)
        losses.append(float(loss))
    assert all(np.isfinite(losses)) and losses[-1] < losses[0]

    # sequence parallelism runs the WINDOWED ring schedule (a global
    # band mask per hop) — previously a fail-fast, now a capability
    sp_mesh = make_mesh(jax.devices(), model_parallel=2, seq_parallel=2)
    sp_state = place_state(
        sp_mesh, init_llama_train_state(jax.random.key(0), config, tc)
    )
    sp_step = make_llama_train_step(sp_mesh, config, tc, sp_state)
    sp_tokens = jax.device_put(
        jax.random.randint(jax.random.key(2), (4, 32), 0, 128, jnp.int32),
        batch_sharding(sp_mesh),
    )
    sp_losses = []
    for _ in range(4):
        sp_state, sp_loss = sp_step(sp_state, sp_tokens)
        sp_losses.append(float(sp_loss))
    assert all(np.isfinite(sp_losses)) and sp_losses[-1] < sp_losses[0]


def test_windowed_llama_composes_with_beam_and_rolling_eos():
    """Cross-feature interactions: beam search over a sliding-window
    llama (beams=1 == windowed greedy) and rolling-cache decode with an
    eos id (finished rows pin, prefixes match the eos-free run)."""
    from kube_sqs_autoscaler_tpu.workloads.beam import beam_search
    from kube_sqs_autoscaler_tpu.workloads.llama import (
        LlamaConfig,
        init_llama_params,
        llama_generate,
    )

    cfg = LlamaConfig(vocab_size=64, d_model=32, n_heads=2, n_kv_heads=1,
                      n_layers=2, d_ff=48, max_seq_len=96,
                      sliding_window=6, dtype=jnp.float32)
    params = init_llama_params(jax.random.key(0), cfg)
    prompt = jax.random.randint(jax.random.key(1), (2, 10), 0, 64,
                                jnp.int32)

    ref = np.asarray(llama_generate(params, prompt, 10, cfg))
    b1 = np.asarray(beam_search(params, cfg, prompt, 10, beams=1))
    np.testing.assert_array_equal(b1, ref)

    free = np.asarray(llama_generate(params, prompt, 12, cfg,
                                     rolling=True))
    eos = int(free[0, 4])
    out = np.asarray(llama_generate(params, prompt, 12, cfg, rolling=True,
                                    eos_id=eos))
    for row_free, row in zip(free, out):
        ids = row.tolist()
        if eos in ids:
            first = ids.index(eos)
            assert all(x == eos for x in ids[first:])
            assert ids[:first] == row_free.tolist()[:first]
        else:
            assert ids == row_free.tolist()
