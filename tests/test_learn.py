"""Learned autoscaling policy (`learn/`): checkpoint contract, network
decision arithmetic, compiled-twin rollout/training, fidelity against
the real ControlLoop, CLI startup validation, and replay integration.
"""

from __future__ import annotations

import contextlib
import io
import json
import os
from dataclasses import replace

import numpy as np
import pytest

from kube_sqs_autoscaler_tpu.core.loop import LoopConfig
from kube_sqs_autoscaler_tpu.core.policy import PolicyConfig
from kube_sqs_autoscaler_tpu.learn.checkpoint import (
    KIND,
    SCHEMA_VERSION,
    CheckpointError,
    PolicyCheckpoint,
    checkpoint_hash,
    load_checkpoint,
    save_checkpoint,
)
from kube_sqs_autoscaler_tpu.learn.network import (
    N_FEATURES,
    hold_depth,
    init_params,
    param_count,
)
from kube_sqs_autoscaler_tpu.learn.policy import LearnedPolicy
from kube_sqs_autoscaler_tpu.learn.rollout import (
    checkpoint_history,
    evaluate_checkpoint,
    evaluate_population,
    learned_config,
)
from kube_sqs_autoscaler_tpu.learn.train import ESConfig, train
from kube_sqs_autoscaler_tpu.sim.evaluate import Scenario, default_battery
from kube_sqs_autoscaler_tpu.sim.scenarios import RampArrival, StepArrival


def make_checkpoint(seed: int = 0, hidden: int = 16, **meta) -> PolicyCheckpoint:
    return PolicyCheckpoint(
        theta=init_params(seed, hidden),
        hidden=hidden,
        meta={"forecast_history": 32, "min_samples": 3, **meta},
    )


def short_scenario(name: str = "ramp-short") -> Scenario:
    return Scenario(
        name=name,
        arrival=RampArrival(
            start_rate=10.0, end_rate=150.0, t_start=30.0, t_end=240.0
        ),
        duration=300.0,
    )


def make_policy(checkpoint: PolicyCheckpoint, **overrides) -> LearnedPolicy:
    kwargs = dict(
        policy=PolicyConfig(),
        poll_interval=5.0,
        max_pods=20,
        min_pods=1,
        initial_replicas=1,
        min_samples=3,
    )
    kwargs.update(overrides)
    return LearnedPolicy(checkpoint, **kwargs)


# --- checkpoint contract ----------------------------------------------------


def test_checkpoint_round_trip_is_bitwise(tmp_path):
    checkpoint = make_checkpoint(seed=5)
    path = str(tmp_path / "ck.json")
    returned_hash = save_checkpoint(path, checkpoint)
    loaded = load_checkpoint(path)
    assert np.array_equal(loaded.theta, checkpoint.theta)
    assert loaded.theta.dtype == np.float32
    assert loaded.hidden == checkpoint.hidden
    assert loaded.hash == checkpoint.hash == returned_hash
    assert loaded.meta == checkpoint.meta


def test_checkpoint_round_trip_decisions_are_bitwise(tmp_path):
    checkpoint = make_checkpoint(seed=6)
    path = str(tmp_path / "ck.json")
    save_checkpoint(path, checkpoint)
    loaded = load_checkpoint(path)
    depths = [0, 40, 90, 160, 300, 250, 120, 60, 30, 10, 5, 0]
    decisions = []
    for candidate in (checkpoint, loaded):
        policy = make_policy(candidate)
        episode = []
        for i, depth in enumerate(depths):
            t = 5.0 * (i + 1)
            episode.append(policy.effective_messages(t, depth))
            policy.history.observe(t, float(depth))
        decisions.append(episode)
    assert decisions[0] == decisions[1]


def test_checkpoint_schema_version_is_pinned(tmp_path):
    # Bumping the schema is an intentional act that needs a loader for
    # every prior version; this pin makes an accidental bump loud.
    assert SCHEMA_VERSION == 1
    path = str(tmp_path / "ck.json")
    save_checkpoint(path, make_checkpoint())
    with open(path) as fh:
        data = json.load(fh)
    assert data["schema"] == 1
    assert data["kind"] == KIND
    assert data["n_features"] == N_FEATURES


def test_checkpoint_rejects_future_schema(tmp_path):
    path = str(tmp_path / "ck.json")
    save_checkpoint(path, make_checkpoint())
    with open(path) as fh:
        data = json.load(fh)
    data["schema"] = SCHEMA_VERSION + 1
    with open(path, "w") as fh:
        json.dump(data, fh)
    with pytest.raises(CheckpointError, match="newer than"):
        load_checkpoint(path)


@pytest.mark.parametrize(
    "mutate, match",
    [
        (lambda d: d.__setitem__("kind", "something/else"), "kind"),
        (lambda d: d.__setitem__("theta", [1.0, 2.0]), "parameters"),
        (lambda d: d.__setitem__("n_features", 4), "features"),
        (lambda d: d.__setitem__("hidden", "wide"), "hidden"),
        (lambda d: d.__setitem__("theta", ["a"]), "finite"),
        (lambda d: d.__setitem__("meta", [1]), "meta"),
        (lambda d: d.__setitem__("schema", 0), "schema"),
    ],
)
def test_checkpoint_rejects_corrupt_fields(tmp_path, mutate, match):
    path = str(tmp_path / "ck.json")
    save_checkpoint(path, make_checkpoint())
    with open(path) as fh:
        data = json.load(fh)
    mutate(data)
    with open(path, "w") as fh:
        json.dump(data, fh)
    with pytest.raises(CheckpointError, match=match):
        load_checkpoint(path)


def test_checkpoint_rejects_missing_and_torn_files(tmp_path):
    with pytest.raises(CheckpointError, match="does not exist"):
        load_checkpoint(str(tmp_path / "missing.json"))
    torn = tmp_path / "torn.json"
    torn.write_text('{"kind": "kube-sqs')
    with pytest.raises(CheckpointError, match="corrupt"):
        load_checkpoint(str(torn))


def test_checkpoint_hash_tracks_decisions_not_provenance():
    a = make_checkpoint(seed=1, note="first")
    b = make_checkpoint(seed=1, note="second, different meta")
    c = make_checkpoint(seed=2)
    d = make_checkpoint(seed=1, forecast_history=128)
    assert a.hash == b.hash  # free-form meta is provenance, not behavior
    assert a.hash != c.hash  # different weights, different hash
    # the feature window is part of what the weights mean: same theta
    # over a different ring capacity is a different policy
    assert a.hash != d.hash
    assert checkpoint_hash(a) == a.hash


def test_checkpoint_validates_geometry():
    with pytest.raises(CheckpointError, match="needs exactly"):
        PolicyCheckpoint(theta=np.zeros(7, np.float32), hidden=16)
    with pytest.raises(CheckpointError, match="non-finite"):
        PolicyCheckpoint(
            theta=np.full(param_count(16), np.nan, np.float32), hidden=16
        )
    with pytest.raises(CheckpointError, match="hidden"):
        PolicyCheckpoint(theta=np.zeros(1, np.float32), hidden=0)


def test_checkpoint_validates_feature_window_pins(tmp_path):
    """The decision-relevant meta pins fail as CheckpointError at
    construction/load time, never as an int() traceback mid-deployment."""
    for bad in (None, "abc", 64.5, 0, True):
        with pytest.raises(CheckpointError, match="forecast_history"):
            PolicyCheckpoint(
                theta=init_params(0), meta={"forecast_history": bad}
            )
    with pytest.raises(CheckpointError, match="min_samples"):
        PolicyCheckpoint(theta=init_params(0), meta={"min_samples": -1})
    # load_checkpoint wraps the same rejection with the file path
    path = tmp_path / "badmeta.json"
    data = make_checkpoint().to_dict()
    data["meta"]["forecast_history"] = None
    path.write_text(json.dumps(data))
    with pytest.raises(CheckpointError, match="badmeta"):
        load_checkpoint(str(path))


# --- network ----------------------------------------------------------------


def test_hold_depth_sits_strictly_between_open_thresholds():
    assert 10 < hold_depth(100, 10) < 100
    # touching/inverted thresholds have no neutral value: deterministic
    # fallback, identical for the live policy and the compiled scan
    assert hold_depth(11, 10) == 11
    assert hold_depth(5, 10) == 11


def test_init_params_is_seeded_and_sized():
    assert init_params(3).shape == (param_count(16),)
    assert np.array_equal(init_params(3), init_params(3))
    assert not np.array_equal(init_params(3), init_params(4))
    assert init_params(3).dtype == np.float32


def test_policy_warms_up_reactive_below_min_samples():
    policy = make_policy(make_checkpoint(), min_samples=3)
    # 1 sample (the current observation): reactive pass-through
    assert policy.effective_messages(5.0, 123) == 123
    policy.history.observe(5.0, 123.0)
    assert policy.effective_messages(10.0, 77) == 77


def test_policy_mirrors_replicas_and_cooldowns():
    from kube_sqs_autoscaler_tpu.core.events import TickRecord
    from kube_sqs_autoscaler_tpu.core.policy import Gate

    policy = make_policy(make_checkpoint(), max_pods=3, initial_replicas=2)
    record = TickRecord(start=10.0, num_messages=500)
    record.up = Gate.FIRE
    policy.on_tick(record)
    assert policy.replicas == 3
    assert policy._last_up == 10.0
    policy.on_tick(record)  # boundary no-op still refreshes the stamp
    assert policy.replicas == 3
    failed = TickRecord(start=20.0, num_messages=500)
    failed.up = Gate.FIRE
    failed.up_error = "boom"
    policy.on_tick(failed)
    assert policy._last_up == 10.0  # failed actuation advances nothing
    down = TickRecord(start=30.0, num_messages=0)
    down.down = Gate.FIRE
    policy.on_tick(down)
    assert policy.replicas == 2
    assert policy._last_down == 30.0


# --- compiled twin: trajectory, summaries, fidelity -------------------------


def test_compiled_trajectory_matches_real_loop_tick_for_tick():
    from kube_sqs_autoscaler_tpu.sim.compiled import run_episodes
    from kube_sqs_autoscaler_tpu.sim.simulator import Simulation

    checkpoint = make_checkpoint(seed=11)
    config = learned_config(short_scenario(), checkpoint)
    [episode] = run_episodes([config])

    records = []

    class Recorder:
        def on_tick(self, record):
            records.append(record)

    result = Simulation(config, extra_observers=(Recorder(),)).run()
    assert len(records) == len(episode.observed)
    for k, record in enumerate(records):
        assert record.num_messages == int(episode.observed[k])
        assert record.decision_messages == int(episode.decision[k])
        up, down = episode.gates(k)
        assert record.up is up
        assert record.down is down
        assert result.timeline[k][2] == int(episode.replicas_before[k])
    assert result.final_replicas == episode.result.final_replicas


def test_in_scan_summaries_match_host_scoring():
    # trajectory OFF must report the same episode numbers the host
    # computes from the trajectory — the training reward's ground truth
    from kube_sqs_autoscaler_tpu.sim.compiled import run_episodes

    scenario = short_scenario()
    checkpoint = make_checkpoint(seed=12)
    config = learned_config(scenario, checkpoint)
    [episode] = run_episodes([config])
    summaries = evaluate_population(
        checkpoint.theta[None, :],
        [scenario],
        hidden=checkpoint.hidden,
        history=32,
        min_samples=3,
    )
    result = episode.result
    assert summaries["max_depth"][0, 0] == pytest.approx(result.max_depth)
    assert int(summaries["replica_changes"][0, 0]) == result.replica_changes
    assert summaries["time_over_slo"][0, 0] == pytest.approx(
        result.time_over(scenario.slo_depth)
    )
    assert int(summaries["final_replicas"][0, 0]) == result.final_replicas
    assert summaries["final_depth"][0, 0] == pytest.approx(result.final_depth)


def test_learned_fidelity_zero_divergences():
    from kube_sqs_autoscaler_tpu.sim.compiled import verify_fidelity

    scenario = short_scenario()
    checkpoint = make_checkpoint(seed=13)
    report = verify_fidelity(
        scenarios=[scenario],
        forecasters=(),
        extra_episodes=[("learned", learned_config(scenario, checkpoint))],
    )
    assert report.ok, report.format_divergences()
    assert report.episodes == 2


def test_mixed_learned_and_reactive_batch_matches_separate_runs():
    from kube_sqs_autoscaler_tpu.sim.compiled import run_episodes
    from kube_sqs_autoscaler_tpu.sim.simulator import SimConfig

    scenario = short_scenario()
    checkpoint = make_checkpoint(seed=14)
    learned = learned_config(scenario, checkpoint)
    reactive = SimConfig(
        arrival_rate=scenario.arrival,
        service_rate_per_replica=scenario.service_rate_per_replica,
        duration=scenario.duration,
        min_pods=scenario.min_pods,
        max_pods=scenario.max_pods,
        loop=scenario.loop,
        forecast_history=32,
    )
    mixed = run_episodes([learned, reactive])
    [solo_learned] = run_episodes([learned])
    [solo_reactive] = run_episodes([reactive])
    for together, alone in zip(mixed, (solo_learned, solo_reactive)):
        assert np.array_equal(together.decision, alone.decision)
        assert np.array_equal(together.replicas_after, alone.replicas_after)


def test_batch_rejects_mixed_hidden_widths():
    from kube_sqs_autoscaler_tpu.sim.compiled import run_episodes

    scenario = short_scenario()
    with pytest.raises(ValueError, match="hidden"):
        run_episodes(
            [
                learned_config(scenario, make_checkpoint(hidden=16)),
                learned_config(scenario, make_checkpoint(hidden=8)),
            ]
        )


def test_simulation_requires_checkpoint_for_learned_policy():
    from kube_sqs_autoscaler_tpu.sim.simulator import SimConfig, Simulation

    with pytest.raises(ValueError, match="learned_checkpoint"):
        Simulation(SimConfig(policy="learned"))


# --- training ---------------------------------------------------------------


def test_smoke_train_is_deterministic_and_stamped():
    scenario = short_scenario()
    config = ESConfig(population=4, generations=2, seed=9)
    first = train([scenario], config)
    second = train([scenario], config)
    assert np.array_equal(first.checkpoint.theta, second.checkpoint.theta)
    assert first.checkpoint.hash == second.checkpoint.hash
    assert len(first.stats) == 2
    meta = first.checkpoint.meta
    assert meta["forecast_history"] == config.history
    assert meta["min_samples"] == config.min_samples
    assert meta["scenarios"] == [scenario.name]
    assert np.isfinite(meta["best_train_reward"])
    # the trained artifact plays through the battery scorer
    [row] = evaluate_checkpoint(first.checkpoint, [scenario])
    assert row["policy"] == f"learned@{first.checkpoint.hash}"
    assert row["ticks"] == 60


def test_es_config_validation():
    with pytest.raises(ValueError, match="even"):
        ESConfig(population=5)
    with pytest.raises(ValueError, match="generations"):
        ESConfig(generations=0)
    with pytest.raises(ValueError, match="sigma"):
        ESConfig(sigma=0.0)


def test_evaluate_population_validates_shapes():
    scenario = short_scenario()
    with pytest.raises(ValueError, match="thetas must be"):
        evaluate_population(np.zeros((2, 3), np.float32), [scenario], hidden=16)
    with pytest.raises(ValueError, match="at least one scenario"):
        evaluate_population(
            np.zeros((1, param_count(16)), np.float32), [], hidden=16
        )
    with pytest.raises(ValueError, match="tick count"):
        evaluate_population(
            np.zeros((1, param_count(16)), np.float32),
            [short_scenario(), replace(short_scenario(), duration=600.0)],
            hidden=16,
        )


def test_checkpoint_history_reads_meta():
    assert checkpoint_history(make_checkpoint()) == (32, 3)
    bare = PolicyCheckpoint(theta=init_params(0))
    assert checkpoint_history(bare) == (64, 3)


# --- CLI startup validation -------------------------------------------------


def _parse(argv):
    from kube_sqs_autoscaler_tpu.cli import build_parser

    return build_parser(), build_parser().parse_args(argv)


def _expect_usage_error(argv, checkpoint_stage=False):
    from kube_sqs_autoscaler_tpu.cli import (
        build_parser,
        load_learned_checkpoint,
        validate_flag_interactions,
    )

    parser = build_parser()
    args = parser.parse_args(argv)
    with pytest.raises(SystemExit) as excinfo:
        with contextlib.redirect_stderr(io.StringIO()):
            validate_flag_interactions(parser, args)
            if checkpoint_stage:
                load_learned_checkpoint(parser, args)
    assert excinfo.value.code == 2


def test_cli_learned_requires_checkpoint():
    _expect_usage_error(["--policy", "learned"])


def test_cli_checkpoint_requires_learned_policy():
    _expect_usage_error(["--policy-checkpoint", "weights.json"])
    _expect_usage_error(
        ["--policy", "predictive", "--policy-checkpoint", "weights.json"]
    )


def test_cli_rejects_missing_checkpoint_before_loop_start(tmp_path):
    _expect_usage_error(
        [
            "--policy", "learned",
            "--policy-checkpoint", str(tmp_path / "missing.json"),
        ],
        checkpoint_stage=True,
    )


def test_cli_rejects_corrupt_and_future_checkpoints(tmp_path):
    corrupt = tmp_path / "corrupt.json"
    corrupt.write_text("{not json")
    _expect_usage_error(
        ["--policy", "learned", "--policy-checkpoint", str(corrupt)],
        checkpoint_stage=True,
    )
    future = tmp_path / "future.json"
    save_checkpoint(str(future), make_checkpoint())
    data = json.loads(future.read_text())
    data["schema"] = SCHEMA_VERSION + 1
    future.write_text(json.dumps(data))
    _expect_usage_error(
        ["--policy", "learned", "--policy-checkpoint", str(future)],
        checkpoint_stage=True,
    )


def test_cli_journal_meta_records_checkpoint_hash(tmp_path):
    from kube_sqs_autoscaler_tpu.cli import (
        _journal_meta,
        build_parser,
        load_learned_checkpoint,
        validate_flag_interactions,
    )

    path = str(tmp_path / "ck.json")
    checkpoint = make_checkpoint(seed=21)
    save_checkpoint(path, checkpoint)
    parser = build_parser()
    args = parser.parse_args(["--policy", "learned", "--policy-checkpoint", path])
    validate_flag_interactions(parser, args)
    loaded = load_learned_checkpoint(parser, args)
    meta = _journal_meta(args, loaded)
    assert meta["policy"] == "learned"
    assert meta["learn"] == {
        "checkpoint_hash": checkpoint.hash,
        "checkpoint_path": path,
        "hidden": 16,
        "history": 32,
        "min_samples": 3,
    }
    # reactive runs keep an empty learn block (same meta shape)
    reactive_args = parser.parse_args([])
    assert _journal_meta(reactive_args, None)["learn"] == {}


# --- replay + counterfactual ------------------------------------------------


def _record_learned_episode(tmp_path, checkpoint):
    from kube_sqs_autoscaler_tpu.sim.replay import record_episode

    config = learned_config(short_scenario(), checkpoint)
    journal = str(tmp_path / "episode.jsonl")
    meta, result = record_episode(config, journal)
    return journal, meta, result


def test_replay_learned_journal_reproduces_decisions(tmp_path):
    from kube_sqs_autoscaler_tpu.sim.replay import replay_journal

    checkpoint = make_checkpoint(seed=31)
    journal, meta, _ = _record_learned_episode(tmp_path, checkpoint)
    assert meta["learn"]["checkpoint_hash"] == checkpoint.hash
    result = replay_journal(journal, checkpoint=checkpoint)
    assert result.divergences == []
    assert result.ticks == 60


def test_replay_learned_journal_demands_matching_checkpoint(tmp_path):
    from kube_sqs_autoscaler_tpu.sim.replay import replay_journal

    checkpoint = make_checkpoint(seed=32)
    journal, _, _ = _record_learned_episode(tmp_path, checkpoint)
    with pytest.raises(ValueError, match="pass the matching"):
        replay_journal(journal)
    with pytest.raises(ValueError, match="does not match"):
        replay_journal(journal, checkpoint=make_checkpoint(seed=33))


def test_replay_live_journal_starts_mirror_at_min_pods():
    """Live journals omit initial_replicas (cli._journal_meta); the live
    mirror starts at min_pods, so the replay-side rebuild must too."""
    from kube_sqs_autoscaler_tpu.sim.replay import _depth_policy_from_meta

    checkpoint = make_checkpoint(seed=36)
    meta = {
        "source": "live",
        "poll_interval": 5.0,
        "policy": "learned",
        "world": {"min_pods": 3, "max_pods": 10},
        "learn": {"checkpoint_hash": checkpoint.hash},
    }
    policy, _ = _depth_policy_from_meta(meta, checkpoint=checkpoint)
    assert policy.replicas == 3


def test_counterfactual_rescoring_with_learned_policy(tmp_path):
    from kube_sqs_autoscaler_tpu.obs.journal import read_journal
    from kube_sqs_autoscaler_tpu.sim.replay import counterfactual

    checkpoint = make_checkpoint(seed=34)
    journal, meta, result = _record_learned_episode(tmp_path, checkpoint)
    _, records = read_journal(journal)
    row = counterfactual(
        records, meta, policy="learned", checkpoint=checkpoint
    )
    assert row["policy"] == f"learned@{checkpoint.hash}"
    # the recorded world is rebuilt from the journal, so re-scoring the
    # SAME policy reproduces the recorded episode's scores
    assert row["final_replicas"] == result.final_replicas
    assert row["max_depth"] == pytest.approx(result.max_depth, rel=0.05)
    with pytest.raises(ValueError, match="checkpoint"):
        counterfactual(records, meta, policy="learned")


def test_replay_cli_verdict_for_learned_journals(tmp_path):
    """The replay tool's exit-2 contract extends to learned journals: no
    traceback without weights, 0-divergence verdict with them."""
    from kube_sqs_autoscaler_tpu.sim.replay import main as replay_main

    checkpoint = make_checkpoint(seed=35)
    journal, _, _ = _record_learned_episode(tmp_path, checkpoint)
    ck_path = str(tmp_path / "ck.json")
    save_checkpoint(ck_path, checkpoint)
    stderr = io.StringIO()
    with contextlib.redirect_stderr(stderr):
        assert replay_main(["--journal", journal]) == 2
    assert "pass the matching checkpoint" in stderr.getvalue()
    with contextlib.redirect_stdout(io.StringIO()) as stdout:
        assert replay_main(["--journal", journal, "--checkpoint", ck_path]) == 0
    assert '"divergences": 0' in stdout.getvalue()


# --- the slow full gate: training beats the sweep winners -------------------


@pytest.mark.slow
def test_trained_policy_beats_sweep_winners_on_held_out():
    """The bench gate's protocol at reduced scale, symmetric by
    construction: both the sweep winners and the learned policy tune on
    the SAME base battery, and the comparison happens on held-out
    variants neither saw (lexicographic depth, churn, SLO aggregate)."""
    from kube_sqs_autoscaler_tpu.sim.scenarios import scenario_variants
    from kube_sqs_autoscaler_tpu.sim.sweep import SweepSpec, run_sweep

    base = list(default_battery())
    held_out = scenario_variants(base, 2, seed=202)
    result = train(
        base,
        ESConfig(
            population=16, generations=25, seed=0,
            churn_weight=0.3, replica_weight=0.15,
        ),
    )
    winners = run_sweep(SweepSpec(), base).best_points_per_scenario()
    winner_rows = []
    for scenario in held_out:
        point = winners[scenario.name.split("~")[0]]
        winner_rows.append(run_sweep([point], [scenario]).rows[0]["score"])
    learned_rows = evaluate_checkpoint(result.checkpoint, held_out)

    def lex(rows):
        return (
            sum(r["max_depth"] for r in rows),
            sum(r["replica_changes"] for r in rows),
            sum(r["time_over_slo_s"] for r in rows),
        )

    assert lex(learned_rows) < lex(winner_rows)
