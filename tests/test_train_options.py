"""Remat and gradient accumulation: both are pure memory/compute trades,
so they must not change the math — losses and updates match the plain
step up to accumulation-order floating point.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kube_sqs_autoscaler_tpu.workloads.model import ModelConfig, init_params
from kube_sqs_autoscaler_tpu.workloads.train import (
    TrainConfig,
    batch_sharding,
    init_train_state,
    loss_fn,
    make_mesh,
    make_train_step,
    place_state,
)

TINY = ModelConfig(
    vocab_size=256, d_model=64, n_heads=4, n_layers=2, d_ff=128,
    max_seq_len=64, dtype=jnp.float32,
)


def tokens_batch(batch=8, seq=32, seed=1):
    return jax.random.randint(
        jax.random.key(seed), (batch, seq), 0, TINY.vocab_size, jnp.int32
    )


def test_remat_is_bit_identical_in_value_and_grad():
    params = init_params(jax.random.key(0), TINY)
    tokens = tokens_batch()
    plain_l, plain_g = jax.value_and_grad(loss_fn)(params, tokens, TINY)
    remat_l, remat_g = jax.value_and_grad(loss_fn)(
        params, tokens, TINY, remat=True
    )
    assert float(plain_l) == float(remat_l)
    for a, b in zip(jax.tree.leaves(plain_g), jax.tree.leaves(remat_g)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-6
        )


def test_grad_accum_matches_full_batch_step():
    mesh = make_mesh(jax.devices(), model_parallel=2, seq_parallel=1)
    tokens = jax.device_put(tokens_batch(), batch_sharding(mesh))

    results = {}
    for accum in (1, 4):
        config = TrainConfig(learning_rate=1e-3, grad_accum=accum)
        state = place_state(
            mesh, init_train_state(jax.random.key(0), TINY, config)
        )
        step_fn = make_train_step(mesh, TINY, config, state)
        state, loss = step_fn(state, tokens)
        results[accum] = (float(loss), state["params"])

    assert results[1][0] == pytest.approx(results[4][0], rel=1e-5)
    for a, b in zip(
        jax.tree.leaves(results[1][1]), jax.tree.leaves(results[4][1])
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        )


def test_grad_accum_with_remat_learns_on_full_mesh():
    mesh = make_mesh(jax.devices(), model_parallel=2, seq_parallel=2)
    config = TrainConfig(learning_rate=1e-2, grad_accum=2, remat=True)
    state = place_state(mesh, init_train_state(jax.random.key(0), TINY, config))
    step_fn = make_train_step(mesh, TINY, config, state)
    tokens = jax.device_put(tokens_batch(batch=8), batch_sharding(mesh))
    losses = []
    for _ in range(4):
        state, loss = step_fn(state, tokens)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_grad_clip_bounds_the_update():
    """With an aggressively small clip norm, the parameter update per step
    is bounded by ~lr * clip (Adam normalizes, but the clipped gradient's
    global norm caps what the moments can see on step one); the unclipped
    step must differ — proving the clip transform is actually in the
    chain."""
    from kube_sqs_autoscaler_tpu.workloads.train import make_optimizer
    import optax

    params = init_params(jax.random.key(0), TINY)
    tokens = tokens_batch()
    _, grads = jax.value_and_grad(loss_fn)(params, tokens, TINY)

    clipped_cfg = TrainConfig(learning_rate=1e-3, grad_clip_norm=1e-3)
    plain_cfg = TrainConfig(learning_rate=1e-3)
    for cfg in (clipped_cfg, plain_cfg):
        opt = make_optimizer(cfg)
        state = opt.init(params)
        updates, _ = opt.update(grads, state, params)
        norm = float(optax.global_norm(updates))
        if cfg.grad_clip_norm:
            clipped_norm = norm
        else:
            plain_norm = norm
    assert clipped_norm != plain_norm
    # the clipped gradient has global norm <= 1e-3, so Adam's step-one
    # update is epsilon-dominated and far smaller than the plain one
    assert clipped_norm < plain_norm


def test_grad_clip_state_shardings_keep_moments_sharded():
    """The clip chain nests the adamw state one tuple deeper —
    state_shardings must still shard mu/nu like the params (a flat walk
    would silently replicate them)."""
    from jax.sharding import PartitionSpec as P
    from kube_sqs_autoscaler_tpu.workloads.train import state_shardings

    mesh = make_mesh(jax.devices(), model_parallel=2, seq_parallel=1)
    config = TrainConfig(grad_clip_norm=1.0)
    state = init_train_state(jax.random.key(0), TINY, config)
    shardings = state_shardings(mesh, state)

    def find_adam(entry):
        if hasattr(entry, "mu"):
            return entry
        if isinstance(entry, tuple):
            for e in entry:
                found = find_adam(e)
                if found is not None:
                    return found
        return None

    adam = find_adam(shardings["opt_state"])
    assert adam is not None
    # wqkv shards its output axis over "model" — its moments must too
    assert adam.mu["layers"][0]["wqkv"].spec == P(None, "model")
    assert adam.nu["layers"][0]["wqkv"].spec == P(None, "model")
    # and the clipped step still runs + learns on the mesh
    placed = place_state(mesh, state)
    step_fn = make_train_step(mesh, TINY, config, placed)
    tokens = jax.device_put(tokens_batch(), batch_sharding(mesh))
    losses = []
    for _ in range(3):
        placed, loss = step_fn(placed, tokens)
        losses.append(float(loss))
    assert all(np.isfinite(losses)) and losses[-1] < losses[0]


def test_grad_accum_validation():
    with pytest.raises(ValueError, match="grad_accum"):
        TrainConfig(grad_accum=0)

    mesh = make_mesh(jax.devices(), model_parallel=2, seq_parallel=1)
    config = TrainConfig(grad_accum=3)
    state = place_state(mesh, init_train_state(jax.random.key(0), TINY, config))
    step_fn = make_train_step(mesh, TINY, config, state)
    with pytest.raises(ValueError, match="divisible"):
        step_fn(state, jax.device_put(tokens_batch(batch=8),
                                      batch_sharding(mesh)))
