"""Actuator tests: the reference's four scale_test.go scenarios with exact
replica sequences, plus the error paths the reference leaves untested
(SURVEY.md §4 gaps).
"""

import pytest

from kube_sqs_autoscaler_tpu.core.types import ScaleError, Scaler
from kube_sqs_autoscaler_tpu.scale import (
    Deployment,
    FakeDeploymentAPI,
    NotFoundError,
    PodAutoScaler,
)


def make_autoscaler(max_, min_, init, up_pods, down_pods) -> PodAutoScaler:
    # Mirrors NewMockPodAutoScaler (scale/scale_test.go:85-115): two seeded
    # deployments; only "deploy" is scaled, "deploy-no-scale" is the control.
    api = FakeDeploymentAPI.with_deployments(
        "namespace", init, "deploy", "deploy-no-scale"
    )
    return PodAutoScaler(
        client=api,
        max=max_,
        min=min_,
        scale_up_pods=up_pods,
        scale_down_pods=down_pods,
        deployment="deploy",
        namespace="namespace",
    )


def test_scale_up_to_max_then_noop():
    # scale/scale_test.go:14-33 — 3 -> 4 -> 5, then no-op at max, all successful
    p = make_autoscaler(5, 1, 3, 1, 1)
    p.scale_up()
    assert p.client.replicas("deploy") == 4
    p.scale_up()
    assert p.client.replicas("deploy") == 5
    p.scale_up()  # boundary no-op must be success, not an error
    assert p.client.replicas("deploy") == 5
    assert p.client.replicas("deploy-no-scale") == 3  # untouched control


def test_scale_up_with_step_clamps_to_max():
    # scale/scale_test.go:35-49 — step 5: 3 -> 8 -> clamp 10
    p = make_autoscaler(10, 1, 3, 5, 5)
    p.scale_up()
    assert p.client.replicas("deploy") == 8
    p.scale_up()
    assert p.client.replicas("deploy") == 10


def test_scale_down_to_min_then_noop():
    # scale/scale_test.go:51-68 — 3 -> 2 -> 1, then no-op at min
    p = make_autoscaler(5, 1, 3, 1, 1)
    p.scale_down()
    assert p.client.replicas("deploy") == 2
    p.scale_down()
    assert p.client.replicas("deploy") == 1
    p.scale_down()
    assert p.client.replicas("deploy") == 1


def test_scale_down_with_step_clamps_to_min():
    # scale/scale_test.go:70-83 — step 5: 8 -> 3 -> clamp 1
    p = make_autoscaler(10, 1, 8, 5, 5)
    p.scale_down()
    assert p.client.replicas("deploy") == 3
    p.scale_down()
    assert p.client.replicas("deploy") == 1


def test_boundary_noop_does_not_call_update():
    # At the bound the reference returns before Update (scale/scale.go:62-65).
    p = make_autoscaler(5, 1, 5, 1, 1)
    p.scale_up()
    assert p.client.update_calls == 0
    p2 = make_autoscaler(5, 1, 1, 1, 1)
    p2.scale_down()
    assert p2.client.update_calls == 0


def test_get_failure_wraps_reference_context_string():
    p = make_autoscaler(5, 1, 3, 1, 1)
    p.client.fail_next_get = ConnectionError("apiserver down")
    with pytest.raises(ScaleError, match="no scale up occurred"):
        p.scale_up()
    assert p.client.replicas("deploy") == 3  # no write happened

    p.client.fail_next_get = ConnectionError("apiserver down")
    with pytest.raises(ScaleError, match="no scale down occurred"):
        p.scale_down()
    assert p.client.replicas("deploy") == 3


def test_update_failure_raises_and_leaves_store():
    p = make_autoscaler(5, 1, 3, 1, 1)
    p.client.fail_next_update = ConnectionError("conflict")
    with pytest.raises(ScaleError, match="Failed to scale up"):
        p.scale_up()
    assert p.client.replicas("deploy") == 3
    p.client.fail_next_update = ConnectionError("conflict")
    with pytest.raises(ScaleError, match="Failed to scale down"):
        p.scale_down()
    assert p.client.replicas("deploy") == 3


def test_missing_deployment_is_a_scale_error():
    api = FakeDeploymentAPI("namespace", [])
    p = PodAutoScaler(
        client=api, max=5, min=1, scale_up_pods=1, scale_down_pods=1,
        deployment="ghost", namespace="namespace",
    )
    with pytest.raises(ScaleError):
        p.scale_up()


def test_fake_copies_objects_like_clientgo_fake():
    api = FakeDeploymentAPI(
        "ns", [Deployment(name="d", namespace="ns", replicas=3)]
    )
    fetched = api.get("d")
    fetched.replicas = 99  # mutating the returned object must not leak in
    assert api.replicas("d") == 3


def test_fake_copies_are_deep_through_the_raw_body():
    api = FakeDeploymentAPI(
        "ns",
        [Deployment(name="d", namespace="ns", replicas=3,
                    raw={"spec": {"replicas": 3, "template": {"x": 1}}})],
    )
    fetched = api.get("d")
    fetched.raw["spec"]["template"]["x"] = 99  # nested mutation must not leak
    assert api.get("d").raw["spec"]["template"]["x"] == 1
    # and store-side objects must be independent of the caller's after update
    sent = fetched.with_replicas(4)
    api.update(sent)
    sent.raw["spec"]["template"]["x"] = 42
    assert api.get("d").raw["spec"]["template"]["x"] == 99


def test_current_above_max_is_noop_and_below_min_is_noop():
    # current > max: reference's `>=` gate no-ops rather than clamping down
    p = make_autoscaler(5, 1, 8, 1, 1)
    p.scale_up()
    assert p.client.replicas("deploy") == 8
    # current < min: `<=` gate no-ops rather than clamping up
    p2 = make_autoscaler(5, 3, 1, 1, 1)
    p2.scale_down()
    assert p2.client.replicas("deploy") == 1


def test_protocol_conformance():
    assert isinstance(make_autoscaler(5, 1, 3, 1, 1), Scaler)
