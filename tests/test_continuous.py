"""Continuous batching: rolling slots must produce EXACTLY what
per-request :func:`decode.generate` produces (scheduling changes, results
don't), refill slots as they finish rather than per batch, and drain a
queue end to end with at-least-once semantics.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np

from kube_sqs_autoscaler_tpu.metrics.fake import FakeMessageQueue
from kube_sqs_autoscaler_tpu.workloads.continuous import (
    ContinuousBatcher,
    ContinuousWorker,
)
from kube_sqs_autoscaler_tpu.workloads.decode import generate
from kube_sqs_autoscaler_tpu.workloads.model import ModelConfig, init_params
from kube_sqs_autoscaler_tpu.workloads.service import ServiceConfig

TINY = ModelConfig(
    vocab_size=128, d_model=64, n_heads=4, n_layers=2, d_ff=128,
    max_seq_len=32, dtype=jnp.float32,
)
URL = "fake://jobs"


def prompts(n, rng_seed=0, max_len=12):
    rng = np.random.default_rng(rng_seed)
    return [
        rng.integers(1, TINY.vocab_size, rng.integers(2, max_len + 1))
        .astype(np.int32)
        for _ in range(n)
    ]


def reference_continuation(params, ids, n_tokens):
    out = generate(
        params, jnp.asarray(ids, jnp.int32)[None], n_tokens, TINY
    )
    return np.asarray(out[0])


def test_batcher_outputs_equal_per_request_generate():
    params = init_params(jax.random.key(0), TINY)
    batcher = ContinuousBatcher(
        params, TINY, batch_size=3, prompt_len=12, generate_tokens=5
    )
    requests = prompts(7)
    results = {}
    queue = list(enumerate(requests))
    # keep slots full; collect as they finish — requests outnumber slots,
    # so slots MUST be reused mid-flight for this to terminate
    for _ in range(200):
        while queue and batcher.free_slots:
            idx, ids = queue.pop(0)
            batcher.submit(ids, payload=idx)
        for idx, tokens in batcher.step():
            results[idx] = tokens
        if not queue and batcher.active == 0:
            break
    assert len(results) == 7
    for idx, ids in enumerate(requests):
        np.testing.assert_array_equal(
            results[idx], reference_continuation(params, ids, 5),
            err_msg=f"request {idx}",
        )


def test_slots_refill_while_others_decode():
    params = init_params(jax.random.key(0), TINY)
    batcher = ContinuousBatcher(
        params, TINY, batch_size=2, prompt_len=8, generate_tokens=4
    )
    reqs = prompts(3, rng_seed=1, max_len=8)
    batcher.submit(reqs[0], payload=0)
    # advance slot 0 halfway, then submit into slot 1 — slot 0's progress
    # must be unaffected by the mid-flight prefill insertion
    assert batcher.step() == []
    batcher.submit(reqs[1], payload=1)
    done = {}
    for _ in range(20):
        for idx, tokens in batcher.step():
            done[idx] = tokens
        if len(done) == 2:
            break
    np.testing.assert_array_equal(
        done[0], reference_continuation(params, reqs[0], 4)
    )
    np.testing.assert_array_equal(
        done[1], reference_continuation(params, reqs[1], 4)
    )


def test_budget_one_finishes_at_submit():
    params = init_params(jax.random.key(0), TINY)
    batcher = ContinuousBatcher(
        params, TINY, batch_size=2, prompt_len=8, generate_tokens=1
    )
    ids = prompts(1, rng_seed=2, max_len=8)[0]
    batcher.submit(ids, payload="only")
    (payload, tokens), = batcher.step()
    assert payload == "only"
    np.testing.assert_array_equal(
        tokens, reference_continuation(params, ids, 1)
    )
    assert batcher.active == 0


def test_continuous_worker_drains_queue():
    params = init_params(jax.random.key(0), TINY)
    queue = FakeMessageQueue()
    queue.send_message(URL, "not json {{{")  # poison: consumed, not fatal
    reqs = prompts(6, rng_seed=3)
    for ids in reqs:
        queue.send_message(URL, json.dumps(ids.tolist()))
    worker = ContinuousWorker(
        queue, params, TINY,
        ServiceConfig(queue_url=URL, batch_size=2, seq_len=12,
                      generate_tokens=3),
    )
    assert worker.drain(total=6, max_cycles=500) == 6
    attrs = queue.get_queue_attributes(URL, ())
    assert attrs["ApproximateNumberOfMessages"] == "0"
    assert attrs["ApproximateNumberOfMessagesNotVisible"] == "0"


LLAMA_TINY = None  # built lazily (imports jax-heavy llama module once)


def _llama_tiny():
    global LLAMA_TINY
    if LLAMA_TINY is None:
        from kube_sqs_autoscaler_tpu.workloads.llama import LlamaConfig

        LLAMA_TINY = LlamaConfig(
            vocab_size=128, d_model=64, n_heads=4, n_kv_heads=2, n_layers=2,
            d_ff=128, max_seq_len=32, dtype=jnp.float32,
        )
    return LLAMA_TINY


def test_llama_batcher_outputs_equal_per_request_generate():
    # the GQA per-row cache through the same slot machine: greedy llama
    # slot outputs must equal per-request llama_generate exactly
    from kube_sqs_autoscaler_tpu.workloads.llama import (
        init_llama_params,
        llama_generate,
    )

    config = _llama_tiny()
    params = init_llama_params(jax.random.key(0), config)
    batcher = ContinuousBatcher(
        params, config, batch_size=2, prompt_len=12, generate_tokens=4,
        family="llama",
    )
    requests = prompts(5, rng_seed=4)
    results = {}
    queue = list(enumerate(requests))
    for _ in range(200):
        while queue and batcher.free_slots:
            idx, ids = queue.pop(0)
            batcher.submit(ids, payload=idx)
        for idx, tokens in batcher.step():
            results[idx] = tokens
        if not queue and batcher.active == 0:
            break
    assert len(results) == 5
    for idx, ids in enumerate(requests):
        ref = llama_generate(
            params, jnp.asarray(ids, jnp.int32)[None], 4, config
        )
        np.testing.assert_array_equal(
            results[idx], np.asarray(ref[0]), err_msg=f"request {idx}"
        )


def test_batcher_eos_frees_slot_early_and_pads():
    params = init_params(jax.random.key(0), TINY)
    ids = prompts(1, rng_seed=5, max_len=8)[0]
    # pick the token greedy decoding emits at step 1 as the eos id, so
    # eos demonstrably fires before the 6-token budget
    plain = reference_continuation(params, ids, 6)
    eos = int(plain[1])
    ref = np.asarray(generate(
        params, jnp.asarray(ids, jnp.int32)[None], 6, TINY, eos_id=eos
    )[0])

    batcher = ContinuousBatcher(
        params, TINY, batch_size=2, prompt_len=8, generate_tokens=6,
        eos_id=eos,
    )
    batcher.submit(ids, payload="req")
    done = []
    steps_to_finish = 0
    for _ in range(10):
        steps_to_finish += 1
        done = batcher.step()
        if done:
            break
    (payload, tokens), = done
    assert payload == "req"
    # identical to generate's eos-padded output...
    np.testing.assert_array_equal(tokens, ref)
    # ...and the slot freed before the budget would have (2 engine steps
    # to emit [t0, eos], not 6)
    assert steps_to_finish < 6
    assert batcher.active == 0


def test_batcher_temperature_sampling_terminates_in_vocab():
    params = init_params(jax.random.key(0), TINY)
    batcher = ContinuousBatcher(
        params, TINY, batch_size=2, prompt_len=8, generate_tokens=5,
        temperature=0.8, top_k=20, top_p=0.95, sample_seed=7,
    )
    reqs = prompts(3, rng_seed=6, max_len=8)
    results = []
    queue = list(reqs)
    for _ in range(100):
        while queue and batcher.free_slots:
            batcher.submit(queue.pop(0))
        for _, tokens in batcher.step():
            results.append(tokens)
        if not queue and batcher.active == 0:
            break
    assert len(results) == 3
    for tokens in results:
        assert tokens.shape == (5,)
        assert (tokens >= 0).all() and (tokens < TINY.vocab_size).all()


def test_continuous_worker_replies_trim_eos_and_correlate():
    params = init_params(jax.random.key(0), TINY)
    ids = prompts(1, rng_seed=5, max_len=8)[0]
    eos = int(reference_continuation(params, ids, 6)[1])
    queue = FakeMessageQueue()
    queue.send_message(URL, json.dumps(ids.tolist()))
    queue.send_message(URL, "not json {{{")  # poison: error reply
    results = FakeMessageQueue()
    worker = ContinuousWorker(
        queue, params, TINY,
        ServiceConfig(queue_url=URL, batch_size=2, seq_len=8,
                      generate_tokens=6, eos_id=eos,
                      result_queue_url="fake://results"),
        result_queue=results,
    )
    worker.drain(total=1, max_cycles=100)
    replies = results.receive_messages("fake://results", max_messages=4)
    assert len(replies) == 2
    payloads = [json.loads(m["Body"]) for m in replies]
    errors = [p for p in payloads if "error" in p]
    oks = [p for p in payloads if "tokens" in p]
    assert len(errors) == 1 and len(oks) == 1
    # trimmed at eos (no padding in the reply), correlated to a request
    assert eos not in oks[0]["tokens"]
    assert oks[0]["request_id"]
    assert errors[0]["request_id"]
    # input queue fully consumed
    attrs = queue.get_queue_attributes(URL, ())
    assert attrs["ApproximateNumberOfMessages"] == "0"


def test_worker_binary_continuous_demo():
    from kube_sqs_autoscaler_tpu.workloads.__main__ import main as worker_main

    worker_main(["--demo", "5", "--continuous", "--batch-size", "2",
                 "--seq-len", "12", "--generate-tokens", "3"])


def test_worker_binary_continuous_llama_sampled_demo():
    # the VERDICT item 3 composition: --continuous --family llama
    # --temperature ... --eos-id ... --result-queue-url ... end to end
    from kube_sqs_autoscaler_tpu.workloads.__main__ import main as worker_main

    worker_main(["--demo", "4", "--continuous", "--family", "llama",
                 "--batch-size", "2", "--seq-len", "12",
                 "--generate-tokens", "3", "--temperature", "0.8",
                 "--top-p", "0.9", "--eos-id", "5",
                 "--result-queue-url", "demo://results"])


def test_sharded_batcher_outputs_equal_single_chip():
    # VERDICT r3 composition hole: --continuous x --model-parallel.
    # Same request sequence through a (data, model)-sharded batcher and
    # a single-chip one: identical greedy outputs (scheduling and
    # sharding change, results don't)
    from kube_sqs_autoscaler_tpu.workloads.train import (
        make_mesh,
        param_shardings,
    )

    params = init_params(jax.random.key(0), TINY)
    mesh = make_mesh(jax.devices()[:4], model_parallel=2, seq_parallel=1)
    placed = jax.device_put(params, param_shardings(mesh, params))
    requests = prompts(5, rng_seed=8)

    def drain(batcher):
        results = {}
        queue = list(enumerate(requests))
        for _ in range(200):
            while queue and batcher.free_slots:
                idx, ids = queue.pop(0)
                batcher.submit(ids, payload=idx)
            for idx, tokens in batcher.step():
                results[idx] = tokens
            if not queue and batcher.active == 0:
                break
        return results

    plain = drain(ContinuousBatcher(
        params, TINY, batch_size=2, prompt_len=12, generate_tokens=4,
    ))
    sharded = drain(ContinuousBatcher(
        placed, TINY, batch_size=2, prompt_len=12, generate_tokens=4,
        mesh=mesh,
    ))
    assert len(sharded) == 5
    for idx in plain:
        np.testing.assert_array_equal(sharded[idx], plain[idx],
                                      err_msg=f"request {idx}")


def test_quantized_slots_equal_per_request_quantized_generate():
    # int8 KV slots: the outputs-equal-per-request invariant holds
    # against generate(quantized_cache=True) — same quantized math,
    # rolling scheduling
    params = init_params(jax.random.key(0), TINY)
    batcher = ContinuousBatcher(
        params, TINY, batch_size=2, prompt_len=12, generate_tokens=4,
        quantized_kv=True, eos_id=5,
    )
    requests = prompts(5, rng_seed=9)
    results = {}
    queue = list(enumerate(requests))
    for _ in range(200):
        while queue and batcher.free_slots:
            idx, ids = queue.pop(0)
            batcher.submit(ids, payload=idx)
        for idx, tokens in batcher.step():
            results[idx] = tokens
        if not queue and batcher.active == 0:
            break
    assert len(results) == 5
    for idx, ids in enumerate(requests):
        ref = np.asarray(generate(
            params, jnp.asarray(ids, jnp.int32)[None], 4, TINY,
            quantized_cache=True, eos_id=5,
        )[0])
        np.testing.assert_array_equal(results[idx], ref,
                                      err_msg=f"request {idx}")


def test_quantized_llama_slots_run():
    from kube_sqs_autoscaler_tpu.workloads.llama import init_llama_params

    config = _llama_tiny()
    params = init_llama_params(jax.random.key(1), config)
    batcher = ContinuousBatcher(
        params, config, batch_size=2, prompt_len=8, generate_tokens=3,
        family="llama", quantized_kv=True,
    )
    done = 0
    for ids in prompts(3, rng_seed=10, max_len=8):
        while not batcher.free_slots:
            done += len(batcher.step())
        batcher.submit(ids)
    for _ in range(50):
        done += len(batcher.step())
        if batcher.active == 0:
            break
    assert done == 3


def test_worker_binary_continuous_quantize_kv_demo():
    from kube_sqs_autoscaler_tpu.workloads.__main__ import main as worker_main

    worker_main(["--demo", "4", "--continuous", "--quantize-kv",
                 "--batch-size", "2", "--seq-len", "12",
                 "--generate-tokens", "3", "--eos-id", "5"])


def test_worker_binary_continuous_model_parallel_demo():
    from kube_sqs_autoscaler_tpu.workloads.__main__ import main as worker_main

    worker_main(["--demo", "5", "--continuous", "--model-parallel", "2",
                 "--batch-size", "4", "--seq-len", "12",
                 "--generate-tokens", "3", "--eos-id", "5"])


def test_worker_binary_continuous_flag_conflicts():
    import pytest

    from kube_sqs_autoscaler_tpu.workloads.__main__ import main as worker_main

    with pytest.raises(SystemExit, match="generate-tokens"):
        worker_main(["--demo", "1", "--continuous"])


def test_empty_poll_backoff_throttles_receives():
    """While slots are decoding and the queue is empty, the worker must
    NOT issue one (billed) zero-wait receive per generated token."""
    params = init_params(jax.random.key(0), TINY)
    queue = FakeMessageQueue()
    queue.send_message(
        URL, json.dumps(prompts(1, rng_seed=4)[0].tolist())
    )
    worker = ContinuousWorker(
        queue, params, TINY,
        ServiceConfig(queue_url=URL, batch_size=4, seq_len=12,
                      generate_tokens=8),
    )
    receives = {"n": 0}
    inner = queue.receive_messages

    def counting_receive(*a, **kw):
        receives["n"] += 1
        return inner(*a, **kw)

    queue.receive_messages = counting_receive
    worker.drain(total=1, max_cycles=50)
    assert worker.processed == 1
    # 8 decode cycles with 3 free slots: without the backoff this would
    # be ~8 receives; with it, the empty polls collapse to a couple
    assert receives["n"] <= 3, receives["n"]


from tests.conftest import drain_batcher as _drain  # noqa: E402


def test_speculative_slots_equal_per_request_generate():
    # VERDICT r4 next #4: speculative decoding INSIDE continuous
    # batching — each engine step is one draft-and-verify round over the
    # rolling slots; greedy outputs equal plain generate per request,
    # slot reuse included, and the per-slot accept counters report the
    # serving-side tuning signal
    params = init_params(jax.random.key(0), TINY)
    batcher = ContinuousBatcher(
        params, TINY, batch_size=2, prompt_len=12, generate_tokens=5,
        draft_layers=1, draft_tokens=3,
    )
    requests = prompts(5, rng_seed=11)
    results = _drain(batcher, requests)
    assert len(results) == 5
    for idx, ids in enumerate(requests):
        np.testing.assert_array_equal(
            results[idx], reference_continuation(params, ids, 5),
            err_msg=f"request {idx}",
        )
    # the early-exit self-draft shares the target's first layer, so the
    # aggregate accept stats must show real acceptance activity
    assert batcher.spec_rounds > 0
    assert 0 <= batcher.spec_accepted <= batcher.spec_rounds * 3


def test_speculative_slots_eos_equal_generate():
    params = init_params(jax.random.key(0), TINY)
    requests = prompts(4, rng_seed=12)
    ref0 = reference_continuation(params, requests[0], 5)
    eos = int(ref0[1])  # fires early for request 0 by construction
    batcher = ContinuousBatcher(
        params, TINY, batch_size=2, prompt_len=12, generate_tokens=5,
        draft_layers=1, draft_tokens=3, eos_id=eos,
    )
    results = _drain(batcher, requests)
    assert len(results) == 4
    for idx, ids in enumerate(requests):
        expected = np.asarray(generate(
            params, jnp.asarray(ids, jnp.int32)[None], 5, TINY, eos_id=eos
        )[0])
        np.testing.assert_array_equal(results[idx], expected,
                                      err_msg=f"request {idx}")


def test_sharded_speculative_slots_equal_single_chip():
    # spec rounds over a (data, model) mesh: weights/caches keep their
    # Megatron/head shardings, acceptance and rollback are row-local
    from kube_sqs_autoscaler_tpu.workloads.train import (
        make_mesh,
        param_shardings,
    )

    params = init_params(jax.random.key(0), TINY)
    mesh = make_mesh(jax.devices()[:4], model_parallel=2, seq_parallel=1)
    placed = jax.device_put(params, param_shardings(mesh, params))
    requests = prompts(5, rng_seed=13)
    plain = _drain(ContinuousBatcher(
        params, TINY, batch_size=2, prompt_len=12, generate_tokens=4,
        draft_layers=1, draft_tokens=2,
    ), requests)
    sharded = _drain(ContinuousBatcher(
        placed, TINY, batch_size=2, prompt_len=12, generate_tokens=4,
        draft_layers=1, draft_tokens=2, mesh=mesh,
    ), requests)
    assert len(sharded) == 5
    for idx in plain:
        np.testing.assert_array_equal(sharded[idx], plain[idx],
                                      err_msg=f"request {idx}")


def test_speculative_slots_sampled_terminate_in_vocab():
    # sampled spec slots: the Leviathan/Chen rule keeps every emitted
    # token an exact warped-target sample; here we pin termination,
    # shape, and vocab-range (the distributional identity is pinned in
    # test_speculative.py over 10^5 rows)
    params = init_params(jax.random.key(0), TINY)
    batcher = ContinuousBatcher(
        params, TINY, batch_size=2, prompt_len=12, generate_tokens=5,
        draft_layers=1, draft_tokens=2, temperature=0.8, top_p=0.9,
    )
    requests = prompts(4, rng_seed=14)
    results = _drain(batcher, requests)
    assert len(results) == 4
    for idx, tokens in results.items():
        assert tokens.shape == (5,)
        assert ((tokens >= 0) & (tokens < TINY.vocab_size)).all()


def test_speculative_slots_reject_bad_draft_depth():
    import pytest

    params = init_params(jax.random.key(0), TINY)
    with pytest.raises(ValueError, match="draft_layers"):
        ContinuousBatcher(
            params, TINY, batch_size=2, prompt_len=12, generate_tokens=4,
            draft_layers=TINY.n_layers, draft_tokens=2,
        )


def test_worker_binary_continuous_speculative_demo():
    from kube_sqs_autoscaler_tpu.workloads.__main__ import main

    main(["--demo", "3", "--batch-size", "2", "--seq-len", "8",
          "--generate-tokens", "4", "--continuous",
          "--speculative-draft-layers", "1",
          "--speculative-draft-tokens", "2"])


def test_beam_slots_equal_standalone_beam_search():
    # beam search INSIDE continuous batching: each slot owns W beam
    # rows and a device-side search state; per-request results equal
    # the standalone beam_search exactly — eos, length penalty, int8
    # cache, and slot reuse included
    from kube_sqs_autoscaler_tpu.workloads.beam import beam_search

    params = init_params(jax.random.key(0), TINY)
    requests = prompts(5, rng_seed=21)

    def pin(batcher_kwargs, beam_kwargs):
        batcher = ContinuousBatcher(
            params, TINY, batch_size=2, prompt_len=12, generate_tokens=6,
            beams=3, **batcher_kwargs,
        )
        results = _drain(batcher, requests)
        assert len(results) == 5
        for idx, ids in enumerate(requests):
            ref = np.asarray(beam_search(
                params, TINY, jnp.asarray(ids, jnp.int32)[None], 6,
                beams=3, **beam_kwargs,
            )[0])
            np.testing.assert_array_equal(results[idx], ref,
                                          err_msg=f"request {idx}")
        return results

    plain = pin({}, {})
    eos = int(plain[0][2])
    pin({"eos_id": eos}, {"eos_id": eos})
    pin({"eos_id": eos, "length_penalty": 0.8},
        {"eos_id": eos, "length_penalty": 0.8})
    pin({"quantized_kv": True}, {"quantized_cache": True})


def test_beam_slots_with_prefix_equal_concat():
    from kube_sqs_autoscaler_tpu.workloads.beam import beam_search
    from kube_sqs_autoscaler_tpu.workloads.decode import prefill_prefix

    params = init_params(jax.random.key(0), TINY)
    requests = prompts(4, rng_seed=22)
    prefix = jnp.arange(1, 7, dtype=jnp.int32)
    pc = prefill_prefix(params, prefix, TINY)
    batcher = ContinuousBatcher(
        params, TINY, batch_size=2, prompt_len=12, generate_tokens=5,
        beams=2, prefix_cache=pc,
    )
    results = _drain(batcher, requests)
    assert len(results) == 4
    for idx, ids in enumerate(requests):
        concat = jnp.concatenate([prefix, jnp.asarray(ids, jnp.int32)])
        ref = np.asarray(beam_search(params, TINY, concat[None], 5,
                                     beams=2)[0])
        np.testing.assert_array_equal(results[idx], ref,
                                      err_msg=f"request {idx}")


def test_sharded_beam_slots_equal_single_chip():
    from kube_sqs_autoscaler_tpu.workloads.train import (
        make_mesh,
        param_shardings,
    )

    params = init_params(jax.random.key(0), TINY)
    mesh = make_mesh(jax.devices()[:4], model_parallel=2, seq_parallel=1)
    placed = jax.device_put(params, param_shardings(mesh, params))
    requests = prompts(5, rng_seed=23)
    plain = _drain(ContinuousBatcher(
        params, TINY, batch_size=2, prompt_len=12, generate_tokens=5,
        beams=2,
    ), requests)
    sharded = _drain(ContinuousBatcher(
        placed, TINY, batch_size=2, prompt_len=12, generate_tokens=5,
        beams=2, mesh=mesh,
    ), requests)
    assert len(sharded) == 5
    for idx in plain:
        np.testing.assert_array_equal(sharded[idx], plain[idx],
                                      err_msg=f"request {idx}")


def test_beam_slots_reject_bad_combos():
    import pytest

    params = init_params(jax.random.key(0), TINY)
    with pytest.raises(ValueError, match="draft_layers"):
        ContinuousBatcher(params, TINY, batch_size=2, prompt_len=12,
                          generate_tokens=4, beams=2, draft_layers=1)
    with pytest.raises(ValueError, match="deterministic"):
        ContinuousBatcher(params, TINY, batch_size=2, prompt_len=12,
                          generate_tokens=4, beams=2, temperature=0.7)


def test_worker_binary_continuous_beams_demo():
    from kube_sqs_autoscaler_tpu.workloads.__main__ import main

    main(["--demo", "3", "--batch-size", "2", "--seq-len", "8",
          "--generate-tokens", "4", "--continuous", "--beams", "2"])
    main(["--demo", "3", "--batch-size", "2", "--seq-len", "8",
          "--generate-tokens", "4", "--continuous", "--beams", "2",
          "--quantize-kv", "--prefix-ids", "5,6", "--family", "llama"])


# ---------------------------------------------------------------------------
# Block decode (decode_block > 1): the pipelined serving hot path must
# change SCHEDULING only — every request's greedy output byte-identical
# to the single-step engine and to per-request generate.
# ---------------------------------------------------------------------------


def test_block_batcher_outputs_equal_per_request_generate():
    # block=3 with slot reuse: requests outnumber slots, budgets don't
    # divide the block, and the dispatch-ahead pipeline must still
    # produce exactly what per-request generate produces
    params = init_params(jax.random.key(0), TINY)
    batcher = ContinuousBatcher(
        params, TINY, batch_size=3, prompt_len=12, generate_tokens=5,
        decode_block=3,
    )
    requests = prompts(7)
    results = _drain(batcher, requests)
    assert len(results) == 7
    for idx, ids in enumerate(requests):
        np.testing.assert_array_equal(
            results[idx], reference_continuation(params, ids, 5),
            err_msg=f"request {idx}",
        )
    # every kept token was counted; capacity >= kept (frozen tail steps)
    assert batcher.tokens_emitted == 7 * 5
    assert 0 < batcher.block_tokens <= batcher.block_capacity


def test_block_eos_at_every_offset_matches_single_step():
    # eos firing at each offset within the block: the device mask must
    # freeze the row mid-block and the host must discard post-eos
    # positions — outputs, freed slots, and padding byte-identical to
    # both generate and the single-step engine
    params = init_params(jax.random.key(0), TINY)
    ids = prompts(1, rng_seed=31, max_len=8)[0]
    plain = reference_continuation(params, ids, 6)
    for offset in range(6):
        eos = int(plain[offset])
        ref = np.asarray(generate(
            params, jnp.asarray(ids, jnp.int32)[None], 6, TINY, eos_id=eos
        )[0])
        blocked = ContinuousBatcher(
            params, TINY, batch_size=2, prompt_len=8, generate_tokens=6,
            eos_id=eos, decode_block=4,
        )
        single = ContinuousBatcher(
            params, TINY, batch_size=2, prompt_len=8, generate_tokens=6,
            eos_id=eos, decode_block=1,
        )
        out_b = _drain(blocked, [ids])
        out_s = _drain(single, [ids])
        np.testing.assert_array_equal(out_b[0], ref,
                                      err_msg=f"offset {offset} (blocked)")
        np.testing.assert_array_equal(out_s[0], ref,
                                      err_msg=f"offset {offset} (single)")
        # the slot freed in both engines; no stale pending state
        assert blocked.active == 0 and single.active == 0
        assert blocked.tokens_emitted == single.tokens_emitted


def test_block_worker_drains_queue_with_replies():
    # worker-level parity: same queue drained by block=4 and block=1
    # workers — same processed counts, same reply payloads per request
    params = init_params(jax.random.key(0), TINY)
    reqs = prompts(5, rng_seed=32)

    def run(block):
        queue = FakeMessageQueue()
        body_by_id = {}
        for ids in reqs:
            body = json.dumps(ids.tolist())
            body_by_id[queue.send_message(URL, body)] = body
        results = FakeMessageQueue()
        worker = ContinuousWorker(
            queue, params, TINY,
            ServiceConfig(queue_url=URL, batch_size=2, seq_len=12,
                          generate_tokens=4, decode_block=block,
                          result_queue_url="fake://results"),
            result_queue=results,
        )
        assert worker.drain(total=5, max_cycles=500) == 5
        attrs = queue.get_queue_attributes(URL, ())
        assert attrs["ApproximateNumberOfMessages"] == "0"
        assert attrs["ApproximateNumberOfMessagesNotVisible"] == "0"
        replies = {}
        for message in results.receive_messages("fake://results",
                                                max_messages=10):
            payload = json.loads(message["Body"])
            replies[body_by_id[payload["request_id"]]] = payload["tokens"]
        return replies

    blocked, single = run(4), run(1)
    assert blocked == single and len(blocked) == 5


def test_submit_many_equals_sequential_submits():
    # one [M, P] admission insert vs M sequential submits: identical
    # cache contents, lengths, pending tokens — and identical outputs
    # when both batchers then run to completion
    params = init_params(jax.random.key(0), TINY)
    requests = prompts(3, rng_seed=33)
    many = ContinuousBatcher(
        params, TINY, batch_size=3, prompt_len=12, generate_tokens=4,
    )
    seq = ContinuousBatcher(
        params, TINY, batch_size=3, prompt_len=12, generate_tokens=4,
    )
    rows = many.submit_many(
        [(ids, idx) for idx, ids in enumerate(requests)]
    )
    assert rows == [seq.submit(ids, payload=idx)
                    for idx, ids in enumerate(requests)]
    np.testing.assert_array_equal(np.asarray(many._current),
                                  np.asarray(seq._current))
    np.testing.assert_array_equal(np.asarray(many._done),
                                  np.asarray(seq._done))
    np.testing.assert_array_equal(np.asarray(many._remaining),
                                  np.asarray(seq._remaining))
    np.testing.assert_array_equal(np.asarray(many.cache["length"]),
                                  np.asarray(seq.cache["length"]))
    for layer_m, layer_s in zip(many.cache["layers"], seq.cache["layers"]):
        for name in layer_m:
            np.testing.assert_allclose(
                np.asarray(layer_m[name]), np.asarray(layer_s[name]),
                err_msg=name,
            )
    out_m = _drain(many, [])
    out_s = _drain(seq, [])
    assert len(out_m) == len(out_s) == 3
    for idx in out_m:
        np.testing.assert_array_equal(out_m[idx], out_s[idx])


def test_submit_many_rejects_overflow():
    import pytest

    params = init_params(jax.random.key(0), TINY)
    batcher = ContinuousBatcher(
        params, TINY, batch_size=2, prompt_len=8, generate_tokens=2,
    )
    with pytest.raises(RuntimeError, match="free slot"):
        batcher.submit_many([(ids, i) for i, ids in
                             enumerate(prompts(3, rng_seed=34, max_len=8))])


def test_block_sampled_slots_terminate_in_vocab():
    params = init_params(jax.random.key(0), TINY)
    batcher = ContinuousBatcher(
        params, TINY, batch_size=2, prompt_len=8, generate_tokens=5,
        temperature=0.8, top_k=20, top_p=0.95, sample_seed=7,
        decode_block=3,
    )
    results = _drain(batcher, prompts(3, rng_seed=35, max_len=8))
    assert len(results) == 3
    for tokens in results.values():
        assert tokens.shape == (5,)
        assert (tokens >= 0).all() and (tokens < TINY.vocab_size).all()


def test_block_quantized_and_prefix_compose():
    # decode_block composes with the int8 cache and with a shared
    # prefix: outputs equal the corresponding generate paths exactly
    from kube_sqs_autoscaler_tpu.workloads.decode import prefill_prefix

    params = init_params(jax.random.key(0), TINY)
    requests = prompts(4, rng_seed=36)
    quantized = _drain(ContinuousBatcher(
        params, TINY, batch_size=2, prompt_len=12, generate_tokens=4,
        quantized_kv=True, eos_id=5, decode_block=3,
    ), requests)
    assert len(quantized) == 4
    for idx, ids in enumerate(requests):
        ref = np.asarray(generate(
            params, jnp.asarray(ids, jnp.int32)[None], 4, TINY,
            quantized_cache=True, eos_id=5,
        )[0])
        np.testing.assert_array_equal(quantized[idx], ref,
                                      err_msg=f"request {idx}")

    prefix = jnp.arange(1, 7, dtype=jnp.int32)
    pc = prefill_prefix(params, prefix, TINY)
    with_prefix = _drain(ContinuousBatcher(
        params, TINY, batch_size=2, prompt_len=12, generate_tokens=4,
        prefix_cache=pc, decode_block=2,
    ), requests)
    assert len(with_prefix) == 4
    for idx, ids in enumerate(requests):
        concat = jnp.concatenate([prefix, jnp.asarray(ids, jnp.int32)])
        ref = np.asarray(generate(params, concat[None], 4, TINY)[0])
        np.testing.assert_array_equal(with_prefix[idx], ref,
                                      err_msg=f"request {idx}")


def test_sharded_block_batcher_equals_single_chip():
    from kube_sqs_autoscaler_tpu.workloads.train import (
        make_mesh,
        param_shardings,
    )

    params = init_params(jax.random.key(0), TINY)
    mesh = make_mesh(jax.devices()[:4], model_parallel=2, seq_parallel=1)
    placed = jax.device_put(params, param_shardings(mesh, params))
    requests = prompts(5, rng_seed=37)
    plain = _drain(ContinuousBatcher(
        params, TINY, batch_size=2, prompt_len=12, generate_tokens=4,
        decode_block=2,
    ), requests)
    sharded = _drain(ContinuousBatcher(
        placed, TINY, batch_size=2, prompt_len=12, generate_tokens=4,
        decode_block=2, mesh=mesh,
    ), requests)
    assert len(sharded) == 5
    for idx in plain:
        np.testing.assert_array_equal(sharded[idx], plain[idx],
                                      err_msg=f"request {idx}")


def test_worker_binary_continuous_decode_block_demo():
    from kube_sqs_autoscaler_tpu.workloads.__main__ import main as worker_main

    worker_main(["--demo", "5", "--continuous", "--decode-block", "4",
                 "--batch-size", "2", "--seq-len", "12",
                 "--generate-tokens", "6", "--eos-id", "5"])


def test_speculative_overlap_rounds_equal_generate():
    # budgets deep enough that rows PROVABLY need another round even on
    # full acceptance -> the deferred-sync second round engages (two
    # rounds per step(), the second dispatched before the first is
    # host-consumed); outputs must still equal per-request generate
    params = init_params(jax.random.key(0), TINY)
    batcher = ContinuousBatcher(
        params, TINY, batch_size=2, prompt_len=12, generate_tokens=9,
        draft_layers=1, draft_tokens=2,
    )
    requests = prompts(4, rng_seed=38)
    results = _drain(batcher, requests)
    assert len(results) == 4
    for idx, ids in enumerate(requests):
        np.testing.assert_array_equal(
            results[idx], reference_continuation(params, ids, 9),
            err_msg=f"request {idx}",
        )
    assert batcher.spec_rounds > 0


def test_beam_slots_count_kept_tokens_and_ttft():
    # beam serving stats: tokens_emitted counts tokens up to and
    # including the first eos (never the padding after it), and TTFT is
    # recorded at completion (beam search has no incremental first token)
    from kube_sqs_autoscaler_tpu.workloads.beam import beam_search

    params = init_params(jax.random.key(0), TINY)
    ids = prompts(1, rng_seed=41)[0]
    plain = np.asarray(beam_search(
        params, TINY, jnp.asarray(ids, jnp.int32)[None], 6, beams=2,
    )[0])
    eos = int(plain[2])  # fires before the budget by construction
    batcher = ContinuousBatcher(
        params, TINY, batch_size=2, prompt_len=12, generate_tokens=6,
        beams=2, eos_id=eos,
    )
    (out,) = _drain(batcher, [ids]).values()
    kept = list(out).index(eos) + 1 if eos in out else out.size
    assert batcher.tokens_emitted == kept < 6
    assert batcher.ttft_count == 1 and batcher.ttft_sum > 0
